"""SiddhiQL recursive-descent parser -> query_api AST.

Covers the rule surface of the reference grammar
(modules/siddhi-query-compiler/.../SiddhiQL.g4, 918 lines) and the AST
construction role of SiddhiQLBaseVisitorImpl.java (3k LoC): app/stream/table/
window/trigger/function/aggregation definitions, queries (standard, join,
pattern, sequence), partitions, on-demand (store) queries, annotations,
expressions with the reference's precedence ladder, and time literals.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..query_api.app import SiddhiApp
from ..query_api.definition import (
    AggregationDefinition,
    Annotation,
    FunctionDefinition,
    StreamDefinition,
    TableDefinition,
    TriggerDefinition,
    WindowDefinition,
)
from ..query_api.expression import (
    Add,
    And,
    AttributeFunction,
    Compare,
    Constant,
    Divide,
    Expression,
    In,
    IsNull,
    Mod,
    Multiply,
    Not,
    Or,
    Subtract,
    Variable,
)
from ..query_api.query import (
    AbsentStreamStateElement,
    CountStateElement,
    DeleteStream,
    EveryStateElement,
    InputStore,
    InsertIntoStream,
    JoinInputStream,
    LogicalStateElement,
    NextStateElement,
    OnDemandQuery,
    OutputAttribute,
    OutputRate,
    Partition,
    Query,
    RangePartitionProperty,
    ReturnStream,
    Selector,
    SingleInputStream,
    StateInputStream,
    StreamStateElement,
    UpdateOrInsertStream,
    UpdateSet,
    UpdateStream,
    Window,
)
from .tokenizer import SiddhiParserException, Token, tokenize

_TIME_UNITS = {
    "millisecond": 1, "milliseconds": 1, "millisec": 1, "ms": 1,
    "second": 1000, "seconds": 1000, "sec": 1000,
    "minute": 60_000, "minutes": 60_000, "min": 60_000,
    "hour": 3_600_000, "hours": 3_600_000,
    "day": 86_400_000, "days": 86_400_000,
    "week": 604_800_000, "weeks": 604_800_000,
    "month": 2_592_000_000, "months": 2_592_000_000,
    "year": 31_536_000_000, "years": 31_536_000_000,
}

_DURATION_NAMES = {
    "sec": "SECONDS", "seconds": "SECONDS", "second": "SECONDS",
    "min": "MINUTES", "minutes": "MINUTES", "minute": "MINUTES",
    "hour": "HOURS", "hours": "HOURS",
    "day": "DAYS", "days": "DAYS",
    "week": "WEEKS", "weeks": "WEEKS",
    "month": "MONTHS", "months": "MONTHS",
    "year": "YEARS", "years": "YEARS",
}

_ATTR_TYPES = {"string", "int", "long", "float", "double", "bool", "object"}

# keywords that terminate a query-input token scan
_SECTION_KWS = {"select", "insert", "delete", "update", "return", "output"}


class Parser:
    def __init__(self, text: str):
        self.toks = tokenize(text)
        self.pos = 0

    def _at(self, node, tok: Token):
        """Attach the source position of `tok` to an AST node as
        `node.pos = (line, col)` — the static analyzer cites findings as
        `app.siddhi:line:col` from these, and they ride along for any
        later diagnostic.  Never overwrites a position set deeper in the
        parse (the first token of a subtree wins)."""
        if getattr(node, "pos", None) is None:
            try:
                node.pos = (tok.line, tok.col)
            except AttributeError:   # slotted/frozen node: skip silently
                pass
        return node

    # ---- token helpers -----------------------------------------------------
    def peek(self, off: int = 0) -> Token:
        return self.toks[min(self.pos + off, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.pos]
        if t.kind != "EOF":
            self.pos += 1
        return t

    def at_kw(self, *kws: str, off: int = 0) -> bool:
        t = self.peek(off)
        return t.kind == "ID" and t.lower in kws

    def at_punct(self, p: str, off: int = 0) -> bool:
        t = self.peek(off)
        return t.kind == "PUNCT" and t.text == p

    def eat_kw(self, *kws: str) -> Optional[Token]:
        if self.at_kw(*kws):
            return self.next()
        return None

    def expect_kw(self, *kws: str) -> Token:
        t = self.next()
        if t.kind != "ID" or t.lower not in kws:
            raise SiddhiParserException(
                f"expected {'/'.join(kws)!r}, got {t.text!r}", t.line, t.col)
        return t

    def eat_punct(self, p: str) -> Optional[Token]:
        if self.at_punct(p):
            return self.next()
        return None

    def expect_punct(self, p: str) -> Token:
        t = self.next()
        if t.kind != "PUNCT" or t.text != p:
            raise SiddhiParserException(
                f"expected {p!r}, got {t.text!r}", t.line, t.col)
        return t

    def expect_name(self) -> str:
        t = self.next()
        if t.kind != "ID":
            raise SiddhiParserException(
                f"expected identifier, got {t.text!r}", t.line, t.col)
        return t.text

    def err(self, msg: str):
        t = self.peek()
        raise SiddhiParserException(msg + f" near {t.text!r}", t.line, t.col)

    # ---- app ---------------------------------------------------------------
    def parse_app(self) -> SiddhiApp:
        app = SiddhiApp()
        while self.at_punct("@") and self._is_app_annotation():
            ann = self.parse_annotation()
            app.annotation(ann)
            if ann.name.lower() == "app:name":
                app.name = ann.element() or ann.element("name")
        while True:
            while self.eat_punct(";"):
                pass
            if self.peek().kind == "EOF":
                break
            anns = []
            while self.at_punct("@"):
                anns.append(self.parse_annotation())
            if self.at_kw("define"):
                self._parse_definition(app, anns)
            elif self.at_kw("from"):
                q = self.parse_query()
                q.annotations = anns + q.annotations
                app.add_query(q)
            elif self.at_kw("partition"):
                p = self.parse_partition()
                p.annotations = anns
                app.add_partition(p)
            else:
                self.err("expected define/from/partition")
        return app

    def _is_app_annotation(self) -> bool:
        return (self.peek(1).kind == "ID" and self.peek(1).lower == "app"
                and self.at_punct(":", 2))

    # ---- annotations -------------------------------------------------------
    def parse_annotation(self) -> Annotation:
        t0 = self.expect_punct("@")
        name = self.expect_name()
        if self.eat_punct(":"):
            name = f"{name}:{self.expect_name()}"
        ann = self._at(Annotation(name), t0)
        if self.eat_punct("("):
            while not self.at_punct(")"):
                if self.at_punct("@"):
                    ann.annotations.append(self.parse_annotation())
                else:
                    key, val = self._parse_annotation_element()
                    if key is None and None in ann.elements:
                        # later positional elements must not overwrite the
                        # first (@Index('a','b'), composite @PrimaryKey)
                        key = f"__p{len(ann.elements)}"
                    ann.elements[key] = val
                if not self.eat_punct(","):
                    break
            self.expect_punct(")")
        return ann

    def _parse_annotation_element(self) -> Tuple[Optional[str], object]:
        # property_name: dotted/dashed/colon-joined names, or bare value
        t = self.peek()
        if t.kind == "ID":
            # lookahead for ('.'|'-'|':') name ... '='
            save = self.pos
            parts = [self.expect_name()]
            while self.at_punct(".") or self.at_punct("-") or self.at_punct(":"):
                sep = self.next().text
                parts.append(sep)
                parts.append(self.expect_name())
            if self.eat_punct("="):
                key = "".join(parts)
                return key, self._parse_annotation_value()
            self.pos = save
            self.err("annotation element must be key=value or a string")
        if t.kind == "STRING":
            return None, self.next().value
        self.err("bad annotation element")

    def _parse_annotation_value(self):
        t = self.next()
        if t.kind in ("STRING", "INT", "LONG", "FLOAT", "DOUBLE"):
            return t.value
        if t.kind == "ID" and t.lower in ("true", "false"):
            return t.lower == "true"
        raise SiddhiParserException(
            f"bad annotation value {t.text!r}", t.line, t.col)

    # ---- definitions -------------------------------------------------------
    def _parse_definition(self, app: SiddhiApp, anns: List[Annotation]):
        t0 = self.expect_kw("define")
        kind = self.next()
        k = kind.lower
        if k == "stream":
            d = self._at(StreamDefinition(self._parse_source_name()), t0)
            self._parse_attr_list(d)
            d.annotations = anns
            app.define_stream(d)
        elif k == "table":
            d = self._at(TableDefinition(self._parse_source_name()), t0)
            self._parse_attr_list(d)
            d.annotations = anns
            app.define_table(d)
        elif k == "window":
            d = self._at(WindowDefinition(self._parse_source_name()), t0)
            self._parse_attr_list(d)
            d.window = self._parse_window_function()
            if self.eat_kw("output"):
                d.output_event_type = self._parse_output_event_type()
            d.annotations = anns
            app.define_window(d)
        elif k == "trigger":
            d = self._at(TriggerDefinition(self.expect_name()), t0)
            self.expect_kw("at")
            if self.eat_kw("every"):
                d.at_every = self._parse_time_value()
            else:
                t = self.next()
                if t.kind != "STRING":
                    raise SiddhiParserException(
                        "trigger at-expression must be 'start' or a cron "
                        "string", t.line, t.col)
                d.at = t.value
            d.annotations = anns
            app.define_trigger(d)
        elif k == "function":
            d = self._at(FunctionDefinition(), t0)
            d.id = self.expect_name()
            self.expect_punct("[")
            d.language = self.expect_name()
            self.expect_punct("]")
            self.expect_kw("return")
            d.return_type = self.expect_name().upper()
            d.body = self._parse_script_body()
            app.define_function(d)
        elif k == "aggregation":
            d = self._at(self._parse_aggregation_definition(anns), t0)
            app.define_aggregation(d)
        else:
            raise SiddhiParserException(
                f"unknown definition kind {kind.text!r}", kind.line, kind.col)

    def _parse_source_name(self) -> str:
        prefix = ""
        if self.eat_punct("#"):
            prefix = "#"
        elif self.eat_punct("!"):
            prefix = "!"
        return prefix + self.expect_name()

    def _parse_attr_list(self, d):
        self.expect_punct("(")
        while True:
            name = self.expect_name()
            t = self.next()
            if t.kind != "ID" or t.lower not in _ATTR_TYPES:
                raise SiddhiParserException(
                    f"bad attribute type {t.text!r}", t.line, t.col)
            d.attribute(name, t.lower.upper())
            if not self.eat_punct(","):
                break
        self.expect_punct(")")

    def _parse_window_function(self) -> Window:
        t0 = self.peek()
        ns, name, params = self._parse_function_call()
        return self._at(Window(ns, name, params), t0)

    def _parse_script_body(self) -> str:
        """The tokenizer captures { ... } bodies verbatim as one SCRIPT
        token (whitespace preserved — python bodies need it)."""
        t = self.next()
        if t.kind != "SCRIPT":
            raise SiddhiParserException("expected { function body }",
                                        t.line, t.col)
        return t.text

    def _parse_aggregation_definition(self, anns) -> AggregationDefinition:
        d = AggregationDefinition(self.expect_name())
        d.annotations = anns
        self.expect_kw("from")
        d.basic_single_input_stream = self._parse_standard_stream()
        d.selector = self._parse_selector(group_by_only=True)
        self.expect_kw("aggregate")
        if self.eat_kw("by"):
            d.aggregate_attribute = self._parse_attribute_reference()
        self.expect_kw("every")
        first = self._parse_duration_name()
        if self.eat_punct("..."):
            last = self._parse_duration_name()
            order = AggregationDefinition.DURATIONS
            i0, i1 = order.index(first), order.index(last)
            if i1 < i0:
                self.err("invalid aggregation duration range")
            d.time_periods = list(order[i0:i1 + 1])
        else:
            periods = [first]
            while self.eat_punct(","):
                periods.append(self._parse_duration_name())
            d.time_periods = periods
        # derive output attributes from selector
        return d

    def _parse_duration_name(self) -> str:
        t = self.next()
        if t.kind != "ID" or t.lower not in _DURATION_NAMES:
            raise SiddhiParserException(
                f"bad aggregation duration {t.text!r}", t.line, t.col)
        return _DURATION_NAMES[t.lower]

    # ---- queries -----------------------------------------------------------
    def parse_query(self) -> Query:
        q = Query()
        t0 = self.expect_kw("from")
        self._at(q, t0)
        q.input_stream = self._at(self._parse_query_input(), t0)
        if self.at_kw("select"):
            tsel = self.peek()
            q.selector = self._at(self._parse_selector(), tsel)
        if self.at_kw("output"):
            trate = self.peek()
            q.output_rate = self._at(self._parse_output_rate(), trate)
        tout = self.peek()
        self._parse_query_output(q)
        if q.output_stream is not None:
            self._at(q.output_stream, tout)
        return q

    def _classify_input(self) -> str:
        """Scan ahead (depth-0) to classify the input as standard/join/
        pattern/sequence."""
        depth = 0
        i = self.pos
        toks = self.toks
        kind = "standard"
        while i < len(toks):
            t = toks[i]
            if t.kind == "EOF":
                break
            if t.kind == "PUNCT":
                if t.text in "([":
                    depth += 1
                elif t.text in ")]":
                    depth -= 1
                    if depth < 0:
                        break
                elif t.text == "->":
                    return "pattern"
                elif t.text == "," and depth == 0:
                    kind = "sequence"
                elif t.text == ";" and depth == 0:
                    break
            elif t.kind == "ID" and depth == 0:
                lw = t.lower
                if lw in _SECTION_KWS:
                    break
                if lw in ("join", "unidirectional") or (
                        lw in ("left", "right", "full", "inner") and
                        i + 1 < len(toks) and toks[i + 1].kind == "ID" and
                        toks[i + 1].lower in ("outer", "join")):
                    return "join"
                if lw in ("every", "not", "and", "or") and kind == "standard":
                    kind = "pattern"
                # event binding  e1=Stream  (depth-0 '=')
                if (toks[i + 1].kind == "PUNCT" and toks[i + 1].text == "="
                        and kind == "standard"):
                    kind = "pattern"
            i += 1
        return kind

    def _parse_query_input(self):
        kind = self._classify_input()
        if kind == "standard":
            return self._parse_standard_stream()
        if kind == "join":
            return self._parse_join_stream()
        if kind == "pattern":
            return self._parse_pattern_stream("PATTERN")
        return self._parse_pattern_stream("SEQUENCE")

    def _parse_standard_stream(self) -> SingleInputStream:
        s = self._parse_basic_source()
        # optional window + post handlers
        while True:
            if self.at_punct("#") and self.at_kw("window", off=1):
                t0 = self.next()
                self.expect_kw("window")
                self.expect_punct(".")
                ns, name, params = self._parse_function_call()
                s.stream_handlers.append(
                    self._at(Window(ns, name, params), t0))
            elif self.at_punct("#") or self.at_punct("["):
                self._parse_stream_handler(s)
            else:
                break
        if self.eat_kw("as"):
            s.stream_reference_id = self.expect_name()
        return s

    def _parse_basic_source(self) -> SingleInputStream:
        t0 = self.peek()
        is_inner = bool(self.eat_punct("#"))
        is_fault = False if is_inner else bool(self.eat_punct("!"))
        sid = self.expect_name()
        s = self._at(SingleInputStream(sid, None, is_inner, is_fault), t0)
        while self.at_punct("[") or (
                self.at_punct("#") and not self.at_kw("window", off=1)):
            self._parse_stream_handler(s)
        return s

    def _parse_stream_handler(self, s: SingleInputStream):
        if self.eat_punct("["):
            expr = self.parse_expression()
            self.expect_punct("]")
            s.filter(expr)
            return
        self.expect_punct("#")
        if self.at_punct("[", off=0):
            self.expect_punct("[")
            expr = self.parse_expression()
            self.expect_punct("]")
            s.filter(expr)
            return
        if self.at_kw("window"):
            t0 = self.expect_kw("window")
            self.expect_punct(".")
            ns, name, params = self._parse_function_call()
            s.stream_handlers.append(
                self._at(Window(ns, name, params), t0))
            return
        ns, name, params = self._parse_function_call()
        s.function(name, *params, namespace=ns)

    def _parse_function_call(self) -> Tuple[str, str, List[Expression]]:
        ns = ""
        name = self.expect_name()
        if self.eat_punct(":"):
            ns = name
            name = self.expect_name()
        params: List[Expression] = []
        self.expect_punct("(")
        if not self.at_punct(")"):
            if self.at_punct("*"):
                self.next()
            else:
                params.append(self.parse_expression())
                while self.eat_punct(","):
                    params.append(self.parse_expression())
        self.expect_punct(")")
        return ns, name, params

    # -- joins ----------------------------------------------------------------
    def _parse_join_stream(self) -> JoinInputStream:
        left = self._parse_join_source()
        trigger = "ALL_EVENTS"
        if self.eat_kw("unidirectional"):
            trigger = "LEFT"
        jt = self._parse_join_type()
        right = self._parse_join_source()
        if self.eat_kw("unidirectional"):
            if trigger == "LEFT":
                self.err("both sides cannot be unidirectional")
            trigger = "RIGHT"
        on = None
        if self.eat_kw("on"):
            on = self.parse_expression()
        within = per = None
        if self.eat_kw("within"):
            within = self.parse_expression()
            if self.eat_punct(","):
                within = (within, self.parse_expression())
        if self.eat_kw("per"):
            per = self.parse_expression()
        return JoinInputStream(left, jt, right, on, within, per, trigger)

    def _parse_join_type(self) -> str:
        if self.eat_kw("left"):
            self.expect_kw("outer")
            self.expect_kw("join")
            return JoinInputStream.LEFT_OUTER_JOIN
        if self.eat_kw("right"):
            self.expect_kw("outer")
            self.expect_kw("join")
            return JoinInputStream.RIGHT_OUTER_JOIN
        if self.eat_kw("full"):
            self.expect_kw("outer")
            self.expect_kw("join")
            return JoinInputStream.FULL_OUTER_JOIN
        if self.eat_kw("outer"):
            self.expect_kw("join")
            return JoinInputStream.FULL_OUTER_JOIN
        self.eat_kw("inner")
        self.expect_kw("join")
        return JoinInputStream.JOIN

    def _parse_join_source(self) -> SingleInputStream:
        s = self._parse_basic_source()
        if self.at_punct("#") and self.at_kw("window", off=1):
            t0 = self.next()
            self.expect_kw("window")
            self.expect_punct(".")
            ns, name, params = self._parse_function_call()
            s.stream_handlers.append(
                self._at(Window(ns, name, params), t0))
        if self.eat_kw("as"):
            s.stream_reference_id = self.expect_name()
        return s

    # -- patterns / sequences --------------------------------------------------
    def _parse_pattern_stream(self, state_type: str) -> StateInputStream:
        sep = "->" if state_type == "PATTERN" else ","
        root = self._parse_state_chain(sep)
        within = None
        if self.eat_kw("within"):
            within = self._parse_time_value()
        return StateInputStream(state_type, root, within)

    def _parse_state_chain(self, sep: str):
        elements = [self._parse_state_element(sep)]
        while (self.at_punct(sep) if sep == "->" else
               (self.at_punct(",") and not self.at_kw("within", off=1))):
            self.next()
            elements.append(self._parse_state_element(sep))
        root = elements[-1]
        for el in reversed(elements[:-1]):
            root = NextStateElement(el, root)
        return root

    def _parse_state_element(self, sep: str):
        t0 = self.peek()
        if self.eat_kw("every"):
            if self.eat_punct("("):
                inner = self._parse_state_chain(sep)
                self.expect_punct(")")
                return self._at(EveryStateElement(inner), t0)
            return self._at(EveryStateElement(self._parse_state_unit(sep)),
                            t0)
        if self.at_punct("("):
            self.next()
            inner = self._parse_state_chain(sep)
            self.expect_punct(")")
            return inner
        return self._parse_state_unit(sep)

    def _parse_state_unit(self, sep: str):
        left = self._parse_stateful_source(sep)
        if self.at_kw("and", "or"):
            op = self.next().lower.upper()
            right = self._parse_stateful_source(sep)
            return LogicalStateElement(left, op, right)
        return left

    def _parse_stateful_source(self, sep: str):
        t0 = self.peek()
        if self.eat_kw("not"):
            src = self._parse_basic_source()
            waiting = None
            if self.eat_kw("for"):
                waiting = self._parse_time_value()
            return self._at(AbsentStreamStateElement(src, waiting), t0)
        # (event '=')? basic_source (<m:n> | * | + | ?)?
        ref = None
        if self.peek().kind == "ID" and self.at_punct("=", off=1):
            ref = self.expect_name()
            self.expect_punct("=")
        src = self._parse_basic_source()
        src.stream_reference_id = ref
        sse = self._at(StreamStateElement(src), t0)
        if self.eat_punct("<"):
            lo_t = self.next()
            if lo_t.kind != "INT":
                if lo_t.kind == "PUNCT" and lo_t.text == ":":
                    lo = 0
                    hi = int(self._expect_int())
                    self.expect_punct(">")
                    return CountStateElement(sse, lo, hi)
                raise SiddhiParserException("bad count range",
                                            lo_t.line, lo_t.col)
            lo = int(lo_t.value)
            hi = CountStateElement.ANY
            if self.eat_punct(":"):
                if self.peek().kind == "INT":
                    hi = int(self.next().value)
            else:
                hi = lo
            self.expect_punct(">")
            return CountStateElement(sse, lo, hi)
        if self.at_punct("*") and sep == ",":
            self.next()
            return CountStateElement(sse, 0, CountStateElement.ANY)
        if self.at_punct("+") and sep == ",":
            self.next()
            return CountStateElement(sse, 1, CountStateElement.ANY)
        if self.at_punct("?") and sep == ",":
            self.next()
            return CountStateElement(sse, 0, 1)
        return sse

    def _expect_int(self) -> int:
        t = self.next()
        if t.kind != "INT":
            raise SiddhiParserException(
                f"expected integer, got {t.text!r}", t.line, t.col)
        return int(t.value)

    # -- selector ---------------------------------------------------------------
    def _parse_selector(self, group_by_only: bool = False) -> Selector:
        sel = Selector()
        self.expect_kw("select")
        if self.eat_punct("*"):
            pass
        else:
            while True:
                expr = self.parse_expression()
                if self.eat_kw("as"):
                    sel.select(self.expect_name(), expr)
                else:
                    sel.selection_list.append(OutputAttribute(None, expr))
                if not self.eat_punct(","):
                    break
        if self.at_kw("group"):
            self.next()
            self.expect_kw("by")
            while True:
                v = self._parse_attribute_reference()
                sel.group_by(v)
                if not self.eat_punct(","):
                    break
        if group_by_only:
            return sel
        if self.eat_kw("having"):
            sel.having(self.parse_expression())
        if self.at_kw("order"):
            self.next()
            self.expect_kw("by")
            while True:
                v = self._parse_attribute_reference()
                order = "ASC"
                if self.eat_kw("asc"):
                    order = "ASC"
                elif self.eat_kw("desc"):
                    order = "DESC"
                sel.order_by(v, order)
                if not self.eat_punct(","):
                    break
        if self.eat_kw("limit"):
            sel.limit = self._parse_const_int()
        if self.eat_kw("offset"):
            sel.offset = self._parse_const_int()
        return sel

    def _parse_const_int(self) -> int:
        t = self.next()
        if t.kind not in ("INT", "LONG"):
            raise SiddhiParserException(
                f"expected integer constant, got {t.text!r}", t.line, t.col)
        return int(t.value)

    # -- output rate / output --------------------------------------------------
    def _parse_output_rate(self) -> OutputRate:
        self.expect_kw("output")
        if self.eat_kw("snapshot"):
            self.expect_kw("every")
            return OutputRate.per_snapshot(self._parse_time_value())
        behavior = "ALL"
        if self.eat_kw("all"):
            behavior = "ALL"
        elif self.eat_kw("first"):
            behavior = "FIRST"
        elif self.eat_kw("last"):
            behavior = "LAST"
        self.expect_kw("every")
        if self.peek().kind == "INT" and self.at_kw("events", off=1):
            n = self._expect_int()
            self.expect_kw("events")
            return OutputRate.per_events(n, behavior)
        return OutputRate.per_time(self._parse_time_value(), behavior)

    def _parse_output_event_type(self) -> str:
        if self.eat_kw("all"):
            self.expect_kw("events")
            return "ALL_EVENTS"
        if self.eat_kw("expired"):
            self.expect_kw("events")
            return "EXPIRED_EVENTS"
        self.eat_kw("current")
        self.expect_kw("events")
        return "CURRENT_EVENTS"

    def _parse_query_output(self, q: Query):
        if self.eat_kw("insert"):
            et = None
            if self.at_kw("all", "expired", "current"):
                et = self._parse_output_event_type()
            self.expect_kw("into")
            target = self._parse_source_name()
            q.output_stream = InsertIntoStream(
                target, et, target.startswith("#"), target.startswith("!"))
            return
        if self.eat_kw("delete"):
            target = self._parse_source_name()
            et = None
            if self.eat_kw("for"):
                et = self._parse_output_event_type()
            self.expect_kw("on")
            q.output_stream = DeleteStream(target, self.parse_expression(), et)
            return
        if self.eat_kw("update"):
            if self.eat_kw("or"):
                self.expect_kw("insert")
                self.expect_kw("into")
                target = self._parse_source_name()
                et = None
                if self.eat_kw("for"):
                    et = self._parse_output_event_type()
                us = self._parse_set_clause()
                self.expect_kw("on")
                q.output_stream = UpdateOrInsertStream(
                    target, self.parse_expression(), us, et)
                return
            target = self._parse_source_name()
            et = None
            if self.eat_kw("for"):
                et = self._parse_output_event_type()
            us = self._parse_set_clause()
            self.expect_kw("on")
            q.output_stream = UpdateStream(target, self.parse_expression(),
                                           us, et)
            return
        if self.eat_kw("return"):
            et = None
            if self.at_kw("all", "expired", "current"):
                et = self._parse_output_event_type()
            q.output_stream = ReturnStream(et)
            return
        self.err("expected insert/delete/update/return")

    def _parse_set_clause(self) -> Optional[UpdateSet]:
        if not self.eat_kw("set"):
            return None
        us = UpdateSet()
        while True:
            var = self._parse_attribute_reference()
            self.expect_punct("=")
            us.set(var, self.parse_expression())
            if not self.eat_punct(","):
                break
        return us

    # -- partitions -------------------------------------------------------------
    def parse_partition(self) -> Partition:
        t0 = self.expect_kw("partition")
        self.expect_kw("with")
        self.expect_punct("(")
        p = self._at(Partition(), t0)
        while True:
            save = self.pos
            expr = self.parse_expression()
            if self.eat_kw("as"):
                # range partition: expr as 'label' (or ...) of stream
                self.pos = save
                ranges = []
                while True:
                    cond = self.parse_expression()
                    self.expect_kw("as")
                    t = self.next()
                    if t.kind != "STRING":
                        raise SiddhiParserException(
                            "range label must be a string", t.line, t.col)
                    ranges.append(RangePartitionProperty(t.value, cond))
                    if not self.eat_kw("or"):
                        break
                self.expect_kw("of")
                sid = self.expect_name()
                p.with_(sid, ranges)
            else:
                self.expect_kw("of")
                sid = self.expect_name()
                p.with_(sid, expr)
            if not self.eat_punct(","):
                break
        self.expect_punct(")")
        self.expect_kw("begin")
        while True:
            while self.eat_punct(";"):
                pass
            if self.at_kw("end"):
                break
            anns = []
            while self.at_punct("@"):
                anns.append(self.parse_annotation())
            q = self.parse_query()
            q.annotations = anns
            p.add_query(q)
        self.expect_kw("end")
        return p

    # -- on-demand (store) query -------------------------------------------------
    def parse_on_demand_query(self) -> OnDemandQuery:
        oq = OnDemandQuery()
        if self.at_kw("select"):
            # "query_section INSERT INTO target" form
            oq.selector = self._parse_selector()
            self.expect_kw("insert")
            self.expect_kw("into")
            oq.type = "INSERT"
            oq.output_stream = InsertIntoStream(self._parse_source_name())
            return oq
        self.expect_kw("from")
        store = InputStore(self.expect_name())
        if self.eat_kw("as"):
            store.alias = self.expect_name()
        if self.eat_kw("on"):
            store.on_condition = self.parse_expression()
        if self.eat_kw("within"):
            a = self.parse_expression()
            b = None
            if self.eat_punct(","):
                b = self.parse_expression()
            store.within = (a, b)
        if self.eat_kw("per"):
            store.per = self.parse_expression()
        oq.input_store = store
        if self.at_kw("select"):
            oq.selector = self._parse_selector()
        if self.eat_kw("delete"):
            tgt = self._parse_source_name()
            self.expect_kw("on")
            oq.type = "DELETE"
            oq.output_stream = DeleteStream(tgt, self.parse_expression())
        elif self.eat_kw("update"):
            if self.eat_kw("or"):
                self.expect_kw("insert")
                self.expect_kw("into")
                tgt = self._parse_source_name()
                us = self._parse_set_clause()
                self.expect_kw("on")
                oq.type = "UPDATE_OR_INSERT"
                oq.output_stream = UpdateOrInsertStream(
                    tgt, self.parse_expression(), us)
            else:
                tgt = self._parse_source_name()
                us = self._parse_set_clause()
                self.expect_kw("on")
                oq.type = "UPDATE"
                oq.output_stream = UpdateStream(tgt, self.parse_expression(), us)
        else:
            oq.type = "FIND"
        return oq

    # ---- expressions ---------------------------------------------------------
    def parse_expression(self) -> Expression:
        t0 = self.peek()
        return self._at(self._parse_or(), t0)

    def _parse_or(self) -> Expression:
        left = self._parse_and()
        while self.at_kw("or"):
            self.next()
            left = Or(left, self._parse_and())
        return left

    def _parse_and(self) -> Expression:
        left = self._parse_in()
        while self.at_kw("and"):
            self.next()
            left = And(left, self._parse_in())
        return left

    def _parse_in(self) -> Expression:
        left = self._parse_equality()
        while self.at_kw("in"):
            self.next()
            left = In(left, self.expect_name())
        return left

    def _parse_equality(self) -> Expression:
        left = self._parse_relational()
        while self.at_punct("==") or self.at_punct("!="):
            t = self.next()
            left = self._at(Compare(left, t.text,
                                    self._parse_relational()), t)
        return left

    def _parse_relational(self) -> Expression:
        left = self._parse_additive()
        while (self.at_punct(">=") or self.at_punct("<=")
               or self.at_punct(">") or self.at_punct("<")):
            t = self.next()
            left = self._at(Compare(left, t.text,
                                    self._parse_additive()), t)
        return left

    def _parse_additive(self) -> Expression:
        left = self._parse_multiplicative()
        while self.at_punct("+") or self.at_punct("-"):
            op = self.next().text
            right = self._parse_multiplicative()
            left = Add(left, right) if op == "+" else Subtract(left, right)
        return left

    def _parse_multiplicative(self) -> Expression:
        left = self._parse_unary()
        while self.at_punct("*") or self.at_punct("/") or self.at_punct("%"):
            op = self.next().text
            right = self._parse_unary()
            left = {"*": Multiply, "/": Divide, "%": Mod}[op](left, right)
        return left

    def _parse_unary(self) -> Expression:
        if self.at_kw("not"):
            self.next()
            return Not(self._parse_unary())
        if self.at_punct("-") or self.at_punct("+"):
            sign = self.next().text
            inner = self._parse_unary()
            if sign == "+":
                return inner
            if isinstance(inner, Constant) and inner.type != "STRING":
                return Constant(-inner.value, inner.type)
            return Subtract(Constant(0, "INT"), inner)
        return self._parse_postfix()

    def _parse_postfix(self) -> Expression:
        e = self._parse_primary()
        if self.at_kw("is") and self.at_kw("null", off=1):
            self.next()
            self.next()
            if isinstance(e, Variable) and e.attribute_name is None:
                return IsNull(None, e.stream_id, e.stream_index)
            return IsNull(e)
        return e

    def _parse_primary(self) -> Expression:
        t = self.peek()
        if self.at_punct("("):
            self.next()
            e = self.parse_expression()
            self.expect_punct(")")
            return e
        if t.kind in ("INT", "LONG", "FLOAT", "DOUBLE"):
            self.next()
            # time literal: INT followed by a unit keyword
            if t.kind == "INT" and self.peek().kind == "ID" and \
                    self.peek().lower in _TIME_UNITS:
                return Constant(self._parse_time_value(int(t.value)), "LONG",
                                is_time=True)
            kind = {"INT": "INT", "LONG": "LONG", "FLOAT": "FLOAT",
                    "DOUBLE": "DOUBLE"}[t.kind]
            return Constant(t.value, kind)
        if t.kind == "STRING":
            self.next()
            return Constant(t.value, "STRING")
        if t.kind == "ID":
            if t.lower == "true" or t.lower == "false":
                self.next()
                return Constant(t.lower == "true", "BOOL")
            if t.lower == "null":
                self.next()
                return Constant(None, "STRING")
            return self._parse_reference_or_function()
        if self.at_punct("#") or self.at_punct("!"):
            return self._parse_reference_or_function()
        self.err("unexpected token in expression")

    def _parse_reference_or_function(self) -> Expression:
        # function call: name '(' or ns ':' name '('
        if (self.peek().kind == "ID" and self.at_punct("(", off=1)) or \
                (self.peek().kind == "ID" and self.at_punct(":", off=1)
                 and self.peek(2).kind == "ID" and self.at_punct("(", off=3)):
            ns, name, params = self._parse_function_call()
            return AttributeFunction(ns, name, params)
        return self._parse_attribute_reference(allow_bare_stream=True)

    def _parse_attribute_reference(self, allow_bare_stream: bool = False
                                   ) -> Variable:
        prefix = ""
        if self.eat_punct("#"):
            prefix = "#"
        elif self.eat_punct("!"):
            prefix = "!"
        name1 = self.expect_name()
        idx1 = None
        if self.at_punct("[") and not prefix:
            self.next()
            idx1 = self._parse_attribute_index()
            self.expect_punct("]")
        # inner-stream second part: name1#name2.attr
        if self.eat_punct("#"):
            name2 = self.expect_name()
            self.expect_punct(".")
            attr = self.expect_name()
            return Variable(attr, stream_id=prefix + name1 + "#" + name2)
        if self.at_punct(".") :
            self.next()
            attr = self.expect_name()
            return Variable(attr, stream_id=prefix + name1, stream_index=idx1)
        if idx1 is not None or prefix:
            if allow_bare_stream:
                # stream reference (for `S is null` in patterns)
                return Variable(None, stream_id=prefix + name1,
                                stream_index=idx1)
            self.err("expected '.attribute' after stream reference")
        return Variable(name1)

    def _parse_attribute_index(self) -> int:
        if self.at_kw("last"):
            self.next()
            if self.eat_punct("-"):
                return -(self._expect_int() + 1)
            return -1
        return self._expect_int()

    # ---- time values -----------------------------------------------------------
    def _parse_time_value(self, first: Optional[int] = None) -> int:
        total = 0
        count = 0
        while True:
            if first is not None:
                amount = first
                first = None
            else:
                if self.peek().kind != "INT":
                    break
                if not (self.peek(1).kind == "ID" and
                        self.peek(1).lower in _TIME_UNITS):
                    break
                amount = int(self.next().value)
            unit = self.next()
            if unit.kind != "ID" or unit.lower not in _TIME_UNITS:
                raise SiddhiParserException(
                    f"expected time unit, got {unit.text!r}",
                    unit.line, unit.col)
            total += amount * _TIME_UNITS[unit.lower]
            count += 1
        if count == 0:
            self.err("expected time value")
        return total
