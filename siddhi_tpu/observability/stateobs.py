"""State observatory: occupancy, key hotness, and high-water telemetry.

Reference (what): the reference engine's metrics/debugger surface reports
per-component statistics and lets an operator inspect live state
(SiddhiAppRuntimeImpl statistics + SiddhiDebugger state inspection).
Here every stateful operator runs against FIXED device shapes — keyed
window slabs, group-slot arenas, NFA blocks, join candidate lanes,
emission compaction blocks, serving rings — so the operational question
the reference never had is *utilization*: how full is each sized
structure, how hot is the key traffic, and what capacity would a
restart actually need.

TPU design (how): every sized device structure already has a HOST
mirror — `SlotAllocator` binds keys host-side before dispatch,
`JoinKeyTracker` mirrors per-bucket retention, `EmissionRing` counts
its own slots, emission demand is decoded from the header fetch that
delivery already pays — so the observatory is an always-on accumulator
over those mirrors, under the repo's never-fetch discipline: zero added
`jax.device_get` / `block_until_ready` anywhere.  The one device-side
quantity with no mirror (plain window-buffer fill, which lives inside
the jitted step state) is probed by a tiny sampled jitted reduction
whose scalar RIDES the delivery fetch that already happens
(`_deliver_output` packs it into the same `device_get` tuple).

Key hotness: staging already computes per-batch key sets (slot ids +
per-key row counts) to group events; the observatory folds them into a
count-min sketch (bounded memory, one-sided overestimates) plus a
space-saving top-K (the heavy hitters) plus an exact distinct bitmap
(slots are dense ints below the allocator capacity).  The derived
`hot_share` — the share of keyed traffic landing in the hottest 1% of
keys — is the measured input ROADMAP item 4's tiered key state needs.

High-water marks accumulate into a sizing-hints ledger that rides app
snapshots (`"sizing"` payload key), so a restarted app reports its
learned capacities from tick zero — the persistence half of ROADMAP
item 5's self-tuning controller.

Surfaces: `siddhi_state_occupancy` / `siddhi_state_high_water` /
`siddhi_key_hotset_share` in /metrics, a `utilization` node in EXPLAIN,
a `state` section in /healthz (near-capacity on a non-growable cap
flips `degraded`), /timeseries series, `runtime.state_report()`, and
REST `GET /siddhi-apps/<app>/state`.

Config: `state.obs.enabled` (default true; false reverts to the PR 13
baseline — the never-fetch guard test's control arm),
`state.obs.sample.every` (window-fill probe modulus, default 8, 0
disables the probe), `state.obs.near.capacity` (healthz near-capacity
threshold, default 0.9).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# canonical structure order — every surface lists structures in this
# order, not dict order (the phases.PHASES convention)
STRUCTURES = ("window_keys", "group_slots", "pattern_keys", "pair_slots",
              "join_keys", "join_lane", "window_fill", "emission_cap",
              "serve_ring")

# count-min sketch geometry: 4 rows x 1024 counters of int64 = 32 KiB
# per tracked query — error bound e*total/1024 per estimate, one-sided
_CMS_DEPTH = 4
_CMS_WIDTH = 1024
# odd multipliers for the per-row multiply-shift hashes (keys are dense
# non-negative slot ints, so multiply-shift mixes them well enough)
_CMS_MULT = (0x9E3779B1, 0x85EBCA77, 0xC2B2AE35, 0x27D4EB2F)
_TOPK = 64


class KeyHotness:
    """Per-query key-traffic tracker: count-min sketch + space-saving
    top-K + exact distinct bitmap.  Fed from staging's already-computed
    per-batch key sets (slot ids + per-key row counts) — numpy only,
    never a device array."""

    __slots__ = ("_cms", "_seen", "_ss", "total")

    def __init__(self, capacity: int):
        self._cms = np.zeros((_CMS_DEPTH, _CMS_WIDTH), np.int64)
        self._seen = np.zeros(max(1, int(capacity)), bool)
        self._ss: Dict[int, int] = {}   # space-saving: key -> count
        self.total = 0

    def update(self, keys, counts) -> None:
        keys = np.asarray(keys, np.int64).ravel()
        counts = np.asarray(counts, np.int64).ravel()
        if keys.size == 1:
            # scalar fast path: single-key batches dominate small sends
            # and vectorized numpy overhead (~10x) would tax every one
            self._update_one(int(keys[0]), int(counts[0]))
            return
        live = (keys >= 0) & (counts > 0)
        if not live.any():
            return
        keys, counts = keys[live], counts[live]
        self.total += int(counts.sum())
        # exact distinct: slots are dense ints < allocator capacity
        inb = keys < self._seen.shape[0]
        if inb.any():
            self._seen[keys[inb]] = True
        # CMS rows: vectorized multiply-shift hash + scatter-add
        for d in range(_CMS_DEPTH):
            h = ((keys + 1) * _CMS_MULT[d]) % (2 ** 31) % _CMS_WIDTH
            np.add.at(self._cms[d], h, counts)
        for k, c in zip(keys.tolist(), counts.tolist()):
            self._ss_feed(k, c)

    def _update_one(self, k: int, c: int) -> None:
        if k < 0 or c <= 0:
            return
        self.total += c
        if k < self._seen.shape[0]:
            self._seen[k] = True
        kk = k + 1
        cms = self._cms
        for d in range(_CMS_DEPTH):
            cms[d, (kk * _CMS_MULT[d]) % (2 ** 31) % _CMS_WIDTH] += c
        self._ss_feed(k, c)

    def _ss_feed(self, k: int, c: int) -> None:
        # space-saving: exact for tracked keys; an untracked key takes
        # over the minimum tracked count (classic overestimate-in-place)
        ss = self._ss
        if k in ss:
            ss[k] += c
        elif len(ss) < _TOPK:
            ss[k] = c
        else:
            victim = min(ss, key=ss.get)
            floor = ss.pop(victim)
            ss[k] = floor + c

    @property
    def distinct(self) -> int:
        return int(self._seen.sum())

    def estimate(self, key: int) -> int:
        """CMS point estimate — never underestimates the true count."""
        k = np.int64(key)
        return int(min(
            self._cms[d][((k + 1) * _CMS_MULT[d]) % (2 ** 31) % _CMS_WIDTH]
            for d in range(_CMS_DEPTH)))

    def top(self, n: int = 10) -> List[Tuple[int, int]]:
        """Heavy hitters with tightened counts: the space-saving count
        and the CMS estimate are both one-sided upper bounds, so their
        min is a tighter upper bound — this keeps eviction inflation
        (space-saving's min-floor creep under uniform traffic) from
        masquerading as heat."""
        items = [(k, min(c, self.estimate(k)))
                 for k, c in self._ss.items()]
        return sorted(items, key=lambda kv: -kv[1])[:n]

    def hot_share(self, fraction: float = 0.01) -> float:
        """Share of total keyed traffic landing in the hottest
        ceil(distinct * fraction) keys (at least one key)."""
        if not self.total:
            return 0.0
        k = max(1, int(np.ceil(self.distinct * fraction)))
        hot = sum(c for _, c in self.top(k))
        return min(1.0, hot / self.total)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "total": self.total,
            "distinct": self.distinct,
            "hot_share_1pct": round(self.hot_share(0.01), 4),
            "top": [[int(k), int(c)] for k, c in self.top(8)],
        }


class StateObservatory:
    """Always-on per-(query, structure) utilization accumulator.  One
    per StatisticsManager (i.e. per app runtime); `observe` is the
    single hot-path entry — a dict upsert under a short lock."""

    __slots__ = ("_lock", "_rec", "_hot")

    def __init__(self):
        self._lock = threading.Lock()
        # (query, structure) -> [occupancy, capacity, high_water,
        #                        growable, config_key]
        self._rec: Dict[tuple, list] = {}
        self._hot: Dict[str, KeyHotness] = {}

    def observe(self, query: str, structure: str,
                occupancy: Optional[int], capacity: int,
                growable: bool = True,
                config_key: Optional[str] = None) -> None:
        """Record one occupancy sample (high-water = running max).
        occupancy=None refreshes capacity/metadata only — the HWM a
        restore adopted survives untouched until real traffic beats
        it."""
        key = (query, structure)
        with self._lock:
            rec = self._rec.get(key)
            if rec is None:
                rec = self._rec[key] = [0, 0, 0, True, None]
            if occupancy is not None:
                occ = int(occupancy)
                rec[0] = occ
                if occ > rec[2]:
                    rec[2] = occ
            rec[1] = int(capacity)
            rec[3] = bool(growable)
            if config_key is not None:
                rec[4] = config_key

    def feed_keys(self, query: str, capacity: int, keys, counts) -> None:
        """Fold one staged batch's key set (slot ids + per-key row
        counts, both host numpy) into the query's hotness tracker."""
        with self._lock:
            hot = self._hot.get(query)
            if hot is None:
                hot = self._hot[query] = KeyHotness(capacity)
            hot.update(keys, counts)

    def hotness(self, query: str) -> Optional[KeyHotness]:
        with self._lock:
            return self._hot.get(query)

    def snapshot(self) -> Dict[str, Any]:
        """{"structures": {q: {s: {...}}}, "hotness": {q: {...}}} —
        structures in canonical order; scrape-safe shallow reads."""
        with self._lock:
            recs = {k: list(v) for k, v in self._rec.items()}
            hots = {q: h.snapshot() for q, h in self._hot.items()}
        structures: Dict[str, Dict] = {}
        for (q, s), (occ, cap, hwm, growable, ck) in recs.items():
            # utilization may exceed 1.0 for emission_cap: occupancy is
            # the batch's total row DEMAND while a partitioned pattern's
            # @emit cap is per-key — >1 reads as drop/growth pressure,
            # not arena fill
            structures.setdefault(q, {})[s] = {
                "occupancy": occ,
                "capacity": cap,
                "utilization": round(occ / cap, 4) if cap else 0.0,
                "high_water": hwm,
                "growable": growable,
                **({"config_key": ck} if ck else {}),
            }
        for q in structures:
            ordered = {s: structures[q][s] for s in STRUCTURES
                       if s in structures[q]}
            ordered.update({s: v for s, v in structures[q].items()
                            if s not in ordered})
            structures[q] = ordered
        return {"structures": structures, "hotness": hots}

    # -- sizing-hints ledger (snapshot persistence) ----------------------
    def ledger(self) -> Dict[str, Dict[str, Dict[str, int]]]:
        """{query: {structure: {"high_water", "capacity"}}} — the
        sizing-hints payload carried in app snapshots.

        `window_fill` is excluded: a sliding window trends to full by
        design (its capacity IS the configured length, nothing to
        learn), and the sampled probe rides the unfused delivery fetch
        — whether an entry exists depends on dispatch strategy, which
        would break the fused-vs-sequential snapshot byte-parity
        contract (tests/test_fused.py).  It stays a live surface
        (state_report/metrics/EXPLAIN), just not a persisted hint."""
        with self._lock:
            out: Dict[str, Dict] = {}
            for (q, s), (_, cap, hwm, _, _) in self._rec.items():
                if s == "window_fill":
                    continue
                out.setdefault(q, {})[s] = {"high_water": int(hwm),
                                            "capacity": int(cap)}
            return out

    def adopt_ledger(self, led: Dict) -> None:
        """Max-merge a restored sizing ledger: high-water marks survive
        the restart (a restarted app reports learned capacities from
        tick zero); live occupancy stays whatever this process saw."""
        if not isinstance(led, dict):
            return
        with self._lock:
            for q, structures in led.items():
                if not isinstance(structures, dict):
                    continue
                for s, hint in structures.items():
                    try:
                        hwm = int(hint.get("high_water", 0))
                        cap = int(hint.get("capacity", 0))
                    except Exception:  # noqa: BLE001 — bad blob: skip
                        continue
                    rec = self._rec.get((q, s))
                    if rec is None:
                        rec = self._rec[(q, s)] = [0, cap, 0, True, None]
                    rec[2] = max(rec[2], hwm)
                    if rec[1] == 0:
                        rec[1] = cap

    def reset(self) -> None:
        with self._lock:
            self._rec.clear()
            self._hot.clear()


# -- config memos (the phases.sample_every pattern) -------------------------

def obs_enabled(rt) -> bool:
    """`state.obs.enabled` (default true), memoized on the runtime —
    the hot path reads one dict slot, never the ConfigManager."""
    on = rt.__dict__.get("_stateobs_enabled")
    if on is None:
        on = True
        try:
            cm = getattr(rt, "config_manager", None)
            v = cm.extract_property("state.obs.enabled") \
                if cm is not None else None
            if v is not None:
                on = str(v).strip().lower() not in ("false", "0", "no")
        except Exception:  # noqa: BLE001 — observability must not throw
            on = True
        rt.__dict__["_stateobs_enabled"] = on
    return on


def obs_sample_every(rt) -> int:
    """`state.obs.sample.every` — window-fill probe modulus (default 8,
    0 disables the sampled probe entirely), memoized like obs_enabled."""
    every = rt.__dict__.get("_stateobs_sample_every")
    if every is None:
        every = 8
        try:
            cm = getattr(rt, "config_manager", None)
            v = cm.extract_property("state.obs.sample.every") \
                if cm is not None else None
            if v is not None:
                every = max(0, int(v))
        except Exception:  # noqa: BLE001 — observability must not throw
            every = 8
        rt.__dict__["_stateobs_sample_every"] = every
    return every


def near_capacity_threshold(rt) -> float:
    """`state.obs.near.capacity` — /healthz degraded threshold over
    non-growable structures (default 0.9)."""
    th = rt.__dict__.get("_stateobs_near_capacity")
    if th is None:
        th = 0.9
        try:
            cm = getattr(rt, "config_manager", None)
            v = cm.extract_property("state.obs.near.capacity") \
                if cm is not None else None
            if v is not None:
                th = min(1.0, max(0.0, float(v)))
        except Exception:  # noqa: BLE001 — observability must not throw
            th = 0.9
        rt.__dict__["_stateobs_near_capacity"] = th
    return th


# tiny test fixtures legitimately run 100%-full 4-key allocators; below
# this capacity a full arena is sizing noise, not an incident
_NEAR_CAPACITY_MIN_CAP = 16

# a sliding length/time window runs 100% full at steady state — that is
# its job, not an incident — and emission-cap "occupancy" is per-batch
# row demand (legitimately >cap for partitioned patterns, and already
# surfaced by drop counters + adaptive growth); only arenas where
# "full" means "next new key raises" count toward the near-capacity
# verdict
_NEAR_CAPACITY_EXEMPT = frozenset({"window_fill", "emission_cap"})


# -- pull collection over the host mirrors ----------------------------------

def collect(rt) -> None:
    """Refresh the observatory from every query's HOST mirrors: slot
    allocators (len/capacity attribute reads), the join tracker's lane
    demand, emission-cap plan metadata, serve-ring facts.  Pure host
    object walk — scrape surfaces call this under the monkeypatched
    never-fetch bomb and must survive."""
    if not obs_enabled(rt):
        return
    obs = rt.stats.stateobs
    for qname, qr in list(getattr(rt, "query_runtimes", {}).items()):
        try:
            _collect_query(obs, qname, qr)
        except Exception:  # noqa: BLE001 — metrics must not throw
            pass


def _collect_query(obs: StateObservatory, qname: str, qr) -> None:
    p = qr.planned
    wk = getattr(p, "window_key_allocator", None)
    if wk is not None:
        obs.observe(qname, "window_keys", len(wk), wk.capacity,
                    growable=False, config_key="@capacity(keys='N')")
    ga = getattr(p, "slot_allocator", None)
    if ga is not None and getattr(qr, "slot_allocator", None) is not ga:
        obs.observe(qname, "group_slots", len(ga), ga.capacity,
                    growable=False, config_key="@capacity(groups='N')")
    pairs = getattr(p, "pair_allocs", None) or ()
    if pairs:
        obs.observe(qname, "pair_slots",
                    max(len(a) for a, _ in pairs),
                    max(a.capacity for a, _ in pairs),
                    growable=False, config_key="@capacity(groups='N')")
    # pattern slab allocator lives on the runtime, not the plan
    pa = getattr(qr, "slot_allocator", None)
    if pa is not None:
        obs.observe(qname, "pattern_keys", len(pa), pa.capacity,
                    growable=False, config_key="@capacity(keys='N')")
    jk_alloc = getattr(p, "join_key_allocator", None)
    if jk_alloc is not None:
        obs.observe(qname, "join_keys", len(jk_alloc), jk_alloc.capacity,
                    growable=False, config_key="@capacity(keys='N')")
    jk = getattr(qr, "_jk", None)
    if jk is not None:
        obs.observe(qname, "join_lane", jk.needed_k(),
                    getattr(p, "lane_k", 0) or 0, growable=True,
                    config_key="auto (lane grows via replan)")
    cap = getattr(p, "compact_rows", None)
    if cap is not None:
        obs.observe(qname, "emission_cap", None, cap,
                    growable=not getattr(p, "emit_explicit", True),
                    config_key="@emit(rows='N')")
    ring = qr.__dict__.get("_serve_ring")
    if ring is not None:
        obs.observe(qname, "serve_ring", ring.occupancy(), ring.capacity,
                    growable=True, config_key="serving.ring.capacity")


# -- window-fill probe (sampled; the scalar rides the delivery fetch) -------

def _alive_leaves(state) -> List:
    """`alive` masks of every window Buffer inside a state pytree —
    a host-side container walk (NamedTuple fields), no device reads."""
    out: List = []

    def walk(node):
        if isinstance(node, tuple):
            fields = getattr(node, "_fields", None)
            if fields is not None and "alive" in fields:
                out.append(node.alive)
            for sub in node:
                walk(sub)
        elif isinstance(node, (list,)):
            for sub in node:
                walk(sub)
        elif isinstance(node, dict):
            for sub in node.values():
                walk(sub)

    walk(state)
    return out


_PROBE_FN = None


def _probe_fn():
    """ONE process-wide jitted fill reduction, shared by every query
    and runtime: jax's jit cache keys on (function object, avals), so a
    module-level function re-uses compiles across queries — and across
    the many short-lived runtimes a test session creates — for every
    repeated window shape.  A per-query closure here recompiled the
    identical reduction once per runtime, which dominated the probe's
    cost under pytest."""
    global _PROBE_FN
    if _PROBE_FN is None:
        import jax
        from ..core.steputil import jit_step

        def _probe(ls):
            return jax.numpy.stack(
                [jax.numpy.sum(a.astype(jax.numpy.int32)) for a in ls])

        _PROBE_FN = jit_step(_probe, owner="stateobs:fill_probe")
    return _PROBE_FN


def arm_fill_probe(qr) -> None:
    """Every Nth dispatch, dispatch ONE tiny jitted reduction over the
    query state's window `alive` masks and stash the lazy [n] fill
    vector on the runtime — `_deliver_output` packs it into the
    `device_get` it already performs (zero added fetches; the probe is
    dispatch-only).  No-op when the state holds no Buffer windows
    (keyed slabs mirror through their allocator instead)."""
    rt = qr.app
    if qr.__dict__.get("_stateobs_probe_off"):
        return
    if not obs_enabled(rt):
        return
    every = obs_sample_every(rt)
    if every <= 0:
        return
    n = qr.__dict__.get("_stateobs_tick", 0) + 1
    qr.__dict__["_stateobs_tick"] = n
    if n % every:
        return
    leaves = _alive_leaves(qr.state)
    if not leaves:
        # no Buffer windows in this state shape — never will be; stop
        # walking the pytree on every Nth dispatch
        qr.__dict__["_stateobs_probe_off"] = True
        return
    try:
        qr.__dict__["_stateobs_probe"] = _probe_fn()(leaves)
        qr.__dict__["_stateobs_probe_caps"] = \
            [int(np.prod(a.shape)) for a in leaves]
    except Exception:  # noqa: BLE001 — observability must not throw
        qr.__dict__.pop("_stateobs_probe", None)


def take_fill_probe(qr):
    """Pop the pending lazy fill vector (or None) — the delivery path
    appends it to its existing fetch tuple."""
    return qr.__dict__.pop("_stateobs_probe", None)


def record_fill(qr, fills) -> None:
    """Fold a fetched fill vector back into the observatory (summed
    across the query's window buffers; capacity is the buffers' total
    row capacity from shape metadata)."""
    if fills is None:
        return
    caps = qr.__dict__.get("_stateobs_probe_caps") or []
    try:
        fill = int(np.asarray(fills).sum())
        cap = int(sum(caps)) or 1
        qr.app.stats.stateobs.observe(
            qr.name, "window_fill", fill, cap, growable=False,
            config_key="window length/time capacity")
    except Exception:  # noqa: BLE001 — observability must not throw
        pass


# -- reports ----------------------------------------------------------------

def near_capacity(rt, snap: Optional[Dict] = None) -> List[Dict]:
    """Non-growable structures at/over the near-capacity threshold —
    the /healthz degraded trigger and the STATE003 lint input."""
    if snap is None:
        snap = rt.stats.stateobs.snapshot()
    th = near_capacity_threshold(rt)
    out: List[Dict] = []
    for q, structures in snap["structures"].items():
        for s, rec in structures.items():
            if rec["growable"] or s in _NEAR_CAPACITY_EXEMPT \
                    or rec["capacity"] < _NEAR_CAPACITY_MIN_CAP:
                continue
            if rec["occupancy"] >= th * rec["capacity"]:
                out.append({"query": q, "structure": s,
                            "occupancy": rec["occupancy"],
                            "capacity": rec["capacity"],
                            "utilization": rec["utilization"],
                            **({"config_key": rec["config_key"]}
                               if rec.get("config_key") else {})})
    return out


def state_report(rt) -> Dict:
    """Full observatory report for one app: per-structure utilization
    and high-water marks, key hotness, near-capacity verdicts, and the
    sizing-hints ledger a snapshot would carry.  Host-side reads only —
    safe to call on a live app."""
    enabled = obs_enabled(rt)
    if enabled:
        collect(rt)
    obs = rt.stats.stateobs
    snap = obs.snapshot()
    return {
        "app": rt.name,
        "enabled": enabled,
        "sample_every": obs_sample_every(rt),
        "structures": snap["structures"],
        "hotness": snap["hotness"],
        "near_capacity": near_capacity(rt, snap) if enabled else [],
        "sizing_hints": obs.ledger(),
    }
