"""State-memory accounting: nbytes per device-state component.

Reference (what): the reference's SiddhiMemoryUsageMetric walks the query
object graph and reports retained heap per query.  TPU design (how): our
state is device pytrees — window buffers, pattern NFA slot blocks, key
slots, tables, fused stack buffers — so the accounting walks each
runtime's pytrees and sums nbytes PER COMPONENT, computed purely from
shape × dtype metadata.  This is the scrape path (`siddhi_state_bytes`
in /metrics, plus the explain report), so the invariant from
exposition.py applies verbatim: **no `device_get`, no array
materialization** — a Prometheus poll must never pay a device sync or a
tunnel roundtrip.  `leaf_nbytes` therefore reads only `.shape`/`.dtype`
(host-side metadata on both numpy and jax arrays) and never the buffer.

Component naming follows the recompile-owner convention so the two
metric families join naturally in dashboards: queries by name with a
sub-component label, shared objects as `table:<id>` / `window:<id>` /
`agg:<id>`.
"""
from __future__ import annotations

from typing import Dict

import numpy as np


def leaf_nbytes(x) -> int:
    """nbytes of one pytree leaf from metadata only (no device access)."""
    try:
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is None or dtype is None:
            # host scalar / python object leaf
            return int(np.asarray(x).nbytes) if np.isscalar(x) else 0
        n = 1
        for d in shape:
            n *= int(d)
        return n * int(np.dtype(dtype).itemsize)
    except Exception:  # noqa: BLE001 — metrics must not throw
        return 0


def tree_nbytes(tree) -> int:
    """Total nbytes of a pytree, metadata-only."""
    try:
        import jax
        return sum(leaf_nbytes(leaf) for leaf in
                   jax.tree_util.tree_leaves(tree))
    except Exception:  # noqa: BLE001 — metrics must not throw
        return 0


def _kind_components(qr) -> Dict[str, int]:
    """Split a query runtime's state tuple into named components.  The
    state layouts are (window, selector) for planned single queries,
    ((b32, b64, scalars), selector) for patterns, and the join's
    (left window, right window, selector...) tuple; anything that doesn't
    match falls back to positional names so the total always adds up."""
    mg = getattr(qr, "_merged", None)
    if mg is not None:
        # merged member (optimizer/mqo.py): report only this query's
        # EXCLUSIVE bytes — the shared window buffer is accounted ONCE,
        # under the group owner (component_bytes adds `merged:<group>`),
        # never per member (the MEM001 double-count fix)
        return mg.member_components(qr)
    state = qr.state
    p = qr.planned
    names = None
    if hasattr(p, "steps") and isinstance(getattr(p, "steps", None), dict):
        names = ("pattern_slots", "selector")
    elif hasattr(p, "step_left"):
        names = ("window_left", "window_right", "selector")
    elif isinstance(state, tuple) and len(state) == 2:
        names = ("window", "selector")
    out: Dict[str, int] = {}
    if isinstance(state, tuple) and names is not None and \
            len(state) <= len(names) + 1:
        for i, part in enumerate(state):
            label = names[i] if i < len(names) else f"state[{i}]"
            out[label] = tree_nbytes(part)
    else:
        out["state"] = tree_nbytes(state)
    # @fuse stack buffers hold K-1 staged host batches awaiting dispatch
    fb = getattr(qr, "_fuse", None)
    if fb is not None and fb.items:
        total = 0
        for args in fb.items:
            for a in args:
                staged = a if hasattr(a, "cols") else None
                if staged is not None:
                    total += leaf_nbytes(staged.ts) + \
                        leaf_nbytes(staged.kind) + leaf_nbytes(staged.valid)
                    total += sum(leaf_nbytes(c) for c in staged.cols)
        if total:
            out["fuse_stack"] = total
    # serving emission ring (serving/ring.py): device-resident output
    # slots awaiting the async drainer — metadata-only walk of the
    # ring's generation buffers
    ring = qr.__dict__.get("_serve_ring")
    if ring is not None:
        try:
            total = sum(tree_nbytes(s) for s in ring.state_leaves())
        except Exception:  # noqa: BLE001 — metrics must not throw
            total = 0
        if total:
            out["serve_ring"] = total
    return out


def query_component_bytes(qr) -> Dict[str, int]:
    """{component: nbytes} for one query runtime (metadata-only walk)."""
    try:
        return _kind_components(qr)
    except Exception:  # noqa: BLE001 — metrics must not throw
        return {}


def component_bytes(rt) -> Dict[str, Dict[str, int]]:
    """{owner: {component: nbytes}} across an app: every query runtime
    plus shared tables, named windows, and aggregations."""
    out: Dict[str, Dict[str, int]] = {}
    for name, qr in list(getattr(rt, "query_runtimes", {}).items()):
        comps = query_component_bytes(qr)
        if comps:
            out[name] = comps
    for gid, mg in list(getattr(rt, "merged_groups", {}).items()):
        try:
            comps = mg.shared_components()
        except Exception:  # noqa: BLE001 — metrics must not throw
            comps = {}
        if comps:
            out[f"merged:{gid}"] = comps
    for tid, t in list(getattr(rt, "tables", {}).items()):
        n = sum(leaf_nbytes(c) for c in getattr(t, "cols", ())) + \
            leaf_nbytes(getattr(t, "ts", None)) + \
            leaf_nbytes(getattr(t, "valid", None))
        if n:
            out[f"table:{tid}"] = {"rows": n}
    for wid, nw in list(getattr(rt, "named_windows", {}).items()):
        n = tree_nbytes(getattr(nw, "state", None))
        if n:
            out[f"window:{wid}"] = {"buffer": n}
    for aid, agg in list(getattr(rt, "aggregations", {}).items()):
        # one device slab per declared duration (_DurationStore.slab)
        comps = {}
        for dur, store in getattr(agg, "_dstores", {}).items():
            n = tree_nbytes(getattr(store, "slab", None))
            if n:
                comps[dur] = n
        if comps:
            out[f"agg:{aid}"] = comps
    return out


def total_bytes(rt) -> int:
    return sum(n for comps in component_bytes(rt).values()
               for n in comps.values())
