"""In-process time-series sampler: windowed series over every host-side
metric, plus per-tenant accounting.

Reference (what): the reference's StatisticsManager feeds *periodic
reporters* (console/JMX) — metrics are meaningful as trajectories, not
point-in-time scrapes.  The Monarch/Prometheus lineage (PAPERS.md) makes
the same argument in-process: keep a short windowed series next to the
counters and evaluate rules over it, instead of hoping an external
scraper was watching when the incident happened.

TPU design (how): a daemon thread (interval configurable, default 1s;
injectable clock so tests drive ticks without sleeping) snapshots every
counter/gauge/histogram-quantile already maintained by
`StatisticsManager` — plus the shard/sink/errorstore families — into
fixed-size ring-buffer series per app, and derives windowed rates
(events/s, drops/s, recompiles/s) from the cumulative counters.  The
scrape-path invariant of exposition.py/health.py applies verbatim:
**a tick reads host counters and shape/dtype metadata only — no
`device_get`, no pytree fetch** — so sampling a soaked multi-tenant
server costs microseconds of host time per tick and can never stall a
query step.

Per-tenant accounting: each app (tenant) gets series for events in/out,
emitted bytes, dispatch wall-time, recompile blame, and state bytes —
the substrate ROADMAP item 4's admission control needs to answer "which
tenant is eating the box".

Results attach to each runtime (`rt._timeseries`, `rt._tenant_account`,
`rt._slo_state`) so `/metrics`, `/healthz`, and
`GET /siddhi-apps/<app>/timeseries` read them without holding a
reference to the sampler.

Config (manager.config_manager properties):
  metrics.sampler.interval.seconds   tick period        (default 1.0)
  metrics.sampler.window             ring size, ticks   (default 600)
  metrics.sampler.enabled            'false' stops the REST service
                                     from auto-starting one
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

DEFAULT_INTERVAL_S = 1.0
DEFAULT_WINDOW = 600          # ticks retained: 10 min at the 1s default


class Series:
    """Fixed-size ring buffer of (t, value) samples for ONE metric.
    Appends are O(1); the deque's maxlen bounds memory regardless of
    soak duration."""

    __slots__ = ("name", "_buf")

    def __init__(self, name: str, window: int = DEFAULT_WINDOW):
        self.name = name
        self._buf: deque = deque(maxlen=max(2, int(window)))

    def append(self, t: float, v: float) -> None:
        self._buf.append((float(t), float(v)))

    def __len__(self) -> int:
        return len(self._buf)

    @property
    def last(self) -> Optional[float]:
        return self._buf[-1][1] if self._buf else None

    def delta(self) -> float:
        """Change over the most recent tick (0.0 with <2 samples)."""
        if len(self._buf) < 2:
            return 0.0
        return self._buf[-1][1] - self._buf[-2][1]

    def rate(self, window_s: Optional[float] = None) -> float:
        """Slope of a cumulative-counter series over the trailing
        `window_s` seconds (whole ring when None): the windowed per-second
        rate.  Clamped at 0 — counter resets read as quiet, not negative."""
        if len(self._buf) < 2:
            return 0.0
        t1, v1 = self._buf[-1]
        t0, v0 = self._buf[0]
        if window_s is not None:
            for t, v in self._buf:
                if t1 - t <= window_s:
                    t0, v0 = t, v
                    break
        span = t1 - t0
        if span <= 0:
            return 0.0
        return max(0.0, (v1 - v0) / span)

    def to_dict(self) -> Dict[str, List[float]]:
        ts = [t for t, _ in self._buf]
        vs = [v for _, v in self._buf]
        return {"t": ts, "v": vs}


class SeriesStore:
    """All of one app's series: name -> Series ring.  The store itself
    lives on the runtime (`rt._timeseries`) so REST/health read it after
    the sampler that filled it is gone."""

    def __init__(self, window: int = DEFAULT_WINDOW):
        self.window = max(2, int(window))
        self._lock = threading.Lock()
        self._series: Dict[str, Series] = {}

    def series(self, name: str) -> Series:
        s = self._series.get(name)
        if s is not None:
            return s
        with self._lock:
            return self._series.setdefault(name, Series(name, self.window))

    def record(self, name: str, t: float, v) -> None:
        self.series(name).append(t, v)

    def get(self, name: str) -> Optional[Series]:
        return self._series.get(name)

    def names(self) -> List[str]:
        return sorted(self._series)

    def last(self, name: str) -> Optional[float]:
        s = self._series.get(name)
        return s.last if s is not None else None

    def to_dict(self) -> Dict[str, Dict[str, List[float]]]:
        with self._lock:
            items = list(self._series.items())
        return {name: s.to_dict() for name, s in sorted(items)}


def _sink_totals(rt) -> Dict[str, int]:
    """Aggregate sink-connection counters for one app (plain attribute
    reads off the io/resilience state machines)."""
    from ..io.resilience import BROKEN
    retries = dropped = buffered = broken = 0
    for sk in getattr(rt, "sinks", ()):
        for conn in getattr(sk, "connections", ()):
            retries += int(getattr(conn, "retries_total", 0))
            dropped += int(getattr(conn, "dropped_total", 0))
            try:
                buffered += int(conn.buffered())
            except Exception:  # noqa: BLE001 — metrics must not throw
                pass
            if conn.state == BROKEN:
                broken += 1
    return {"retries": retries, "dropped": dropped,
            "buffered": buffered, "broken": broken}


def tenant_account(rt, snap: Optional[Dict] = None) -> Dict:
    """Per-tenant resource accounting for one app runtime, from host
    counters and metadata only: the numbers a future admission controller
    charges a tenant for.  `snap` is a stats exposition_snapshot (taken
    fresh when None)."""
    st = rt.stats
    if snap is None:
        snap = st.exposition_snapshot()
    counters = snap.get("counters", {})
    qhist = snap.get("query_hist", {})
    recompiles = {}
    try:
        recompiles = {owner: info["count"]
                      for owner, info in st.recompiles(rt).items()
                      if info.get("count")}
    except Exception:  # noqa: BLE001 — metrics must not throw
        pass
    from .memory import total_bytes
    sink = _sink_totals(rt)
    return {
        "events_in": sum(snap.get("stream_in", {}).values()),
        "events_out": sum(v for k, v in counters.items()
                          if k.endswith(".emitted_rows")),
        "emitted_bytes": sum(v for k, v in counters.items()
                             if k.endswith(".emitted_bytes")),
        # total wall time spent inside query dispatch (base per-query
        # histograms only: `:e2e` carries queue wait, not dispatch work,
        # and `:fused` dispatches are already inside the triggering
        # batch's base sample — both would double-bill the tenant)
        "dispatch_wall_ns": sum(h.sum_ns for k, h in qhist.items()
                                if ":" not in k),
        "dropped": sum(v for k, v in counters.items()
                       if k.endswith(".dropped")) + sink["dropped"],
        "cap_growths": sum(v for k, v in counters.items()
                           if k.endswith(".cap_growths")),
        "recompiles": sum(recompiles.values()),
        "recompile_blame": recompiles,
        "state_bytes": total_bytes(rt),
        "sink_retries": sink["retries"],
        "queue_depth": sum(rt.queue_depths().values())
        if hasattr(rt, "queue_depths") else 0,
        # admission charges (core/admission.py): decided-not-discovered
        # overload, so shed/blocked work is attributed per tenant too
        "admission_shed": getattr(getattr(rt, "admission", None),
                                  "shed_total", 0),
        "admission_blocked_ms": getattr(getattr(rt, "admission", None),
                                        "blocked_ms_total", 0),
        # state observatory (observability/stateobs.py): the worst
        # fixed-capacity utilization and the deepest high-water a
        # tenant's structures have reached — the sizing exposure an
        # admission controller would charge for
        "state_worst_utilization": _stateobs_worst(snap),
        "state_high_water_sum": sum(
            rec.get("high_water", 0)
            for structures in snap.get("stateobs", {})
            .get("structures", {}).values()
            for rec in structures.values()),
    }


def _stateobs_worst(snap: Dict) -> float:
    worst = 0.0
    for structures in snap.get("stateobs", {}).get("structures",
                                                   {}).values():
        for rec in structures.values():
            if not rec.get("growable", True):
                worst = max(worst, rec.get("utilization", 0.0))
    return round(worst, 4)


class TimeSeriesSampler:
    """Samples every deployed app on a fixed tick into per-app
    `SeriesStore` rings and evaluates the SLO engine over them.

    Tests drive `tick(now)` directly with a virtual clock — the thread
    is only the production scheduler around it."""

    def __init__(self, manager, interval_s: Optional[float] = None,
                 window: Optional[int] = None, rules=None,
                 clock: Optional[Callable[[], float]] = None):
        cm = getattr(manager, "config_manager", None)

        def prop(name):
            try:
                return cm.extract_property(name) if cm is not None else None
            except Exception:  # noqa: BLE001 — config must not break boot
                return None

        if interval_s is None:
            interval_s = float(prop("metrics.sampler.interval.seconds")
                               or DEFAULT_INTERVAL_S)
        if window is None:
            window = int(prop("metrics.sampler.window") or DEFAULT_WINDOW)
        self.manager = manager
        self.interval_s = max(0.01, float(interval_s))
        self.window = max(2, int(window))
        self._clock = clock if clock is not None else time.monotonic
        from .slo import SLOEngine
        self.slo = SLOEngine(rules=rules, config=cm)
        self.ticks = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_tick_wall_ns = 0      # host cost of the last tick

    # -- sampling --------------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> None:
        """One sampling pass over every app.  Host-side reads only."""
        now = self._clock() if now is None else float(now)
        t_wall = time.perf_counter_ns()
        for name, rt in list(getattr(self.manager, "runtimes", {}).items()):
            try:
                self._sample_app(name, rt, now)
            except Exception:  # noqa: BLE001 — one sick app must not
                pass           # starve the others' series
        self.ticks += 1
        self._last_tick_wall_ns = time.perf_counter_ns() - t_wall

    def _sample_app(self, name: str, rt, now: float) -> None:
        store = rt.__dict__.get("_timeseries")
        if store is None or store.window != self.window:
            store = rt.__dict__["_timeseries"] = SeriesStore(self.window)
        st = rt.stats
        # refresh the state observatory from the host mirrors before
        # snapshotting, so the tick's series see current occupancy
        from .stateobs import collect as _stateobs_collect
        _stateobs_collect(rt)
        snap = st.exposition_snapshot()
        acct = tenant_account(rt, snap)
        rt._tenant_account = acct

        rec = store.record
        # tenant accounting: cumulative counters sampled as series
        rec("events_in", now, acct["events_in"])
        rec("events_out", now, acct["events_out"])
        rec("emitted_bytes", now, acct["emitted_bytes"])
        rec("dispatch_wall_ns", now, acct["dispatch_wall_ns"])
        rec("dropped", now, acct["dropped"])
        rec("cap_growths", now, acct["cap_growths"])
        rec("recompiles", now, acct["recompiles"])
        rec("state_bytes", now, acct["state_bytes"])
        # queue/backpressure gauges
        rec("buffered_emissions", now, rt.buffered_emissions()
            if hasattr(rt, "buffered_emissions") else 0)
        rec("async_queue_depth", now, acct["queue_depth"])
        rec("drainer_queue_depth", now, rt.drainer_depth()
            if hasattr(rt, "drainer_depth") else 0)
        # sink resilience + error store
        sink = _sink_totals(rt)
        rec("sink_retries", now, sink["retries"])
        rec("sink_dropped", now, sink["dropped"])
        rec("sink_buffered", now, sink["buffered"])
        rec("sink_broken", now, sink["broken"])
        es = getattr(rt, "error_store", None)
        if es is not None:
            try:
                rec("errorstore_buffered", now,
                    es.stats().get("buffered", 0))
            except Exception:  # noqa: BLE001 — custom SPI must not break
                pass
        # per-stream throughput + ingress queue depth
        for sid, n in snap.get("stream_in", {}).items():
            rec(f"stream.{sid}.events", now, n)
        if hasattr(rt, "queue_depths"):
            for sid, d in rt.queue_depths().items():
                rec(f"stream.{sid}.queue_depth", now, d)
        # per-query latency quantiles (cumulative log2 histograms — the
        # series is the TRAJECTORY of the quantile, i.e. the p99 curve
        # the soak artifact plots) + processed-event counters
        for q, h in snap.get("query_hist", {}).items():
            rec(f"query.{q}.p50_us", now, h.quantile(0.50) / 1e3)
            rec(f"query.{q}.p99_us", now, h.quantile(0.99) / 1e3)
        for q, n in snap.get("query_events", {}).items():
            rec(f"query.{q}.events", now, n)
        # phase profiler series: cumulative per-phase ns plus the sampled
        # deep-mode dispatch counter (observability/phases.py) — windowed
        # per-phase rates derive below with the other counter rates
        ph_snap = snap.get("phases", {})
        for q, phases in ph_snap.get("queries", {}).items():
            for p, v in phases.items():
                rec(f"phase.{q}.{p}_ns", now, v["ns"])
        for q, n in ph_snap.get("sampled", {}).items():
            rec(f"phase.{q}.sampled_dispatches", now, n)
        # state observatory series: per-(query, structure) utilization +
        # high-water trajectories and per-query hot-set concentration —
        # the occupancy histogram ROADMAP item 4's tiering design reads
        so_snap = snap.get("stateobs", {})
        for q, structures in so_snap.get("structures", {}).items():
            for s, v in structures.items():
                rec(f"state.{q}.{s}.utilization", now, v["utilization"])
                rec(f"state.{q}.{s}.high_water", now, v["high_water"])
        for q, hot in so_snap.get("hotness", {}).items():
            rec(f"state.{q}.hot_share_1pct", now, hot["hot_share_1pct"])
        # shard balance (meshed apps): skew gauge from host counters
        try:
            from ..sharding import shard_report
            rep = shard_report(rt)
            if rep is not None and rep.get("event_skew_max_over_mean"):
                rec("shard_skew", now, rep["event_skew_max_over_mean"])
        except Exception:  # noqa: BLE001 — metrics must not throw
            pass
        # admission controller series (core/admission.py): the quota
        # ladder's trajectory — shed/blocked counters, quota state, and
        # the effective (possibly degraded) rate limit
        adm = getattr(rt, "admission", None)
        if adm is not None:
            from ..core.admission import QUOTA_GAUGE
            rec("admission_shed", now, adm.shed_total)
            rec("admission_blocked_ms", now, adm.blocked_ms_total)
            rec("admission_growth_denials", now, adm.growth_denials)
            rec("admission_quota_state", now,
                QUOTA_GAUGE.get(adm.quota_state, 0))
            rec("admission_compile_penalties", now,
                adm.compile_penalties)
            eff = adm.effective_rate()
            if eff is not None:
                rec("admission_rate_limit", now, eff)
        # @async(queue.policy='shed') losses, summed across streams
        a_shed = sum(v for k, v in snap.get("counters", {}).items()
                     if k.startswith("async.") and k.endswith(".shed"))
        if a_shed:
            rec("async_shed", now, a_shed)
        # derived windowed rates, recorded as series themselves so the
        # artifact carries the ev/s curve, not just the raw counter
        rate_w = min(60.0, self.window * self.interval_s)
        for src, dst in (("events_in", "rate.events_in_per_s"),
                         ("events_out", "rate.events_out_per_s"),
                         ("dropped", "rate.dropped_per_s"),
                         ("recompiles", "rate.recompiles_per_s")):
            s = store.get(src)
            if s is not None:
                rec(dst, now, s.rate(rate_w))
        # per-phase burn rates (ns of phase wall accumulated per second):
        # the live view of where the pipeline budget is going right now
        for q, phases in ph_snap.get("queries", {}).items():
            for p in phases:
                s = store.get(f"phase.{q}.{p}_ns")
                if s is not None:
                    rec(f"rate.phase.{q}.{p}_ns_per_s", now,
                        s.rate(rate_w))
        # SLO rules evaluate over the freshly-appended series
        rt._slo_state = self.slo.evaluate(name, rt, store, now)
        # ... and the mitigation ladder climbs on the verdict: under
        # admission.overload='degrade' a FIRING tick halves the app's
        # effective ingest rate; sustained ok ticks recover it
        if adm is not None:
            try:
                adm.on_slo(rt._slo_state, now)
            except Exception:  # noqa: BLE001 — ladder must not kill tick
                pass

    # -- thread lifecycle ------------------------------------------------------
    def start(self) -> "TimeSeriesSampler":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="siddhi-sampler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — sampler must not die
                pass
