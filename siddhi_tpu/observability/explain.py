"""Query EXPLAIN: planned operator tree + per-step XLA cost analysis.

Reference (what): the reference exposes per-operator runtime statistics and
an event-flow debugger (SiddhiAppRuntime.getStatistics / SiddhiDebugger),
so an operator can see which processor in a query chain owns the time.
TPU design (how): our "operators" compile into a handful of jitted XLA
programs (query step, per-stream pattern steps, join side steps, fused
scan steps), so the right introspection unit is the *compiled step*:
`explain()` renders the syntactic operator chain (filter / window /
stream-fn / join / NFA stages from the query AST) next to the compiled
facts — carry/state dtypes and shapes, emission caps, fusion eligibility
— and annotates each jitted step with XLA `cost_analysis()` (flops, bytes
accessed) plus `memory_analysis()` (argument/output/temp bytes = the
estimated device peak) from a re-lowering of the step at the signature it
last actually ran (steputil.jit_step captures the argument
ShapeDtypeStructs at trace time).

The diagnostic re-trace runs under `RECOMPILES.suppress()` so EXPLAIN can
never inflate the recompile counters it sits next to, and lowered cost
reports are memoized per (step, signature) on the runtime, so a repeated
`GET /explain` costs one dict lookup.  EXPLAIN may compile (deep=True);
it is an on-demand diagnostic, NOT scrape-path — `/metrics` and
`/healthz` never call it.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .recompile import RECOMPILES

# cost_analysis keys worth surfacing (the raw dict carries per-operand
# utilization entries too noisy for a report)
_COST_KEYS = ("flops", "transcendentals", "bytes accessed")


# ---------------------------------------------------------------------------
# expression / AST rendering (SiddhiQL-ish, for the operator tree)
# ---------------------------------------------------------------------------

_BINOPS = {"Add": "+", "Subtract": "-", "Multiply": "*", "Divide": "/",
           "Mod": "%", "And": "and", "Or": "or"}


def render_expr(e) -> str:
    """Compact one-line rendering of a query_api expression tree."""
    from ..query_api import expression as ex
    if e is None:
        return ""
    if isinstance(e, ex.Constant):
        return repr(e.value)
    if isinstance(e, ex.Variable):
        pre = f"{e.stream_id}." if e.stream_id else ""
        if e.stream_index is not None:
            pre = f"{e.stream_id}[{e.stream_index}]."
        return pre + e.attribute_name
    if isinstance(e, ex.Compare):
        return (f"{render_expr(e.left)} {e.operator} "
                f"{render_expr(e.right)}")
    if isinstance(e, ex.Not):
        return f"not ({render_expr(e.expression)})"
    if isinstance(e, ex.IsNull):
        if e.expression is not None:
            return f"{render_expr(e.expression)} is null"
        return f"{e.stream_id} is null"
    if isinstance(e, ex.In):
        return f"{render_expr(e.expression)} in {e.source_id}"
    if isinstance(e, ex.AttributeFunction):
        ns = f"{e.namespace}:" if e.namespace else ""
        args = ", ".join(render_expr(p) for p in e.parameters)
        return f"{ns}{e.name}({args})"
    op = _BINOPS.get(type(e).__name__)
    if op is not None:
        return f"({render_expr(e.left)} {op} {render_expr(e.right)})"
    return type(e).__name__


def _handler_nodes(sis) -> List[Dict]:
    """filter/window/stream-fn chain of a SingleInputStream, in order."""
    from ..query_api.query import Filter, StreamFunction, Window
    out: List[Dict] = []
    for h in getattr(sis, "stream_handlers", ()):
        if isinstance(h, Filter):
            out.append({"op": "filter",
                        "expression": render_expr(h.expression)})
        elif isinstance(h, Window):
            name = (h.namespace + ":" if h.namespace else "") + h.name
            out.append({"op": "window", "name": name,
                        "parameters": [render_expr(p)
                                       for p in h.parameters]})
        elif isinstance(h, StreamFunction):
            name = (h.namespace + ":" if h.namespace else "") + h.name
            out.append({"op": "function", "name": name,
                        "parameters": [render_expr(p)
                                       for p in h.parameters]})
    return out


def _state_node(el) -> Dict:
    """Recursive rendering of a pattern/sequence state-element tree."""
    from ..query_api import query as q
    if isinstance(el, q.StreamStateElement):
        sis = el.basic_single_input_stream
        return {"op": "stream", "stream": sis.stream_id,
                "handlers": _handler_nodes(sis)}
    if isinstance(el, q.AbsentStreamStateElement):
        sis = el.basic_single_input_stream
        return {"op": "absent", "stream": sis.stream_id,
                "waiting_time_ms": el.waiting_time,
                "handlers": _handler_nodes(sis)}
    if isinstance(el, q.CountStateElement):
        return {"op": "count", "min": el.min_count, "max": el.max_count,
                "of": _state_node(el.stream_state_element)}
    if isinstance(el, q.LogicalStateElement):
        return {"op": el.type.lower(),
                "left": _state_node(el.stream_state_element_1),
                "right": _state_node(el.stream_state_element_2)}
    if isinstance(el, q.NextStateElement):
        return {"op": "next", "first": _state_node(el.state_element),
                "then": _state_node(el.next_state_element)}
    if isinstance(el, q.EveryStateElement):
        return {"op": "every", "of": _state_node(el.state_element)}
    return {"op": type(el).__name__}


def _selector_node(sel, planned) -> Dict:
    node: Dict[str, Any] = {"op": "select"}
    if sel is not None:
        if sel.selection_list:
            node["projection"] = [
                {"as": a.name, "expression": render_expr(a.expression)}
                for a in sel.selection_list]
        else:
            node["projection"] = "*"
        if sel.group_by_list:
            node["group_by"] = [render_expr(v) for v in sel.group_by_list]
        if sel.having_expression is not None:
            node["having"] = render_expr(sel.having_expression)
        if sel.order_by_list:
            node["order_by"] = [f"{render_expr(o.variable)} {o.order}"
                                for o in sel.order_by_list]
        if sel.limit is not None:
            node["limit"] = sel.limit
    out = getattr(planned, "out_schema", None)
    if out is not None:
        node["out_columns"] = list(out.names)
    return node


# ---------------------------------------------------------------------------
# state / carry description
# ---------------------------------------------------------------------------

def describe_state(state) -> List[Dict]:
    """One entry per state-pytree leaf: path, dtype, shape, nbytes —
    computed from shape/dtype metadata only (never fetches device data)."""
    import jax
    from .memory import leaf_nbytes
    out: List[Dict] = []
    try:
        flat = jax.tree_util.tree_flatten_with_path(state)[0]
    except Exception:  # noqa: BLE001 — diagnostics must not throw
        return out
    for path, leaf in flat:
        keys = "".join(str(p) for p in path) or "/"
        out.append({
            "path": keys,
            "dtype": str(getattr(leaf, "dtype", type(leaf).__name__)),
            "shape": list(getattr(leaf, "shape", ())),
            "nbytes": leaf_nbytes(leaf),
        })
    return out


# ---------------------------------------------------------------------------
# XLA cost analysis of jitted steps
# ---------------------------------------------------------------------------

def _spec_sig(specs) -> str:
    import jax
    try:
        return " ".join(f"{s.dtype}{list(s.shape)}"
                        for s in jax.tree_util.tree_leaves(specs))
    except Exception:  # noqa: BLE001
        return repr(specs)


def step_cost(fn, cache: Optional[Dict] = None,
              deep: bool = True, specs=None,
              collectives: bool = False) -> Dict:
    """XLA cost analysis of one jitted step at its last-traced signature.

    Returns {available, flops, bytes_accessed, peak_bytes, ...} or
    {available: False, reason} when the step has not run yet (no captured
    signature) or the backend rejects the analysis.  `deep=True` also
    compiles the lowering for memory_analysis (argument/output/temp
    bytes); the result is memoized in `cache` keyed by (owner, signature)
    so repeated EXPLAINs never re-lower.

    `specs` supplies synthesized argument ShapeDtypeStructs for steps
    that have never traced (analysis/signatures.py) — the plan auditor's
    no-traffic path; a captured (traced) signature always wins so
    EXPLAIN keeps reporting what actually ran.  `collectives=True` also
    scans the compiled HLO for collective ops (implies compiling)."""
    holder = getattr(fn, "_siddhi_argspec", None)
    traced = holder.get("argspecs") if holder else None
    origin = "traced" if traced is not None else "synthesized"
    if traced is not None:
        specs = traced
    if specs is None:
        return {"available": False,
                "reason": "step has not executed yet — send traffic, "
                          "then re-run explain"}
    owner = getattr(fn, "_siddhi_owner", "step")
    sig = _spec_sig(specs)
    key = (owner, id(fn), sig, bool(deep), bool(collectives))
    if cache is not None:
        hit = cache.get(key)
        if hit is not None:
            return hit
    out: Dict[str, Any] = {"available": True, "signature": sig,
                           "signature_origin": origin}
    try:
        with RECOMPILES.suppress():
            lowered = fn.lower(*specs)
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        for k in _COST_KEYS:
            if k in ca:
                out[k.replace(" ", "_")] = float(ca[k])
        if deep or collectives:
            with RECOMPILES.suppress():
                compiled = lowered.compile()
            ma = compiled.memory_analysis()
            arg = int(getattr(ma, "argument_size_in_bytes", 0))
            outb = int(getattr(ma, "output_size_in_bytes", 0))
            tmp = int(getattr(ma, "temp_size_in_bytes", 0))
            alias = int(getattr(ma, "alias_size_in_bytes", 0))
            out["memory"] = {
                "argument_bytes": arg, "output_bytes": outb,
                "temp_bytes": tmp, "alias_bytes": alias,
                # live-at-once estimate while the step executes
                "peak_bytes": arg + outb + tmp - alias,
            }
            if collectives:
                from ..sharding.metrics import hlo_collectives
                out["collectives"] = hlo_collectives(compiled)
    except Exception as exc:  # noqa: BLE001 — diagnostics must not throw
        return {"available": False, "signature": sig,
                "reason": f"cost analysis failed: {exc!r}"}
    if cache is not None:
        if len(cache) >= 64:
            cache.clear()
        cache[key] = out
    return out


def _steps_of(qr, kind: str) -> List[Tuple[str, Any]]:
    """(role, jitted fn) pairs for a query runtime — every compiled XLA
    program that can run on the query's hot path."""
    p = qr.planned
    steps: List[Tuple[str, Any]] = []
    if kind == "pattern":
        # each variant is its own XLA program: the plain per-stream step,
        # the ts-delta wire twin (steps_w — what steady-state traffic
        # actually runs), and the contiguous-slot dense specialization
        for role, d in (("step", p.steps), ("step_w", p.steps_w),
                        ("dense_step", getattr(p, "dense_steps", None)),
                        ("dense_step_w",
                         getattr(p, "dense_steps_w", None)),
                        ("shard_fused_step",
                         getattr(p, "shard_fused_steps", None))):
            for sid, fn in (d or {}).items():
                steps.append((f"{role}[{sid}]", fn))
        if p.timer_step is not None:
            steps.append(("timer_step", p.timer_step))
    elif kind == "join":
        if p.step_left is not None:
            steps.append(("step[left]", p.step_left))
        if p.step_right is not None:
            steps.append(("step[right]", p.step_right))
    else:
        steps.append(("step", p.step))
    for (fkind, _), (body, fn) in getattr(qr, "_fused_cache", {}).items():
        steps.append((f"fused_step[{fkind}]", fn))
    mg = getattr(qr, "_merged", None)
    if mg is not None:
        # the program a merged member ACTUALLY dispatches through
        # (optimizer/mqo.py); costs appear once it has traced — the
        # audit gate pins merging via the `merge` fact instead, so this
        # traced-only entry can never make fingerprints nondeterministic
        steps.append(("merged_step", mg._step))
        for (fkind, _), (body, fn) in \
                getattr(mg, "_fused_cache", {}).items():
            steps.append((f"merged_fused_step[{fkind}]", fn))
    return steps


# ---------------------------------------------------------------------------
# the report
# ---------------------------------------------------------------------------

def _runtime_kind(qr) -> str:
    kind = getattr(qr, "_kind", None)   # set at wiring (runtime._maybe_fuse)
    if kind in ("plain", "pattern", "join"):
        return kind
    p = qr.planned
    if isinstance(getattr(p, "steps", None), dict):
        return "pattern"
    if hasattr(p, "step_left"):
        return "join"
    return "plain"


def _fusion_node(qr, kind: str) -> Dict:
    from ..core import fusion as _fusion
    return _fusion.eligibility(qr, kind)


def _merge_node(qr) -> Dict:
    """Multi-query-optimizer fact for this query (core/plan_facts.
    merge_facts): group/owner/mode/members when merged, the planner's
    exact ineligibility reason otherwise — the same single source lint
    MQO001 prints."""
    from ..core.plan_facts import merge_facts
    try:
        return merge_facts(qr)
    except Exception:  # noqa: BLE001 — diagnostics must not throw
        return {"merged": False}


def _sharding_entry(qr, kind: str, deep: bool) -> Dict:
    """{'sharding': node} for mesh-sharded queries (shard layout,
    per-shard residency, and — deep — the collectives in the compiled
    HLO), {} for single-device plans."""
    try:
        from ..sharding import explain_node
        node = explain_node(qr, kind, deep=deep)
    except Exception:  # noqa: BLE001 — diagnostics must not throw
        node = None
    return {"sharding": node} if node is not None else {}


def _emission_node(qr, kind: str) -> Dict:
    from ..core.plan_facts import render_cap
    p = qr.planned
    node: Dict[str, Any] = {}
    cap = getattr(p, "compact_rows", None)
    if cap is not None:
        node["cap_rows"] = render_cap(cap)
        node["cap_explicit"] = bool(getattr(p, "emit_explicit", True))
    bc = getattr(p, "batch_capacity", None)
    if bc is not None:
        node["batch_capacity"] = int(bc)
    if kind == "pattern":
        node["per_key"] = True
    return node


def _serving_node(rt, qr) -> Dict:
    """Device-resident serving facts (serving/ring.py): whether @serve
    routes this query's emissions through an on-device ring, the live
    ring occupancy/overflow counters once traffic has flowed, and the
    exclusion reason when the planner keeps delivery inline."""
    enabled = bool(getattr(qr, "serve_emit", False))
    node: Dict[str, Any] = {"enabled": enabled}
    if not enabled:
        return node
    try:
        from ..serving import serving_config
        node["drain_interval_ms"] = \
            serving_config(rt)["drain_interval_ms"]
    except Exception:  # noqa: BLE001 — diagnostics must not throw
        pass
    if getattr(qr.planned, "needs_timer", False):
        # same exclusion as @pipeline: timer-bearing queries deliver
        # inline so wake scheduling stays synchronous
        node["active"] = False
        node["excluded"] = "needs_timer"
        return node
    node["active"] = True
    ring = qr.__dict__.get("_serve_ring")
    if ring is not None:
        try:
            node["ring"] = ring.facts()
        except Exception:  # noqa: BLE001 — diagnostics must not throw
            pass
    return node


def _phases_node(rt, qr) -> Dict:
    """Live phase budget for this query (observability/phases.py): the
    per-phase seconds/share entry from phase_report, or a hint to send
    traffic when nothing has accumulated yet.  Host counters only."""
    try:
        rep = rt.phase_report()
        node = rep.get("queries", {}).get(qr.name)
        if node is None:
            return {"available": False,
                    "reason": "no phase samples yet — send traffic, "
                              "then re-run explain"}
        return {"available": True,
                "sample_every": rep.get("sample_every", 0), **node}
    except Exception:  # noqa: BLE001 — diagnostics must not throw
        return {"available": False, "reason": "phase report failed"}


def _utilization_node(rt, qr) -> Dict:
    """Live state-observatory view for this query (observability/
    stateobs.py): per-structure occupancy/capacity/high-water plus key
    hotness, or a hint to send traffic.  Host mirrors only — this node
    never touches the device."""
    try:
        from .stateobs import collect, obs_enabled
        if not obs_enabled(rt):
            return {"available": False,
                    "reason": "state observatory disabled "
                              "(state.obs.enabled=false)"}
        collect(rt)
        snap = rt.stats.stateobs.snapshot()
        structures = snap["structures"].get(qr.name)
        hotness = snap["hotness"].get(qr.name)
        if not structures and not hotness:
            return {"available": False,
                    "reason": "no sized structures observed yet — send "
                              "traffic, then re-run explain"}
        return {"available": True,
                "structures": structures or {},
                **({"hotness": hotness} if hotness else {})}
    except Exception:  # noqa: BLE001 — diagnostics must not throw
        return {"available": False, "reason": "state report failed"}


def _tree_for(qr, kind: str) -> Dict:
    """Planned operator tree from the query AST + compiled plan facts."""
    from ..query_api.query import (JoinInputStream, SingleInputStream,
                                   StateInputStream)
    p = qr.planned
    ast = getattr(qr, "_query_ast", None)
    tree: Dict[str, Any] = {"kind": kind}
    ist = getattr(ast, "input_stream", None) if ast is not None else None
    if isinstance(ist, StateInputStream):
        tree["pattern"] = {
            "type": ist.state_type.lower(),
            "within_ms": ist.within_time,
            "states": _state_node(ist.state_element),
        }
        tree["key_capacity"] = getattr(p, "key_capacity", None)
        tree["nfa_slots"] = getattr(p, "slots", None)
    elif isinstance(ist, JoinInputStream):
        sides = {}
        for label, sis in (("left", ist.left_input_stream),
                           ("right", ist.right_input_stream)):
            sides[label] = {"stream": sis.stream_id,
                            "handlers": _handler_nodes(sis)}
        tree["join"] = {
            "type": ist.type,
            "on": render_expr(ist.on_compare),
            "trigger": ist.trigger,
            **sides,
        }
    elif isinstance(ist, SingleInputStream):
        tree["input"] = {"stream": ist.unique_stream_id,
                         "handlers": _handler_nodes(ist)}
    else:
        tree["input"] = {"stream": getattr(p, "input_stream_id", "?")}
    w = getattr(p, "window", None)
    if w is not None:
        tree["window_processor"] = {
            "class": type(w).__name__,
            "needs_timer": bool(getattr(w, "needs_timer", False)),
            "keyed": bool(getattr(p, "keyed_window", False)),
        }
    sel = getattr(ast, "selector", None) if ast is not None else None
    tree["select"] = _selector_node(sel, p)
    tree["output"] = {
        "target": getattr(p, "output_target", "") or "(return)",
        "event_type": getattr(p, "output_event_type", "ALL_EVENTS"),
    }
    return tree


def explain_query(rt, query_name: str, deep: bool = True) -> Dict:
    """Full EXPLAIN report for one query of a SiddhiAppRuntime: operator
    tree, per-step XLA cost analysis, state shapes + bytes, emission caps,
    fusion eligibility, and recompile history."""
    qr = rt.query_runtimes.get(query_name)
    if qr is None:
        raise KeyError(f"no query named {query_name!r} "
                       f"(queries: {sorted(rt.query_runtimes)})")
    kind = _runtime_kind(qr)
    cache = rt.__dict__.setdefault("_explain_cost_cache", {})
    # canonical no-traffic signatures (analysis/signatures.py): steps
    # that have never traced still get cost analysis, marked
    # signature_origin='synthesized'
    try:
        from ..analysis.signatures import synthesize
        synth = synthesize(qr, kind)
    except Exception:  # noqa: BLE001 — diagnostics must not throw
        synth = {}
    steps = {}
    for role, fn in _steps_of(qr, kind):
        steps[role] = step_cost(fn, cache, deep=deep,
                                specs=synth.get(role))
    from .memory import query_component_bytes
    try:
        plan = qr.planned.describe()     # compiled facts from the planner
    except Exception:  # noqa: BLE001 — diagnostics must not throw
        plan = {}
    leaves = describe_state(qr.state)
    report = {
        "app": rt.name,
        "query": query_name,
        "kind": kind,
        "operator_tree": _tree_for(qr, kind),
        "plan": plan,
        "steps": steps,
        "state": {
            "leaves": leaves,
            "component_bytes": query_component_bytes(qr),
            "total_bytes": sum(d["nbytes"] for d in leaves),
        },
        "emission": _emission_node(qr, kind),
        "fusion": _fusion_node(qr, kind),
        "merge": _merge_node(qr),
        "serving": _serving_node(rt, qr),
        "phases": _phases_node(rt, qr),
        "utilization": _utilization_node(rt, qr),
        **_sharding_entry(qr, kind, deep),
        "recompiles": RECOMPILES.snapshot(
            [query_name, f"fused:{query_name}"]),
        "findings": _lint_findings(rt, query_name),
    }
    return report


def _lint_findings(rt, query_name: Optional[str]) -> List[Dict]:
    """Static-analyzer findings echoed into the EXPLAIN report: app-wide
    findings plus the named query's (attribute/metadata reads only — no
    compile, safe even for shallow explain)."""
    try:
        from ..analysis import analyze
        return [f.to_dict() for f in analyze(rt)
                if query_name is None or f.query in (None, query_name)]
    except Exception:  # noqa: BLE001 — diagnostics must not throw
        return []


def _admission_entry(rt) -> Dict:
    """{'admission': report} — the app's quota/ladder state rendered
    into EXPLAIN so capacity questions and plan questions are answered
    in one place (core/admission.py; attribute reads only)."""
    adm = getattr(rt, "admission", None)
    if adm is None:
        return {}
    try:
        return {"admission": adm.report()}
    except Exception:  # noqa: BLE001 — diagnostics must not throw
        return {}


def explain_app(rt, deep: bool = False) -> Dict:
    """EXPLAIN for every query of an app (shallow by default: skips the
    per-step compile for memory analysis)."""
    return {"app": rt.name,
            **_admission_entry(rt),
            "queries": {q: explain_query(rt, q, deep=deep)
                        for q in sorted(rt.query_runtimes)}}
