"""Chrome trace-event (Perfetto) export of the pipeline-trace ring buffer.

Reference (what): Dapper-style distributed trace viewers (Sigelman et al.,
2010) made per-request span trees the standard latency-debugging surface;
the reference engine's event-flow debugger serves the same role per event.
TPU design (how): our PipelineTracer already holds per-batch span trees
(ingest -> query -> step/compile -> emit, plus `fused_step` dispatch
spans); this module converts that ring buffer to the Chrome trace-event
JSON format, so `GET /trace.json` downloads a file that opens DIRECTLY in
Perfetto (ui.perfetto.dev) or `chrome://tracing` with no translation step.

Layout: one Chrome *process* per app, one *thread* (track) per batch
trace — a batch's spans nest by time on its own track, and slow batches
stand out as long tracks.  Timestamps are the tracer's own
`perf_counter_ns` values scaled to microseconds: monotonic process-wide,
so tracks order correctly across batches.  Spans recorded under a
cross-thread adoption (tracing.adopt — drainer deliveries tagged
`track="drain"`) render on ONE shared per-app "drain" track, and each
trace with drain-side spans gets a flow arrow (`ph:"s"`/`ph:"f"`,
id = trace id) from its dispatch track to the delivery span, so Perfetto
draws the handoff the serving loop actually performs.

Also here: the guarded `jax.profiler` start/stop used by
`POST /profiler/start|stop` for device-level deep dives (XLA ops, HBM) —
one active session at a time, never started implicitly.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

# drain tracks sit far above any realistic trace id so they never collide
# with per-batch tids (trace ids are a process-global counter from 1)
_DRAIN_TID_BASE = 1_000_000_000


def trace_events(runtimes: Dict, query: Optional[str] = None,
                 limit: int = 256) -> List[Dict]:
    """Flat trace-event list for every app's recent batch traces."""
    events: List[Dict] = []
    for pid, (app_name, rt) in enumerate(sorted(runtimes.items()), 1):
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": f"siddhi:{app_name}"}})
        # all drain-side (adopted) spans of an app share one track: the
        # drainer really is one thread, and a shared track makes its
        # serialised deliveries visually obvious
        drain_tid = _DRAIN_TID_BASE + pid
        drain_named = False
        for tr in rt.trace_dump(query, limit):
            tid = int(tr["trace_id"])
            spans = tr.get("spans", ())
            # batch-level umbrella event spans the whole dispatch
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": f"batch {tr['trace_id']} "
                                 f"[{tr['stream']}]"}})
            # offsets are relative to the batch start; re-anchor on the
            # batch's wall clock (ms resolution) so tracks align in time
            base_us = float(tr.get("wall_ms", 0)) * 1e3
            events.append({
                "ph": "X", "name": f"dispatch {tr['stream']}",
                "cat": "batch", "pid": pid, "tid": tid,
                "ts": base_us, "dur": float(tr.get("total_us", 0.0)),
                "args": {"events": tr.get("events"),
                         "trace_id": tr.get("trace_id")}})
            first_drain_ts = None
            last_dispatch_end = base_us
            for s in spans:
                on_drain = s.get("track") == "drain"
                ts = base_us + float(s.get("offset_us") or 0.0)
                dur = float(s.get("duration_us", 0.0))
                args = {k: v for k, v in s.items()
                        if k not in ("stage", "duration_us", "offset_us",
                                     "track")}
                events.append({
                    "ph": "X", "name": s["stage"], "cat": "span",
                    "pid": pid, "tid": drain_tid if on_drain else tid,
                    "ts": ts, "dur": dur, "args": args})
                if on_drain:
                    if first_drain_ts is None or ts < first_drain_ts:
                        first_drain_ts = ts
                else:
                    last_dispatch_end = max(last_dispatch_end, ts + dur)
            if first_drain_ts is None:
                continue
            # flow arrow: dispatch track -> drainer delivery.  The start
            # binds at the last dispatch-side span (the emit/handoff) and
            # the finish (bp:"e" = bind to enclosing slice) at the first
            # adopted span, so Perfetto draws one arrow per batch.
            if not drain_named:
                drain_named = True
                events.append({
                    "ph": "M", "name": "thread_name", "pid": pid,
                    "tid": drain_tid, "args": {"name": "drain"}})
            flow_id = int(tr["trace_id"])
            events.append({
                "ph": "s", "name": "handoff", "cat": "flow",
                "id": flow_id, "pid": pid, "tid": tid,
                "ts": min(last_dispatch_end, first_drain_ts)})
            events.append({
                "ph": "f", "bp": "e", "name": "handoff", "cat": "flow",
                "id": flow_id, "pid": pid, "tid": drain_tid,
                "ts": first_drain_ts})
    # a stable time order keeps the JSON loadable by strict parsers and
    # the tracks deterministic (metadata records lead, then global ts
    # order across all processes)
    events.sort(key=lambda e: (0 if e["ph"] == "M" else 1,
                               e.get("ts", 0.0)))
    return events


def chrome_trace(runtimes: Dict, query: Optional[str] = None,
                 limit: int = 256) -> Dict:
    """Chrome trace-event JSON object (the format Perfetto ingests)."""
    return {
        "traceEvents": trace_events(runtimes, query, limit),
        "displayTimeUnit": "ms",
        "otherData": {"source": "siddhi_tpu PipelineTracer",
                      "format": "chrome-trace-event"},
    }


# ---------------------------------------------------------------------------
# jax.profiler guard: explicit start/stop, one session at a time
# ---------------------------------------------------------------------------

_prof_lock = threading.Lock()
_prof_dir: Optional[str] = None


def start_profiler(log_dir: str = "/tmp/siddhi_tpu_profile") -> Dict:
    """Start a jax.profiler trace session (device-level deep dive).
    Returns {started, log_dir} or raises RuntimeError when a session is
    already active (the profiler is process-global — two sessions would
    corrupt each other's capture)."""
    global _prof_dir
    with _prof_lock:
        if _prof_dir is not None:
            raise RuntimeError(
                f"profiler already running (log_dir={_prof_dir!r}); "
                f"POST /profiler/stop first")
        import jax
        jax.profiler.start_trace(log_dir)
        _prof_dir = log_dir
    return {"started": True, "log_dir": log_dir}


def stop_profiler() -> Dict:
    """Stop the active jax.profiler session; raises RuntimeError when
    none is running."""
    global _prof_dir
    with _prof_lock:
        if _prof_dir is None:
            raise RuntimeError("no profiler session running")
        import jax
        d, _prof_dir = _prof_dir, None
        jax.profiler.stop_trace()
    return {"stopped": True, "log_dir": d}


def profiler_status() -> Dict:
    with _prof_lock:
        return {"running": _prof_dir is not None, "log_dir": _prof_dir}
