"""Health probes: readiness vs. liveness, stream staleness, backlog, and
sliding-window drop/recompile rates.

Reference (what): the reference's monitoring story distinguishes "the
JVM answers" from "the app processes events" (isRunning + per-stream
throughput gauges).  TPU design (how): against a remote accelerator the
operator's first question about a stalled stream is *backlog problem or
dead source?* — so `/healthz` reports, per stream, both the async-ingress
backlog depth AND the last-event age, and classifies each stream from
the pair.  Rates (drops, emission-cap growths, XLA recompiles) are
reported over a sliding window sampled at probe time from the cumulative
counters — a counter that jumped an hour ago must not keep a deployment
red forever.  The window is the `health.window.seconds` manager config
property (default 60).  When the time-series sampler is running
(observability/timeseries.py), each app also reports its `slo` section
and a FIRING rule flips the `degraded` verdict.

Verdicts are distinct by design:

- **live**: the engine's own threads (scheduler, emission drainer) are
  running for every started app — restart-worthy when false.
- **ready**: every app is started and accepting ingress (the snapshot
  quiesce gate is open) — route-traffic-elsewhere-worthy when false,
  e.g. during deploy or a long persist.

Scrape-path invariant (same as exposition.py): probes read host-side
counters, thread states, and queue depths only — never `device_get`,
never a pytree fetch — so a flapping health checker can't stall a query
step or pay a tunnel roundtrip.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Dict, Optional, Tuple

_WINDOW_S = 60.0


def _window_s(rt) -> float:
    """Sliding-rate window in seconds: the `health.window.seconds` config
    property of the owning manager (default 60).  Memoized per runtime —
    probes run every few seconds and the property cannot change under a
    live manager."""
    w = rt.__dict__.get("_health_window_s")
    if w is not None:
        return w
    w = _WINDOW_S
    try:
        cm = getattr(getattr(rt, "manager", None), "config_manager", None)
        v = cm.extract_property("health.window.seconds") \
            if cm is not None else None
        if v:
            w = float(v)
    except Exception:  # noqa: BLE001 — probe must not throw
        w = _WINDOW_S
    rt.__dict__["_health_window_s"] = w
    return w


class SlidingRate:
    """Rate of a cumulative counter over a trailing window: each probe
    appends (monotonic_t, value) and evicts samples older than the
    window; the rate is the slope across the retained span."""

    __slots__ = ("window_s", "samples")

    def __init__(self, window_s: float = _WINDOW_S):
        self.window_s = window_s
        self.samples: deque = deque(maxlen=256)

    def observe(self, value: float, now: Optional[float] = None) -> float:
        t = time.monotonic() if now is None else now
        self.samples.append((t, float(value)))
        while len(self.samples) > 1 and \
                t - self.samples[0][0] > self.window_s:
            self.samples.popleft()
        t0, v0 = self.samples[0]
        span = t - t0
        if span <= 0:
            return 0.0
        return max(0.0, (float(value) - v0) / span)


def _rates_of(rt) -> Dict[str, SlidingRate]:
    return rt.__dict__.setdefault("_health_rates", {})


def _rate(rt, key: str, value: float) -> float:
    rates = _rates_of(rt)
    r = rates.get(key)
    if r is None:
        r = rates[key] = SlidingRate(_window_s(rt))
    return r.observe(value)


def _counter_sums(snap_counters: Dict[str, int]) -> Tuple[int, int]:
    drops = sum(v for k, v in snap_counters.items()
                if k.endswith(".dropped"))
    growths = sum(v for k, v in snap_counters.items()
                  if k.endswith(".cap_growths"))
    return drops, growths


def _threads_live(rt) -> Tuple[bool, Dict[str, bool]]:
    """Engine-thread liveness of one app.  Only meaningful once started;
    a deployed-but-stopped app is live (nothing should be running)."""
    detail: Dict[str, bool] = {}
    if not getattr(rt, "_started", False):
        return True, detail
    sched = getattr(getattr(rt, "_scheduler", None), "_thread", None)
    if sched is not None:
        detail["scheduler"] = bool(sched.is_alive())
    drainer = getattr(rt, "_drainer", None)
    # the drainer thread starts lazily on the first async emission: an
    # idle drainer is healthy, a started-then-dead one is not
    if drainer is not None and getattr(drainer, "_started", False):
        t = getattr(drainer, "_thread", None)
        detail["emission_drainer"] = t is not None and bool(t.is_alive())
    return all(detail.values()) if detail else True, detail


def app_health(rt, now_ms: Optional[int] = None) -> Dict:
    """Health report for one SiddhiAppRuntime (host-side reads only)."""
    now_ms = int(time.time() * 1000) if now_ms is None else now_ms
    started = bool(getattr(rt, "_started", False))
    gate = getattr(rt, "_ingress_gate", None)
    accepting = bool(gate.is_set()) if gate is not None else started
    live, threads = _threads_live(rt)

    st = rt.stats
    snap = st.exposition_snapshot()
    window_s = _window_s(rt)
    last_ms = snap.get("stream_last_ms", {})
    backlog = rt.buffered_ingress()
    qdepth = rt.queue_depths() if hasattr(rt, "queue_depths") else {}
    counters = snap.get("counters", {})
    streams: Dict[str, Dict] = {}
    for sid in sorted(rt.junctions):
        if sid.startswith("!"):
            continue
        seen = last_ms.get(sid)
        age_s = (now_ms - seen) / 1e3 if seen else None
        depth = int(backlog.get(sid, 0))
        queued = int(qdepth.get(sid, 0))
        # @async(queue.policy='shed') losses take precedence in the
        # classification: a shedding queue IS full, but "backlogged"
        # would hide that accepted-load is being dropped right now.
        # "Actively" = sheds moved within the sliding window, or sheds
        # have happened and the queue is still backed up (the first
        # probe has no rate span yet).
        async_shed = int(counters.get(f"async.{sid}.shed", 0))
        shed_rate = _rate(rt, f"async_shed.{sid}", async_shed) \
            if async_shed else 0.0
        if async_shed and (shed_rate > 0 or depth > 0 or queued > 0):
            status = "shedding"            # full queue actively dropping
        elif depth > 0 or queued > 0:
            status = "backlogged"          # source alive, engine behind
        elif seen is None:
            status = "no-events" if st.enabled else "unknown"
        elif age_s is not None and age_s > window_s:
            status = "idle"                # engine drained, source quiet
        else:
            status = "ok"
        streams[sid] = {"last_event_age_s": age_s, "backlog": depth,
                        "queue_depth": queued, "status": status,
                        **({"async_shed": async_shed}
                           if async_shed else {})}

    # sink connection states (io/resilience.py): a BROKEN circuit means
    # events are being shed at the edge — the app still processes, so
    # `ready` stays true, but the verdict detail flips to degraded and
    # routing dashboards can alarm on it
    from ..io.resilience import BROKEN
    sinks: Dict[str, Dict] = {}
    degraded = False
    for sk in getattr(rt, "sinks", ()):
        for i, conn in enumerate(getattr(sk, "connections", ())):
            sinks[f"{sk.stream_id}[{i}]"] = {
                "state": conn.state,
                "retries": conn.retries_total,
                "dropped": conn.dropped_total,
                "buffered": conn.buffered(),
            }
            if conn.state == BROKEN:
                degraded = True

    drops, growths = _counter_sums(snap.get("counters", {}))
    recompiles = sum(info["count"]
                     for info in st.recompiles(rt).values())
    # queries whose @fuse request was skipped at wiring time, with the
    # concrete reason — an operator watching /healthz for throughput
    # should see "your fusion never engaged" here, not in a log line
    # (shared helper: core/plan_facts.py, same strings as explain/lint)
    from ..core.plan_facts import fusion_exclusions
    try:
        excluded = fusion_exclusions(rt)
    except Exception:  # noqa: BLE001 — probe must not throw
        excluded = {}
    # shard dimension: per-shard residency + routing balance of a meshed
    # app (sharding/metrics.py — layout metadata + host counters only)
    shards = None
    try:
        from ..sharding import shard_report
        shards = shard_report(rt)
    except Exception:  # noqa: BLE001 — probe must not throw
        shards = None

    # SLO verdicts (observability/slo.py): evaluated by the time-series
    # sampler each tick and attached to the runtime; a FIRING rule flips
    # the same `degraded` verdict a BROKEN sink does — the app still
    # processes, but an operator-promised objective is being missed
    slo = rt.__dict__.get("_slo_state")
    if slo is not None and any(r.get("state") == "firing"
                               for r in slo.get("rules", {}).values()):
        degraded = True

    # admission controller (core/admission.py): quota state, shed/
    # blocked/denied counters, ladder level — attribute reads only.  A
    # non-ok quota state flips the same `degraded` verdict a BROKEN
    # sink does: the app still processes, but it is deliberately
    # shedding or rate-halved
    admission = None
    adm = getattr(rt, "admission", None)
    if adm is not None:
        try:
            admission = adm.report()
            if admission.get("quota_state") != "ok":
                degraded = True
        except Exception:  # noqa: BLE001 — probe must not throw
            admission = None

    # serving drainer (siddhi_tpu/serving/drain.py): a stalled or dead
    # drainer flips `degraded`, NOT `live` — producers fall back to
    # bounded ring backpressure while the app keeps processing, so the
    # right response is alarm-and-drain, not a restart loop
    serving = None
    sd = getattr(rt, "_serve_drainer", None)
    if sd is not None and getattr(sd, "_started", False):
        try:
            stalled = bool(sd.stalled())
            alive = bool(sd.alive())
            serving = {
                "drainer_alive": alive,
                "drainer_stalled": stalled,
                "pending": sd.pending(),
                "drains_total": sd.drains_total,
                "drained_outputs_total": sd.drained_outputs_total,
                "rings": {q: r.facts()
                          for q, r in rt.serve_rings().items()}
                if hasattr(rt, "serve_rings") else {},
            }
            if stalled or not alive:
                degraded = True
        except Exception:  # noqa: BLE001 — probe must not throw
            serving = None

    # phase budget (observability/phases.py): per-query share of e2e wall
    # by pipeline phase — the profiler's counters are host-clock sums, so
    # this keeps the probe's never-fetch invariant
    phases = None
    try:
        ph = rt.phase_report()
        if ph.get("queries"):
            phases = ph
    except Exception:  # noqa: BLE001 — probe must not throw
        phases = None

    # state observatory (observability/stateobs.py): per-structure
    # utilization + high-water from the HOST mirrors, key-hotness
    # concentration, and near-capacity verdicts.  A non-growable
    # structure at/over the near-capacity threshold flips the same
    # `degraded` verdict a BROKEN sink does — the app still processes,
    # but the next key/slot past the cap raises instead of degrading
    # gracefully, so the operator should resize BEFORE that happens
    state = None
    try:
        from .stateobs import (_NEAR_CAPACITY_EXEMPT, collect,
                               near_capacity, obs_enabled)
        if obs_enabled(rt):
            collect(rt)
            so_snap = rt.stats.stateobs.snapshot()
            near = near_capacity(rt, so_snap)
            worst = 0.0
            n_structs = 0
            for q, structures in so_snap["structures"].items():
                for s, rec in structures.items():
                    n_structs += 1
                    # window_fill runs 100% full at steady state by
                    # design — not a capacity-pressure signal
                    if not rec["growable"] and \
                            s not in _NEAR_CAPACITY_EXEMPT:
                        worst = max(worst, rec["utilization"])
            state = {
                "structures_tracked": n_structs,
                "worst_fixed_utilization": round(worst, 4),
                "near_capacity": near,
                "hot_share_1pct": {
                    q: h["hot_share_1pct"]
                    for q, h in so_snap["hotness"].items()},
            }
            if near:
                degraded = True
    except Exception:  # noqa: BLE001 — probe must not throw
        state = None

    report = {
        "started": started,
        "accepting_ingress": accepting,
        "live": live,
        "ready": started and accepting,
        "threads": threads,
        "streams": streams,
        "sinks": sinks,
        "degraded": degraded,
        **({"shards": shards} if shards is not None else {}),
        **({"phases": phases} if phases is not None else {}),
        **({"state": state} if state is not None else {}),
        **({"serving": serving} if serving is not None else {}),
        **({"slo": slo} if slo is not None else {}),
        **({"admission": admission} if admission is not None else {}),
        "buffered_emissions": rt.buffered_emissions(),
        "drainer_queue_depth": rt.drainer_depth()
        if hasattr(rt, "drainer_depth") else 0,
        "rates_window_s": window_s,
        "dropped_per_s": round(_rate(rt, "dropped", drops), 6),
        "cap_growths_per_s": round(_rate(rt, "cap_growths", growths), 6),
        "recompiles_per_s": round(_rate(rt, "recompiles", recompiles), 6),
        "totals": {"dropped": drops, "cap_growths": growths,
                   "recompiles": recompiles},
        "fusion_exclusions": excluded,
    }
    return report


def healthz(manager) -> Tuple[int, Dict]:
    """(http_status, payload) for GET /healthz: 200 while every app's
    engine threads live, 503 otherwise.  `ready` is reported separately —
    route on it via /healthz/ready (503 while any app is deploying,
    quiesced, or stopped)."""
    apps = {}
    live = True
    ready = True
    degraded = False
    for name, rt in sorted(getattr(manager, "runtimes", {}).items()):
        try:
            rep = app_health(rt)
        except Exception as exc:  # noqa: BLE001 — probe must not throw
            rep = {"error": repr(exc), "live": False, "ready": False}
        apps[name] = rep
        live = live and bool(rep.get("live"))
        ready = ready and bool(rep.get("ready"))
        degraded = degraded or bool(rep.get("degraded"))
    payload = {
        "status": "degraded" if live and degraded
        else ("ok" if live else "unhealthy"),
        "live": live,
        "ready": ready,
        "degraded": degraded,
        "apps": apps,
    }
    return (200 if live else 503), payload


def readiness(manager) -> Tuple[int, Dict]:
    """(http_status, payload) for GET /healthz/ready: 200 only when every
    deployed app is started and accepting ingress."""
    code, payload = healthz(manager)
    ok = payload["ready"] and payload["live"]
    return (200 if ok else 503), {"ready": ok,
                                  "live": payload["live"],
                                  "apps": payload["apps"]}


def liveness(manager) -> Tuple[int, Dict]:
    """(http_status, payload) for GET /healthz/live."""
    code, payload = healthz(manager)
    return code, {"live": payload["live"]}
