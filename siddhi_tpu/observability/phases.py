"""Phase-level hot-path profiler for the serving pipeline.

Reference (what): the reference's DETAIL statistics level leaves per-event
breadcrumbs (StreamJunction.sendEvent :147, QuerySelector.process :77);
every open perf question here is instead a per-PHASE budget question —
which slice of the batch pipeline (host staging, H2D upload, dispatch
submit, device compute, ring residency, D2H drain, demux, sink fan-out)
owns the wall time.  TPU design (how): an always-on accumulator of
per-(query, phase) nanosecond counters fed exclusively from HOST clocks
at the existing hot-path boundaries — zero device fetches and zero
`block_until_ready` on the steady path, so it can stay on in production
(the Google-Wide-Profiling posture: continuous, cheap, always there).

The async-dispatch blind spot: a jitted step call returns at SUBMIT, so
the host-side `dispatch_submit` wall says nothing about device time —
that is paid later inside whichever `device_get` drains the output
(`d2h_drain`).  The sampled deep mode (`profile.sample.every=N`) fences
every Nth dispatch per query with `block_until_ready` to split the two:
the fence wall is `device_compute`, and the sampled-dispatch counter
(`siddhi_phase_dispatches_sampled_total`) says how much of the traffic
paid for that visibility.

Phase taxonomy (one batch, ingest -> sink):

  stage_host       host staging: pack_np + the sharded [n,Kb,E] regroup
  h2d              explicit device upload (serving/staging.py)
  dispatch_submit  jitted step call wall (async dispatch: submit only)
  device_compute   sampled only: block_until_ready fence after submit
  ring_wait        emission-ring residency (append -> take)
  d2h_drain        device->host output fetch (blocking or drainer-side)
  demux            header decode / unpack / ts restore in emission sync
  sink             callbacks + downstream routing + sink publish

Counters are per-query LATENCY attribution, not wall-clock utilization:
a batched drainer fetch serving three queries charges its full wall to
each of them, exactly as each query's `<q>:e2e` histogram sample does —
so per query, sum(phases) tracks the e2e histogram and the unattributed
remainder surfaces as `other` in `runtime.phase_report()`.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

# canonical order — every surface (report, /metrics, /timeseries, PERF
# tables) lists phases in pipeline order, not dict order
PHASES = ("stage_host", "h2d", "dispatch_submit", "device_compute",
          "ring_wait", "d2h_drain", "demux", "sink")


class PhaseProfiler:
    """Always-on per-(query, phase) ns accumulator.  One per
    StatisticsManager (i.e. per app runtime); `add` is the single
    hot-path entry — a dict upsert under a short lock, no allocation
    beyond the first sample of a (query, phase) pair."""

    __slots__ = ("_lock", "_ns", "_count", "_dispatches", "_sampled")

    def __init__(self):
        self._lock = threading.Lock()
        self._ns: Dict[tuple, int] = {}        # (query, phase) -> total ns
        self._count: Dict[tuple, int] = {}     # (query, phase) -> samples
        self._dispatches: Dict[str, int] = {}  # query -> dispatch counter
        self._sampled: Dict[str, int] = {}     # query -> fenced dispatches

    def add(self, query: str, phase: str, ns: int) -> None:
        if ns <= 0:
            return
        key = (query, phase)
        with self._lock:
            self._ns[key] = self._ns.get(key, 0) + int(ns)
            self._count[key] = self._count.get(key, 0) + 1

    def should_sample(self, query: str, every: int) -> bool:
        """Per-query dispatch modulus for the deep mode: True on every
        Nth dispatch (the caller then fences with block_until_ready and
        records `device_compute`).  Counts the sampled dispatch so the
        exposition can report what fraction of traffic paid the fence."""
        if every <= 0:
            return False
        with self._lock:
            n = self._dispatches.get(query, 0) + 1
            self._dispatches[query] = n
            if n % every:
                return False
            self._sampled[query] = self._sampled.get(query, 0) + 1
        return True

    def snapshot(self) -> Dict:
        """{"queries": {q: {phase: {"ns", "count"}}}, "sampled": {q: n}}
        — phases in canonical order; shallow int copies, scrape-safe."""
        with self._lock:
            ns = dict(self._ns)
            count = dict(self._count)
            sampled = dict(self._sampled)
        queries: Dict[str, Dict] = {}
        for (q, p), total in ns.items():
            queries.setdefault(q, {})[p] = {"ns": total,
                                            "count": count.get((q, p), 0)}
        for q in queries:
            queries[q] = {p: queries[q][p] for p in PHASES
                          if p in queries[q]}
        return {"queries": queries, "sampled": sampled}

    def reset(self) -> None:
        with self._lock:
            self._ns.clear()
            self._count.clear()
            self._dispatches.clear()
            self._sampled.clear()


def sample_every(rt) -> int:
    """`profile.sample.every=N` config (0 = deep mode off, the default),
    memoized on the runtime like serving_config — the hot path reads one
    dict slot, never the ConfigManager."""
    every = rt.__dict__.get("_profile_sample_every")
    if every is None:
        every = 0
        try:
            cm = getattr(rt, "config_manager", None)
            v = cm.extract_property("profile.sample.every") \
                if cm is not None else None
            if v is not None:
                every = max(0, int(v))
        except Exception:  # noqa: BLE001 — profiling must not throw
            every = 0
        rt.__dict__["_profile_sample_every"] = every
    return every


def phase_report(rt) -> Dict:
    """Per-query phase budget vs the `<q>:e2e` histogram: seconds + share
    per phase, with the unattributed remainder reported as `other` (the
    acceptance bar: phases account >=90% of measured e2e wall for a
    @serve flagship run).  Queries with phase samples but no e2e
    histogram (statistics OFF mid-flight) report shares of the phase sum
    instead."""
    st = rt.stats
    snap = st.phases.snapshot()
    queries = {}
    for q, phases in snap["queries"].items():
        total_ns = sum(v["ns"] for v in phases.values())
        e2e = st.e2e_sum_ns(q)
        base = e2e if e2e > 0 else total_ns
        entry = {
            p: {"seconds": round(v["ns"] / 1e9, 6),
                "count": v["count"],
                "share": round(v["ns"] / base, 4) if base else 0.0}
            for p, v in phases.items()}
        other_ns = max(0, e2e - total_ns) if e2e > 0 else 0
        queries[q] = {
            "phases": entry,
            "e2e_seconds": round(e2e / 1e9, 6),
            "other_seconds": round(other_ns / 1e9, 6),
            "accounted": round(min(total_ns / base, 1.0), 4)
            if base else 0.0,
            "sampled_dispatches": snap["sampled"].get(q, 0),
        }
    return {"app": rt.name, "sample_every": sample_every(rt),
            "queries": queries}
