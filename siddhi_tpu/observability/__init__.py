"""Observability layer: latency histograms, pipeline tracing, JIT/recompile
accounting, and Prometheus text exposition.

Reference (what): the reference engine ships a Dropwizard-metrics statistics
subsystem (throughput/latency/memory/buffered-event gauges, runtime-
switchable OFF/BASIC/DETAIL — SiddhiAppRuntimeImpl.setStatisticsLevel
:859-895) plus log4j TRACE-level event tracing.

TPU design (how): a JAX/XLA deployment has two failure modes the reference
never had — *tail latency* dominated by device dispatch + tunnel roundtrips,
and *silent XLA recompilation* (a re-trace stalls a query for seconds on CPU
and minutes through a remote TPU tunnel).  This package therefore records

- fixed-bucket log2 latency **histograms** (p50/p95/p99/max) instead of
  avg/max scalars (`histogram.py`),
- per-batch **pipeline traces** with per-stage spans in a ring buffer
  (`tracing.py`),
- per-query **recompile counters** with the triggering abstract shapes,
  hooked into `steputil.jit_step` (`recompile.py`),
- **Prometheus text exposition** of all of the above (`exposition.py`).

Everything is allocation-free on the hot path when statistics are OFF: each
hook sits behind a single `enabled`/`active()` check.
"""
from .histogram import LogHistogram                       # noqa: F401
from .recompile import RECOMPILES, RecompileRegistry      # noqa: F401
from .tracing import PipelineTracer, active, span         # noqa: F401
from .exposition import render_prometheus                 # noqa: F401

__all__ = [
    "LogHistogram", "PipelineTracer", "RECOMPILES", "RecompileRegistry",
    "active", "span", "render_prometheus",
]
