"""Observability layer: latency histograms, pipeline tracing, JIT/recompile
accounting, and Prometheus text exposition.

Reference (what): the reference engine ships a Dropwizard-metrics statistics
subsystem (throughput/latency/memory/buffered-event gauges, runtime-
switchable OFF/BASIC/DETAIL — SiddhiAppRuntimeImpl.setStatisticsLevel
:859-895) plus log4j TRACE-level event tracing.

TPU design (how): a JAX/XLA deployment has two failure modes the reference
never had — *tail latency* dominated by device dispatch + tunnel roundtrips,
and *silent XLA recompilation* (a re-trace stalls a query for seconds on CPU
and minutes through a remote TPU tunnel).  This package therefore records

- fixed-bucket log2 latency **histograms** (p50/p95/p99/max) instead of
  avg/max scalars (`histogram.py`),
- per-batch **pipeline traces** with per-stage spans in a ring buffer
  (`tracing.py`),
- per-query **recompile counters** with the triggering abstract shapes,
  hooked into `steputil.jit_step` (`recompile.py`),
- **Prometheus text exposition** of all of the above (`exposition.py`),

and the v2 introspection layer (where the time and memory actually go):

- query **EXPLAIN**: planned operator tree annotated with XLA
  `cost_analysis()` per jitted step — flops, bytes accessed, estimated
  peak memory — plus state shapes, emission caps, and fusion
  eligibility (`explain.py`),
- **state-memory accounting**: nbytes per device-state component from
  shape/dtype metadata only, exported as `siddhi_state_bytes`
  (`memory.py`),
- **Perfetto export**: the pipeline-trace ring buffer as Chrome
  trace-event JSON (`GET /trace.json`) + guarded `jax.profiler`
  start/stop (`chrome_trace.py`),
- **health probes**: readiness vs. liveness, per-stream last-event age
  and backlog, sliding-window drop/recompile rates (`health.py`),

and the soak-telemetry layer (metrics over TIME, not just at scrape):

- **time-series sampler**: a daemon tick snapshots every host-side
  counter/gauge/histogram-quantile into per-app ring-buffer series with
  derived windowed rates, plus per-tenant accounting (events in/out,
  emitted bytes, dispatch wall-time, recompile blame, state bytes) —
  `timeseries.py`,
- **SLO engine**: declarative rules (zero-drop, max-p99, breaker,
  shard-imbalance, recompile-rate) evaluated over those series each
  tick with ok/pending/firing hysteresis, surfaced as
  `siddhi_slo_state` in `/metrics` and an `slo` section in `/healthz`
  (`slo.py`),
- **state observatory**: always-on per-(app, query, structure)
  occupancy/capacity/high-water tracking for every sized device
  structure (keyed slabs, group slots, join lanes, window fill,
  emission caps, serve rings) plus key hotness from a host-side
  count-min sketch + space-saving top-K; high-water marks persist
  across restarts as a sizing-hints ledger carried in snapshots
  (`stateobs.py`; surfaced as `siddhi_state_occupancy` /
  `siddhi_state_high_water` / `siddhi_key_hotset_share`,
  `GET /siddhi-apps/<app>/state`, EXPLAIN `utilization`, and a
  `state` section in `/healthz`),
- **phase profiler**: always-on per-(app, query, phase) wall-time
  counters over the canonical hot-path taxonomy (stage_host, h2d,
  dispatch_submit, device_compute, ring_wait, d2h_drain, demux, sink)
  from host clocks only, a sampled deep mode
  (`profile.sample.every=N`) that fences every Nth dispatch to split
  submit from device compute, and cross-thread trace handoff/adoption
  so one pipeline trace spans ingest -> dispatch -> drain -> sink
  (`phases.py`; surfaced as `siddhi_phase_seconds_total`,
  `GET /siddhi-apps/<app>/phases`, EXPLAIN, and a drain track with
  flow arrows in `/trace.json`).

Everything is allocation-free on the hot path when statistics are OFF: each
hook sits behind a single `enabled`/`active()` check, and every scrape/
probe path (`/metrics`, `/healthz`) reads host-side metadata only — no
`device_get`, ever.
"""
from .histogram import LogHistogram                       # noqa: F401
from .recompile import RECOMPILES, RecompileRegistry      # noqa: F401
from .tracing import (PipelineTracer, active, adopt,      # noqa: F401
                      handoff, span)
from .phases import PHASES, PhaseProfiler, phase_report   # noqa: F401
from .stateobs import (STRUCTURES, KeyHotness,            # noqa: F401
                       StateObservatory, state_report)
from .exposition import render_prometheus                 # noqa: F401
from .explain import explain_app, explain_query           # noqa: F401
from .memory import component_bytes, total_bytes          # noqa: F401
from .chrome_trace import (chrome_trace, profiler_status,  # noqa: F401
                           start_profiler, stop_profiler)
from .health import app_health, healthz, liveness, readiness  # noqa: F401
from .timeseries import (Series, SeriesStore,                 # noqa: F401
                         TimeSeriesSampler, tenant_account)
from .slo import SLOEngine, SLORule, default_rules            # noqa: F401

__all__ = [
    "LogHistogram", "PipelineTracer", "RECOMPILES", "RecompileRegistry",
    "active", "adopt", "handoff", "span", "render_prometheus",
    "PHASES", "PhaseProfiler", "phase_report",
    "STRUCTURES", "KeyHotness", "StateObservatory", "state_report",
    "explain_app", "explain_query", "component_bytes", "total_bytes",
    "chrome_trace", "start_profiler", "stop_profiler", "profiler_status",
    "app_health", "healthz", "liveness", "readiness",
    "Series", "SeriesStore", "TimeSeriesSampler", "tenant_account",
    "SLOEngine", "SLORule", "default_rules",
]
