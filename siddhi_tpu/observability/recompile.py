"""JIT trace/recompile accounting.

Reference (what): not applicable — the reference's per-event processors are
plain Java; object identity is stable and nothing ever "recompiles"
mid-stream.  TPU design (how): every query step is a `jax.jit` program
keyed on the abstract shapes/dtypes of its arguments.  A batch arriving in
a new bucket size, a weak-type leak, or an emission-cap regrow silently
re-traces and re-compiles — a sub-second stall on CPU and a minutes-long
stall through the remote TPU tunnel (steputil.py documents the observed
round-4 incident: p99 of 2150ms vs p50 14.9ms from exactly two such
recompiles).  This registry makes those events *visible*: `steputil.
jit_step` calls `record(owner, args)` from inside the wrapped function —
which Python only executes while jax is TRACING a new signature — so the
count per owner is exactly the number of compiles, and the signature string
captures the triggering abstract shapes.

The registry is process-global (planners don't know their app), keyed by
owner label; `StatisticsManager.report()` projects the slice relevant to
its app.  Recording is two dict ops per COMPILE — never on the steady-state
hot path, by construction.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

_MAX_SIGNATURES = 4     # last-N triggering signatures kept per owner
_MAX_SIG_CHARS = 240


def _describe(x) -> str:
    aval = getattr(x, "aval", None)
    if aval is not None and hasattr(aval, "shape"):
        d = getattr(aval, "dtype", None)
        w = "w" if getattr(aval, "weak_type", False) else ""
        return f"{getattr(d, 'name', d)}{w}{list(aval.shape)}"
    return type(x).__name__


def signature_of(args) -> str:
    """Compact one-line abstract-shape signature of a traced call's args."""
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(args)
    except Exception:  # noqa: BLE001 — accounting must never break a trace
        leaves = []
    s = " ".join(_describe(v) for v in leaves)
    if len(s) > _MAX_SIG_CHARS:
        s = s[:_MAX_SIG_CHARS] + "..."
    return s


_suppress_tls = threading.local()


class RecompileRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._sigs: Dict[str, deque] = {}
        self._last_ms: Dict[str, int] = {}

    @staticmethod
    def suppressed() -> bool:
        """True while this thread is inside a diagnostic re-trace (EXPLAIN
        lowering a step for cost analysis) — those traces are not real
        recompiles and must not inflate the per-owner counters."""
        return getattr(_suppress_tls, "on", False)

    @staticmethod
    def suppress():
        """Context manager marking this thread's traces as diagnostic."""
        import contextlib

        @contextlib.contextmanager
        def _cm():
            prev = getattr(_suppress_tls, "on", False)
            _suppress_tls.on = True
            try:
                yield
            finally:
                _suppress_tls.on = prev
        return _cm()

    def record(self, owner: str, args) -> None:
        if getattr(_suppress_tls, "on", False):
            return
        sig = signature_of(args)
        with self._lock:
            self._counts[owner] = self._counts.get(owner, 0) + 1
            dq = self._sigs.get(owner)
            if dq is None:
                dq = self._sigs[owner] = deque(maxlen=_MAX_SIGNATURES)
            dq.append(sig)
            self._last_ms[owner] = int(time.time() * 1000)

    def count(self, owner: str) -> int:
        return self._counts.get(owner, 0)

    def snapshot(self, owners: Optional[List[str]] = None) -> Dict:
        """{owner: {count, last_ms, signatures}} — all owners, or just the
        requested ones (an app projecting its own queries)."""
        with self._lock:
            keys = list(self._counts) if owners is None else \
                [o for o in owners if o in self._counts]
            return {o: {"count": self._counts[o],
                        "last_ms": self._last_ms.get(o, 0),
                        "signatures": list(self._sigs.get(o, ()))}
                    for o in keys}

    def owners_with_prefix(self, prefix: str) -> List[str]:
        with self._lock:
            return [o for o in self._counts if o.startswith(prefix)]

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._sigs.clear()
            self._last_ms.clear()


RECOMPILES = RecompileRegistry()
