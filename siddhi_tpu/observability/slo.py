"""Declarative SLO rules evaluated over the in-process time series.

Reference (what): the reference leaves alerting to external systems
watching its reporters.  TPU design (how): the operator questions
ROADMAP item 4 asks — "is p99 stable?", "were any events silently
dropped?" — are *windowed* judgments, so the rules live next to the
ring-buffer series (observability/timeseries.py) and are evaluated by
the sampler each tick, Prometheus-rule style but with zero external
infrastructure.  Results surface three ways: `siddhi_slo_state{rule}`
in `/metrics`, an `slo` section in `/healthz` (a FIRING rule flips the
`degraded` verdict), and the soak artifact's machine-checked verdict.

States follow the Prometheus alerting lifecycle: a rule that evaluates
false is **ok**; true for fewer than `for_ticks` consecutive ticks is
**pending**; sustained for `for_ticks`+ is **firing**.  The hysteresis
keeps a single warmup compile or one retried publish from flapping a
deployment red.

Rule kinds (all evaluated from host counters/series only):

  zero_drop        events dropped this tick (emission cap + sink) > threshold
  max_p99          any query's p99 step latency exceeds `threshold` ms
                   (one query when `query` is set; `:`-suffixed series
                   like `<q>:e2e`/`<q>:fused` are skipped unless named)
  breaker          sink circuit breakers in BROKEN state > threshold
  shard_imbalance  routed-event skew (max/mean) of a meshed app > threshold
  recompile_rate   windowed XLA recompiles/s > threshold
  max_queue_depth  total @async ingress + drainer backlog > threshold

Config (manager.config_manager properties) tunes the default rule set:
  slo.for.ticks                 hysteresis ticks        (default 3)
  slo.max.p99.ms                adds a max_p99 rule when set
  slo.recompile.rate.per.s      recompile_rate threshold (default 5.0)
  slo.shard.imbalance.max       shard_imbalance threshold (default 4.0)
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

OK, PENDING, FIRING = "ok", "pending", "firing"
STATE_GAUGE = {OK: 0, PENDING: 1, FIRING: 2}

# rate window for windowed-rate rules (recompiles/s): trailing seconds
_RATE_WINDOW_S = 60.0


@dataclass
class SLORule:
    """One declarative rule: `kind` picks the evaluator, `threshold` the
    bound, `for_ticks` the pending->firing hysteresis."""
    name: str
    kind: str
    threshold: float = 0.0
    query: Optional[str] = None        # max_p99: restrict to one query
    for_ticks: int = 3


def default_rules(config=None) -> List[SLORule]:
    """The standing rule set: zero silent drops, no open breakers, a
    recompile-rate ceiling, and (meshed apps) a shard-imbalance bound.
    `slo.max.p99.ms` opts every query into a p99 ceiling."""
    def prop(name):
        try:
            return config.extract_property(name) \
                if config is not None else None
        except Exception:  # noqa: BLE001 — config must not break boot
            return None

    for_ticks = int(prop("slo.for.ticks") or 3)
    rules = [
        SLORule("zero-drop", "zero_drop", 0.0, for_ticks=1),
        SLORule("breaker-not-broken", "breaker", 0.0, for_ticks=for_ticks),
        SLORule("recompile-rate", "recompile_rate",
                float(prop("slo.recompile.rate.per.s") or 5.0),
                for_ticks=for_ticks),
        SLORule("shard-imbalance", "shard_imbalance",
                float(prop("slo.shard.imbalance.max") or 4.0),
                for_ticks=for_ticks),
    ]
    p99 = prop("slo.max.p99.ms")
    if p99:
        rules.append(SLORule("max-p99", "max_p99", float(p99),
                             for_ticks=for_ticks))
    return rules


class SLOEngine:
    """Evaluates a rule set over one app's SeriesStore each tick and
    tracks per-(app, rule) violation streaks for the pending->firing
    hysteresis.  All reads are host-side (series values, sink states,
    shard counters) — the engine shares the sampler's never-fetch
    invariant."""

    def __init__(self, rules: Optional[List[SLORule]] = None, config=None):
        self.rules = list(rules) if rules else default_rules(config)
        self._streak: Dict = {}       # (app, rule) -> consecutive hits

    # -- per-kind evaluators (value, violated) ---------------------------------
    def _eval(self, rule: SLORule, rt, store) -> tuple:
        kind = rule.kind
        if kind == "zero_drop":
            d = store.get("dropped")
            s = store.get("sink_dropped")
            v = (d.delta() if d is not None else 0.0) + \
                (s.delta() if s is not None else 0.0)
            return v, v > rule.threshold
        if kind == "max_p99":
            worst = 0.0
            for name in store.names():
                if not name.startswith("query.") or \
                        not name.endswith(".p99_us"):
                    continue
                q = name[len("query."):-len(".p99_us")]
                if rule.query is not None:
                    if q != rule.query:
                        continue
                elif ":" in q:
                    continue       # :e2e/:fused ride-alongs opt in by name
                worst = max(worst, (store.last(name) or 0.0) / 1e3)
            return worst, worst > rule.threshold
        if kind == "breaker":
            s = store.get("sink_broken")
            v = s.last if s is not None and s.last is not None else 0.0
            return v, v > rule.threshold
        if kind == "shard_imbalance":
            s = store.get("shard_skew")
            v = s.last if s is not None and s.last is not None else 0.0
            return v, v > rule.threshold
        if kind == "recompile_rate":
            s = store.get("recompiles")
            v = s.rate(_RATE_WINDOW_S) if s is not None else 0.0
            return v, v > rule.threshold
        if kind == "max_queue_depth":
            a = store.get("async_queue_depth")
            d = store.get("drainer_queue_depth")
            v = (a.last or 0.0 if a is not None else 0.0) + \
                (d.last or 0.0 if d is not None else 0.0)
            return v, v > rule.threshold
        return 0.0, False            # unknown kind: never fires

    def evaluate(self, app_name: str, rt, store, now: float) -> Dict:
        """One evaluation pass; returns the `slo` report attached to the
        runtime ({verdict, rules: {name: {state, value, threshold,
        streak}}})."""
        rules_out: Dict[str, Dict] = {}
        verdict = OK
        for rule in self.rules:
            try:
                value, violated = self._eval(rule, rt, store)
            except Exception:  # noqa: BLE001 — a broken rule reads ok,
                value, violated = 0.0, False   # never crashes the tick
            key = (app_name, rule.name)
            streak = self._streak.get(key, 0) + 1 if violated else 0
            self._streak[key] = streak
            state = OK if not violated else \
                (FIRING if streak >= rule.for_ticks else PENDING)
            rules_out[rule.name] = {
                "state": state,
                "value": round(float(value), 6),
                "threshold": rule.threshold,
                "streak": streak,
            }
            if state == FIRING:
                verdict = FIRING
            elif state == PENDING and verdict == OK:
                verdict = PENDING
        return {"verdict": verdict, "now": now, "rules": rules_out}
