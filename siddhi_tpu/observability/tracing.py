"""Per-batch pipeline tracing with a ring-buffer trace store.

Reference (what): the reference's DETAIL statistics level enables log4j
TRACE lines at StreamJunction.sendEvent :147 and QuerySelector.process :77
— per-event breadcrumbs scattered through the log.  TPU design (how): our
unit of work is a micro-batch flowing ingest -> junction -> query step ->
(window/join/pattern) -> rate-limit -> sink; a slow batch needs a stage-by-
stage explanation, not interleaved log lines.  Each dispatched batch gets a
`BatchTrace` (trace id, stream, event count, per-stage spans); finished
traces land in a bounded ring buffer and are dumped via
`SiddhiAppRuntime.trace_dump()` / `GET /trace/<query>`.

The active trace is a module-level thread-local so deep layers (rate
limiters, sinks, the jitted-step wrappers) can attach spans without any
plumbing; a batch handed to another thread (@async / drainer) simply stops
collecting spans there — the dispatch-side stages are the ones that explain
latency, and cross-thread handoff would need locking on the hot path.
Everything is a no-op (one thread-local read) when no trace is active.
"""
from __future__ import annotations

import contextlib
import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional

_tls = threading.local()
_ids = itertools.count(1)


class Span:
    __slots__ = ("stage", "start_ns", "end_ns", "meta")

    def __init__(self, stage: str, start_ns: int, end_ns: int, meta: Dict):
        self.stage = stage
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.meta = meta

    def to_dict(self) -> Dict:
        d = {"stage": self.stage,
             "duration_us": (self.end_ns - self.start_ns) / 1e3,
             "offset_us": None}  # filled by BatchTrace.to_dict
        d.update(self.meta)
        return d


class BatchTrace:
    __slots__ = ("trace_id", "stream_id", "n_events", "wall_ms",
                 "start_ns", "end_ns", "spans")

    def __init__(self, stream_id: str, n_events: int):
        self.trace_id = next(_ids)
        self.stream_id = stream_id
        self.n_events = n_events
        self.wall_ms = int(time.time() * 1000)
        self.start_ns = time.perf_counter_ns()
        self.end_ns = self.start_ns
        self.spans: List[Span] = []

    def add_span(self, stage: str, start_ns: int, end_ns: int,
                 meta: Dict) -> None:
        self.spans.append(Span(stage, start_ns, end_ns, meta))

    def queries(self) -> List[str]:
        return sorted({s.meta["query"] for s in self.spans
                       if "query" in s.meta})

    def to_dict(self) -> Dict:
        spans = []
        for s in self.spans:
            d = s.to_dict()
            d["offset_us"] = (s.start_ns - self.start_ns) / 1e3
            spans.append(d)
        return {
            "trace_id": self.trace_id,
            "stream": self.stream_id,
            "events": self.n_events,
            "wall_ms": self.wall_ms,
            "total_us": (self.end_ns - self.start_ns) / 1e3,
            "spans": spans,
        }


def active() -> Optional[BatchTrace]:
    """The thread's in-flight trace, or None.  THE hot-path guard: callers
    must check this before building span context managers."""
    return getattr(_tls, "trace", None)


@contextlib.contextmanager
def span(stage: str, **meta):
    """Record one stage span on the active trace (no-op without one).
    Callers on latency-sensitive paths should guard with `active()` first
    so the generator isn't even created at OFF/BASIC."""
    tr = getattr(_tls, "trace", None)
    if tr is None:
        yield
        return
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        tr.add_span(stage, t0, time.perf_counter_ns(), meta)


class PipelineTracer:
    """Owns the ring buffer and the start/finish lifecycle.  One per
    StatisticsManager (i.e. per app runtime)."""

    def __init__(self, capacity: int = 256):
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def start(self, stream_id: str, n_events: int) -> Optional[BatchTrace]:
        """Begin tracing the batch being dispatched on this thread.  Nested
        dispatch (a query emitting into a downstream stream) keeps the
        OUTER trace: the inner hop shows up as spans on it, which is
        exactly the stage-by-stage story a slow batch needs."""
        if getattr(_tls, "trace", None) is not None:
            return None
        tr = BatchTrace(stream_id, n_events)
        _tls.trace = tr
        return tr

    def finish(self, tr: Optional[BatchTrace]) -> None:
        if tr is None:      # nested dispatch: outer owner finishes it
            return
        _tls.trace = None
        tr.end_ns = time.perf_counter_ns()
        with self._lock:
            self._ring.append(tr)

    def dump(self, query: Optional[str] = None,
             limit: int = 64) -> List[Dict]:
        """Newest-first trace dicts, optionally only those that touched
        `query` (matched against span `query=` metadata)."""
        with self._lock:
            traces = list(self._ring)
        out = []
        for tr in reversed(traces):
            if query is not None and query not in tr.queries():
                continue
            out.append(tr.to_dict())
            if len(out) >= limit:
                break
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
