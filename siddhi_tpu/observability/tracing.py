"""Per-batch pipeline tracing with a ring-buffer trace store.

Reference (what): the reference's DETAIL statistics level enables log4j
TRACE lines at StreamJunction.sendEvent :147 and QuerySelector.process :77
— per-event breadcrumbs scattered through the log.  TPU design (how): our
unit of work is a micro-batch flowing ingest -> junction -> query step ->
(window/join/pattern) -> rate-limit -> sink; a slow batch needs a stage-by-
stage explanation, not interleaved log lines.  Each dispatched batch gets a
`BatchTrace` (trace id, stream, event count, per-stage spans); finished
traces land in a bounded ring buffer and are dumped via
`SiddhiAppRuntime.trace_dump()` / `GET /trace/<query>`.

The active trace is a module-level thread-local so deep layers (rate
limiters, sinks, the jitted-step wrappers) can attach spans without any
plumbing.  Cross-thread handoff is EXPLICIT: the dispatch side calls
`handoff()` to arm the active trace for concurrent appends (a per-trace
lock, paid only once armed) and carries the returned token on whatever
queue crosses the thread boundary (@async drainer items, serving-ring
generations); the drain side wraps its delivery in `adopt(token)`, so one
trace spans ingest -> dispatch -> drain -> sink and the delivery-side
spans carry `track="drain"` for the Chrome-trace drainer track.
Everything is a no-op (one thread-local read) when no trace is active.
"""
from __future__ import annotations

import contextlib
import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional

_tls = threading.local()
_ids = itertools.count(1)

# span-meta caps: DETAIL tracing on queries with large pattern metadata
# must not grow ring-buffer entries unboundedly — values clamp to a
# bounded repr and a span keeps at most _MAX_META_KEYS entries
_MAX_META_KEYS = 16
_MAX_META_CHARS = 200
_MAX_SPANS = 512


def _clamp_value(v):
    if v is None or isinstance(v, (bool, int, float)):
        return v
    s = v if isinstance(v, str) else repr(v)
    if len(s) > _MAX_META_CHARS:
        return s[:_MAX_META_CHARS] + f"...(+{len(s) - _MAX_META_CHARS})"
    return s


def _clamp_meta(meta: Dict) -> Dict:
    if not meta:
        return meta
    out = {}
    for i, (k, v) in enumerate(meta.items()):
        if i >= _MAX_META_KEYS:
            out["meta_truncated"] = len(meta) - _MAX_META_KEYS
            break
        out[str(k)[:64]] = _clamp_value(v)
    return out


class Span:
    __slots__ = ("stage", "start_ns", "end_ns", "meta", "track")

    def __init__(self, stage: str, start_ns: int, end_ns: int, meta: Dict,
                 track: Optional[str] = None):
        self.stage = stage
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.meta = meta
        self.track = track

    def to_dict(self) -> Dict:
        d = {"stage": self.stage,
             "duration_us": (self.end_ns - self.start_ns) / 1e3,
             "offset_us": None}  # filled by BatchTrace.to_dict
        if self.track is not None:
            d["track"] = self.track
        d.update(self.meta)
        return d


class BatchTrace:
    __slots__ = ("trace_id", "stream_id", "n_events", "wall_ms",
                 "start_ns", "end_ns", "spans", "spans_truncated",
                 "_append_lock")

    def __init__(self, stream_id: str, n_events: int):
        self.trace_id = next(_ids)
        self.stream_id = stream_id
        self.n_events = n_events
        self.wall_ms = int(time.time() * 1000)
        self.start_ns = time.perf_counter_ns()
        self.end_ns = self.start_ns
        self.spans: List[Span] = []
        self.spans_truncated = 0
        # armed by PipelineTracer.handoff(): appends from an adopting
        # thread serialize against the dispatch side.  None until a
        # handoff happens, so single-thread traces never pay the lock.
        self._append_lock = None

    def arm(self) -> None:
        if self._append_lock is None:
            self._append_lock = threading.Lock()

    def add_span(self, stage: str, start_ns: int, end_ns: int,
                 meta: Dict, track: Optional[str] = None) -> None:
        lk = self._append_lock
        if lk is None:
            self._add_span(stage, start_ns, end_ns, meta, track)
        else:
            with lk:
                self._add_span(stage, start_ns, end_ns, meta, track)

    def _add_span(self, stage: str, start_ns: int, end_ns: int,
                  meta: Dict, track: Optional[str]) -> None:
        # bounded entries: meta values clamp to a bounded repr and a
        # runaway dispatch (re-ingestion loop) can't make one trace hold
        # unlimited spans — drops are COUNTED and surface as
        # `spans_truncated` in the dump, never lost silently
        if len(self.spans) >= _MAX_SPANS:
            self.spans_truncated += 1
            return
        self.spans.append(
            Span(stage, start_ns, end_ns, _clamp_meta(meta), track))
        # adopted spans land after finish(): keep the trace total honest
        # so drain-side time shows in `total_us`, not past its end
        if end_ns > self.end_ns:
            self.end_ns = end_ns

    def queries(self) -> List[str]:
        return sorted({s.meta["query"] for s in tuple(self.spans)
                       if "query" in s.meta})

    def to_dict(self) -> Dict:
        spans = []
        # snapshot the list: a trace being finished on another thread
        # must not interleave half-written span entries into the dump
        for s in tuple(self.spans):
            d = s.to_dict()
            d["offset_us"] = (s.start_ns - self.start_ns) / 1e3
            spans.append(d)
        return {
            "trace_id": self.trace_id,
            "stream": self.stream_id,
            "events": self.n_events,
            "wall_ms": self.wall_ms,
            "total_us": (self.end_ns - self.start_ns) / 1e3,
            "spans": spans,
            "spans_truncated": self.spans_truncated,
        }


def active() -> Optional[BatchTrace]:
    """The thread's in-flight trace, or None.  THE hot-path guard: callers
    must check this before building span context managers."""
    return getattr(_tls, "trace", None)


@contextlib.contextmanager
def span(stage: str, **meta):
    """Record one stage span on the active trace (no-op without one).
    Callers on latency-sensitive paths should guard with `active()` first
    so the generator isn't even created at OFF/BASIC."""
    tr = getattr(_tls, "trace", None)
    if tr is None:
        yield
        return
    track = getattr(_tls, "track", None)
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        tr.add_span(stage, t0, time.perf_counter_ns(), meta, track)


def handoff() -> Optional[BatchTrace]:
    """Arm the active trace for cross-thread appends and return it as the
    token to carry on the handoff queue (@async drainer items, serving-
    ring generations).  None when no trace is active — the token rides
    the queue either way, so the drain side needs no special case."""
    tr = getattr(_tls, "trace", None)
    if tr is not None:
        tr.arm()
    return tr


@contextlib.contextmanager
def adopt(token: Optional[BatchTrace], track: str = "drain"):
    """Make a handed-off trace the thread's active trace for the scope of
    one delivery: spans recorded inside (emit, sink, nested re-ingestion
    dispatches) attach to the ORIGINATING trace, tagged with `track` for
    the Chrome-trace drainer lane.  With a None token this is the plain
    no-op path.  Nested dispatch under adoption behaves exactly like
    same-thread nesting: PipelineTracer.start() sees the adopted trace
    and returns None, so the inner hop's spans join the outer story
    instead of being silently skipped."""
    if token is None:
        yield
        return
    prev_tr = getattr(_tls, "trace", None)
    prev_track = getattr(_tls, "track", None)
    _tls.trace = token
    _tls.track = track
    try:
        yield
    finally:
        _tls.trace = prev_tr
        _tls.track = prev_track


class PipelineTracer:
    """Owns the ring buffer and the start/finish lifecycle.  One per
    StatisticsManager (i.e. per app runtime)."""

    def __init__(self, capacity: int = 256):
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def start(self, stream_id: str, n_events: int) -> Optional[BatchTrace]:
        """Begin tracing the batch being dispatched on this thread.  Nested
        dispatch (a query emitting into a downstream stream) keeps the
        OUTER trace: the inner hop shows up as spans on it, which is
        exactly the stage-by-stage story a slow batch needs."""
        if getattr(_tls, "trace", None) is not None:
            return None
        tr = BatchTrace(stream_id, n_events)
        _tls.trace = tr
        return tr

    def finish(self, tr: Optional[BatchTrace]) -> None:
        if tr is None:      # nested dispatch: outer owner finishes it
            return
        _tls.trace = None
        # max(): an adopted drain-side span may already have pushed the
        # trace end past the dispatch side's finish instant
        tr.end_ns = max(tr.end_ns, time.perf_counter_ns())
        with self._lock:
            self._ring.append(tr)

    def dump(self, query: Optional[str] = None,
             limit: int = 64) -> List[Dict]:
        """Newest-first trace dicts, optionally only those that touched
        `query` (matched against span `query=` metadata).  The dict
        conversion runs under the ring lock so a dump taken under churn
        is one consistent snapshot — concurrent finish() appends (which
        also take the lock) can never interleave into it."""
        out = []
        with self._lock:
            for tr in reversed(self._ring):
                if query is not None and query not in tr.queries():
                    continue
                out.append(tr.to_dict())
                if len(out) >= limit:
                    break
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
