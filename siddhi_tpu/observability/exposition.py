"""Prometheus text-format exposition (version 0.0.4) of the statistics
registry.

Reference (what): the reference exposes Dropwizard metrics through its
reporter SPI (console/JMX); operators bridge to Prometheus externally.
TPU design (how): render the text format directly — no dependency, one
pass over the registries, and the scrape never touches the device (no
`device_get`, no pytree walks), so a Prometheus poll can never stall a
query step or pay a tunnel roundtrip.
"""
from __future__ import annotations

from typing import Dict, List

from .histogram import LogHistogram


def _esc(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _labels(**kv) -> str:
    inner = ",".join(f'{k}="{_esc(v)}"' for k, v in kv.items()
                     if v is not None)
    return "{" + inner + "}" if inner else ""


def _fmt(v: float) -> str:
    f = float(v)
    return repr(f) if f != int(f) else str(int(f))


class _Family:
    def __init__(self, lines: List[str], name: str, kind: str, help_: str):
        self.lines = lines
        self.name = name
        self._opened = False
        self._kind = kind
        self._help = help_

    def _open(self) -> None:
        if not self._opened:
            self._opened = True
            self.lines.append(f"# HELP {self.name} {self._help}")
            self.lines.append(f"# TYPE {self.name} {self._kind}")

    def sample(self, value, suffix: str = "", **labels) -> None:
        self._open()
        self.lines.append(
            f"{self.name}{suffix}{_labels(**labels)} {_fmt(value)}")

    def histogram(self, h: LogHistogram, **labels) -> None:
        """Cumulative le-buckets + _sum + _count for one labelled series."""
        self._open()
        for le, cum in h.buckets_seconds():
            self.sample(cum, "_bucket", **dict(labels, le=_fmt_le(le)))
        self.sample(h.total, "_bucket", **dict(labels, le="+Inf"))
        self.sample(h.sum_ns / 1e9, "_sum", **labels)
        self.sample(h.total, "_count", **labels)

    def histogram_raw(self, h: LogHistogram, **labels) -> None:
        """Same shape as histogram() but in the histogram's RAW recorded
        unit (count-valued series: events per shard per batch)."""
        self._open()
        for le, cum in h.buckets_raw():
            self.sample(cum, "_bucket", **dict(labels, le=_fmt_le(le)))
        self.sample(h.total, "_bucket", **dict(labels, le="+Inf"))
        self.sample(h.sum_ns, "_sum", **labels)
        self.sample(h.total, "_count", **labels)


def _fmt_le(le: float) -> str:
    return f"{le:.9g}"


def render_prometheus(runtimes: Dict) -> str:
    """Render every app's metrics in one exposition payload.  `runtimes`
    maps app name -> SiddhiAppRuntime (the manager's `runtimes` dict)."""
    lines: List[str] = []

    def fam(name, kind, help_):
        return _Family(lines, name, kind, help_)

    uptime = fam("siddhi_uptime_seconds", "gauge",
                 "Seconds since the app's statistics epoch")
    level = fam("siddhi_statistics_level", "gauge",
                "Statistics level (0=OFF, 1=BASIC, 2=DETAIL)")
    s_in = fam("siddhi_stream_events_total", "counter",
               "Events received per stream")
    q_ev = fam("siddhi_query_events_total", "counter",
               "Events processed per query")
    q_lat = fam("siddhi_query_latency_seconds", "histogram",
                "Per-query processing latency")
    j_lat = fam("siddhi_junction_dispatch_seconds", "histogram",
                "Per-junction-hop dispatch latency (all subscribers)")
    k_lat = fam("siddhi_sink_flush_seconds", "histogram",
                "Per-sink-flush publish latency")
    recomp = fam("siddhi_query_recompiles_total", "counter",
                 "XLA trace/compile events per query step owner")
    ctr = fam("siddhi_events_dropped_total", "counter",
              "Output rows dropped at emission capacity, per query")
    grow = fam("siddhi_emission_cap_growths_total", "counter",
               "Adaptive emission-cap growths (each one recompiles), "
               "per query")
    buf_e = fam("siddhi_buffered_emissions", "gauge",
                "Device outputs queued in the async emission drainer")
    buf_i = fam("siddhi_buffered_ingress_events", "gauge",
                "Batches pending in @async ingress queues, per stream")
    q_dep = fam("siddhi_async_queue_depth", "gauge",
                "Batches sitting in a stream's bounded @async ingress "
                "queue right now (pure queue-wait backlog; excludes the "
                "batch a worker is processing)")
    d_dep = fam("siddhi_drainer_queue_depth", "gauge",
                "Device outputs sitting in the async emission drainer "
                "queue right now")
    e_rows = fam("siddhi_emitted_rows_total", "counter",
                 "Output rows delivered per query (callbacks, downstream "
                 "routing, sinks) — per-tenant events_out accounting")
    e_byt = fam("siddhi_emitted_bytes_total", "counter",
                "Output bytes delivered per query (rows x schema row "
                "width from dtype metadata, never fetched)")
    slo_g = fam("siddhi_slo_state", "gauge",
                "SLO rule state per app (0=ok 1=pending 2=firing), "
                "evaluated over the in-process time series each sampler "
                "tick (observability/slo.py)")
    fus_d = fam("siddhi_fused_dispatches_total", "counter",
                "@fuse scan dispatches per query (one device step runs "
                "K stacked batches)")
    fus_b = fam("siddhi_fused_batches_total", "counter",
                "Micro-batches executed through @fuse dispatches, "
                "per query")
    mem = fam("siddhi_state_bytes", "gauge",
              "Device-state bytes per query component (window buffers, "
              "pattern slot blocks, selector slabs, tables, fuse "
              "stacks) — computed from cached shape/dtype metadata, "
              "never fetched")
    s_ret = fam("siddhi_sink_retries_total", "counter",
                "Reconnect/redial attempts per sink connection "
                "(io/resilience.py state machine)")
    s_brk = fam("siddhi_sink_breaker_state", "gauge",
                "Sink connection state: 0=CONNECTED 1=RETRYING "
                "2=BROKEN (circuit open, load shed)")
    s_drp = fam("siddhi_sink_dropped_total", "counter",
                "Events/payloads dropped at a sink (buffer overflow, "
                "open breaker, or terminal on.error failure)")
    s_buf = fam("siddhi_sink_buffered_payloads", "gauge",
                "Payloads held in a sink's in-flight retry buffer")
    e_st = fam("siddhi_errorstore_events", "gauge",
               "Error-store events by state (buffered=waiting for "
               "replay; stored/dropped/replayed are lifetime totals)")
    r_fb = fam("siddhi_restore_fallbacks_total", "counter",
               "Snapshot revisions skipped as corrupt/unreadable "
               "during restore_last_revision")
    sh_ev = fam("siddhi_shard_events_total", "counter",
                "Events routed to each mesh shard by a sharded query's "
                "key-space router (sharding/router.py)")
    sh_oc = fam("siddhi_shard_batch_events", "histogram",
                "Per-batch events landing on each mesh shard (raw event "
                "counts, not seconds) — diverging shard p50s mean "
                "routing skew")
    sh_mem = fam("siddhi_shard_state_bytes", "gauge",
                 "Device-state bytes RESIDENT PER SHARD (sharded leaves "
                 "count their 1/n slice, replicated leaves count whole) "
                 "— layout metadata only, never fetched")
    adm_shed = fam("siddhi_admission_shed_total", "counter",
                   "Events shed at the external ingest edge by the "
                   "admission rate limit, per stream "
                   "(core/admission.py; shed/degrade overload policies)")
    adm_blk = fam("siddhi_admission_blocked_ms_total", "counter",
                  "Milliseconds callers spent blocked at the admission "
                  "rate limit (overload='block' backpressure)")
    adm_qs = fam("siddhi_admission_quota_state", "gauge",
                 "Admission quota state per app: 0=ok 1=degraded "
                 "(SLO ladder halved the rate) 2=shedding (state "
                 "ceiling hit, growth denied)")
    adm_gd = fam("siddhi_admission_growth_denials_total", "counter",
                 "Emission-cap/state growths denied by the memory "
                 "ceiling (the app sheds overflow instead of growing)")
    adm_cp = fam("siddhi_admission_compile_penalties_total", "counter",
                 "Compile-gate penalties applied to this app's traces "
                 "for exceeding admission.max.recompiles.per.min")
    a_shed = fam("siddhi_async_shed_total", "counter",
                 "Events shed by a full bounded @async ingress queue "
                 "under queue.policy='shed', per stream")
    mrg_d = fam("siddhi_merged_dispatches_total", "counter",
                "Merged-group device dispatches (one jitted step runs "
                "every member query's stacked body — "
                "siddhi_tpu/optimizer)")
    mrg_b = fam("siddhi_merged_member_batches_total", "counter",
                "Per-query batches served through merged dispatches "
                "(members x dispatches) — divide by "
                "siddhi_merged_dispatches_total for the amortization "
                "factor")
    mrg_q = fam("siddhi_merged_queries", "gauge",
                "Member queries compiled into each merge group")
    ring_oc = fam("siddhi_ring_occupancy", "gauge",
                  "Emissions resident in a query's on-device serving "
                  "ring, awaiting the async drainer "
                  "(siddhi_tpu/serving)")
    ring_dr = fam("siddhi_ring_drains_total", "counter",
                  "Serving-ring emissions delivered by the async "
                  "drainer, per query")
    ring_gr = fam("siddhi_ring_overflow_grows_total", "counter",
                  "Serving-ring overflow growths (full ring doubled "
                  "via the admission-gated grow-via-replan path), "
                  "per query")
    srv_dep = fam("siddhi_serve_drainer_queue_depth", "gauge",
                  "Ring entries awaiting the serving drainer across "
                  "all of an app's rings right now")
    ph_sec = fam("siddhi_phase_seconds_total", "counter",
                 "Accumulated wall seconds attributed to each pipeline "
                 "phase per query (host clocks only — see "
                 "observability/phases.py for the latency-attribution "
                 "semantics)")
    ph_smp = fam("siddhi_phase_dispatches_sampled_total", "counter",
                 "Dispatches fenced with block_until_ready by the "
                 "sampled deep profiling mode (profile.sample.every=N) "
                 "to split submit wall from device compute, per query")
    so_occ = fam("siddhi_state_occupancy", "gauge",
                 "Utilization (occupancy/capacity, 0-1) of each sized "
                 "device state structure, from its host mirror "
                 "(observability/stateobs.py — never a device fetch)")
    so_hwm = fam("siddhi_state_high_water", "gauge",
                 "High-water occupancy of each sized device state "
                 "structure (rows/slots/keys) — monotone per process "
                 "and max-merged across snapshot restores")
    so_hot = fam("siddhi_key_hotset_share", "gauge",
                 "Share of keyed traffic landing in the hottest 1% of "
                 "observed keys (count-min + space-saving top-K over "
                 "staging's per-batch key sets), per query")

    from .stateobs import collect as _stateobs_collect
    for app_name, rt in sorted(runtimes.items()):
        st = rt.stats
        # refresh the observatory from the host mirrors first (plain
        # attribute reads: allocator lengths, ring counters — no device
        # work rides the scrape)
        _stateobs_collect(rt)
        snap = st.exposition_snapshot()
        uptime.sample(snap["uptime_s"], app=app_name)
        level.sample({"OFF": 0, "BASIC": 1, "DETAIL": 2}.get(st.level, 0),
                     app=app_name)
        for sid, n in sorted(snap["stream_in"].items()):
            s_in.sample(n, app=app_name, stream=sid)
        for q, n in sorted(snap["query_events"].items()):
            q_ev.sample(n, app=app_name, query=q)
        for q, h in sorted(snap["query_hist"].items()):
            q_lat.histogram(h, app=app_name, query=q)
        for sid, h in sorted(snap["junction_hist"].items()):
            j_lat.histogram(h, app=app_name, stream=sid)
        for sid, h in sorted(snap["sink_hist"].items()):
            k_lat.histogram(h, app=app_name, sink=sid)
        for owner, info in sorted(st.recompiles(rt).items()):
            recomp.sample(info["count"], app=app_name, query=owner)
        for name, n in sorted(snap["counters"].items()):
            if name.endswith(".dropped"):
                ctr.sample(n, app=app_name, query=name[:-len(".dropped")])
            elif name.endswith(".cap_growths"):
                grow.sample(n, app=app_name,
                            query=name[:-len(".cap_growths")])
            elif name.endswith(".fused_dispatches"):
                fus_d.sample(n, app=app_name,
                             query=name[:-len(".fused_dispatches")])
            elif name.endswith(".fused_batches"):
                fus_b.sample(n, app=app_name,
                             query=name[:-len(".fused_batches")])
            elif name.endswith(".emitted_rows"):
                e_rows.sample(n, app=app_name,
                              query=name[:-len(".emitted_rows")])
            elif name.endswith(".emitted_bytes"):
                e_byt.sample(n, app=app_name,
                             query=name[:-len(".emitted_bytes")])
            elif name.startswith("async.") and name.endswith(".shed"):
                a_shed.sample(n, app=app_name,
                              stream=name[len("async."):-len(".shed")])
            elif name.startswith("merged.") and \
                    name.endswith(".dispatches"):
                mrg_d.sample(n, app=app_name,
                             group=name[len("merged."):
                                        -len(".dispatches")])
            elif name.startswith("merged.") and \
                    name.endswith(".member_batches"):
                mrg_b.sample(n, app=app_name,
                             group=name[len("merged."):
                                        -len(".member_batches")])
            elif name.endswith(".ring_drains"):
                ring_dr.sample(n, app=app_name,
                               query=name[:-len(".ring_drains")])
            elif name.endswith(".ring_grows"):
                ring_gr.sample(n, app=app_name,
                               query=name[:-len(".ring_grows")])
        # phase profiler: host-clock ns accumulators, snapshot under the
        # profiler's own lock — still zero device work on the scrape
        ph_snap = snap.get("phases", {})
        ph_sampled = ph_snap.get("sampled", {})
        for q, phases in sorted(ph_snap.get("queries", {}).items()):
            for p, v in phases.items():
                ph_sec.sample(v["ns"] / 1e9, app=app_name, query=q,
                              phase=p)
            # emitted at 0 while deep mode is off so rate() works from
            # the first scrape after profile.sample.every flips on
            ph_smp.sample(ph_sampled.get(q, 0), app=app_name, query=q)
        for q, n in sorted(ph_sampled.items()):
            if q not in ph_snap.get("queries", {}):
                ph_smp.sample(n, app=app_name, query=q)
        # state observatory: occupancy ratio + high-water per sized
        # structure, hot-set concentration per keyed query
        so_snap = snap.get("stateobs", {})
        for q, structures in sorted(so_snap.get("structures",
                                                {}).items()):
            for s, rec in structures.items():
                so_occ.sample(rec["utilization"], app=app_name,
                              query=q, structure=s)
                so_hwm.sample(rec["high_water"], app=app_name,
                              query=q, structure=s)
        for q, hot in sorted(so_snap.get("hotness", {}).items()):
            so_hot.sample(hot["hot_share_1pct"], app=app_name, query=q)
        for gid, mg in sorted(getattr(rt, "merged_groups", {}).items()):
            mrg_q.sample(len(getattr(mg, "members", ())), app=app_name,
                         group=gid)
        buf_e.sample(rt.buffered_emissions(), app=app_name)
        for sid, n in sorted(rt.buffered_ingress().items()):
            buf_i.sample(n, app=app_name, stream=sid)
        # bounded-queue depth gauges (queue qsize reads — host only)
        if hasattr(rt, "queue_depths"):
            for sid, n in sorted(rt.queue_depths().items()):
                q_dep.sample(n, app=app_name, stream=sid)
        if hasattr(rt, "drainer_depth"):
            d_dep.sample(rt.drainer_depth(), app=app_name)
        # serving-loop gauges: ring occupancy per query + drainer
        # backlog (host-side deque length reads — never a fetch)
        if hasattr(rt, "ring_occupancies"):
            for q, n in sorted(rt.ring_occupancies().items()):
                ring_oc.sample(n, app=app_name, query=q)
        if hasattr(rt, "serve_drainer_depth"):
            srv_dep.sample(rt.serve_drainer_depth(), app=app_name)
        # SLO rule states, attached to the runtime by the sampler tick
        slo = rt.__dict__.get("_slo_state") \
            if hasattr(rt, "__dict__") else None
        if slo:
            from .slo import STATE_GAUGE
            for rname, r in sorted(slo.get("rules", {}).items()):
                slo_g.sample(STATE_GAUGE.get(r.get("state"), 0),
                             app=app_name, rule=rname)
        # state-memory accounting rides the scrape under the same
        # invariant: memory.component_bytes walks shape/dtype metadata
        # only (observability/memory.py), so this adds zero device work
        from .memory import component_bytes
        for owner, comps in sorted(component_bytes(rt).items()):
            for comp, nb in sorted(comps.items()):
                mem.sample(nb, app=app_name, query=owner, component=comp)
        # shard dimension: routing totals + per-batch occupancy from the
        # stats registry, per-shard residency from sharding metadata
        # (shard_shape arithmetic — still no device work)
        for q, per_shard in sorted(snap.get("shard_events", {}).items()):
            for d, c in enumerate(per_shard):
                sh_ev.sample(c, app=app_name, query=q, shard=d)
        for key, h in sorted(snap.get("shard_hist", {}).items()):
            q, _, shard = key.rpartition(":shard")
            sh_oc.histogram_raw(h, app=app_name, query=q, shard=shard)
        from ..sharding import shard_state_bytes
        for d, nb in sorted(shard_state_bytes(rt).items()):
            sh_mem.sample(nb, app=app_name, shard=d)
        # sink resilience: plain attribute reads off each connection's
        # state machine — no locks held, no device work
        from ..io.resilience import state_gauge
        for sk in getattr(rt, "sinks", ()):
            for i, conn in enumerate(getattr(sk, "connections", ())):
                lbl = dict(app=app_name, stream=sk.stream_id, dest=i)
                s_ret.sample(conn.retries_total, **lbl)
                s_brk.sample(state_gauge(conn.state), **lbl)
                s_drp.sample(conn.dropped_total, **lbl)
                s_buf.sample(conn.buffered(), **lbl)
        es = getattr(rt, "error_store", None)
        if es is not None:
            try:
                for state, v in sorted(es.stats().items()):
                    if state in ("buffered", "stored", "dropped",
                                 "replayed"):
                        e_st.sample(v, app=app_name, state=state)
            except Exception:  # noqa: BLE001 — custom SPI must not
                pass           # break the scrape
        r_fb.sample(getattr(rt, "restore_fallbacks", 0), app=app_name)
        # admission controller counters: plain attribute reads off the
        # per-app controller (core/admission.py) — still no device work
        adm = getattr(rt, "admission", None)
        if adm is not None:
            from ..core.admission import QUOTA_GAUGE
            for sid, n in sorted(adm.shed_by_stream.items()):
                adm_shed.sample(n, app=app_name, stream=sid)
            adm_blk.sample(adm.blocked_ms_total, app=app_name)
            adm_qs.sample(QUOTA_GAUGE.get(adm.quota_state, 0),
                          app=app_name)
            adm_gd.sample(adm.growth_denials, app=app_name)
            adm_cp.sample(adm.compile_penalties, app=app_name)

    # process-wide admission families: deploys denied before a runtime
    # existed, and the shared compile-gate queue depth
    from ..core.admission import COMPILE_GATE, denied_deploys
    fam("siddhi_admission_denied_deploys_total", "counter",
        "App deployments denied by the admission memory gate before "
        "any planning or compile (process-wide)").sample(
            denied_deploys())
    fam("siddhi_admission_compile_queue_depth", "gauge",
        "Traces currently waiting at (or penalized before) the shared "
        "XLA compile-admission gate").sample(COMPILE_GATE.waiting)

    return "\n".join(lines) + ("\n" if lines else "")
