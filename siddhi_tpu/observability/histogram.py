"""Fixed-bucket log2 latency histogram.

Reference (what): the reference wires Dropwizard `Histogram`s with
exponentially-decaying reservoirs per query (ThroughputMetric /
LatencyMetric roles).  TPU design (how): a reservoir samples and locks; on
our hot path (one record per micro-batch, potentially from several junction
worker threads) we want something lock-free and allocation-free.  A value's
bucket is just `int.bit_length()` — bucket `i` holds durations in
`[2^(i-1), 2^i)` nanoseconds — so `record()` is two int adds and a list
increment.  Quantiles interpolate linearly inside the winning bucket, which
bounds the error at one octave (factor 2) — plenty to tell a 10µs p50 from
a 2s recompile-stall p99.

Concurrent `record()`s may very rarely lose a count to a GIL interleave;
that is the accepted trade for keeping the hot path lock-free (the
reference's reservoirs make the same kind of approximation by sampling).
"""
from __future__ import annotations

from typing import Dict, List

NBUCKETS = 64  # covers 1ns .. ~292 years in powers of two


class LogHistogram:
    __slots__ = ("counts", "total", "sum_ns", "max_ns")

    def __init__(self):
        self.counts: List[int] = [0] * NBUCKETS
        self.total = 0
        self.sum_ns = 0
        self.max_ns = 0

    # -- hot path --------------------------------------------------------------
    def record(self, ns: int) -> None:
        if ns < 0:
            ns = 0
        i = ns.bit_length()
        if i >= NBUCKETS:
            i = NBUCKETS - 1
        self.counts[i] += 1
        self.total += 1
        self.sum_ns += ns
        if ns > self.max_ns:
            self.max_ns = ns

    # -- queries ---------------------------------------------------------------
    def quantile(self, q: float) -> float:
        """Approximate q-quantile in nanoseconds (error <= one octave).

        Bucket convention (the log2 UPPER-BOUND convention, shared with
        `buckets_seconds`/`buckets_raw` exposition): bucket `i` holds
        integer values with `bit_length() == i`, i.e. the half-open range
        `[2^(i-1), 2^i)` for `i >= 1` and exactly `{0}` for `i == 0`.
        The quantile interpolates linearly inside the winning bucket over
        `[2^(i-1), 2^i]` — so a target landing EXACTLY on a bucket's
        cumulative boundary reports that bucket's exclusive upper bound
        `2^i`, the same `le` value Prometheus' `histogram_quantile` would
        interpolate to from the exported buckets.  The result is clamped
        to the observed max, which also makes a single-sample histogram
        report the exact recorded value at every q."""
        if self.total == 0:
            return 0.0
        target = q * self.total
        cum = 0.0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = float(1 << (i - 1)) if i > 0 else 0.0
                hi = float(1 << i) if i > 0 else 0.0
                frac = (target - cum) / c
                return min(lo + frac * (hi - lo), float(self.max_ns))
            cum += c
        return float(self.max_ns)

    @property
    def mean_ns(self) -> float:
        return self.sum_ns / self.total if self.total else 0.0

    def snapshot(self) -> Dict:
        """Summary dict for `report()` (microseconds for readability, like
        the scalar metrics they replace)."""
        return {
            "count": self.total,
            "mean_us": self.mean_ns / 1e3,
            "p50_us": self.quantile(0.50) / 1e3,
            "p95_us": self.quantile(0.95) / 1e3,
            "p99_us": self.quantile(0.99) / 1e3,
            "max_us": self.max_ns / 1e3,
        }

    def buckets_seconds(self) -> List:
        """Cumulative (le_seconds, count) pairs for Prometheus exposition,
        trimmed to the occupied range (+Inf is appended by the renderer)."""
        out = []
        cum = 0
        hi = 0
        for i in range(NBUCKETS - 1, -1, -1):
            if self.counts[i]:
                hi = i
                break
        for i in range(hi + 1):
            cum += self.counts[i]
            out.append(((1 << i) / 1e9, cum))
        return out

    def buckets_raw(self) -> List:
        """Cumulative (le, count) pairs in the RAW recorded unit — for
        count-valued histograms (batches per @fuse dispatch, events per
        shard per batch) where a seconds conversion would lie."""
        out = []
        cum = 0
        hi = 0
        for i in range(NBUCKETS - 1, -1, -1):
            if self.counts[i]:
                hi = i
                break
        for i in range(hi + 1):
            cum += self.counts[i]
            out.append((float(1 << i), cum))
        return out

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        m = LogHistogram()
        m.counts = [a + b for a, b in zip(self.counts, other.counts)]
        m.total = self.total + other.total
        m.sum_ns = self.sum_ns + other.sum_ns
        m.max_ns = max(self.max_ns, other.max_ns)
        return m


def hist_of(registry: Dict[str, LogHistogram], name: str,
            lock=None) -> LogHistogram:
    """Get-or-create without holding `lock` on the steady-state path: the
    dict lookup is GIL-atomic; only first-touch of a name takes the lock."""
    h = registry.get(name)
    if h is not None:
        return h
    if lock is None:
        return registry.setdefault(name, LogHistogram())
    with lock:
        return registry.setdefault(name, LogHistogram())
