"""Device-resident serving loop (ROADMAP open item 2).

Three pieces, one invariant — the SEND PATH NEVER FETCHES:

- ring.py      on-device emission rings: emissions append into a
               persistent device buffer (dispatch-only send path)
- drain.py     per-app async drainer: the only thread that blocks on
               D2H, feeding the unchanged delivery machinery
- staging.py   double-buffered H2D staging: batch N+1 uploads while
               batch N computes

Enablement: `@serve` on a query / input stream / `@app:serve`
(core/plan_facts.serve_enabled), or app-wide via the config property
`serving.enabled: 'true'`.  Ring sizing and drain cadence read
`serving.ring.capacity` (slots, default plan_facts.SERVE_RING_SLOTS)
and `serving.drain.interval.ms` (default 2 ms); both are overridable
per query with @serve(ring.capacity=).
"""
from __future__ import annotations

from ..core.plan_facts import SERVE_RING_SLOTS
from .drain import ServingDrainer
from .ring import EmissionRing
from .staging import DoubleBufferedStager

__all__ = ["EmissionRing", "ServingDrainer", "DoubleBufferedStager",
           "serving_config", "ensure_ring", "ring_append",
           "SERVE_RING_SLOTS"]

_TRUE = ("true", "1", "yes", "on")
DEFAULT_DRAIN_INTERVAL_MS = 2.0


def serving_config(rt) -> dict:
    """App-level serving settings from the manager config (memoized on
    the runtime: config cannot change under a live manager)."""
    cfg = rt.__dict__.get("_serving_config")
    if cfg is not None:
        return cfg
    enabled = False
    capacity = SERVE_RING_SLOTS
    interval_ms = DEFAULT_DRAIN_INTERVAL_MS
    try:
        cm = getattr(rt, "config_manager", None)
        if cm is not None:
            v = cm.extract_property("serving.enabled")
            if v is not None:
                enabled = str(v).lower() in _TRUE
            v = cm.extract_property("serving.ring.capacity")
            if v:
                capacity = max(1, int(v))
            v = cm.extract_property("serving.drain.interval.ms")
            if v:
                interval_ms = max(0.0, float(v))
    except Exception:  # noqa: BLE001 — malformed config reads as default
        pass
    cfg = {"enabled": enabled, "ring_capacity": capacity,
           "drain_interval_ms": interval_ms}
    rt.__dict__["_serving_config"] = cfg
    return cfg


def ensure_ring(qr) -> EmissionRing:
    """The query's emission ring, created on first serving emission and
    registered with the app drainer (which lazy-starts its thread)."""
    ring = qr.__dict__.get("_serve_ring")
    if ring is None:
        app = qr.app
        cfg = serving_config(app)
        # @serve(ring.capacity=) stashed at wiring time (runtime.py sets
        # `serve_ring_capacity` next to `serve_emit`); 0 = use config
        cap = int(getattr(qr, "serve_ring_capacity", 0) or 0)
        drainer = app._serve_drainer
        ring = EmissionRing(qr, capacity=cap or cfg["ring_capacity"],
                            on_highwater=drainer.kick)
        qr.__dict__["_serve_ring"] = ring
        drainer.register(ring)
    return ring


def ring_append(qr, out, now: int, ingest_ns=None, trace=None) -> None:
    """Producer edge of the serving loop: dispatch the ring append and
    return — zero host<->device synchronization (core/runtime.py
    `_emit_output` routes here for serve-enabled runtimes).  `trace` is
    the dispatch thread's handed-off BatchTrace (tracing.handoff): it
    rides the ring so the drainer's delivery spans join the trace."""
    ensure_ring(qr).append(out, now, ingest_ns, trace)
