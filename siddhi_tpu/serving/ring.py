"""On-device emission rings: the send path becomes dispatch-only.

Reference behavior (what): the reference decouples producers from
consumers host-side with its Disruptor-backed async StreamJunction
(CORE/stream/StreamJunction.java:276) — a producer never blocks on a
consumer; it writes into a preallocated ring and moves on.

TPU design (how): every perf round since r04 shows the chip doing
~0.2 ms of work per dispatch while the host round-trip costs 73-95 ms,
and @pipeline/@fuse only *amortize* the blocking `device_get` — the
depth-k drain still makes a periodic fetch burst structural.  This
module does the Disruptor decoupling *across the PCIe boundary*: a
query's emissions append into a persistent DEVICE ring buffer (one
jitted `dynamic_update_index_in_dim` dispatch, no fetch) and stay in
HBM until the dedicated drainer thread (serving/drain.py) pulls whole
segments asynchronously.  The producer thread never calls
`jax.device_get` — tests guard this with a monkeypatched fetch.

Ring layout: a stacked pytree — every leaf of the query's output block
gains a leading [S] slot axis, preallocated once (so the ring's bytes
are static state: MEM001/state-bytes/audit account for them).  Appends
and reads are slot-indexed jitted programs shared across slots (the
index rides as a traced scalar: ONE compile per output signature, not
one per slot).  For mesh-sharded queries the ring leaves preserve the
output's NamedSharding with a replicated slot axis, so each shard hosts
its own ring segment and the drain fetches per-shard buffers
independently.

Overflow follows the emission-cap grow-via-replan pattern
(`_grow_emission_cap`): a full ring doubles in one jump, gated by
admission's state ceilings (`admit_growth`); a denied growth degrades
to bounded blocking backpressure on the producer — never a silent
drop.  An output-signature change (emission-cap growth replans the
step) seals the current ring generation and opens a fresh one; sealed
generations drain FIFO before newer entries, so delivery order per
query is exactly send order.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax

from ..observability import stateobs as _stateobs

jnp = jax.numpy
log = logging.getLogger("siddhi_tpu")

# ring capacity ceiling mirrors the emission-cap growth budget: past
# this the producer blocks (bounded-lag watermark) instead of growing
RING_CAP_MAX = 1 << 10


def _aval_key(out) -> Tuple:
    """Hashable (shape, dtype) signature of an output pytree — the ring
    generation key: entries with one signature share one buffer + one
    compiled append/read pair."""
    return tuple((tuple(x.shape), str(x.dtype))
                 for x in jax.tree_util.tree_leaves(out))


def _alloc_like(x, slots: int):
    """[S, ...] zeros for one output leaf.  Sharded leaves keep their
    NamedSharding with a replicated slot axis: each mesh device holds
    its own segment of every ring slot (per-shard rings — the drain
    transfers each shard's buffer independently)."""
    z = jnp.zeros((slots,) + tuple(x.shape), x.dtype)
    sh = getattr(x, "sharding", None)
    spec = getattr(sh, "spec", None)
    mesh = getattr(sh, "mesh", None)
    if spec is not None and mesh is not None and \
            any(p is not None for p in tuple(spec)):
        try:
            from jax.sharding import NamedSharding, PartitionSpec
            z = jax.device_put(
                z, NamedSharding(mesh, PartitionSpec(None, *tuple(spec))))
        except Exception:  # noqa: BLE001 — fall back to default placement
            pass
    return z


class _Generation:
    """One ring buffer: a stacked [S, ...] pytree plus FIFO head/tail.
    Appends go to the NEWEST generation only; sealed (older) generations
    drain to empty and are dropped, so a signature change never reorders
    delivery."""

    __slots__ = ("state", "slots", "head", "tail", "count", "key",
                 "out_len", "_set", "_read")

    def __init__(self, out, slots: int, owner: str):
        from ..core.steputil import jit_step
        self.slots = slots
        self.head = 0          # next write slot
        self.tail = 0          # next read slot
        self.count = 0         # occupied slots
        self.key = _aval_key(out)
        self.out_len = len(out)
        self.state = jax.tree.map(lambda x: _alloc_like(x, slots), out)

        def _set(state, o, i):
            return jax.tree.map(
                lambda b, x: jax.lax.dynamic_update_index_in_dim(
                    b, x, i, 0), state, o)

        def _read(state, i):
            return jax.tree.map(
                lambda b: jax.lax.dynamic_index_in_dim(
                    b, i, 0, keepdims=False), state)

        # slot index rides as a traced scalar: one compile per output
        # signature.  The buffer is donated — XLA updates the ring in
        # place instead of copying S slots per append.
        self._set = jit_step(_set, owner=f"serve:{owner}",
                             donate_argnums=(0,))
        self._read = jit_step(_read, owner=f"serve:{owner}:read")

    def append(self, out) -> int:
        slot = self.head
        self.state = self._set(self.state, out, slot)
        self.head = (slot + 1) % self.slots
        self.count += 1
        return slot

    def read_tail(self):
        """Dispatch the device read of the oldest slot (lazy arrays, no
        fetch) and free it.  Device execution order guarantees the read
        completes before any later append overwrites the slot."""
        out = self._read(self.state, self.tail)
        self.tail = (self.tail + 1) % self.slots
        self.count -= 1
        return out

    def nbytes(self) -> int:
        from ..observability.memory import tree_nbytes
        try:
            return tree_nbytes(self.state)
        except Exception:  # noqa: BLE001 — metrics must not throw
            return 0


class EmissionRing:
    """Per-runtime device emission ring.

    `append` is the producer edge (runs under the query lock, zero
    fetches); `take` is the drainer edge (dispatches slot reads, the
    blocking fetch happens downstream in serving/drain.py).  All
    bookkeeping is guarded by the ring's own condition lock so the
    drainer never needs the query lock — a full-ring producer blocking
    for space cannot deadlock against the thread that frees it.
    """

    def __init__(self, qr, capacity: int = 8,
                 on_highwater=None):
        self.qr = qr
        self.capacity = max(1, int(capacity))
        self._cond = threading.Condition()
        self._gens: List[_Generation] = []
        # (generation, now, ingest_ns, trace_token, append_ns) in send
        # order, across generations: the token is the dispatch thread's
        # handed-off BatchTrace (observability/tracing.handoff) so the
        # drainer's delivery spans join the originating trace; append_ns
        # stamps ring entry for the `ring_wait` phase (take - append)
        self._meta: "list" = []
        self._on_highwater = on_highwater
        self.appends_total = 0
        self.grows_total = 0
        self.generation = 0

    # -- producer edge (query lock held; never fetches) ---------------------
    def append(self, out, now: int, ingest_ns=None, trace=None) -> None:
        append_ns = time.perf_counter_ns()
        with self._cond:
            gen = self._gens[-1] if self._gens else None
            if gen is None or gen.key != _aval_key(out):
                # output signature changed (emission-cap replan): seal
                # the old generation — it keeps draining FIFO — and
                # open a fresh buffer at the configured capacity
                gen = _Generation(out, self.capacity, self.qr.name)
                self._gens.append(gen)
                self.generation += 1
            if gen.count >= gen.slots:
                gen = self._make_room(gen, out)
            gen.append(out)
            self._meta.append((gen, now, ingest_ns, trace, append_ns))
            self.appends_total += 1
            occ = len(self._meta)
            kick = occ >= self._high_water()
        if _stateobs.obs_enabled(self.qr.app):
            # serve-ring depth high-water for the sizing ledger (host
            # counter read — the producer edge stays fetch-free)
            self.qr.app.stats.stateobs.observe(
                self.qr.name, "serve_ring", occ, self.capacity,
                growable=self.capacity < RING_CAP_MAX,
                config_key="serving.ring.capacity")
        if kick and self._on_highwater is not None:
            # bounded-lag watermark: occupancy crossed high-water, wake
            # the drainer NOW instead of waiting out its interval
            self._on_highwater()

    def _high_water(self) -> int:
        return max(1, (self.capacity * 3) // 4)

    def _make_room(self, gen: "_Generation", out) -> "_Generation":
        """Full ring: grow 2x (admission-gated, the emission-cap
        grow-via-replan pattern) or block as bounded backpressure until
        the drainer frees a slot.  Called with the cond lock held."""
        new_cap = min(self.capacity * 2, RING_CAP_MAX)
        adm = getattr(self.qr.app, "admission", None)
        grown = False
        if new_cap > self.capacity and (
                adm is None or adm.admit_growth(
                    self.qr.name, (new_cap - self.capacity) *
                    max(1, gen.nbytes() // max(1, gen.slots)))):
            log.warning(
                "%s: emission ring full at %d slots; growing to %d "
                "(serving.ring.capacity pre-sizes and silences this)",
                self.qr.name, self.capacity, new_cap)
            self.capacity = new_cap
            stats = self.qr.app.stats
            if stats.enabled:
                stats.counter_inc(f"{self.qr.name}.ring_grows")
            self.grows_total += 1
            gen = _Generation(out, new_cap, self.qr.name)
            self._gens.append(gen)
            self.generation += 1
            grown = True
        if grown:
            return gen
        # growth denied (state ceiling) or at RING_CAP_MAX: block until
        # the drainer frees a slot — backpressure, never a silent drop
        if self._on_highwater is not None:
            self._on_highwater()
        waited = 0.0
        while gen.count >= gen.slots:
            if not self._cond.wait(timeout=0.05):
                waited += 0.05
                if waited >= 30.0:
                    raise RuntimeError(
                        f"{self.qr.name}: emission ring full for 30s "
                        f"with no drain progress (drainer dead?)")
                if self._on_highwater is not None:
                    self._on_highwater()
        return gen

    # -- drainer edge --------------------------------------------------------
    def take(self, max_n: Optional[int] = None) -> List[Tuple]:
        """Pop up to `max_n` pending entries in send order, dispatching
        each slot's device read (lazy arrays — the caller does ONE
        batched blocking fetch for everything it took).  Each item is
        (qr, out, now, ingest_ns, trace_token, ring_wait_ns)."""
        out: List[Tuple] = []
        take_ns = time.perf_counter_ns()
        with self._cond:
            n = len(self._meta) if max_n is None else \
                min(max_n, len(self._meta))
            for _ in range(n):
                gen, now, ingest_ns, trace, append_ns = self._meta.pop(0)
                out.append((self.qr, gen.read_tail(), now, ingest_ns,
                            trace, take_ns - append_ns))
            # drop fully-drained sealed generations (their buffers free)
            while len(self._gens) > 1 and self._gens[0].count == 0:
                self._gens.pop(0)
            if out:
                self._cond.notify_all()
        return out

    # -- introspection (host-side reads only) --------------------------------
    def occupancy(self) -> int:
        return len(self._meta)

    def nbytes(self) -> int:
        with self._cond:
            return sum(g.nbytes() for g in self._gens)

    def state_leaves(self):
        """Current generations' device buffers (metadata walks only —
        observability/memory.py counts the ring under `serve_ring`)."""
        return [g.state for g in self._gens]

    def facts(self) -> Dict[str, Any]:
        """EXPLAIN / healthz node for this ring."""
        return {
            "capacity": self.capacity,
            "occupancy": self.occupancy(),
            "high_water": self._high_water(),
            "appends_total": self.appends_total,
            "overflow_grows": self.grows_total,
            "generation": self.generation,
            "nbytes": self.nbytes(),
        }
