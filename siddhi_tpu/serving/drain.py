"""Async ring drainer: the only place serving emissions cross D2H.

One thread per app pulls every registered ring's pending segments and
blocks on the transfers HERE — `jax.device_get` / `block_until_ready`
never run in the send path (the producer merely dispatched a slot
write).  Delivery re-enters `_emit_output_sync`, so batch callbacks,
table ops, rate limiting, sink publication, breaker/error-store
routing, and the `<q>:e2e` histogram behave exactly as a blocking
fetch would — the serving loop changes WHEN the fetch happens, never
what delivery does.

Cadence: the thread wakes every `serving.drain.interval.ms` (bounded
lag for a quiet ring) and immediately on a high-water kick from any
ring (bounded occupancy under load).  Each cycle drains every ring and
pays ONE batched `device_get` for all taken segments — len-6
pattern/join outs contribute only their 16-byte count header (bulk
rows stay lazy via `_LazyBatchPayload`), len-4 outs are
window-capacity bounded and ship whole — the same amortization as
`_EmissionDrainer._run`.

`drain_all()` is the synchronous edge for flush/quiesce/shutdown: it
runs a cycle on the CALLER'S thread under the same delivery lock the
thread uses, so quiesce can drain rings to empty without racing the
drainer and snapshot never sees a non-empty ring.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import List

import jax

log = logging.getLogger("siddhi_tpu")

# a drainer that hasn't ticked for this many intervals while work is
# pending is considered stalled (healthz flips `degraded`, not `live`:
# producers fall back to backpressure, the app still processes)
STALL_INTERVALS = 10.0


class ServingDrainer:
    """Per-app serving drain thread (lazy-started on first ring)."""

    def __init__(self, app, interval_ms: float = 2.0):
        self.app = app
        self.interval_ms = float(interval_ms)
        self._rings: List = []
        self._cv = threading.Condition()
        # serializes delivery cycles: thread ticks and caller-side
        # drain_all never interleave, so per-ring delivery order is
        # exactly take order (which is exactly send order)
        self._deliver_lock = threading.Lock()
        self._thread = None
        self._started = False
        self._running = False
        self._kicked = False
        self.last_tick_ns = time.monotonic_ns()
        self.drains_total = 0
        self.drained_outputs_total = 0

    # -- registration --------------------------------------------------------
    def register(self, ring) -> None:
        with self._cv:
            if ring not in self._rings:
                self._rings.append(ring)
        self.start()

    def start(self) -> None:
        with self._cv:
            if self._started:
                return
            self._started = True
            self._running = True
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="siddhi-serve-drain")
            # see StreamJunction workers: internal threads bypass the
            # ingress gate so quiesce doesn't deadlock on its own drain
            self._thread._siddhi_internal = True
            self._thread.start()

    def kick(self) -> None:
        """High-water wakeup from a ring (bounded-lag watermark)."""
        with self._cv:
            self._kicked = True
            self._cv.notify_all()

    def stop(self) -> None:
        with self._cv:
            if not self._started:
                return
            self._running = False
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        self.drain_all()   # anything dispatched after the final tick

    # -- introspection -------------------------------------------------------
    def pending(self) -> int:
        """Ring entries accepted but not yet delivered (the serving
        analog of `_EmissionDrainer.pending`)."""
        return sum(r.occupancy() for r in list(self._rings))

    def depth(self) -> int:
        return self.pending()

    def alive(self) -> bool:
        t = self._thread
        return (not self._started) or (t is not None and t.is_alive())

    def stalled(self) -> bool:
        """Work pending but no tick within the stall budget — /healthz
        flips `degraded` on this (the app still processes; producers
        degrade to ring backpressure)."""
        if not self._started or self.pending() == 0:
            return False
        idle_ns = time.monotonic_ns() - self.last_tick_ns
        budget_ns = max(self.interval_ms, 1.0) * 1e6 * STALL_INTERVALS
        return idle_ns > budget_ns or not self.alive()

    # -- drain ---------------------------------------------------------------
    def drain_all(self) -> int:
        """Synchronous full drain on the caller's thread (flush /
        quiesce / shutdown).  Loops until every ring reads empty so
        snapshot state never includes an occupied ring."""
        total = 0
        for _ in range(64):
            n = self._cycle()
            total += n
            if n == 0 and self.pending() == 0:
                break
        return total

    def _cycle(self) -> int:
        with self._deliver_lock:
            items = []
            for ring in list(self._rings):
                items.extend(ring.take())
            if not items:
                return 0
            self._deliver(items)
            self.drains_total += 1
            self.drained_outputs_total += len(items)
            return len(items)

    def _deliver(self, items) -> None:
        import traceback
        from ..core.runtime import _emit_output_sync
        from ..observability import tracing as _tracing
        # phase accounting: each item's ring residency (append -> take,
        # stamped by ring.take) plus this cycle's batched fetch wall —
        # charged per item, exactly as each item's e2e sample counts it
        t_fetch = time.perf_counter_ns()
        # ONE blocking fetch for every segment taken this cycle: len-6
        # outs contribute the 16-byte header, len-4 outs ship whole
        try:
            fetched = jax.device_get([
                (out[0], out[1]) if len(out) == 6 else out
                for _, out, _, _, _, _ in items])
        except Exception:  # noqa: BLE001 — drainer must survive
            traceback.print_exc()
            fetched = [None] * len(items)
        fetch_ns = time.perf_counter_ns() - t_fetch
        per_q = {}
        loop_t0 = time.perf_counter_ns()
        for (qr, out, now, t_in, trace, wait_ns), fetch_h in \
                zip(items, fetched):
            ph = qr.app.stats.phases
            # in-batch wait: deliveries run serially, so a later item's
            # e2e contains every predecessor's demux/sink wall — that
            # residency is drainer wait, charged here so the phase sum
            # keeps tracking e2e (attribution rule in phases.py)
            ph.add(qr.name, "ring_wait",
                   wait_ns + (time.perf_counter_ns() - loop_t0))
            ph.add(qr.name, "d2h_drain", fetch_ns)
            try:
                if fetch_h is None:
                    continue
                with _tracing.adopt(trace):
                    if len(out) == 6:
                        _emit_output_sync(qr, out, now, header=fetch_h,
                                          ingest_ns=t_in)
                    else:
                        _emit_output_sync(qr, fetch_h, now,
                                          ingest_ns=t_in)
                per_q[qr] = per_q.get(qr, 0) + 1
            except Exception as exc:  # noqa: BLE001 — drainer survives
                # same fault routing as _EmissionDrainer._run: overflow
                # and callback failures reach the exception listener
                log.error("serving drain error in %s: %s",
                          getattr(qr, "name", "?"), exc)
                listener = getattr(qr.app, "exception_listener", None)
                if listener is not None:
                    try:
                        listener(exc)
                    except Exception:  # noqa: BLE001
                        traceback.print_exc()
                else:
                    traceback.print_exc()
        for qr, n in per_q.items():
            st = qr.app.stats
            if st.enabled:
                st.counter_inc(f"{qr.name}.ring_drains", n)

    def _run(self) -> None:
        while True:
            with self._cv:
                if not self._running:
                    return
                if not self._kicked:
                    self._cv.wait(timeout=max(self.interval_ms, 0.1) / 1e3)
                self._kicked = False
                if not self._running:
                    return
            self.last_tick_ns = time.monotonic_ns()
            try:
                self._cycle()
            except Exception:  # noqa: BLE001 — drainer must survive
                import traceback
                traceback.print_exc()
