"""Double-buffered H2D staging: batch N+1 uploads while N computes.

In the blocking path a batch's host->device transfer starts inside
`process_staged` (StagedBatch.to_device), AFTER the junction has
waited on the query lock and resolved group slots — the upload
serializes behind host staging work, and on a remote accelerator its
tunnel latency lands in the send path.

The stager moves the upload to the junction's ACCEPT edge: the moment
a staged batch enters dispatch (sync path) or the @async ingress queue
(async path), its columns are cast host-side and `jax.device_put`
starts — non-blocking, so by the time `to_device` runs the transfer
has overlapped slot resolution, lock wait, and (because dispatch is
asynchronous) the previous batch's device compute.  `to_device` then
adopts the prestaged arrays instead of re-transferring.

Ownership is donation-discipline: the stager's device buffers are
handed to exactly ONE step dispatch and never touched host-side again
(mirrors `jit_step(donate_argnums=(0,))` on state) — the pipeline
keeps at most `depth` uploads in flight, so a slow device backpressures
staging instead of accumulating transfers.
"""
from __future__ import annotations

import collections
import threading

import jax
import numpy as np

jnp = jax.numpy


class DoubleBufferedStager:
    """Per-app H2D staging pipeline (default depth 2: the classic
    double buffer — one upload in flight while one batch computes)."""

    def __init__(self, depth: int = 2):
        self.depth = max(1, int(depth))
        self._lock = threading.Lock()
        # refs to in-flight uploads; bounded so a stalled device holds
        # at most `depth` staged transfers alive
        self._inflight = collections.deque(maxlen=self.depth)
        self.staged_total = 0
        self.adopted_total = 0

    def stage(self, staged, schema) -> None:
        """Start the non-blocking upload of one StagedBatch's arrays and
        attach them for `to_device` adoption.  Idempotent per batch; a
        failure leaves the batch unstaged (to_device transfers as
        before) — staging is an overlap optimization, never a
        correctness dependency."""
        if getattr(staged, "dev", None) is not None:
            return
        try:
            from ..core.event import EventBatch
            cols = tuple(
                jnp.asarray(np.asarray(c).astype(d, copy=False))
                for c, d in zip(staged.cols, schema.dtypes))
            batch = EventBatch(jnp.asarray(staged.ts),
                               jnp.asarray(staged.kind),
                               jnp.asarray(staged.valid), cols)
        except Exception:  # noqa: BLE001 — fall back to in-path transfer
            return
        staged.dev = (schema, batch)
        with self._lock:
            self._inflight.append(batch)
            self.staged_total += 1

    def adopted(self) -> None:
        with self._lock:
            self.adopted_total += 1

    def facts(self) -> dict:
        with self._lock:
            return {
                "depth": self.depth,
                "in_flight": len(self._inflight),
                "staged_total": self.staged_total,
                "adopted_total": self.adopted_total,
            }
