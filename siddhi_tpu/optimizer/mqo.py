"""Multi-query optimizer: compile co-resident queries into shared
dispatches.

Reference role (what): the reference plans strictly per query off a
shared async junction (CORE/query/QueryRuntime.java — each query gets
its own processor chain even when dozens hang off one StreamJunction),
so N queries on one stream cost N traversals per event.

TPU design (how): here each query compiles to one jitted step, so N
co-resident queries cost N device dispatches, N emission fetches, and N
recompile owners per batch — and every perf round since r04 names the
per-dispatch host round-trip as the bottleneck.  This pass runs AFTER
per-query planning and BEFORE traffic: it partitions an app's plain
stream queries into **merge groups** keyed on (stream, @async/@pipeline/
@fuse decorations), stacks the member bodies into ONE jitted step per
group (`merged:<group>` recompile owner), fetches every member's
emission block in ONE device_get, and demultiplexes host-side so each
query's sinks, callbacks, rate limits, table writes, and error-store
semantics are untouched.  Members whose pre-window chain + window spec
+ group-by layout agree form a **shared unit** inside the group: they
reference one window buffer and one group-slot allocator (the
`window[shared]` component in state accounting) instead of per-query
duplicates.

Grouping is decided by `core/plan_facts.merge_plan` — the same single
source lint MQO001 and EXPLAIN's `merge` node read — and validated here
against the actual plans (any surprise demotes the query back to its
own dispatch with a recorded reason).  `optimizer.merge.enabled=false`
(manager config property) disables the pass app-wide.

Semantics kept exact, per query: outputs are byte-identical to the
unmerged plan (tests/test_mqo.py asserts this across filters, windows,
group-by, @fuse, @async, rate limits, and fault routing); snapshots
store each member's state view (shared window included once per member
record, identical bytes), so merged<->unmerged and mesh-resize restores
ride the existing per-query snapshot machinery unchanged.  The one
relaxation matches @fuse: a member's table writes become visible to
co-members at dispatch granularity, not mid-batch.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Tuple

import jax

from ..core import event as ev
from ..core import plan_facts
from ..core.steputil import jit_step
from ..core.window import NO_WAKEUP

jnp = jax.numpy
log = logging.getLogger("siddhi_tpu")


def merge_enabled(rt) -> bool:
    """`optimizer.merge.enabled` manager config property (default on);
    any of false/0/off/no disables the pass."""
    try:
        cm = getattr(rt.manager, "config_manager", None)
        v = cm.extract_property("optimizer.merge.enabled") \
            if cm is not None else None
    except Exception:  # noqa: BLE001 — config must not break deploy
        v = None
    if v is None:
        return True
    return str(v).strip().lower() not in ("false", "0", "off", "no")


class MergedGroupRuntime:
    """One merge group's host wrapper: stages each batch once, runs the
    stacked member bodies as ONE jitted step, and demuxes per-query
    emissions.  Subscribes to the junction in place of its members;
    members stay in `rt.query_runtimes` (snapshots, callbacks, metrics,
    EXPLAIN all keep addressing them by name) and read/write their state
    through `member_state`/`set_member_state` views."""

    def __init__(self, rt, gmeta: Dict,
                 members: List[Tuple[str, object]],
                 units: List[Tuple[str, List[int]]]):
        self.app = rt
        self.group = gmeta["group"]
        self.stream_id = gmeta["stream"]
        self.name = f"merged:{self.group}"
        self.members = [qr for _, qr in members]
        self.units = units
        self._junction = rt.junctions[self.stream_id]
        self.in_schema = self.members[0].planned.in_schema
        # ONE lock for the group: demux re-enters member emission paths
        # (pipeline deques, table writes), and quiesce/flush take member
        # locks — sharing the RLock keeps every such path serialized
        # exactly as the per-query lock did unmerged
        self._qlock = threading.RLock()
        # member position map: id(member) -> (unit idx, pos in unit, mode)
        self._slots: Dict[int, Tuple[int, int, str]] = {}
        state: List = []
        for u, (mode, idxs) in enumerate(units):
            if mode == "solo":
                m = self.members[idxs[0]]
                self._slots[id(m)] = (u, 0, mode)
                state.append(m._state)
            else:
                lead = self.members[idxs[0]]
                astates = []
                for j, i in enumerate(idxs):
                    m = self.members[i]
                    self._slots[id(m)] = (u, j, mode)
                    astates.append(m._state[1])
                state.append((lead._state[0], tuple(astates)))
                # shared group-slot space: every member resolves group
                # keys through the LEADER's allocator (identical key
                # layout is the shared-unit precondition), so the slot
                # maps — and MEM001's key-slot bytes — exist once
                for i in idxs[1:]:
                    self.members[i].planned.slot_allocator = \
                        lead.planned.slot_allocator
        self._state = tuple(state)
        for m in self.members:
            m._merged = self
            m._state = None
            m._qlock = self._qlock
        self.raw_body = self._build_body()
        self._step = jit_step(self.raw_body, owner=self.name,
                              donate_argnums=(0,))
        # @fuse(batches=K) on every member: the MERGED dispatch owns the
        # stack (kind 'merged' in core/fusion.py); members drop theirs
        self._fuse = None
        k = int(gmeta.get("decorations", {}).get("fuse", 0) or 0)
        if k > 0:
            from ..core import fusion as _fusion
            for m in self.members:
                if getattr(m, "_fuse", None) is not None:
                    m._fuse = None
                    m._fuse_excluded = (
                        f"query dispatch is merged — {self.name} owns "
                        f"the @fuse stack")
            self._fuse = _fusion.FuseBuffer(self, k, "merged")

    # -- state views (snapshots/restore address members by name) ---------------
    def member_state(self, qr):
        u, j, mode = self._slots[id(qr)]
        st = self._state[u]
        return st if mode == "solo" else (st[0], st[1][j])

    def set_member_state(self, qr, v) -> None:
        u, j, mode = self._slots[id(qr)]
        state = list(self._state)
        if mode == "solo":
            state[u] = v
        else:
            w_new, a_new = v
            astates = list(state[u][1])
            astates[j] = a_new
            state[u] = (w_new, tuple(astates))
        self._state = tuple(state)

    def mode_of(self, qr) -> str:
        _, _, mode = self._slots[id(qr)]
        return "shared" if mode == "shared" else "stacked"

    # -- state accounting (observability/memory.py) ----------------------------
    def member_components(self, qr) -> Dict[str, int]:
        """A member's EXCLUSIVE state bytes: shared-unit members carry
        only their selector slab — the shared window buffer is reported
        once, under the group (shared_components)."""
        from ..observability.memory import tree_nbytes
        u, j, mode = self._slots[id(qr)]
        st = self._state[u]
        if mode == "solo":
            return {"window": tree_nbytes(st[0]),
                    "selector": tree_nbytes(st[1])}
        return {"selector": tree_nbytes(st[1][j])}

    def shared_components(self) -> Dict[str, int]:
        """{component: bytes} the GROUP owns: shared window buffers
        (counted once) + any pending @fuse stack."""
        from ..observability.memory import leaf_nbytes, tree_nbytes
        out: Dict[str, int] = {}
        shared = 0
        for u, (mode, _idxs) in enumerate(self.units):
            if mode == "shared":
                shared += tree_nbytes(self._state[u][0])
        if shared:
            out[plan_facts.MERGE_SHARED_COMPONENT] = shared
        fb = self._fuse
        if fb is not None and fb.items:
            total = 0
            for staged, _now in fb.items:
                total += leaf_nbytes(staged.ts) + \
                    leaf_nbytes(staged.kind) + leaf_nbytes(staged.valid)
                total += sum(leaf_nbytes(c) for c in staged.cols)
            if total:
                out["fuse_stack"] = total
        return out

    # -- the merged step -------------------------------------------------------
    def _build_body(self):
        units = self.units
        members = self.members

        def merged_body(state, ts, kind, valid, cols, gslots, now,
                        in_tabs, pslots):
            outs: List = [None] * len(members)
            new_state: List = []
            for u, (mode, idxs) in enumerate(units):
                if mode == "solo":
                    i = idxs[0]
                    p = members[i].planned
                    st, out, _wake = p.raw_step(
                        state[u], ts, kind, valid, cols, gslots[u], now,
                        in_tabs[i], pslots[i])
                    new_state.append(st)
                    outs[i] = out
                else:
                    wstate, astates = state[u]
                    lead = members[idxs[0]].planned
                    wstate, orows, _wake = lead.stage_body(
                        wstate, ts, kind, valid, cols, gslots[u], now,
                        in_tabs[idxs[0]])
                    new_as = []
                    for j, i in enumerate(idxs):
                        a, out = members[i].planned.select_body(
                            astates[j], orows, now, in_tabs[i],
                            pslots[i])
                        new_as.append(a)
                        outs[i] = out
                    new_state.append((wstate, tuple(new_as)))
            return (tuple(new_state), tuple(outs),
                    jnp.asarray(NO_WAKEUP, jnp.int64))
        return merged_body

    # -- dispatch --------------------------------------------------------------
    def _prep(self, staged: ev.StagedBatch, now: int) -> Tuple:
        """Host slot staging, ONCE per unit: shared units resolve group
        keys through the leader (one allocator), solo units through
        their own member."""
        gslots: List = []
        pslots: List = [()] * len(self.members)
        for mode, idxs in self.units:
            lead = self.members[idxs[0]]
            g, ps = lead._slots_for_batch(staged, now)
            gslots.append(jnp.asarray(g))
            if mode == "solo" and ps:
                pslots[idxs[0]] = tuple(jnp.asarray(s) for s in ps)
        return tuple(gslots), tuple(pslots)

    def _in_tabs(self) -> Tuple:
        return tuple(self.app.in_probe_tables(m.planned.in_deps)
                     for m in self.members)

    def process_staged(self, staged: ev.StagedBatch, now: int) -> None:
        dbg = getattr(self.app, "_debugger", None)
        if dbg is not None:
            for m in self.members:
                dbg.check_break_point(m.name, "IN", staged)
        fb = self._fuse
        if fb is not None and fb.offer((staged, now), staged, None):
            return
        self._dispatch(staged, now)

    def _dispatch(self, staged: ev.StagedBatch, now: int) -> None:
        from ..core.runtime import _maybe_span
        stats = self.app.stats
        t0 = time.perf_counter_ns() if stats.enabled else 0
        gslots, pslots = self._prep(staged, now)
        batch = staged.to_device(self.in_schema)
        with _maybe_span("step", query=self.name, kind="merged"):
            self._state, outs, _wake = self._step(
                self._state, batch.ts, batch.kind, batch.valid,
                batch.cols, gslots,
                jnp.asarray(now, jnp.int64), self._in_tabs(), pslots)
        if stats.enabled:
            stats.counter_inc(f"merged.{self.group}.dispatches")
            stats.counter_inc(f"merged.{self.group}.member_batches",
                              len(self.members))
        stamp = self.__dict__.get("_ingest_ns")
        self._demux([(outs, staged, now, stamp)], t0)

    # -- demux: one combined fetch, per-query delivery -------------------------
    def _demux(self, batches: List[Tuple], t0: int) -> None:
        """Deliver per-query emissions for one or more dispatched
        batches.  `batches` entries are (outs, staged, now, ingest_ns)
        where `outs` is the per-member output tuple of ONE batch.

        Sync mode fetches every consumed member's block across all
        batches in ONE `device_get`; @async/@pipeline members get device
        slices and re-enter their deferred paths (the drainer/deque
        already batch their fetches).  A member's delivery failure
        routes through the junction's fault handling exactly as an
        unmerged query's would, without blocking its co-members.  Step
        wall time splits evenly across members; each member's own demux
        time is measured around its delivery — the per-query latency
        accounting admission/tenant blame rides on."""
        from ..core import runtime as _rt
        stats = self.app.stats
        members = self.members
        deferred = (getattr(members[0], "async_emit", False) and
                    self.app._drainer is not None) or \
            bool(getattr(members[0], "pipeline_emit", 0) or 0) or \
            getattr(members[0], "serve_emit", False)
        consumers = [i for i, m in enumerate(members)
                     if _rt._has_consumers(m)]
        hosted: Dict[int, List] = {}
        if consumers and not deferred:
            flat = jax.device_get(
                [[b[0][i] for b in batches] for i in consumers])
            hosted = dict(zip(consumers, flat))
        elif consumers:
            hosted = {i: [b[0][i] for b in batches] for i in consumers}
        share = 0
        if stats.enabled:
            share = (time.perf_counter_ns() - t0) // \
                max(1, len(members) * len(batches))
        for k, (_outs, staged, now, stamp) in enumerate(batches):
            for i, m in enumerate(members):
                td = time.perf_counter_ns() if stats.enabled else 0
                try:
                    if i in hosted:
                        m.__dict__["_ingest_ns"] = stamp
                        try:
                            _rt._emit_output(m, hosted[i][k], now,
                                             wake=None)
                        finally:
                            m.__dict__["_ingest_ns"] = None
                except Exception as exc:  # noqa: BLE001 — per-query fault
                    self._junction._handle_error_staged(staged, exc, now)
                finally:
                    if stats.enabled:
                        stats.query_latency(
                            m.name, staged.n,
                            share + time.perf_counter_ns() - td)
                        if m.__dict__.pop("_e2e_owed", False) and \
                                stamp is not None:
                            stats.e2e_latency(
                                m.name,
                                time.perf_counter_ns() - stamp)


def apply_merge(rt) -> None:
    """Run the merge pass over a freshly-constructed SiddhiAppRuntime:
    build a MergedGroupRuntime per group from `plan_facts.merge_plan`,
    swap junction subscriptions, and record the exact ineligibility
    reason on every unmerged query for EXPLAIN/lint."""
    from ..core import runtime as _rt
    rt.merged_groups = {}
    rt._merge_reasons = {}
    mesh_n = int(rt.mesh.devices.size) if rt.mesh is not None else 0
    if not merge_enabled(rt):
        why = "multi-query merge disabled (optimizer.merge.enabled=false)"
        for name, qr in rt.query_runtimes.items():
            qr._merge_excluded = why
            rt._merge_reasons[name] = why
        return
    try:
        plan = plan_facts.merge_plan(rt.app, mesh_devices=mesh_n)
    except Exception as exc:  # noqa: BLE001 — the pass must not break deploy
        log.warning("multi-query merge pass skipped: %r", exc)
        return
    reasons = dict(plan["reasons"])
    for g in plan["groups"]:
        junction = rt.junctions.get(g["stream"])
        members: List[Tuple[str, object]] = []
        for name in g["members"]:
            qr = rt.query_runtimes.get(name)
            p = getattr(qr, "planned", None)
            ok = (isinstance(qr, _rt.QueryRuntime) and p is not None
                  and getattr(p, "raw_step", None) is not None
                  and getattr(p, "stage_body", None) is not None
                  and not getattr(p, "needs_timer", False)
                  and not getattr(p, "keyed_window", False)
                  and getattr(p, "partition_key_fn", None) is None
                  and junction is not None and qr in junction.queries)
            if ok:
                members.append((name, qr))
            else:
                # static plan said mergeable but the actual plan is not:
                # demote loudly instead of merging a surprise
                reasons[name] = ("planner produced no mergeable step "
                                 "body for this query (demoted)")
        if len(members) < 2:
            for name, _qr in members:
                reasons[name] = (
                    f"no co-resident query shares stream "
                    f"{g['stream']!r} and its @async/@pipeline/@fuse/"
                    f"@serve decorations")
            continue
        kept = {n for n, _ in members}
        pos_of = {n: i for i, (n, _) in enumerate(members)}
        units: List[Tuple[str, List[int]]] = []
        for u in g["units"]:
            names = [n for n in u["members"] if n in kept]
            if not names:
                continue
            if u["mode"] == "shared" and len(names) >= 2:
                units.append(("shared", [pos_of[n] for n in names]))
            else:
                for n in names:
                    units.append(("solo", [pos_of[n]]))
        mg = MergedGroupRuntime(rt, g, members, units)
        rt.merged_groups[mg.group] = mg
        # swap subscriptions: the merged runtime takes the FIRST
        # member's junction slot (members subscribe in query order, so
        # relative order vs unmerged co-subscribers is preserved)
        qs = junction.queries
        pos = qs.index(members[0][1])
        for _name, qr in members:
            qs.remove(qr)
        qs.insert(pos, mg)
        log.info("multi-query merge: %s merges %d queries on %r "
                 "(%d shared unit(s))", mg.name, len(members),
                 g["stream"],
                 sum(1 for mode, _ in units if mode == "shared"))
    for name, why in reasons.items():
        qr = rt.query_runtimes.get(name)
        if qr is not None:
            qr._merge_excluded = why
    rt._merge_reasons = reasons
