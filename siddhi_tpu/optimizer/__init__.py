"""Whole-app multi-query optimizer (ROADMAP item 3).

Merges co-resident queries that hang off one stream junction into
shared device dispatches: one jitted step runs every member's selector
stack, one combined emission fetch serves the whole group, and members
with identical pre-window chains + window specs + group-by layouts
reference ONE window buffer and ONE group-slot space instead of per
query duplicates.  `core/plan_facts.merge_plan` is the single source of
truth for grouping (shared with lint MQO001 and EXPLAIN); this package
applies it to a live runtime.
"""
from .mqo import MergedGroupRuntime, apply_merge, merge_enabled

__all__ = ["MergedGroupRuntime", "apply_merge", "merge_enabled"]
