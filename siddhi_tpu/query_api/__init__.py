"""Object model / AST for SiddhiQL apps (fluent Python builder).

Reference module: modules/siddhi-query-api (9.7k LoC Java) — re-expressed as
Python dataclasses; see SURVEY.md L8b.
"""
from .app import SiddhiApp
from .definition import (
    AbstractDefinition,
    AggregationDefinition,
    Annotation,
    Attribute,
    FunctionDefinition,
    StreamDefinition,
    TableDefinition,
    TriggerDefinition,
    WindowDefinition,
)
from .expression import (
    Add,
    And,
    AttributeFunction,
    Compare,
    Constant,
    Divide,
    Expression,
    In,
    IsNull,
    Mod,
    Multiply,
    Not,
    Or,
    Subtract,
    Variable,
)
from .query import (
    AbsentStreamStateElement,
    CountStateElement,
    DeleteStream,
    EveryStateElement,
    Filter,
    InputStore,
    InputStream,
    InsertIntoStream,
    JoinInputStream,
    LogicalStateElement,
    NextStateElement,
    OnDemandQuery,
    OrderByAttribute,
    OutputAttribute,
    OutputRate,
    OutputStream,
    Partition,
    Query,
    RangePartitionProperty,
    RangePartitionType,
    ReturnStream,
    Selector,
    SingleInputStream,
    StateInputStream,
    StreamFunction,
    StreamStateElement,
    UpdateOrInsertStream,
    UpdateSet,
    UpdateStream,
    ValuePartitionType,
    Window,
)

__all__ = [n for n in dir() if not n.startswith("_")]
