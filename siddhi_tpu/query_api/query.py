"""Query object model: input streams, handlers, selectors, output, rate limiting.

Reference: modules/siddhi-query-api/.../execution/query/* (Query.java,
input/stream/{SingleInputStream,JoinInputStream,StateInputStream}.java,
input/handler/{Filter,Window,StreamFunction}.java, input/state/*.java,
selection/Selector.java, output/stream/*.java, output/ratelimit/*.java).
"""
from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Tuple, Union

from .definition import Annotation
from .expression import Expression, Variable


# ---------------------------------------------------------------------------
# Stream handlers
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Filter:
    expression: Expression


@dataclasses.dataclass
class Window:
    namespace: str
    name: str          # time, length, lengthBatch, timeBatch, session, sort, ...
    parameters: List[Expression]


@dataclasses.dataclass
class StreamFunction:
    namespace: str
    name: str
    parameters: List[Expression]


StreamHandler = Union[Filter, Window, StreamFunction]


# ---------------------------------------------------------------------------
# Input streams
# ---------------------------------------------------------------------------

class InputStream:
    @staticmethod
    def stream(stream_id: str, ref_id: Optional[str] = None) -> "SingleInputStream":
        return SingleInputStream(stream_id, ref_id)

    @staticmethod
    def join_stream(left, join_type, right, on=None, within=None, per=None,
                    trigger="ALL_EVENTS") -> "JoinInputStream":
        return JoinInputStream(left, join_type, right, on, within, per, trigger)

    @staticmethod
    def pattern_stream(state_element, within=None) -> "StateInputStream":
        return StateInputStream("PATTERN", state_element, within)

    @staticmethod
    def sequence_stream(state_element, within=None) -> "StateInputStream":
        return StateInputStream("SEQUENCE", state_element, within)


class SingleInputStream(InputStream):
    def __init__(self, stream_id: str, ref_id: Optional[str] = None,
                 is_inner: bool = False, is_fault: bool = False):
        self.stream_id = stream_id
        self.stream_reference_id = ref_id
        self.is_inner_stream = is_inner
        self.is_fault_stream = is_fault
        self.stream_handlers: List[StreamHandler] = []

    @property
    def unique_stream_id(self) -> str:
        base = self.stream_id
        if self.is_inner_stream:
            base = "#" + base
        if self.is_fault_stream:
            base = "!" + base
        return base

    def filter(self, expr: Expression) -> "SingleInputStream":
        self.stream_handlers.append(Filter(expr))
        return self

    def window(self, name: str, *params: Expression, namespace: str = "") -> "SingleInputStream":
        self.stream_handlers.append(Window(namespace, name, list(params)))
        return self

    def function(self, name: str, *params: Expression, namespace: str = "") -> "SingleInputStream":
        self.stream_handlers.append(StreamFunction(namespace, name, list(params)))
        return self

    @property
    def window_handler(self) -> Optional[Window]:
        for h in self.stream_handlers:
            if isinstance(h, Window):
                return h
        return None


class JoinInputStream(InputStream):
    JOIN = "JOIN"
    INNER_JOIN = "JOIN"
    LEFT_OUTER_JOIN = "LEFT_OUTER_JOIN"
    RIGHT_OUTER_JOIN = "RIGHT_OUTER_JOIN"
    FULL_OUTER_JOIN = "FULL_OUTER_JOIN"

    def __init__(self, left: SingleInputStream, join_type: str,
                 right: SingleInputStream, on: Optional[Expression],
                 within=None, per=None, trigger: str = "ALL_EVENTS"):
        self.left_input_stream = left
        self.type = join_type
        self.right_input_stream = right
        self.on_compare = on
        self.within = within      # for aggregation joins
        self.per = per            # for aggregation joins
        self.trigger = trigger    # LEFT / RIGHT / ALL_EVENTS


# ---------------------------------------------------------------------------
# Pattern / sequence state elements
# ---------------------------------------------------------------------------

class StateElement:
    pass


@dataclasses.dataclass
class StreamStateElement(StateElement):
    basic_single_input_stream: SingleInputStream
    within: Optional[int] = None  # ms


@dataclasses.dataclass
class AbsentStreamStateElement(StateElement):
    """not A for 1 sec — absence detection with waiting time."""
    basic_single_input_stream: SingleInputStream
    waiting_time: Optional[int] = None  # ms
    within: Optional[int] = None


@dataclasses.dataclass
class CountStateElement(StateElement):
    stream_state_element: StreamStateElement
    min_count: int
    max_count: int  # -1 == ANY/unbounded
    within: Optional[int] = None
    ANY = -1


@dataclasses.dataclass
class LogicalStateElement(StateElement):
    stream_state_element_1: StateElement
    type: str  # 'AND' | 'OR'
    stream_state_element_2: StateElement
    within: Optional[int] = None


@dataclasses.dataclass
class NextStateElement(StateElement):
    state_element: StateElement
    next_state_element: StateElement
    within: Optional[int] = None


@dataclasses.dataclass
class EveryStateElement(StateElement):
    state_element: StateElement
    within: Optional[int] = None


class StateInputStream(InputStream):
    def __init__(self, state_type: str, state_element: StateElement,
                 within: Optional[int] = None):
        self.state_type = state_type  # 'PATTERN' | 'SEQUENCE'
        self.state_element = state_element
        self.within_time = within

    @property
    def all_stream_ids(self) -> List[str]:
        out: List[str] = []

        def rec(el):
            if isinstance(el, (StreamStateElement, AbsentStreamStateElement)):
                sid = el.basic_single_input_stream.stream_id
                if sid not in out:
                    out.append(sid)
            elif isinstance(el, CountStateElement):
                rec(el.stream_state_element)
            elif isinstance(el, LogicalStateElement):
                rec(el.stream_state_element_1)
                rec(el.stream_state_element_2)
            elif isinstance(el, NextStateElement):
                rec(el.state_element)
                rec(el.next_state_element)
            elif isinstance(el, EveryStateElement):
                rec(el.state_element)

        rec(self.state_element)
        return out


# ---------------------------------------------------------------------------
# Selector
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class OutputAttribute:
    rename: Optional[str]
    expression: Expression

    @property
    def name(self) -> str:
        if self.rename:
            return self.rename
        if isinstance(self.expression, Variable):
            return self.expression.attribute_name
        raise ValueError("projection expression needs an explicit alias (as)")


@dataclasses.dataclass
class OrderByAttribute:
    variable: Variable
    order: str = "ASC"  # ASC | DESC


class Selector:
    def __init__(self):
        self.selection_list: List[OutputAttribute] = []
        self.group_by_list: List[Variable] = []
        self.having_expression: Optional[Expression] = None
        self.order_by_list: List[OrderByAttribute] = []
        self.limit: Optional[int] = None
        self.offset: Optional[int] = None

    @staticmethod
    def selector() -> "Selector":
        return Selector()

    def select(self, rename_or_expr, expr: Optional[Expression] = None) -> "Selector":
        if expr is None:
            self.selection_list.append(OutputAttribute(None, rename_or_expr))
        else:
            self.selection_list.append(OutputAttribute(rename_or_expr, expr))
        return self

    def group_by(self, var: Variable) -> "Selector":
        self.group_by_list.append(var)
        return self

    def having(self, expr: Expression) -> "Selector":
        self.having_expression = expr
        return self

    def order_by(self, var: Variable, order: str = "ASC") -> "Selector":
        self.order_by_list.append(OrderByAttribute(var, order))
        return self

    def limit_count(self, n: int) -> "Selector":
        self.limit = n
        return self

    def offset_count(self, n: int) -> "Selector":
        self.offset = n
        return self

    @property
    def is_select_all(self) -> bool:
        return not self.selection_list


# ---------------------------------------------------------------------------
# Output streams & rate limiting
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class OutputStream:
    target_id: str
    output_event_type: Optional[str] = None  # CURRENT_EVENTS / EXPIRED_EVENTS / ALL_EVENTS


class InsertIntoStream(OutputStream):
    def __init__(self, target_id: str, output_event_type=None,
                 is_inner: bool = False, is_fault: bool = False):
        super().__init__(target_id, output_event_type)
        self.is_inner_stream = is_inner
        self.is_fault_stream = is_fault


class ReturnStream(OutputStream):
    def __init__(self, output_event_type=None):
        super().__init__("", output_event_type)


@dataclasses.dataclass
class UpdateSetAttribute:
    table_variable: Variable
    value_expression: Expression


class UpdateSet:
    def __init__(self):
        self.set_attribute_list: List[UpdateSetAttribute] = []

    def set(self, table_var: Variable, value: Expression) -> "UpdateSet":
        self.set_attribute_list.append(UpdateSetAttribute(table_var, value))
        return self


class DeleteStream(OutputStream):
    def __init__(self, target_id: str, on: Expression, output_event_type=None):
        super().__init__(target_id, output_event_type)
        self.on_delete_expression = on


class UpdateStream(OutputStream):
    def __init__(self, target_id: str, on: Expression,
                 update_set: Optional[UpdateSet] = None, output_event_type=None):
        super().__init__(target_id, output_event_type)
        self.on_update_expression = on
        self.update_set = update_set


class UpdateOrInsertStream(OutputStream):
    def __init__(self, target_id: str, on: Expression,
                 update_set: Optional[UpdateSet] = None, output_event_type=None):
        super().__init__(target_id, output_event_type)
        self.on_update_expression = on
        self.update_set = update_set


class OutputRate:
    """output [all|first|last] every N events / every <time> | output snapshot every <time>."""

    def __init__(self, type: str, value, behavior: str = "ALL"):
        self.type = type        # 'EVENTS' | 'TIME' | 'SNAPSHOT'
        self.value = value      # event count or ms
        self.behavior = behavior  # ALL | FIRST | LAST

    @staticmethod
    def per_events(n: int, behavior: str = "ALL") -> "OutputRate":
        return OutputRate("EVENTS", n, behavior)

    @staticmethod
    def per_time(ms: int, behavior: str = "ALL") -> "OutputRate":
        return OutputRate("TIME", ms, behavior)

    @staticmethod
    def per_snapshot(ms: int) -> "OutputRate":
        return OutputRate("SNAPSHOT", ms)


# ---------------------------------------------------------------------------
# Query
# ---------------------------------------------------------------------------

class Query:
    def __init__(self):
        self.input_stream: Optional[InputStream] = None
        self.selector: Selector = Selector()
        self.output_stream: Optional[OutputStream] = None
        self.output_rate: Optional[OutputRate] = None
        self.annotations: List[Annotation] = []

    @staticmethod
    def query() -> "Query":
        return Query()

    def from_(self, input_stream: InputStream) -> "Query":
        self.input_stream = input_stream
        return self

    def select(self, selector: Selector) -> "Query":
        self.selector = selector
        return self

    def insert_into(self, stream_id: str, event_type=None) -> "Query":
        self.output_stream = InsertIntoStream(stream_id, event_type)
        return self

    def return_output(self, event_type=None) -> "Query":
        self.output_stream = ReturnStream(event_type)
        return self

    def output(self, rate: OutputRate) -> "Query":
        self.output_rate = rate
        return self

    def annotation(self, ann: Annotation) -> "Query":
        self.annotations.append(ann)
        return self

    def get_annotation(self, name: str) -> Optional[Annotation]:
        for a in self.annotations:
            if a.name.lower() == name.lower():
                return a
        return None


# ---------------------------------------------------------------------------
# Partition
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RangePartitionProperty:
    partition_key: str      # label
    condition: Expression


class PartitionType:
    pass


@dataclasses.dataclass
class ValuePartitionType(PartitionType):
    stream_id: str
    expression: Expression


@dataclasses.dataclass
class RangePartitionType(PartitionType):
    stream_id: str
    ranges: List[RangePartitionProperty]


class Partition:
    def __init__(self):
        self.partition_type_map: dict = {}  # stream_id -> PartitionType
        self.query_list: List[Query] = []
        self.annotations: List[Annotation] = []

    @staticmethod
    def partition() -> "Partition":
        return Partition()

    def with_(self, stream_id: str, expr_or_ranges) -> "Partition":
        if isinstance(expr_or_ranges, list):
            self.partition_type_map[stream_id] = RangePartitionType(stream_id, expr_or_ranges)
        else:
            self.partition_type_map[stream_id] = ValuePartitionType(stream_id, expr_or_ranges)
        return self

    def add_query(self, query: Query) -> "Partition":
        self.query_list.append(query)
        return self


ExecutionElement = Union[Query, Partition]


# ---------------------------------------------------------------------------
# On-demand (store) queries
# ---------------------------------------------------------------------------

class OnDemandQuery:
    """One-shot query against tables/windows/aggregations.
    Reference: QAPI/execution/query/StoreQuery.java / OnDemandQuery.java"""

    def __init__(self):
        self.input_store = None           # InputStore
        self.selector: Selector = Selector()
        self.output_stream: Optional[OutputStream] = None
        self.type: str = "FIND"           # FIND | INSERT | UPDATE | DELETE | UPDATE_OR_INSERT

    @staticmethod
    def query() -> "OnDemandQuery":
        return OnDemandQuery()

    def from_(self, input_store) -> "OnDemandQuery":
        self.input_store = input_store
        return self

    def select(self, selector: Selector) -> "OnDemandQuery":
        self.selector = selector
        return self


@dataclasses.dataclass
class InputStore:
    store_id: str
    on_condition: Optional[Expression] = None
    within: Optional[Tuple[Any, Any]] = None  # aggregation within
    per: Optional[Expression] = None          # aggregation per duration

    @staticmethod
    def store(store_id: str) -> "InputStore":
        return InputStore(store_id)

    def on(self, condition: Expression) -> "InputStore":
        self.on_condition = condition
        return self
