"""Stream/table/window/trigger/aggregation definitions.

Reference: modules/siddhi-query-api/.../definition/* (StreamDefinition.java,
TableDefinition.java, WindowDefinition.java, TriggerDefinition.java,
AggregationDefinition.java, FunctionDefinition.java, Attribute.java).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


class Attribute:
    class Type:
        STRING = "STRING"
        INT = "INT"
        LONG = "LONG"
        FLOAT = "FLOAT"
        DOUBLE = "DOUBLE"
        BOOL = "BOOL"
        OBJECT = "OBJECT"

    ALL_TYPES = ("STRING", "INT", "LONG", "FLOAT", "DOUBLE", "BOOL", "OBJECT")

    def __init__(self, name: str, type: str):
        type = type.upper()
        if type not in self.ALL_TYPES:
            raise ValueError(f"unknown attribute type {type!r}")
        self.name = name
        self.type = type

    def __repr__(self):
        return f"Attribute({self.name}:{self.type})"

    def __eq__(self, other):
        return (
            isinstance(other, Attribute)
            and self.name == other.name
            and self.type == other.type
        )


@dataclasses.dataclass
class Annotation:
    """@name(element='v', ...) annotations (reference: QAPI/annotation/Annotation.java)."""

    name: str
    elements: Dict[Optional[str], Any] = dataclasses.field(default_factory=dict)
    annotations: List["Annotation"] = dataclasses.field(default_factory=list)

    def element(self, key: Optional[str] = None, default: Any = None) -> Any:
        return self.elements.get(key, default)

    def positional_elements(self) -> List[Any]:
        """All positional (key-less) elements in source order.  The parser
        stores the first under None and later ones under synthetic '__pN'
        keys (dicts cannot repeat None); consumers must use this instead of
        filtering elements by key."""
        return [v for k, v in self.elements.items()
                if k is None or str(k).startswith("__p")]

    def named_elements(self) -> Dict[str, Any]:
        """Key=value elements only (no positionals, no synthetic keys)."""
        return {k: v for k, v in self.elements.items()
                if k is not None and not str(k).startswith("__p")}


class AbstractDefinition:
    def __init__(self, id: str):
        self.id = id
        self.attribute_list: List[Attribute] = []
        self.annotations: List[Annotation] = []

    def attribute(self, name: str, type: str) -> "AbstractDefinition":
        if any(a.name == name for a in self.attribute_list):
            raise ValueError(f"duplicate attribute {name!r} in {self.id!r}")
        self.attribute_list.append(Attribute(name, type))
        return self

    def annotation(self, ann: Annotation) -> "AbstractDefinition":
        self.annotations.append(ann)
        return self

    def get_annotation(self, name: str) -> Optional[Annotation]:
        for a in self.annotations:
            if a.name.lower() == name.lower():
                return a
        return None

    @property
    def attribute_names(self) -> List[str]:
        return [a.name for a in self.attribute_list]

    def attribute_type(self, name: str) -> str:
        for a in self.attribute_list:
            if a.name == name:
                return a.type
        raise KeyError(f"attribute {name!r} not found in {self.id!r}")

    def attribute_position(self, name: str) -> int:
        for i, a in enumerate(self.attribute_list):
            if a.name == name:
                return i
        raise KeyError(f"attribute {name!r} not found in {self.id!r}")

    def __repr__(self):
        return f"{type(self).__name__}({self.id}, {self.attribute_list})"


class StreamDefinition(AbstractDefinition):
    @staticmethod
    def id(stream_id: str) -> "StreamDefinition":
        return StreamDefinition(stream_id)


class TableDefinition(AbstractDefinition):
    @staticmethod
    def id(table_id: str) -> "TableDefinition":
        return TableDefinition(table_id)


class WindowDefinition(AbstractDefinition):
    """define window W(attrs) window.type(args) [output current/expired/all events]."""

    def __init__(self, id: str):
        super().__init__(id)
        self.window = None           # query_api.query.Window handler
        self.output_event_type = "ALL_EVENTS"

    @staticmethod
    def id(window_id: str) -> "WindowDefinition":
        return WindowDefinition(window_id)


class TriggerDefinition:
    """define trigger T at {'start' | every <time> | 'cron expr'}.
    Reference: QAPI/definition/TriggerDefinition.java"""

    def __init__(self, id: str):
        self.id = id
        self.at_every: Optional[int] = None  # period ms
        self.at: Optional[str] = None        # 'start' or cron expression
        self.annotations: List[Annotation] = []

    @staticmethod
    def id(trigger_id: str) -> "TriggerDefinition":
        return TriggerDefinition(trigger_id)


class FunctionDefinition:
    """define function f[lang] return type { body } (script functions)."""

    def __init__(self, id: str = ""):
        self.id = id
        self.language = ""
        self.body = ""
        self.return_type = "OBJECT"


class AggregationDefinition(AbstractDefinition):
    """define aggregation A from S select ... group by ... aggregate by ts every sec...year.
    Reference: QAPI/definition/AggregationDefinition.java"""

    DURATIONS = ("SECONDS", "MINUTES", "HOURS", "DAYS", "MONTHS", "YEARS")

    def __init__(self, id: str):
        super().__init__(id)
        self.basic_single_input_stream = None  # query.SingleInputStream
        self.selector = None                   # query.Selector
        self.aggregate_attribute = None        # Variable or None (-> event ts)
        self.time_periods: List[str] = []      # subset of DURATIONS, ordered

    @staticmethod
    def id(agg_id: str) -> "AggregationDefinition":
        return AggregationDefinition(agg_id)
