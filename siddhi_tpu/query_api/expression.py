"""Expression AST for the SiddhiQL surface.

Mirrors the capability surface of the reference object model
(reference: modules/siddhi-query-api/src/main/java/io/siddhi/query/api/expression/*),
re-designed as plain Python dataclasses that compile to JAX column ops
(see siddhi_tpu/core/executor.py) instead of interpreter object trees.
"""
from __future__ import annotations

import dataclasses
from typing import Any, List, Optional


class Expression:
    """Base class for all expressions. Also hosts the fluent constructors
    (reference: QAPI/expression/Expression.java)."""

    # ---- fluent constructors -------------------------------------------------
    @staticmethod
    def value(v: Any) -> "Constant":
        if isinstance(v, bool):
            return Constant(v, "BOOL")
        if isinstance(v, int):
            return Constant(v, "LONG" if abs(v) > 2**31 - 1 else "INT")
        if isinstance(v, float):
            return Constant(v, "DOUBLE")
        if isinstance(v, str):
            return Constant(v, "STRING")
        raise TypeError(f"unsupported constant type: {type(v)}")

    @staticmethod
    def variable(attribute_name: str) -> "Variable":
        return Variable(attribute_name)

    @staticmethod
    def add(a, b):
        return Add(a, b)

    @staticmethod
    def subtract(a, b):
        return Subtract(a, b)

    @staticmethod
    def multiply(a, b):
        return Multiply(a, b)

    @staticmethod
    def divide(a, b):
        return Divide(a, b)

    @staticmethod
    def mod(a, b):
        return Mod(a, b)

    @staticmethod
    def compare(a, op: str, b):
        return Compare(a, op, b)

    @staticmethod
    def and_(a, b):
        return And(a, b)

    @staticmethod
    def or_(a, b):
        return Or(a, b)

    @staticmethod
    def not_(a):
        return Not(a)

    @staticmethod
    def is_null(a):
        return IsNull(a)

    @staticmethod
    def in_(a, source_id: str):
        return In(a, source_id)

    @staticmethod
    def function(name: str, *args, namespace: str = ""):
        return AttributeFunction(namespace, name, list(args))

    class Time:
        """Duration helpers returning LONG milliseconds
        (reference: QAPI/expression/Expression.java Time inner class)."""

        @staticmethod
        def millisec(i: int) -> "Constant":
            return Constant(int(i), "LONG", is_time=True)

        @staticmethod
        def sec(i: int) -> "Constant":
            return Constant(int(i) * 1000, "LONG", is_time=True)

        @staticmethod
        def minute(i: int) -> "Constant":
            return Constant(int(i) * 60 * 1000, "LONG", is_time=True)

        @staticmethod
        def hour(i: int) -> "Constant":
            return Constant(int(i) * 60 * 60 * 1000, "LONG", is_time=True)

        @staticmethod
        def day(i: int) -> "Constant":
            return Constant(int(i) * 24 * 60 * 60 * 1000, "LONG", is_time=True)

        @staticmethod
        def week(i: int) -> "Constant":
            return Constant(int(i) * 7 * 24 * 60 * 60 * 1000, "LONG", is_time=True)

        @staticmethod
        def month(i: int) -> "Constant":
            return Constant(int(i) * 30 * 24 * 60 * 60 * 1000, "LONG", is_time=True)

        @staticmethod
        def year(i: int) -> "Constant":
            return Constant(int(i) * 365 * 24 * 60 * 60 * 1000, "LONG", is_time=True)


class Constant(Expression):
    # plain class (not a dataclass): the field name `value` would collide with
    # Expression.value's staticmethod under dataclass field discovery
    def __init__(self, value: Any, type: str, is_time: bool = False):
        self.value = value
        self.type = type  # STRING INT LONG FLOAT DOUBLE BOOL
        self.is_time = is_time

    def __repr__(self):
        return f"Constant({self.value!r}:{self.type})"

    def __eq__(self, other):
        return (isinstance(other, Constant) and self.value == other.value
                and self.type == other.type)


@dataclasses.dataclass
class Variable(Expression):
    attribute_name: str
    stream_id: Optional[str] = None     # explicit `stream.attr` reference
    stream_index: Optional[int] = None  # pattern event index  e[2].attr ; -1 == LAST
    function_id: Optional[str] = None

    def of_stream(self, stream_id: str, idx: Optional[int] = None) -> "Variable":
        self.stream_id = stream_id
        self.stream_index = idx
        return self


@dataclasses.dataclass
class _Binary(Expression):
    left: Expression
    right: Expression


class Add(_Binary):
    pass


class Subtract(_Binary):
    pass


class Multiply(_Binary):
    pass


class Divide(_Binary):
    pass


class Mod(_Binary):
    pass


@dataclasses.dataclass
class Compare(Expression):
    left: Expression
    operator: str  # '<' '<=' '>' '>=' '==' '!='
    right: Expression


class And(_Binary):
    pass


class Or(_Binary):
    pass


@dataclasses.dataclass
class Not(Expression):
    expression: Expression


@dataclasses.dataclass
class IsNull(Expression):
    expression: Optional[Expression] = None
    stream_id: Optional[str] = None
    stream_index: Optional[int] = None


@dataclasses.dataclass
class In(Expression):
    expression: Expression
    source_id: str  # table/window to probe


@dataclasses.dataclass
class AttributeFunction(Expression):
    namespace: str
    name: str
    parameters: List[Expression]


def walk(expr: Expression):
    """Yield every node of an expression tree."""
    yield expr
    if isinstance(expr, (_Binary, Compare)):
        yield from walk(expr.left)
        yield from walk(expr.right)
    elif isinstance(expr, Not):
        yield from walk(expr.expression)
    elif isinstance(expr, IsNull) and expr.expression is not None:
        yield from walk(expr.expression)
    elif isinstance(expr, In):
        yield from walk(expr.expression)
    elif isinstance(expr, AttributeFunction):
        for p in expr.parameters:
            yield from walk(p)
