"""SiddhiApp: the top-level AST / fluent builder.

Reference: modules/siddhi-query-api/.../SiddhiApp.java
"""
from __future__ import annotations

from typing import Dict, List, Optional, Union

from .definition import (
    AbstractDefinition,
    AggregationDefinition,
    Annotation,
    FunctionDefinition,
    StreamDefinition,
    TableDefinition,
    TriggerDefinition,
    WindowDefinition,
)
from .query import ExecutionElement, OnDemandQuery, Partition, Query


class SiddhiApp:
    def __init__(self, name: Optional[str] = None):
        self.name = name
        self.stream_definition_map: Dict[str, StreamDefinition] = {}
        self.table_definition_map: Dict[str, TableDefinition] = {}
        self.window_definition_map: Dict[str, WindowDefinition] = {}
        self.trigger_definition_map: Dict[str, TriggerDefinition] = {}
        self.aggregation_definition_map: Dict[str, AggregationDefinition] = {}
        self.function_definition_map: Dict[str, FunctionDefinition] = {}
        self.execution_element_list: List[ExecutionElement] = []
        self.annotations: List[Annotation] = []

    @staticmethod
    def siddhi_app(name: Optional[str] = None) -> "SiddhiApp":
        return SiddhiApp(name)

    def define_stream(self, d: StreamDefinition) -> "SiddhiApp":
        self.stream_definition_map[d.id] = d
        return self

    def define_table(self, d: TableDefinition) -> "SiddhiApp":
        self.table_definition_map[d.id] = d
        return self

    def define_window(self, d: WindowDefinition) -> "SiddhiApp":
        self.window_definition_map[d.id] = d
        return self

    def define_trigger(self, d: TriggerDefinition) -> "SiddhiApp":
        self.trigger_definition_map[d.id] = d
        # a trigger implicitly defines a stream <id> (triggered_time long)
        sd = StreamDefinition(d.id).attribute("triggered_time", "LONG")
        self.stream_definition_map[d.id] = sd
        return self

    def define_aggregation(self, d: AggregationDefinition) -> "SiddhiApp":
        self.aggregation_definition_map[d.id] = d
        return self

    def define_function(self, d: FunctionDefinition) -> "SiddhiApp":
        self.function_definition_map[d.id] = d
        return self

    def add_query(self, q: Query) -> "SiddhiApp":
        self.execution_element_list.append(q)
        return self

    def add_partition(self, p: Partition) -> "SiddhiApp":
        self.execution_element_list.append(p)
        return self

    def annotation(self, ann: Annotation) -> "SiddhiApp":
        self.annotations.append(ann)
        return self

    def get_annotation(self, name: str) -> Optional[Annotation]:
        for a in self.annotations:
            if a.name.lower() == name.lower():
                return a
        return None

    def definition(self, id: str) -> AbstractDefinition:
        for m in (
            self.stream_definition_map,
            self.table_definition_map,
            self.window_definition_map,
            self.aggregation_definition_map,
        ):
            if id in m:
                return m[id]
        raise KeyError(f"no definition for {id!r}")
