"""SiddhiApp: the top-level AST / fluent builder.

Reference: modules/siddhi-query-api/.../SiddhiApp.java
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .definition import (
    AbstractDefinition,
    AggregationDefinition,
    Annotation,
    FunctionDefinition,
    StreamDefinition,
    TableDefinition,
    TriggerDefinition,
    WindowDefinition,
)
from .query import ExecutionElement, Partition, Query


class SiddhiApp:
    def __init__(self, name: Optional[str] = None):
        self.name = name
        self.stream_definition_map: Dict[str, StreamDefinition] = {}
        self.table_definition_map: Dict[str, TableDefinition] = {}
        self.window_definition_map: Dict[str, WindowDefinition] = {}
        self.trigger_definition_map: Dict[str, TriggerDefinition] = {}
        self.aggregation_definition_map: Dict[str, AggregationDefinition] = {}
        self.function_definition_map: Dict[str, FunctionDefinition] = {}
        self.execution_element_list: List[ExecutionElement] = []
        self.annotations: List[Annotation] = []

    @staticmethod
    def siddhi_app(name: Optional[str] = None) -> "SiddhiApp":
        return SiddhiApp(name)

    def _check_duplicate(self, kind: str, d) -> None:
        """One id names ONE definition: redefinition with a different
        schema, a different kind (stream vs table vs window), or — for
        windows — a different window function is an error; an identical
        re-definition is a no-op (reference: DuplicateDefinitionException,
        AbstractDefinition.equalsIgnoreAnnotations)."""
        from ..exceptions import DuplicateDefinitionError
        for other_kind, dmap in (("stream", self.stream_definition_map),
                                 ("table", self.table_definition_map),
                                 ("window", self.window_definition_map)):
            existing = dmap.get(d.id)
            if existing is None:
                continue
            if other_kind != kind:
                raise DuplicateDefinitionError(
                    f"{d.id!r} is already defined as a {other_kind}")
            if existing.attribute_list != d.attribute_list:
                raise DuplicateDefinitionError(
                    f"{d.id!r} is already defined with a different schema")
            if kind == "window" and self._window_spec(existing) != \
                    self._window_spec(d):
                raise DuplicateDefinitionError(
                    f"window {d.id!r} is already defined with a different "
                    f"window function")

    @staticmethod
    def _window_spec(wd):
        w = wd.window
        return (None if w is None else (w.namespace, w.name,
                                        [repr(p) for p in w.parameters]),
                wd.output_event_type)

    def define_stream(self, d: StreamDefinition) -> "SiddhiApp":
        self._check_duplicate("stream", d)
        self.stream_definition_map[d.id] = d
        return self

    def define_table(self, d: TableDefinition) -> "SiddhiApp":
        self._check_duplicate("table", d)
        self.table_definition_map[d.id] = d
        return self

    def define_window(self, d: WindowDefinition) -> "SiddhiApp":
        self._check_duplicate("window", d)
        self.window_definition_map[d.id] = d
        return self

    def define_trigger(self, d: TriggerDefinition) -> "SiddhiApp":
        self.trigger_definition_map[d.id] = d
        # a trigger implicitly defines a stream <id> (triggered_time long)
        sd = StreamDefinition(d.id).attribute("triggered_time", "LONG")
        self.stream_definition_map[d.id] = sd
        return self

    def define_aggregation(self, d: AggregationDefinition) -> "SiddhiApp":
        self.aggregation_definition_map[d.id] = d
        return self

    def define_function(self, d: FunctionDefinition) -> "SiddhiApp":
        self.function_definition_map[d.id] = d
        return self

    def add_query(self, q: Query) -> "SiddhiApp":
        self.execution_element_list.append(q)
        return self

    def add_partition(self, p: Partition) -> "SiddhiApp":
        self.execution_element_list.append(p)
        return self

    def annotation(self, ann: Annotation) -> "SiddhiApp":
        self.annotations.append(ann)
        return self

    def get_annotation(self, name: str) -> Optional[Annotation]:
        for a in self.annotations:
            if a.name.lower() == name.lower():
                return a
        return None

    def definition(self, id: str) -> AbstractDefinition:
        for m in (
            self.stream_definition_map,
            self.table_definition_map,
            self.window_definition_map,
            self.aggregation_definition_map,
        ):
            if id in m:
                return m[id]
        raise KeyError(f"no definition for {id!r}")
