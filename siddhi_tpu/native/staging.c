/* Host-side staging kernels for the TPU streaming runtime.
 *
 * Reference role (what): the per-event hot path the JVM engine runs in
 * CORE/query/selector/GroupByKeyGenerator.java:63 (string-concat group keys),
 * CORE/util/snapshot/state/PartitionStateHolder.java:43 (keyed state maps)
 * and CORE/partition/PartitionStreamReceiver.java:100-216 (clone-per-key
 * chunk grouping).
 *
 * TPU design (how): the host must turn a raw event micro-batch into the
 * device's dense [K, E] key layout faster than the chip consumes it.  numpy
 * needed ~75ms per 524k-event batch (hash temporaries + argsort); this C
 * path is a fused single pass: FNV-style 128-bit key hashing, open-address
 * probe/insert into an INTERLEAVED cell table (h1,h2,slot in one 24-byte
 * cell, so a probe costs one cache line, not three), and counting-sort
 * grouping whose count pass is fused into the probe loop.  The column
 * gather itself happens ON DEVICE (a [K,E] gather is ~60us on TPU), so the
 * host never copies event payloads at all.
 *
 * Single-threaded by design: the driver host has one core; the win is
 * constant-factor (cache lines, fused passes), not parallelism.
 */
#include <stdint.h>
#include <string.h>
#include <stdlib.h>

#define FNV_OFF 0xCBF29CE484222325ULL
#define FNV_PRIME 0x100000001B3ULL
#define MIX 0x9E3779B97F4A7C15ULL
#define EMPTY 0ULL
#define TOMB 1ULL

/* cells: [cap2][3] u64 = {h1, h2, slot}; h1 0=empty 1=tombstone. */
#define C_H1(c, i) ((c)[(i) * 3])
#define C_H2(c, i) ((c)[(i) * 3 + 1])
#define C_SLOT(c, i) ((int32_t)(c)[(i) * 3 + 2])

/* Must match keyslots._hash_words exactly (snapshot compatibility: Python
 * rebuild/restore re-hashes with its own implementation). */
static inline uint64_t hash_words(const uint64_t *w, int64_t w8,
                                  uint64_t seed) {
    uint64_t h = FNV_OFF ^ seed;
    for (int64_t j = 0; j < w8; j++) {
        h = (h ^ w[j]) * FNV_PRIME;
        h = (h ^ (h >> 29)) * MIX;
    }
    h ^= h >> 32;
    return h;
}

/* meta: [0]=count [1]=free_top [2]=tombstones [3]=journal_len
 *       [4]=journal_overflow [5]=journal_cap
 * free_stack[free_top-1] is the next slot to pop.
 *
 * Optionally fuses the grouping count pass: when cnt/touched/group_meta are
 * non-NULL, per-slot occurrence counts accumulate during the probe loop
 * (group_meta: [0]=n_uniq out, [1]=max_count out).
 *
 * Returns number of newly inserted keys, or -1 on capacity exhaustion. */
int64_t sg_slots_for(const uint64_t *words, int64_t n, int64_t w8,
                     const uint8_t *live,
                     uint64_t *cells, int64_t cap2,
                     int64_t *cell_by_slot, uint8_t *arena,
                     int32_t *free_stack, int32_t *journal, uint8_t *used,
                     int64_t *meta, int32_t lookup_only,
                     int32_t *out_slots,
                     int32_t *cnt, int32_t *touched, int64_t *group_meta,
                     uint64_t *pcache, int64_t pc_mask) {
    const uint64_t mask = (uint64_t)(cap2 - 1);
    const int64_t wb = w8 * 8;
    int64_t inserted = 0;
    int64_t n_uniq = 0;
    int32_t maxc = 0;
    /* The cell table is far larger than L2, so nearly every probe is a
     * cache miss; hash the lookahead key and prefetch its home cell a few
     * iterations early to overlap the misses. */
    enum { LOOKAHEAD = 12 };
    for (int64_t i = 0; i < n; i++) {
        if (i + LOOKAHEAD < n && (!live || live[i + LOOKAHEAD])) {
            uint64_t ph = hash_words(words + (i + LOOKAHEAD) * w8, w8, 0);
            __builtin_prefetch(&cells[(ph & mask) * 3], 0, 1);
        }
        if (live && !live[i]) { out_slots[i] = -1; continue; }
        const uint64_t *key = words + i * w8;
        uint64_t h1 = hash_words(key, w8, 0);
        if (h1 < 2) h1 = 2;
        uint64_t h2 = hash_words(key, w8, 0xABCD);
        int32_t slot = -1;
        /* L2-resident direct-mapped cache in front of the big table:
         * events of one key cluster within a batch, so most probes hit
         * here instead of missing into the (HBM-sized) cell table.
         * Invalidated wholesale by Python on purge/rebuild/restore. */
        uint64_t pidx = (h1 & (uint64_t)pc_mask) * 3;
        if (pcache[pidx] == h1 && pcache[pidx + 1] == h2) {
            slot = (int32_t)pcache[pidx + 2];
        } else {
            /* bounded: cap2 steps visit every cell, so exceeding the bound
             * (possible when purge-churn tombstones consume the last EMPTY
             * cells) proves absence instead of spinning forever. */
            uint64_t idx = h1 & mask;
            for (int64_t probes = 0; probes < cap2; probes++) {
                uint64_t c = C_H1(cells, idx);
                if (c == h1 && C_H2(cells, idx) == h2) {
                    slot = C_SLOT(cells, idx); break;
                }
                if (c == EMPTY) break;
                idx = (idx + 1) & mask;
            }
            if (slot >= 0) {
                pcache[pidx] = h1; pcache[pidx + 1] = h2;
                pcache[pidx + 2] = (uint64_t)(uint32_t)slot;
            }
        }
        if (slot < 0 && !lookup_only) {
            if (meta[1] <= 0) return -1;          /* capacity exhausted */
            slot = free_stack[--meta[1]];
            /* insert at first EMPTY or TOMB cell */
            uint64_t j = h1 & mask;
            while (C_H1(cells, j) > TOMB) j = (j + 1) & mask;
            C_H1(cells, j) = h1; C_H2(cells, j) = h2;
            cells[j * 3 + 2] = (uint64_t)(uint32_t)slot;
            cell_by_slot[slot] = (int64_t)j;
            memcpy(arena + (int64_t)slot * wb, key, (size_t)wb);
            used[slot] = 1;
            meta[0]++;
            if (meta[3] < meta[5]) journal[meta[3]++] = slot;
            else meta[4] = 1;                     /* journal overflow */
            inserted++;
            pcache[pidx] = h1; pcache[pidx + 1] = h2;
            pcache[pidx + 2] = (uint64_t)(uint32_t)slot;
        }
        out_slots[i] = slot;
        if (cnt && slot >= 0) {                   /* fused group count */
            int32_t c2 = ++cnt[slot];
            if (c2 == 1) touched[n_uniq++] = slot;
            if (c2 > maxc) maxc = c2;
        }
    }
    if (group_meta) { group_meta[0] = n_uniq; group_meta[1] = maxc; }
    return inserted;
}

/* Rebuild the probe table from the arena (tombstone GC / restore). */
void sg_rebuild(uint64_t *cells, int64_t cap2,
                int64_t *cell_by_slot, const uint8_t *arena, int64_t w8,
                const uint8_t *used, int64_t capacity) {
    const uint64_t mask = (uint64_t)(cap2 - 1);
    memset(cells, 0, (size_t)cap2 * 24);
    for (int64_t s = 0; s < capacity; s++) {
        cell_by_slot[s] = -1;
        if (!used[s]) continue;
        const uint64_t *key = (const uint64_t *)(arena + s * w8 * 8);
        uint64_t h1 = hash_words(key, w8, 0);
        if (h1 < 2) h1 = 2;
        uint64_t h2 = hash_words(key, w8, 0xABCD);
        uint64_t j = h1 & mask;
        while (C_H1(cells, j) > TOMB) j = (j + 1) & mask;
        C_H1(cells, j) = h1; C_H2(cells, j) = h2;
        cells[j * 3 + 2] = (uint64_t)(uint32_t)s;
        cell_by_slot[s] = (int64_t)j;
    }
}

/* Standalone count pass (used when slots come from elsewhere, e.g. the
 * sharded path regrouping by local slot). */
int64_t sg_group_count(const int32_t *slots, const uint8_t *valid, int64_t n,
                       int32_t *cnt, int32_t *touched,
                       int64_t *max_count_out) {
    int64_t u = 0;
    int32_t maxc = 0;
    for (int64_t i = 0; i < n; i++) {
        int32_t s = slots[i];
        if (s < 0 || (valid && !valid[i])) continue;
        int32_t c = ++cnt[s];
        if (c == 1) touched[u++] = s;
        if (c > maxc) maxc = c;
    }
    *max_count_out = maxc;
    return u;
}

static void radix_sort_u32(uint32_t *a, int64_t n, uint32_t *tmp) {
    int64_t hist[2048];
    for (int shift = 0; shift < 32; shift += 11) {
        memset(hist, 0, sizeof(hist));
        const uint32_t m = (shift + 11 >= 32) ? (0xFFFFFFFFu >> shift)
                                              : 0x7FFu;
        for (int64_t i = 0; i < n; i++)
            hist[(a[i] >> shift) & m]++;
        int64_t sum = 0;
        for (int64_t b = 0; b < 2048; b++) {
            int64_t c = hist[b]; hist[b] = sum; sum += c;
        }
        for (int64_t i = 0; i < n; i++)
            tmp[hist[(a[i] >> shift) & m]++] = a[i];
        memcpy(a, tmp, (size_t)n * 4);
    }
}

/* Fill pass: sort unique slots ascending, emit key_idx [Kb] (pad beyond
 * n_uniq), sel [Kb*E] (-1 = padding), re-zero cnt.  rank is a scratch
 * array >= capacity.  Returns 1 if slots are one contiguous ascending run
 * starting at key_idx[0] (dense fast path), else 0. */
int32_t sg_group_fill(const int32_t *slots, const uint8_t *valid, int64_t n,
                      int32_t *cnt, int32_t *rank, int32_t *touched,
                      int64_t n_uniq, int64_t Kb, int64_t E, int32_t pad,
                      int32_t *key_idx, int32_t *sel) {
    uint32_t *tmp = (uint32_t *)malloc((size_t)n_uniq * 4);
    radix_sort_u32((uint32_t *)touched, n_uniq, tmp);
    free(tmp);
    for (int64_t k = 0; k < Kb; k++)
        key_idx[k] = (k < n_uniq) ? touched[k] : pad;
    memset(sel, 0xFF, (size_t)(Kb * E) * 4);
    for (int64_t k = 0; k < n_uniq; k++) {
        rank[touched[k]] = (int32_t)k;
        cnt[touched[k]] = 0;                      /* reuse as within-counter */
    }
    for (int64_t i = 0; i < n; i++) {
        int32_t s = slots[i];
        if (s < 0 || (valid && !valid[i])) continue;
        int64_t r = rank[s];
        sel[r * E + cnt[s]++] = (int32_t)i;
    }
    for (int64_t k = 0; k < n_uniq; k++)
        cnt[touched[k]] = 0;                      /* leave cnt clean */
    return (n_uniq > 0 &&
            touched[n_uniq - 1] == touched[0] + (int32_t)(n_uniq - 1)) ? 1 : 0;
}
