"""ctypes loader for the native host-staging library.

Compiles `staging.c` with the system gcc on first import (cached as
`_staging_<mtime>.so` next to the source); falls back to None so callers
keep the pure-numpy path when no toolchain is available.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import time

_dir = os.path.dirname(__file__)
_src = os.path.join(_dir, "staging.c")


def _build():
    if not os.path.exists(_src):
        return None
    tag = int(os.stat(_src).st_mtime)
    so = os.path.join(_dir, f"_staging_{tag}.so")
    if not os.path.exists(so):
        now = time.time()
        for old in os.listdir(_dir):
            if not old.startswith("_staging_"):
                continue
            p = os.path.join(_dir, old)
            try:
                # stale .so from an older source; orphaned .tmp only when
                # old enough that no concurrent gcc can still be writing it
                if old.endswith(".so") or now - os.stat(p).st_mtime > 600:
                    os.unlink(p)
            except OSError:
                pass
        # per-process temp name: concurrent importers must not interleave
        # writes to one file and publish a corrupt .so via os.replace
        tmp = f"{so}.tmp{os.getpid()}"
        cmd = ["gcc", "-O3", "-shared", "-fPIC", "-o", tmp, _src]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
        except (OSError, subprocess.SubprocessError) as exc:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            # a concurrent importer may have published the .so meanwhile
            if not os.path.exists(so):
                logging.getLogger("siddhi_tpu").warning(
                    "native staging build failed (%s); using numpy fallback",
                    exc)
                return None
    try:
        return ctypes.CDLL(so)
    except OSError as exc:
        logging.getLogger("siddhi_tpu").warning(
            "native staging load failed (%s); using numpy fallback", exc)
        return None


def _bind(lib):
    c = ctypes
    p = c.POINTER
    u64p, i64p = p(c.c_uint64), p(c.c_int64)
    i32p, u8p = p(c.c_int32), p(c.c_uint8)
    lib.sg_slots_for.restype = c.c_int64
    lib.sg_slots_for.argtypes = [
        u64p, c.c_int64, c.c_int64, u8p,
        u64p, c.c_int64,
        i64p, u8p, i32p, i32p, u8p, i64p, c.c_int32, i32p,
        i32p, i32p, i64p, u64p, c.c_int64]
    lib.sg_rebuild.restype = None
    lib.sg_rebuild.argtypes = [
        u64p, c.c_int64, i64p, u8p, c.c_int64, u8p, c.c_int64]
    lib.sg_group_count.restype = c.c_int64
    lib.sg_group_count.argtypes = [i32p, u8p, c.c_int64, i32p, i32p, i64p]
    lib.sg_group_fill.restype = c.c_int32
    lib.sg_group_fill.argtypes = [
        i32p, u8p, c.c_int64, i32p, i32p, i32p,
        c.c_int64, c.c_int64, c.c_int64, c.c_int32, i32p, i32p]
    return lib


LIB = _build()
if LIB is not None:
    LIB = _bind(LIB)


def ptr(a, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))
