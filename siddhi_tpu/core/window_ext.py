"""Extended window processors: externalTime, externalTimeBatch, timeLength,
delay, batch, sort, cron, session, frequent, lossyFrequent.

Reference behavior (what): CORE/query/processor/stream/window/
{ExternalTime,ExternalTimeBatch,TimeLength,Delay,Batch,Sort,Cron,Session,
Frequent,LossyFrequent}WindowProcessor.java.

TPU-native design (how): same columnar fixed-capacity buffer model as
window.py — whole micro-batches in, vectorized merge/sort/compact, output
rows carrying explicit sequence numbers.  The frequency-counting windows
(Misra-Gries / lossy counting) are inherently per-event sequential, so they
run as a `lax.scan` over the batch with a tiny counter state — still compiled,
still on device, just not width-parallel (they are tail features, not the
hot path).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..query_api.expression import Constant, Variable
from . import event as ev
from .window import (
    BIG_SEQ,
    NO_WAKEUP,
    Buffer,
    Rows,
    WindowOutput,
    WindowProcessor,
    concat_rows,
    empty_buffer,
    sort_rows,
    _param_int,
)


def _param_var_position(params, i, schema, what="window"):
    if i >= len(params) or not isinstance(params[i], Variable):
        raise ValueError(f"{what} parameter {i} must be an attribute name")
    return schema.position(params[i].attribute_name)


def _scatter_buffer(schema, capacity, cand_valid, cand_rank, cand_ts,
                    cand_add, cand_expts, cand_gslot, cand_cols) -> Buffer:
    """Compact candidates into a fresh buffer by rank."""
    tgt = jnp.where(cand_valid, cand_rank, capacity).astype(jnp.int32)
    fresh = empty_buffer(schema, capacity)
    return Buffer(
        ts=fresh.ts.at[tgt].set(cand_ts, mode="drop"),
        add_seq=fresh.add_seq.at[tgt].set(cand_add, mode="drop"),
        expire_seq=fresh.expire_seq,
        expire_ts=fresh.expire_ts.at[tgt].set(cand_expts, mode="drop"),
        alive=jnp.zeros((capacity,), jnp.bool_).at[tgt].set(
            cand_valid, mode="drop"),
        gslot=fresh.gslot.at[tgt].set(cand_gslot, mode="drop"),
        cols=tuple(f.at[tgt].set(c, mode="drop")
                   for f, c in zip(fresh.cols, cand_cols)),
    )


class ExternalTimeWindow(WindowProcessor):
    """Sliding window over an event-time attribute (reference:
    ExternalTimeWindowProcessor.java): entry expires when a later event's
    timestamp attribute passes entry_ts + t.  No wall-clock timers — expiry
    is driven purely by arrivals, so out-of-band time does not advance it."""

    name = "externalTime"

    def __init__(self, schema, params, batch_capacity, capacity_hint=2048):
        super().__init__(schema, params, batch_capacity)
        self.ts_pos = _param_var_position(params, 0, schema, "externalTime")
        self.time_ms = _param_int(params, 1)
        self.capacity = max(capacity_hint, 2 * batch_capacity)

    @property
    def out_capacity(self):
        return 2 * (self.capacity + self.batch_capacity)

    def init_state(self):
        return (empty_buffer(self.schema, self.capacity),
                jnp.asarray(0, jnp.int64))

    def process(self, state, rows: Rows, now):
        buf, seq0 = state
        C, B, t = self.capacity, rows.capacity, self.time_ms
        is_cur = jnp.logical_and(rows.valid, rows.kind == ev.CURRENT)
        ets = rows.cols[self.ts_pos].astype(jnp.int64)
        ext_now = jnp.max(jnp.where(is_cur, ets, -BIG_SEQ))

        # candidates: old entries then arrivals; event-time stored in expire_ts
        cand_ts = jnp.concatenate([buf.ts, rows.ts])
        cand_ets = jnp.concatenate([buf.expire_ts - t, ets])  # entry event-ts
        cand_alive = jnp.concatenate([buf.alive, is_cur])
        cand_add = jnp.concatenate(
            [buf.add_seq, jnp.full((B,), 0, jnp.int64)])
        cand_gslot = jnp.concatenate([buf.gslot, rows.gslot])
        cand_cols = tuple(jnp.concatenate([bc, rc])
                          for bc, rc in zip(buf.cols, rows.cols))
        cand_expts = cand_ets + t
        due = jnp.logical_and(cand_alive, cand_expts <= ext_now)

        # emission merge: EXPIRED at key 2*expire_ts, CURRENT at 2*ts+1
        cur_key = jnp.where(is_cur, ets * 2 + 1, BIG_SEQ)
        exp_key = jnp.where(due, cand_expts * 2, BIG_SEQ)
        em_key = jnp.concatenate([exp_key, cur_key])
        order = jnp.argsort(em_key, stable=True)
        rank = jnp.zeros((C + 2 * B,), jnp.int64).at[order].set(
            jnp.arange(C + 2 * B, dtype=jnp.int64))
        exp_rows = Rows(
            ts=cand_expts, kind=jnp.full((C + B,), ev.EXPIRED, jnp.int32),
            valid=due, seq=seq0 + rank[:C + B], gslot=cand_gslot,
            cols=cand_cols)
        cur_rows = Rows(
            ts=rows.ts, kind=jnp.full((B,), ev.CURRENT, jnp.int32),
            valid=is_cur, seq=seq0 + rank[C + B:], gslot=rows.gslot,
            cols=rows.cols)
        out = sort_rows(concat_rows(exp_rows, cur_rows))

        # fix arrival add_seq now that ranks exist
        cand_add = jnp.concatenate([buf.add_seq, seq0 + rank[C + B:]])

        # survivors, oldest-first by event time then add order
        keep = jnp.logical_and(cand_alive, jnp.logical_not(due))
        keep_key = jnp.where(keep, cand_ets * (C + 2 * B) + 0, BIG_SEQ)
        # tie-break by original position to keep stability
        keep_key = keep_key + jnp.arange(C + B, dtype=jnp.int64) % (C + 2 * B)
        korder = jnp.argsort(keep_key)
        total = jnp.sum(keep.astype(jnp.int64))
        drop = jnp.maximum(total - C, 0)
        sel = jnp.clip(jnp.arange(C, dtype=jnp.int64) + drop,
                       0, C + B - 1)
        pos = korder[sel.astype(jnp.int32)]
        svalid = (jnp.arange(C, dtype=jnp.int64) + drop) < total
        nbuf = Buffer(
            ts=cand_ts[pos],
            add_seq=jnp.where(svalid, cand_add[pos], BIG_SEQ),
            expire_seq=jnp.full((C,), BIG_SEQ, jnp.int64),
            expire_ts=jnp.where(svalid, cand_expts[pos], BIG_SEQ),
            alive=svalid, gslot=cand_gslot[pos],
            cols=tuple(c[pos] for c in cand_cols),
        )
        nem = jnp.sum(due.astype(jnp.int64)) + jnp.sum(is_cur.astype(jnp.int64))
        return ((nbuf, seq0 + nem),
                WindowOutput(out, nbuf, jnp.asarray(NO_WAKEUP, jnp.int64)))


class ExternalTimeBatchWindow(WindowProcessor):
    emits_reset = True
    """Tumbling window over an event-time attribute (reference:
    ExternalTimeBatchWindowProcessor.java): slices [start+k*t, start+(k+1)*t)
    of the timestamp attribute; a slice flushes when an arrival's event time
    crosses its end.  Like TimeBatchWindow, slices that would flush empty in
    the same micro-batch collapse into the batch's single flush."""

    name = "externalTimeBatch"

    def __init__(self, schema, params, batch_capacity, capacity_hint=2048):
        super().__init__(schema, params, batch_capacity)
        self.ts_pos = _param_var_position(params, 0, schema,
                                          "externalTimeBatch")
        self.time_ms = _param_int(params, 1)
        self.start = _param_int(params, 2, default=-1) if len(params) > 2 \
            else -1
        self.capacity = max(capacity_hint, 2 * batch_capacity)

    @property
    def out_capacity(self):
        return 2 * self.capacity + 2 * self.batch_capacity + 2

    def init_state(self):
        return (
            empty_buffer(self.schema, self.capacity),   # pending slice
            empty_buffer(self.schema, self.capacity),   # previous slice
            jnp.asarray(self.start, jnp.int64),         # slice start (-1 unset)
            jnp.asarray(0, jnp.int64),                  # seq counter
        )

    def process(self, state, rows: Rows, now):
        pend, prev, start0, seq0 = state
        t = self.time_ms
        C, B = self.capacity, rows.capacity
        is_cur = jnp.logical_and(rows.valid, rows.kind == ev.CURRENT)
        any_cur = jnp.any(is_cur)
        ets = rows.cols[self.ts_pos].astype(jnp.int64)
        first_ts = jnp.min(jnp.where(is_cur, ets, BIG_SEQ))
        last_ts = jnp.max(jnp.where(is_cur, ets, -BIG_SEQ))
        start = jnp.where(start0 >= 0, start0, first_ts)

        nflush = jnp.where(any_cur, jnp.maximum(last_ts - start, 0) // t, 0)
        flush = nflush > 0
        boundary = start + jnp.where(flush, nflush, 1) * t
        new_start = jnp.where(flush, start + nflush * t, start)

        to_pend = jnp.logical_and(is_cur, ets < boundary)
        to_next = jnp.logical_and(is_cur, jnp.logical_not(to_pend))

        pend_rank = jnp.cumsum(pend.alive.astype(jnp.int64)) - 1
        fill0 = jnp.sum(pend.alive.astype(jnp.int64))
        arr_rank = fill0 + jnp.cumsum(to_pend.astype(jnp.int64)) - 1

        exp_rows = Rows(
            ts=prev.ts, kind=jnp.full((C,), ev.EXPIRED, jnp.int32),
            valid=jnp.logical_and(prev.alive, flush),
            seq=seq0 + jnp.cumsum(prev.alive.astype(jnp.int64)) - 1,
            gslot=prev.gslot, cols=prev.cols)
        reset_rows = Rows(
            ts=jnp.full((1,), 0, jnp.int64) + now,
            kind=jnp.full((1,), ev.RESET, jnp.int32),
            valid=jnp.reshape(flush, (1,)),
            seq=jnp.full((1,), seq0 + C, jnp.int64),
            gslot=jnp.full((1,), -1, jnp.int32),
            cols=tuple(jnp.full((1,), ev.default_value(t_), d)
                       for t_, d in zip(self.schema.types,
                                        self.schema.dtypes)))
        cur_rows = Rows(
            ts=jnp.concatenate([pend.ts, rows.ts]),
            kind=jnp.full((C + B,), ev.CURRENT, jnp.int32),
            valid=jnp.concatenate([
                jnp.logical_and(pend.alive, flush),
                jnp.logical_and(to_pend, flush)]),
            seq=seq0 + C + 1 + jnp.concatenate([pend_rank, arr_rank]),
            gslot=jnp.concatenate([pend.gslot, rows.gslot]),
            cols=tuple(jnp.concatenate([pc, rc])
                       for pc, rc in zip(pend.cols, rows.cols)))
        out = sort_rows(concat_rows(concat_rows(exp_rows, cur_rows),
                                    reset_rows))

        keep_pend = jnp.logical_and(pend.alive, jnp.logical_not(flush))
        arr_keep = jnp.where(flush, to_next, to_pend)
        base_fill = jnp.sum(keep_pend.astype(jnp.int64))
        cand_valid = jnp.concatenate([keep_pend, arr_keep])
        cand_rank = jnp.concatenate([
            pend_rank, base_fill + jnp.cumsum(arr_keep.astype(jnp.int64)) - 1])
        cand_ts = jnp.concatenate([pend.ts, rows.ts])
        cand_gslot = jnp.concatenate([pend.gslot, rows.gslot])
        cand_cols = tuple(jnp.concatenate([pc, rc])
                          for pc, rc in zip(pend.cols, rows.cols))
        big = jnp.full(cand_ts.shape, BIG_SEQ, jnp.int64)
        npend = _scatter_buffer(self.schema, C, cand_valid, cand_rank,
                                cand_ts, big, big, cand_gslot, cand_cols)

        fvalid = jnp.concatenate([pend.alive, to_pend])
        frank = jnp.concatenate([pend_rank, arr_rank])
        fprev = _scatter_buffer(self.schema, C, fvalid, frank, cand_ts, big,
                                big, cand_gslot, cand_cols)
        nprev = jax.tree.map(lambda a, b: jnp.where(flush, a, b), fprev, prev)

        nseq = jnp.where(flush, seq0 + 2 * C + B + 2, seq0)
        nstart = jnp.where(jnp.logical_or(start0 >= 0, any_cur), new_start,
                           jnp.asarray(-1, jnp.int64))
        return ((npend, nprev, nstart, nseq),
                WindowOutput(out, None, jnp.asarray(NO_WAKEUP, jnp.int64)))


class TimeLengthWindow(WindowProcessor):
    """Sliding window bounded by both time and count (reference:
    TimeLengthWindowProcessor.java): an entry leaves after t ms, or earlier
    if more than n newer entries arrive.  Time expiry and length eviction
    both emit EXPIRED rows; time expiries are stamped with their expiry time,
    length evictions with the evicting arrival's time."""

    name = "timeLength"
    needs_timer = True

    def __init__(self, schema, params, batch_capacity, capacity_hint=2048):
        super().__init__(schema, params, batch_capacity)
        self.time_ms = _param_int(params, 0)
        self.length = _param_int(params, 1)
        self.capacity = self.length

    @property
    def out_capacity(self):
        return 2 * (self.capacity + self.batch_capacity)

    def init_state(self):
        return (empty_buffer(self.schema, self.capacity),
                jnp.asarray(0, jnp.int64))

    def process(self, state, rows: Rows, now):
        buf, seq0 = state
        C, B, t, n = self.capacity, rows.capacity, self.time_ms, self.length
        is_cur = jnp.logical_and(rows.valid, rows.kind == ev.CURRENT)
        ncur = jnp.sum(is_cur.astype(jnp.int64))
        k = jnp.cumsum(is_cur.astype(jnp.int64)) - 1

        # ---- phase 1: time expiry of old entries ---------------------------
        time_due = jnp.logical_and(buf.alive, buf.expire_ts <= now)
        # ---- phase 2: length eviction among survivors + arrivals -----------
        keep_old = jnp.logical_and(buf.alive, jnp.logical_not(time_due))
        count0 = jnp.sum(keep_old.astype(jnp.int64))
        old_key = jnp.where(keep_old, buf.add_seq, BIG_SEQ)
        old_order = jnp.argsort(old_key)           # alive survivors by age
        # the k-th arrival evicts virtual survivor (count0 + k - n)
        evict_pos = count0 + k - n
        has_evict = jnp.logical_and(is_cur, evict_pos >= 0)

        comb_ts = jnp.concatenate([buf.ts[old_order], rows.ts])
        comb_expts = jnp.concatenate([buf.expire_ts[old_order], rows.ts + t])
        comb_gslot = jnp.concatenate([buf.gslot[old_order], rows.gslot])
        comb_cols = tuple(jnp.concatenate([bc[old_order], rc])
                          for bc, rc in zip(buf.cols, rows.cols))

        def phys(v):
            return jnp.clip(jnp.where(v < count0, v, C + v - count0),
                            0, C + B - 1).astype(jnp.int32)

        # emission merge: time-expiries by expire_ts, then per-arrival
        # (evicted, current) pairs.  Use key = 4*time + priority.
        te_key = jnp.where(time_due, buf.expire_ts * 4, BIG_SEQ)
        ev_key = jnp.where(has_evict, rows.ts * 4 + 1, BIG_SEQ)
        cu_key = jnp.where(is_cur, rows.ts * 4 + 2, BIG_SEQ)
        # within equal arrival ts, order by k via small epsilon on rank sort
        em_key = jnp.concatenate([te_key, ev_key, cu_key])
        order = jnp.argsort(em_key, stable=True)
        rank = jnp.zeros((C + 2 * B,), jnp.int64).at[order].set(
            jnp.arange(C + 2 * B, dtype=jnp.int64))

        te_rows = Rows(
            ts=buf.expire_ts, kind=jnp.full((C,), ev.EXPIRED, jnp.int32),
            valid=time_due, seq=seq0 + rank[:C], gslot=buf.gslot,
            cols=buf.cols)
        evict_phys = phys(evict_pos)
        ev_rows = Rows(
            ts=rows.ts, kind=jnp.full((B,), ev.EXPIRED, jnp.int32),
            valid=has_evict, seq=seq0 + rank[C:C + B],
            gslot=comb_gslot[evict_phys],
            cols=tuple(c[evict_phys] for c in comb_cols))
        cu_rows = Rows(
            ts=rows.ts, kind=jnp.full((B,), ev.CURRENT, jnp.int32),
            valid=is_cur, seq=seq0 + rank[C + B:], gslot=rows.gslot,
            cols=rows.cols)
        out = sort_rows(concat_rows(concat_rows(te_rows, ev_rows), cu_rows))

        # ---- new buffer: last n of (survivors + arrivals) ------------------
        total = count0 + ncur
        start = jnp.maximum(total - n, 0)
        take = jnp.arange(C, dtype=jnp.int64) + start
        tvalid = take < total
        tpos = phys(take)
        comb_add = jnp.concatenate([buf.add_seq[old_order],
                                    seq0 + rank[C + B:]])
        nbuf = Buffer(
            ts=comb_ts[tpos], add_seq=jnp.where(tvalid, comb_add[tpos],
                                                BIG_SEQ),
            expire_seq=jnp.full((C,), BIG_SEQ, jnp.int64),
            expire_ts=jnp.where(tvalid, comb_expts[tpos], BIG_SEQ),
            alive=tvalid, gslot=comb_gslot[tpos],
            cols=tuple(c[tpos] for c in comb_cols))
        nem = (jnp.sum(time_due.astype(jnp.int64)) +
               jnp.sum(has_evict.astype(jnp.int64)) + ncur)
        wake = jnp.min(jnp.where(nbuf.alive, nbuf.expire_ts, NO_WAKEUP))
        return ((nbuf, seq0 + nem), WindowOutput(out, nbuf, wake))


class DelayWindow(WindowProcessor):
    """Delay window (reference: DelayWindowProcessor.java): events are held
    for t ms and released downstream as CURRENT when the delay elapses."""

    name = "delay"
    needs_timer = True

    def __init__(self, schema, params, batch_capacity, capacity_hint=2048):
        super().__init__(schema, params, batch_capacity)
        self.time_ms = _param_int(params, 0)
        self.capacity = max(capacity_hint, 2 * batch_capacity)

    @property
    def out_capacity(self):
        return self.capacity + self.batch_capacity

    def init_state(self):
        return (empty_buffer(self.schema, self.capacity),
                jnp.asarray(0, jnp.int64))

    def process(self, state, rows: Rows, now):
        buf, seq0 = state
        C, B, t = self.capacity, rows.capacity, self.time_ms
        is_cur = jnp.logical_and(rows.valid, rows.kind == ev.CURRENT)

        cand_ts = jnp.concatenate([buf.ts, rows.ts])
        cand_rel = jnp.concatenate([buf.expire_ts, rows.ts + t])
        cand_alive = jnp.concatenate([buf.alive, is_cur])
        cand_gslot = jnp.concatenate([buf.gslot, rows.gslot])
        cand_cols = tuple(jnp.concatenate([bc, rc])
                          for bc, rc in zip(buf.cols, rows.cols))
        release = jnp.logical_and(cand_alive, cand_rel <= now)

        rel_key = jnp.where(release, cand_rel, BIG_SEQ)
        order = jnp.argsort(rel_key, stable=True)
        rank = jnp.zeros((C + B,), jnp.int64).at[order].set(
            jnp.arange(C + B, dtype=jnp.int64))
        out = sort_rows(Rows(
            ts=cand_ts, kind=jnp.full((C + B,), ev.CURRENT, jnp.int32),
            valid=release, seq=seq0 + rank, gslot=cand_gslot,
            cols=cand_cols))

        keep = jnp.logical_and(cand_alive, jnp.logical_not(release))
        krank = jnp.cumsum(keep.astype(jnp.int64)) - 1
        big = jnp.full((C + B,), BIG_SEQ, jnp.int64)
        nbuf = _scatter_buffer(self.schema, C, keep, krank, cand_ts, big,
                               cand_rel, cand_gslot, cand_cols)
        nem = jnp.sum(release.astype(jnp.int64))
        wake = jnp.min(jnp.where(nbuf.alive, nbuf.expire_ts, NO_WAKEUP))
        return ((nbuf, seq0 + nem), WindowOutput(out, nbuf, wake))


class ChunkBatchWindow(WindowProcessor):
    emits_reset = True
    """`batch()` (reference: BatchWindowProcessor.java): each processed
    micro-batch is the window; the previous batch is replayed as EXPIRED
    ahead of the new CURRENT chunk."""

    name = "batch"

    def __init__(self, schema, params, batch_capacity, capacity_hint=1024):
        super().__init__(schema, params, batch_capacity)
        self.capacity = batch_capacity

    @property
    def out_capacity(self):
        return self.capacity + self.batch_capacity + 1

    def init_state(self):
        return (empty_buffer(self.schema, self.capacity),
                jnp.asarray(0, jnp.int64))

    def process(self, state, rows: Rows, now):
        prev, seq0 = state
        C, B = self.capacity, rows.capacity
        is_cur = jnp.logical_and(rows.valid, rows.kind == ev.CURRENT)
        any_cur = jnp.any(is_cur)
        ncur = jnp.sum(is_cur.astype(jnp.int64))
        nprev_n = jnp.sum(prev.alive.astype(jnp.int64))

        exp_rows = Rows(
            ts=prev.ts, kind=jnp.full((C,), ev.EXPIRED, jnp.int32),
            valid=jnp.logical_and(prev.alive, any_cur),
            seq=seq0 + jnp.cumsum(prev.alive.astype(jnp.int64)) - 1,
            gslot=prev.gslot, cols=prev.cols)
        k = jnp.cumsum(is_cur.astype(jnp.int64)) - 1
        cur_rows = Rows(
            ts=rows.ts, kind=jnp.full((B,), ev.CURRENT, jnp.int32),
            valid=is_cur, seq=seq0 + nprev_n + 1 + k, gslot=rows.gslot,
            cols=rows.cols)
        reset_rows = Rows(
            ts=jnp.full((1,), 0, jnp.int64) + now,
            kind=jnp.full((1,), ev.RESET, jnp.int32),
            valid=jnp.reshape(any_cur, (1,)),
            seq=jnp.full((1,), seq0 + nprev_n, jnp.int64),
            gslot=jnp.full((1,), -1, jnp.int32),
            cols=tuple(jnp.full((1,), ev.default_value(t_), d)
                       for t_, d in zip(self.schema.types,
                                        self.schema.dtypes)))
        out = sort_rows(concat_rows(concat_rows(exp_rows, cur_rows),
                                    reset_rows))

        big = jnp.full((B,), BIG_SEQ, jnp.int64)
        nprev = _scatter_buffer(self.schema, C, is_cur, k, rows.ts, big, big,
                                rows.gslot, rows.cols)
        nprev = jax.tree.map(lambda a, b: jnp.where(any_cur, a, b),
                             nprev, prev)
        nseq = jnp.where(any_cur, seq0 + nprev_n + 1 + ncur, seq0)
        return ((nprev, nseq),
                WindowOutput(out, None, jnp.asarray(NO_WAKEUP, jnp.int64)))


class SortWindow(WindowProcessor):
    """Sort window (reference: SortWindowProcessor.java): retains the n
    smallest (asc, default) or largest (desc) events by the key attribute;
    when full, the event at the losing end is evicted as EXPIRED."""

    name = "sort"

    def __init__(self, schema, params, batch_capacity, capacity_hint=1024):
        super().__init__(schema, params, batch_capacity)
        self.length = _param_int(params, 0)
        self.key_pos = _param_var_position(params, 1, schema, "sort")
        self.descending = False
        if len(params) > 2:
            p = params[2]
            if isinstance(p, Constant) and str(p.value).lower() == "desc":
                self.descending = True
        if len(params) > 3:
            raise ValueError("sort window supports a single sort key in "
                             "this build")
        self.capacity = self.length

    @property
    def out_capacity(self):
        return 2 * self.batch_capacity + self.capacity

    def init_state(self):
        return (empty_buffer(self.schema, self.capacity),
                jnp.asarray(0, jnp.int64))

    def process(self, state, rows: Rows, now):
        buf, seq0 = state
        C, B, n = self.capacity, rows.capacity, self.length
        is_cur = jnp.logical_and(rows.valid, rows.kind == ev.CURRENT)
        ncur = jnp.sum(is_cur.astype(jnp.int64))
        k = jnp.cumsum(is_cur.astype(jnp.int64)) - 1

        cand_ts = jnp.concatenate([buf.ts, rows.ts])
        cand_alive = jnp.concatenate([buf.alive, is_cur])
        cand_gslot = jnp.concatenate([buf.gslot, rows.gslot])
        cand_cols = tuple(jnp.concatenate([bc, rc])
                          for bc, rc in zip(buf.cols, rows.cols))
        key = cand_cols[self.key_pos]
        if self.descending:
            key = -key

        # keep the n best (smallest key); evict the rest as EXPIRED
        skey = jnp.where(cand_alive, key.astype(jnp.float64)
                         if key.dtype in (jnp.float32, jnp.float64)
                         else key.astype(jnp.int64), jnp.inf
                         if key.dtype in (jnp.float32, jnp.float64)
                         else BIG_SEQ)
        order = jnp.argsort(skey, stable=True)
        pos_rank = jnp.zeros((C + B,), jnp.int64).at[order].set(
            jnp.arange(C + B, dtype=jnp.int64))
        total = jnp.sum(cand_alive.astype(jnp.int64))
        keep = jnp.logical_and(cand_alive,
                               pos_rank < jnp.minimum(total, n))
        evict = jnp.logical_and(cand_alive, jnp.logical_not(keep))

        cur_rows = Rows(
            ts=rows.ts, kind=jnp.full((B,), ev.CURRENT, jnp.int32),
            valid=is_cur, seq=seq0 + k, gslot=rows.gslot, cols=rows.cols)
        erank = jnp.cumsum(evict.astype(jnp.int64)) - 1
        exp_rows = Rows(
            ts=cand_ts, kind=jnp.full((C + B,), ev.EXPIRED, jnp.int32),
            valid=evict, seq=seq0 + ncur + erank, gslot=cand_gslot,
            cols=cand_cols)
        out = sort_rows(concat_rows(cur_rows, exp_rows))

        krank = jnp.cumsum(keep.astype(jnp.int64)) - 1
        big = jnp.full((C + B,), BIG_SEQ, jnp.int64)
        nbuf = _scatter_buffer(self.schema, C, keep, krank, cand_ts, big,
                               big, cand_gslot, cand_cols)
        nem = ncur + jnp.sum(evict.astype(jnp.int64))
        return ((nbuf, seq0 + nem),
                WindowOutput(out, nbuf, jnp.asarray(NO_WAKEUP, jnp.int64)))


class CronWindow(WindowProcessor):
    emits_reset = True
    """Cron batch window (reference: CronWindowProcessor.java): accumulates
    events and flushes the batch at cron-scheduled times.  The cron schedule
    cannot be evaluated inside the compiled step, so the host scheduler
    computes fire times (`host_next_wakeup`) and the device flushes whenever
    a TIMER row arrives."""

    name = "cron"
    needs_timer = True
    host_scheduled = True

    def __init__(self, schema, params, batch_capacity, capacity_hint=2048):
        super().__init__(schema, params, batch_capacity)
        if not params or not isinstance(params[0], Constant):
            raise ValueError("cron window needs a cron expression string")
        from ..utils.cron import CronExpression
        self.cron = CronExpression(str(params[0].value))
        self.capacity = max(capacity_hint, 2 * batch_capacity)

    def host_next_wakeup(self, now: int) -> int:
        return self.cron.next_fire(now)

    @property
    def out_capacity(self):
        return 2 * self.capacity + self.batch_capacity + 1

    def init_state(self):
        return (
            empty_buffer(self.schema, self.capacity),   # pending
            empty_buffer(self.schema, self.capacity),   # previous
            jnp.asarray(0, jnp.int64),
        )

    def process(self, state, rows: Rows, now):
        pend, prev, seq0 = state
        C, B = self.capacity, rows.capacity
        is_cur = jnp.logical_and(rows.valid, rows.kind == ev.CURRENT)
        flush = jnp.any(jnp.logical_and(rows.valid, rows.kind == ev.TIMER))

        pend_rank = jnp.cumsum(pend.alive.astype(jnp.int64)) - 1
        fill0 = jnp.sum(pend.alive.astype(jnp.int64))
        k = jnp.cumsum(is_cur.astype(jnp.int64)) - 1

        exp_rows = Rows(
            ts=prev.ts, kind=jnp.full((C,), ev.EXPIRED, jnp.int32),
            valid=jnp.logical_and(prev.alive, flush),
            seq=seq0 + jnp.cumsum(prev.alive.astype(jnp.int64)) - 1,
            gslot=prev.gslot, cols=prev.cols)
        reset_rows = Rows(
            ts=jnp.full((1,), 0, jnp.int64) + now,
            kind=jnp.full((1,), ev.RESET, jnp.int32),
            valid=jnp.reshape(flush, (1,)),
            seq=jnp.full((1,), seq0 + C, jnp.int64),
            gslot=jnp.full((1,), -1, jnp.int32),
            cols=tuple(jnp.full((1,), ev.default_value(t_), d)
                       for t_, d in zip(self.schema.types,
                                        self.schema.dtypes)))
        cur_rows = Rows(
            ts=pend.ts, kind=jnp.full((C,), ev.CURRENT, jnp.int32),
            valid=jnp.logical_and(pend.alive, flush),
            seq=seq0 + C + 1 + pend_rank, gslot=pend.gslot, cols=pend.cols)
        out = sort_rows(concat_rows(concat_rows(exp_rows, cur_rows),
                                    reset_rows))

        # new pending: arrivals append; if flush, pending cleared first
        keep_pend = jnp.logical_and(pend.alive, jnp.logical_not(flush))
        base = jnp.where(flush, 0, fill0)
        cand_valid = jnp.concatenate([keep_pend, is_cur])
        cand_rank = jnp.concatenate([pend_rank, base + k])
        cand_ts = jnp.concatenate([pend.ts, rows.ts])
        cand_gslot = jnp.concatenate([pend.gslot, rows.gslot])
        cand_cols = tuple(jnp.concatenate([pc, rc])
                          for pc, rc in zip(pend.cols, rows.cols))
        big = jnp.full(cand_ts.shape, BIG_SEQ, jnp.int64)
        npend = _scatter_buffer(self.schema, C, cand_valid, cand_rank,
                                cand_ts, big, big, cand_gslot, cand_cols)
        nprev = jax.tree.map(lambda a, b: jnp.where(flush, a, b), pend, prev)
        nseq = jnp.where(flush, seq0 + 2 * C + 1, seq0)
        return ((npend, nprev, nseq),
                WindowOutput(out, None, jnp.asarray(NO_WAKEUP, jnp.int64)))


class SessionWindow(WindowProcessor):
    """Session window, single-session form (reference:
    SessionWindowProcessor.java — the largest reference window, 696 LoC).
    Events pass through as CURRENT and accumulate in the live session; when
    `gap` elapses with no arrivals the whole session is expired together.
    The per-key variant (`session(gap, key)`) belongs to the partitioned
    path and is not yet wired here."""

    name = "session"
    needs_timer = True

    def __init__(self, schema, params, batch_capacity, capacity_hint=2048):
        super().__init__(schema, params, batch_capacity)
        self.gap_ms = _param_int(params, 0)
        # session(gap, key): per-key sessions ride the keyed-window slab —
        # the planner detects session_key_pos and vmaps this processor
        # over a [K, ...] state slab (reference: SessionWindowProcessor
        # sessionKey overload, SessionWindowProcessor.java:74-88)
        self.session_key_pos = None
        if len(params) >= 2:
            self.session_key_pos = _param_var_position(
                params, 1, schema, "session")
        if len(params) > 2:
            raise ValueError(
                "session(gap, key, allowed.latency) late-arrival grace "
                "lands in a later phase")
        self.capacity = max(capacity_hint, 2 * batch_capacity)

    @property
    def out_capacity(self):
        return self.capacity + self.batch_capacity

    def init_state(self):
        return (
            empty_buffer(self.schema, self.capacity),
            jnp.asarray(-1, jnp.int64),   # session start ts (-1: no session)
            jnp.asarray(-1, jnp.int64),   # last event ts (-1: no session)
            jnp.asarray(0, jnp.int64),
        )

    def process(self, state, rows: Rows, now):
        buf, start0, last0, seq0 = state
        C, B, gap = self.capacity, rows.capacity, self.gap_ms
        is_cur = jnp.logical_and(rows.valid, rows.kind == ev.CURRENT)

        # session expires if gap passed before this batch's first arrival
        expire_now = jnp.logical_and(last0 >= 0, last0 + gap <= now)

        # late events within `start - gap` re-open the session backwards
        # (they sort into ts order on expiry); anything older than that is
        # DROPPED — its session has already timed out (reference:
        # SessionWindowProcessor.addLateEvent else-branch removes + logs)
        session_alive = jnp.logical_and(last0 >= 0,
                                        jnp.logical_not(expire_now))
        too_late = jnp.logical_and(session_alive, rows.ts < start0 - gap)
        is_cur = jnp.logical_and(is_cur, jnp.logical_not(too_late))
        any_cur = jnp.any(is_cur)
        ncur = jnp.sum(is_cur.astype(jnp.int64))
        k = jnp.cumsum(is_cur.astype(jnp.int64)) - 1

        brank = jnp.cumsum(buf.alive.astype(jnp.int64)) - 1
        # expiry emits the session's rows in EVENT-TIME order (late joins
        # sort before the rows they arrived after — reference:
        # insertBeforeCurrent keeps the chunk ts-ordered)
        bts = jnp.where(buf.alive, buf.ts, jnp.iinfo(jnp.int64).max)
        order = jnp.argsort(bts, stable=True)
        ts_rank = jnp.zeros((C,), jnp.int64).at[order].set(
            jnp.arange(C, dtype=jnp.int64))
        exp_rows = Rows(
            ts=buf.ts, kind=jnp.full((C,), ev.EXPIRED, jnp.int32),
            valid=jnp.logical_and(buf.alive, expire_now),
            seq=seq0 + ts_rank, gslot=buf.gslot, cols=buf.cols)
        nexp = jnp.where(expire_now,
                         jnp.sum(buf.alive.astype(jnp.int64)), 0)
        cur_rows = Rows(
            ts=rows.ts, kind=jnp.full((B,), ev.CURRENT, jnp.int32),
            valid=is_cur, seq=seq0 + nexp + k, gslot=rows.gslot,
            cols=rows.cols)
        out = sort_rows(concat_rows(exp_rows, cur_rows))

        keep = jnp.logical_and(buf.alive, jnp.logical_not(expire_now))
        fill0 = jnp.sum(keep.astype(jnp.int64))
        cand_valid = jnp.concatenate([keep, is_cur])
        cand_rank = jnp.concatenate([brank, fill0 + k])
        cand_ts = jnp.concatenate([buf.ts, rows.ts])
        cand_gslot = jnp.concatenate([buf.gslot, rows.gslot])
        cand_cols = tuple(jnp.concatenate([bc, rc])
                          for bc, rc in zip(buf.cols, rows.cols))
        big = jnp.full(cand_ts.shape, BIG_SEQ, jnp.int64)
        nbuf = _scatter_buffer(self.schema, C, cand_valid, cand_rank,
                               cand_ts, big, big, cand_gslot, cand_cols)
        last_arr = jnp.max(jnp.where(is_cur, rows.ts, -1))
        nlast = jnp.where(any_cur, jnp.maximum(last_arr, 0),
                          jnp.where(expire_now, -1, last0))
        # session start: min arrival for a fresh session; an in-gap late
        # event pulls it backwards (reference: setStartTimestamp)
        min_arr = jnp.min(jnp.where(is_cur, rows.ts,
                                    jnp.iinfo(jnp.int64).max))
        fresh = jnp.logical_or(expire_now, last0 < 0)
        nstart = jnp.where(any_cur,
                           jnp.where(fresh, min_arr,
                                     jnp.minimum(start0, min_arr)),
                           jnp.where(expire_now, -1, start0))
        nseq = seq0 + nexp + ncur
        wake = jnp.where(nlast >= 0, nlast + gap, NO_WAKEUP)
        return ((nbuf, nstart, nlast, nseq), WindowOutput(out, nbuf, wake))


class SessionLatencyWindow(WindowProcessor):
    """session(gap, key, allowed.latency) — late-arrival grace (reference:
    SessionWindowProcessor.java:240-440 with allowedLatency > 0).

    Reference behavior (what): each key keeps a CURRENT session plus one
    PREVIOUS session that lingers for `latency` after its gap expiry; a
    new session rotates current → previous (flushing any older previous
    as EXPIRED); late events merge into current (extending it backwards)
    or into previous (possibly re-merging the two); events older than
    both sessions' reach are dropped; previous finally EXPIRES when its
    alive timestamp (end + latency) passes.

    TPU design (how): per-event classification is inherently sequential,
    so the batch advances under `lax.scan` with two fixed slabs (current/
    previous) in the carry; slab order is free because expiry emission
    re-sorts by event time.  The key axis comes from the keyed-window
    vmap (planner), exactly like the 2-param form."""

    name = "session"
    needs_timer = True

    def __init__(self, schema, params, batch_capacity, capacity_hint=2048):
        super().__init__(schema, params, batch_capacity)
        self.gap_ms = _param_int(params, 0)
        self.session_key_pos = _param_var_position(
            params, 1, schema, "session") \
            if not isinstance(params[1], Constant) else None
        if self.session_key_pos is None:
            raise ValueError("session's 2nd parameter must name the "
                             "session key attribute")
        self.latency_ms = _param_int(params, 2)
        if self.latency_ms > self.gap_ms:
            # reference: validateAllowedLatency
            raise ValueError(
                "session window's allowed.latency must not exceed the "
                "session gap")
        # same sizing rule as the 2-param form: an explicit
        # @capacity(window='N') hint is honored, never clamped
        self.capacity = max(capacity_hint, 2 * batch_capacity)

    @property
    def out_capacity(self):
        return 2 * self.capacity + 2 * self.batch_capacity

    def init_state(self):
        C = self.capacity
        z = lambda: jnp.zeros((C,), jnp.int64)      # noqa: E731
        mk = lambda: (                               # noqa: E731
            z(), jnp.zeros((C,), jnp.bool_), jnp.full((C,), -1, jnp.int32),
            tuple(jnp.full((C,), ev.default_value(t), d)
                  for t, d in zip(self.schema.types, self.schema.dtypes)))
        neg = jnp.asarray(-1, jnp.int64)
        return (mk(), neg, neg,          # current slab, start, last
                mk(), neg, neg, neg,     # previous slab, start, last, alive
                jnp.asarray(0, jnp.int64))

    def current_buffer(self, state):
        (cts, calive, cgslot, ccols) = state[0]
        C = self.capacity
        big = jnp.full((C,), BIG_SEQ, jnp.int64)
        return Buffer(ts=cts, add_seq=big, expire_seq=big, expire_ts=big,
                      alive=calive, gslot=cgslot, cols=ccols)

    # -- slab helpers (order-free: expiry re-sorts by ts) -------------------
    def _emit(self, out, out_n, slab, seq_base, do):
        """Append slab's alive rows (ts-sorted) to the out grid."""
        ots, okind, ovalid, oseq, ogslot, ocols = out
        sts, salive, sgslot, scols = slab
        C = self.capacity
        live = jnp.logical_and(salive, do)
        key = jnp.where(live, sts, jnp.iinfo(jnp.int64).max)
        order = jnp.argsort(key, stable=True)
        rank = jnp.zeros((C,), jnp.int64).at[order].set(
            jnp.arange(C, dtype=jnp.int64))
        pos = jnp.where(live, out_n + rank, self.out_capacity)
        ots = ots.at[pos].set(sts, mode="drop")
        okind = okind.at[pos].set(ev.EXPIRED, mode="drop")
        ovalid = ovalid.at[pos].set(True, mode="drop")
        oseq = oseq.at[pos].set(seq_base + rank, mode="drop")
        ogslot = ogslot.at[pos].set(sgslot, mode="drop")
        ocols = tuple(oc.at[pos].set(sc, mode="drop")
                      for oc, sc in zip(ocols, scols))
        n = jnp.sum(live.astype(jnp.int64))
        return (ots, okind, ovalid, oseq, ogslot, ocols), out_n + n, \
            seq_base + n

    def _append(self, slab, ts_e, gslot_e, cols_e, do):
        sts, salive, sgslot, scols = slab
        n = jnp.sum(salive.astype(jnp.int64))
        pos = jnp.where(do, n, self.capacity)   # capacity overflow drops
        return (sts.at[pos].set(ts_e, mode="drop"),
                salive.at[pos].set(True, mode="drop"),
                sgslot.at[pos].set(gslot_e, mode="drop"),
                tuple(sc.at[pos].set(ce, mode="drop")
                      for sc, ce in zip(scols, cols_e)))

    def _merge_into(self, dst, src, do):
        """Scatter src's alive rows into dst's free tail (when `do`)."""
        dts, dalive, dgslot, dcols = dst
        sts, salive, sgslot, scols = src
        n = jnp.sum(dalive.astype(jnp.int64))
        srank = jnp.cumsum(salive.astype(jnp.int64)) - 1
        live = jnp.logical_and(salive, do)
        pos = jnp.where(live, n + srank, self.capacity)
        return (dts.at[pos].set(sts, mode="drop"),
                dalive.at[pos].set(True, mode="drop"),
                dgslot.at[pos].set(sgslot, mode="drop"),
                tuple(dc.at[pos].set(sc, mode="drop")
                      for dc, sc in zip(dcols, scols)))

    def _clear(self, slab, do):
        sts, salive, sgslot, scols = slab
        return (sts, jnp.where(do, False, salive), sgslot, scols)

    def process(self, state, rows: Rows, now):
        cur, cs0, cl0, prev, ps0, pl0, pa0, seq0 = state
        C, B = self.capacity, rows.capacity
        gap, lat = self.gap_ms, self.latency_ms
        OC = self.out_capacity
        out = (jnp.zeros((OC,), jnp.int64), jnp.zeros((OC,), jnp.int32),
               jnp.zeros((OC,), jnp.bool_), jnp.full((OC,), BIG_SEQ,
                                                     jnp.int64),
               jnp.full((OC,), -1, jnp.int32),
               tuple(jnp.full((OC,), ev.default_value(t), d)
                     for t, d in zip(self.schema.types, self.schema.dtypes)))
        out_n = jnp.asarray(0, jnp.int64)
        seq = seq0

        # ---- batch-start timeouts ----
        prev_has = pl0 >= 0
        cur_has = cl0 >= 0
        # previous expires at alive = end + latency
        pto = jnp.logical_and(prev_has, pa0 <= now)
        out, out_n, seq = self._emit(out, out_n, prev, seq, pto)
        prev = self._clear(prev, pto)
        ps0 = jnp.where(pto, -1, ps0)
        pl0 = jnp.where(pto, -1, pl0)
        pa0 = jnp.where(pto, -1, pa0)
        prev_has = jnp.logical_and(prev_has, jnp.logical_not(pto))
        # current's gap passed: rotate into previous (flushing an older
        # previous immediately — reference: moveCurrentSessionToPrevious)
        cto = jnp.logical_and(cur_has, cl0 + gap <= now)
        flush_old = jnp.logical_and(cto, prev_has)
        out, out_n, seq = self._emit(out, out_n, prev, seq, flush_old)
        prev = jax.tree.map(lambda p, c: jnp.where(cto, c, p), prev, cur)
        ps0 = jnp.where(cto, cs0, ps0)
        pl0 = jnp.where(cto, cl0, pl0)
        pa0 = jnp.where(cto, cl0 + gap + lat, pa0)
        cur = self._clear(cur, cto)
        cs0 = jnp.where(cto, -1, cs0)
        cl0 = jnp.where(cto, -1, cl0)

        # ---- per-event scan ----
        is_cur = jnp.logical_and(rows.valid, rows.kind == ev.CURRENT)

        def body(carry, xs):
            cur, cs, cl, prev, ps, pl, pa, out, out_n, seq = carry
            t, live, gslot_e, cols_e = xs
            cur_has = cl >= 0
            prev_has = pl >= 0
            cend = cl + gap
            in_cur = jnp.logical_and(
                cur_has, jnp.logical_and(t >= cs, t <= cend))
            new_sess = jnp.logical_and(
                cur_has, jnp.logical_and(t >= cs, t > cend))
            late_cur = jnp.logical_and(
                cur_has, jnp.logical_and(t < cs, t >= cs - gap))
            late_prev = jnp.logical_and(
                jnp.logical_and(cur_has, t < cs - gap),
                jnp.logical_and(prev_has, t >= ps - gap))
            fresh = jnp.logical_not(cur_has)
            kept = jnp.logical_and(live, jnp.logical_or(
                jnp.logical_or(fresh, in_cur),
                jnp.logical_or(new_sess,
                               jnp.logical_or(late_cur, late_prev))))

            # rotate on new session: flush old previous, previous <- cur
            do_rot = jnp.logical_and(live, new_sess)
            out, out_n, seq = self._emit(
                out, out_n, prev, seq, jnp.logical_and(do_rot, prev_has))
            prev = jax.tree.map(lambda p, c: jnp.where(do_rot, c, p),
                                prev, cur)
            ps = jnp.where(do_rot, cs, ps)
            pl = jnp.where(do_rot, cl, pl)
            pa = jnp.where(do_rot, cl + gap + lat, pa)
            cur = self._clear(cur, do_rot)
            prev_has = jnp.logical_or(prev_has, do_rot)

            # place the event
            to_prev = jnp.logical_and(live, late_prev)
            to_cur = jnp.logical_and(kept, jnp.logical_not(late_prev))
            cur = self._append(cur, t, gslot_e, cols_e, to_cur)
            prev = self._append(prev, t, gslot_e, cols_e, to_prev)

            # boundary updates
            cs = jnp.where(to_cur, jnp.where(
                jnp.logical_or(fresh, do_rot), t, jnp.minimum(cs, t)), cs)
            cl = jnp.where(to_cur, jnp.maximum(cl, t), cl)
            # late-to-previous: extend backwards or forwards
            p_back = jnp.logical_and(to_prev, t < ps)
            ps = jnp.where(p_back, t, ps)
            p_fwd = jnp.logical_and(to_prev, t > pl)
            pl = jnp.where(p_fwd, t, pl)
            pa = jnp.where(p_fwd, t + gap + lat, pa)

            # merge previous into current when their reaches touch
            # (reference: mergeWindows — prev end >= cur start - gap)
            can_merge = jnp.logical_and(
                jnp.logical_and(prev_has, cl >= 0),
                pl + gap >= cs - gap)
            do_merge = jnp.logical_and(
                jnp.logical_or(jnp.logical_and(live, late_cur),
                               jnp.logical_and(live, p_fwd)), can_merge)
            cur = self._merge_into(cur, prev, do_merge)
            prev = self._clear(prev, do_merge)
            cs = jnp.where(do_merge, jnp.minimum(cs, ps), cs)
            cl = jnp.where(do_merge, jnp.maximum(cl, pl), cl)
            ps = jnp.where(do_merge, -1, ps)
            pl = jnp.where(do_merge, -1, pl)
            pa = jnp.where(do_merge, -1, pa)

            return (cur, cs, cl, prev, ps, pl, pa, out, out_n, seq), kept

        carry0 = (cur, cs0, cl0, prev, ps0, pl0, pa0, out, out_n, seq)
        xs = (rows.ts, is_cur, rows.gslot, tuple(c for c in rows.cols))
        (cur, cs0, cl0, prev, ps0, pl0, pa0, out, out_n, seq), kept = \
            jax.lax.scan(body, carry0, xs)

        # ---- pass-through CURRENT rows (arrival order, after expiries) ----
        ots, okind, ovalid, oseq, ogslot, ocols = out
        k = jnp.cumsum(kept.astype(jnp.int64)) - 1
        pos = jnp.where(kept, out_n + k, OC)
        ots = ots.at[pos].set(rows.ts, mode="drop")
        okind = okind.at[pos].set(ev.CURRENT, mode="drop")
        ovalid = ovalid.at[pos].set(True, mode="drop")
        oseq = oseq.at[pos].set(seq + k, mode="drop")
        ogslot = ogslot.at[pos].set(rows.gslot, mode="drop")
        ocols = tuple(oc.at[pos].set(rc, mode="drop")
                      for oc, rc in zip(ocols, rows.cols))
        nk = jnp.sum(kept.astype(jnp.int64))
        seq = seq + nk

        out_rows = sort_rows(Rows(ts=ots, kind=okind, valid=ovalid,
                                  seq=oseq, gslot=ogslot, cols=ocols))
        nstate = (cur, cs0, cl0, prev, ps0, pl0, pa0, seq)
        wake = jnp.minimum(
            jnp.where(cl0 >= 0, cl0 + gap, NO_WAKEUP),
            jnp.where(pl0 >= 0, pa0, NO_WAKEUP))
        return nstate, WindowOutput(out_rows, self.current_buffer(nstate),
                                    wake)


class FrequentWindow(WindowProcessor):
    """Misra-Gries frequent window (reference: FrequentWindowProcessor.java):
    keeps the latest event per key for up to n keys; a miss with full
    counters decrements all counts and evicts keys reaching zero.  Per-event
    sequential by nature — runs as a compiled lax.scan over the batch."""

    name = "frequent"

    def __init__(self, schema, params, batch_capacity, capacity_hint=1024):
        super().__init__(schema, params, batch_capacity)
        self.n = _param_int(params, 0)
        if len(params) > 1:
            self.key_positions = [
                _param_var_position(params, i, schema, "frequent")
                for i in range(1, len(params))]
        else:
            self.key_positions = list(range(len(schema.names)))

    @property
    def out_capacity(self):
        return self.batch_capacity * (self.n + 1)

    def init_state(self):
        n = self.n
        return (
            jnp.zeros((n,), jnp.int64),                 # counts (0 = free)
            jnp.full((n, len(self.key_positions)), 0, jnp.int64),  # keys
            empty_buffer(self.schema, n),               # stored events
            jnp.asarray(0, jnp.int64),
        )

    def _key_of(self, cols):
        return jnp.stack(
            [_as_i64_key(cols[p]) for p in self.key_positions], axis=-1)

    def process(self, state, rows: Rows, now):
        counts0, keys0, buf0, seq0 = state
        n = self.n
        B = rows.capacity
        is_cur = jnp.logical_and(rows.valid, rows.kind == ev.CURRENT)
        ev_keys = self._key_of(rows.cols)     # [B, K]

        def step(carry, x):
            counts, keys, bts, bgslot, bcols = carry
            valid, key, ts, gslot, cols = x
            match = jnp.logical_and(
                counts > 0, jnp.all(keys == key[None, :], axis=1))
            hit = jnp.any(match)
            midx = jnp.argmax(match)
            free = counts == 0
            has_free = jnp.any(free)
            fidx = jnp.argmax(free)

            # case 1 hit: count+1, replace stored event (old expires)
            # case 2 free: insert
            # case 3 full miss: decrement all; evict zeros
            do_insert = jnp.logical_and(valid, jnp.logical_or(hit, has_free))
            slot = jnp.where(hit, midx, fidx)
            dec = jnp.logical_and(valid,
                                  jnp.logical_not(jnp.logical_or(hit,
                                                                 has_free)))
            ncounts = jnp.where(
                dec, jnp.maximum(counts - 1, 0),
                counts.at[slot].add(jnp.where(do_insert, 1, 0)))
            evicted = jnp.logical_and(dec & (counts > 0), ncounts == 0)
            # replaced stored event on hit -> expired
            replaced = jnp.logical_and(hit & valid,
                                       jnp.zeros((n,), jnp.bool_).at[
                                           midx].set(True))
            exp_mask = jnp.logical_or(evicted, replaced)
            exp_ts, exp_gslot, exp_cols = bts, bgslot, bcols

            nkeys = keys.at[slot].set(
                jnp.where(do_insert, key, keys[slot]))
            nbts = bts.at[slot].set(jnp.where(do_insert, ts, bts[slot]))
            nbgslot = bgslot.at[slot].set(
                jnp.where(do_insert, gslot, bgslot[slot]))
            nbcols = tuple(
                bc.at[slot].set(jnp.where(do_insert, c, bc[slot]))
                for bc, c in zip(bcols, cols))
            emit_cur = do_insert
            return ((ncounts, nkeys, nbts, nbgslot, nbcols),
                    (emit_cur, exp_mask, exp_ts, exp_gslot, exp_cols))

        xs = (is_cur, ev_keys, rows.ts, rows.gslot, rows.cols)
        carry0 = (counts0, keys0, buf0.ts, buf0.gslot, buf0.cols)
        (counts, keys, bts, bgslot, bcols), outs = jax.lax.scan(
            step, carry0, xs)
        emit_cur, exp_mask, exp_ts, exp_gslot, exp_cols = outs

        # sequence: per event i, expired emissions (n slots) then current
        base = seq0 + jnp.arange(B, dtype=jnp.int64) * (n + 1)
        cur_rows = Rows(
            ts=rows.ts, kind=jnp.full((B,), ev.CURRENT, jnp.int32),
            valid=emit_cur, seq=base + n, gslot=rows.gslot, cols=rows.cols)
        exp_rows = Rows(
            ts=jnp.repeat(rows.ts, n),
            kind=jnp.full((B * n,), ev.EXPIRED, jnp.int32),
            valid=exp_mask.reshape(-1),
            seq=(base[:, None] + jnp.arange(n, dtype=jnp.int64)[None, :]
                 ).reshape(-1),
            gslot=exp_gslot.reshape(-1),
            cols=tuple(c.reshape(-1) for c in exp_cols))
        out = sort_rows(concat_rows(exp_rows, cur_rows))

        nbuf = Buffer(
            ts=bts, add_seq=jnp.full((n,), BIG_SEQ, jnp.int64),
            expire_seq=jnp.full((n,), BIG_SEQ, jnp.int64),
            expire_ts=jnp.full((n,), BIG_SEQ, jnp.int64),
            alive=counts > 0, gslot=bgslot, cols=bcols)
        nseq = seq0 + B * (n + 1)
        return ((counts, keys, nbuf, nseq),
                WindowOutput(out, nbuf, jnp.asarray(NO_WAKEUP, jnp.int64)))


class LossyFrequentWindow(FrequentWindow):
    """Lossy-counting window (reference: LossyFrequentWindowProcessor.java).
    Approximated here with the same Misra-Gries machinery sized at
    ceil(1/support) counters — both give the classic heavy-hitter guarantee
    (undercount bounded by N*support)."""

    name = "lossyFrequent"

    def __init__(self, schema, params, batch_capacity, capacity_hint=1024):
        if not params or not isinstance(params[0], Constant):
            raise ValueError("lossyFrequent needs a support fraction")
        support = float(params[0].value)
        if not (0.0 < support < 1.0):
            raise ValueError("support must be in (0, 1)")
        n = max(int(1.0 / support), 1)
        rest = [p for p in params[1:]
                if not (isinstance(p, Constant)
                        and isinstance(p.value, float))]
        fake = [Constant(n, "INT")] + rest
        super().__init__(schema, fake, batch_capacity, capacity_hint)


def _as_i64_key(col):
    if col.dtype in (jnp.float32, jnp.float64):
        return jax.lax.bitcast_convert_type(
            col.astype(jnp.float64), jnp.int64)
    return col.astype(jnp.int64)


class HoppingWindow(WindowProcessor):
    """Hopping (sliding-batch) time window (reference:
    HopingWindowProcessor — `#window.hoping(window.time, hop.time)`): every
    hop.time the events of the trailing window.time emit as one batch, so
    consecutive batches overlap when hop < window.

    TPU design: one retained buffer of the trailing window.time + hop.time;
    each hop boundary emits CURRENT = rows inside [emit-win, emit) and
    EXPIRED = the previous boundary's rows, with a RESET row between epochs
    (standard batch-window aggregation semantics).  If several hop
    boundaries pass inside one quiet gap, intermediate empty emissions
    collapse to the latest boundary — same collapsing rule as timeBatch."""

    name = "hopping"
    needs_timer = True
    emits_reset = True

    def __init__(self, schema, params, batch_capacity, capacity_hint=2048):
        super().__init__(schema, params, batch_capacity)
        self.win_ms = _param_int(params, 0)
        self.hop_ms = _param_int(params, 1, default=self.win_ms)
        self.capacity = max(capacity_hint, 2 * batch_capacity)

    @property
    def out_capacity(self):
        return 2 * (self.capacity + self.batch_capacity) + 1

    def init_state(self):
        return (
            empty_buffer(self.schema, self.capacity),   # retained rows
            jnp.asarray(-1, jnp.int64),                 # next emit boundary
            jnp.asarray(0, jnp.int64),                  # seq counter
        )

    def process(self, state, rows: Rows, now):
        buf, next0, seq0 = state
        win, hop = self.win_ms, self.hop_ms
        C, B = self.capacity, rows.capacity

        is_cur = jnp.logical_and(rows.valid, rows.kind == ev.CURRENT)
        any_cur = jnp.any(is_cur)
        first_ts = jnp.min(jnp.where(is_cur, rows.ts, BIG_SEQ))
        nxt = jnp.where(next0 >= 0, next0,
                        jnp.where(any_cur, first_ts + hop, -1))
        flush = jnp.logical_and(nxt >= 0, now >= nxt)
        emit_ts = jnp.where(flush, nxt + ((now - nxt) // hop) * hop, nxt)

        cand_ts = jnp.concatenate([buf.ts, rows.ts])
        cand_live = jnp.concatenate([buf.alive, is_cur])
        cand_gslot = jnp.concatenate([buf.gslot, rows.gslot])
        cand_cols = tuple(jnp.concatenate([bc, rc])
                          for bc, rc in zip(buf.cols, rows.cols))
        CB = C + B

        in_cur = jnp.logical_and(
            cand_live, jnp.logical_and(cand_ts >= emit_ts - win,
                                       cand_ts < emit_ts))
        prev_ts = emit_ts - hop
        in_prev = jnp.logical_and(
            cand_live, jnp.logical_and(cand_ts >= prev_ts - win,
                                       cand_ts < prev_ts))
        # seq layout: expired prev batch [0..CB), reset CB, current [CB+1..)
        exp_rows = Rows(
            ts=cand_ts, kind=jnp.full((CB,), ev.EXPIRED, jnp.int32),
            valid=jnp.logical_and(in_prev, flush),
            seq=seq0 + jnp.cumsum(in_prev.astype(jnp.int64)) - 1,
            gslot=cand_gslot, cols=cand_cols)
        reset_rows = Rows(
            ts=jnp.reshape(now, (1,)) * jnp.ones((1,), jnp.int64),
            kind=jnp.full((1,), ev.RESET, jnp.int32),
            valid=jnp.reshape(flush, (1,)),
            seq=jnp.full((1,), seq0 + CB, jnp.int64),
            gslot=jnp.full((1,), -1, jnp.int32),
            cols=tuple(jnp.full((1,), ev.default_value(t_), d)
                       for t_, d in zip(self.schema.types,
                                        self.schema.dtypes)))
        cur_rows = Rows(
            ts=cand_ts, kind=jnp.full((CB,), ev.CURRENT, jnp.int32),
            valid=jnp.logical_and(in_cur, flush),
            seq=seq0 + CB + 1 + jnp.cumsum(in_cur.astype(jnp.int64)) - 1,
            gslot=cand_gslot, cols=cand_cols)
        out = sort_rows(concat_rows(concat_rows(exp_rows, cur_rows),
                                    reset_rows))

        # retention: the next flush at new_next expires window
        # [new_next - hop - win, new_next - hop), so rows must survive one
        # hop PAST their own window or EXPIRED batches lose their old rows
        new_next = jnp.where(flush, emit_ts + hop, nxt)
        keep = jnp.logical_and(
            cand_live,
            jnp.where(new_next >= 0,
                      cand_ts >= new_next - win - hop, True))
        rank = jnp.cumsum(keep.astype(jnp.int64)) - 1
        big = jnp.full((CB,), BIG_SEQ, jnp.int64)
        nbuf = _scatter_buffer(self.schema, C, keep, rank, cand_ts,
                               big, big, cand_gslot, cand_cols)
        nseq = jnp.where(flush, seq0 + 2 * CB + 2, seq0)
        wake = jnp.where(new_next >= 0, new_next, NO_WAKEUP)
        return ((nbuf, new_next, nseq), WindowOutput(out, None, wake))


def _session_factory(schema, params, batch_capacity, capacity_hint=2048):
    """Session window: events within `session.gap` of each other group
    into one session that expires together after a quiet gap.  Overloads
    (reference: SessionWindowProcessor.java:86-88): session(gap),
    session(gap, key) for independent per-key sessions, and
    session(gap, key, allowed.latency) which keeps the previous session
    alive for `allowed.latency` so late events can still merge."""
    # session(gap[, key]) -> vectorized single-session processor (per-key
    # isolation rides the keyed-window vmap slab); 3-arg form needs the
    # two-session late-merge scan
    if len(params) >= 3:
        return SessionLatencyWindow(schema, params, batch_capacity,
                                    capacity_hint=capacity_hint)
    return SessionWindow(schema, params, batch_capacity,
                         capacity_hint=capacity_hint)


def register(window_types: dict) -> None:
    for cls in (ExternalTimeWindow, ExternalTimeBatchWindow, TimeLengthWindow,
                DelayWindow, ChunkBatchWindow, SortWindow, CronWindow,
                FrequentWindow, LossyFrequentWindow,
                HoppingWindow):
        window_types[cls.name] = cls
    window_types["session"] = _session_factory
    window_types["hoping"] = HoppingWindow   # the reference's spelling
