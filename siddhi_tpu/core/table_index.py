"""Secondary table indexes + index-aware condition planning.

Reference behavior (what): IndexEventHolder keeps one map per @Index
attribute next to the primary-key map (CORE/table/holder/
IndexEventHolder.java:60-127 — indexData TreeMaps :65-66, add/delete
maintenance :94-127), and CollectionExpressionParser
(CORE/util/parser/CollectionExpressionParser.java) rewrites a table
condition into an indexed probe plus a residual exhaustive part, so
`table.attr == v and <rest>` touches only the matching rows.

TPU-native design (how): the per-event TreeMap of the reference becomes a
batched two-level structure. Values hash to dense *bucket* ids through the
same vectorized SlotAllocator used for partition keys (C kernel, no Python
per-row work), and a host [n_buckets, K] lane table maps each bucket to its
row ids. An equality probe for a whole event batch is one vectorized
allocator lookup + one gather — candidates come back as a padded [B, K]
block that the residual condition evaluates on device, replacing the dense
[B, C] broadcast with [B, K] where K is the widest bucket. Range conditions
(<, <=, >, >=) use a lazily re-sorted (value, row) view + searchsorted —
the batched analogue of the reference's TreeMap.subMap scan.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..query_api.expression import (And, Compare, Constant, Expression,
                                    Variable, walk)
from .keyslots import SlotAllocator

_GROW = 2


class AttributeIndex:
    """One secondary index: encoded column value -> row ids.

    Maintenance is vectorized per batch: inserts counting-sort rows by
    bucket, deletes swap-remove lanes. `shadow` mirrors the indexed
    column's encoded values on host so deletes/updates never read the
    device."""

    def __init__(self, capacity: int, dtype, name: str = "?"):
        self.capacity = capacity
        self.dtype = dtype
        self.alloc = SlotAllocator(capacity, name=f"index:{name}")
        self.lanes = np.full((capacity, 4), -1, np.int32)  # bucket -> rows
        self.counts = np.zeros(capacity, np.int32)         # rows per bucket
        self.shadow = np.zeros(capacity, dtype)            # row -> value
        self.bucket_of = np.full(capacity, -1, np.int32)   # row -> bucket
        self._sorted_dirty = True
        self._sorted_vals: Optional[np.ndarray] = None
        self._sorted_rows: Optional[np.ndarray] = None

    # -- maintenance -------------------------------------------------------
    def _key_cols(self, values: np.ndarray) -> List[np.ndarray]:
        if np.issubdtype(self.dtype, np.floating):
            # -0.0 and +0.0 must hash identically (dense `==` matches them)
            values = values + np.dtype(self.dtype).type(0.0)
        return [np.ascontiguousarray(values)]

    def on_write(self, rows: np.ndarray, values: np.ndarray) -> None:
        """Rows were inserted or overwritten with `values` (encoded)."""
        rows = np.asarray(rows, np.int64)
        if rows.size == 0:
            return
        values = np.asarray(values, self.dtype)
        if rows.size > 1:
            # a batch may hit one row several times (pkey upsert with a
            # repeated key): only the LAST write per row is live — earlier
            # ones would leave stale lane entries and leaked bucket counts
            _, last_rev = np.unique(rows[::-1], return_index=True)
            keep = rows.size - 1 - last_rev
            if keep.size != rows.size:
                rows = rows[keep]
                values = values[keep]
        # drop stale lane entries for rows that already had a value
        stale = self.bucket_of[rows] >= 0
        if stale.any():
            self._remove_lanes(rows[stale])
        valid = np.ones(rows.shape[0], bool)
        buckets = self.alloc.slots_for(self._key_cols(values), valid)
        self.shadow[rows] = values
        self.bucket_of[rows] = buckets
        # counting-sort style lane fill: group rows by bucket
        order = np.argsort(buckets, kind="stable")
        b_sorted = buckets[order]
        r_sorted = rows[order]
        uniq, start, cnt = np.unique(b_sorted, return_index=True,
                                     return_counts=True)
        need = self.counts[uniq] + cnt
        width = self.lanes.shape[1]
        if need.max(initial=0) > width:
            new_w = max(width * _GROW, int(need.max()))
            self.lanes = np.concatenate(
                [self.lanes, np.full((self.capacity, new_w - width),
                                     -1, np.int32)], axis=1)
        for b, s, c in zip(uniq, start, cnt):
            base = self.counts[b]
            self.lanes[b, base:base + c] = r_sorted[s:s + c]
            self.counts[b] = base + c
        self._sorted_dirty = True

    def _remove_lanes(self, rows: np.ndarray) -> None:
        for r in rows:
            b = self.bucket_of[r]
            if b < 0:
                continue
            n = self.counts[b]
            lane = self.lanes[b, :n]
            hit = np.nonzero(lane == r)[0]
            if hit.size:
                i = hit[0]
                lane[i] = lane[n - 1]
                self.lanes[b, n - 1] = -1
                self.counts[b] = n - 1
                if self.counts[b] == 0:
                    self.alloc.purge([int(b)])
        self.bucket_of[rows] = -1

    def on_delete(self, rows: np.ndarray) -> None:
        rows = np.asarray(rows, np.int64)
        if rows.size == 0:
            return
        self._remove_lanes(rows)
        self._sorted_dirty = True

    def rebuild(self, col: np.ndarray, valid: np.ndarray) -> None:
        """Recreate from a full column (restore path)."""
        self.alloc = SlotAllocator(self.capacity,
                                   name=self.alloc.name)
        self.lanes = np.full((self.capacity, 4), -1, np.int32)
        self.counts[:] = 0
        self.bucket_of[:] = -1
        rows = np.nonzero(valid)[0]
        if rows.size:
            self.on_write(rows, np.asarray(col)[rows])
        self._sorted_dirty = True

    # -- probes ------------------------------------------------------------
    def probe_eq(self, values: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """values [B] -> (candidates [B, K] int32 row ids padded -1,
        lane-valid [B, K] bool). One allocator lookup + one gather."""
        values = np.asarray(values, self.dtype)
        valid = np.ones(values.shape[0], bool)
        buckets = self.alloc.slots_for(self._key_cols(values), valid,
                                       lookup_only=True)
        safe = np.clip(buckets, 0, self.capacity - 1)
        cand = self.lanes[safe]                       # [B, K]
        lane_ok = cand >= 0
        lane_ok[buckets < 0] = False
        cand = np.where(lane_ok, cand, -1)
        return cand.astype(np.int32), lane_ok

    def rows_eq(self, value) -> np.ndarray:
        cand, ok = self.probe_eq(np.asarray([value], self.dtype))
        return cand[0][ok[0]].astype(np.int64)

    def _ensure_sorted(self, valid_mask: np.ndarray) -> None:
        if not self._sorted_dirty and self._sorted_vals is not None:
            return
        rows = np.nonzero(valid_mask & (self.bucket_of >= 0))[0]
        vals = self.shadow[rows]
        order = np.argsort(vals, kind="stable")
        self._sorted_vals = vals[order]
        self._sorted_rows = rows[order]
        self._sorted_dirty = False

    def rows_range(self, valid_mask: np.ndarray, op: str,
                   value) -> np.ndarray:
        """Rows satisfying `col <op> value` (op in < <= > >=)."""
        self._ensure_sorted(valid_mask)
        bound = np.asarray(value)
        if (np.issubdtype(self.dtype, np.integer)
                and np.issubdtype(bound.dtype, np.floating)):
            # Compare in the value domain: casting a fractional bound to the
            # integer dtype truncates toward zero, which under-approximates
            # strict probes (`v < 27.5` would miss v==27). O(1) exact
            # adjustment: tighten a fractional bound to the adjacent integer
            # (`v < 27.5` == `v <= 27`); out-of-range bounds resolve to
            # all/none rows.
            import math
            fv = float(bound)
            if math.isnan(fv):
                return self._sorted_rows[:0]
            below = op in ("<", "<=")
            if math.isinf(fv):
                everything = below == (fv > 0)
                return self._sorted_rows if everything \
                    else self._sorted_rows[:0]
            b = math.floor(fv) if below else math.ceil(fv)
            if b != fv:
                op = "<=" if below else ">="
            info = np.iinfo(self.dtype)
            if b > info.max:
                return self._sorted_rows if below else self._sorted_rows[:0]
            if b < info.min:
                return self._sorted_rows[:0] if below else self._sorted_rows
            v = np.asarray(b, self.dtype)
        else:
            v = np.asarray(value, self.dtype)
        if op == "<":
            hi = np.searchsorted(self._sorted_vals, v, side="left")
            return self._sorted_rows[:hi]
        if op == "<=":
            hi = np.searchsorted(self._sorted_vals, v, side="right")
            return self._sorted_rows[:hi]
        if op == ">":
            lo = np.searchsorted(self._sorted_vals, v, side="right")
            return self._sorted_rows[lo:]
        if op == ">=":
            lo = np.searchsorted(self._sorted_vals, v, side="left")
            return self._sorted_rows[lo:]
        raise ValueError(op)


# ---------------------------------------------------------------------------
# Condition planning (reference: CollectionExpressionParser's split into
# indexed + exhaustive parts).
# ---------------------------------------------------------------------------

def _refs_table(expr: Expression, table_id: str, table_attrs,
                unqualified_is_table: bool) -> bool:
    for node in walk(expr):
        if isinstance(node, Variable):
            if node.stream_id == table_id:
                return True
            if (unqualified_is_table and node.stream_id is None
                    and node.attribute_name in table_attrs):
                return True
    return False


def _table_var(expr: Expression, table_id: str, table_attrs,
               unqualified_is_table: bool):
    if isinstance(expr, Variable) and (
            expr.stream_id == table_id or
            (unqualified_is_table and expr.stream_id is None
             and expr.attribute_name in table_attrs)):
        return expr
    return None


_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "==": "=="}


class IndexPlan:
    """One indexed conjunct + the residual condition.

    kind 'eq': probe_pos/rhs gives per-stream-row candidate buckets.
    kind 'range': constant-bound range (on-demand path).
    """

    def __init__(self, kind: str, pos: int, op: str, rhs: Expression,
                 residual: Optional[Expression]):
        self.kind = kind
        self.pos = pos
        self.op = op
        self.rhs = rhs
        self.residual = residual


def split_index_condition(cond: Expression, table_id: str, schema,
                          indexed_positions: Sequence[int],
                          unqualified_is_table: bool = False,
                          ) -> Optional[IndexPlan]:
    """Find one `table.attr <op> rhs` conjunct where attr is indexed and rhs
    never references the table; return it + the AND-residual. Equality wins
    over range (hash probe beats sorted scan).

    `unqualified_is_table`: whether bare attribute names resolve to the table
    (on-demand store queries) or to the other side (streaming table ops,
    where unqualified names bind to the query output — reference:
    OnDemandQueryParser vs OutputParser scoping)."""
    table_attrs = set(schema.names)
    conjuncts: List[Expression] = []

    def flatten(e: Expression):
        if isinstance(e, And):
            flatten(e.left)
            flatten(e.right)
        else:
            conjuncts.append(e)

    flatten(cond)
    indexed = set(indexed_positions)
    best: Optional[Tuple[int, int, str, Expression]] = None  # (rank, i, op, rhs)
    for i, c in enumerate(conjuncts):
        if not isinstance(c, Compare):
            continue
        for lhs, rhs, op in ((c.left, c.right, c.operator),
                             (c.right, c.left, _FLIP.get(c.operator))):
            if op is None:
                continue
            v = _table_var(lhs, table_id, table_attrs, unqualified_is_table)
            if v is None:
                continue
            pos = schema.position(v.attribute_name)
            if pos not in indexed:
                continue
            if _refs_table(rhs, table_id, table_attrs, unqualified_is_table):
                continue
            if op == "==":
                rank = 0
            elif op in ("<", "<=", ">", ">="):
                rank = 1
            else:
                continue
            if best is None or rank < best[0]:
                best = (rank, i, op, rhs)
                if rank == 0:
                    break
        if best is not None and best[0] == 0:
            break
    if best is None:
        return None
    rank, i, op, rhs = best
    rest = conjuncts[:i] + conjuncts[i + 1:]
    residual: Optional[Expression] = None
    for r in rest:
        residual = r if residual is None else And(residual, r)
    v = _table_var(conjuncts[i].left, table_id, table_attrs,
                   unqualified_is_table) or \
        _table_var(conjuncts[i].right, table_id, table_attrs,
                   unqualified_is_table)
    pos = schema.position(v.attribute_name)
    kind = "eq" if op == "==" else "range"
    if kind == "range" and not isinstance(rhs, Constant):
        # batched range probes degrade to the dense path; only the
        # constant-bound (on-demand) form uses the sorted view
        return None
    return IndexPlan(kind, pos, op, rhs, residual)
