"""Window processors as fixed-capacity columnar buffers.

Reference behavior (what): CORE/query/processor/stream/window/* — sliding and
batch retention policies emitting CURRENT + EXPIRED (+RESET) events, driven by
arrivals and scheduler TIMER ticks (e.g. TimeWindowProcessor.java:132-168,
LengthWindowProcessor.java, LengthBatchWindowProcessor.java,
TimeBatchWindowProcessor.java).

TPU-native design (how): each window keeps a struct-of-arrays buffer of
capacity C.  Every event admitted to the window gets a monotone global
sequence number `add_seq`; when it leaves it gets `expire_seq`.  One `process`
call consumes a whole micro-batch and emits an output `Rows` block where every
row carries its own sequence number, so downstream aggregation can recover the
exact per-event ordering (expired-before-current interleavings included)
without any per-event control flow.  Scan-style aggregators (min/max/
distinctCount over a sliding window) receive an `alive[i, c]` exposure mask:
entry c is visible to output row i iff add_seq[c] <= seq[i] < expire_seq[c].

Buffers are recompacted (gather) once per batch instead of ring-indexed per
event — O(C+B) vector work that XLA fuses well.
"""
from __future__ import annotations

from typing import Any, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..query_api.expression import Constant
from . import event as ev

BIG_SEQ = jnp.iinfo(jnp.int64).max // 4  # "never expired"
NO_WAKEUP = jnp.iinfo(jnp.int64).max // 4


class Rows(NamedTuple):
    """Ordered operator rows flowing between window -> selector -> output."""

    ts: Any     # i64[B]
    kind: Any   # i32[B] CURRENT/EXPIRED/TIMER/RESET
    valid: Any  # bool[B]
    seq: Any    # i64[B] global order
    gslot: Any  # i32[B] group-by slot (-1 none)
    cols: Tuple[Any, ...]

    @property
    def capacity(self):
        return self.ts.shape[0]


class Buffer(NamedTuple):
    """Columnar window contents."""

    ts: Any          # i64[C] original event ts
    add_seq: Any     # i64[C]
    expire_seq: Any  # i64[C] BIG_SEQ if still in window
    expire_ts: Any   # i64[C] scheduled wall expiry (time windows) else BIG
    alive: Any       # bool[C]
    gslot: Any       # i32[C]
    cols: Tuple[Any, ...]

    @property
    def capacity(self):
        return self.ts.shape[0]


def empty_buffer(schema: ev.Schema, capacity: int) -> Buffer:
    cols = tuple(
        jnp.full((capacity,), ev.default_value(t), dtype=d)
        for t, d in zip(schema.types, schema.dtypes)
    )
    big = jnp.full((capacity,), BIG_SEQ, jnp.int64)
    return Buffer(
        ts=jnp.zeros((capacity,), jnp.int64),
        add_seq=big,
        expire_seq=big,
        expire_ts=big,
        alive=jnp.zeros((capacity,), jnp.bool_),
        gslot=jnp.full((capacity,), -1, jnp.int32),
        cols=cols,
    )


def _gather_rows(rows: Rows, idx, valid):
    return Rows(
        ts=rows.ts[idx], kind=rows.kind[idx],
        valid=jnp.logical_and(rows.valid[idx], valid),
        seq=rows.seq[idx], gslot=rows.gslot[idx],
        cols=tuple(c[idx] for c in rows.cols),
    )


def sort_rows(rows: Rows) -> Rows:
    """Stable order by (valid desc, seq asc): invalid rows pushed to the end."""
    key = jnp.where(rows.valid, rows.seq, BIG_SEQ)
    idx = jnp.argsort(key, stable=True)
    return _gather_rows(rows, idx, jnp.ones_like(rows.valid)[idx])


def concat_rows(a: Rows, b: Rows) -> Rows:
    return Rows(
        ts=jnp.concatenate([a.ts, b.ts]),
        kind=jnp.concatenate([a.kind, b.kind]),
        valid=jnp.concatenate([a.valid, b.valid]),
        seq=jnp.concatenate([a.seq, b.seq]),
        gslot=jnp.concatenate([a.gslot, b.gslot]),
        cols=tuple(jnp.concatenate([x, y]) for x, y in zip(a.cols, b.cols)),
    )


class WindowOutput(NamedTuple):
    rows: Rows
    buffer: Optional[Buffer]      # post-state buffer (exposure source)
    next_wakeup: Any              # i64 scalar, NO_WAKEUP if none


# ---------------------------------------------------------------------------


class WindowProcessor:
    """Base: subclasses are pure — state is an explicit pytree."""

    name = "?"
    needs_timer = False
    # True for batch windows that emit RESET rows (epoch flushes) — the
    # sharded keyed path excludes them: a RESET resets ALL selector slots
    # on whichever device sees it, violating the single-writer merge
    emits_reset = False

    def __init__(self, schema: ev.Schema, params: List[Constant],
                 batch_capacity: int, capacity_hint: int = 1024):
        self.schema = schema
        self.batch_capacity = batch_capacity
        self.capacity_hint = capacity_hint

    # -- static description ---------------------------------------------------
    @property
    def out_capacity(self) -> int:
        raise NotImplementedError

    def init_state(self):
        raise NotImplementedError

    def process(self, state, rows: Rows, now) -> Tuple[Any, WindowOutput]:
        raise NotImplementedError

    def current_buffer(self, state) -> Optional[Buffer]:
        """Current window contents for on-demand reads/joins (reference:
        FindableProcessor.find).  Works for every window whose state leads
        with its Buffer."""
        if isinstance(state, tuple) and state and isinstance(state[0], Buffer):
            return state[0]
        return None


def _param_int(params, i, default=None):
    from ..exceptions import CompileError
    if i >= len(params):
        if default is not None:
            return default
        raise CompileError("missing window parameter")
    p = params[i]
    if not isinstance(p, Constant):
        raise CompileError("window parameters must be constants")
    return int(p.value)


class NoWindow(WindowProcessor):
    """Pass-through when the query has no window handler.

    `compact` (default True) moves valid rows to the front via sort_rows;
    the mesh-sharded plain path disables it so output rows stay aligned to
    input rows on every device and merge with a psum (planner
    _shard_plain_step) — valid rows are already in input order either way.
    """

    name = "(none)"
    compact = True

    @property
    def out_capacity(self):
        return self.batch_capacity

    def init_state(self):
        return jnp.asarray(0, jnp.int64)  # seq counter

    def process(self, state, rows: Rows, now):
        seq0 = state
        n = rows.capacity
        is_cur = jnp.logical_and(rows.valid, rows.kind == ev.CURRENT)
        ord_ = jnp.cumsum(is_cur.astype(jnp.int64)) - 1
        seq = jnp.where(is_cur, seq0 + ord_, BIG_SEQ)
        out = Rows(rows.ts, rows.kind, is_cur, seq, rows.gslot, rows.cols)
        nseq = seq0 + jnp.sum(is_cur.astype(jnp.int64))
        return nseq, WindowOutput(sort_rows(out) if self.compact else out,
                                  None, jnp.asarray(NO_WAKEUP, jnp.int64))


class PassAllWindow(WindowProcessor):
    """Pass-through for queries reading a named window (reference:
    CORE/window/Window.java:65 — the window publishes CURRENT+EXPIRED events
    to subscribing queries, which must not re-window them).  Both kinds are
    forwarded with fresh sequence numbers so the selector's signed
    aggregation (add on CURRENT, subtract on EXPIRED) sees them in order."""

    name = "(named-window input)"

    @property
    def out_capacity(self):
        return self.batch_capacity

    def init_state(self):
        return jnp.asarray(0, jnp.int64)  # seq counter

    def process(self, state, rows: Rows, now):
        seq0 = state
        is_data = jnp.logical_and(
            rows.valid,
            jnp.logical_or(rows.kind == ev.CURRENT, rows.kind == ev.EXPIRED))
        ord_ = jnp.cumsum(is_data.astype(jnp.int64)) - 1
        seq = jnp.where(is_data, seq0 + ord_, BIG_SEQ)
        out = Rows(rows.ts, rows.kind, is_data, seq, rows.gslot, rows.cols)
        nseq = seq0 + jnp.sum(is_data.astype(jnp.int64))
        return nseq, WindowOutput(sort_rows(out), None,
                                  jnp.asarray(NO_WAKEUP, jnp.int64))


class LengthWindow(WindowProcessor):
    """Sliding length window (reference: LengthWindowProcessor).

    On each arrival: if full, the oldest entry is emitted as EXPIRED just
    before the CURRENT event.  expired ts keeps the original event ts.
    """

    name = "length"

    def __init__(self, schema, params, batch_capacity, capacity_hint=1024):
        super().__init__(schema, params, batch_capacity)
        self.length = _param_int(params, 0)

    @property
    def out_capacity(self):
        return 2 * self.batch_capacity

    def init_state(self):
        return (empty_buffer(self.schema, self.length),
                jnp.asarray(0, jnp.int64))

    def process(self, state, rows: Rows, now):
        buf, seq0 = state
        C = self.length
        B = rows.capacity
        is_cur = jnp.logical_and(rows.valid, rows.kind == ev.CURRENT)
        ncur = jnp.sum(is_cur.astype(jnp.int64))

        # order arrivals among themselves: k = 0..ncur-1
        k = jnp.cumsum(is_cur.astype(jnp.int64)) - 1   # [B]

        # combined virtual sequence: old alive entries (by add_seq) then
        # currents, BOTH compacted to the front of their region; virtual index
        # v maps to physical position v (old region) or C + v - count0.
        old_key = jnp.where(buf.alive, buf.add_seq, BIG_SEQ)
        old_order = jnp.argsort(old_key)               # [C] alive first by age
        count0 = jnp.sum(buf.alive.astype(jnp.int64))
        cur_order = jnp.argsort(jnp.where(is_cur, k, BIG_SEQ))  # [B]

        comb_ts = jnp.concatenate([buf.ts[old_order], rows.ts[cur_order]])
        comb_gslot = jnp.concatenate([buf.gslot[old_order],
                                      rows.gslot[cur_order]])
        comb_cols = tuple(jnp.concatenate([bc[old_order], rc[cur_order]])
                          for bc, rc in zip(buf.cols, rows.cols))
        cur_addseq = jnp.where(is_cur, seq0 + 2 * k + 1, BIG_SEQ)
        comb_addseq = jnp.concatenate([buf.add_seq[old_order],
                                       cur_addseq[cur_order]])

        def phys(v):
            return jnp.where(v < count0, v, C + v - count0)

        # the k-th arrival evicts virtual entry (count0 + k - length) (if >= 0)
        evict_pos = (count0 + k - C)
        has_evict = jnp.logical_and(is_cur, evict_pos >= 0)
        safe_pos = jnp.clip(phys(evict_pos), 0, C + B - 1).astype(jnp.int32)

        exp_rows = Rows(
            ts=comb_ts[safe_pos],
            kind=jnp.full((B,), ev.EXPIRED, jnp.int32),
            valid=has_evict,
            seq=seq0 + 2 * k,           # expired emitted just before current k
            gslot=comb_gslot[safe_pos],
            cols=tuple(c[safe_pos] for c in comb_cols),
        )
        cur_rows = Rows(
            ts=rows.ts, kind=jnp.full((B,), ev.CURRENT, jnp.int32),
            valid=is_cur, seq=seq0 + 2 * k + 1, gslot=rows.gslot,
            cols=rows.cols,
        )
        out = sort_rows(concat_rows(exp_rows, cur_rows))

        # new buffer = last `length` of combined valid entries
        total = count0 + ncur
        start = jnp.maximum(total - C, 0)
        take = jnp.arange(C, dtype=jnp.int64) + start        # [C] virtual
        tvalid = take < total
        tpos = jnp.clip(phys(take), 0, C + B - 1).astype(jnp.int32)
        # expire_seq of evicted entries: entry at combined pos p (p < total-C
        # after the batch) was evicted by arrival k = p - count0 + C
        nbuf = Buffer(
            ts=comb_ts[tpos],
            add_seq=comb_addseq[tpos],
            expire_seq=jnp.where(tvalid, BIG_SEQ, BIG_SEQ),
            expire_ts=jnp.full((C,), BIG_SEQ, jnp.int64),
            alive=tvalid,
            gslot=comb_gslot[tpos],
            cols=tuple(c[tpos] for c in comb_cols),
        )
        nseq = seq0 + 2 * ncur
        return ((nbuf, nseq),
                WindowOutput(out, nbuf, jnp.asarray(NO_WAKEUP, jnp.int64)))


class TimeWindow(WindowProcessor):
    """Sliding time window (reference: TimeWindowProcessor.java:86).

    Entries expire `t` ms after arrival; EXPIRED rows carry ts = expiry time
    (matching the reference, which pre-stamps the cloned expired event).
    Expiry is driven both by arrivals and by TIMER rows; `next_wakeup`
    reports the earliest pending expiry for the host scheduler.
    """

    name = "time"
    needs_timer = True

    def __init__(self, schema, params, batch_capacity, capacity_hint=2048):
        super().__init__(schema, params, batch_capacity)
        self.time_ms = _param_int(params, 0)
        self.capacity = max(capacity_hint, 2 * batch_capacity)

    @property
    def out_capacity(self):
        return self.batch_capacity + self.capacity

    def init_state(self):
        return (empty_buffer(self.schema, self.capacity),
                jnp.asarray(0, jnp.int64))

    def process(self, state, rows: Rows, now):
        buf, seq0 = state
        C = self.capacity
        B = rows.capacity
        t = self.time_ms

        is_cur = jnp.logical_and(rows.valid, rows.kind == ev.CURRENT)
        ncur = jnp.sum(is_cur.astype(jnp.int64))

        # ordering: merge (existing entries' expiries <= now) and arrivals by
        # time; seq = 2*rank within this batch via sorting a combined key.
        # Assign arrivals local order first.
        k = jnp.cumsum(is_cur.astype(jnp.int64)) - 1

        # Candidate expiries from the old buffer
        exp_due = jnp.logical_and(buf.alive, buf.expire_ts <= now)

        # Build combined "emission" list: expired entries (key=expire_ts, pri 0)
        # + current arrivals (key=ts, pri 1)
        em_ts = jnp.concatenate([buf.expire_ts, rows.ts])
        em_pri = jnp.concatenate([jnp.zeros((C,), jnp.int64),
                                  jnp.ones((B,), jnp.int64)])
        em_valid = jnp.concatenate([exp_due, is_cur])
        em_key = jnp.where(em_valid, em_ts * 2 + em_pri, BIG_SEQ)
        order = jnp.argsort(em_key, stable=True)      # [C+B]
        rank = jnp.zeros((C + B,), jnp.int64).at[order].set(
            jnp.arange(C + B, dtype=jnp.int64))
        seqs = seq0 + rank

        exp_rows = Rows(
            ts=buf.expire_ts,               # reference stamps expiry time
            kind=jnp.full((C,), ev.EXPIRED, jnp.int32),
            valid=exp_due,
            seq=seqs[:C],
            gslot=buf.gslot,
            cols=buf.cols,
        )
        cur_rows = Rows(
            ts=rows.ts, kind=jnp.full((B,), ev.CURRENT, jnp.int32),
            valid=is_cur, seq=seqs[C:], gslot=rows.gslot, cols=rows.cols,
        )
        out = sort_rows(concat_rows(exp_rows, cur_rows))

        # new buffer = (old alive minus expired) + arrivals; compact by age
        keep_old = jnp.logical_and(buf.alive, jnp.logical_not(exp_due))
        cand_ts = jnp.concatenate([buf.ts, rows.ts])
        cand_add = jnp.concatenate([buf.add_seq, seqs[C:]])
        cand_expts = jnp.concatenate([buf.expire_ts, rows.ts + t])
        cand_gslot = jnp.concatenate([buf.gslot, rows.gslot])
        cand_cols = tuple(jnp.concatenate([bc, rc])
                          for bc, rc in zip(buf.cols, rows.cols))
        cand_valid = jnp.concatenate([keep_old, is_cur])
        cand_key = jnp.where(cand_valid, cand_add, BIG_SEQ)
        corder = jnp.argsort(cand_key)                # oldest first
        total = jnp.sum(cand_valid.astype(jnp.int64))
        # overflow: drop OLDEST if total > C (keep most recent C)
        drop = jnp.maximum(total - C, 0)
        sel = jnp.clip(jnp.arange(C, dtype=jnp.int64) + drop, 0, C + B - 1)
        pos = corder[sel.astype(jnp.int32)]
        svalid = (jnp.arange(C, dtype=jnp.int64) + drop) < total
        nbuf = Buffer(
            ts=cand_ts[pos], add_seq=jnp.where(svalid, cand_add[pos], BIG_SEQ),
            expire_seq=jnp.full((C,), BIG_SEQ, jnp.int64),
            expire_ts=jnp.where(svalid, cand_expts[pos], BIG_SEQ),
            alive=svalid, gslot=cand_gslot[pos],
            cols=tuple(c[pos] for c in cand_cols),
        )
        nseq = seq0 + rank.max() + 1
        nseq = jnp.where(jnp.any(em_valid), nseq, seq0)
        wake = jnp.min(jnp.where(nbuf.alive, nbuf.expire_ts, NO_WAKEUP))
        return ((nbuf, nseq), WindowOutput(out, nbuf, wake))


class LengthBatchWindow(WindowProcessor):
    emits_reset = True
    """Tumbling length batch (reference: LengthBatchWindowProcessor).

    Arrivals accumulate silently; when `n` have gathered the whole batch is
    emitted as CURRENT, preceded by the previous batch as EXPIRED and a RESET
    row separating them.
    """

    name = "lengthBatch"

    def __init__(self, schema, params, batch_capacity, capacity_hint=1024):
        super().__init__(schema, params, batch_capacity)
        self.length = _param_int(params, 0)

    @property
    def out_capacity(self):
        # worst case: every arrival completes a batch of size 1
        n = self.length
        flushes = self.batch_capacity // n + 1
        return 2 * self.batch_capacity + 2 * n + flushes

    def init_state(self):
        # pending buffer (filling), previous batch buffer (for EXPIRED replay)
        return (empty_buffer(self.schema, self.length),
                empty_buffer(self.schema, self.length),
                jnp.asarray(0, jnp.int64))

    def process(self, state, rows: Rows, now):
        pend, prev, seq0 = state
        n = self.length
        B = rows.capacity
        is_cur = jnp.logical_and(rows.valid, rows.kind == ev.CURRENT)
        ncur = jnp.sum(is_cur.astype(jnp.int64))
        fill0 = jnp.sum(pend.alive.astype(jnp.int64))

        # global arrival index g = fill0 + k (k = order within batch)
        k = jnp.cumsum(is_cur.astype(jnp.int64)) - 1
        g = fill0 + k
        batch_idx = g // n           # which tumble this arrival belongs to
        nflush = (fill0 + ncur) // n  # completed batches this step

        # ---- output construction -------------------------------------------
        # seq layout per flush f (0-based among this step's flushes):
        #   expired rows of batch f-1+prev : seq = seq0 + f*(2n+2) + [0..n)
        #   reset row                      : seq0 + f*(2n+2) + n
        #   current rows of batch f        : seq0 + f*(2n+2) + n+1 + [0..n)
        span = 2 * n + 2

        # currents of flushed batches: arrival with batch_idx < nflush
        flushed_cur = jnp.logical_and(is_cur, batch_idx < nflush)
        pos_in_batch = g % n
        cur_seq = seq0 + batch_idx * span + n + 1 + pos_in_batch
        # pending entries flushed in flush 0
        pend_flush = jnp.logical_and(pend.alive, nflush > 0)
        pend_rank = jnp.cumsum(pend.alive.astype(jnp.int64)) - 1
        pend_seq = seq0 + 0 * span + n + 1 + pend_rank

        cur_rows = Rows(
            ts=jnp.concatenate([pend.ts, rows.ts]),
            kind=jnp.full((n + B,), ev.CURRENT, jnp.int32),
            valid=jnp.concatenate([pend_flush, flushed_cur]),
            seq=jnp.concatenate([pend_seq, cur_seq]),
            gslot=jnp.concatenate([pend.gslot, rows.gslot]),
            cols=tuple(jnp.concatenate([pc, rc])
                       for pc, rc in zip(pend.cols, rows.cols)),
        )

        # expired rows: prev batch replayed at flush 0; batch f-1 replayed at
        # flush f.  prev buffer: ranks 0..n-1.
        prev_rank = jnp.cumsum(prev.alive.astype(jnp.int64)) - 1
        prev_valid = jnp.logical_and(prev.alive, nflush > 0)
        prev_seq = seq0 + prev_rank
        # arrivals replayed as expired at flush (batch_idx+1) if batch_idx+1 < nflush
        arr_exp_valid = jnp.logical_and(is_cur, batch_idx + 1 < nflush)
        arr_exp_seq = seq0 + (batch_idx + 1) * span + pos_in_batch
        # pending entries (flushed at 0) replayed as expired at flush 1
        pend_exp_valid = jnp.logical_and(pend.alive, nflush > 1)
        pend_exp_seq = seq0 + 1 * span + pend_rank

        exp_rows = Rows(
            ts=jnp.concatenate([prev.ts, pend.ts, rows.ts]),
            kind=jnp.full((2 * n + B,), ev.EXPIRED, jnp.int32),
            valid=jnp.concatenate([prev_valid, pend_exp_valid, arr_exp_valid]),
            seq=jnp.concatenate([prev_seq, pend_exp_seq, arr_exp_seq]),
            gslot=jnp.concatenate([prev.gslot, pend.gslot, rows.gslot]),
            cols=tuple(jnp.concatenate([a, b, c]) for a, b, c in
                       zip(prev.cols, pend.cols, rows.cols)),
        )

        # reset rows, one per flush
        F = B // n + 1
        f = jnp.arange(F, dtype=jnp.int64)
        reset_rows = Rows(
            ts=jnp.full((F,), 0, jnp.int64) + now,
            kind=jnp.full((F,), ev.RESET, jnp.int32),
            valid=f < nflush,
            seq=seq0 + f * span + n,
            gslot=jnp.full((F,), -1, jnp.int32),
            cols=tuple(jnp.full((F,), ev.default_value(t_), d)
                       for t_, d in zip(self.schema.types, self.schema.dtypes)),
        )

        out = sort_rows(concat_rows(concat_rows(exp_rows, cur_rows), reset_rows))

        # ---- new state ------------------------------------------------------
        # pending' = arrivals with batch_idx == nflush (+ old pending if no flush)
        np_old_valid = jnp.logical_and(pend.alive, nflush == 0)
        np_arr_valid = jnp.logical_and(is_cur, batch_idx == nflush)
        cand_valid = jnp.concatenate([np_old_valid, np_arr_valid])
        cand_rank_src = jnp.concatenate([pend_rank, pos_in_batch])
        cand_ts = jnp.concatenate([pend.ts, rows.ts])
        cand_gslot = jnp.concatenate([pend.gslot, rows.gslot])
        cand_cols = tuple(jnp.concatenate([pc, rc])
                          for pc, rc in zip(pend.cols, rows.cols))
        # scatter into fresh pending by rank
        npend = empty_buffer(self.schema, n)
        tgt = jnp.where(cand_valid, cand_rank_src, n).astype(jnp.int32)
        def scat(dst, src):
            return dst.at[tgt].set(src, mode="drop")
        npend = Buffer(
            ts=scat(npend.ts, cand_ts),
            add_seq=npend.add_seq,
            expire_seq=npend.expire_seq,
            expire_ts=npend.expire_ts,
            alive=jnp.zeros((n,), jnp.bool_).at[tgt].set(cand_valid, mode="drop"),
            gslot=scat(npend.gslot, cand_gslot),
            cols=tuple(scat(c0, c) for c0, c in zip(npend.cols, cand_cols)),
        )

        # prev' = last flushed batch (batch nflush-1) if any flush else prev
        lb_old_valid = jnp.logical_and(pend.alive, nflush == 1)
        lb_arr_valid = jnp.logical_and(is_cur, batch_idx == nflush - 1)
        lbc_valid = jnp.concatenate([lb_old_valid, lb_arr_valid])
        nprev0 = empty_buffer(self.schema, n)
        tgt2 = jnp.where(lbc_valid, cand_rank_src, n).astype(jnp.int32)
        def scat2(dst, src):
            return dst.at[tgt2].set(src, mode="drop")
        flushed_prev = Buffer(
            ts=scat2(nprev0.ts, cand_ts),
            add_seq=nprev0.add_seq, expire_seq=nprev0.expire_seq,
            expire_ts=nprev0.expire_ts,
            alive=jnp.zeros((n,), jnp.bool_).at[tgt2].set(lbc_valid, mode="drop"),
            gslot=scat2(nprev0.gslot, cand_gslot),
            cols=tuple(scat2(c0, c) for c0, c in zip(nprev0.cols, cand_cols)),
        )
        nprev = jax.tree.map(
            lambda new, old: jnp.where(nflush > 0, new, old), flushed_prev, prev)

        nseq = seq0 + nflush * span
        return ((npend, nprev, nseq),
                WindowOutput(out, None, jnp.asarray(NO_WAKEUP, jnp.int64)))


class TimeBatchWindow(WindowProcessor):
    emits_reset = True
    """Tumbling time batch (reference: TimeBatchWindowProcessor).

    Time is divided into [start + k*t, start + (k+1)*t) slices; at each slice
    boundary the gathered events are emitted as CURRENT (preceded by the
    previous slice as EXPIRED + RESET).  Driven by arrivals and TIMER rows.
    """

    name = "timeBatch"
    needs_timer = True

    def __init__(self, schema, params, batch_capacity, capacity_hint=2048):
        super().__init__(schema, params, batch_capacity)
        self.time_ms = _param_int(params, 0)
        self.capacity = max(capacity_hint, 2 * batch_capacity)

    @property
    def out_capacity(self):
        return 2 * self.capacity + 2 * self.batch_capacity + 2

    def init_state(self):
        return (
            empty_buffer(self.schema, self.capacity),   # pending slice
            empty_buffer(self.schema, self.capacity),   # previous slice
            jnp.asarray(-1, jnp.int64),                 # slice start ts (-1 unset)
            jnp.asarray(0, jnp.int64),                  # seq counter
        )

    def process(self, state, rows: Rows, now):
        pend, prev, start0, seq0 = state
        t = self.time_ms
        C = self.capacity
        B = rows.capacity

        is_cur = jnp.logical_and(rows.valid, rows.kind == ev.CURRENT)
        any_cur = jnp.any(is_cur)
        first_ts = jnp.min(jnp.where(is_cur, rows.ts, BIG_SEQ))
        start = jnp.where(start0 >= 0, start0, first_ts)

        # how many slice boundaries passed by `now`?
        elapsed = jnp.maximum(now - start, 0)
        nflush = jnp.where(start0 >= 0,
                           elapsed // t,
                           jnp.maximum((now - first_ts), 0) // t)
        nflush = jnp.where(jnp.logical_or(start0 >= 0, any_cur), nflush, 0)
        flush = nflush > 0
        # NOTE: if multiple slice boundaries pass in one gap, intermediate
        # empty slices collapse — matching observable outputs (empty batches
        # emit nothing).
        new_start = jnp.where(flush, start + nflush * t, start)

        # arrivals belong to pending slice if ts < boundary else to the new one
        boundary = start + jnp.where(flush, nflush, 1) * t
        to_pend = jnp.logical_and(is_cur, rows.ts < boundary)
        to_next = jnp.logical_and(is_cur, jnp.logical_not(to_pend))

        # flushed slice contents = pending + arrivals with ts < boundary
        pend_rank = jnp.cumsum(pend.alive.astype(jnp.int64)) - 1
        npend_fill = jnp.sum(pend.alive.astype(jnp.int64))
        arr_rank = npend_fill + jnp.cumsum(to_pend.astype(jnp.int64)) - 1

        # seq layout: expired prev [0..C), reset C, current flushed [C+1 ...)
        exp_rows = Rows(
            ts=prev.ts, kind=jnp.full((C,), ev.EXPIRED, jnp.int32),
            valid=jnp.logical_and(prev.alive, flush),
            seq=seq0 + jnp.cumsum(prev.alive.astype(jnp.int64)) - 1,
            gslot=prev.gslot, cols=prev.cols,
        )
        reset_rows = Rows(
            ts=jnp.full((1,), 0, jnp.int64) + now,
            kind=jnp.full((1,), ev.RESET, jnp.int32),
            valid=jnp.reshape(flush, (1,)),
            seq=jnp.full((1,), seq0 + C, jnp.int64),
            gslot=jnp.full((1,), -1, jnp.int32),
            cols=tuple(jnp.full((1,), ev.default_value(t_), d)
                       for t_, d in zip(self.schema.types, self.schema.dtypes)),
        )
        cur_rows = Rows(
            ts=jnp.concatenate([pend.ts, rows.ts]),
            kind=jnp.full((C + B,), ev.CURRENT, jnp.int32),
            valid=jnp.concatenate([
                jnp.logical_and(pend.alive, flush),
                jnp.logical_and(to_pend, flush)]),
            seq=seq0 + C + 1 + jnp.concatenate([pend_rank, arr_rank]),
            gslot=jnp.concatenate([pend.gslot, rows.gslot]),
            cols=tuple(jnp.concatenate([pc, rc])
                       for pc, rc in zip(pend.cols, rows.cols)),
        )
        out = sort_rows(concat_rows(concat_rows(exp_rows, cur_rows), reset_rows))

        # new pending: if flush -> arrivals beyond boundary; else pending+arrivals
        keep_pend = jnp.logical_and(pend.alive, jnp.logical_not(flush))
        arr_keep = jnp.where(flush, to_next, to_pend)
        base_fill = jnp.sum(keep_pend.astype(jnp.int64))
        cand_valid = jnp.concatenate([keep_pend, arr_keep])
        cand_rank = jnp.concatenate([
            pend_rank,
            base_fill + jnp.cumsum(arr_keep.astype(jnp.int64)) - 1])
        cand_ts = jnp.concatenate([pend.ts, rows.ts])
        cand_gslot = jnp.concatenate([pend.gslot, rows.gslot])
        cand_cols = tuple(jnp.concatenate([pc, rc])
                          for pc, rc in zip(pend.cols, rows.cols))
        tgt = jnp.where(cand_valid, cand_rank, C).astype(jnp.int32)
        fresh = empty_buffer(self.schema, C)
        npend = Buffer(
            ts=fresh.ts.at[tgt].set(cand_ts, mode="drop"),
            add_seq=fresh.add_seq, expire_seq=fresh.expire_seq,
            expire_ts=fresh.expire_ts,
            alive=jnp.zeros((C,), jnp.bool_).at[tgt].set(cand_valid, mode="drop"),
            gslot=fresh.gslot.at[tgt].set(cand_gslot, mode="drop"),
            cols=tuple(f.at[tgt].set(c, mode="drop")
                       for f, c in zip(fresh.cols, cand_cols)),
        )

        # new prev: flushed slice if flush else old prev
        ftgt = jnp.where(
            jnp.concatenate([pend.alive, to_pend]),
            jnp.concatenate([pend_rank, arr_rank]), C).astype(jnp.int32)
        fprev = Buffer(
            ts=fresh.ts.at[ftgt].set(cand_ts, mode="drop"),
            add_seq=fresh.add_seq, expire_seq=fresh.expire_seq,
            expire_ts=fresh.expire_ts,
            alive=jnp.zeros((C,), jnp.bool_).at[ftgt].set(
                jnp.concatenate([pend.alive, to_pend]), mode="drop"),
            gslot=fresh.gslot.at[ftgt].set(cand_gslot, mode="drop"),
            cols=tuple(f.at[ftgt].set(c, mode="drop")
                       for f, c in zip(fresh.cols, cand_cols)),
        )
        nprev = jax.tree.map(lambda a, b: jnp.where(flush, a, b), fprev, prev)

        nseq = jnp.where(flush, seq0 + 2 * C + B + 2, seq0)
        nstart = jnp.where(jnp.logical_or(start0 >= 0, any_cur), new_start,
                           jnp.asarray(-1, jnp.int64))
        wake = jnp.where(nstart >= 0, nstart + t, NO_WAKEUP)
        return ((npend, nprev, nstart, nseq), WindowOutput(out, None, wake))


# ---------------------------------------------------------------------------

WINDOW_TYPES = {
    "length": LengthWindow,
    "time": TimeWindow,
    "lengthBatch": LengthBatchWindow,
    "timeBatch": TimeBatchWindow,
}

from . import window_ext as _window_ext  # noqa: E402  (registry extension)
_window_ext.register(WINDOW_TYPES)
from . import window_expr as _window_expr  # noqa: E402
_window_expr.register(WINDOW_TYPES)


def create_window(name: str, schema: ev.Schema, params, batch_capacity: int,
                  capacity_hint: int = 2048) -> WindowProcessor:
    if name not in WINDOW_TYPES:
        from ..exceptions import CompileError
        raise CompileError(f"unknown window type {name!r}; "
                           f"available: {sorted(WINDOW_TYPES)}")
    return WINDOW_TYPES[name](schema, params, batch_capacity,
                              capacity_hint=capacity_hint)
