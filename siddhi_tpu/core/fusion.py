"""Scan-fused multi-batch stepping: K device steps per dispatch, one
header fetch (`@fuse(batches='K')`).

Reference behavior (what): none — the reference processes one event at a
time; batching depth is a TPU-native concern.

TPU design (how): PERF.md's phase breakdown shows the engine is
host/tunnel-bound — the device does ~0.2 ms of HBM work per send while
each send pays a fixed ~73-95 ms round-trip plus a blocking emission
fetch.  Fused stepping stacks K staged micro-batches into [K, B]
host arrays, ships them in ONE transfer, and runs the compiled query
step as a `lax.scan` over the leading axis in ONE dispatch:
partition/window/NFA state threads through the scan carry exactly as it
threads through K sequential `jit_step` calls, emissions accumulate into
a [K, cap] block, and a single combined [K, 2] header rides one
`device_get`.  Per-send RTT and dispatch overhead divide by K.

Semantics: a fused query's processing (and therefore its delivery,
table writes, and downstream routing) lags up to K-1 batches until the
stack fills or `flush()` drains it — the same relaxation `@pipeline`
makes for delivery, extended to the step itself.  Partial stacks drain
through the ORIGINAL sequential path, so a flush is byte-identical to
never having fused.  Timer-bearing queries (time/cron windows, absent
patterns) are excluded at wiring time, same rule as `@pipeline`: their
device-computed wake scalar cannot lag.

Paths fused: plain (non-keyed, non-range-partition) single-stream
queries, non-partitioned pattern/sequence queries, join sides — each
wraps the plan's un-jitted step body so fused and sequential execution
run the identical per-batch program — and MESH-SHARDED partitioned
patterns, whose stacks run a lax.scan INSIDE the shard_map
(pattern_planner._shard_fused_step) so the per-dispatch overhead divides
by K per shard.  Keyed-window and unsharded partitioned-pattern paths
fall back to sequential dispatch.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, Tuple

import jax
import numpy as np

from ..observability import tracing as _tracing
from . import event as ev
from .steputil import fuse_step

jnp = jax.numpy


def ineligible_reason(qr, kind: str):
    """Why this runtime cannot fuse (None = eligible).  Static properties
    only; per-batch variation is handled by the stack signature."""
    if kind == "merged":
        # a merge group only admits timer-free, unsharded plain members
        # (optimizer/mqo.py), so the merged body always fuses
        return None
    p = qr.planned
    if kind == "plain":
        if p.needs_timer:
            return "timer-bearing window (time/cron) — wake cannot lag"
        if p.keyed_window:
            return "keyed-window slab path is not fused yet"
        if p.partition_key_fn is not None:
            return "range-partition key derivation is not fused yet"
        if p.raw_step is None:
            return "sharded step has no fusable body"
        return None
    if kind == "pattern":
        if p.timer_step is not None:
            return "absent pattern needs timer wakeups — wake cannot lag"
        if getattr(p, "mesh", None) is not None:
            # sharded partitioned patterns fuse through the shard_map'd
            # scan step (pattern_planner._shard_fused_step)
            if getattr(p, "shard_fused_steps", None):
                return None
            return "sharded pattern step has no fusable body"
        if p.partition_positions:
            return "partitioned pattern grouping is not fused yet"
        if p.step_bodies is None:
            return "sharded pattern step has no fusable body"
        return None
    if kind == "join":
        if p.needs_timer:
            return "timer-bearing join window — wake cannot lag"
        if (p.step_left is not None and p.raw_left is None) or \
                (p.step_right is not None and p.raw_right is None):
            return "sharded join step has no fusable body"
        return None
    return f"unknown runtime kind {kind!r}"


def eligibility(qr, kind: str) -> Dict:
    """Fusion facts for EXPLAIN (observability/explain.py): whether the
    query CAN fuse, whether it IS fusing (and at what K), and — when
    @fuse was requested but wiring skipped it — the concrete exclusion
    reason instead of a log line that scrolled away."""
    reason = ineligible_reason(qr, kind)
    node: Dict = {"eligible": reason is None}
    if reason is not None:
        node["exclusion_reason"] = reason
    fb = getattr(qr, "_fuse", None)
    node["active"] = fb is not None
    if fb is not None:
        node["batches"] = fb.k
    elif getattr(qr, "_fuse_requested", 0):
        node["requested_batches"] = qr._fuse_requested
    return node


class FuseBuffer:
    """Per-query accumulator of staged sends for fused dispatch.

    All entry points run under the query lock (junction dispatch holds
    it), so the buffer needs no lock of its own.  `offer` stacks
    same-signature batches (same input tag + bucket capacity); a
    signature change drains the pending stack sequentially first, so
    cross-batch order within the query is preserved exactly.
    """

    __slots__ = ("qr", "k", "kind", "items", "sig", "bypass", "ingests")

    def __init__(self, qr, k: int, kind: str):
        self.qr = qr
        self.k = max(1, int(k))
        self.kind = kind
        self.items: List[Tuple] = []
        # per-item ingest stamps (junction send-acceptance perf_counter_ns,
        # or None at OFF): a batch's `<query>:e2e` sample must include the
        # time it sat in this stack waiting for the dispatch
        self.ingests: List = []
        self.sig = None
        self.bypass = False

    def offer(self, args: Tuple, staged: ev.StagedBatch, tag) -> bool:
        """Accept a send into the stack.  Returns False when the caller
        must run the sequential path itself (drain re-entry, or an
        attached debugger that expects per-batch breakpoints)."""
        if self.bypass or self.qr.app.__dict__.get("_debugger") is not None:
            return False
        # captured before a signature-change drain(), which resets the
        # runtime's stash while re-processing the OLD stack
        t_in = self.qr.__dict__.get("_ingest_ns")
        sig = (tag, staged.ts.shape[0])
        if self.items and sig != self.sig:
            self.drain()
        self.sig = sig
        self.items.append(args)
        self.ingests.append(t_in)
        if len(self.items) >= self.k:
            self.dispatch()
        return True

    def drain(self) -> None:
        """Deliver a partial stack through the ORIGINAL sequential path
        (flush()/quiesce/signature change): byte-identical to never
        having fused, at sequential cost — partial stacks are rare and a
        scan re-trace per partial length would be a recompile per size."""
        if not self.items:
            return
        items, self.items = self.items, []
        ingests, self.ingests = self.ingests, []
        qr = self.qr
        self.bypass = True
        try:
            for args, t_in in zip(items, ingests):
                qr.__dict__["_ingest_ns"] = t_in
                qr.process_staged(*args)
                # consume the inline-delivery flag HERE (a drain may run
                # from flush()/quiesce with no junction dispatch around
                # it to close e2e) — stack wait is inside the sample
                if qr.__dict__.pop("_e2e_owed", False) and \
                        t_in is not None and qr.app.stats.enabled:
                    qr.app.stats.e2e_latency(
                        qr.name, time.perf_counter_ns() - t_in)
        finally:
            self.bypass = False
            qr.__dict__["_ingest_ns"] = None

    def dispatch(self) -> None:
        """Run the full stack as ONE fused device dispatch."""
        items, self.items = self.items, []
        self.qr.__dict__["_fused_ingests"], self.ingests = self.ingests, []
        qr = self.qr
        stats = qr.app.stats
        k = len(items)
        t0 = time.perf_counter_ns() if stats.enabled else 0
        if _tracing.active() is None:
            _DISPATCH[self.kind](qr, items)
        else:
            with _tracing.span("fused_step", query=qr.name, k=k):
                _DISPATCH[self.kind](qr, items)
        if stats.enabled:
            n = sum(int(a[-2].n) for a in items)
            stats.fused_dispatch(qr.name, k, n,
                                 time.perf_counter_ns() - t0)


def pending(qr) -> int:
    """Batches held in a runtime's fuse stack (0 for unfused runtimes)."""
    fb = getattr(qr, "_fuse", None)
    return len(fb.items) if fb is not None else 0


def drain(qr) -> None:
    """Flush a runtime's partial stack (lifecycle: flush/quiesce/
    shutdown).  Takes the query lock — the producer's offer path runs
    under it too, so a concurrent send can never double-process."""
    fb = getattr(qr, "_fuse", None)
    if fb is None or not fb.items:
        return
    lk = getattr(qr, "_qlock", None)
    if lk is None:
        fb.drain()
        return
    with lk:
        fb.drain()


# ---------------------------------------------------------------------------
# fused step compilation (one per (kind, base body); jit handles K/shape
# specialization).  The cache holds the body so a replan (emission-cap
# growth swaps the plan's bodies) can never alias a recycled id().
# ---------------------------------------------------------------------------

def _fused_fn(qr, kind: str, body: Callable) -> Callable:
    cache: Dict = qr.__dict__.setdefault("_fused_cache", {})
    key = (kind, id(body))
    ent = cache.get(key)
    if ent is not None and ent[0] is body:
        return ent[1]
    adapter = _ADAPTERS[kind](body)
    fn = fuse_step(adapter, owner=f"fused:{qr.name}")
    cache[key] = (body, fn)
    return fn


def _adapt_plain(body):
    def fused_body(carry, x, const):
        ts, kind, valid, cols, gslot, now, pslots = x
        carry, out, _wake = body(carry, ts, kind, valid, cols, gslot,
                                 now, const, pslots)
        return carry, out
    return fused_body


def _adapt_pattern(body):
    def fused_body(carry, x, const):
        cols, ts, sel_idx, key_idx, now = x
        pstate, sel_state, out, _wake = body(
            carry[0], carry[1], cols, ts, sel_idx, key_idx, now, const)
        return (pstate, sel_state), out
    return fused_body


def _adapt_join(body):
    def fused_body(carry, x, const):
        if len(x) == 7:
            # equi-join fast path: per-batch probe (bucket slots or
            # host table candidates) rides the stack
            ts, kind, valid, cols, gslot, probe, now = x
            carry, out, _wake = body(carry, ts, kind, valid, cols,
                                     gslot, probe, const, now)
        else:
            ts, kind, valid, cols, gslot, now = x
            carry, out, _wake = body(carry, ts, kind, valid, cols, gslot,
                                     const, now)
        return carry, out
    return fused_body


def _adapt_merged(body):
    def fused_body(carry, x, const):
        ts, kind, valid, cols, gslots, now, pslots = x
        carry, out, _wake = body(carry, ts, kind, valid, cols, gslots,
                                 now, const, pslots)
        return carry, out
    return fused_body


_ADAPTERS = {"plain": _adapt_plain, "pattern": _adapt_pattern,
             "join": _adapt_join, "merged": _adapt_merged}


# ---------------------------------------------------------------------------
# per-kind dispatch: host slot prep (in arrival order), stack, one fused
# step, unstack + deliver
# ---------------------------------------------------------------------------

def _now_stack(items) -> jax.Array:
    return jnp.asarray(np.asarray([a[-1] for a in items], np.int64))


def _dispatch_plain(qr, items) -> None:
    from . import runtime as _rt
    p = qr.planned
    prep = [qr._slots_for_batch(staged, now) for staged, now in items]
    stack = ev.StackedBatch([staged for staged, _ in items])
    batch = stack.to_device(p.in_schema)
    gslot_k = jnp.asarray(np.stack([np.asarray(g) for g, _ in prep]))
    pslots_k = tuple(
        jnp.asarray(np.stack([np.asarray(ps[j]) for _, ps in prep]))
        for j in range(len(p.pair_allocs)))
    xs = (batch.ts, batch.kind, batch.valid, batch.cols, gslot_k,
          _now_stack(items), pslots_k)
    const = qr.app.in_probe_tables(p.in_deps)
    fn = _fused_fn(qr, "plain", p.raw_step)
    _st, outs = _rt._step_phase(
        qr, lambda: fn(qr.state, xs, const), mult=len(items))
    _rt._rebind_state(qr, _st, mult=len(items))
    _deliver_fused(qr, outs, [now for _, now in items])


def _prepare_pattern(qr, items) -> Tuple[Callable, Tuple, Tuple]:
    """(fused fn, stacked xs, const) for a pattern stack — also the entry
    bench.py's device_loop mode uses to time chip-side throughput with
    device-resident inputs and zero emission fetches."""
    from . import runtime as _rt
    p = qr.planned
    stream_id = items[0][0]
    B = items[0][1].ts.shape[0]
    sels = []
    for _, staged, _ in items:
        if staged.valid.all():
            sels.append(_rt._identity_sel(B))
        else:
            sels.append(np.where(staged.valid,
                                 np.arange(B, dtype=np.int32),
                                 -1)[None, :])
    stack = ev.StackedBatch([staged for _, staged, _ in items])
    # the sequential pattern path ships raw staged columns (np_dtype
    # already matches the device dtypes) — mirror it exactly
    cols_k = tuple(jnp.asarray(c) for c in stack.cols)
    k = len(items)
    xs = (cols_k, jnp.asarray(stack.ts), jnp.asarray(np.stack(sels)),
          jnp.asarray(np.zeros((k, 1), np.int32)), _now_stack(items))
    return (_fused_fn(qr, "pattern", p.step_bodies[stream_id]), xs,
            qr._in_tabs())


def _dispatch_pattern(qr, items) -> None:
    from . import runtime as _rt
    if getattr(qr.planned, "mesh", None) is not None:
        return _dispatch_pattern_sharded(qr, items)
    fn, xs, const = _prepare_pattern(qr, items)
    _st, outs = _rt._step_phase(
        qr, lambda: fn(qr.state, xs, const), mult=len(items))
    _rt._rebind_state(qr, _st, mult=len(items))
    _deliver_fused(qr, outs, [now for _, _, now in items])


def _dispatch_pattern_sharded(qr, items) -> None:
    """Fused dispatch of a MESH-sharded partitioned pattern: each batch
    routes through the key-space router on the host (slot binding,
    liveness touch, dirty marking, per-shard counters — the identical
    bookkeeping the sequential sharded path does), the grouped layouts
    pad to one common [n*Kb, E] shape across the stack, and the whole
    [K, ...] block runs as ONE shard_map'd scan dispatch
    (pattern_planner._shard_fused_step)."""
    p = qr.planned
    stream_id = items[0][0]
    preps = [qr._shard_prep(stream_id, staged, now)
             for _, staged, now in items]
    n = preps[0][0].shape[0]
    Kb = max(ki.shape[1] for ki, _ in preps)
    E = max(s.shape[2] for _, s in preps)
    block = qr.shard_router.block
    k = len(items)
    key_k = np.full((k, n, Kb), block, np.int32)
    sel_k = np.full((k, n, Kb, E), -1, np.int32)
    for i, (ki, s) in enumerate(preps):
        key_k[i, :, :ki.shape[1]] = ki
        sel_k[i, :, :s.shape[1], :s.shape[2]] = s
    stack = ev.StackedBatch([staged for _, staged, _ in items])
    xs = (tuple(jnp.asarray(c) for c in stack.cols),
          jnp.asarray(stack.ts),
          jnp.asarray(sel_k.reshape(k, n * Kb, E)),
          jnp.asarray(key_k.reshape(k, n * Kb)),
          _now_stack(items))
    from . import runtime as _rt
    fn = p.shard_fused_steps[stream_id]
    _st, outs = _rt._step_phase(
        qr, lambda: fn(qr.state, xs, qr._in_tabs()), mult=len(items))
    _rt._rebind_state(qr, _st, mult=len(items))
    _deliver_fused(qr, outs, [now for _, _, now in items])


def _dispatch_join(qr, items) -> None:
    p = qr.planned
    is_left = items[0][0]
    side = p.left if is_left else p.right
    body = p.raw_left if is_left else p.raw_right
    gs = [qr._join_slots(is_left, staged) for _, staged, _ in items]
    stack = ev.StackedBatch([staged for _, staged, _ in items])
    batch = stack.to_device(side.schema)
    xs = [batch.ts, batch.kind, batch.valid, batch.cols,
          jnp.asarray(np.stack([np.asarray(g) for g in gs]))]
    if p.fastpath == "bucket":
        # probes were bound (and the retention mirror fed) at offer
        # time, so the stack replays them verbatim
        xs.append(jnp.asarray(np.stack(
            [np.asarray(qr._join_key_probe(is_left, staged))
             for _, staged, _ in items])))
    elif p.fastpath == "table":
        # candidates resolve against the table at DISPATCH time — the
        # same moment `const` snapshots its columns below
        probes = [qr._table_probe(staged) for _, staged, _ in items]
        w = max(c.shape[1] for c, _ in probes)
        b = probes[0][0].shape[0]
        cand_k = np.full((len(probes), b, w), -1, np.int32)
        ok_k = np.zeros((len(probes), b, w), np.bool_)
        for i, (c, o) in enumerate(probes):
            cand_k[i, :, :c.shape[1]] = c
            ok_k[i, :, :o.shape[1]] = o
        xs.append((jnp.asarray(cand_k), jnp.asarray(ok_k)))
    xs.append(_now_stack(items))
    # table/aggregation other-side snapshot is taken ONCE at dispatch:
    # under @fuse the per-batch read-your-writes of a concurrently
    # updated table relaxes to dispatch granularity (stream other-sides
    # live in the carry and stay exact)
    const = qr._other_table(is_left)
    fn = _fused_fn(qr, "join", body)
    from . import runtime as _rt
    _st, outs = _rt._step_phase(
        qr, lambda: fn(qr.state, tuple(xs), const), mult=len(items))
    _rt._rebind_state(qr, _st, mult=len(items))
    _deliver_fused(qr, outs, [now for _, _, now in items])


def _dispatch_merged(qr, items) -> None:
    """Fused dispatch of a MERGE GROUP's stack (optimizer/mqo.py): K
    staged batches × N member queries in ONE lax.scan device dispatch,
    then one combined fetch feeds the per-batch, per-query demux."""
    from . import runtime as _rt
    stats = qr.app.stats
    t0 = time.perf_counter_ns() if stats.enabled else 0
    preps = [qr._prep(staged, now) for staged, now in items]
    stack = ev.StackedBatch([staged for staged, _ in items])
    batch = stack.to_device(qr.in_schema)
    n_units = len(qr.units)
    gslots_k = tuple(
        jnp.asarray(np.stack([np.asarray(p[0][u]) for p in preps]))
        for u in range(n_units))
    pslots_k = tuple(
        tuple(jnp.asarray(np.stack([np.asarray(p[1][i][j])
                                    for p in preps]))
              for j in range(len(qr.members[i].planned.pair_allocs)))
        for i in range(len(qr.members)))
    xs = (batch.ts, batch.kind, batch.valid, batch.cols, gslots_k,
          _now_stack(items), pslots_k)
    fn = _fused_fn(qr, "merged", qr.raw_body)
    _st, outs = _rt._step_phase(
        qr, lambda: fn(qr._state, xs, qr._in_tabs()),
        name=f"merged:{qr.group}", mult=len(items))
    _rt._rebind_state(qr, _st, mult=len(items),
                      name=f"merged:{qr.group}", attr="_state")
    if stats.enabled:
        stats.counter_inc(f"merged.{qr.group}.dispatches")
        stats.counter_inc(f"merged.{qr.group}.member_batches",
                          len(qr.members) * len(items))
    ingests = qr.__dict__.pop("_fused_ingests", None)
    K = len(items)
    if ingests is None or len(ingests) != K:
        ingests = [None] * K
    consumers = [i for i, m in enumerate(qr.members)
                 if _rt._has_consumers(m)]
    deferred = (getattr(qr.members[0], "async_emit", False) and
                qr.app._drainer is not None) or \
        bool(getattr(qr.members[0], "pipeline_emit", 0) or 0) or \
        getattr(qr.members[0], "serve_emit", False)
    if consumers and not deferred:
        # ONE fetch for every consumed member's whole [K, ...] block;
        # per-batch views below are then numpy slices
        tf = time.perf_counter_ns()
        host = jax.device_get([outs[i] for i in consumers])
        if stats.enabled:
            stats.phases.add(f"merged:{qr.group}", "d2h_drain",
                             time.perf_counter_ns() - tf)
        outs = list(outs)
        for i, h in zip(consumers, host):
            outs[i] = h
        outs = tuple(outs)
    batches = []
    for k, (staged, now) in enumerate(items):
        out_k = tuple(
            (o[0][k], o[1][k], o[2][k], tuple(c[k] for c in o[3]))
            if i in consumers else None
            for i, o in enumerate(outs))
        batches.append((out_k, staged, now, ingests[k]))
    qr._demux(batches, t0)


_DISPATCH = {"plain": _dispatch_plain, "pattern": _dispatch_pattern,
             "join": _dispatch_join, "merged": _dispatch_merged}


# ---------------------------------------------------------------------------
# fused delivery: one [K, 2] header fetch, per-batch unstacked emission
# ---------------------------------------------------------------------------

def _deliver_fused(qr, outs, nows: List[int]) -> None:
    """Unstack the fused [K, ...] output block and deliver each batch's
    emission in order.

    Sync mode fetches ONE combined header ([K, 2] for compacted
    pattern/join outputs; the whole capacity-bounded block for plain
    outputs) and feeds per-batch numpy slices through the standard
    emission path.  @serve/@async/@pipeline compose by re-entering
    `_emit_output` per batch — the serving ring appends stay
    dispatch-only and the drainer/deque already batch their header
    fetches.  A per-batch failure (emission-cap overflow, callback
    error) defers until every batch has been delivered, then the first
    error propagates to the junction's fault routing."""
    from . import runtime as _rt
    ingests = qr.__dict__.pop("_fused_ingests", None)
    if not _rt._has_consumers(qr):
        return
    K = len(nows)
    if ingests is None or len(ingests) != K:
        ingests = [None] * K
    if getattr(qr, "serve_emit", False) \
            or getattr(qr, "async_emit", False) and \
            qr.app._drainer is not None \
            or getattr(qr, "pipeline_emit", 0):
        for i in range(K):
            # per-batch stamp restored so _emit_output's deferred queues
            # (drainer / @pipeline deque) carry the right e2e origin
            qr.__dict__["_ingest_ns"] = ingests[i]
            _rt._emit_output(qr, _slice_out(outs, i), nows[i], wake=None)
        qr.__dict__["_ingest_ns"] = None
        return
    first_exc = None
    _st = qr.app.stats
    if len(outs) == 6:
        # ONE fetch for the combined [K, 2] header (join headers are
        # [K, 2] vectors themselves; still one fetch)
        tf = time.perf_counter_ns()
        h0, h1 = jax.device_get((outs[0], outs[1]))
        if _st.enabled:
            _st.phases.add(qr.name, "d2h_drain",
                           time.perf_counter_ns() - tf)
        need_rows = bool(qr.callbacks) or \
            getattr(qr, "table_op", None) is not None or \
            getattr(qr, "rate_limiter", None) is not None or \
            getattr(qr.planned, "emits_uuid", False)
        tgt = qr.planned.output_target
        if not need_rows and tgt:
            # mirror _emit_output_sync_impl's target-live check: a dead
            # downstream junction must not force a bulk fetch
            app = qr.app
            if tgt in getattr(app, "named_windows", {}) or \
                    tgt in getattr(app, "tables", {}):
                need_rows = True
            else:
                j = app.junctions.get(tgt)
                need_rows = j is not None and bool(
                    j.queries or j.stream_callbacks or app.stats.enabled)
        tf = time.perf_counter_ns()
        bulk = jax.device_get(outs[2:]) if need_rows else outs[2:]
        if need_rows and _st.enabled:
            _st.phases.add(qr.name, "d2h_drain",
                           time.perf_counter_ns() - tf)
        for i in range(K):
            out_i = (h0[i], h1[i], bulk[0][i], bulk[1][i], bulk[2][i],
                     tuple(c[i] for c in bulk[3]))
            try:
                _rt._emit_output_sync(qr, out_i, nows[i],
                                      header=(h0[i], h1[i]),
                                      ingest_ns=ingests[i])
            except Exception as exc:  # noqa: BLE001 — deliver the rest
                first_exc = first_exc or exc
    else:
        # plain outputs are window-capacity bounded and always ship
        # whole on the sequential path too: ONE fetch for the block
        tf = time.perf_counter_ns()
        ots, okind, ovalid, ocols = jax.device_get(outs)
        if _st.enabled:
            _st.phases.add(qr.name, "d2h_drain",
                           time.perf_counter_ns() - tf)
        for i in range(K):
            out_i = (ots[i], okind[i], ovalid[i],
                     tuple(c[i] for c in ocols))
            try:
                _rt._emit_output_sync(qr, out_i, nows[i],
                                      ingest_ns=ingests[i])
            except Exception as exc:  # noqa: BLE001 — deliver the rest
                first_exc = first_exc or exc
    if first_exc is not None:
        raise first_exc


def _slice_out(outs, i: int):
    """Per-batch device-array view of the stacked output (for @async/
    @pipeline composition, where the fetch happens downstream)."""
    if len(outs) == 6:
        return (outs[0][i], outs[1][i], outs[2][i], outs[3][i],
                outs[4][i], tuple(c[i] for c in outs[5]))
    return (outs[0][i], outs[1][i], outs[2][i],
            tuple(c[i] for c in outs[3]))
