"""Shard-safe state mutation helpers.

Host-context `.at[idx].set(...)` scatters into a MESH-SHARDED jax array
silently drop the updates that land on remote shards (observed on the
virtual CPU mesh; the op runs per-shard without the cross-device routing
jit/GSPMD would insert).  Every host-side reset/restore of potentially
sharded state must go through an elementwise masked `where` instead —
these helpers are the single home for that idiom (used by the partition
purger in core/runtime.py and the aggregation duration slabs in
core/aggregation.py).
"""
from __future__ import annotations

import jax
import numpy as np


def key_mask(idx: np.ndarray, capacity: int):
    """Device bool mask of `capacity` with True at `idx`."""
    mask = np.zeros(capacity, bool)
    mask[idx] = True
    return jax.numpy.asarray(mask)


def masked_fill(arr, mask, init, key_axis: int = 0):
    """Reset `arr` rows where mask is True along key_axis with `init`
    (scalar or an array broadcastable over the masked rows)."""
    shape = [1] * arr.ndim
    shape[key_axis] = mask.shape[0]
    m = mask.reshape(shape)
    return jax.numpy.where(m, jax.numpy.asarray(init, arr.dtype), arr)


def axis0_sharding(mesh, x):
    """NamedSharding splitting a leaf's axis 0 over the mesh's first axis,
    or None when the leaf is not evenly divisible (replicate it).  The ONE
    eligibility rule shared by host placement (JoinQueryRuntime.place_state
    seeds the layout with device_put) and the in-graph pin
    (join._constrain_state keeps GSPMD from re-replicating the buffers) —
    two hand-rolled copies of this predicate WILL drift."""
    if mesh is None or mesh.devices.size < 2:
        return None
    n = mesh.devices.size
    if getattr(x, "ndim", 0) >= 1 and x.shape[0] >= n and \
            x.shape[0] % n == 0:
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(
            mesh, P(*([mesh.axis_names[0]] + [None] * (x.ndim - 1))))
    return None
