"""Columnar event model — the TPU-native replacement for the reference's
pooled linked-list event chunks.

Reference (what, not how): CORE/event/stream/StreamEvent.java:37,
CORE/event/ComplexEventChunk.java:32, CORE/event/Event.java. The reference
pushes one pooled Java object at a time through processor chains; here an
event micro-batch is a struct-of-arrays pytree with static shapes so each
query step jit-compiles once per batch bucket and runs fully on device.

Design:
  * EventBatch: timestamps i64[B], kind i32[B] (CURRENT/EXPIRED/TIMER/RESET),
    valid bool[B], and one fixed-dtype column per schema attribute.
  * Strings are dictionary-encoded to int32 ids by a host-side interner
    (per SiddhiManager), so string equality/group-by/partition-by are pure
    integer ops on device.
  * Batches are padded to bucket sizes (powers of 4) to bound the number of
    XLA compilations.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..query_api.definition import AbstractDefinition

# Event kinds (reference: ComplexEvent.Type CURRENT/EXPIRED/TIMER/RESET)
CURRENT = 0
EXPIRED = 1
TIMER = 2
RESET = 3

KIND_NAMES = {CURRENT: "CURRENT", EXPIRED: "EXPIRED", TIMER: "TIMER", RESET: "RESET"}

# Attribute type -> on-device dtype.  DOUBLE maps to float32: TPU has no
# native f64; parity tests use tolerances (see SURVEY.md §7 hard part (f)).
# LONG is i64 (jax_enable_x64 is switched on in siddhi_tpu/__init__) because
# epoch-millisecond timestamps overflow i32; XLA:TPU emulates s64.
_DTYPES = {
    "STRING": jnp.int32,   # interned id; -1 == null
    "INT": jnp.int32,
    "LONG": jnp.int64,
    "FLOAT": jnp.float32,
    "DOUBLE": jnp.float32,
    "BOOL": jnp.bool_,
    "OBJECT": jnp.int32,   # host-side object registry id
}

NULL_ID = -1  # interned id representing null string
UUID_SENTINEL = -2  # UUID() marker id: decodes to a fresh uuid4 per cell

# In-band numeric nulls (reference: events carry boxed Java nulls,
# JoinProcessor emits them for unmatched outer-join rows).  Columnar numerics
# carry no side mask; instead one value per dtype is reserved as null —
# INT/LONG reserve their minimum (kdb-style), FLOAT/DOUBLE use NaN.  The
# reserved values round-trip to Python None at every host decode boundary.
# BOOL has no spare value: null bools decode as False (PARITY.md).
NULL_INT = int(np.iinfo(np.int32).min)
NULL_LONG = int(np.iinfo(np.int64).min)


def null_value(attr_type: str):
    """The encoded cell value representing null for this attribute type."""
    t = attr_type.upper()
    if t in ("STRING", "OBJECT"):
        return NULL_ID
    if t == "BOOL":
        return False
    if t in ("FLOAT", "DOUBLE"):
        return float("nan")
    if t == "INT":
        return NULL_INT
    return NULL_LONG


def null_mask(x, attr_type: str):
    """[B] bool mask of null cells; works on jnp arrays/tracers and np."""
    t = attr_type.upper()
    host = isinstance(x, np.ndarray)
    if t in ("STRING", "OBJECT"):
        # exactly NULL_ID: UUID_SENTINEL (-2) is a real pending value, not
        # null — `UUID() != 'x'` must stay true, isNull(UUID()) false
        return x == NULL_ID
    if t in ("FLOAT", "DOUBLE"):
        return np.isnan(x) if host else jnp.isnan(x)
    if t == "INT":
        return x == NULL_INT
    if t == "LONG":
        return x == NULL_LONG
    return (np.zeros if host else jnp.zeros)(np.shape(x), bool)


def decode_scalar(attr_type: str, v, interner, objects=None):
    """Encoded cell -> Python value at a host boundary: the ONE scalar
    decode rule (Events, on-demand results, script-function arguments all
    share it).  Reserved null values decode to None; UUID sentinels
    materialize a fresh id (reference: UUIDFunctionExecutor)."""
    t = attr_type.upper()
    if t == "STRING":
        iv = int(v)
        if iv == UUID_SENTINEL:
            import uuid
            return str(uuid.uuid4())
        return interner.lookup(iv)
    if t == "OBJECT":
        return objects.lookup(int(v)) if objects is not None else None
    if t == "BOOL":
        return bool(v)
    if t in ("FLOAT", "DOUBLE"):
        f = float(v)
        return None if f != f else f            # NaN is the float null
    iv = int(v)
    if iv == (NULL_INT if t == "INT" else NULL_LONG):
        return None
    return iv


def fill_uuid_cells(interner, col: "np.ndarray",
                    mask: "np.ndarray") -> "np.ndarray":
    """Replace masked cells with freshly interned uuid4 ids (copy-on-write).
    The single primitive behind every UUID_SENTINEL materialization site —
    one contract, one implementation."""
    import uuid
    if not mask.any():
        return col
    col = col.copy()
    col[mask] = [interner.intern(str(uuid.uuid4()))
                 for _ in range(int(mask.sum()))]
    return col


def materialize_uuid_sentinels(schema, valid_np, cols):
    """UUID() sentinels become real interned ids ONCE at a host boundary
    (query emission, table storage), so every consumer observes the same id
    per row (reference: CORE/executor/function/UUIDFunctionExecutor — one
    UUID per event, not per reader).  Returns [(position, new_col)] for the
    STRING columns that contained sentinels in valid rows."""
    changed = []
    for pos, t in enumerate(schema.types):
        if t.upper() != "STRING":
            continue
        col = np.asarray(cols[pos])
        mask = (col == UUID_SENTINEL) & valid_np
        if mask.any():
            changed.append((pos, fill_uuid_cells(schema.interner, col, mask)))
    return changed

_BUCKETS = (8, 32, 128, 512, 2048, 8192, 32768, 131072, 262144, 524288,
            1048576, 2097152)


def bucket_size(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    raise ValueError(f"batch of {n} events exceeds max bucket {_BUCKETS[-1]}")


def dtype_of(attr_type: str):
    return _DTYPES[attr_type.upper()]


def default_value(attr_type: str):
    t = attr_type.upper()
    if t in ("STRING", "OBJECT"):
        return NULL_ID
    if t == "BOOL":
        return False
    if t in ("FLOAT", "DOUBLE"):
        return 0.0
    return 0


class StringInterner:
    """Host-side dictionary encoder shared across an app's streams so ids are
    comparable across streams/tables/joins."""

    def __init__(self):
        self._lock = threading.Lock()
        self._to_id: Dict[str, int] = {}
        self._to_str: List[str] = []

    def intern(self, s: Optional[str]) -> int:
        if s is None:
            return NULL_ID
        got = self._to_id.get(s)
        if got is not None:
            return got
        with self._lock:
            got = self._to_id.get(s)
            if got is None:
                got = len(self._to_str)
                self._to_str.append(s)
                self._to_id[s] = got
            return got

    def lookup(self, i: int) -> Optional[str]:
        if i < 0 or i >= len(self._to_str):
            return None
        return self._to_str[i]

    def __len__(self):
        return len(self._to_str)


class ObjectRegistry:
    """Host-side registry giving OBJECT attributes a device-representable id."""

    def __init__(self):
        self._lock = threading.Lock()
        self._objs: List[Any] = []

    def register(self, o: Any) -> int:
        if o is None:
            return NULL_ID
        with self._lock:
            self._objs.append(o)
            return len(self._objs) - 1

    def lookup(self, i: int) -> Any:
        if i < 0 or i >= len(self._objs):
            return None
        return self._objs[i]


class Event:
    """Host-side event (reference: CORE/event/Event.java)."""

    __slots__ = ("timestamp", "data")

    def __init__(self, timestamp: int, data: Sequence[Any]):
        self.timestamp = int(timestamp)
        self.data = list(data)

    def __repr__(self):
        return f"Event({self.timestamp}, {self.data})"

    def __eq__(self, other):
        return (
            isinstance(other, Event)
            and self.timestamp == other.timestamp
            and self.data == other.data
        )


class Schema:
    """Runtime view of a definition: attribute order, dtypes, interner."""

    def __init__(self, definition: AbstractDefinition, interner: StringInterner,
                 objects: Optional[ObjectRegistry] = None):
        self.definition = definition
        self.id = definition.id
        self.names: Tuple[str, ...] = tuple(definition.attribute_names)
        self.types: Tuple[str, ...] = tuple(a.type for a in definition.attribute_list)
        self.dtypes = tuple(dtype_of(t) for t in self.types)
        self.interner = interner
        self.objects = objects or ObjectRegistry()

    def position(self, name: str) -> int:
        return self.names.index(name)

    def encode_value(self, attr_type: str, v: Any):
        t = attr_type.upper()
        if t == "STRING":
            return self.interner.intern(v) if isinstance(v, str) or v is None else int(v)
        if t == "OBJECT":
            return self.objects.register(v)
        if v is None:
            # reference events carry real nulls; numerics use the reserved
            # in-band value so None round-trips through the device
            return null_value(t)
        if t == "BOOL":
            return bool(v)
        if t in ("FLOAT", "DOUBLE"):
            return float(v)
        return int(v)

    def decode_value(self, attr_type: str, v):
        return decode_scalar(attr_type, v, self.interner, self.objects)


@jax.tree_util.register_pytree_node_class
class EventBatch:
    """Struct-of-arrays event micro-batch (static shape [B])."""

    def __init__(self, ts, kind, valid, cols: Tuple):
        self.ts = ts          # i64[B]
        self.kind = kind      # i32[B]
        self.valid = valid    # bool[B]
        self.cols = tuple(cols)

    # -- pytree protocol --
    def tree_flatten(self):
        return ((self.ts, self.kind, self.valid, self.cols), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        ts, kind, valid, cols = children
        return cls(ts, kind, valid, cols)

    @property
    def capacity(self) -> int:
        return self.ts.shape[0]

    def col(self, i: int):
        return self.cols[i]

    def with_cols(self, cols) -> "EventBatch":
        return EventBatch(self.ts, self.kind, self.valid, tuple(cols))

    def mask(self, keep) -> "EventBatch":
        return EventBatch(self.ts, self.kind, jnp.logical_and(self.valid, keep), self.cols)

    def with_kind(self, kind_value: int) -> "EventBatch":
        return EventBatch(
            self.ts, jnp.full_like(self.kind, kind_value), self.valid, self.cols
        )

    @staticmethod
    def empty(schema: Schema, capacity: int) -> "EventBatch":
        cols = tuple(
            jnp.full((capacity,), default_value(t), dtype=d)
            for t, d in zip(schema.types, schema.dtypes)
        )
        return EventBatch(
            ts=jnp.zeros((capacity,), jnp.int64),
            kind=jnp.zeros((capacity,), jnp.int32),
            valid=jnp.zeros((capacity,), jnp.bool_),
            cols=cols,
        )


def np_dtype(attr_type: str):
    t = attr_type.upper()
    if t in ("STRING", "OBJECT", "INT"):
        return np.int32
    if t == "LONG":
        return np.int64
    if t == "FLOAT":
        return np.float32
    if t == "DOUBLE":
        return np.float32
    return np.bool_


class StagedBatch:
    """Host (numpy) staging of a batch: used for group-key/partition-key slot
    computation before the single host->device transfer."""

    __slots__ = ("ts", "kind", "valid", "cols", "n", "jprobe", "dev")

    def __init__(self, ts, kind, valid, cols, n):
        self.ts, self.kind, self.valid, self.cols, self.n = ts, kind, valid, cols, n
        # equi-join bucket slots, bound once at the fuse-offer edge and
        # replayed verbatim by drains/dispatch (core/runtime.py
        # JoinQueryRuntime._join_key_probe)
        self.jprobe = None
        # (schema, EventBatch) prestaged by the serving double-buffer
        # (serving/staging.py): the H2D transfer started at the junction
        # accept edge; to_device adopts it instead of re-transferring
        self.dev = None

    def to_device(self, schema: Schema) -> EventBatch:
        dev = self.dev
        if dev is not None and (dev[0] is schema or
                                dev[0].dtypes == schema.dtypes):
            return dev[1]
        cols = tuple(jnp.asarray(c).astype(d)
                     for c, d in zip(self.cols, schema.dtypes))
        return EventBatch(jnp.asarray(self.ts), jnp.asarray(self.kind),
                          jnp.asarray(self.valid), cols)


class StackedBatch:
    """K same-capacity staged micro-batches stacked into [K, B] host
    arrays for ONE fused device dispatch (core/fusion.py): one
    host->device transfer and one `lax.scan` execution replace K of
    each.  Capacity equality is the caller's contract (the fuse buffer
    keys its stack on the bucket size)."""

    __slots__ = ("ts", "kind", "valid", "cols", "k")

    def __init__(self, staged_list: Sequence["StagedBatch"]):
        self.k = len(staged_list)
        self.ts = np.stack([s.ts for s in staged_list])
        self.kind = np.stack([s.kind for s in staged_list])
        self.valid = np.stack([s.valid for s in staged_list])
        self.cols = tuple(
            np.stack([s.cols[j] for s in staged_list])
            for j in range(len(staged_list[0].cols)))

    def to_device(self, schema: Schema) -> EventBatch:
        """[K, B] EventBatch (EventBatch is shape-agnostic)."""
        cols = tuple(jnp.asarray(c).astype(d)
                     for c, d in zip(self.cols, schema.dtypes))
        return EventBatch(jnp.asarray(self.ts), jnp.asarray(self.kind),
                          jnp.asarray(self.valid), cols)


def pack_np(schema: Schema, events: Sequence[Event],
            kinds: Optional[Sequence[int]] = None,
            capacity: Optional[int] = None) -> StagedBatch:
    """Encode host events into padded numpy staging arrays."""
    n = len(events)
    cap = capacity if capacity is not None else bucket_size(max(n, 1))
    ts = np.zeros((cap,), np.int64)
    kind = np.zeros((cap,), np.int32)
    valid = np.zeros((cap,), np.bool_)
    raw_cols = [np.zeros((cap,), np_dtype(t)) for t in schema.types]
    for i, e in enumerate(events):
        ts[i] = e.timestamp
        valid[i] = True
        if kinds is not None:
            kind[i] = kinds[i]
        for j, (t, v) in enumerate(zip(schema.types, e.data)):
            raw_cols[j][i] = schema.encode_value(t, v)
    return StagedBatch(ts, kind, valid, raw_cols, n)


def pack(schema: Schema, events: Sequence[Event],
         kinds: Optional[Sequence[int]] = None,
         capacity: Optional[int] = None) -> EventBatch:
    """Encode host events into a padded columnar device batch."""
    return pack_np(schema, events, kinds, capacity).to_device(schema)


def timer_batch(schema: Schema, timestamp: int, capacity: int = 8) -> EventBatch:
    """A batch containing a single TIMER row (reference: Scheduler timer events,
    CORE/util/Scheduler.java:171)."""
    b = EventBatch.empty(schema, capacity)
    return EventBatch(
        b.ts.at[0].set(timestamp),
        b.kind.at[0].set(TIMER),
        b.valid.at[0].set(True),
        b.cols,
    )


def unpack(schema: Schema, batch: EventBatch,
           want_kinds: Tuple[int, ...] = (CURRENT,)) -> List[Tuple[int, Event]]:
    """Decode a device batch back to host [(kind, Event)] preserving order.
    Vectorized: one boolean reduction + per-column .tolist()."""
    kind = np.asarray(batch.kind)
    valid = np.asarray(batch.valid)
    keep = valid & (kind != TIMER) & (kind != RESET)
    if want_kinds is not None:
        sel = np.zeros_like(keep)
        for k in want_kinds:
            sel |= kind == k
        keep &= sel
    idx = np.nonzero(keep)[0]
    if idx.size == 0:
        return []
    ts_l = np.asarray(batch.ts)[idx].tolist()
    kind_l = kind[idx].tolist()
    col_np = [np.asarray(c)[idx] for c in batch.cols]
    col_ls = [c.tolist() for c in col_np]
    decoders = []

    def _str_decode(i, _lk=schema.interner.lookup):
        if i == UUID_SENTINEL:
            import uuid
            return str(uuid.uuid4())
        return _lk(i)

    for t, cnp in zip(schema.types, col_np):
        tu = t.upper()
        if tu == "STRING":
            decoders.append(_str_decode)
        elif tu == "OBJECT":
            decoders.append(schema.objects.lookup)
        elif cnp.size and null_mask(cnp, tu).any():
            # numeric nulls present: reserved values decode to None.  The
            # vectorized pre-check keeps null-free columns on the direct
            # (no per-cell call) path.
            nv = NULL_INT if tu == "INT" else NULL_LONG
            if tu in ("FLOAT", "DOUBLE"):
                decoders.append(lambda v: None if v != v else v)
            else:
                decoders.append(lambda v, _n=nv: None if v == _n else v)
        else:
            decoders.append(None)
    out: List[Tuple[int, Event]] = []
    for i in range(len(idx)):
        data = [c[i] if d is None else d(c[i])
                for c, d in zip(col_ls, decoders)]
        out.append((kind_l[i], Event(ts_l[i], data)))
    return out
