"""Output rate limiting (reference: CORE/query/output/ratelimit/* — 17
limiter classes: {All,First,Last}Per{Event,Time} (+GroupBy variants) and
snapshot limiters).

The device step always computes the full output batch; limiting is a host
concern on the emission path (events are already host-side there), matching
the reference's placement between QuerySelector and OutputCallback.
`output snapshot every t` re-emits the latest row per group at each tick,
with the group key recovered from the projected group-by attributes when
they appear in the output (the common `select g, agg(x) ... group by g`
shape); otherwise the whole latest row stands in.
"""
from __future__ import annotations

import threading
from typing import Callable, List, Optional, Tuple

from ..observability import tracing as _tracing
from . import event as ev


class OutputRateLimiter:
    """Base: `process` receives (kind, Event) pairs in emission order and
    forwards whatever is due to `deliver`.

    `process` (query/drainer thread) and `on_timer` (scheduler thread)
    mutate the same buffers; subclasses call them through the public
    entry points which serialize on the limiter's own RLock."""

    needs_timer = False

    def __init__(self, deliver: Callable[[List[Tuple[int, ev.Event]], int], None]):
        self.deliver = deliver
        self._lk = threading.RLock()

    def process(self, pairs: List[Tuple[int, ev.Event]], now: int) -> None:
        # rate-limit span on a DETAIL pipeline trace; the active() guard
        # keeps the common (untraced) path allocation-free
        if _tracing.active() is not None:
            with _tracing.span("ratelimit",
                               limiter=type(self).__name__,
                               pairs=len(pairs)):
                with self._lk:
                    self._process(pairs, now)
            return
        with self._lk:
            self._process(pairs, now)

    def on_timer(self, now: int) -> None:
        with self._lk:
            self._on_timer(now)

    def _process(self, pairs, now) -> None:
        raise NotImplementedError

    def _on_timer(self, now: int) -> None:  # pragma: no cover - overridden
        pass


class PerEventsLimiter(OutputRateLimiter):
    """`output [all|first|last] every N events` (reference:
    ratelimit/event/*PerEventOutputRateLimiter.java, incl. the
    First/LastGroupByPerEvent variants).  Counts CURRENT output events; at
    each full window of N, ALL flushes the buffer, FIRST emits only the
    window's first event, LAST only its Nth.  With group-by, FIRST emits
    each GROUP's first event within the window and LAST emits each group's
    latest event at the window boundary."""

    def __init__(self, deliver, n: int, behavior: str,
                 group_positions: Optional[List[int]] = None):
        super().__init__(deliver)
        self.n = n
        self.behavior = behavior
        self.group_positions = group_positions
        self._buf: List[Tuple[int, ev.Event]] = []
        self._count = 0
        self._first_sent = False
        self._group_first: set = set()
        self._group_last: dict = {}

    def _key(self, e: ev.Event):
        return tuple(e.data[i] for i in self.group_positions)

    def _process(self, pairs, now):
        out: List[Tuple[int, ev.Event]] = []
        grouped = bool(self.group_positions)
        for kind, e in pairs:
            if self.behavior == "ALL":
                self._buf.append((kind, e))
                self._count += 1
                if self._count == self.n:
                    out.extend(self._buf)
                    self._buf.clear()
                    self._count = 0
            elif self.behavior == "FIRST":
                if grouped:
                    k = self._key(e)
                    if k not in self._group_first:
                        out.append((kind, e))
                        self._group_first.add(k)
                else:
                    if not self._first_sent:
                        out.append((kind, e))
                        self._first_sent = True
                self._count += 1
                if self._count == self.n:
                    self._count = 0
                    self._first_sent = False
                    self._group_first.clear()
            else:  # LAST
                if grouped:
                    self._group_last[self._key(e)] = (kind, e)
                self._count += 1
                if self._count == self.n:
                    if grouped:
                        out.extend(self._group_last.values())
                        self._group_last.clear()
                    else:
                        out.append((kind, e))
                    self._count = 0
        if out:
            self.deliver(out, now)


class PerTimeLimiter(OutputRateLimiter):
    """`output [all|first|last] every <t>` (reference: ratelimit/time/*,
    incl. First/LastGroupByPerTime variants).  Scheduler-driven: every t ms
    the buffered (ALL), first (FIRST) or most recent (LAST) output is
    flushed.  With group-by, FIRST emits each group's first event of the
    interval immediately; LAST flushes each group's latest at the tick."""

    needs_timer = True

    def __init__(self, deliver, interval_ms: int, behavior: str,
                 group_positions: Optional[List[int]] = None):
        super().__init__(deliver)
        self.interval = interval_ms
        self.behavior = behavior
        self.group_positions = group_positions
        self._buf: List[Tuple[int, ev.Event]] = []
        self._group_first: set = set()
        self._group_last: dict = {}
        self._schedule: Optional[Callable[[int], None]] = None

    def _key(self, e: ev.Event):
        return tuple(e.data[i] for i in self.group_positions)

    def _process(self, pairs, now):
        grouped = bool(self.group_positions)
        if self.behavior == "FIRST":
            if grouped:
                out = []
                for kind, e in pairs:
                    k = self._key(e)
                    if k not in self._group_first:
                        self._group_first.add(k)
                        out.append((kind, e))
                if out:
                    self.deliver(out, now)
            elif not self._buf and pairs:
                # emit immediately the first event of each interval
                self.deliver([pairs[0]], now)
                self._buf = [pairs[0]]       # marks "sent this interval"
        elif self.behavior == "LAST":
            if grouped:
                for kind, e in pairs:
                    self._group_last[self._key(e)] = (kind, e)
            elif pairs:
                self._buf = [pairs[-1]]
        else:
            self._buf.extend(pairs)

    def _on_timer(self, now: int) -> None:
        if self.behavior == "FIRST":
            self._buf = []
            self._group_first.clear()
        elif self.behavior == "LAST" and self._group_last:
            self.deliver(list(self._group_last.values()), now)
            self._group_last.clear()
        elif self._buf:
            self.deliver(self._buf, now)
            self._buf = []
        if self._schedule is not None:
            self._schedule(now + self.interval)


class SnapshotLimiter(OutputRateLimiter):
    """`output snapshot every <t>` (reference: ratelimit/snapshot/*): at each
    tick, re-emit the latest CURRENT row per group."""

    needs_timer = True

    def __init__(self, deliver, interval_ms: int,
                 group_positions: Optional[List[int]] = None):
        super().__init__(deliver)
        self.interval = interval_ms
        self.group_positions = group_positions
        self._latest = {}
        self._schedule: Optional[Callable[[int], None]] = None

    def _key(self, e: ev.Event):
        if self.group_positions:
            return tuple(e.data[i] for i in self.group_positions)
        return ()

    def _process(self, pairs, now):
        for kind, e in pairs:
            if kind == ev.CURRENT:
                self._latest[self._key(e)] = e

    def _on_timer(self, now: int) -> None:
        if self._latest:
            self.deliver([(ev.CURRENT, e) for e in self._latest.values()],
                         now)
        if self._schedule is not None:
            self._schedule(now + self.interval)


def create_rate_limiter(output_rate, deliver,
                        group_positions=None) -> Optional[OutputRateLimiter]:
    if output_rate is None:
        return None
    if output_rate.type == "EVENTS":
        return PerEventsLimiter(deliver, int(output_rate.value),
                                output_rate.behavior, group_positions)
    if output_rate.type == "TIME":
        return PerTimeLimiter(deliver, int(output_rate.value),
                              output_rate.behavior, group_positions)
    if output_rate.type == "SNAPSHOT":
        return SnapshotLimiter(deliver, int(output_rate.value),
                               group_positions)
    raise ValueError(f"unknown output rate type {output_rate.type!r}")
