"""Expression-driven windows: #window.expression / #window.expressionBatch.

Reference behavior (what): CORE/query/processor/stream/window/
ExpressionWindowProcessor.java:395, ExpressionBatchWindowProcessor.java:589 —
windows that shrink/grow according to a boolean expression over the window
contents, with `first`/`last` event references, `count()`, aggregates, and
`eventTimestamp(first|last)`.

TPU-native design (how): the retention expression is compiled once into a
vectorized *range evaluator*: for a fixed newest index `hi` it returns, for
EVERY candidate oldest index j at once, whether the expression holds over
the range [j, hi] — aggregates become prefix/suffix scans over the combined
buffer (sum via cumsum difference, min/max via reversed running scans).  The
reference's per-event "evict oldest until satisfied" loop becomes, per
arrival, one argmax over that vector; arrivals within a micro-batch advance
through a `lax.scan` carrying only the eviction front.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from ..query_api.expression import (
    Add,
    And,
    AttributeFunction,
    Compare,
    Constant,
    Divide,
    Mod,
    Multiply,
    Not,
    Or,
    Subtract,
    Variable,
)
from . import event as ev
from .window import (
    BIG_SEQ,
    NO_WAKEUP,
    Buffer,
    Rows,
    WindowOutput,
    WindowProcessor,
    concat_rows,
    empty_buffer,
    sort_rows,
)


class _RangeCtx:
    """Evaluation context for one `hi`: arrays indexed by candidate j."""

    def __init__(self, schema, cols, ts, hi, N):
        self.schema = schema
        self.cols = cols          # combined columns, each [N]
        self.ts = ts              # [N]
        self.hi = hi              # traced scalar
        self.N = N
        self.j = jnp.arange(N, dtype=jnp.int64)
        self.in_range = self.j <= hi   # candidate j values beyond hi unused

    def col(self, name):
        return self.cols[self.schema.position(name)]

    def at_hi(self, arr):
        # arr[hi] without a serialized gather: one-hot over N
        oh = self.j == self.hi
        return jnp.sum(jnp.where(oh, arr, jnp.zeros((), arr.dtype)),
                       dtype=arr.dtype)


def _col_eval(expr, ctx: _RangeCtx):
    """Aggregate-argument evaluation: bare attributes are per-event COLUMNS
    (one value per window entry), not the latest event's scalar."""
    if isinstance(expr, Constant):
        return jnp.asarray(expr.value)
    if isinstance(expr, Variable):
        if expr.stream_id is None:
            return ctx.col(expr.attribute_name)
        raise ValueError(
            "first/last references are not allowed inside window-expression "
            "aggregates")
    for node, op in ((Add, jnp.add), (Subtract, jnp.subtract),
                     (Multiply, jnp.multiply), (Mod, jnp.mod)):
        if isinstance(expr, node):
            return op(_col_eval(expr.left, ctx), _col_eval(expr.right, ctx))
    if isinstance(expr, Divide):
        return (_col_eval(expr.left, ctx).astype(jnp.float64) /
                _col_eval(expr.right, ctx))
    raise ValueError(
        f"unsupported aggregate argument in window expression: {expr!r}")


def _range_eval(expr, ctx: _RangeCtx):
    """Recursively evaluate `expr` -> array [N] over candidate oldest j."""
    if isinstance(expr, Constant):
        return jnp.asarray(expr.value)
    if isinstance(expr, Variable):
        sid = expr.stream_id
        if sid == "first":
            return ctx.col(expr.attribute_name)                 # value at j
        if sid == "last":
            return ctx.at_hi(ctx.col(expr.attribute_name))      # scalar
        if sid is None:
            # bare attribute: the latest (triggering) event, as in reference
            return ctx.at_hi(ctx.col(expr.attribute_name))
        raise ValueError(
            f"expression window reference {sid!r} (use first/last)")
    if isinstance(expr, AttributeFunction):
        nm = expr.name
        if nm == "count":
            return (ctx.hi - ctx.j + 1).astype(jnp.int64)
        if nm == "eventTimestamp":
            p = expr.parameters
            if p and isinstance(p[0], Variable) and \
                    p[0].attribute_name == "first":
                return ctx.ts
            return ctx.at_hi(ctx.ts)
        if nm in ("sum", "avg"):
            x = _col_eval(expr.parameters[0], ctx)
            x = jnp.where(ctx.in_range, x, 0).astype(jnp.float64)
            P = jnp.cumsum(x)                          # inclusive prefix
            total_to_hi = ctx.at_hi(P)
            s = total_to_hi - P + x                    # sum over [j, hi]
            if nm == "avg":
                return s / jnp.maximum(
                    (ctx.hi - ctx.j + 1).astype(jnp.float64), 1.0)
            return s
        if nm in ("min", "max"):
            x = _col_eval(expr.parameters[0], ctx).astype(jnp.float64)
            pad = jnp.where(ctx.in_range, x,
                            jnp.inf if nm == "min" else -jnp.inf)
            rev = pad[::-1]
            acc = lax.associative_scan(
                jnp.minimum if nm == "min" else jnp.maximum, rev)
            return acc[::-1]                           # agg over [j, N) = [j, hi]
        raise ValueError(f"unsupported function {nm!r} in window expression")
    if isinstance(expr, Add):
        return _range_eval(expr.left, ctx) + _range_eval(expr.right, ctx)
    if isinstance(expr, Subtract):
        return _range_eval(expr.left, ctx) - _range_eval(expr.right, ctx)
    if isinstance(expr, Multiply):
        return _range_eval(expr.left, ctx) * _range_eval(expr.right, ctx)
    if isinstance(expr, Divide):
        return (_range_eval(expr.left, ctx).astype(jnp.float64) /
                _range_eval(expr.right, ctx))
    if isinstance(expr, Mod):
        return _range_eval(expr.left, ctx) % _range_eval(expr.right, ctx)
    if isinstance(expr, Compare):
        l, r = _range_eval(expr.left, ctx), _range_eval(expr.right, ctx)
        return {"<": l < r, "<=": l <= r, ">": l > r, ">=": l >= r,
                "==": l == r, "!=": l != r}[expr.operator]
    if isinstance(expr, And):
        return jnp.logical_and(_range_eval(expr.left, ctx),
                               _range_eval(expr.right, ctx))
    if isinstance(expr, Or):
        return jnp.logical_or(_range_eval(expr.left, ctx),
                              _range_eval(expr.right, ctx))
    if isinstance(expr, Not):
        return jnp.logical_not(_range_eval(expr.expression, ctx))
    raise ValueError(f"unsupported node in window expression: {expr!r}")


def _parse_expr_param(params) -> Any:
    if not params or not isinstance(params[0], Constant) or \
            params[0].type != "STRING":
        raise ValueError(
            "expression window takes a constant string expression")
    from ..compiler.parser import Parser
    return Parser(str(params[0].value)).parse_expression()


def _combine(buf: Buffer, rows: Rows, is_cur):
    """Compacted combined arrays: alive buffer entries (by age) then this
    batch's arrivals (by arrival order)."""
    C = buf.capacity
    B = rows.capacity
    k = jnp.cumsum(is_cur.astype(jnp.int64)) - 1
    old_key = jnp.where(buf.alive, buf.add_seq, BIG_SEQ)
    old_order = jnp.argsort(old_key)
    cur_order = jnp.argsort(jnp.where(is_cur, k, BIG_SEQ))
    comb_ts = jnp.concatenate([buf.ts[old_order], rows.ts[cur_order]])
    comb_gslot = jnp.concatenate([buf.gslot[old_order],
                                  rows.gslot[cur_order]])
    comb_cols = tuple(jnp.concatenate([bc[old_order], rc[cur_order]])
                      for bc, rc in zip(buf.cols, rows.cols))
    count0 = jnp.sum(buf.alive.astype(jnp.int64))
    ncur = jnp.sum(is_cur.astype(jnp.int64))
    # virtual compaction: index v walks buffer entries then arrivals with no
    # gap (v < count0 -> physical v; else physical C + v - count0)
    v = jnp.arange(C + B, dtype=jnp.int64)
    phys = jnp.clip(jnp.where(v < count0, v, C + v - count0),
                    0, C + B - 1).astype(jnp.int32)
    comb_ts = comb_ts[phys]
    comb_gslot = comb_gslot[phys]
    comb_cols = tuple(c[phys] for c in comb_cols)
    return comb_ts, comb_gslot, comb_cols, count0, ncur, k


class ExpressionWindow(WindowProcessor):
    """Sliding expression window (reference: ExpressionWindowProcessor).

    Holds events while the expression over the window contents is satisfied;
    when it is not, events expire oldest-first until it is.  Retention is
    additionally bounded by the slab capacity (@capacity hint): beyond it the
    oldest rows force-expire as EXPIRED events — never silent truncation."""

    name = "expression"

    def __init__(self, schema, params, batch_capacity, capacity_hint=1024):
        super().__init__(schema, params, batch_capacity, capacity_hint)
        self.expr = _parse_expr_param(params)
        self.capacity = capacity_hint

    @property
    def out_capacity(self):
        return self.capacity + 2 * self.batch_capacity

    def init_state(self):
        return (empty_buffer(self.schema, self.capacity),
                jnp.asarray(0, jnp.int64))

    def process(self, state, rows: Rows, now):
        buf, seq0 = state
        C, B = self.capacity, rows.capacity
        N = C + B
        is_cur = jnp.logical_and(rows.valid, rows.kind == ev.CURRENT)
        (comb_ts, comb_gslot, comb_cols, count0, ncur, k) = _combine(
            buf, rows, is_cur)
        jN = jnp.arange(N, dtype=jnp.int64)

        def step(front, kk):
            hi = count0 + kk
            ctx = _RangeCtx(self.schema, comb_cols, comb_ts, hi, N)
            sat = jnp.broadcast_to(_range_eval(self.expr, ctx), (N,))
            ok = jnp.logical_and(sat, jnp.logical_and(jN >= front, jN <= hi))
            nfront = jnp.where(jnp.any(ok), jnp.argmax(ok).astype(jnp.int64),
                               hi + 1)
            # capacity bound: never retain more than C rows — the oldest
            # force-expire through the normal EXPIRED path instead of being
            # silently truncated when the batch carries over (reference keeps
            # an unbounded list; a fixed slab needs visible eviction)
            nfront = jnp.maximum(nfront, hi + 1 - C)
            nfront = jnp.where(kk < ncur, nfront, front)
            return nfront, nfront

        front_final, fronts = lax.scan(
            step, jnp.asarray(0, jnp.int64), jnp.arange(B, dtype=jnp.int64))

        # eviction arrival for each combined entry p: first k with fronts[k]>p
        gt = fronts[:, None] > jN[None, :]             # [B, N]
        evicted = jnp.logical_and(jN < front_final,
                                  jN < count0 + ncur)
        evict_k = jnp.argmax(gt, axis=0).astype(jnp.int64)   # [N]
        prev_front = jnp.where(evict_k > 0, fronts[jnp.maximum(evict_k - 1, 0)],
                               0)
        span = N + 1
        exp_rows = Rows(
            ts=comb_ts,
            kind=jnp.full((N,), ev.EXPIRED, jnp.int32),
            valid=evicted,
            seq=seq0 + evict_k * span + (jN - prev_front),
            gslot=comb_gslot,
            cols=comb_cols,
        )
        cur_rows = Rows(
            ts=rows.ts, kind=jnp.full((B,), ev.CURRENT, jnp.int32),
            valid=is_cur, seq=seq0 + k * span + span - 1, gslot=rows.gslot,
            cols=rows.cols,
        )
        out = sort_rows(concat_rows(exp_rows, cur_rows))

        total = count0 + ncur
        take = front_final + jnp.arange(C, dtype=jnp.int64)
        tvalid = take < total
        tpos = jnp.clip(take, 0, N - 1).astype(jnp.int32)
        nbuf = Buffer(
            ts=comb_ts[tpos],
            add_seq=seq0 + tpos,   # age-ordered (relative order is all we need)
            expire_seq=jnp.full((C,), BIG_SEQ, jnp.int64),
            expire_ts=jnp.full((C,), BIG_SEQ, jnp.int64),
            alive=tvalid,
            gslot=comb_gslot[tpos],
            cols=tuple(c[tpos] for c in comb_cols),
        )
        nseq = seq0 + B * span + 1
        return ((nbuf, nseq),
                WindowOutput(out, nbuf, jnp.asarray(NO_WAKEUP, jnp.int64)))


class ExpressionBatchWindow(WindowProcessor):
    """Batch expression window (reference: ExpressionBatchWindowProcessor).

    Collects events while the expression holds; when an arrival breaks it,
    the collected batch flushes as CURRENT (previous batch replayed as
    EXPIRED first).  Options: include.triggering.event (the breaking event
    joins the flushed batch), stream.current.event (arrivals stream out
    individually while expiry stays batched).  A pending run exceeding the
    slab capacity force-flushes rather than silently truncating."""

    name = "expressionBatch"

    def __init__(self, schema, params, batch_capacity, capacity_hint=1024):
        super().__init__(schema, params, batch_capacity, capacity_hint)
        self.expr = _parse_expr_param(params)
        self.include_trigger = bool(
            params[1].value) if len(params) > 1 and \
            isinstance(params[1], Constant) else False
        self.stream_current = bool(
            params[2].value) if len(params) > 2 and \
            isinstance(params[2], Constant) else False
        self.capacity = capacity_hint

    @property
    def out_capacity(self):
        return 3 * (self.capacity + self.batch_capacity)

    def init_state(self):
        # prev holds one flushed batch: up to C pending rows PLUS the
        # triggering event (include.triggering.event), hence C + 1
        return (empty_buffer(self.schema, self.capacity),       # pending
                empty_buffer(self.schema, self.capacity + 1),   # prev batch
                jnp.asarray(0, jnp.int64))

    def process(self, state, rows: Rows, now):
        pend, prev, seq0 = state
        C, B = self.capacity, rows.capacity
        N = C + B
        is_cur = jnp.logical_and(rows.valid, rows.kind == ev.CURRENT)
        (comb_ts, comb_gslot, comb_cols, count0, ncur, k) = _combine(
            pend, rows, is_cur)
        jN = jnp.arange(N, dtype=jnp.int64)

        def step(carry, kk):
            start, nflush = carry
            hi = count0 + kk
            ctx = _RangeCtx(self.schema, comb_cols, comb_ts, hi, N)
            sat_vec = jnp.broadcast_to(_range_eval(self.expr, ctx), (N,))
            sat = jnp.sum(jnp.where(jN == start, sat_vec, False))  # sat[start]
            # capacity bound: a pending run longer than the slab force-
            # flushes (visible CURRENT batch) instead of silently dropping
            # its overflow when carried to the next step
            over = (hi - start + 1) > C
            flush = jnp.logical_and(
                kk < ncur,
                jnp.logical_and(start <= hi,
                                jnp.logical_or(jnp.logical_not(sat), over)))
            nstart = jnp.where(
                flush, hi + 1 if self.include_trigger else hi, start)
            return ((nstart, nflush + flush.astype(jnp.int64)),
                    (nstart, flush))

        (start_final, _nfl), (starts, flushes) = lax.scan(
            step, (jnp.asarray(0, jnp.int64), jnp.asarray(0, jnp.int64)),
            jnp.arange(B, dtype=jnp.int64))

        # entry p flushed in flush ordinal f_p = #flushes whose new start <= p
        after = jnp.where(flushes, starts, -1)               # [B]
        f_p = jnp.sum(jnp.logical_and(flushes[:, None],
                                      after[:, None] <= jN[None, :]),
                      axis=0).astype(jnp.int64)              # [N]
        flushed = jnp.logical_and(jN < start_final, jN < count0 + ncur)
        # batch start for p: largest flush-start <= p (or 0)
        bstart = jnp.max(jnp.where(
            jnp.logical_and(flushes[:, None], after[:, None] <= jN[None, :]),
            after[:, None], 0), axis=0)                      # [N]
        rank = jN - bstart
        span = 2 * N + 2
        npend0 = jnp.sum(prev.alive.astype(jnp.int64))

        # CURRENT: flushed entries at their flush ordinal (or streamed on
        # arrival when stream.current.event)
        if self.stream_current:
            cur_rows = Rows(
                ts=rows.ts, kind=jnp.full((B,), ev.CURRENT, jnp.int32),
                valid=is_cur, seq=seq0 + k, gslot=rows.gslot, cols=rows.cols)
            base = seq0 + B   # expired flushes sequence after streamed rows
        else:
            cur_rows = Rows(
                ts=comb_ts, kind=jnp.full((N,), ev.CURRENT, jnp.int32),
                valid=flushed, seq=seq0 + f_p * span + N + 1 + rank,
                gslot=comb_gslot, cols=comb_cols)
            base = seq0

        # EXPIRED: prev batch replays at flush 0; flushed batch f replays at
        # flush f+1 (if it happens within this step)
        total_flushes = jnp.sum(flushes.astype(jnp.int64))
        P = C + 1                                  # prev slab capacity
        prev_rank = jnp.cumsum(prev.alive.astype(jnp.int64)) - 1
        prev_exp = Rows(
            ts=prev.ts, kind=jnp.full((P,), ev.EXPIRED, jnp.int32),
            valid=jnp.logical_and(prev.alive, total_flushes > 0),
            seq=base + prev_rank,
            gslot=prev.gslot, cols=prev.cols)
        ent_exp = Rows(
            ts=comb_ts, kind=jnp.full((N,), ev.EXPIRED, jnp.int32),
            valid=jnp.logical_and(flushed, f_p + 1 < total_flushes),
            seq=base + (f_p + 1) * span + rank,
            gslot=comb_gslot, cols=comb_cols)
        out = sort_rows(concat_rows(concat_rows(prev_exp, ent_exp), cur_rows))

        # new pending = [start_final, total); new prev = last flushed batch
        total = count0 + ncur
        take = start_final + jnp.arange(C, dtype=jnp.int64)
        tvalid = take < total
        tpos = jnp.clip(take, 0, N - 1).astype(jnp.int32)
        npend = Buffer(
            ts=comb_ts[tpos], add_seq=seq0 + tpos,
            expire_seq=jnp.full((C,), BIG_SEQ, jnp.int64),
            expire_ts=jnp.full((C,), BIG_SEQ, jnp.int64),
            alive=tvalid, gslot=comb_gslot[tpos],
            cols=tuple(c[tpos] for c in comb_cols))
        # last flushed batch = entries with f_p == total_flushes-1; the
        # P = C+1 slab fits a full pending run plus its triggering event
        last_b = jnp.logical_and(flushed, f_p == total_flushes - 1)
        lrank = jnp.cumsum(last_b.astype(jnp.int64)) - 1
        tgt = jnp.where(last_b, lrank, P).astype(jnp.int32)
        fresh = empty_buffer(self.schema, P)
        nprev = Buffer(
            ts=fresh.ts.at[tgt].set(comb_ts, mode="drop"),
            add_seq=fresh.add_seq.at[tgt].set(seq0 + jN, mode="drop"),
            expire_seq=fresh.expire_seq,
            expire_ts=fresh.expire_ts,
            alive=jnp.zeros((P,), jnp.bool_).at[tgt].set(last_b, mode="drop"),
            gslot=fresh.gslot.at[tgt].set(comb_gslot, mode="drop"),
            cols=tuple(f.at[tgt].set(c, mode="drop")
                       for f, c in zip(fresh.cols, comb_cols)),
        )
        # keep the old prev batch when no flush happened this step
        nprev = jax.tree.map(
            lambda new, old: jnp.where(_bcast(total_flushes > 0, new),
                                       new, old), nprev, prev)
        nseq = seq0 + (B + 2) * span
        return ((npend, nprev, nseq),
                WindowOutput(out, npend, jnp.asarray(NO_WAKEUP, jnp.int64)))


def _bcast(pred, like):
    return jnp.reshape(pred, (1,) * like.ndim)


def register(window_types: dict) -> None:
    for cls in (ExpressionWindow, ExpressionBatchWindow):
        window_types[cls.name] = cls
