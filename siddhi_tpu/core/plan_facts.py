"""Shared compiled-plan fact helpers: uncapped-sentinel rendering,
fusion-exclusion reasons, and the static state-bytes estimator.

Three surfaces report the same two plan facts — whether a query's
emission cap is real or the 1<<30 "effectively uncapped" sentinel, and
why a requested `@fuse` was skipped at wiring time: the static analyzer
(`siddhi_tpu/analysis`), EXPLAIN (`observability/explain.py`), and
`/healthz` (`observability/health.py`).  Each used to re-derive them
locally (the sentinel rendering lived only in explain; the exclusion
reason only in a wiring-time log line), so the renderings could drift.
This module is the single source of truth all three import.

The same single-source rule applies to the *static state-bytes
estimate*: lint's MEM001 rule and the admission controller's
deploy-time memory gate (core/admission.py) must agree on how big an
app's device state will be BEFORE anything is planned or traced, or an
app could lint green and still be denied at deploy (or vice versa).
`static_state_components` below is that one implementation — a pure
AST walk mirroring the planner/runtime capacity defaults, shape×dtype
arithmetic only, never touching jax — and both consumers cite the same
per-component breakdown it returns.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

# pattern_planner's compact_rows default for non-partitioned patterns:
# "effectively uncapped" (a per-key cap with K=1 would cap the batch).
# Every surface that renders an emission cap must treat values at or
# above this sentinel as "no cap", never as a 1073741824-row budget.
UNCAPPED_SENTINEL = 1 << 30


def render_cap(rows: Optional[int]) -> Optional[int]:
    """Human-facing emission cap: None when absent or at/above the
    uncapped sentinel, else the concrete row count."""
    if rows is None:
        return None
    rows = int(rows)
    return None if rows >= UNCAPPED_SENTINEL else rows


def fusion_exclusion(qr) -> Optional[str]:
    """The concrete reason @fuse was requested but skipped for this query
    runtime, or None (fusing, eligible, or never requested).

    Prefers the reason stored at wiring time (runtime._maybe_fuse) and
    falls back to recomputing from the plan's static properties, so a
    runtime restored from a snapshot still reports it.  Attribute reads
    only — safe on the scrape path."""
    why = getattr(qr, "_fuse_excluded", None)
    if why is not None:
        return why
    if getattr(qr, "_fuse_requested", 0) and \
            getattr(qr, "_fuse", None) is None:
        from . import fusion
        try:
            return fusion.ineligible_reason(
                qr, getattr(qr, "_kind", "plain"))
        except Exception:  # noqa: BLE001 — diagnostics must not throw
            return "unknown (plan facts unavailable)"
    return None


def fusion_exclusions(rt) -> Dict[str, str]:
    """{query: exclusion reason} for every runtime of an app whose @fuse
    request was skipped at wiring time (empty when none were)."""
    out: Dict[str, str] = {}
    for name, qr in list(getattr(rt, "query_runtimes", {}).items()):
        why = fusion_exclusion(qr)
        if why is not None:
            out[name] = why
    return out


# ---------------------------------------------------------------------------
# static state-bytes estimator (shared by lint MEM001 and the admission
# deploy gate — one implementation, one component breakdown)
# ---------------------------------------------------------------------------

# mirrors of the planner/runtime defaults (planner.plan_single_query,
# runtime._add_query/_add_partition) — the static estimates must predict
# what those paths would build
BATCH_CAPACITY = 512
WINDOW_HINT = 2048
PARTITION_WINDOW_HINT = 128
PARTITION_KEYS = 4096
NFA_SLOTS = 8
# columnar buffer overhead per row beyond the payload columns:
# ts i64 + seq i64 + gslot i32 + alive bool (core/window.py empty_buffer)
ROW_OVERHEAD = 8 + 8 + 4 + 1


def iter_named_queries(app):
    """(name, query, partition|None) with runtime-identical naming
    (mirrors SiddhiAppRuntime._query_name: @info name, else `query<i>`
    numbered across top-level queries and partition bodies)."""
    from ..query_api.query import Partition, Query
    qi = 0

    def name_of(q) -> str:
        info = q.get_annotation("info")
        if info:
            n = info.element("name")
            if n:
                return n
        return f"query{qi + 1}"

    for element in app.execution_element_list:
        if isinstance(element, Query):
            yield name_of(element), element, None
            qi += 1
        elif isinstance(element, Partition):
            for q in element.query_list:
                yield name_of(q), q, element
                qi += 1


def query_kind(q) -> str:
    from ..query_api.query import JoinInputStream, StateInputStream
    if isinstance(q.input_stream, JoinInputStream):
        return "join"
    if isinstance(q.input_stream, StateInputStream):
        return "pattern"
    return "plain"


def window_handler(sis):
    from ..query_api.query import Window
    for h in getattr(sis, "stream_handlers", ()):
        if isinstance(h, Window):
            return h
    return None


def pattern_atoms(el) -> List:
    """Flat list of the stream/absent atoms of a state-element tree."""
    from ..query_api.query import (
        AbsentStreamStateElement,
        CountStateElement,
        EveryStateElement,
        LogicalStateElement,
        NextStateElement,
        StreamStateElement,
    )
    out: List = []

    def rec(e):
        if isinstance(e, (StreamStateElement, AbsentStreamStateElement)):
            out.append(e)
        elif isinstance(e, CountStateElement):
            rec(e.stream_state_element)
        elif isinstance(e, LogicalStateElement):
            rec(e.stream_state_element_1)
            rec(e.stream_state_element_2)
        elif isinstance(e, NextStateElement):
            rec(e.state_element)
            rec(e.next_state_element)
        elif isinstance(e, EveryStateElement):
            rec(e.state_element)

    rec(el)
    return out


def window_capacity(win, hint: int) -> int:
    """Resident-row capacity the planner would give this window: the
    first non-time integer parameter (length/lengthBatch/sort/... row
    counts), else the capacity hint time-based windows are built with."""
    if win is None:
        return BATCH_CAPACITY
    from ..query_api.expression import Constant
    for p in win.parameters:
        if isinstance(p, Constant) and p.type in ("INT", "LONG") and \
                not getattr(p, "is_time", False):
            return max(1, int(p.value))
    return hint


def capacity_annotation(q, part) -> Dict[str, int]:
    """@capacity(keys=, slots=, window=) merged across the query and its
    partition (runtime._add_partition scans both)."""
    out: Dict[str, int] = {}
    anns = list(q.annotations)
    if part is not None:
        anns += list(part.annotations)
        for pq in part.query_list:
            anns += list(pq.annotations)
    for ann in anns:
        if ann.name.lower() == "capacity":
            for k in ("keys", "slots", "window"):
                v = ann.element(k)
                if v is not None:
                    out[k] = int(v)
    return out


def row_bytes(sdef) -> int:
    """Bytes per buffered window row: payload columns (device dtypes via
    event.dtype_of — STRING is an interned i32, DOUBLE an f32 on TPU)
    plus the fixed Buffer bookkeeping columns."""
    import numpy as np

    from . import event as ev
    n = ROW_OVERHEAD
    for a in getattr(sdef, "attribute_list", ()):
        try:
            n += int(np.dtype(ev.dtype_of(a.type)).itemsize)
        except Exception:  # noqa: BLE001 — OBJECT columns etc.
            n += 8
    return n


def query_state_components(app, q, kind: str, part,
                           caps: Dict[str, int],
                           keys: int) -> Dict[str, int]:
    """Per-component shape×dtype estimate of the device state the
    planner would allocate for ONE query (windows and NFA slot blocks;
    group-by slabs are bounded and small by comparison).  Empty dict
    when the query holds no estimable state."""
    defs = app.stream_definition_map

    def stream_def(sid):
        return defs.get(sid) or app.window_definition_map.get(sid)

    hint = caps.get(
        "window",
        PARTITION_WINDOW_HINT if part is not None else WINDOW_HINT)
    if kind == "plain":
        win = window_handler(q.input_stream)
        if win is None:
            return {}
        rows = window_capacity(win, hint)
        per_key = rows * row_bytes(stream_def(q.input_stream.stream_id))
        return {"window": per_key * (keys if part is not None else 1)}
    if kind == "join":
        out: Dict[str, int] = {}

        def _kind_of(sid):
            if sid in app.aggregation_definition_map:
                return "aggregation"
            if sid in app.window_definition_map:
                return "named_window"
            if sid in app.table_definition_map:
                return "table"
            return "stream"

        def _probe_attrs(sid):
            d = app.table_definition_map.get(sid)
            return table_probe_attrs_of(d) if d is not None else []

        try:
            fp_mode, _, _ = join_fastpath(q.input_stream, _kind_of,
                                          _probe_attrs)
        except Exception:  # noqa: BLE001 — estimator must not throw
            fp_mode = None
        # bucketed sides carry one extra i32 key-slot column per row
        extra = 4 if fp_mode == "bucket" else 0
        for side, sis in (("join.left", q.input_stream.left_input_stream),
                          ("join.right",
                           q.input_stream.right_input_stream)):
            win = window_handler(sis)
            if win is not None:
                out[side] = window_capacity(win, WINDOW_HINT) * \
                    (row_bytes(stream_def(sis.stream_id)) + extra)
        return out
    # pattern: per-key NFA slot block — `slots` pending matches per key,
    # each capturing one row per pattern state
    atoms = pattern_atoms(q.input_stream.state_element)
    slots = caps.get("slots", NFA_SLOTS)
    per_state = max(
        (row_bytes(stream_def(a.basic_single_input_stream.stream_id))
         for a in atoms), default=ROW_OVERHEAD)
    return {"pattern_slots": (keys if part is not None else 1) * slots *
            max(1, len(atoms)) * per_state}


def static_state_components(app) -> Dict[str, Dict[str, int]]:
    """{query: {component: bytes}} static state estimate for every query
    of a parsed (unplanned) app — THE shared MEM001/deploy-gate numbers.
    Pure AST walk; never plans, traces, or allocates."""
    out: Dict[str, Dict[str, int]] = {}
    for name, q, part in iter_named_queries(app):
        kind = query_kind(q)
        caps = capacity_annotation(q, part)
        keys = caps.get("keys", PARTITION_KEYS)
        comps = query_state_components(app, q, kind, part, caps, keys)
        if comps:
            out[name] = comps
    return out


def static_state_bytes(app) -> int:
    """Total static state estimate across the app's queries."""
    return sum(sum(c.values())
               for c in static_state_components(app).values())


# ---------------------------------------------------------------------------
# equi-join fast-path facts (shared by the join planner, lint JOIN002,
# and EXPLAIN — one implementation, one set of reason strings, so lint
# prints exactly the condition the wiring tested)
# ---------------------------------------------------------------------------

def join_equi_pairs(jis) -> List[Tuple[object, object, object]]:
    """Top-level `==` conjuncts of a join ON-condition comparing one
    side-qualified attribute from each side: [(Compare node, left
    Variable, right Variable)], the left side's variable first whatever
    the written order.  The same shape analysis/typeflow._equi_conjuncts
    reports — kept AST-only so the planner can run it pre-compile."""
    from ..query_api import expression as ex
    on = getattr(jis, "on_compare", None)
    if on is None:
        return []
    ls, rs = jis.left_input_stream, jis.right_input_stream
    left_keys = {ls.stream_reference_id or ls.stream_id, ls.stream_id}
    right_keys = {rs.stream_reference_id or rs.stream_id, rs.stream_id}

    def conjuncts(e):
        if isinstance(e, ex.And):
            yield from conjuncts(e.left)
            yield from conjuncts(e.right)
        else:
            yield e

    def side_of(v):
        if v.stream_id in left_keys:
            return "left"
        if v.stream_id in right_keys:
            return "right"
        return None

    out: List[Tuple[object, object, object]] = []
    for c in conjuncts(on):
        if not isinstance(c, ex.Compare) or c.operator != "==":
            continue
        if not (isinstance(c.left, ex.Variable) and
                isinstance(c.right, ex.Variable)):
            continue
        sides = (side_of(c.left), side_of(c.right))
        if sides == ("left", "right"):
            out.append((c, c.left, c.right))
        elif sides == ("right", "left"):
            out.append((c, c.right, c.left))
    return out


# lane width floor for the bucketed join probe; host occupancy tracking
# grows it in power-of-two steps (core/join.py JoinKeyTracker)
JOIN_LANE_K_MIN = 8


def join_fastpath(jis, side_kind, table_probe_attrs=None
                  ) -> Tuple[Optional[str], List, Optional[str]]:
    """Equi-join fast-path decision: (mode, pairs, reason).

    mode 'bucket' — both sides are stream windows: key slots ride the
    window buffers and the step probes only same-bucket pairs.
    mode 'table' — one side is an indexed table and the trigger side is
    a windowless stream: the table's AttributeIndex/primary-key hash
    answers candidates host-side.  mode None + reason — an equality
    conjunct exists but the fast path cannot apply (lint JOIN002 WARNs
    with exactly this string).  mode None + reason None — no equality
    conjunct (nothing to accelerate, JOIN002 stays silent).

    `side_kind(sid)` -> 'stream'|'table'|'named_window'|'aggregation';
    `table_probe_attrs(sid)` -> attribute names probe-able through a
    single-column @PrimaryKey or an @Index (table mode only)."""
    pairs = join_equi_pairs(jis)
    if not pairs:
        return None, [], None
    sides = {}
    for label, sis in (("left", jis.left_input_stream),
                       ("right", jis.right_input_stream)):
        sides[label] = (sis, side_kind(sis.stream_id))
    kinds = {label: k for label, (_, k) in sides.items()}
    for label, (sis, kind) in sides.items():
        if kind in ("named_window", "aggregation"):
            return None, pairs, (
                f"{label} side {sis.stream_id!r} is a {kind} — its rows "
                f"are probed from a shared buffer the join cannot carry "
                f"key slots through")
    if kinds["left"] == "stream" and kinds["right"] == "stream":
        from ..query_api.query import Filter
        for label, (sis, _) in sides.items():
            if any(isinstance(h, Filter) for h in sis.stream_handlers):
                return None, pairs, (
                    f"{label} side {sis.stream_id!r} has a stream filter "
                    f"— host key-retention tracking would under-count "
                    f"the window and could free live key buckets")
        return "bucket", pairs, None
    # stream-table: the stream side triggers, the table answers probes
    t_label = "left" if kinds["left"] == "table" else "right"
    s_label = "right" if t_label == "left" else "left"
    t_sis = sides[t_label][0]
    s_sis = sides[s_label][0]
    if kinds[s_label] != "stream":
        return None, pairs, "cannot join two table-like sides"
    if window_handler(s_sis) is not None:
        return None, pairs, (
            f"windowed stream side {s_sis.stream_id!r} joining table "
            f"{t_sis.stream_id!r} — buffered rows cannot re-probe the "
            f"table index at step time")
    probe_attrs = set(table_probe_attrs(t_sis.stream_id)) \
        if table_probe_attrs is not None else set()
    usable = []
    for c, lv, rv in pairs:
        t_var = lv if t_label == "left" else rv
        if t_var.attribute_name in probe_attrs:
            usable.append((c, lv, rv))
    if not usable:
        attrs = ", ".join(
            repr((lv if t_label == "left" else rv).attribute_name)
            for _, lv, rv in pairs)
        return None, pairs, (
            f"table {t_sis.stream_id!r} has no single-column @PrimaryKey "
            f"or @Index on join key {attrs} — equality probes stay "
            f"linear scans")
    return "table", usable, None


def table_probe_attrs_of(tdef) -> List[str]:
    """Attribute names of a TableDefinition probe-able by hash: a
    single-column @PrimaryKey plus every @Index attribute (reference:
    EventHolderPasser.java builds exactly these maps)."""
    out: List[str] = []
    pk = tdef.get_annotation("PrimaryKey")
    if pk is not None:
        names = pk.positional_elements()
        if len(names) == 1:
            out.append(names[0])
    idx = tdef.get_annotation("Index")
    if idx is not None:
        out.extend(n for n in idx.positional_elements() if n not in out)
    return out


def format_component_bytes(comps: Dict[str, int],
                           limit: int = 6) -> str:
    """Human-facing component breakdown, largest first — the SAME string
    shape in lint MEM001 findings and AdmissionDeniedError messages, so
    an operator can line the two up by eye."""
    items: List[Tuple[str, int]] = sorted(
        comps.items(), key=lambda kv: (-kv[1], kv[0]))
    parts = [f"{k}={v / (1024 * 1024):.1f} MiB" for k, v in items[:limit]]
    if len(items) > limit:
        parts.append(f"... +{len(items) - limit} more")
    return ", ".join(parts)
