"""Shared compiled-plan fact helpers: uncapped-sentinel rendering and
fusion-exclusion reasons.

Three surfaces report the same two plan facts — whether a query's
emission cap is real or the 1<<30 "effectively uncapped" sentinel, and
why a requested `@fuse` was skipped at wiring time: the static analyzer
(`siddhi_tpu/analysis`), EXPLAIN (`observability/explain.py`), and
`/healthz` (`observability/health.py`).  Each used to re-derive them
locally (the sentinel rendering lived only in explain; the exclusion
reason only in a wiring-time log line), so the renderings could drift.
This module is the single source of truth all three import.
"""
from __future__ import annotations

from typing import Dict, Optional

# pattern_planner's compact_rows default for non-partitioned patterns:
# "effectively uncapped" (a per-key cap with K=1 would cap the batch).
# Every surface that renders an emission cap must treat values at or
# above this sentinel as "no cap", never as a 1073741824-row budget.
UNCAPPED_SENTINEL = 1 << 30


def render_cap(rows: Optional[int]) -> Optional[int]:
    """Human-facing emission cap: None when absent or at/above the
    uncapped sentinel, else the concrete row count."""
    if rows is None:
        return None
    rows = int(rows)
    return None if rows >= UNCAPPED_SENTINEL else rows


def fusion_exclusion(qr) -> Optional[str]:
    """The concrete reason @fuse was requested but skipped for this query
    runtime, or None (fusing, eligible, or never requested).

    Prefers the reason stored at wiring time (runtime._maybe_fuse) and
    falls back to recomputing from the plan's static properties, so a
    runtime restored from a snapshot still reports it.  Attribute reads
    only — safe on the scrape path."""
    why = getattr(qr, "_fuse_excluded", None)
    if why is not None:
        return why
    if getattr(qr, "_fuse_requested", 0) and \
            getattr(qr, "_fuse", None) is None:
        from . import fusion
        try:
            return fusion.ineligible_reason(
                qr, getattr(qr, "_kind", "plain"))
        except Exception:  # noqa: BLE001 — diagnostics must not throw
            return "unknown (plan facts unavailable)"
    return None


def fusion_exclusions(rt) -> Dict[str, str]:
    """{query: exclusion reason} for every runtime of an app whose @fuse
    request was skipped at wiring time (empty when none were)."""
    out: Dict[str, str] = {}
    for name, qr in list(getattr(rt, "query_runtimes", {}).items()):
        why = fusion_exclusion(qr)
        if why is not None:
            out[name] = why
    return out
