"""Shared compiled-plan fact helpers: uncapped-sentinel rendering,
fusion-exclusion reasons, and the static state-bytes estimator.

Three surfaces report the same two plan facts — whether a query's
emission cap is real or the 1<<30 "effectively uncapped" sentinel, and
why a requested `@fuse` was skipped at wiring time: the static analyzer
(`siddhi_tpu/analysis`), EXPLAIN (`observability/explain.py`), and
`/healthz` (`observability/health.py`).  Each used to re-derive them
locally (the sentinel rendering lived only in explain; the exclusion
reason only in a wiring-time log line), so the renderings could drift.
This module is the single source of truth all three import.

The same single-source rule applies to the *static state-bytes
estimate*: lint's MEM001 rule and the admission controller's
deploy-time memory gate (core/admission.py) must agree on how big an
app's device state will be BEFORE anything is planned or traced, or an
app could lint green and still be denied at deploy (or vice versa).
`static_state_components` below is that one implementation — a pure
AST walk mirroring the planner/runtime capacity defaults, shape×dtype
arithmetic only, never touching jax — and both consumers cite the same
per-component breakdown it returns.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

# pattern_planner's compact_rows default for non-partitioned patterns:
# "effectively uncapped" (a per-key cap with K=1 would cap the batch).
# Every surface that renders an emission cap must treat values at or
# above this sentinel as "no cap", never as a 1073741824-row budget.
UNCAPPED_SENTINEL = 1 << 30


def render_cap(rows: Optional[int]) -> Optional[int]:
    """Human-facing emission cap: None when absent or at/above the
    uncapped sentinel, else the concrete row count."""
    if rows is None:
        return None
    rows = int(rows)
    return None if rows >= UNCAPPED_SENTINEL else rows


def fusion_exclusion(qr) -> Optional[str]:
    """The concrete reason @fuse was requested but skipped for this query
    runtime, or None (fusing, eligible, or never requested).

    Prefers the reason stored at wiring time (runtime._maybe_fuse) and
    falls back to recomputing from the plan's static properties, so a
    runtime restored from a snapshot still reports it.  Attribute reads
    only — safe on the scrape path."""
    why = getattr(qr, "_fuse_excluded", None)
    if why is not None:
        return why
    if getattr(qr, "_fuse_requested", 0) and \
            getattr(qr, "_fuse", None) is None:
        from . import fusion
        try:
            return fusion.ineligible_reason(
                qr, getattr(qr, "_kind", "plain"))
        except Exception:  # noqa: BLE001 — diagnostics must not throw
            return "unknown (plan facts unavailable)"
    return None


def fusion_exclusions(rt) -> Dict[str, str]:
    """{query: exclusion reason} for every runtime of an app whose @fuse
    request was skipped at wiring time (empty when none were)."""
    out: Dict[str, str] = {}
    for name, qr in list(getattr(rt, "query_runtimes", {}).items()):
        why = fusion_exclusion(qr)
        if why is not None:
            out[name] = why
    return out


# ---------------------------------------------------------------------------
# static state-bytes estimator (shared by lint MEM001 and the admission
# deploy gate — one implementation, one component breakdown)
# ---------------------------------------------------------------------------

# mirrors of the planner/runtime defaults (planner.plan_single_query,
# runtime._add_query/_add_partition) — the static estimates must predict
# what those paths would build
BATCH_CAPACITY = 512
WINDOW_HINT = 2048
PARTITION_WINDOW_HINT = 128
PARTITION_KEYS = 4096
NFA_SLOTS = 8
# columnar buffer overhead per row beyond the payload columns:
# ts i64 + seq i64 + gslot i32 + alive bool (core/window.py empty_buffer)
ROW_OVERHEAD = 8 + 8 + 4 + 1


def iter_named_queries(app):
    """(name, query, partition|None) with runtime-identical naming
    (mirrors SiddhiAppRuntime._query_name: @info name, else `query<i>`
    numbered across top-level queries and partition bodies)."""
    from ..query_api.query import Partition, Query
    qi = 0

    def name_of(q) -> str:
        info = q.get_annotation("info")
        if info:
            n = info.element("name")
            if n:
                return n
        return f"query{qi + 1}"

    for element in app.execution_element_list:
        if isinstance(element, Query):
            yield name_of(element), element, None
            qi += 1
        elif isinstance(element, Partition):
            for q in element.query_list:
                yield name_of(q), q, element
                qi += 1


def query_kind(q) -> str:
    from ..query_api.query import JoinInputStream, StateInputStream
    if isinstance(q.input_stream, JoinInputStream):
        return "join"
    if isinstance(q.input_stream, StateInputStream):
        return "pattern"
    return "plain"


def window_handler(sis):
    from ..query_api.query import Window
    for h in getattr(sis, "stream_handlers", ()):
        if isinstance(h, Window):
            return h
    return None


def pattern_atoms(el) -> List:
    """Flat list of the stream/absent atoms of a state-element tree."""
    from ..query_api.query import (
        AbsentStreamStateElement,
        CountStateElement,
        EveryStateElement,
        LogicalStateElement,
        NextStateElement,
        StreamStateElement,
    )
    out: List = []

    def rec(e):
        if isinstance(e, (StreamStateElement, AbsentStreamStateElement)):
            out.append(e)
        elif isinstance(e, CountStateElement):
            rec(e.stream_state_element)
        elif isinstance(e, LogicalStateElement):
            rec(e.stream_state_element_1)
            rec(e.stream_state_element_2)
        elif isinstance(e, NextStateElement):
            rec(e.state_element)
            rec(e.next_state_element)
        elif isinstance(e, EveryStateElement):
            rec(e.state_element)

    rec(el)
    return out


def window_capacity(win, hint: int) -> int:
    """Resident-row capacity the planner would give this window: the
    first non-time integer parameter (length/lengthBatch/sort/... row
    counts), else the capacity hint time-based windows are built with."""
    if win is None:
        return BATCH_CAPACITY
    from ..query_api.expression import Constant
    for p in win.parameters:
        if isinstance(p, Constant) and p.type in ("INT", "LONG") and \
                not getattr(p, "is_time", False):
            return max(1, int(p.value))
    return hint


def capacity_annotation(q, part) -> Dict[str, int]:
    """@capacity(keys=, slots=, window=) merged across the query and its
    partition (runtime._add_partition scans both)."""
    out: Dict[str, int] = {}
    anns = list(q.annotations)
    if part is not None:
        anns += list(part.annotations)
        for pq in part.query_list:
            anns += list(pq.annotations)
    for ann in anns:
        if ann.name.lower() == "capacity":
            for k in ("keys", "slots", "window"):
                v = ann.element(k)
                if v is not None:
                    out[k] = int(v)
    return out


def row_bytes(sdef) -> int:
    """Bytes per buffered window row: payload columns (device dtypes via
    event.dtype_of — STRING is an interned i32, DOUBLE an f32 on TPU)
    plus the fixed Buffer bookkeeping columns."""
    import numpy as np

    from . import event as ev
    n = ROW_OVERHEAD
    for a in getattr(sdef, "attribute_list", ()):
        try:
            n += int(np.dtype(ev.dtype_of(a.type)).itemsize)
        except Exception:  # noqa: BLE001 — OBJECT columns etc.
            n += 8
    return n


def query_state_components(app, q, kind: str, part,
                           caps: Dict[str, int],
                           keys: int) -> Dict[str, int]:
    """Per-component shape×dtype estimate of the device state the
    planner would allocate for ONE query (windows and NFA slot blocks;
    group-by slabs are bounded and small by comparison).  Empty dict
    when the query holds no estimable state."""
    defs = app.stream_definition_map

    def stream_def(sid):
        return defs.get(sid) or app.window_definition_map.get(sid)

    hint = caps.get(
        "window",
        PARTITION_WINDOW_HINT if part is not None else WINDOW_HINT)
    if kind == "plain":
        win = window_handler(q.input_stream)
        if win is None:
            return {}
        rows = window_capacity(win, hint)
        per_key = rows * row_bytes(stream_def(q.input_stream.stream_id))
        return {"window": per_key * (keys if part is not None else 1)}
    if kind == "join":
        out: Dict[str, int] = {}
        for side, sis in (("join.left", q.input_stream.left_input_stream),
                          ("join.right",
                           q.input_stream.right_input_stream)):
            win = window_handler(sis)
            if win is not None:
                out[side] = window_capacity(win, WINDOW_HINT) * \
                    row_bytes(stream_def(sis.stream_id))
        return out
    # pattern: per-key NFA slot block — `slots` pending matches per key,
    # each capturing one row per pattern state
    atoms = pattern_atoms(q.input_stream.state_element)
    slots = caps.get("slots", NFA_SLOTS)
    per_state = max(
        (row_bytes(stream_def(a.basic_single_input_stream.stream_id))
         for a in atoms), default=ROW_OVERHEAD)
    return {"pattern_slots": (keys if part is not None else 1) * slots *
            max(1, len(atoms)) * per_state}


def static_state_components(app) -> Dict[str, Dict[str, int]]:
    """{query: {component: bytes}} static state estimate for every query
    of a parsed (unplanned) app — THE shared MEM001/deploy-gate numbers.
    Pure AST walk; never plans, traces, or allocates."""
    out: Dict[str, Dict[str, int]] = {}
    for name, q, part in iter_named_queries(app):
        kind = query_kind(q)
        caps = capacity_annotation(q, part)
        keys = caps.get("keys", PARTITION_KEYS)
        comps = query_state_components(app, q, kind, part, caps, keys)
        if comps:
            out[name] = comps
    return out


def static_state_bytes(app) -> int:
    """Total static state estimate across the app's queries."""
    return sum(sum(c.values())
               for c in static_state_components(app).values())


def format_component_bytes(comps: Dict[str, int],
                           limit: int = 6) -> str:
    """Human-facing component breakdown, largest first — the SAME string
    shape in lint MEM001 findings and AdmissionDeniedError messages, so
    an operator can line the two up by eye."""
    items: List[Tuple[str, int]] = sorted(
        comps.items(), key=lambda kv: (-kv[1], kv[0]))
    parts = [f"{k}={v / (1024 * 1024):.1f} MiB" for k, v in items[:limit]]
    if len(items) > limit:
        parts.append(f"... +{len(items) - limit} more")
    return ", ".join(parts)
