"""Shared compiled-plan fact helpers: uncapped-sentinel rendering,
fusion-exclusion reasons, and the static state-bytes estimator.

Three surfaces report the same two plan facts — whether a query's
emission cap is real or the 1<<30 "effectively uncapped" sentinel, and
why a requested `@fuse` was skipped at wiring time: the static analyzer
(`siddhi_tpu/analysis`), EXPLAIN (`observability/explain.py`), and
`/healthz` (`observability/health.py`).  Each used to re-derive them
locally (the sentinel rendering lived only in explain; the exclusion
reason only in a wiring-time log line), so the renderings could drift.
This module is the single source of truth all three import.

The same single-source rule applies to the *static state-bytes
estimate*: lint's MEM001 rule and the admission controller's
deploy-time memory gate (core/admission.py) must agree on how big an
app's device state will be BEFORE anything is planned or traced, or an
app could lint green and still be denied at deploy (or vice versa).
`static_state_components` below is that one implementation — a pure
AST walk mirroring the planner/runtime capacity defaults, shape×dtype
arithmetic only, never touching jax — and both consumers cite the same
per-component breakdown it returns.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

# pattern_planner's compact_rows default for non-partitioned patterns:
# "effectively uncapped" (a per-key cap with K=1 would cap the batch).
# Every surface that renders an emission cap must treat values at or
# above this sentinel as "no cap", never as a 1073741824-row budget.
UNCAPPED_SENTINEL = 1 << 30


def render_cap(rows: Optional[int]) -> Optional[int]:
    """Human-facing emission cap: None when absent or at/above the
    uncapped sentinel, else the concrete row count."""
    if rows is None:
        return None
    rows = int(rows)
    return None if rows >= UNCAPPED_SENTINEL else rows


def fusion_exclusion(qr) -> Optional[str]:
    """The concrete reason @fuse was requested but skipped for this query
    runtime, or None (fusing, eligible, or never requested).

    Prefers the reason stored at wiring time (runtime._maybe_fuse) and
    falls back to recomputing from the plan's static properties, so a
    runtime restored from a snapshot still reports it.  Attribute reads
    only — safe on the scrape path."""
    why = getattr(qr, "_fuse_excluded", None)
    if why is not None:
        return why
    if getattr(qr, "_fuse_requested", 0) and \
            getattr(qr, "_fuse", None) is None:
        from . import fusion
        try:
            return fusion.ineligible_reason(
                qr, getattr(qr, "_kind", "plain"))
        except Exception:  # noqa: BLE001 — diagnostics must not throw
            return "unknown (plan facts unavailable)"
    return None


def fusion_exclusions(rt) -> Dict[str, str]:
    """{query: exclusion reason} for every runtime of an app whose @fuse
    request was skipped at wiring time (empty when none were)."""
    out: Dict[str, str] = {}
    for name, qr in list(getattr(rt, "query_runtimes", {}).items()):
        why = fusion_exclusion(qr)
        if why is not None:
            out[name] = why
    return out


# ---------------------------------------------------------------------------
# static state-bytes estimator (shared by lint MEM001 and the admission
# deploy gate — one implementation, one component breakdown)
# ---------------------------------------------------------------------------

# mirrors of the planner/runtime defaults (planner.plan_single_query,
# runtime._add_query/_add_partition) — the static estimates must predict
# what those paths would build
BATCH_CAPACITY = 512
WINDOW_HINT = 2048
PARTITION_WINDOW_HINT = 128
PARTITION_KEYS = 4096
NFA_SLOTS = 8
# default serving emission-ring slot count (serving/ring.py) when
# neither @serve(ring.capacity=) nor `serving.ring.capacity` says
# otherwise — kept here so the static state estimator and the runtime
# agree on the ring's footprint
SERVE_RING_SLOTS = 8
# columnar buffer overhead per row beyond the payload columns:
# ts i64 + seq i64 + gslot i32 + alive bool (core/window.py empty_buffer)
ROW_OVERHEAD = 8 + 8 + 4 + 1


def iter_named_queries(app):
    """(name, query, partition|None) with runtime-identical naming
    (mirrors SiddhiAppRuntime._query_name: @info name, else `query<i>`
    numbered across top-level queries and partition bodies)."""
    from ..query_api.query import Partition, Query
    qi = 0

    def name_of(q) -> str:
        info = q.get_annotation("info")
        if info:
            n = info.element("name")
            if n:
                return n
        return f"query{qi + 1}"

    for element in app.execution_element_list:
        if isinstance(element, Query):
            yield name_of(element), element, None
            qi += 1
        elif isinstance(element, Partition):
            for q in element.query_list:
                yield name_of(q), q, element
                qi += 1


def query_kind(q) -> str:
    from ..query_api.query import JoinInputStream, StateInputStream
    if isinstance(q.input_stream, JoinInputStream):
        return "join"
    if isinstance(q.input_stream, StateInputStream):
        return "pattern"
    return "plain"


def window_handler(sis):
    from ..query_api.query import Window
    for h in getattr(sis, "stream_handlers", ()):
        if isinstance(h, Window):
            return h
    return None


def pattern_atoms(el) -> List:
    """Flat list of the stream/absent atoms of a state-element tree."""
    from ..query_api.query import (
        AbsentStreamStateElement,
        CountStateElement,
        EveryStateElement,
        LogicalStateElement,
        NextStateElement,
        StreamStateElement,
    )
    out: List = []

    def rec(e):
        if isinstance(e, (StreamStateElement, AbsentStreamStateElement)):
            out.append(e)
        elif isinstance(e, CountStateElement):
            rec(e.stream_state_element)
        elif isinstance(e, LogicalStateElement):
            rec(e.stream_state_element_1)
            rec(e.stream_state_element_2)
        elif isinstance(e, NextStateElement):
            rec(e.state_element)
            rec(e.next_state_element)
        elif isinstance(e, EveryStateElement):
            rec(e.state_element)

    rec(el)
    return out


def window_capacity(win, hint: int) -> int:
    """Resident-row capacity the planner would give this window: the
    first non-time integer parameter (length/lengthBatch/sort/... row
    counts), else the capacity hint time-based windows are built with."""
    if win is None:
        return BATCH_CAPACITY
    from ..query_api.expression import Constant
    for p in win.parameters:
        if isinstance(p, Constant) and p.type in ("INT", "LONG") and \
                not getattr(p, "is_time", False):
            return max(1, int(p.value))
    return hint


def capacity_annotation(q, part) -> Dict[str, int]:
    """@capacity(keys=, slots=, window=) merged across the query and its
    partition (runtime._add_partition scans both)."""
    out: Dict[str, int] = {}
    anns = list(q.annotations)
    if part is not None:
        anns += list(part.annotations)
        for pq in part.query_list:
            anns += list(pq.annotations)
    for ann in anns:
        if ann.name.lower() == "capacity":
            for k in ("keys", "slots", "window"):
                v = ann.element(k)
                if v is not None:
                    out[k] = int(v)
    return out


def row_bytes(sdef) -> int:
    """Bytes per buffered window row: payload columns (device dtypes via
    event.dtype_of — STRING is an interned i32, DOUBLE an f32 on TPU)
    plus the fixed Buffer bookkeeping columns."""
    import numpy as np

    from . import event as ev
    n = ROW_OVERHEAD
    for a in getattr(sdef, "attribute_list", ()):
        try:
            n += int(np.dtype(ev.dtype_of(a.type)).itemsize)
        except Exception:  # noqa: BLE001 — OBJECT columns etc.
            n += 8
    return n


def query_state_components(app, q, kind: str, part,
                           caps: Dict[str, int],
                           keys: int) -> Dict[str, int]:
    """Per-component shape×dtype estimate of the device state the
    planner would allocate for ONE query (windows and NFA slot blocks;
    group-by slabs are bounded and small by comparison).  Empty dict
    when the query holds no estimable state."""
    defs = app.stream_definition_map

    def stream_def(sid):
        return defs.get(sid) or app.window_definition_map.get(sid)

    hint = caps.get(
        "window",
        PARTITION_WINDOW_HINT if part is not None else WINDOW_HINT)
    if kind == "plain":
        win = window_handler(q.input_stream)
        if win is None:
            return {}
        rows = window_capacity(win, hint)
        per_key = rows * row_bytes(stream_def(q.input_stream.stream_id))
        return {"window": per_key * (keys if part is not None else 1)}
    if kind == "join":
        out: Dict[str, int] = {}

        def _kind_of(sid):
            if sid in app.aggregation_definition_map:
                return "aggregation"
            if sid in app.window_definition_map:
                return "named_window"
            if sid in app.table_definition_map:
                return "table"
            return "stream"

        def _probe_attrs(sid):
            d = app.table_definition_map.get(sid)
            return table_probe_attrs_of(d) if d is not None else []

        try:
            fp_mode, _, _ = join_fastpath(q.input_stream, _kind_of,
                                          _probe_attrs)
        except Exception:  # noqa: BLE001 — estimator must not throw
            fp_mode = None
        # bucketed sides carry one extra i32 key-slot column per row
        extra = 4 if fp_mode == "bucket" else 0
        for side, sis in (("join.left", q.input_stream.left_input_stream),
                          ("join.right",
                           q.input_stream.right_input_stream)):
            win = window_handler(sis)
            if win is not None:
                out[side] = window_capacity(win, WINDOW_HINT) * \
                    (row_bytes(stream_def(sis.stream_id)) + extra)
        return out
    # pattern: per-key NFA slot block — `slots` pending matches per key,
    # each capturing one row per pattern state
    atoms = pattern_atoms(q.input_stream.state_element)
    slots = caps.get("slots", NFA_SLOTS)
    per_state = max(
        (row_bytes(stream_def(a.basic_single_input_stream.stream_id))
         for a in atoms), default=ROW_OVERHEAD)
    return {"pattern_slots": (keys if part is not None else 1) * slots *
            max(1, len(atoms)) * per_state}


def static_state_components(app, mesh_devices: int = 0,
                            merged: bool = True
                            ) -> Dict[str, Dict[str, int]]:
    """{owner: {component: bytes}} static state estimate for every query
    of a parsed (unplanned) app — THE shared MEM001/deploy-gate numbers.
    Pure AST walk; never plans, traces, or allocates.

    When the multi-query optimizer would share a window buffer between
    co-resident queries (`merge_plan` shared units), the shared buffer
    is counted ONCE under the ``merged:<group>`` owner and the member
    queries keep only their exclusive bytes — the same no-double-count
    contract the live accounting (observability/memory.py) honors.
    Pass ``merged=False`` (or a multi-device mesh) to estimate the
    unmerged layout."""
    out: Dict[str, Dict[str, int]] = {}
    for name, q, part in iter_named_queries(app):
        kind = query_kind(q)
        caps = capacity_annotation(q, part)
        keys = caps.get("keys", PARTITION_KEYS)
        comps = query_state_components(app, q, kind, part, caps, keys)
        if serve_enabled(app, q):
            # serving emission ring (serving/ring.py): device-resident,
            # so it counts against the same MEM001/deploy-gate budget
            # window buffers do
            comps = dict(comps)
            comps["serve_ring"] = serve_ring_bytes(app, q, kind, part,
                                                   caps)
        if comps:
            out[name] = comps
    if merged and mesh_devices <= 1:
        try:
            plan = merge_plan(app, mesh_devices)
        except Exception:  # noqa: BLE001 — estimator must not throw
            plan = {"groups": []}
        for g in plan["groups"]:
            shared_total = 0
            for u in g["units"]:
                if u["mode"] != "shared":
                    continue
                lead = u["members"][0]
                shared_total += out.get(lead, {}).get("window", 0)
                for m in u["members"]:
                    comps = out.get(m)
                    if comps and "window" in comps:
                        comps = dict(comps)
                        del comps["window"]
                        if comps:
                            out[m] = comps
                        else:
                            del out[m]
            if shared_total:
                out[f"merged:{g['group']}"] = {
                    MERGE_SHARED_COMPONENT: shared_total}
    return out


def static_state_bytes(app) -> int:
    """Total static state estimate across the app's queries."""
    return sum(sum(c.values())
               for c in static_state_components(app).values())


# ---------------------------------------------------------------------------
# equi-join fast-path facts (shared by the join planner, lint JOIN002,
# and EXPLAIN — one implementation, one set of reason strings, so lint
# prints exactly the condition the wiring tested)
# ---------------------------------------------------------------------------

def join_equi_pairs(jis) -> List[Tuple[object, object, object]]:
    """Top-level `==` conjuncts of a join ON-condition comparing one
    side-qualified attribute from each side: [(Compare node, left
    Variable, right Variable)], the left side's variable first whatever
    the written order.  The same shape analysis/typeflow._equi_conjuncts
    reports — kept AST-only so the planner can run it pre-compile."""
    from ..query_api import expression as ex
    on = getattr(jis, "on_compare", None)
    if on is None:
        return []
    ls, rs = jis.left_input_stream, jis.right_input_stream
    left_keys = {ls.stream_reference_id or ls.stream_id, ls.stream_id}
    right_keys = {rs.stream_reference_id or rs.stream_id, rs.stream_id}

    def conjuncts(e):
        if isinstance(e, ex.And):
            yield from conjuncts(e.left)
            yield from conjuncts(e.right)
        else:
            yield e

    def side_of(v):
        if v.stream_id in left_keys:
            return "left"
        if v.stream_id in right_keys:
            return "right"
        return None

    out: List[Tuple[object, object, object]] = []
    for c in conjuncts(on):
        if not isinstance(c, ex.Compare) or c.operator != "==":
            continue
        if not (isinstance(c.left, ex.Variable) and
                isinstance(c.right, ex.Variable)):
            continue
        sides = (side_of(c.left), side_of(c.right))
        if sides == ("left", "right"):
            out.append((c, c.left, c.right))
        elif sides == ("right", "left"):
            out.append((c, c.right, c.left))
    return out


# lane width floor for the bucketed join probe; host occupancy tracking
# grows it in power-of-two steps (core/join.py JoinKeyTracker)
JOIN_LANE_K_MIN = 8


def join_fastpath(jis, side_kind, table_probe_attrs=None
                  ) -> Tuple[Optional[str], List, Optional[str]]:
    """Equi-join fast-path decision: (mode, pairs, reason).

    mode 'bucket' — both sides are stream windows: key slots ride the
    window buffers and the step probes only same-bucket pairs.
    mode 'table' — one side is an indexed table and the trigger side is
    a windowless stream: the table's AttributeIndex/primary-key hash
    answers candidates host-side.  mode None + reason — an equality
    conjunct exists but the fast path cannot apply (lint JOIN002 WARNs
    with exactly this string).  mode None + reason None — no equality
    conjunct (nothing to accelerate, JOIN002 stays silent).

    `side_kind(sid)` -> 'stream'|'table'|'named_window'|'aggregation';
    `table_probe_attrs(sid)` -> attribute names probe-able through a
    single-column @PrimaryKey or an @Index (table mode only)."""
    pairs = join_equi_pairs(jis)
    if not pairs:
        return None, [], None
    sides = {}
    for label, sis in (("left", jis.left_input_stream),
                       ("right", jis.right_input_stream)):
        sides[label] = (sis, side_kind(sis.stream_id))
    kinds = {label: k for label, (_, k) in sides.items()}
    for label, (sis, kind) in sides.items():
        if kind in ("named_window", "aggregation"):
            return None, pairs, (
                f"{label} side {sis.stream_id!r} is a {kind} — its rows "
                f"are probed from a shared buffer the join cannot carry "
                f"key slots through")
    if kinds["left"] == "stream" and kinds["right"] == "stream":
        from ..query_api.query import Filter
        for label, (sis, _) in sides.items():
            if any(isinstance(h, Filter) for h in sis.stream_handlers):
                return None, pairs, (
                    f"{label} side {sis.stream_id!r} has a stream filter "
                    f"— host key-retention tracking would under-count "
                    f"the window and could free live key buckets")
        return "bucket", pairs, None
    # stream-table: the stream side triggers, the table answers probes
    t_label = "left" if kinds["left"] == "table" else "right"
    s_label = "right" if t_label == "left" else "left"
    t_sis = sides[t_label][0]
    s_sis = sides[s_label][0]
    if kinds[s_label] != "stream":
        return None, pairs, "cannot join two table-like sides"
    if window_handler(s_sis) is not None:
        return None, pairs, (
            f"windowed stream side {s_sis.stream_id!r} joining table "
            f"{t_sis.stream_id!r} — buffered rows cannot re-probe the "
            f"table index at step time")
    probe_attrs = set(table_probe_attrs(t_sis.stream_id)) \
        if table_probe_attrs is not None else set()
    usable = []
    for c, lv, rv in pairs:
        t_var = lv if t_label == "left" else rv
        if t_var.attribute_name in probe_attrs:
            usable.append((c, lv, rv))
    if not usable:
        attrs = ", ".join(
            repr((lv if t_label == "left" else rv).attribute_name)
            for _, lv, rv in pairs)
        return None, pairs, (
            f"table {t_sis.stream_id!r} has no single-column @PrimaryKey "
            f"or @Index on join key {attrs} — equality probes stay "
            f"linear scans")
    return "table", usable, None


def table_probe_attrs_of(tdef) -> List[str]:
    """Attribute names of a TableDefinition probe-able by hash: a
    single-column @PrimaryKey plus every @Index attribute (reference:
    EventHolderPasser.java builds exactly these maps)."""
    out: List[str] = []
    pk = tdef.get_annotation("PrimaryKey")
    if pk is not None:
        names = pk.positional_elements()
        if len(names) == 1:
            out.append(names[0])
    idx = tdef.get_annotation("Index")
    if idx is not None:
        out.extend(n for n in idx.positional_elements() if n not in out)
    return out


# ---------------------------------------------------------------------------
# multi-query merge facts (whole-app optimizer, siddhi_tpu/optimizer).
# ONE implementation decides which co-resident queries share a merged
# dispatch: the runtime optimizer pass, lint MQO001, and EXPLAIN's
# `merge` node all read the plan built here, so the reason lint prints
# is exactly the one the wiring applied.
# ---------------------------------------------------------------------------

# component label the shared window buffer of a merge group is reported
# under (observability/memory + the static estimator below): bytes held
# ONCE for the whole group, never per member
MERGE_SHARED_COMPONENT = "window[shared]"


def _expr_fp(e) -> str:
    """Stable structural fingerprint of a query_api expression tree —
    two filters with this fingerprint compile to the identical device
    program, which is the merge pass's sharing precondition."""
    from ..query_api import expression as ex
    if e is None:
        return "-"
    if isinstance(e, ex.Constant):
        return f"c:{e.type}:{e.value!r}"
    if isinstance(e, ex.Variable):
        idx = "" if e.stream_index is None else f"[{e.stream_index}]"
        return f"v:{e.stream_id or ''}{idx}.{e.attribute_name}"
    if isinstance(e, ex.Compare):
        return f"({_expr_fp(e.left)}{e.operator}{_expr_fp(e.right)})"
    if isinstance(e, ex.Not):
        return f"not({_expr_fp(e.expression)})"
    if isinstance(e, ex.IsNull):
        if getattr(e, "expression", None) is not None:
            return f"isnull({_expr_fp(e.expression)})"
        return f"isnull({e.stream_id})"
    if isinstance(e, ex.In):
        return f"in({_expr_fp(e.expression)},{e.source_id})"
    if isinstance(e, ex.AttributeFunction):
        ns = f"{e.namespace}:" if e.namespace else ""
        args = ",".join(_expr_fp(p) for p in e.parameters)
        return f"f:{ns}{e.name}({args})"
    left = getattr(e, "left", None)
    right = getattr(e, "right", None)
    if left is not None and right is not None:
        return f"{type(e).__name__}({_expr_fp(left)},{_expr_fp(right)})"
    return type(e).__name__


def handler_fingerprints(sis) -> Tuple[Tuple[str, ...], str,
                                       Tuple[str, ...]]:
    """(pre-window chain, window, post-window chain) fingerprints of a
    SingleInputStream's handler chain.  Queries can only share one
    window buffer when the pre-chain AND window fingerprints agree —
    different pre-filters would admit different rows into the buffer."""
    from ..query_api.query import Filter, StreamFunction, Window
    pre: List[str] = []
    post: List[str] = []
    win = "-"
    seen = False
    for h in getattr(sis, "stream_handlers", ()):
        if isinstance(h, Window):
            ns = f"{h.namespace}:" if h.namespace else ""
            win = f"w:{ns}{h.name}(" + ",".join(
                _expr_fp(p) for p in h.parameters) + ")"
            seen = True
        elif isinstance(h, Filter):
            (post if seen else pre).append(f"filt:{_expr_fp(h.expression)}")
        elif isinstance(h, StreamFunction):
            ns = f"{h.namespace}:" if h.namespace else ""
            fp = f"fn:{ns}{h.name}(" + ",".join(
                _expr_fp(p) for p in h.parameters) + ")"
            (post if seen else pre).append(fp)
    return tuple(pre), win, tuple(post)


def async_enabled(app, q) -> bool:
    """@async on the app, the query, or any input stream definition —
    the ONE implementation runtime wiring (`_async_enabled`) and the
    merge planner share."""
    if app.get_annotation("async") is not None:
        return True
    if q.get_annotation("async") is not None:
        return True
    ist = q.input_stream
    sids = getattr(ist, "all_stream_ids", None) or \
        [getattr(ist, "stream_id", None)]
    for sid in sids:
        sdef = app.stream_definition_map.get(sid)
        if sdef is not None and sdef.get_annotation("async") is not None:
            return True
    return False


def pipeline_depth(app, q) -> int:
    """@pipeline(depth=k) on the query (wins) or @app:pipeline; 0 = off
    (shared by runtime `_pipeline_enabled` and the merge planner)."""
    ann = q.get_annotation("pipeline")
    if ann is None:
        ann = app.get_annotation("app:pipeline")
    if ann is None:
        return 0
    return max(1, int(ann.element("depth", 1) or 1))


def fuse_depth(app, q) -> int:
    """@fuse(batches=K) on the query, any input stream definition, or
    @app:fuse; 0 = off (shared by runtime `_fuse_enabled`, lint's
    `fuse_requested`, and the merge planner)."""
    ann = q.get_annotation("fuse")
    if ann is None:
        ist = q.input_stream
        sids = getattr(ist, "all_stream_ids", None) or \
            [getattr(ist, "stream_id", None)]
        for sid in sids:
            sdef = app.stream_definition_map.get(sid)
            if sdef is not None and \
                    sdef.get_annotation("fuse") is not None:
                ann = sdef.get_annotation("fuse")
                break
    if ann is None:
        ann = app.get_annotation("app:fuse")
    if ann is None:
        return 0
    k = ann.element("batches", ann.element(None, 8)) or 8
    return max(1, int(k))


def serve_enabled(app, q) -> bool:
    """@serve on the query, any input stream definition, or @app:serve —
    the device-resident serving loop (siddhi_tpu/serving): emissions
    append to an on-device ring and the async drainer delivers them;
    the send path never fetches.  `enabled='false'` opts a query out of
    an app-wide @app:serve.  The ONE implementation runtime wiring
    (`_serve_enabled`), the merge planner, EXPLAIN, and lint SERVE001
    share.  (The `serving.enabled` config property enables serving at
    the runtime level without annotations — that path is resolved in
    runtime wiring, not here: plan facts stay pure AST.)"""
    ann = q.get_annotation("serve")
    if ann is None:
        ist = q.input_stream
        sids = getattr(ist, "all_stream_ids", None) or \
            [getattr(ist, "stream_id", None)]
        for sid in sids:
            sdef = app.stream_definition_map.get(sid)
            if sdef is not None and \
                    sdef.get_annotation("serve") is not None:
                ann = sdef.get_annotation("serve")
                break
    if ann is None:
        ann = app.get_annotation("app:serve")
    if ann is None:
        return False
    flag = str(ann.element("enabled", "true") or "true").lower()
    return flag not in ("false", "0", "no", "off")


def serve_ring_capacity(app, q) -> int:
    """@serve(ring.capacity=S) on the query (wins) or @app:serve; 0
    means "use the `serving.ring.capacity` config property / default"."""
    ann = q.get_annotation("serve")
    if ann is None:
        ann = app.get_annotation("app:serve")
    if ann is None:
        return 0
    try:
        return max(0, int(ann.element("ring.capacity", 0) or 0))
    except Exception:  # noqa: BLE001 — malformed element reads as unset
        return 0


def serve_ring_bytes(app, q, kind: str, part, caps: Dict[str, int]) -> int:
    """Static estimate of one query's serving emission ring
    (serving/ring.py): SERVE_RING_SLOTS stacked output blocks.  Output
    rows bound by the window/batch capacity; row width is ts i64 +
    kind i32 + valid bool + one device word per selected column."""
    hint = caps.get(
        "window",
        PARTITION_WINDOW_HINT if part is not None else WINDOW_HINT)
    if kind == "plain":
        rows = window_capacity(window_handler(q.input_stream), hint)
    else:
        rows = hint
    slots = serve_ring_capacity(app, q) or SERVE_RING_SLOTS
    ncols = max(1, len(q.selector.selection_list))
    return slots * rows * (12 + 1 + 8 * ncols)


def merge_decorations(app, q) -> Tuple:
    """The emission/dispatch decorations that must agree across a merge
    group: members of one dispatch share the demux path, so @async,
    @pipeline depth, @fuse K, and @serve cannot differ within a
    group."""
    return (async_enabled(app, q), pipeline_depth(app, q),
            fuse_depth(app, q), serve_enabled(app, q))


def merge_ineligibility(app, q, kind: str, part,
                        mesh_devices: int = 0) -> Optional[str]:
    """Why ONE query can never join any merge group (None = eligible).
    Static AST properties only — the runtime optimizer pass re-validates
    against the actual plan and demotes on any surprise."""
    if mesh_devices > 1:
        return (f"app deployed on a {mesh_devices}-device mesh — "
                f"sharded dispatch is not merged")
    if part is not None:
        return "partitioned query — per-key dispatch is not merged"
    if kind == "pattern":
        return "pattern/sequence NFA keeps its own per-stream steps"
    if kind == "join":
        return "join side steps keep their own dispatch"
    sid = q.input_stream.unique_stream_id
    if sid in getattr(app, "window_definition_map", {}):
        return ("named-window input is delivered by the window "
                "runtime, not a stream junction")
    win = window_handler(q.input_stream)
    if win is not None:
        from .window import WINDOW_TYPES
        full = (win.namespace + ":" if win.namespace else "") + win.name
        cls = WINDOW_TYPES.get(full)
        if cls is not None and getattr(cls, "needs_timer", False):
            return (f"timer-bearing window ({full}) — the device wake "
                    f"scalar cannot ride a merged dispatch")
        if win.name == "session" and len(win.parameters) >= 2:
            return ("session(gap, key) runs the keyed-window slab — "
                    "per-key dispatch is not merged")
    return None


def _in_table_deps(app, q) -> set:
    """Tables this query probes with the `in` operator (filters +
    selector expressions) — merge-relevant because an unmerged plan
    lets a query observe a co-resident query's SAME-BATCH table writes,
    which a merged dispatch (one table snapshot per dispatch) would
    relax; the planner demotes such probers instead of relaxing."""
    from ..query_api.expression import In, walk
    from ..query_api.query import Filter
    exprs = []
    for h in getattr(q.input_stream, "stream_handlers", ()):
        if isinstance(h, Filter):
            exprs.append(h.expression)
    sel = q.selector
    exprs += [oa.expression for oa in sel.selection_list]
    if sel.having_expression is not None:
        exprs.append(sel.having_expression)
    deps = set()
    for e in exprs:
        for node in walk(e):
            if isinstance(node, In):
                deps.add(node.source_id)
    return {d for d in deps if d in app.table_definition_map}


def merge_plan(app, mesh_devices: int = 0) -> Dict:
    """The whole-app merge decision, statically.

    Returns ``{"groups": [...], "reasons": {query: reason}}`` where each
    group is ``{"group", "stream", "members", "decorations", "units"}``
    and each unit is ``{"mode": "shared"|"solo", "members": [...]}``.
    A *shared* unit's members stage one window buffer and one group-slot
    space (identical pre-chain + window + group-by); *solo* units run
    their full per-query body inside the merged dispatch.  Every query
    in no group appears in ``reasons`` with the planner's exact
    ineligibility string — lint MQO001, EXPLAIN, and the runtime
    optimizer pass (siddhi_tpu/optimizer) all read THIS plan."""
    reasons: Dict[str, str] = {}
    eligible: List[Tuple[str, object, Tuple]] = []
    for name, q, part in iter_named_queries(app):
        kind = query_kind(q)
        why = merge_ineligibility(app, q, kind, part, mesh_devices)
        if why is not None:
            reasons[name] = why
            continue
        eligible.append((name, q, merge_decorations(app, q)))

    # dispatch groups: same stream + same @async/@pipeline/@fuse
    by_key: Dict[Tuple, List[Tuple[str, object]]] = {}
    order: List[Tuple] = []
    for name, q, deco in eligible:
        key = (q.input_stream.unique_stream_id, deco)
        if key not in by_key:
            by_key[key] = []
            order.append(key)
        by_key[key].append((name, q))

    groups: List[Dict] = []
    per_stream: Dict[str, int] = {}
    for key in order:
        sid, deco = key
        members = by_key[key]
        # exactness demotions: merging must stay BYTE-IDENTICAL per
        # query, so (a) a member inserting into the group's own input
        # stream keeps its own dispatch (the unmerged plan interleaves
        # the feedback recursion mid-fanout; a merged demux would
        # reorder what co-members' windows see), and (b) a member
        # probing a table a CO-MEMBER writes keeps its own dispatch
        # (unmerged, it observes same-batch writes; a merged dispatch
        # snapshots tables once)
        written = {q.output_stream.target_id: name
                   for name, q in members
                   if q.output_stream is not None and
                   q.output_stream.target_id in app.table_definition_map}
        demoted: List[Tuple[str, str]] = []
        for name, q in members:
            if q.output_stream is not None and \
                    q.output_stream.target_id == sid:
                demoted.append((name, (
                    f"inserts into its own input stream {sid!r} — "
                    f"merging would reorder the feedback loop the "
                    f"unmerged fan-out interleaves")))
                continue
            hit = sorted(t for t in _in_table_deps(app, q)
                         if written.get(t) not in (None, name))
            if hit:
                demoted.append((name, (
                    f"probes table {hit[0]!r} written by co-resident "
                    f"query {written[hit[0]]!r} — same-batch "
                    f"read-your-writes must stay exact")))
        if demoted:
            dropped = {n for n, _ in demoted}
            for name, why in demoted:
                reasons[name] = why
            members = [(n, q) for n, q in members if n not in dropped]
        if len(members) < 2:
            for name, _q in members:
                reasons[name] = (
                    f"no co-resident query shares stream {sid!r} and "
                    f"its @async/@pipeline/@fuse/@serve decorations")
            continue
        gi = per_stream.get(sid, 0)
        per_stream[sid] = gi + 1
        gid = f"{sid}#{gi}"
        # state-share units: identical pre-chain + window + group-by
        # (and window capacity) members reference ONE window buffer and
        # ONE group-slot space; windowless members stay solo (their
        # window state is a scalar seq counter — nothing to share)
        units: List[Dict] = []
        shared: Dict[Tuple, List[str]] = {}
        shared_order: List[Tuple] = []
        for name, q in members:
            pre, win, _post = handler_fingerprints(q.input_stream)
            if win == "-":
                units.append({"mode": "solo", "members": [name]})
                continue
            caps = capacity_annotation(q, None)
            gby = tuple(_expr_fp(v) for v in q.selector.group_by_list)
            skey = (pre, win, gby, caps.get("window", 0))
            if skey not in shared:
                shared[skey] = []
                shared_order.append(skey)
                units.append({"mode": "solo", "members": [],
                              "_skey": skey})
            shared[skey].append(name)
        resolved: List[Dict] = []
        for u in units:
            skey = u.pop("_skey", None)
            if skey is None:
                resolved.append(u)
                continue
            names = shared[skey]
            resolved.append({
                "mode": "shared" if len(names) >= 2 else "solo",
                "members": names})
        groups.append({
            "group": gid, "stream": sid,
            "members": [n for n, _ in members],
            "decorations": {"async": bool(deco[0]),
                            "pipeline": int(deco[1]),
                            "fuse": int(deco[2]),
                            "serve": bool(deco[3])},
            "units": resolved,
        })
    return {"groups": groups, "reasons": reasons}


def merge_facts(qr) -> Dict:
    """Per-query merge fact for EXPLAIN and the audit fingerprint.

    ``{"merged": True, "group", "owner", "mode", "members",
    "group_dispatch_programs": 1}`` for a merged member;
    ``{"merged": False, "reason": ...}`` otherwise.  Attribute reads
    only — safe on diagnostic paths."""
    mg = getattr(qr, "_merged", None)
    if mg is not None:
        return {
            "merged": True,
            "group": mg.group,
            "owner": mg.name,
            "mode": mg.mode_of(qr),
            "members": [m.name for m in mg.members],
            "group_dispatch_programs": 1,
        }
    why = getattr(qr, "_merge_excluded", None)
    if why is not None:
        return {"merged": False, "reason": why}
    return {"merged": False}


def format_component_bytes(comps: Dict[str, int],
                           limit: int = 6) -> str:
    """Human-facing component breakdown, largest first — the SAME string
    shape in lint MEM001 findings and AdmissionDeniedError messages, so
    an operator can line the two up by eye."""
    items: List[Tuple[str, int]] = sorted(
        comps.items(), key=lambda kv: (-kv[1], kv[0]))
    parts = [f"{k}={v / (1024 * 1024):.1f} MiB" for k, v in items[:limit]]
    if len(items) > limit:
        parts.append(f"... +{len(items) - limit} more")
    return ", ".join(parts)
