"""App runtime: manager, junctions, input handlers, callbacks, scheduler.

Reference (what): CORE/SiddhiManager.java:49, CORE/SiddhiAppRuntimeImpl.java:99,
CORE/stream/StreamJunction.java:61, CORE/stream/input/InputHandler.java:50,
CORE/util/Scheduler.java:48.  The reference routes one pooled event at a time
through object chains with per-query locks; here the junction stages a whole
micro-batch into numpy once, each subscribing query computes its group slots
and runs its fused jitted step, and a host scheduler injects TIMER batches
for time-based windows.
"""
from __future__ import annotations

import collections
import contextlib
import heapq
import logging
import pickle
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from ..exceptions import (CannotRestoreStateError, DefinitionNotExistError,
                          MatchOverflowError, QueryNotExistError)
from ..observability import tracing as _tracing
from ..observability import phases as _phases
from ..observability import stateobs as _stateobs
from ..query_api.app import SiddhiApp
from ..query_api.definition import StreamDefinition
from ..query_api.query import Partition, Query, SingleInputStream
from . import event as ev
from .executor import CompileError
from .keyslots import SlotAllocator
from .planner import PlannedQuery, plan_single_query
from .window import NO_WAKEUP
from .steputil import jit_step
from . import fusion as _fusion
from .. import sharding as _sharding

_NO_WAKEUP_INT = int(NO_WAKEUP)

# @app:statistics DETAIL-level event tracing (reference: log4j TRACE at
# StreamJunction.sendEvent :147 and QuerySelector.process :77)
_trace_log = logging.getLogger("siddhi_tpu.trace")

# shared no-op context for span sites on the OFF/BASIC hot path (nullcontext
# enter/exit is stateless, so ONE instance serves every thread without
# allocating per batch)
_NULL_CM = contextlib.nullcontext()


def _maybe_span(stage: str, **meta):
    """A `tracing.span` when a DETAIL pipeline trace is active on this
    thread, else the shared no-op context — one thread-local read at
    OFF/BASIC, zero allocation."""
    if _tracing.active() is None:
        return _NULL_CM
    return _tracing.span(stage, **meta)


def _sub_name(sub, default: str) -> str:
    """Metric name of a junction subscriber (wrappers hold the runtime in
    _qr; plain runtimes carry .name)."""
    return getattr(getattr(sub, "_qr", sub), "name", default)


def _step_phase(qr, fn, name=None, mult=1):
    """Run one jitted step call, recording its wall as the
    `dispatch_submit` phase (async dispatch: the call returns at SUBMIT,
    so this wall says nothing about device time).  Every
    `profile.sample.every` dispatches per query the deep mode fences the
    returned pytree with `block_until_ready` and records the fence wall
    as `device_compute` — the only block the profiler ever takes, and
    never on the steady (unsampled) path.  `mult` is the number of
    source batches one dispatch serves (a @fuse stack of K): each of the
    K batches' `<q>:e2e` sample contains this full wall, so the phase
    charges it K times to keep sum(phases) tracking sum(e2e) — the
    attribution rule documented in observability/phases.py."""
    st = qr.app.stats
    if not st.enabled:
        return fn()
    qname = name or qr.name
    ph = st.phases
    t0 = time.perf_counter_ns()
    res = fn()
    t1 = time.perf_counter_ns()
    ph.add(qname, "dispatch_submit", (t1 - t0) * mult)
    every = _phases.sample_every(qr.app)
    if every and ph.should_sample(qname, every):
        jax.block_until_ready(res)
        ph.add(qname, "device_compute",
               (time.perf_counter_ns() - t1) * mult)
    return res


def _rebind_state(qr, v, mult=1, name=None, attr="state"):
    """Rebind a query's device state to the step's returned pytree,
    timing the rebind as `device_compute`.  Under async dispatch this
    plain assignment is where the device wall surfaces on the host:
    dropping the previous generation's buffers — live inputs of the
    step still executing — blocks in the XLA client until that step
    retires them.  No fence or fetch is added; the wait is inherent to
    the rebind, so always-on mode stays zero-sync while still
    accounting the compute wall each batch's e2e sample contains.
    (When the sampled deep mode fenced this dispatch the buffers are
    already retired and this records ~0 — the two never double-count.)
    `mult`: batches served by one fused dispatch, as in _step_phase."""
    st = qr.app.stats
    if not st.enabled:
        setattr(qr, attr, v)
        return
    t0 = time.perf_counter_ns()
    setattr(qr, attr, v)
    st.phases.add(name or qr.name, "device_compute",
                  (time.perf_counter_ns() - t0) * mult)


def current_millis() -> int:
    return int(time.time() * 1000)


class StreamCallback:
    """Subscribe to all events of a stream (reference:
    CORE/stream/output/StreamCallback.java:38)."""

    def receive(self, events: List[ev.Event]) -> None:
        raise NotImplementedError


class QueryCallback:
    """Per-query output callback (reference: CORE/query/output/callback/
    QueryCallback.java): receive(timestamp, current_events, expired_events)."""

    def receive(self, timestamp: int, in_events: Optional[List[ev.Event]],
                out_events: Optional[List[ev.Event]]) -> None:
        raise NotImplementedError


def _sub_lock(sub):
    """Per-query processing lock of a junction subscriber (wrappers hold
    the runtime in _qr; aggregations lock internally -> None)."""
    target = getattr(sub, "_qr", None) or sub
    return getattr(target, "_qlock", None)


@contextlib.contextmanager
def _query_lock(lk, stream_id: str, timeout: float = 30.0):
    """Bounded query-lock acquisition: a worker holding query X's lock and
    synchronously routing into query Y can form a cycle with another
    worker.  Rather than deadlocking forever, fail loudly with the remedy
    (mark a stream in the cycle @async to break it)."""
    if not lk.acquire(timeout=timeout):
        from ..exceptions import SiddhiAppRuntimeError
        raise SiddhiAppRuntimeError(
            f"query lock timeout dispatching {stream_id!r}: likely a "
            f"cyclic synchronous insert-into topology under concurrent "
            f"ingestion; annotate a stream in the cycle with @async to "
            f"break it")
    try:
        yield
    finally:
        lk.release()


def _acquire_all(locks):
    """All-or-nothing multi-lock acquisition with backoff.  Ingestion
    workers take query locks in routing order (a query emitting into a
    downstream stream holds its own lock while taking the next), so a
    fixed-order blocking acquisition here could deadlock; try-acquire and
    retry instead."""
    while True:
        acquired = []
        for lk in locks:
            if lk.acquire(timeout=0.05):
                acquired.append(lk)
            else:
                break
        if len(acquired) == len(locks):
            stack = contextlib.ExitStack()
            for lk in acquired:
                stack.callback(lk.release)
            return stack
        for lk in reversed(acquired):
            lk.release()
        time.sleep(0.001)


def _rebucket_for(qr, old_layout, host_state):
    """Mesh-resize restore: permute a snapshot's key-state rows into THIS
    runtime's shard layout when it was written under a different mesh
    size (sharding/snapshot.py).  Identity for same-mesh restores and
    pre-layout snapshots."""
    new_layout = _sharding.query_layout(qr)
    if not _sharding.needs_rebucket(old_layout, new_layout):
        return host_state
    return _sharding.rebucket_state(host_state, old_layout, new_layout,
                                    qr.planned)


def _allocator_of(qr):
    """Slot allocator of a query runtime (pattern runtimes hold it
    directly, planned single queries on the plan).  Explicit None checks:
    an EMPTY allocator is len()==0 and must still be returned (a fresh
    runtime restoring a snapshot hits exactly that state)."""
    a = getattr(qr, "slot_allocator", None)
    if a is None:
        a = getattr(qr.planned, "slot_allocator", None)
    return a


_STATEOBS_ONE = np.ones(1, np.int64)


def _stateobs_feed_slots(qr, alloc, slots) -> None:
    """Fold one batch's resolved key slots (per-event slot ids, -1 =
    invalid) into the app's key-hotness tracker — host numpy only; a
    disabled observatory costs one memoized dict read."""
    if not _stateobs.obs_enabled(qr.app):
        return
    live = slots[slots >= 0]
    if live.size == 0:
        return
    if live.size == 1:
        # single-row sends dominate interactive/test traffic; skip the
        # np.unique pass (KeyHotness.update has the matching fast path)
        keys, counts = live, _STATEOBS_ONE
    else:
        keys, counts = np.unique(live, return_counts=True)
    qr.app.stats.stateobs.feed_keys(qr.name, alloc.capacity, keys, counts)


def _stateobs_feed_group(qr, alloc, key_idx, sel, pad) -> None:
    """Fold one grouped batch's key set into the hotness tracker: the
    per-key row counts fall out of the already-computed [Kb, E] group
    selection (`(sel >= 0).sum(axis=1)`) — no extra np.unique pass."""
    if not _stateobs.obs_enabled(qr.app):
        return
    keys = np.asarray(key_idx)
    live = keys < pad
    if not live.any():
        return
    counts = (np.asarray(sel) >= 0).sum(axis=1)
    qr.app.stats.stateobs.feed_keys(qr.name, alloc.capacity,
                                    keys[live], counts[live])


def _wrap_stream_callback(cb) -> Callable[[List[ev.Event]], None]:
    if isinstance(cb, StreamCallback):
        return cb.receive
    return cb


def _wrap_query_callback(cb) -> Callable:
    if isinstance(cb, QueryCallback):
        return cb.receive
    return cb


class InputHandler:
    """reference: CORE/stream/input/InputHandler.java:50

    This is the app's EXTERNAL ingest edge, so admission control
    (core/admission.py) decides every send here: under an
    `admission.max.events.per.sec` quota a send may block (caller
    backpressure to a deadline), be shed (dropped, counted in
    `siddhi_admission_shed_total`), or raise AdmissionDeniedError.
    Internal re-routing (query outputs, fault streams, error-store
    replay via `_admit=False`) is never throttled — shedding an event
    the engine already accepted would be a silent loss."""

    def __init__(self, stream_id: str, runtime: "SiddhiAppRuntime"):
        self.stream_id = stream_id
        self._runtime = runtime
        self._admit = True

    def _admitted(self, n: int) -> bool:
        if not self._admit:
            return True
        adm = getattr(self._runtime, "admission", None)
        if adm is None or not adm.ingest_enabled:
            return True
        return adm.admit_ingest(self.stream_id, n)

    def send(self, data, timestamp: Optional[int] = None) -> None:
        """Accepts one event's data list/tuple, an Event, or a list of those."""
        self._runtime._gate_wait()     # entry valve, see _gate_wait
        events = self._to_events(data, timestamp)
        if not self._admitted(len(events)):
            return                     # shed at the edge (counted)
        self._runtime._route(self.stream_id, events)

    def _to_events(self, data, timestamp) -> List[ev.Event]:
        now = timestamp if timestamp is not None \
            else self._runtime.timestamp_millis()
        if isinstance(data, ev.Event):
            return [data]
        if isinstance(data, (list, tuple)) and data and isinstance(
                data[0], (list, tuple, ev.Event)):
            return [d if isinstance(d, ev.Event) else ev.Event(now, d)
                    for d in data]
        return [ev.Event(now, list(data))]

    def send_columns(self, cols: Sequence, timestamps=None) -> None:
        """Columnar high-throughput ingestion: `cols` is a sequence of numpy
        arrays (one per attribute, equal length; strings pre-encoded as
        interner ids).  Bypasses per-event Python staging.

        OWNERSHIP: arrays whose length exactly fills the staging bucket
        (a power of two >= 8) are ADOPTED, not copied — the caller must
        not mutate them after send (re-sending the same unchanged buffer
        is fine, and fast: repeated identical buffers dedupe on the
        device link).  This matches the reference's InputHandler.send
        (Object[] ownership transfers, InputHandler.java:70); pass a copy
        if you need to keep writing into the array."""
        self._runtime._gate_wait()     # entry valve, see _gate_wait
        if not self._admitted(len(cols[0]) if cols else 0):
            return                     # shed at the edge (counted)
        self._runtime._route_columns(self.stream_id, cols, timestamps)


class _MeshResolved:
    """Resolved mesh/router accessors shared by every query-runtime
    wrapper: the ONE way host code asks "is this query sharded, and how".
    sharding/router.py owns the layout; the former scattered
    `getattr(.., "mesh"/"keyed_mesh", None)` call sites (purger resets,
    staging grouping, snapshot layout, fusion eligibility) all route
    through these."""

    @property
    def mesh(self):
        return _sharding.mesh_of(self)

    @property
    def keyed_mesh(self):
        return _sharding.keyed_mesh_of(self)

    @property
    def shard_router(self):
        # memoized in a 1-tuple so a resolved None doesn't re-resolve
        # per batch (replans never change mesh/capacity, so no staleness)
        r = self.__dict__.get("_shard_router_memo")
        if r is None:
            r = self.__dict__["_shard_router_memo"] = \
                (_sharding.router_for(self),)
        return r[0]


class QueryRuntime(_MeshResolved):
    """Host wrapper around one planned query: staging, group slots, routing."""

    def __init__(self, planned: PlannedQuery, app: "SiddhiAppRuntime"):
        self.planned = planned
        self.app = app
        # set by optimizer.apply_merge when this query joins a merge
        # group: state then lives in the group's stacked pytree and the
        # `state` property serves this member's view of it
        self._merged = None
        # force-copy every leaf: constant-folding can alias identical init
        # arrays into one buffer, which breaks donated-argument execution
        self._state = jax.tree.map(
            lambda x: jax.numpy.array(x, copy=True), planned.init_state())
        self.callbacks: List[Callable] = []
        self.batch_callbacks: List[Callable] = []
        self.next_wakeup: int = _NO_WAKEUP_INT
        # per-query processing lock: parallel ingestion serializes PER
        # QUERY, not per app (reference: per-query ReentrantLock chosen in
        # QueryParser.java:159-215 instead of one engine-wide lock)
        self._qlock = threading.RLock()
        # set by _PartitionPurger: fn(slots, now) recording key liveness
        self._touch = None
        self._touch_group = None
        # @fuse(batches=K): stack buffer for scan-fused dispatch, or None
        self._fuse = None

    @property
    def name(self):
        return self.planned.name

    @property
    def state(self):
        """This query's state pytree.  Unmerged: the runtime's own
        tuple.  Merged (optimizer/mqo.py): a view into the merge
        group's stacked state — snapshots, restores, EXPLAIN, and
        memory accounting keep addressing the member by name and see
        exactly the (window, selector) tuple an unmerged plan holds."""
        mg = self._merged
        return self._state if mg is None else mg.member_state(self)

    @state.setter
    def state(self, v):
        mg = self._merged
        if mg is None:
            self._state = v
        else:
            mg.set_member_state(self, v)

    def _slots_for_batch(self, staged: ev.StagedBatch,
                         now: int) -> Tuple[np.ndarray, Tuple]:
        """Group/distinctCount slot resolution for a non-range-partition
        batch (host side effects: slot binding + purger liveness touch) —
        shared by the sequential path and fused dispatch (core/fusion.py)."""
        p = self.planned
        valid = staged.valid
        if p.group_by_positions and p.slot_allocator is not None:
            gslot = p.slot_allocator.slots_for(
                [staged.cols[i] for i in p.group_by_positions], valid)
            _stateobs_feed_slots(self, p.slot_allocator, gslot)
        else:
            gslot = _zero_slots(staged.ts.shape[0])
        if self._touch is not None:
            self._touch(gslot, now)
        # distinctCount: (group, value) -> pair refcount slots
        pslots = tuple(alloc.slots_for([gslot, staged.cols[pos]], valid)
                       for alloc, pos in p.pair_allocs)
        return gslot, pslots

    def process_staged(self, staged: ev.StagedBatch, now: int) -> None:
        p = self.planned
        dbg = getattr(self.app, "_debugger", None)
        if dbg is not None:
            dbg.check_break_point(self.name, "IN", staged)
        if p.keyed_window:
            self._process_keyed(staged, now)
            return
        fb = self._fuse
        if fb is not None and fb.offer((staged, now), staged, None):
            return
        if p.partition_key_fn is not None:
            # range partition: derived key column; rows matching no range
            # are excluded from the query entirely
            kcols, kvalid = p.partition_key_fn(staged)
            valid = staged.valid & kvalid
            if p.slot_allocator is not None:
                key_cols = list(kcols) + [staged.cols[i]
                                          for i in p.group_by_positions]
                gslot = p.slot_allocator.slots_for(key_cols, valid)
            else:
                gslot = _zero_slots(staged.ts.shape[0])
            staged = ev.StagedBatch(staged.ts, staged.kind, valid,
                                    staged.cols, staged.n)
            if self._touch is not None:
                self._touch(gslot, now)
            pslots = tuple(alloc.slots_for([gslot, staged.cols[pos]], valid)
                           for alloc, pos in p.pair_allocs)
        else:
            gslot, pslots = self._slots_for_batch(staged, now)
        pslots = tuple(jax.numpy.asarray(s) for s in pslots)
        batch = staged.to_device(p.in_schema)
        in_tabs = self.app.in_probe_tables(p.in_deps)
        with _maybe_span("step", query=self.name, kind="window"):
            _st, out, wake = _step_phase(self, lambda: p.step(
                self.state, batch.ts, batch.kind, batch.valid, batch.cols,
                jax.numpy.asarray(gslot),
                jax.numpy.asarray(now, jax.numpy.int64),
                in_tabs, pslots))
        _rebind_state(self, _st)
        # sampled window-fill probe: dispatch-only; its scalar rides the
        # delivery fetch in _deliver_output (observability/stateobs.py)
        _stateobs.arm_fill_probe(self)
        # the device-computed wake scalar rides the emission fetch (a sync
        # int(wake) here would stall the send path one tunnel RTT per batch)
        wake_arg = None
        if p.needs_timer:
            if getattr(p.window, "host_scheduled", False):
                self._apply_wake(p.window.host_next_wakeup(now))
            else:
                wake_arg = wake
        self._emit(out, now, wake_arg)

    def _process_keyed(self, staged: ev.StagedBatch, now: int,
                       all_keys: bool = False) -> None:
        """Keyed-window path: events group per partition key into [Kb, E]
        and the window state slab advances under vmap (planner.kstep)."""
        p = self.planned
        valid = staged.valid
        kcols: List[np.ndarray] = []
        if all_keys:
            # timer tick: advance EVERY key's window; each key sees the
            # TIMER row (staged row 0) so flush-on-timer windows
            # (cron/timeBatch) fire per key, and `now` drives time expiry.
            # The partition key fn is NOT applied: a TIMER row's zeroed
            # columns would fail every range condition and kill the row.
            key_idx = np.arange(p.key_capacity, dtype=np.int32)
            sel = np.zeros((p.key_capacity, 1), np.int32)
        elif p.partition_key_fn is not None:
            kcols, kvalid = p.partition_key_fn(staged)
            valid = valid & kvalid
            kcols = list(kcols)
        else:
            kcols = [staged.cols[i] for i in p.window_key_positions]
        if not all_keys:
            _, key_idx, sel = p.window_key_allocator.slots_and_group(
                kcols, valid, pad=p.key_capacity)
            _stateobs_feed_group(self, p.window_key_allocator, key_idx,
                                 sel, p.key_capacity)
        if self._touch is not None and not all_keys:
            self._touch(key_idx, now)
        if p.slot_allocator is not None and not all_keys:
            if p.partition_key_fn is not None:
                gk = kcols + [staged.cols[i] for i in p.group_by_positions]
            else:
                gk = [staged.cols[i] for i in p.group_by_positions]
            gslot = p.slot_allocator.slots_for(gk, valid)
            if self._touch_group is not None:
                self._touch_group(gslot, now)
        else:
            # timer ticks carry no data rows: no group slots to resolve
            gslot = _zero_slots(staged.ts.shape[0])
        batch = ev.StagedBatch(staged.ts, staged.kind, valid, staged.cols,
                               staged.n).to_device(p.in_schema)
        in_tabs = self.app.in_probe_tables(p.in_deps)
        with _maybe_span("step", query=self.name, kind="keyed-window"):
            _st, out, wake = _step_phase(self, lambda: p.step(
                self.state, batch.ts, batch.kind, batch.valid, batch.cols,
                jax.numpy.asarray(gslot), jax.numpy.asarray(key_idx),
                jax.numpy.asarray(sel),
                jax.numpy.asarray(now, jax.numpy.int64), in_tabs))
        _rebind_state(self, _st)
        wake_arg = None
        if p.needs_timer:
            if getattr(p.window, "host_scheduled", False):
                # cron-style windows schedule on the host clock
                self._apply_wake(p.window.host_next_wakeup(now))
            else:
                wake_arg = wake
        self._emit(out, now, wake_arg)

    def on_timer(self, now: int) -> None:
        p = self.planned
        staged = ev.pack_np(p.in_schema, [], capacity=8)
        staged.ts[0] = now
        staged.kind[0] = ev.TIMER
        staged.valid[0] = True
        if p.keyed_window:
            self._process_keyed(staged, now, all_keys=True)
            return
        self.process_staged(staged, now)

    def _apply_wake(self, w: int) -> None:
        self.next_wakeup = w
        if w < _NO_WAKEUP_INT:
            self.app._scheduler.notify_at(w, self)

    def _emit(self, out, now: int, wake=None) -> None:
        _emit_output(self, out, now, wake)


class PatternQueryRuntime(_MeshResolved):
    """Host wrapper for a pattern/sequence query: groups events per key into
    the [K, E] device layout and drives the per-stream NFA steps."""

    def __init__(self, planned, app: "SiddhiAppRuntime",
                 slot_allocator=None):
        self.planned = planned
        self.app = app
        self.state = jax.tree.map(
            lambda x: jax.numpy.array(x, copy=True),
            planned.init_state(planned.key_capacity))
        self.callbacks: List[Callable] = []
        self.batch_callbacks: List[Callable] = []
        self.next_wakeup: int = _NO_WAKEUP_INT
        self.slot_allocator = slot_allocator  # shared per partition
        self._qlock = threading.RLock()
        # per-key dirty mask since the last (incremental) snapshot
        self._dirty = np.zeros(planned.key_capacity, np.bool_) \
            if planned.partition_positions else None
        # set by _PartitionPurger: fn(slots, now) recording key liveness
        self._touch = None
        # set at wiring time: fn(new_cap) -> PlannedPatternQuery re-planned
        # with a larger emission cap (adaptive overflow growth)
        self._replan = None
        # steady-state block memo for _grouped_slots: (k0, n) ->
        # (allocator version, key_idx, sel, keys copy)
        self._block_cache: Dict = {}
        # @fuse(batches=K): stack buffer for scan-fused dispatch, or None
        self._fuse = None

    @property
    def name(self):
        return self.planned.name

    _EMIT_CAP_MAX = 512

    def _grow_emission_cap(self, n_dropped: int, n_valid: int = 0) -> bool:
        """Adaptive degradation for implicit-cap overflow (reference emits
        unbounded): size the per-key emission cap to the OBSERVED demand
        (delivered + dropped, next power of two) in one jump — each regrow
        is a full step rebuild/recompile, so doubling blindly would pay
        that minutes-long cost repeatedly on a large fan-out.  State shapes
        are cap-independent, so the live NFA slab carries over.  The
        overflowing batch already lost `n_dropped` rows (logged);
        subsequent batches get headroom.  Returns False once the growth
        budget is exhausted, surfacing the normal overflow error."""
        if self._replan is None:
            return False
        cap = getattr(self.planned, "compact_rows", 8)
        need = max(n_valid + n_dropped, cap * 2)
        new_cap = min(1 << (need - 1).bit_length(), self._EMIT_CAP_MAX)
        if new_cap <= cap:
            return False
        # admission: a regrow allocates a bigger emission block AND pays
        # a recompile — past the state ceiling the growth is denied and
        # the app sheds overflow at the current cap instead of OOMing
        adm = getattr(self.app, "admission", None)
        if adm is not None and not adm.admit_growth(
                self.name, (new_cap - cap) * _row_nbytes(self)):
            return False
        import logging
        logging.getLogger("siddhi_tpu").warning(
            "%s: %d pattern match rows dropped at emission capacity %d; "
            "growing the cap to %d (set @emit(rows='N') to pre-size and "
            "silence this)", self.name, n_dropped, cap, new_cap)
        # operator-visible counter: each growth is a step recompile
        # (minutes through the TPU tunnel) — invisible cap churn was the
        # old failure mode
        stats = self.app.stats
        if stats.enabled:
            stats.counter_inc(f"{self.name}.cap_growths")
        self.planned = self._replan(new_cap)
        return True

    def _in_tabs(self):
        """Table snapshots for `x in Table` probes inside NFA filters
        (reference: InConditionExpressionExecutor in pattern conditions)."""
        return self.app.in_probe_tables(
            getattr(self.planned.exec, "in_deps", None) or ())

    def _grouped_slots(self, key_cols, valid, p):
        """Slot resolution + [Kb, E] grouping with a steady-state block
        memo.  Keyed workloads re-send the same key blocks sweep after
        sweep (the bench's 1M-key stream cycles 8 contiguous blocks); when
        the allocator's bindings are unchanged since the block was last
        resolved (`version`) and the keys compare equal, the C pass and
        group fill are pure functions of the block and replay from cache
        (~30ms -> ~0.2ms per 131k-key send: 16% of flagship wall time)."""
        alloc = self.slot_allocator
        keys = key_cols[0] if len(key_cols) == 1 else None
        cacheable = (keys is not None and keys.dtype.kind in "iu" and
                     keys.shape[0] >= 1024 and bool(valid.all()))
        if cacheable:
            blk = (int(keys[0]), keys.shape[0])
            ent = self._block_cache.get(blk)
            if ent is not None and ent[0] == alloc.version and \
                    np.array_equal(keys, ent[3]):
                return ent[1], ent[2]
        _, key_idx, sel = alloc.slots_and_group(key_cols, valid,
                                                pad=p.key_capacity)
        if cacheable:
            if len(self._block_cache) >= 64:
                self._block_cache.clear()
            self._block_cache[blk] = (alloc.version, key_idx, sel,
                                      keys.copy())
        return key_idx, sel

    def process_staged(self, stream_id: str, staged: ev.StagedBatch,
                       now: int) -> None:
        p = self.planned
        B = staged.ts.shape[0]
        # @fuse stacks BEFORE the mesh branch: sharded pattern dispatches
        # fuse too (fusion._dispatch_pattern routes stacks through the
        # shard_map'd scan step built in pattern_planner._shard_fused_step)
        fb = self._fuse
        if fb is not None and fb.offer((stream_id, staged, now), staged,
                                       stream_id):
            return
        if self.shard_router is not None:
            self._process_sharded(stream_id, staged, now)
            return
        # host prep wall (uploads, ts-wire fit check, key->slot routing)
        # charges to stage_host right before the step — without it the
        # pattern path's per-batch routing work lands in `other` and the
        # flagship phase budget can't account its e2e (phases.py)
        _prep0 = time.perf_counter_ns() if self.app.stats.enabled else None
        raw_cols = tuple(jax.numpy.asarray(c) for c in staged.cols)
        # ts-delta wire: ship (base scalar, i32 delta) instead of a fresh
        # i64 column when the batch's span fits i32 (PERF.md lever 1);
        # falls back to the plain i64 step otherwise
        ts_wire = None
        if p.steps_w is not None and staged.n:
            # fit-check over the REAL rows only: a partial bucket's zero
            # padding vs an epoch base would always fail it.  Padding
            # rows (valid=False) reconstruct to `base` on device — their
            # values are never read through a valid selection.
            tsn = staged.ts[:staged.n]
            base = tsn[0]
            dmax = int(tsn.max()) - int(base)
            dmin = int(tsn.min()) - int(base)
            if dmax < 2**31 and dmin >= -(2**31):
                delta32 = np.zeros(staged.ts.shape, np.int32)
                delta32[:staged.n] = tsn - base
                ts_wire = (jax.numpy.asarray(base, jax.numpy.int64),
                           jax.numpy.asarray(delta32))
        raw_ts = jax.numpy.asarray(staged.ts) if ts_wire is None else None
        if p.partition_positions:
            kf = (p.partition_key_fns or {}).get(stream_id)
            if kf is not None:
                key_cols, kvalid = kf(staged)
                valid = staged.valid & kvalid
            else:
                pos = p.partition_positions[stream_id]
                key_cols = [staged.cols[i] for i in pos]
                valid = staged.valid
            key_idx_np, sel = self._grouped_slots(key_cols, valid, p)
            _stateobs_feed_group(self, self.slot_allocator, key_idx_np,
                                 sel, p.key_capacity)
            if self._touch is not None:
                self._touch(key_idx_np, now)
            sel_d = jax.numpy.asarray(sel)
            # contiguous-slot fast path: dynamic-slice state access instead
            # of row-serialized gather/scatter (see dense_steps)
            Kb = key_idx_np.shape[0]
            nuniq = int((key_idx_np < p.key_capacity).sum())
            if self._dirty is not None and nuniq:
                self._dirty[key_idx_np[:nuniq]] = True
            # nuniq >= 2: the Kb=1 dense specialization trips an XLA:CPU
            # fused-dynamic-slice codegen bug (RET_CHECK llvm_module), and a
            # 1-row gather is as fast as a 1-row slice anyway
            if (p.dense_steps is not None and nuniq > 1 and
                    int(key_idx_np[0]) + Kb <= p.key_capacity and
                    int(key_idx_np[nuniq - 1]) ==
                    int(key_idx_np[0]) + nuniq - 1):
                if self._dirty is not None:
                    # the dense step also time-ticks slots beyond nuniq
                    self._dirty[int(key_idx_np[0]):
                                int(key_idx_np[0]) + Kb] = True
                pstate, sel_state = self.state
                key_lo = jax.numpy.asarray(int(key_idx_np[0]),
                                           jax.numpy.int32)
                now_d = jax.numpy.asarray(now, jax.numpy.int64)
                if _prep0 is not None:
                    self.app.stats.phases.add(
                        self.name, "stage_host",
                        time.perf_counter_ns() - _prep0)
                if ts_wire is not None:
                    pstate, sel_state, out, wake = _step_phase(
                        self, lambda: p.dense_steps_w[stream_id](
                            pstate, sel_state, raw_cols, ts_wire[0],
                            ts_wire[1], sel_d, key_lo, now_d,
                            self._in_tabs()))
                else:
                    pstate, sel_state, out, wake = _step_phase(
                        self, lambda: p.dense_steps[stream_id](
                            pstate, sel_state, raw_cols, raw_ts, sel_d,
                            key_lo, now_d, self._in_tabs()))
                _rebind_state(self, (pstate, sel_state))
                _emit_output(self, out, now, wake=self._wake_arg(wake))
                return
            key_idx = jax.numpy.asarray(key_idx_np)
        else:
            if staged.valid.all():
                # full bucket: the identity selection is a constant per
                # capacity — cached read-only so repeat sends dedupe
                sel_np = _identity_sel(B)
            else:
                sel_np = np.where(staged.valid,
                                  np.arange(B, dtype=np.int32),
                                  -1)[None, :]
            sel_d = jax.numpy.asarray(sel_np)
            key_idx = jax.numpy.asarray(np.zeros((1,), np.int32))
        pstate, sel_state = self.state
        now_d = jax.numpy.asarray(now, jax.numpy.int64)
        if _prep0 is not None:
            self.app.stats.phases.add(self.name, "stage_host",
                                      time.perf_counter_ns() - _prep0)
        with _maybe_span("step", query=self.name, kind="pattern"):
            if ts_wire is not None:
                pstate, sel_state, out, wake = _step_phase(
                    self, lambda: p.steps_w[stream_id](
                        pstate, sel_state, raw_cols, ts_wire[0],
                        ts_wire[1], sel_d, key_idx, now_d,
                        self._in_tabs()))
            else:
                pstate, sel_state, out, wake = _step_phase(
                    self, lambda: p.steps[stream_id](
                        pstate, sel_state, raw_cols, raw_ts, sel_d,
                        key_idx, now_d, self._in_tabs()))
        _rebind_state(self, (pstate, sel_state))
        _emit_output(self, out, now, wake=self._wake_arg(wake))

    def _shard_prep(self, stream_id: str, staged: ev.StagedBatch,
                    now: int) -> Tuple[np.ndarray, np.ndarray]:
        """Staging-time routing of one batch through the key-space router
        (host side effects: slot binding, purger liveness touch, dirty
        marking, per-shard routing counters).  Returns the grouped
        (key_idx [n, Kb], sel [n, Kb, E]) device layout — shared by the
        sequential sharded path and fused dispatch (core/fusion.py)."""
        p = self.planned
        router = self.shard_router
        kf = (p.partition_key_fns or {}).get(stream_id)
        if kf is not None:
            key_cols, kvalid = kf(staged)
            valid = staged.valid & kvalid
        else:
            pos = p.partition_positions[stream_id]
            key_cols = [staged.cols[i] for i in pos]
            valid = staged.valid
        t0 = time.perf_counter_ns()
        slots = self.slot_allocator.slots_for(key_cols, valid)
        _stateobs_feed_slots(self, self.slot_allocator, slots)
        if self._touch is not None:
            self._touch(slots, now)
        if self._dirty is not None:
            live = slots[slots >= 0]
            if live.size:
                # global state column of slot s under the shard layout
                self._dirty[router.state_row(live)] = True
        key_idx, sel, counts = router.group(slots, staged.valid)
        t1 = time.perf_counter_ns()
        stats = self.app.stats
        if stats.enabled:
            stats.shard_events(self.name, counts)
            # the [n, Kb, E] regroup is host staging work: it belongs to
            # the stage_host phase even though it runs post-publish
            stats.phases.add(self.name, "stage_host", t1 - t0)
            tr = _tracing.active()
            if tr is not None:
                # per-shard sub-spans over the regroup wall: the even
                # time split is nominal, but the per-shard event counts
                # are real — trace viewers read the skew off the meta
                n_sh = max(1, len(counts))
                for d, c in enumerate(counts):
                    tr.add_span(
                        f"shard{d}", t0 + (t1 - t0) * d // n_sh,
                        t0 + (t1 - t0) * (d + 1) // n_sh,
                        {"query": self.name, "events": int(c)})
        return key_idx, sel

    def _process_sharded(self, stream_id: str, staged: ev.StagedBatch,
                         now: int) -> None:
        """Multi-chip path: route each key to its shard (slot % n), build the
        stacked [n*Kb, E] layout, run the shard_map step."""
        p = self.planned
        key_idx, sel = self._shard_prep(stream_id, staged, now)
        flat = lambda a: a.reshape((-1,) + a.shape[2:])   # noqa: E731
        pstate, sel_state = self.state
        with _maybe_span("step", query=self.name, kind="sharded-pattern"):
            pstate, sel_state, out, wake = _step_phase(
                self, lambda: p.steps[stream_id](
                    pstate, sel_state,
                    tuple(jax.numpy.asarray(c) for c in staged.cols),
                    jax.numpy.asarray(staged.ts),
                    jax.numpy.asarray(flat(sel)),
                    jax.numpy.asarray(flat(key_idx)),
                    jax.numpy.asarray(now, jax.numpy.int64),
                    self._in_tabs()))
        _rebind_state(self, (pstate, sel_state))
        _emit_output(self, out, now, wake=self._wake_arg(wake))

    def on_timer(self, now: int) -> None:
        p = self.planned
        if p.timer_step is None:
            return
        pstate, sel_state = self.state
        pstate, sel_state, out, wake, changed = p.timer_step(
            pstate, sel_state, jax.numpy.asarray(now, jax.numpy.int64),
            self._in_tabs())
        self.state = (pstate, sel_state)
        if self._dirty is not None:
            # timer-driven expiry/absent firing mutates key NFA state;
            # without marking, incremental snapshots miss those changes and
            # a restore resurrects expired pending states.  The device
            # reports exactly which keys changed.
            self._dirty |= np.asarray(jax.device_get(changed))
        _emit_output(self, out, now, wake=self._wake_arg(wake))

    def _wake_arg(self, wake):
        """Only patterns with absent atoms need timer wakeups; everything
        else skips the wake fetch entirely."""
        return wake if self.planned.timer_step is not None else None

    def _apply_wake(self, w: int) -> None:
        self.next_wakeup = w
        if w < _NO_WAKEUP_INT:
            self.app._scheduler.notify_at(w, self)


def _has_consumers(qr) -> bool:
    """Anything downstream that would read this output?  Checked BEFORE any
    device->host transfer so unconsumed outputs cost zero tunnel traffic."""
    if qr.callbacks or qr.batch_callbacks:
        return True
    if getattr(qr, "table_op", None) is not None or \
            getattr(qr, "rate_limiter", None) is not None:
        return True
    p = qr.planned
    if p.output_target:
        app = qr.app
        if p.output_target in getattr(app, "named_windows", {}) or \
                p.output_target in getattr(app, "tables", {}):
            return True
        j = app.junctions.get(p.output_target)
        return j is not None and bool(
            j.queries or j.stream_callbacks or app.stats.enabled)
    return False


def _emit_output(qr, out, now: int, wake=None) -> None:
    """Emission entry: async mode (@async) defers the device->host sync to a
    background drainer thread so the producer keeps dispatching device work
    (the reference's Disruptor-decoupled delivery, StreamJunction.java:276);
    @pipeline mode keeps a ONE-DEEP deferred emission on the producer
    thread itself — the device_get for step N happens only after step N+1
    has been dispatched, so host staging overlaps device compute without a
    second thread to contend with (the win on a 1-core driver host feeding
    an accelerator); sync mode delivers inline.  `wake` is the
    device-computed next-wakeup scalar (or None): fetched WITH the output
    in one roundtrip and applied before delivery."""
    if not _has_consumers(qr):
        if wake is not None:
            qr._apply_wake(int(wake))
        return
    # ingest stamp (perf_counter_ns at send acceptance, stashed by the
    # junction under the query lock): rides every deferred-delivery queue
    # so the `<query>:e2e` histogram includes queue wait — None when
    # statistics are OFF or the batch arrived outside a junction dispatch
    ingest_ns = qr.__dict__.get("_ingest_ns")
    if getattr(qr, "serve_emit", False) and wake is None and \
            not getattr(qr.planned, "needs_timer", False):
        # device-resident serving loop (siddhi_tpu/serving): the output
        # pytree appends into the query's on-device emission ring — a
        # single jitted dispatch, zero fetches — and the per-app drainer
        # thread delivers it through _emit_output_sync later.  Timer-
        # bearing queries keep their inline path (same exclusion as
        # @pipeline: a deferred wake scalar would stall expiry), and
        # serving takes precedence over @async/@pipeline below.
        from ..serving import ring_append
        # handoff(): arm + carry the dispatch thread's trace so the
        # drainer's delivery spans join it (None when tracing is off)
        ring_append(qr, out, now, ingest_ns, _tracing.handoff())
        return
    if getattr(qr, "async_emit", False) and qr.app._drainer is not None:
        qr.app._drainer.enqueue(qr, out, now, wake, ingest_ns,
                                _tracing.handoff())
        return
    depth = int(getattr(qr, "pipeline_emit", 0) or 0)
    if depth and wake is None and \
            not getattr(qr.planned, "needs_timer", False):
        # timer-bearing queries never pipeline: a device wake scalar would
        # stall time-driven expiry if deferred, and host-scheduled (cron)
        # windows pass wake=None yet their flush emissions must not slip a
        # period — needs_timer covers both
        dq = getattr(qr, "_pending_emit", None)
        if dq is None:
            dq = qr._pending_emit = collections.deque()
        dq.append((out, now, None, ingest_ns, _tracing.handoff()))
        if len(dq) > depth:
            if depth == 1:
                # exactly-one-deep contract: each send delivers its
                # predecessor (the original @pipeline behavior)
                _deliver_output(qr, *dq.popleft())
            else:
                # depth-k: drain to half depth in ONE batched roundtrip —
                # the per-fetch tunnel latency amortizes over ~k/2 sends
                # instead of serializing one RTT per send
                take = len(dq) - depth // 2
                _deliver_many(qr, [dq.popleft() for _ in range(take)])
        return
    if ingest_ns is not None:
        # inline delivery: flag the dispatcher to close e2e AFTER
        # process_staged fully returns, so per batch e2e >= the step
        # latency sample by construction (same end point, earlier start)
        qr.__dict__["_e2e_owed"] = True
    _deliver_output(qr, out, now, wake)


def _deliver_output(qr, out, now: int, wake, ingest_ns=None,
                    trace=None) -> None:
    """Blocking device->host fetch + delivery of one emission.  `trace`
    is a handed-off BatchTrace for deferred (@pipeline) deliveries whose
    originating dispatch has moved on — delivery spans adopt it."""
    t0 = time.perf_counter_ns()
    # sampled window-fill probe rides THIS fetch (same device_get call:
    # the never-fetch guard counts calls, and this adds none)
    probe = _stateobs.take_fill_probe(qr)
    if len(out) == 6:
        header, wake_h, fills = jax.device_get(
            ((out[0], out[1]), wake, probe))
    else:
        out, wake_h, fills = jax.device_get((out, wake, probe))
        header = None
    st = qr.app.stats
    if st.enabled:
        st.phases.add(qr.name, "d2h_drain",
                      time.perf_counter_ns() - t0)
    if fills is not None:
        _stateobs.record_fill(qr, fills)
    if wake_h is not None:
        qr._apply_wake(int(wake_h))
    with _tracing.adopt(trace):
        _emit_output_sync(qr, out, now, header=header, ingest_ns=ingest_ns)


def _deliver_many(qr, items) -> None:
    """Deliver several deferred emissions with ONE batched device_get for
    all their headers (same amortization as _EmissionDrainer._run)."""
    if len(items) == 1:
        _deliver_output(qr, *items[0])
        return
    t0 = time.perf_counter_ns()
    fetched = jax.device_get([
        (out[0], out[1]) if len(out) == 6 else out
        for out, _, _, _, _ in items])
    fetch_ns = time.perf_counter_ns() - t0
    st = qr.app.stats
    loop_t0 = time.perf_counter_ns()
    for (out, now, _, t_in, trace), fetch_h in zip(items, fetched):
        if st.enabled:
            # latency attribution: the batched fetch wall charges to
            # every item it served, and the serialized wait behind
            # predecessors' deliveries is queue residency — both are
            # inside each item's e2e sample (see phases.py)
            st.phases.add(qr.name, "d2h_drain", fetch_ns)
            st.phases.add(qr.name, "ring_wait",
                          time.perf_counter_ns() - loop_t0)
        with _tracing.adopt(trace):
            if len(out) == 6:
                _emit_output_sync(qr, out, now, header=fetch_h,
                                  ingest_ns=t_in)
            else:
                _emit_output_sync(qr, fetch_h, now, ingest_ns=t_in)


def _drain_pending_emit(qr) -> None:
    """Deliver a @pipeline runtime's held emissions (flush/quiesce/
    shutdown).  Swap + delivery run under the query lock — the producer's
    pipeline branch in _emit_output also runs under it (junction dispatch),
    so a concurrent flush can never double-deliver the same emission."""
    if not getattr(qr, "_pending_emit", None):
        return
    lk = getattr(qr, "_qlock", None) or contextlib.nullcontext()
    with lk:
        dq = getattr(qr, "_pending_emit", None)
        if not dq:
            return
        items = list(dq)
        dq.clear()
        _deliver_many(qr, items)


class _LazyBatchPayload(dict):
    """Batch-callback payload materializing device->host pulls on access.

    Device-computed scalar counts ('n_valid', 'n_current', 'n_expired',
    'n_dropped') are prefetched with the drainer's batched header get, so a
    counting consumer costs ZERO per-batch tunnel roundtrips.  Bulk data
    fetches lazily in two groups — ('ts', 'kind', 'valid') in one roundtrip,
    'cols' in another — because each device_get pays a fixed tunnel latency
    regardless of size.  Any whole-dict access (iteration, get, `in`, ...)
    materializes everything so the plain-dict contract holds."""

    _LAZY = ("ts", "kind", "valid", "cols")
    _COUNTS = ("n_valid", "n_current", "n_expired", "n_dropped")

    def __init__(self, names, ots, okind, ovalid, ocols, counts=None):
        super().__init__()
        self._names = names
        self._ots, self._okind = ots, okind
        self._ovalid, self._ocols = ovalid, ocols
        if counts:
            for k, v in counts.items():
                dict.__setitem__(self, k, v)

    def __missing__(self, k):
        if k in ("ts", "kind", "valid"):
            ts, kind, valid = jax.device_get(
                (self._ots, self._okind, self._ovalid))
            dict.__setitem__(self, "ts", ts)
            dict.__setitem__(self, "kind", kind)
            dict.__setitem__(self, "valid", valid)
            return dict.__getitem__(self, k)
        if k == "cols":
            cols = jax.device_get(self._ocols)
            v = dict(zip(self._names, cols))
            dict.__setitem__(self, k, v)
            return v
        if k == "n_valid":
            v = int(np.sum(self["valid"]))
        elif k == "n_current":
            v = int(np.sum(self["valid"] & (self["kind"] == ev.CURRENT)))
        elif k == "n_expired":
            v = int(np.sum(self["valid"] & (self["kind"] == ev.EXPIRED)))
        elif k == "n_dropped":
            v = 0
        else:
            raise KeyError(k)
        dict.__setitem__(self, k, v)
        return v

    def _materialize(self):
        for k in self._LAZY + self._COUNTS:
            if not dict.__contains__(self, k):
                self[k]
        return self

    def get(self, k, default=None):
        try:
            return self[k]
        except KeyError:
            return default

    def __contains__(self, k):
        return k in self._LAZY or k in self._COUNTS or \
            dict.__contains__(self, k)

    def __iter__(self):
        return iter(dict.keys(self._materialize()))

    def keys(self):
        return dict.keys(self._materialize())

    def items(self):
        return dict.items(self._materialize())

    def values(self):
        return dict.values(self._materialize())

    def __len__(self):
        # fixed key set: counting costs no device->host materialization
        extra = sum(1 for k in dict.keys(self)
                    if k not in self._LAZY and k not in self._COUNTS)
        return len(self._LAZY) + len(self._COUNTS) + extra


def _emit_output_sync(qr, out, now: int, header=None,
                      ingest_ns=None) -> None:
    """Emission with an `emit` span when a pipeline trace is active on
    this thread — which now includes drainer threads: deferred deliveries
    carry the dispatch side's handed-off trace and run under
    `tracing.adopt`, so their spans (tagged track="drain") join the
    originating trace.  `ingest_ns` (send-acceptance perf_counter_ns)
    closes the `<query>:e2e` histogram here — after callbacks, downstream
    routing, and the synchronous sink publish they trigger."""
    try:
        if _tracing.active() is None:
            return _emit_output_sync_impl(qr, out, now, header)
        with _tracing.span("emit", query=qr.name):
            return _emit_output_sync_impl(qr, out, now, header)
    finally:
        if ingest_ns is not None:
            st = qr.app.stats
            if st.enabled:
                st.e2e_latency(qr.name,
                               time.perf_counter_ns() - ingest_ns)


def _row_nbytes(qr) -> int:
    """Wire bytes of ONE output row from schema metadata (ts int64 +
    kind int32 + payload column itemsizes), cached per runtime — feeds
    the `<q>.emitted_bytes` tenant-accounting counter without touching
    any buffer."""
    nb = qr.__dict__.get("_out_row_nbytes")
    if nb is None:
        nb = 12
        try:
            for t in qr.planned.out_schema.types:
                nb += int(np.dtype(ev.np_dtype(t)).itemsize)
        except Exception:  # noqa: BLE001 — metrics must not throw
            pass
        qr.__dict__["_out_row_nbytes"] = nb
    return nb


def _emit_output_sync_impl(qr, out, now: int, header=None) -> None:
    """Shared output emission: fan out to columnar batch callbacks first
    (zero-transfer for counting consumers — the device-computed count
    scalars ride the header fetch), then unpack to host events only if
    someone needs them (Event callbacks or downstream routing).

    Pattern outputs (len-6) may still hold DEVICE arrays here; only the
    count header has been fetched.  Bulk rows transfer lazily through the
    payload / the event-delivery path below.  Plain outputs (len-4) arrive
    fully fetched (they are bounded by the window batch capacity)."""
    p = qr.planned
    target_live = getattr(qr, "table_op", None) is not None or \
        getattr(qr, "rate_limiter", None) is not None
    if p.output_target and not target_live:
        app = qr.app
        if p.output_target in getattr(app, "named_windows", {}) or \
                p.output_target in getattr(app, "tables", {}):
            target_live = True
        else:
            j = app.junctions.get(p.output_target)
            target_live = j is not None and bool(
                j.queries or j.stream_callbacks or app.stats.enabled)
    if not (qr.callbacks or qr.batch_callbacks or target_live):
        return
    if qr.app.stats.detail:
        # reference: log4j TRACE at QuerySelector.process :77
        _trace_log.debug("query %s: emitting output batch @ %d",
                         qr.name, now)
    counts = None
    overflow_exc = None
    # phase split of this delivery: device fetches paid here (`d2h_drain`),
    # consumer-facing work (`sink`), and everything else — header decode,
    # unpack, ts-order restore — as `demux`
    _st = qr.app.stats
    _ph_t0 = time.perf_counter_ns() if _st.enabled else None
    _sink_ns = 0
    _fetch_ns = 0
    if len(out) == 6:
        n_valid, n_dropped, ots, okind, ovalid, ocols = out
        if header is None:
            _tf = time.perf_counter_ns()
            header = jax.device_get((n_valid, n_dropped))
            _fetch_ns += time.perf_counter_ns() - _tf
        h0 = np.asarray(header[0])
        nd = int(header[1])
        if h0.ndim:
            # join header vector [n_valid, n_current] (see join.py)
            nv, ncur = int(h0[0]), int(h0[1])
        else:
            nv, ncur = int(h0), None
        if nd:
            # dropped-row counter BEFORE the growth attempt: even when the
            # cap grows for the next batch, THIS batch lost nd rows
            _st = qr.app.stats
            if _st.enabled:
                _st.counter_inc(f"{qr.name}.dropped", nd)
            what = ("join result rows exceeded the emission"
                    if getattr(qr.planned, "mixed_kinds", False)
                    else "pattern match rows exceeded the per-key emission")
            if not getattr(qr.planned, "emit_explicit", True):
                # the cap was an implicit default: losing matches silently
                # is a correctness hole.  First try ADAPTIVE GROWTH — the
                # runtime rebuilds its steps with a doubled cap (state
                # shapes don't depend on it) so subsequent batches have
                # headroom; only when growth is exhausted does the loss
                # surface as a processing error (fault stream / exception
                # listener), raised in the finally below so the error
                # reports partial loss, not total loss.
                grow = getattr(qr, "_grow_emission_cap", None)
                if grow is None or not grow(nd, nv):
                    overflow_exc = MatchOverflowError(
                        f"{qr.name}: {nd} {what} capacity this batch; set "
                        f"@emit(rows='N') on the query to raise the cap or "
                        f"accept capped delivery")
            else:
                import logging
                logging.getLogger("siddhi_tpu").warning(
                    "%s: %d %s capacity this batch and were dropped",
                    qr.name, nd, what)
        if ncur is not None:
            # join emissions mix CURRENT and EXPIRED rows; both counts
            # rode the prefetched header — no bulk fetch for counting
            counts = {"n_valid": nv, "n_current": ncur,
                      "n_expired": nv - ncur, "n_dropped": nd}
        else:
            # pattern matches are always CURRENT-kind rows
            counts = {"n_valid": nv, "n_current": nv, "n_expired": 0,
                      "n_dropped": nd}
        # emission-cap demand (nv + nd rows wanted out this batch) is
        # already host-side off the header fetch — the high-water mark
        # the sizing ledger persists for @emit pre-sizing
        _cap = getattr(qr.planned, "compact_rows", None)
        if _cap is not None and _stateobs.obs_enabled(qr.app):
            qr.app.stats.stateobs.observe(
                qr.name, "emission_cap", nv + nd, _cap,
                growable=not getattr(qr.planned, "emit_explicit", True),
                config_key="@emit(rows='N')")
    try:
        if len(out) == 6:
            if nv == 0:
                return
            rows_out = nv
        else:
            ots, okind, ovalid, ocols = out
            ovalid_np = np.asarray(ovalid)
            if not ovalid_np.any():
                return
            rows_out = int(ovalid_np.sum())
        _st = qr.app.stats
        if _st.enabled and rows_out:
            # per-tenant events_out/emitted_bytes accounting: row count is
            # already host-side (header / staged valid plane) and the byte
            # figure is schema metadata × rows — no extra fetch
            _st.emitted(qr.name, rows_out, rows_out * _row_nbytes(qr))
        if getattr(p, "emits_uuid", False):
            # UUID() sentinels materialize ONCE here, at the device->host
            # emission boundary, so every consumer of this emission (event
            # callbacks, batch payloads, downstream routing, table writes)
            # observes the same id per row
            if len(out) == 6:
                _tf = time.perf_counter_ns()
                ots, okind, ovalid, ocols = jax.device_get(
                    (ots, okind, ovalid, ocols))
                _fetch_ns += time.perf_counter_ns() - _tf
            changed = ev.materialize_uuid_sentinels(
                p.out_schema, np.asarray(ovalid), ocols)
            if changed:
                oc = list(ocols)
                for pos, col in changed:
                    oc[pos] = col
                ocols = tuple(oc)
        if qr.batch_callbacks:
            payload = _LazyBatchPayload(p.out_schema.names, ots, okind,
                                        ovalid, ocols, counts)
            _ts = time.perf_counter_ns()
            for bcb in qr.batch_callbacks:
                bcb(now, payload)
            _sink_ns += time.perf_counter_ns() - _ts
        if not qr.callbacks and not target_live:
            return
        if len(out) == 6:
            # pattern outputs are compacted [R,K] rank-major on device;
            # fetch them now and restore timestamp order for event delivery
            # with a host-side stable sort of just the valid rows
            # (O(matches), runs on the drainer thread)
            _tf = time.perf_counter_ns()
            ts_np, okind, ovalid_np, ocols = jax.device_get(
                (ots, okind, ovalid, ocols))
            _fetch_ns += time.perf_counter_ns() - _tf
            idxv = np.nonzero(ovalid_np)[0]
            order = idxv[np.argsort(ts_np[idxv], kind="stable")]
            ots = ts_np[order]
            okind = np.asarray(okind)[order]
            ocols = tuple(np.asarray(c)[order] for c in ocols)
            ovalid = np.ones(order.shape[0], np.bool_)
        batch = ev.EventBatch(ots, okind, ovalid, ocols)
        pairs = ev.unpack(p.out_schema, batch,
                          want_kinds=(ev.CURRENT, ev.EXPIRED))
        if not pairs:
            return
        if getattr(qr, "table_op", None) is not None:
            _ts = time.perf_counter_ns()
            current = [e for k, e in pairs if k == ev.CURRENT]
            expired = [e for k, e in pairs if k == ev.EXPIRED]
            for cb in qr.callbacks:
                cb(now, current or None, expired or None)
            _apply_table_op(qr, ots, okind, ovalid, ocols, now)
            _sink_ns += time.perf_counter_ns() - _ts
            return
        limiter = getattr(qr, "rate_limiter", None)
        if limiter is not None:
            _ts = time.perf_counter_ns()
            limiter.process(pairs, now)
            _sink_ns += time.perf_counter_ns() - _ts
            return
        _ts = time.perf_counter_ns()
        _deliver_pairs(qr, pairs, now)
        _sink_ns += time.perf_counter_ns() - _ts
    finally:
        if _ph_t0 is not None:
            _ph = _st.phases
            if _sink_ns:
                _ph.add(qr.name, "sink", _sink_ns)
            if _fetch_ns:
                _ph.add(qr.name, "d2h_drain", _fetch_ns)
            _ph.add(qr.name, "demux",
                    time.perf_counter_ns() - _ph_t0 - _sink_ns - _fetch_ns)
        if overflow_exc is not None:
            raise overflow_exc


def _aggregation_view(agg, per: str, within) -> Tuple:
    """Padded columnar snapshot of an aggregation's buckets for the join
    device step (reference: AggregateWindowProcessor adapter role)."""
    ts, cols = agg.snapshot_rows(per, within)
    n = ts.shape[0]
    cap = ev.bucket_size(max(n, 1))
    valid = np.zeros((cap,), np.bool_)
    valid[:n] = True
    pts = np.zeros((cap,), np.int64)
    pts[:n] = ts
    padded = []
    for c in cols:
        a = np.zeros((cap,), c.dtype)
        a[:n] = c
        padded.append(jax.numpy.asarray(a))
    return (tuple(padded), jax.numpy.asarray(pts), jax.numpy.asarray(valid))


def _deliver_pairs(qr, pairs, now: int) -> None:
    """Terminal delivery: query callbacks + downstream routing (reference:
    OutputCallback implementations, CORE/query/output/callback/*)."""
    p = qr.planned
    current = [e for k, e in pairs if k == ev.CURRENT]
    expired = [e for k, e in pairs if k == ev.EXPIRED]
    dbg = getattr(qr.app, "_debugger", None)
    if dbg is not None:
        dbg.check_break_point(qr.name, "OUT", current)
    for cb in qr.callbacks:
        cb(now, current or None, expired or None)
    if p.output_target:
        sel = p.output_event_type
        if sel == "CURRENT_EVENTS":
            routed = current
        elif sel == "EXPIRED_EVENTS":
            routed = expired
        else:
            routed = [e for _, e in pairs]
        if routed:
            qr.app._route(p.output_target, routed)


def _apply_table_op(qr, ots, okind, ovalid, ocols, now) -> None:
    """Table write operations from query output (reference: CORE/query/output/
    callback/{InsertIntoTable,UpdateTable,DeleteTable,UpdateOrInsertTable}
    Callback.java)."""
    op, table, cond, set_fns, key = qr.table_op
    want = okind == 0  # CURRENT rows drive table ops
    valid = jax.numpy.logical_and(ovalid, jax.numpy.asarray(np.asarray(want)))
    batch = ev.EventBatch(ots, okind, valid, ocols)
    if op == "insert":
        staged = ev.StagedBatch(
            np.asarray(ots), np.asarray(okind), np.asarray(valid),
            [np.asarray(c) for c in ocols], int(np.asarray(valid).sum()))
        table.insert(batch, staged)
    elif op == "delete":
        table.delete_where(cond, key, batch)
    elif op == "update":
        table.update_where(cond, key, batch, set_fns)
    elif op == "upsert":
        staged = ev.StagedBatch(
            np.asarray(ots), np.asarray(okind), np.asarray(valid),
            [np.asarray(c) for c in ocols], int(np.asarray(valid).sum()))
        table.update_where(cond, key, batch, set_fns, upsert=True,
                           staged=staged)


class JoinQueryRuntime(_MeshResolved):
    """Host wrapper for join queries: routes each side's batches to the
    side-specific jitted step, passing table snapshots for table sides."""

    def __init__(self, planned, app: "SiddhiAppRuntime"):
        self.planned = planned
        self.app = app
        self.state = jax.tree.map(
            lambda x: jax.numpy.array(x, copy=True), planned.init_state())
        self.state = self.place_state(self.state)
        self.callbacks: List[Callable] = []
        self.batch_callbacks: List[Callable] = []
        self.next_wakeup: int = _NO_WAKEUP_INT
        self._qlock = threading.RLock()
        self.table_op = None
        # set at wiring time: fn(new_rows) -> PlannedJoinQuery replanned
        # with a larger emission compaction cap
        self._replan = None
        # @fuse(batches=K): stack buffer for scan-fused dispatch, or None
        self._fuse = None
        # equi-join bucket fast path: host retention mirror + the lane
        # width the NEXT replan must keep (core/join.py JoinKeyTracker)
        self._jk = None
        self._lane_k = 0
        if planned.fastpath == "bucket":
            from .join import JoinKeyTracker
            self._jk = JoinKeyTracker(planned.join_key_allocator,
                                      planned.ring_caps,
                                      planned.lane_buckets)
            self._lane_k = planned.lane_k

    @property
    def name(self):
        return self.planned.name

    _EMIT_CAP_MAX = 1 << 21   # 2M emitted rows per batch

    def _grow_emission_cap(self, n_dropped: int, n_valid: int = 0) -> bool:
        """Adaptive growth for the implicit join emission cap (same contract
        as PatternQueryRuntime._grow_emission_cap: size to observed demand
        in one jump; each regrow recompiles the side steps).  Join state
        shapes are cap-independent, so the live window/selector state
        carries over, as do the host group-slot allocators."""
        if self._replan is None:
            return False
        need = max(n_valid + n_dropped, 1024)
        cur = self.planned.compact_rows
        if cur is not None and need <= cur:
            # an earlier growth (possibly racing this one) already covers
            # the demand: the overflowing batch was compiled pre-growth —
            # not an error, the next batch delivers in full
            return True
        new_rows = min(1 << (need - 1).bit_length(), self._EMIT_CAP_MAX)
        if cur is not None and new_rows <= cur:
            return False
        # admission: deny growth past the state ceiling (see
        # PatternQueryRuntime._grow_emission_cap) — overflow keeps
        # dropping at the current cap, loudly, instead of OOMing
        adm = getattr(self.app, "admission", None)
        if adm is not None and not adm.admit_growth(
                self.name, (new_rows - (cur or 0)) * _row_nbytes(self)):
            return False
        logging.getLogger("siddhi_tpu").warning(
            "%s: %d join result rows dropped at emission capacity; growing "
            "the cap to %d (set @emit(rows='N') to pre-size and silence "
            "this)", self.name, n_dropped, new_rows)
        # operator-visible counter (see PatternQueryRuntime._grow_emission_cap)
        stats = self.app.stats
        if stats.enabled:
            stats.counter_inc(f"{self.name}.cap_growths")
        old = self.planned
        newp = self._replan(new_rows)
        # group allocators hold live host slot maps — carry them over,
        # then publish the fully-formed plan in ONE assignment (workers
        # read self.planned once; they must never observe empty allocators)
        newp.slot_allocator = old.slot_allocator
        newp.slot_allocator2 = old.slot_allocator2
        newp.join_key_allocator = old.join_key_allocator
        self.planned = newp
        return True

    def _join_key_probe(self, is_left: bool,
                        staged: ev.StagedBatch) -> np.ndarray:
        """Key bucket slots for one arriving batch (bucket fast path).
        Cached on the staged batch — keyed by (runtime, side), since a
        junction hands ONE staged object to every subscriber and a
        self-join sees it on both sides — so fused-drain re-entries and
        deferred dispatches can never double-count the retention
        mirror.  Grows the planned lane width BEFORE the dispatch that
        would overflow it."""
        cache = staged.jprobe
        if cache is None:
            cache = staged.jprobe = {}
        key = (id(self), is_left)
        cached = cache.get(key)
        if cached is not None:
            return cached
        from .join import _norm_key_cols
        p = self.planned
        kvalid = staged.valid & (staged.kind == ev.CURRENT)
        pos = p.key_left if is_left else p.key_right
        slots = self._jk.track(
            is_left, _norm_key_cols(staged.cols, pos, p.key_dtypes),
            kvalid)
        need = self._jk.needed_k()
        if need > p.lane_k:
            self._grow_lane_k(need)
        out = np.where(kvalid, slots, -1).astype(np.int32)
        if _stateobs.obs_enabled(self.app):
            # lane demand is a running bucket-occupancy max the tracker
            # already mirrors host-side; push it so the HWM survives
            # window expiry shrinking the live lanes back down
            self.app.stats.stateobs.observe(
                self.name, "join_lane", need, self.planned.lane_k,
                growable=True, config_key="auto (lane grows via replan)")
            _stateobs_feed_slots(self, p.join_key_allocator, out)
        cache[key] = out
        return out

    def _grow_lane_k(self, need: int) -> None:
        """Recompile the side steps with wider candidate lanes.  Called
        BEFORE the batch that needs them dispatches, so the device
        program can never silently drop same-bucket candidates (which
        would diverge from the grid path).  State shapes are
        lane-independent — window/selector state carries over live."""
        new_k = 1 << (max(need, 1) - 1).bit_length()
        logging.getLogger("siddhi_tpu").info(
            "%s: growing equi-join candidate lanes to %d (max same-"
            "bucket window occupancy %d)", self.name, new_k, need)
        stats = self.app.stats
        if stats.enabled:
            stats.counter_inc(f"{self.name}.lane_growths")
        fb = self._fuse
        if fb is not None:
            # the pending stack was offered under the old lane width;
            # drain it sequentially first (byte-identical by contract)
            fb.drain()
        self._lane_k = new_k
        old = self.planned
        newp = self._replan(None if old.emit_explicit
                            else old.compact_rows)
        newp.slot_allocator = old.slot_allocator
        newp.slot_allocator2 = old.slot_allocator2
        newp.join_key_allocator = old.join_key_allocator
        self.planned = newp

    def _table_probe(self, staged: ev.StagedBatch):
        """Host-side table-index candidates for one trigger batch
        (table fast path): [B, K] row ids ascending per row (the grid
        path's emission order) + their validity."""
        p = self.planned
        tid = (p.left if p.table_is_left else p.right).stream_id
        table = self.app.tables[tid]
        vals = np.asarray(staged.cols[p.stream_key_pos])
        with table._lock:
            cand, ok = table.probe_rows(p.table_pos, vals)
        big = np.int32(np.iinfo(np.int32).max)
        cand = np.where(ok, cand, big)
        cand.sort(axis=1)
        ok = cand < big
        return np.where(ok, cand, -1).astype(np.int32), ok

    def _after_restore(self, host_state) -> None:
        """Re-seed the key retention mirror from restored window
        buffers (alive rows in arrival order) and re-widen lanes if the
        snapshot needs more than the current plan carries."""
        p = self.planned
        if p.fastpath != "bucket" or self._jk is None:
            return
        sides = []
        for st in (host_state[0], host_state[1]):
            slots = np.empty(0, np.int64)
            buf = st[0] if isinstance(st, tuple) and st else None
            if buf is not None and hasattr(buf, "alive"):
                alive = np.asarray(buf.alive)
                add_seq = np.asarray(buf.add_seq)[alive]
                slots = np.asarray(buf.cols[-1])[alive][
                    np.argsort(add_seq, kind="stable")].astype(np.int64)
            sides.append(slots)
        self._jk.rebuild(sides)
        need = self._jk.needed_k()
        if need > p.lane_k:
            self._grow_lane_k(need)

    def place_state(self, state):
        """GSPMD scale-out: shard window buffers / selector slabs on axis 0
        and let XLA partition the [R, C] join compare and buffer
        maintenance (sharding is a layout hint — semantics are preserved
        whatever the choice; scatters/sorts get collectives as needed).
        Scalars and indivisible leaves stay replicated.  Restore paths call
        this too, so a restored runtime keeps its sharding."""
        mesh = self.app.mesh
        if mesh is None or mesh.devices.size < 2:
            return state
        from .shardsafe import axis0_sharding

        def _place(x):
            s = axis0_sharding(mesh, x)
            return jax.device_put(x, s) if s is not None else x
        return jax.tree.map(_place, state)

    def _other_table(self, is_left):
        p = self.planned
        other = p.right if is_left else p.left
        if other.is_aggregation:
            agg = self.app.aggregations[other.stream_id]
            return _aggregation_view(agg, p.per_duration, p.within_range)
        if getattr(other, "is_named_window", False):
            # probe the shared window's live buffer (reference:
            # WindowWindowProcessor.find against Window.java's chain)
            nw = self.app.named_windows[other.stream_id]
            buf = nw.wproc.current_buffer(nw.state)
            return (buf.cols, buf.ts, buf.alive)
        if other.is_table:
            t = self.app.tables[other.stream_id]
            return (t.cols, t.ts, t.valid)
        return (jax.numpy.zeros((1,)),) * 3

    def _join_slots(self, is_left: bool,
                    staged: ev.StagedBatch) -> np.ndarray:
        """Per-side group-by slots (joined rows compose both sides' ids);
        TIMER rows carry zeroed columns — allocating for them would burn
        a phantom slot for the all-zeros key on every tick.  Shared by the
        sequential path and fused dispatch (core/fusion.py)."""
        p = self.planned
        galloc = p.slot_allocator if is_left else p.slot_allocator2
        gpos = p.gl_pos if is_left else p.gr_pos
        if galloc is None:
            return _zero_slots(staged.ts.shape[0])
        gvalid = staged.valid & (staged.kind != ev.TIMER)
        return galloc.slots_for([staged.cols[i] for i in gpos], gvalid)

    def process_staged(self, is_left: bool, staged: ev.StagedBatch,
                       now: int) -> None:
        p = self.planned
        probe = None
        if p.fastpath == "bucket":
            # slot binding + retention mirror BEFORE the fuse offer: a
            # lane-width growth must replan before this batch dispatches
            probe = self._join_key_probe(is_left, staged)
            p = self.planned          # _grow_lane_k may have swapped it
        side = p.left if is_left else p.right
        step = p.step_left if is_left else p.step_right
        if step is None:
            return
        fb = self._fuse
        if fb is not None and fb.offer((is_left, staged, now), staged,
                                       is_left):
            return
        gslot = self._join_slots(is_left, staged)
        batch = staged.to_device(side.schema)
        args = [self.state, batch.ts, batch.kind, batch.valid, batch.cols,
                jax.numpy.asarray(gslot)]
        if p.fastpath == "bucket":
            args.append(jax.numpy.asarray(probe))
        elif p.fastpath == "table":
            cand, ok = self._table_probe(staged)
            args.append((jax.numpy.asarray(cand), jax.numpy.asarray(ok)))
        args += [self._other_table(is_left),
                 jax.numpy.asarray(now, jax.numpy.int64)]
        with _maybe_span("step", query=self.name, kind="join"):
            _st, out, wake = _step_phase(
                self, lambda: step(*args))
        _rebind_state(self, _st)
        _emit_output(self, out, now,
                     wake=wake if p.needs_timer else None)

    def _apply_wake(self, w: int) -> None:
        self.next_wakeup = w
        if w < _NO_WAKEUP_INT:
            self.app._scheduler.notify_at(w, self)

    def on_timer(self, now: int) -> None:
        p = self.planned
        for is_left, side in ((True, p.left), (False, p.right)):
            if side.window is not None and side.window.needs_timer:
                staged = ev.pack_np(side.schema, [], capacity=8)
                staged.ts[0] = now
                staged.kind[0] = ev.TIMER
                staged.valid[0] = True
                self.process_staged(is_left, staged, now)


class TriggerRuntime:
    """Event generator into a stream named after the trigger (reference:
    CORE/trigger/{PeriodicTrigger,CronTrigger,StartTrigger}.java).  Rides the
    app scheduler: each firing publishes one event `[triggered_time]` and
    reschedules itself."""

    def __init__(self, tdef, app: "SiddhiAppRuntime"):
        self.definition = tdef
        self.app = app
        self.stream_id = tdef.id
        self._cron = None
        if tdef.at is not None and tdef.at.lower() != "start":
            from ..utils.cron import CronExpression
            self._cron = CronExpression(tdef.at)

    def start(self, now: int) -> None:
        d = self.definition
        if d.at is not None and d.at.lower() == "start":
            self.app._scheduler.notify_at(now, self)
        elif d.at_every is not None:
            self.app._scheduler.notify_at(now + d.at_every, self)
        elif self._cron is not None:
            self.app._scheduler.notify_at(self._cron.next_fire(now), self)

    def on_timer(self, now: int) -> None:
        self.app._route(self.stream_id, [ev.Event(now, [now])])
        d = self.definition
        if d.at_every is not None:
            self.app._scheduler.notify_at(now + d.at_every, self)
        elif self._cron is not None:
            self.app._scheduler.notify_at(self._cron.next_fire(now), self)


class NamedWindowRuntime:
    """A shared window instance (reference: CORE/window/Window.java:65 —
    `define window W (...) <window>(...) output <type> events`).  Queries
    insert into it; reader queries subscribe to its CURRENT/EXPIRED output.

    TPU design: one jitted step wrapping the window processor; output rows are
    staged once to numpy (kinds preserved) and fanned out to subscribers."""

    def __init__(self, wdef, schema: ev.Schema, app: "SiddhiAppRuntime"):
        import jax.numpy as jnp
        from .window import Rows, create_window

        self.definition = wdef
        self.schema = schema
        self.app = app
        w = wdef.window
        if w is None:
            raise CompileError(
                f"window definition {wdef.id!r} needs a window function")
        self.wproc = create_window(
            (w.namespace + ":" if w.namespace else "") + w.name,
            schema, w.parameters, batch_capacity=512)
        if getattr(self.wproc, "session_key_pos", None) is not None:
            # the keyed-window slab is a query-planner construct; a shared
            # named window has no key axis — running the key-less processor
            # would silently merge every key into ONE session
            raise CompileError(
                "session(gap, key) is not supported on a `define window` "
                "shared instance; use it on a query's input stream")
        self.needs_timer = self.wproc.needs_timer
        self.output_event_type = wdef.output_event_type or "ALL_EVENTS"
        self.subscribers: List = []      # QueryRuntime-likes (process_staged)
        self.stream_callbacks: List[Callable] = []
        # serializes ingest (via _route) against scheduler timers and
        # snapshot reads of self.state
        self._qlock = threading.RLock()
        self.next_wakeup: int = _NO_WAKEUP_INT
        wproc = self.wproc

        def step(state, ts, kind, valid, cols, now):
            rows = Rows(ts=ts, kind=kind, valid=valid,
                        seq=jnp.zeros_like(ts),
                        gslot=jnp.full(ts.shape, -1, jnp.int32), cols=cols)
            state, wout = wproc.process(state, rows, now)
            o = wout.rows
            return state, (o.ts, o.kind, o.valid, o.cols), wout.next_wakeup

        # NOT donated: join queries probe this window's live buffer
        # (_other_table) without holding _qlock through their own step —
        # donation would let a concurrent ingest delete the buffers a
        # join just captured
        self._step = jit_step(step, owner=f"window:{wdef.id}")
        self.state = jax.tree.map(
            lambda x: jax.numpy.array(x, copy=True), wproc.init_state())

    @property
    def name(self):
        return self.definition.id

    def process_staged(self, staged: ev.StagedBatch, now: int) -> None:
        batch = staged.to_device(self.schema)
        self.state, out, wake = self._step(
            self.state, batch.ts, batch.kind, batch.valid, batch.cols,
            jax.numpy.asarray(now, jax.numpy.int64))
        self._fanout(out, now)
        if self.needs_timer:
            w = int(wake)
            self.next_wakeup = w
            if w < _NO_WAKEUP_INT:
                self.app._scheduler.notify_at(w, self)

    def on_timer(self, now: int) -> None:
        staged = ev.pack_np(self.schema, [], capacity=8)
        staged.ts[0] = now
        staged.kind[0] = ev.TIMER
        staged.valid[0] = True
        self.process_staged(staged, now)

    def _fanout(self, out, now: int) -> None:
        ots, okind, ovalid, ocols = out
        ovalid_np = np.asarray(ovalid)
        if not ovalid_np.any():
            return
        okind_np = np.asarray(okind)
        sel = self.output_event_type
        if sel == "CURRENT_EVENTS":
            keep = okind_np == ev.CURRENT
        elif sel == "EXPIRED_EVENTS":
            keep = okind_np == ev.EXPIRED
        else:
            keep = (okind_np == ev.CURRENT) | (okind_np == ev.EXPIRED)
        ovalid_np = ovalid_np & keep
        if not ovalid_np.any():
            return
        staged = ev.StagedBatch(
            np.asarray(ots), okind_np, ovalid_np,
            [np.asarray(c) for c in ocols], int(ovalid_np.sum()))
        for cb in self.stream_callbacks:
            batch = ev.EventBatch(staged.ts, staged.kind, ovalid_np,
                                  tuple(staged.cols))
            pairs = ev.unpack(self.schema, batch,
                              want_kinds=(ev.CURRENT, ev.EXPIRED))
            cb([e for _, e in pairs])
        for q in self.subscribers:
            lk = _sub_lock(q)
            if lk is not None:
                with _query_lock(lk, self.definition.id):
                    q.process_staged(staged, now)
            else:
                q.process_staged(staged, now)


class StreamJunction:
    """Per-stream pub/sub hub (reference: CORE/stream/StreamJunction.java:61).
    Packs each published chunk to numpy once; subscribers share the staging.

    `@OnError(action='STREAM')` on the stream definition routes events whose
    processing raised, together with the error, into the `!stream` fault
    stream (reference: StreamJunction.handleError :368-430 +
    FaultStreamEventConverter); the default action logs and drops."""

    def __init__(self, schema: ev.Schema, stream_id: str = "",
                 on_error: str = "LOG", app=None):
        self.schema = schema
        self.stream_id = stream_id
        self.on_error = on_error
        self.app = app
        self.queries: List[QueryRuntime] = []
        self.stream_callbacks: List[Callable] = []
        # @async(buffer.size, workers): bounded ingress queue + worker
        # threads (the reference's Disruptor ring,
        # StreamJunction.java:276-313).  None => synchronous dispatch.
        self._async_q = None
        self._async_policy = "block"
        self._async_shed_warn = 0.0
        self._async_workers: List[threading.Thread] = []

    def enable_async(self, buffer_size: int = 256, workers: int = 1,
                     policy: str = "block") -> None:
        """Decouple ingestion: sends enqueue (bounded) and worker threads
        dispatch to the queries.  `queue.policy` picks the full-queue
        behavior: 'block' (default) backpressures the producer — the
        reference's Disruptor blocking-wait; 'shed' drops the send
        loudly instead (`siddhi_async_shed_total{app,stream}`), for
        feeds where stale events are worth less than producer liveness.
        With workers > 1, cross-batch ordering within the stream is
        relaxed — same trade as the reference's multi-consumer
        Disruptor."""
        if self._async_q is not None:
            return
        if policy not in ("block", "shed"):
            raise CompileError(
                f"@async(queue.policy={policy!r}) on {self.stream_id!r}: "
                "policy must be 'block' or 'shed'")
        import queue
        self._async_policy = policy
        self._async_q = queue.Queue(maxsize=max(1, buffer_size))
        for i in range(max(1, workers)):
            t = threading.Thread(
                target=self._drain_async, daemon=True,
                name=f"siddhi-ingest-{self.stream_id}-{i}")
            # exempt from the snapshot ingress gate: a worker whose callback
            # re-ingests must keep draining or _quiesce's queue join would
            # deadlock against the closed gate
            t._siddhi_internal = True
            t.start()
            self._async_workers.append(t)

    def _serve_stage(self, staged) -> None:
        """Double-buffered H2D staging (serving/staging.py): when any
        subscriber runs the serving loop, the batch's device upload
        starts HERE at the accept edge — batch N+1's transfer overlaps
        batch N's compute (and, on the @async path, the queue wait)."""
        on = getattr(self, "_serve_staging", None)
        if on is None:
            # memoized on first dispatch: wiring is complete by then
            on = self._serve_staging = any(
                getattr(getattr(q, "_qr", q), "serve_emit", False)
                for q in self.queries)
        if on and self.app is not None:
            st = getattr(self.app, "_serve_stager", None)
            if st is not None:
                st.stage(staged, self.schema)

    def enqueue(self, tag: str, payload, now: int) -> None:
        q = self._async_q
        stats = self.app.stats if self.app is not None else None
        if tag == "staged":
            s0 = time.perf_counter_ns()
            self._serve_stage(payload)
            if stats is not None and stats.enabled:
                # @async accept-edge upload: the h2d wall is paid here,
                # not in dispatch_staged's idempotent re-call
                h2d_ns = time.perf_counter_ns() - s0
                ph = stats.phases
                for sub in self.queries:
                    ph.add(_sub_name(sub, self.stream_id), "h2d", h2d_ns)
        # ingest stamp taken BEFORE the queue put: the `<query>:e2e`
        # histogram must include @async queue wait, not start at dispatch
        t_in = time.perf_counter_ns() \
            if stats is not None and stats.enabled else None
        if q is None:          # raced with stop_async: process inline
            if tag == "staged":
                self.dispatch_staged(payload, now, ingest_ns=t_in)
            else:
                self.publish(payload, now, ingest_ns=t_in)
            return
        if self._async_policy == "shed":
            import queue as _queue
            try:
                q.put_nowait((tag, payload, now, t_in))
            except _queue.Full:
                self._shed_async(tag, payload)
            return
        q.put((tag, payload, now, t_in))

    def _shed_async(self, tag: str, payload) -> None:
        """@async(queue.policy='shed') full-queue drop: loud and counted
        (`async.<stream>.shed` counter -> siddhi_async_shed_total,
        sampler series, /healthz stream classification) — never a
        silent loss."""
        n = payload.n if tag == "staged" else len(payload)
        stats = self.app.stats if self.app is not None else None
        if stats is not None and stats.enabled:
            stats.counter_inc(f"async.{self.stream_id}.shed", n)
        t = time.monotonic()
        if t - self._async_shed_warn >= 10.0:   # rate-limited
            self._async_shed_warn = t
            import logging
            logging.getLogger("siddhi_tpu").warning(
                "@async queue for %r full: shed %d events "
                "(queue.policy='shed')", self.stream_id, n)

    def _drain_async(self) -> None:
        while True:
            tag, payload, now, t_in = self._async_q.get()
            try:
                if tag == "stop":
                    return
                if tag == "staged":
                    self.dispatch_staged(payload, now, ingest_ns=t_in)
                else:
                    self.publish(payload, now, ingest_ns=t_in)
            except Exception:  # noqa: BLE001 — worker must survive
                import traceback
                traceback.print_exc()
            finally:
                self._async_q.task_done()

    def flush_async(self) -> None:
        if self._async_q is not None:
            self._async_q.join()

    def pending_async(self) -> int:
        return self._async_q.unfinished_tasks if self._async_q is not None \
            else 0

    def queue_depth(self) -> int:
        """Batches sitting in the @async ingress queue RIGHT NOW (0 for
        synchronous junctions).  Distinct from pending_async(): qsize
        excludes the batch a worker is currently processing, so this is
        the pure queue-wait backlog the sampler/healthz watch."""
        q = self._async_q
        try:
            return q.qsize() if q is not None else 0
        except Exception:  # noqa: BLE001 — metrics must not throw
            return 0

    def stop_async(self) -> None:
        """Drain remaining batches, then terminate the workers (clean
        shutdown keeps at-least-once delivery for accepted sends)."""
        if self._async_q is None:
            return
        self._async_q.join()
        for _ in self._async_workers:
            self._async_q.put(("stop", None, 0, None))
        for t in self._async_workers:
            t.join(timeout=2.0)
        self._async_workers.clear()
        self._async_q = None

    def subscribe_query(self, q: QueryRuntime) -> None:
        self.queries.append(q)

    def subscribe_callback(self, cb: Callable) -> None:
        self.stream_callbacks.append(cb)

    def _dispatch_one(self, q, staged: ev.StagedBatch, now: int,
                      stats, n: int, traced: bool,
                      ingest_ns=None) -> None:
        """One subscriber's processing, with per-query latency histogram
        and (at DETAIL with an active trace) a per-query span.
        `ingest_ns` (send-acceptance stamp) is stashed on the runtime
        UNDER the query lock so the emission path — however deferred
        (@pipeline deque, @fuse stack, @async drainer) — can close the
        `<query>:e2e` histogram against the right batch.  The stamp must
        land on the REAL runtime (wrappers hold it in _qr, same deref as
        _sub_name/_sub_lock) — _emit_output reads it from the runtime the
        emission belongs to, so stamping a _Sub/_JSub wrapper would
        silently drop e2e for every pattern/join query."""
        lk = _sub_lock(q)
        if stats is None:
            if lk is not None:
                with _query_lock(lk, self.stream_id):
                    q.process_staged(staged, now)
            else:
                q.process_staged(staged, now)
            return
        qname = _sub_name(q, self.stream_id)
        tgt = getattr(q, "_qr", None) or q
        t0 = time.perf_counter_ns()
        try:
            with (_tracing.span("query", query=qname) if traced
                  else _NULL_CM):
                if lk is not None:
                    with _query_lock(lk, self.stream_id):
                        tgt.__dict__["_ingest_ns"] = ingest_ns
                        try:
                            q.process_staged(staged, now)
                        finally:
                            # cleared so a later timer-driven emission
                            # can't close e2e against this batch's stamp
                            tgt.__dict__["_ingest_ns"] = None
                else:
                    tgt.__dict__["_ingest_ns"] = ingest_ns
                    try:
                        q.process_staged(staged, now)
                    finally:
                        tgt.__dict__["_ingest_ns"] = None
        finally:
            stats.query_latency(qname, n, time.perf_counter_ns() - t0)
            if ingest_ns is not None and \
                    tgt.__dict__.pop("_e2e_owed", False):
                # emission delivered inline during this dispatch: close
                # `<query>:e2e` here, after the step AND delivery — the
                # stamp predates t0, so e2e >= the step-latency sample
                stats.e2e_latency(qname,
                                  time.perf_counter_ns() - ingest_ns)

    def dispatch_staged(self, staged: ev.StagedBatch, now: int,
                        ingest_ns=None) -> None:
        """Run every subscribed query over a staged batch, serialized per
        QUERY (not per app) so queries on different streams — or workers of
        different streams — process concurrently."""
        s0 = time.perf_counter_ns()
        self._serve_stage(staged)   # idempotent (skips if prestaged)
        s1 = time.perf_counter_ns()
        stats = self.app.stats if self.app is not None else None
        if stats is None or not stats.enabled:
            for q in self.queries:
                try:
                    self._dispatch_one(q, staged, now, None, 0, False)
                except Exception as exc:  # noqa: BLE001 — fault routing
                    self._handle_error_staged(staged, exc, now)
            return
        if ingest_ns is None:
            ingest_ns = time.perf_counter_ns()   # synchronous send path
        if s1 > s0:
            ph = stats.phases
            for q in self.queries:
                ph.add(_sub_name(q, self.stream_id), "h2d", s1 - s0)
        stats.stream_in(self.stream_id, staged.n)
        tr = stats.tracer.start(self.stream_id, staged.n) \
            if stats.detail else None
        if stats.detail:
            # reference: log4j TRACE at StreamJunction.sendEvent :147
            _trace_log.debug("junction %s: dispatching %d staged rows to "
                             "%d queries @ %d", self.stream_id, staged.n,
                             len(self.queries), now)
        j0 = time.perf_counter_ns()
        try:
            for q in self.queries:
                try:
                    self._dispatch_one(q, staged, now, stats, staged.n,
                                       tr is not None, ingest_ns)
                except Exception as exc:  # noqa: BLE001 — fault routing
                    self._handle_error_staged(staged, exc, now)
        finally:
            stats.junction_latency(self.stream_id,
                                   time.perf_counter_ns() - j0)
            if tr is not None:
                stats.tracer.finish(tr)

    def publish(self, events: List[ev.Event], now: int,
                ingest_ns=None) -> None:
        stats = self.app.stats if self.app is not None else None
        if stats is None or not stats.enabled:
            for cb in self.stream_callbacks:
                cb(events)
            if self.queries:
                staged = ev.pack_np(self.schema, events)
                self._serve_stage(staged)
                for q in self.queries:
                    try:
                        self._dispatch_one(q, staged, now, None, 0, False)
                    except Exception as exc:  # noqa: BLE001 — fault route
                        self._handle_error(events, exc, now)
            return
        if ingest_ns is None:
            ingest_ns = time.perf_counter_ns()   # synchronous send path
        stats.stream_in(self.stream_id, len(events))
        tr = stats.tracer.start(self.stream_id, len(events)) \
            if stats.detail else None
        if stats.detail:
            # reference: log4j TRACE at StreamJunction.sendEvent :147
            _trace_log.debug(
                "junction %s: dispatching %d events to %d queries @ %d",
                self.stream_id, len(events), len(self.queries), now)
        j0 = time.perf_counter_ns()
        try:
            for cb in self.stream_callbacks:
                cb(events)
            if self.queries:
                s0 = time.perf_counter_ns()
                with (_tracing.span("ingest", stream=self.stream_id)
                      if tr is not None else _NULL_CM):
                    staged = ev.pack_np(self.schema, events)
                s1 = time.perf_counter_ns()
                self._serve_stage(staged)
                s2 = time.perf_counter_ns()
                # per-query latency attribution (see phases.py): pack and
                # upload walls charge to every subscriber, as their e2e does
                ph = stats.phases
                for q in self.queries:
                    qn = _sub_name(q, self.stream_id)
                    ph.add(qn, "stage_host", s1 - s0)
                    ph.add(qn, "h2d", s2 - s1)
                for q in self.queries:
                    try:
                        self._dispatch_one(q, staged, now, stats,
                                           len(events), tr is not None,
                                           ingest_ns)
                    except Exception as exc:  # noqa: BLE001 — fault route
                        self._handle_error(events, exc, now)
        finally:
            stats.junction_latency(self.stream_id,
                                   time.perf_counter_ns() - j0)
            if tr is not None:
                stats.tracer.finish(tr)

    def _handle_error(self, events, exc: Exception, now: int) -> None:
        import logging
        if self.on_error == "STREAM" and self.app is not None:
            fault_id = "!" + self.stream_id
            if fault_id in self.app.junctions:
                fault_events = [
                    ev.Event(e.timestamp, list(e.data) + [repr(exc)])
                    for e in events]
                self.app._route(fault_id, fault_events)
                return
        if self.on_error == "STORE" and self.app is not None:
            # @OnError(action='STORE'): capture the failed events for
            # inspection/replay (reference: ErrorStore.saveOnError)
            store = getattr(self.app, "error_store", None)
            if store is not None and events:
                store.store(self.stream_id, events, exc, origin="junction")
                return
        logging.getLogger("siddhi_tpu").error(
            "error processing %r events: %s", self.stream_id, exc)
        listener = getattr(self.app, "exception_listener", None)
        if listener is not None:
            listener(exc)

    def _handle_error_staged(self, staged: ev.StagedBatch, exc: Exception,
                             now: int) -> None:
        """Columnar-path twin of _handle_error: rows decode to host events
        only when a fault stream or the error store actually consumes
        them."""
        wants_events = (
            self.on_error == "STREAM" and self.app is not None and
            ("!" + self.stream_id) in self.app.junctions) or (
            self.on_error == "STORE" and
            getattr(self.app, "error_store", None) is not None)
        if wants_events:
            idx = np.nonzero(staged.valid)[0]
            events = []
            for i in idx.tolist():
                data = [self.schema.decode_value(t, c[i]) for t, c in
                        zip(self.schema.types, staged.cols)]
                events.append(ev.Event(int(staged.ts[i]), data))
            self._handle_error(events, exc, now)
            return
        self._handle_error([], exc, now)


class _PartitionPurger:
    """Idle partition-key GC (reference: @purge config,
    PartitionRuntimeImpl.java:120-147).

    Tracks the last event time per key slot across a partition's queries;
    keys idle past `idle.period` free their allocator slots and their state
    columns reset to initial values — slot capacity recycles instead of
    ratcheting up until CapacityExceededError."""

    def __init__(self, app, shared_alloc, runtimes, interval_ms: int,
                 idle_ms: int):
        self.app = app
        self.shared_alloc = shared_alloc
        self.runtimes = runtimes
        self.interval_ms = interval_ms
        self.idle_ms = idle_ms
        self._seen_shared = np.zeros(shared_alloc.capacity, np.int64)
        self._seen_q: Dict[int, np.ndarray] = {}
        self._init_cols: Dict[int, Tuple] = {}
        for qr in runtimes:
            if isinstance(qr, PatternQueryRuntime):
                qr._touch = self._make_touch(self._seen_shared)
                (b32i, b64i, _), _ = qr.planned.init_state(1)
                self._init_cols[id(qr)] = (jax.numpy.asarray(b32i),
                                           jax.numpy.asarray(b64i))
                continue
            if not hasattr(qr, "_touch"):
                # join runtimes have no liveness hook: purging their group
                # allocator would judge ACTIVE slots idle and corrupt
                # aggregates; leave them out of the GC
                continue
            if getattr(qr.planned, "pair_allocs", None):
                # distinctCount pair slots key on the group slot; recycling
                # group slots under them would corrupt refcounts
                import logging
                logging.getLogger("siddhi_tpu").warning(
                    "@purge skips query %s: distinctCount state is not "
                    "purgeable yet", qr.name)
                continue
            if getattr(qr.planned, "keyed_window", False):
                # keyed-window runtimes share the partition key allocator
                qr._touch = self._make_touch(self._seen_shared)
            # per-query group-by allocator (keyed-window queries have BOTH:
            # the shared window-key axis and their own group slots)
            alloc = getattr(qr.planned, "slot_allocator", None)
            if alloc is not None:
                seen = np.zeros(alloc.capacity, np.int64)
                self._seen_q[id(qr)] = seen
                if getattr(qr.planned, "keyed_window", False):
                    qr._touch_group = self._make_touch(seen)
                else:
                    qr._touch = self._make_touch(seen)
        app._scheduler.notify_at(
            app.timestamp_millis() + interval_ms, self)

    @staticmethod
    def _make_touch(seen: np.ndarray):
        cap = seen.shape[0]

        def touch(slots: np.ndarray, now: int) -> None:
            live = slots[(slots >= 0) & (slots < cap)]
            if live.size:
                seen[live] = now
        return touch

    @staticmethod
    def _idle_slots(alloc, seen: np.ndarray, now: int,
                    cutoff: int) -> np.ndarray:
        used = np.nonzero(alloc._used)[0]
        # slots never touched since this purger saw them (e.g. restored
        # from a snapshot) start aging NOW, not at epoch — else a restore
        # followed by one purge tick would wipe every restored key
        fresh = used[seen[used] == 0]
        if fresh.size:
            seen[fresh] = now
        return used[seen[used] < cutoff]

    def on_timer(self, now: int) -> None:
        cutoff = now - self.idle_ms
        # barrier over every runtime this purger mutates: state resets must
        # not interleave with their ingestion workers
        locks = [qr._qlock for qr in self.runtimes
                 if getattr(qr, "_qlock", None) is not None]
        with _acquire_all(locks):
            idle = self._idle_slots(self.shared_alloc, self._seen_shared,
                                    now, cutoff)
            if idle.size:
                self.shared_alloc.purge(idle.tolist())
                for qr in self.runtimes:
                    if isinstance(qr, PatternQueryRuntime):
                        self._reset_pattern_keys(qr, idle)
                    elif getattr(qr.planned, "keyed_window", False):
                        self._reset_keyed_window(qr, idle)
            for qr in self.runtimes:
                if isinstance(qr, PatternQueryRuntime):
                    continue
                alloc = getattr(qr.planned, "slot_allocator", None)
                seen = self._seen_q.get(id(qr))
                if alloc is None or seen is None:
                    continue
                qidle = self._idle_slots(alloc, seen, now, cutoff)
                if qidle.size:
                    alloc.purge(qidle.tolist())
                    self._reset_selector_slots(qr, qidle)
        self.app._scheduler.notify_at(now + self.interval_ms, self)

    @staticmethod
    def _key_mask(idx: np.ndarray, capacity: int):
        from .shardsafe import key_mask
        return key_mask(idx, capacity)

    @staticmethod
    def _masked_fill(arr, mask, init, key_axis: int = 0):
        from .shardsafe import masked_fill
        return masked_fill(arr, mask, init, key_axis)

    def _reset_pattern_keys(self, qr, idx: np.ndarray) -> None:
        (b32, b64, scalars), sel_state = qr.state
        init32, init64 = self._init_cols[id(qr)]
        router = qr.shard_router
        if router is not None:
            # the sharded path routes allocator slot s to state column
            # router.state_row(s) (keys round-robin over devices,
            # _process_sharded) — the reset must hit the same columns
            idx = router.state_row(idx)
        mask = self._key_mask(idx, b32.shape[1])
        b32 = self._masked_fill(b32, mask, init32, key_axis=1)
        b64 = self._masked_fill(b64, mask, init64, key_axis=1)
        # selector accumulators (per-key sums etc.) key on the same shared
        # slots — same [K] axis, same mask: a recycled slot must NOT leak
        # the purged key's aggregates into whatever key comes next
        specs = qr.planned.selector_exec.bank.specs
        sel_state = tuple(
            a if s.slot_src is not None
            else self._masked_fill(a, mask, s.init)
            for a, s in zip(sel_state, specs))
        qr.state = ((b32, b64, scalars), sel_state)
        if qr._dirty is not None:
            qr._dirty[idx] = True

    def _reset_selector_slots(self, qr, idx: np.ndarray) -> None:
        wstate, astate = qr.state
        specs = qr.planned.selector_exec.bank.specs
        router = _sharding.group_router_for(qr)
        if router is not None:
            # sharded plain step stores slot s at row router.state_row(s)
            idx = router.state_row(idx)
        # pair-indexed specs (distinctCount refcounts) live in a different
        # slot space; queries carrying them are excluded from purge at
        # registration, this guard is defense in depth
        astate = tuple(
            a if s.slot_src is not None
            else self._masked_fill(a, self._key_mask(idx, a.shape[0]),
                                   s.init)
            for a, s in zip(astate, specs))
        qr.state = (wstate, astate)

    def _reset_keyed_window(self, qr, idx: np.ndarray) -> None:
        wslab, astate = qr.state
        single = qr.planned.window.init_state()
        router = qr.shard_router
        if router is not None:
            # sharded slab stores key k at row router.state_row(k)
            idx = router.state_row(idx)
        mask = self._key_mask(idx, qr.planned.key_capacity)
        wslab = jax.tree.map(
            lambda s, i0: self._masked_fill(s, mask, i0),
            wslab, single)
        qr.state = (wslab, astate)


_BUCKET_PLANES: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
_IDENTITY_SEL: Dict[int, np.ndarray] = {}
_ZERO_SLOTS: Dict[int, np.ndarray] = {}


def _zero_slots(cap: int) -> np.ndarray:
    """[cap] all-zero int32 group-slot column, cached read-only per size —
    every send of every keyed stream allocated this afresh before (consumers
    only read it: device upload and purger liveness touch)."""
    z = _ZERO_SLOTS.get(cap)
    if z is None:
        z = np.zeros((cap,), np.int32)
        z.setflags(write=False)
        _ZERO_SLOTS[cap] = z
    return z


def _identity_sel(cap: int) -> np.ndarray:
    """[1, cap] arange selection for a full single-key bucket, cached
    read-only so repeat sends ship the identical (deduped) buffer."""
    s = _IDENTITY_SEL.get(cap)
    if s is None:
        s = np.arange(cap, dtype=np.int32)[None, :]
        s.setflags(write=False)
        _IDENTITY_SEL[cap] = s
    return s


def _full_bucket_planes(cap: int) -> Tuple[np.ndarray, np.ndarray]:
    """(all-true valid, all-zero kind) for a full bucket, cached read-only
    so repeat sends ship the identical (tunnel-deduped) buffers."""
    ent = _BUCKET_PLANES.get(cap)
    if ent is None:
        valid = np.ones((cap,), np.bool_)
        valid.setflags(write=False)
        kind = np.zeros((cap,), np.int32)
        kind.setflags(write=False)
        ent = _BUCKET_PLANES[cap] = (valid, kind)
    return ent


class _EmissionDrainer:
    """Background thread pulling device outputs and delivering callbacks.
    Bounded queue gives backpressure (reference: Disruptor ring buffer
    capacity, @async(buffer.size)).

    The device->host fetch through the tunnel costs one fixed-latency
    roundtrip per device_get REGARDLESS of payload size, so the drainer
    drains every queued output in ONE batched device_get — under load the
    fetch latency amortizes across batches instead of serializing them."""

    def __init__(self, capacity: int = 64):
        import queue
        self._q = queue.Queue(maxsize=capacity)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="siddhi-drain")
        self._thread._siddhi_internal = True   # see StreamJunction workers
        self._stop = object()
        self._started = False

    def start(self):
        if not self._started:
            self._started = True
            self._thread.start()

    def enqueue(self, qr, out, now, wake=None, ingest_ns=None,
                trace=None):
        self.start()
        # start the D2H copy of everything the drainer will fetch NOW
        # (non-blocking): by the time the drainer's device_get runs, the
        # bytes are already on the host and the get costs ~0 instead of one
        # tunnel roundtrip per drain cycle
        targets = (out[0], out[1], wake) if len(out) == 6 else (out, wake)
        for leaf in jax.tree_util.tree_leaves(targets):
            fn = getattr(leaf, "copy_to_host_async", None)
            if fn is not None:
                try:
                    fn()
                except Exception:  # noqa: BLE001 — best-effort prefetch
                    pass
        self._q.put((qr, out, now, wake, ingest_ns, trace))

    def flush(self):
        self._q.join()

    def pending(self) -> int:
        """Outputs accepted but not yet delivered (public accessor for the
        buffered-emissions metric; safe on a never-started drainer)."""
        return self._q.unfinished_tasks

    def depth(self) -> int:
        """Outputs sitting in the drainer queue right now (qsize; excludes
        the item being delivered) — the siddhi_drainer_queue_depth gauge."""
        try:
            return self._q.qsize()
        except Exception:  # noqa: BLE001 — metrics must not throw
            return 0

    def stop(self):
        if self._started:
            self._q.join()

    def _run(self):
        import queue as queue_mod
        import traceback
        while True:
            items = [self._q.get()]
            while len(items) < 32:
                try:
                    items.append(self._q.get_nowait())
                except queue_mod.Empty:
                    break
            # one roundtrip for ALL queued outputs: pattern outs (len 6)
            # contribute only their 16-byte count header; plain outs are
            # window-capacity bounded and ship whole
            t_fetch = time.perf_counter_ns()
            try:
                fetched = jax.device_get([
                    ((out[0], out[1]), wake) if len(out) == 6
                    else (out, wake)
                    for _, out, _, wake, _, _ in items])
            except Exception:  # noqa: BLE001 — drainer must survive
                traceback.print_exc()
                fetched = [(None, None)] * len(items)
            fetch_ns = time.perf_counter_ns() - t_fetch
            loop_t0 = time.perf_counter_ns()
            for (qr, out, now, _, t_in, trace), (fetch_h, wake_h) in \
                    zip(items, fetched):
                try:
                    st = qr.app.stats
                    if st.enabled:
                        # latency attribution: the batched fetch charges
                        # to every item it served, and a later item's
                        # serialized wait behind its predecessors'
                        # deliveries counts as queue residency — both
                        # inside its e2e sample (see phases.py)
                        st.phases.add(qr.name, "d2h_drain", fetch_ns)
                        st.phases.add(qr.name, "ring_wait",
                                      time.perf_counter_ns() - loop_t0)
                    if wake_h is not None:
                        qr._apply_wake(int(wake_h))
                    if fetch_h is None:
                        continue
                    with _tracing.adopt(trace):
                        if len(out) == 6:
                            _emit_output_sync(qr, out, now, header=fetch_h,
                                              ingest_ns=t_in)
                        else:
                            _emit_output_sync(qr, fetch_h, now,
                                              ingest_ns=t_in)
                except Exception as exc:  # noqa: BLE001 — drainer survives
                    # route to the app error path (reference: the Disruptor
                    # ExceptionHandler) — MatchOverflowError and callback
                    # failures must reach the exception listener, not stderr
                    import logging
                    logging.getLogger("siddhi_tpu").error(
                        "async emission error in %s: %s",
                        getattr(qr, "name", "?"), exc)
                    listener = getattr(qr.app, "exception_listener", None)
                    if listener is not None:
                        try:
                            listener(exc)
                        except Exception:  # noqa: BLE001
                            traceback.print_exc()
                    else:
                        traceback.print_exc()
                finally:
                    self._q.task_done()


class _Scheduler:
    """Host timer thread injecting TIMER batches
    (reference: CORE/util/Scheduler.java:48)."""

    def __init__(self, app: "SiddhiAppRuntime"):
        self.app = app
        self._heap: List[Tuple[int, int, QueryRuntime]] = []
        self._cv = threading.Condition()
        self._counter = 0
        self._running = False
        self._draining = False
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if self.app.playback:
            return  # event-driven time: timers fire from _route drains
        self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="siddhi-scheduler")
        self._thread._siddhi_internal = True   # see StreamJunction workers
        self._thread.start()

    def drain_playback(self, now: int) -> None:
        if self._draining:
            return
        self._draining = True
        try:
            while self._heap and self._heap[0][0] <= now:
                ts, _, q = heapq.heappop(self._heap)
                lk = getattr(q, "_qlock", None)
                if lk is None:
                    lk = q.__dict__.setdefault("_qlock", threading.RLock())
                with lk:
                    q.on_timer(ts)
        finally:
            self._draining = False

    def stop(self):
        with self._cv:
            self._running = False
            self._cv.notify_all()
        if self._thread:
            self._thread.join(timeout=2.0)

    def notify_at(self, ts: int, q: QueryRuntime) -> None:
        with self._cv:
            self._counter += 1
            heapq.heappush(self._heap, (ts, self._counter, q))
            self._cv.notify_all()

    def _run(self):
        while True:
            with self._cv:
                if not self._running:
                    return
                if not self._heap:
                    self._cv.wait(timeout=0.2)
                    continue
                ts, _, q = self._heap[0]
                now = self.app.timestamp_millis()
                if ts > now:
                    self._cv.wait(timeout=min((ts - now) / 1000.0, 0.2))
                    continue
                heapq.heappop(self._heap)
            try:
                # serialize against the target's ingestion workers; targets
                # without a query lock get their own (NOT the app lock — a
                # timer target holding the app lock while taking query
                # locks downstream could deadlock against a worker emitting
                # into a named window)
                lk = getattr(q, "_qlock", None)
                if lk is None:
                    lk = q.__dict__.setdefault(
                        "_qlock", threading.RLock())
                with lk:
                    q.on_timer(max(ts, self.app.timestamp_millis()))
            except Exception:  # noqa: BLE001 - scheduler must survive
                import traceback
                traceback.print_exc()


class SiddhiAppRuntime:
    """reference: CORE/SiddhiAppRuntimeImpl.java:99"""

    def __init__(self, app: SiddhiApp, manager: "SiddhiManager",
                 name: Optional[str] = None, mesh=None):
        self.app = app
        self.manager = manager
        self.mesh = mesh  # jax.sharding.Mesh with a 'shard' axis, or None
        self.name = name or app.name or "SiddhiApp"
        self.interner = manager.interner
        # system-wide properties + per-extension ConfigReaders; handed to the
        # planner so extensions can read config at compile time
        self.config_manager = manager.config_manager
        self.objects = ev.ObjectRegistry()
        self._lock = threading.RLock()
        # open => InputHandler sends flow; cleared by _quiesce so snapshots
        # can drain async queues without racing persistent producers
        # (reference: ThreadBarrier, CORE/util/ThreadBarrier.java:27)
        self._ingress_gate = threading.Event()
        self._ingress_gate.set()
        self._scheduler = _Scheduler(self)
        self._drainer = _EmissionDrainer()
        # device-resident serving loop (siddhi_tpu/serving): ring drainer
        # (thread lazy-starts on the first ring) + H2D staging pipeline
        from ..serving import (DoubleBufferedStager, ServingDrainer,
                               serving_config)
        self._serve_drainer = ServingDrainer(
            self, serving_config(self)["drain_interval_ms"])
        self._serve_stager = DoubleBufferedStager()
        # on-demand plan LRU: query string -> (parsed AST, OnDemandPlanMemo)
        self._ondemand_cache: "collections.OrderedDict" = \
            collections.OrderedDict()
        self._ondemand_cache_lock = threading.Lock()
        self._started = False
        # playback: event-driven time (reference: @app:playback,
        # CORE/util/timestamp/TimestampGeneratorImpl.java:118)
        pb = app.get_annotation("app:playback")
        self.playback = pb is not None
        self._playback_time = 0
        # @app:playback(idle.time='...', increment='...'): when the input
        # goes quiet for idle.time (wall clock), advance the event clock by
        # increment and fire the timers it passes, so time windows/patterns
        # still flush (reference: TimestampGeneratorImpl.java:118-140).
        self._playback_idle_ms: Optional[int] = None
        self._playback_increment_ms = 1000
        self._playback_last_wall = current_millis()
        self._idle_stop: Optional[threading.Event] = None
        self._idle_thread: Optional[threading.Thread] = None
        if pb is not None:
            from .aggregation import parse_time_ms
            it = pb.element("idle.time")
            if it is not None:
                self._playback_idle_ms = parse_time_ms(str(it))
                inc = pb.element("increment", "1 sec")
                self._playback_increment_ms = parse_time_ms(str(inc)) or 1000

        # statistics (reference: @app:statistics levels OFF/BASIC/DETAIL)
        from ..utils.statistics import OFF, StatisticsManager
        st_ann = app.get_annotation("app:statistics")
        level = OFF
        if st_ann is not None:
            v = st_ann.element() or st_ann.element("level") or "BASIC"
            level = str(v).upper()
            if level == "TRUE":
                level = "BASIC"
            elif level == "FALSE":
                level = OFF
        self.stats = StatisticsManager(
            level, include=str(st_ann.element("include", ""))
            if st_ann is not None else "")
        # @app:statistics(reporter='console', interval='5 sec') starts a
        # periodic reporter with the app (reference: startReporting :55)
        self._stats_reporter = None
        if st_ann is not None and \
                str(st_ann.element("reporter", "")).lower() == "console":
            from ..utils.statistics import ConsoleReporter
            from .aggregation import parse_time_ms
            iv = parse_time_ms(st_ann.element("interval", "5 sec")) or 5000
            self._stats_reporter = ConsoleReporter(self, iv / 1000.0)
        self.exception_listener = None

        # error store: failed events captured by @OnError(action='STORE')
        # and @sink(on.error='store'), replayable via replay_errors()/
        # REST (reference: core.util.error.handler ErrorStore).  SPI:
        # assign a custom ErrorStore before start().
        from ..io.errorstore import InMemoryErrorStore
        es_ann = app.get_annotation("app:errorStore")
        self.error_store = InMemoryErrorStore(
            capacity=int(es_ann.element("capacity", 1024))
            if es_ann is not None else 1024)
        # snapshot revisions skipped as corrupt/unreadable during
        # restore_last_revision (siddhi_restore_fallbacks_total)
        self.restore_fallbacks = 0

        # schemas & junctions
        self.schemas: Dict[str, ev.Schema] = {}
        self.junctions: Dict[str, StreamJunction] = {}
        for sid, sdef in list(app.stream_definition_map.items()):
            self._define_stream_runtime(sdef)

        # tables (reference: CORE/table/InMemoryTable.java; @store tables
        # back onto a RecordTable SPI store, AbstractRecordTable.java:449)
        from .table import RecordTableRuntime, TableRuntime
        self.tables: Dict[str, TableRuntime] = {}
        for tid, tdef in app.table_definition_map.items():
            schema = ev.Schema(tdef, self.interner)
            store_ann = tdef.get_annotation("store")
            if store_ann is not None:
                from ..io.store import CacheTable, create_store
                stype = store_ann.element("type")
                if stype is None:
                    raise CompileError(
                        f"@store on table {tid!r} needs a type element")
                props = {k: v for k, v in store_ann.named_elements().items()
                         if k != "type"}
                reader = self.config_manager.generate_config_reader(
                    "store", str(stype))
                store = create_store(str(stype), tdef, schema, props, reader)
                cache = None
                for sub in store_ann.annotations:
                    if sub.name.lower() == "cache":
                        pk = tdef.get_annotation("PrimaryKey")
                        kpos = [schema.position(v)
                                for v in pk.positional_elements()] if pk else \
                            list(range(len(schema.names)))
                        cache = CacheTable(
                            store, kpos,
                            max_size=int(sub.element("size",
                                                     sub.element("max.size",
                                                                 10))),
                            policy=str(sub.element("policy",
                                                   sub.element("cache.policy",
                                                               "FIFO"))))
                self.tables[tid] = RecordTableRuntime(
                    tdef, schema, store, self.interner, cache=cache)
            else:
                self.tables[tid] = TableRuntime(tdef, schema)

        # named windows (reference: CORE/window/Window.java:65)
        self.named_windows: Dict[str, NamedWindowRuntime] = {}
        for wid, wdef in getattr(app, "window_definition_map", {}).items():
            schema = ev.Schema(wdef, self.interner)
            self.schemas[wid] = schema
            self.named_windows[wid] = NamedWindowRuntime(wdef, schema, self)

        # incremental aggregations (reference: CORE/aggregation/*)
        from .aggregation import AggregationRuntime
        self.aggregations: Dict[str, AggregationRuntime] = {}
        for aid, adef in app.aggregation_definition_map.items():
            agg = AggregationRuntime(adef, self)
            self.aggregations[aid] = agg

            class _ASub:
                def __init__(self, a):
                    self._a = a

                def process_staged(self, staged, now):
                    self._a.process_staged(staged, now)

            self.junctions[agg.input_stream_id].subscribe_query(_ASub(agg))
            if agg.purge_enabled or agg._store_tables:
                # periodic retention purge + store write-through
                # (reference: IncrementalDataPurger scheduled executor)
                self._scheduler.notify_at(
                    self.timestamp_millis() + agg.purge_interval_ms, agg)

        # triggers define a stream `<id> (triggered_time long)` (reference:
        # QAPI/definition/TriggerDefinition -> DefinitionParserHelper)
        self.triggers: Dict[str, TriggerRuntime] = {}
        for tid, tdef in app.trigger_definition_map.items():
            if tid not in self.schemas:
                sdef = StreamDefinition(tid).attribute(
                    "triggered_time", "LONG")
                app.stream_definition_map[tid] = sdef
                self._define_stream_runtime(sdef)
            self.triggers[tid] = TriggerRuntime(tdef, self)

        # sources & sinks from @source/@sink stream annotations (reference:
        # DefinitionParserHelper.addEventSource/addEventSink)
        from ..io.sink import SinkRuntime
        from ..io.source import SourceRuntime
        self.sources: List[SourceRuntime] = []
        self.sinks: List[SinkRuntime] = []
        for sid, sdef in list(app.stream_definition_map.items()):
            for ann in sdef.annotations:
                n = ann.name.lower()
                if n == "source":
                    self.sources.append(SourceRuntime(sid, ann, self))
                elif n == "sink":
                    sk = SinkRuntime(sid, ann, self)
                    self.sinks.append(sk)
                    self.junctions[sid].subscribe_callback(sk)

        # plan queries
        self.query_runtimes: Dict[str, QueryRuntime] = {}
        self._timed_limiters: List = []
        self._partition_purgers: List[_PartitionPurger] = []
        qi = 0
        for element in app.execution_element_list:
            if isinstance(element, Query):
                qname = self._query_name(element, qi)
                qi += 1
                self._add_query(element, qname)
            elif isinstance(element, Partition):
                qi = self._add_partition(element, qi)

        # whole-app multi-query optimizer (siddhi_tpu/optimizer): merge
        # co-resident queries on one junction into shared dispatches.
        # Runs AFTER per-query planning (it stacks the planned step
        # bodies) and BEFORE admission registration (merged owners get
        # compile-gate labels too).
        self.merged_groups: Dict[str, object] = {}
        self._merge_reasons: Dict[str, str] = {}
        from ..optimizer import apply_merge
        apply_merge(self)

        # admission control: per-app quotas + overload ladder
        # (core/admission.py).  Registered with the shared CompileGate
        # HERE (not start()) — the first trace can happen before start()
        # via a direct process call or EXPLAIN deep mode.
        from .admission import AdmissionController
        self.admission = AdmissionController(self)
        self.admission.register_owners(
            self.stats._owners_of(self) or [])

    # -- construction ---------------------------------------------------------
    def _define_stream_runtime(self, sdef: StreamDefinition):
        schema = ev.Schema(sdef, self.interner, objects=None)
        self.schemas[sdef.id] = schema
        on_error = "LOG"
        ann = sdef.get_annotation("OnError")
        if ann is not None:
            on_error = (ann.element("action") or "LOG").upper()
        self.junctions[sdef.id] = StreamJunction(
            schema, stream_id=sdef.id, on_error=on_error, app=self)
        if on_error == "STREAM" and not sdef.id.startswith("!"):
            self._ensure_fault_stream(sdef.id)

    def _ensure_fault_stream(self, stream_id: str) -> None:
        """Auto-define the `!stream` fault stream: original attrs +
        `_error` (reference: FaultStreamEventConverter).  Used by
        @OnError(action='STREAM') and @sink(on.error='stream') — both
        route failures into the same junction."""
        fault_id = "!" + stream_id
        if fault_id in self.junctions or stream_id.startswith("!"):
            return
        sdef = self.app.stream_definition_map[stream_id]
        fdef = StreamDefinition(fault_id)
        for a in sdef.attribute_list:
            fdef.attribute(a.name, a.type)
        fdef.attribute("_error", "STRING")
        self.app.stream_definition_map[fdef.id] = fdef
        self._define_stream_runtime(fdef)

    def _query_name(self, q: Query, i: int) -> str:
        info = q.get_annotation("info")
        if info:
            n = info.element("name")
            if n:
                return n
        return f"query{i + 1}"

    def _add_query(self, q: Query, name: str):
        from ..query_api.query import JoinInputStream, StateInputStream
        if isinstance(q.input_stream, JoinInputStream):
            self._add_join_query(q, name)
            return
        if isinstance(q.input_stream, StateInputStream):
            from .pattern_planner import plan_pattern_query
            import functools
            # @capacity(slots='N') bounds the pending-state slab for
            # non-partitioned patterns too (the reference's pending list is
            # unbounded, StreamPreStateProcessor.java:80; P is our bound)
            nfa_slots = 8
            cap_ann = q.get_annotation("capacity")
            if cap_ann is not None:
                nfa_slots = int(cap_ann.element("slots", nfa_slots))
            plan = functools.partial(
                plan_pattern_query, q, name, self.schemas, self.interner,
                slots=nfa_slots,
                script_functions=self.app.function_definition_map)
            planned = plan()
            self._validate_in_deps(
                getattr(planned.exec, "in_deps", ()), name)
            runtime = PatternQueryRuntime(planned, self)
            # the SAME partial replans on emission-cap growth: initial plan
            # and regrow can never drift apart
            runtime._replan = lambda cap, _p=plan: _p(
                compact_rows_override=cap)
            runtime.async_emit = self._async_enabled(q)
            runtime.pipeline_emit = self._pipeline_enabled(q)
            self._wire_serve(runtime, q)
            self._maybe_fuse(runtime, q, "pattern")
            self.query_runtimes[name] = runtime
            for sid in planned.spec.stream_ids:

                class _Sub:
                    def __init__(self, qr, stream):
                        self._qr, self._sid = qr, stream

                    def process_staged(self, staged, now):
                        self._qr.process_staged(self._sid, staged, now)

                self.junctions[sid].subscribe_query(_Sub(runtime, sid))
            self._wire_output(runtime, q, planned, name)
            return
        in_sid = q.input_stream.unique_stream_id
        from_window = in_sid in self.named_windows
        # @capacity(window='N') bounds the window state slab for this query
        wch, wch_set = 2048, False
        cap_ann = q.get_annotation("capacity")
        if cap_ann is not None and cap_ann.element("window"):
            wch, wch_set = int(cap_ann.element("window")), True
        # session(gap, key) runs the keyed-window slab outside partitions:
        # per-key batch slices are small (E rows), so the per-key window
        # capacity and the batch capacity shrink like the partition path's
        from ..query_api.query import Window as _Win
        skeyed = any(
            isinstance(h, _Win) and h.name == "session" and
            len(h.parameters) >= 2
            for h in getattr(q.input_stream, "stream_handlers", []))
        kw = dict(window_capacity_hint=wch)
        if skeyed:
            kcap = 4096
            if cap_ann is not None and cap_ann.element("keys"):
                kcap = int(cap_ann.element("keys"))
            if self.mesh is not None:
                n = self.mesh.devices.size
                kcap = ((kcap + n - 1) // n) * n
            kw = dict(
                batch_capacity=64,
                window_capacity_hint=wch if wch_set else 128,
                window_key_allocator=SlotAllocator(
                    kcap, name=f"{name}:sessionkey"),
                key_capacity=kcap, mesh=self.mesh)
        planned = plan_single_query(
            q, name, self.app.stream_definition_map, self.schemas,
            self.interner, named_window_input=from_window,
            config_manager=self.config_manager,
            script_functions=self.app.function_definition_map,
            **kw)
        self._validate_in_deps(planned.in_deps, name)
        runtime = QueryRuntime(planned, self)
        runtime.async_emit = self._async_enabled(q)
        runtime.pipeline_emit = self._pipeline_enabled(q)
        self._wire_serve(runtime, q)
        self._maybe_fuse(runtime, q, "plain")
        self.query_runtimes[name] = runtime
        if from_window:
            self.named_windows[in_sid].subscribers.append(runtime)
        else:
            self.junctions[planned.input_stream_id].subscribe_query(runtime)
        self._wire_output(runtime, q, planned, name)

    def _attach_rate_limiter(self, q: Query, runtime) -> None:
        """`output [all|first|last] every ... | snapshot every t` (reference:
        OutputParser.constructOutputRateLimiter, OutputParser.java:282)."""
        from .ratelimit import create_rate_limiter
        runtime.rate_limiter = None
        if q.output_rate is None:
            return
        group_positions = None
        if q.selector.group_by_list:
            # positions of projected group-by attributes in the OUTPUT row
            # (the GroupBy limiter variants key on them; reference:
            # ratelimit/event/FirstGroupByPerEventOutputRateLimiter etc.)
            from ..query_api.expression import Variable as V

            def _matches(oa_expr) -> bool:
                # match qualified group-by vars by (stream, attr) so a
                # same-named attribute from another join side cannot
                # satisfy the check
                if not isinstance(oa_expr, V):
                    return False
                for v in q.selector.group_by_list:
                    if v.attribute_name != oa_expr.attribute_name:
                        continue
                    if v.stream_id is None or oa_expr.stream_id is None \
                            or v.stream_id == oa_expr.stream_id:
                        return True
                return False
            group_positions = [
                i for i, oa in enumerate(q.selector.selection_list)
                if _matches(oa.expression)] or None
            if group_positions is None and \
                    q.output_rate.behavior in ("FIRST", "LAST"):
                # the grouped limiter keys on the group attrs in the OUTPUT
                # row; without them it would silently degrade to ungrouped
                # first/last (reference keys on the internal group key)
                raise CompileError(
                    f"output {q.output_rate.behavior.lower()} with group "
                    f"by requires projecting the group-by attribute(s) in "
                    f"the select clause")
        lim = create_rate_limiter(
            q.output_rate,
            lambda pairs, now, _rt=runtime: _deliver_pairs(_rt, pairs, now),
            group_positions)
        runtime.rate_limiter = lim
        if lim is not None and lim.needs_timer:
            lim._schedule = lambda ts, _l=lim: \
                self._scheduler.notify_at(ts, _l)
            self._timed_limiters.append(lim)

    def _wire_output(self, runtime, q: Query, planned, name: str):
        """Route query output: stream (define if missing), table op, or
        window insert."""
        self._attach_rate_limiter(q, runtime)
        from ..query_api.query import (
            DeleteStream,
            UpdateOrInsertStream,
            UpdateStream,
        )
        runtime.table_op = None
        tgt = planned.output_target
        out_stream = q.output_stream
        if tgt and tgt in self.tables:
            table = self.tables[tgt]
            out_key = "__out__"
            scope_schema = planned.out_schema
            if isinstance(out_stream, (DeleteStream, UpdateStream,
                                       UpdateOrInsertStream)):
                cond_expr = (out_stream.on_delete_expression
                             if isinstance(out_stream, DeleteStream)
                             else out_stream.on_update_expression)
                from .executor import Scope, compile_expression
                scope = Scope()
                scope.interner = self.interner
                scope.add_source(out_key, scope_schema)
                # table attrs must be qualified (T.attr); unqualified names
                # resolve to the query output side, as in the reference
                scope.add_source(tgt, table.schema, default=False)
                cond = table.plan_condition(cond_expr, scope)
                set_fns = []
                us = getattr(out_stream, "update_set", None)
                if us is None and not isinstance(out_stream, DeleteStream):
                    # default set: overwrite all same-named columns
                    for n in table.schema.names:
                        if n in scope_schema.names:
                            from ..query_api.expression import Variable as V
                            e = compile_expression(V(n, stream_id=out_key),
                                                   scope)
                            set_fns.append((table.schema.position(n), e.fn))
                elif us is not None:
                    for sa in us.set_attribute_list:
                        pos = table.schema.position(
                            sa.table_variable.attribute_name)
                        e = compile_expression(sa.value_expression, scope)
                        set_fns.append((pos, e.fn))
                op = ("delete" if isinstance(out_stream, DeleteStream) else
                      "upsert" if isinstance(out_stream, UpdateOrInsertStream)
                      else "update")
                runtime.table_op = (op, table, cond, set_fns, out_key)
            else:
                if len(table.schema.names) != len(planned.out_schema.names):
                    raise CompileError(
                        f"query {name!r} output arity does not match table "
                        f"{tgt!r}")
                runtime.table_op = ("insert", table, None, [], out_key)
            return
        self._define_output_for(planned, name)

    def _add_join_query(self, q: Query, name: str):
        import functools
        from .join import plan_join_query
        plan = functools.partial(
            plan_join_query, q, name, self.schemas, self.tables,
            self.interner, aggregations=self.aggregations,
            named_windows=self.named_windows, mesh=self.mesh)
        planned = plan()
        runtime = JoinQueryRuntime(planned, self)

        # the SAME partial replans on emission-cap growth AND equi-join
        # lane growth; the runtime's current lane width always rides
        # along so one growth can never silently reset the other
        def _join_replan(rows=None, _p=plan, _rt=runtime, **kw):
            if getattr(_rt, "_lane_k", 0):
                kw.setdefault("lane_k_override", _rt._lane_k)
            return _p(emit_rows_override=rows, **kw)
        runtime._replan = _join_replan
        runtime.async_emit = self._async_enabled(q)
        runtime.pipeline_emit = self._pipeline_enabled(q)
        self._wire_serve(runtime, q)
        self._maybe_fuse(runtime, q, "join")
        self.query_runtimes[name] = runtime
        for side, is_left in ((planned.left, True), (planned.right, False)):
            class _JSub:
                def __init__(self, qr, left):
                    self._qr, self._left = qr, left

                def process_staged(self, staged, now):
                    self._qr.process_staged(self._left, staged, now)
            if not side.is_table:
                self.junctions[side.stream_id].subscribe_query(
                    _JSub(runtime, is_left))
            elif side.is_named_window and (
                    planned.step_left if is_left else
                    planned.step_right) is not None:
                # bidirectional named-window join: events flowing through
                # the shared window trigger the join side too (reference:
                # Window.java:145-184 publishes to subscribing queries)
                self.named_windows[side.stream_id].subscribers.append(
                    _JSub(runtime, is_left))
        self._wire_output(runtime, q, planned, name)

    def _async_enabled(self, q) -> bool:
        """@async at app level, on the query, or on any input stream
        definition (reference: @async is a stream-level annotation,
        StreamJunction.startProcessing :276-313)."""
        from .plan_facts import async_enabled
        return async_enabled(self.app, q)

    def _pipeline_enabled(self, q) -> int:
        """@pipeline(depth='k') on the app or the query: deferred emission
        so host staging of batch N+1 overlaps the device step of batch N
        (no extra thread).  depth=1 (default) delivers each send's
        predecessor; depth>1 lets emissions lag up to k sends and drains
        them in batched device_gets, amortizing the per-fetch tunnel
        latency over ~k/2 sends.  The WHOLE delivery lags until flush():
        callbacks, table writes, and downstream stream/window inserts — a
        reader query in the same app observes this query's effects up to k
        batches behind (same relaxation @async makes, minus the thread).
        Timer-bearing (time/cron-window, absent-pattern) queries are
        excluded in _emit_output.  Returns the depth (0 = off)."""
        # the query's own annotation wins (it may carry a depth the
        # app-level blanket annotation lacks); plan_facts.pipeline_depth
        # is the one implementation, shared with the merge planner
        from .plan_facts import pipeline_depth
        return pipeline_depth(self.app, q)

    def _serve_enabled(self, q) -> bool:
        """Device-resident serving loop (siddhi_tpu/serving): emissions
        append to an on-device ring (dispatch-only send path) and the
        per-app drainer thread delivers them asynchronously.  Enabled by
        @serve on the query / any input stream / @app:serve
        (plan_facts.serve_enabled — the one implementation, shared with
        the merge planner and lint) or app-wide by the `serving.enabled`
        config property; @serve(enabled='false') opts a query out of
        either blanket.  Takes precedence over @async/@pipeline in
        _emit_output; timer-bearing queries fall back to inline
        delivery there (same exclusion @pipeline has)."""
        from .plan_facts import serve_enabled
        if serve_enabled(self.app, q):
            return True
        # any explicit @serve annotation that did NOT enable is an
        # opt-out — the config blanket must not override it
        if q.get_annotation("serve") is not None or \
                self.app.get_annotation("app:serve") is not None:
            return False
        from ..serving import serving_config
        return bool(serving_config(self)["enabled"])

    def _wire_serve(self, runtime, q) -> None:
        """Stash the serving decision + ring sizing on the runtime at
        wiring time (the emission hot path reads attributes only)."""
        runtime.serve_emit = self._serve_enabled(q)
        if runtime.serve_emit:
            from .plan_facts import serve_ring_capacity
            runtime.serve_ring_capacity = serve_ring_capacity(self.app, q)

    def _fuse_enabled(self, q) -> int:
        """@fuse(batches='K') on the query, any input stream definition,
        or the app (@app:fuse): stack K staged micro-batches and run them
        as ONE lax.scan device dispatch — per-send RTT and dispatch
        overhead divide by K (core/fusion.py).  Composes with @pipeline/
        @async (per-batch emissions re-enter their paths) and @emit.
        Returns the stack depth K (0 = off)."""
        from .plan_facts import fuse_depth
        return fuse_depth(self.app, q)

    def _maybe_fuse(self, runtime, q, kind: str) -> None:
        # every query runtime passes through here with its AST and path
        # kind — retained for EXPLAIN (observability/explain.py renders
        # the operator tree from the AST; kind selects the fusion rules)
        runtime._query_ast = q
        runtime._kind = kind
        k = self._fuse_enabled(q)
        if k <= 0:
            return
        runtime._fuse_requested = k
        why = _fusion.ineligible_reason(runtime, kind)
        if why is not None:
            # kept for explain(): the concrete reason @fuse skipped this
            # query, not just a log line that scrolled away
            runtime._fuse_excluded = why
            logging.getLogger("siddhi_tpu").warning(
                "@fuse(batches=%d) ignored on query %s: %s", k,
                runtime.name, why)
            return
        runtime._fuse = _fusion.FuseBuffer(runtime, k, kind)

    def _add_partition(self, part: Partition, qi: int) -> int:
        """Partitions: key-scoped state clones (reference:
        CORE/partition/PartitionRuntimeImpl.java:75).  Here the partition key
        becomes an explicit key axis: pattern queries get per-key NFA slabs,
        aggregations compose the partition key into their group key."""
        from ..query_api.query import (
            JoinInputStream,
            RangePartitionType,
            StateInputStream,
            ValuePartitionType,
        )
        from ..query_api.expression import Variable as V
        from .pattern_planner import plan_pattern_query

        # partition key attribute position per stream (value partitions) or
        # a derived-key fn (range partitions: first matching range's label,
        # reference: RangePartitionExecutor.java:45; non-matching rows drop)
        positions: Dict[str, List[int]] = {}
        key_fns: Dict[str, Callable] = {}
        for sid, pt in part.partition_type_map.items():
            schema = self.schemas.get(sid)
            if schema is None:
                raise CompileError(f"undefined partitioned stream {sid!r}")
            if isinstance(pt, RangePartitionType):
                from .executor import Scope, compile_expression
                scope = Scope()
                scope.interner = self.interner
                scope.add_source(sid, schema)
                conds = []
                for rp in pt.ranges:
                    c = compile_expression(rp.condition, scope)
                    if c.type != "BOOL":
                        raise CompileError(
                            "range partition conditions must be boolean")
                    conds.append((self.interner.intern(rp.partition_key),
                                  c))

                def make_fn(sid=sid, conds=conds):
                    def fn(staged):
                        env = {sid: tuple(staged.cols),
                               "__ts__": staged.ts, "__now__": staged.ts}
                        ids = np.full(staged.ts.shape[0], -1, np.int32)
                        for label, c in conds:
                            m = np.asarray(c.fn(env)).astype(bool)
                            ids = np.where((ids < 0) & m, label, ids)
                        return [ids], ids >= 0
                    return fn
                key_fns[sid] = make_fn()
                positions[sid] = []
                continue
            assert isinstance(pt, ValuePartitionType)
            if not isinstance(pt.expression, V):
                raise CompileError(
                    "partition-by expression must be a plain attribute in "
                    "this build")
            positions[sid] = [schema.position(pt.expression.attribute_name)]

        # capacity annotation: @capacity(keys='..', slots='..') on the
        # partition or any of its queries
        keys_cap, nfa_slots = 4096, 8
        # per-key window slab rows for windows inside the partition (small
        # default: the slab is keys x window-capacity)
        win_cap = 128
        all_anns = list(part.annotations)
        for q in part.query_list:
            all_anns.extend(q.annotations)
        for ann in all_anns:
            if ann.name.lower() == "capacity":
                keys_cap = int(ann.element("keys", keys_cap))
                nfa_slots = int(ann.element("slots", nfa_slots))
                win_cap = int(ann.element("window", win_cap))
        if self.mesh is not None:
            n = self.mesh.devices.size
            keys_cap = ((keys_cap + n - 1) // n) * n

        shared_allocator = SlotAllocator(keys_cap, name="partition")
        part_runtimes: List = []

        for q in part.query_list:
            qname = self._query_name(q, qi)
            qi += 1
            if isinstance(q.input_stream, StateInputStream):
                spec_streams = q.input_stream.all_stream_ids
                ppos = {}
                pfns = {}
                for sid in spec_streams:
                    if sid not in positions:
                        raise CompileError(
                            f"pattern stream {sid!r} has no partition key")
                    ppos[sid] = positions[sid]
                    if sid in key_fns:
                        pfns[sid] = key_fns[sid]
                import functools
                plan = functools.partial(
                    plan_pattern_query, q, qname, self.schemas,
                    self.interner, key_capacity=keys_cap, slots=nfa_slots,
                    partition_positions=ppos,
                    partition_key_fns=pfns or None, mesh=self.mesh,
                    script_functions=self.app.function_definition_map)
                planned = plan()
                self._validate_in_deps(
                    getattr(planned.exec, "in_deps", ()), qname)
                runtime = PatternQueryRuntime(planned, self,
                                              slot_allocator=shared_allocator)
                # same partial => initial plan and regrow cannot drift
                runtime._replan = lambda cap, _p=plan: _p(
                    compact_rows_override=cap)
                runtime.async_emit = self._async_enabled(q)
                runtime.pipeline_emit = self._pipeline_enabled(q)
                self._wire_serve(runtime, q)
                self._maybe_fuse(runtime, q, "pattern")
                self.query_runtimes[qname] = runtime
                part_runtimes.append(runtime)
                for sid in planned.spec.stream_ids:
                    class _Sub:
                        def __init__(self, qr, stream):
                            self._qr, self._sid = qr, stream

                        def process_staged(self, staged, now):
                            self._qr.process_staged(self._sid, staged, now)
                    self.junctions[sid].subscribe_query(_Sub(runtime, sid))
                self._attach_rate_limiter(q, runtime)
                self._define_output_for(planned, qname)
            elif isinstance(q.input_stream, JoinInputStream):
                # partitioned join: lower to a plain join whose `on`
                # condition additionally requires equal partition keys on
                # both sides — only same-key rows match, the partition
                # isolation semantics of the reference's per-key clone
                # (PartitionParser.java:137).  NOTE: join-side window
                # CAPACITY is shared across keys here (tune @capacity),
                # unlike the reference's per-key window instances.
                jis = q.input_stream
                lsis, rsis = jis.left_input_stream, jis.right_input_stream
                lsid = lsis.unique_stream_id
                rsid = rsis.unique_stream_id
                if lsid in key_fns or rsid in key_fns:
                    raise CompileError(
                        "range-partitioned joins are not supported")
                from ..query_api.expression import Expression as E
                sides = []
                for sis, ssid in ((lsis, lsid), (rsis, rsid)):
                    if ssid in self.tables or \
                            ssid in self.named_windows or \
                            ssid in self.aggregations:
                        continue        # shared collections: no key column
                    pos = positions.get(ssid)
                    if not pos:
                        # mirror the single-stream branch: a plain stream
                        # side without a partition key would silently join
                        # across partitions
                        raise CompileError(
                            f"stream {ssid!r} has no partition key")
                    schema = self.schemas[ssid]
                    ref = sis.stream_reference_id or ssid
                    sides.append(E.variable(
                        schema.names[pos[0]]).of_stream(ref))
                if len(sides) == 2:
                    eq = E.compare(sides[0], "==", sides[1])
                    jis.on_compare = E.and_(jis.on_compare, eq) \
                        if jis.on_compare is not None else eq
                self._add_join_query(q, qname)
                part_runtimes.append(self.query_runtimes[qname])
                continue
            else:
                ist = q.input_stream
                if not isinstance(ist, SingleInputStream):
                    raise CompileError(
                        "only single-stream, pattern and join queries are "
                        "supported inside partitions")
                sid = ist.unique_stream_id
                ppos = positions.get(sid)
                if ppos is None and not ist.is_inner_stream:
                    raise CompileError(
                        f"stream {sid!r} has no partition key")
                from ..query_api.query import Window as _QWindow
                has_window = any(isinstance(h, _QWindow)
                                 for h in ist.stream_handlers)
                planned = plan_single_query(
                    q, qname, self.app.stream_definition_map, self.schemas,
                    self.interner, group_slots=max(keys_cap, 4096),
                    # keyed windows see per-key E-row batches, so their
                    # window shapes key off a small batch capacity; the
                    # flat (no-window) path keeps the full default
                    batch_capacity=64 if has_window else 512,
                    window_capacity_hint=win_cap,
                    partition_positions=ppos,
                    partition_key_fn=key_fns.get(sid),
                    window_key_allocator=shared_allocator,
                    key_capacity=keys_cap,
                    config_manager=self.config_manager,
                    script_functions=self.app.function_definition_map,
                    mesh=self.mesh)
                self._validate_in_deps(planned.in_deps, qname)
                runtime = QueryRuntime(planned, self)
                runtime.async_emit = self._async_enabled(q)
                runtime.pipeline_emit = self._pipeline_enabled(q)
                self._wire_serve(runtime, q)
                self._maybe_fuse(runtime, q, "plain")
                self.query_runtimes[qname] = runtime
                part_runtimes.append(runtime)
                self.junctions[sid].subscribe_query(runtime)
                self._attach_rate_limiter(q, runtime)
                self._define_output_for(planned, qname)

        # @purge(enable, interval='1 sec', idle.period='10 min'): idle-key
        # GC recycling slots through the allocators (reference:
        # PartitionRuntimeImpl.java:120-147).  Accepted on the partition or
        # any of its queries.
        for ann in all_anns:
            if ann.name.lower() == "purge":
                enabled = str(ann.element("enable", "true")).lower() == "true"
                if not enabled:
                    break
                from ..core.aggregation import parse_time_ms
                interval = parse_time_ms(
                    ann.element("interval", "1 sec")) or 1000
                idle = parse_time_ms(
                    ann.element("idle.period", "5 min")) or 300_000
                purger = _PartitionPurger(
                    self, shared_allocator, part_runtimes, interval, idle)
                self._partition_purgers.append(purger)
                break
        return qi

    def _define_output_for(self, planned, name: str):
        # define the output stream if missing
        tgt = planned.output_target
        if tgt and tgt in self.named_windows:
            nw = self.named_windows[tgt]
            if len(nw.schema.names) != len(planned.out_schema.names):
                raise CompileError(
                    f"query {name!r} output arity does not match window "
                    f"{tgt!r}")
            return
        if tgt and tgt not in self.junctions:
            sdef = StreamDefinition(tgt)
            for a in planned.out_schema.definition.attribute_list:
                sdef.attribute(a.name, a.type)
            self.app.stream_definition_map[tgt] = sdef
            self._define_stream_runtime(sdef)
        elif tgt:
            # validate compatibility
            tdef = self.app.stream_definition_map.get(tgt)
            if tdef is not None and len(tdef.attribute_list) != len(
                    planned.out_schema.names):
                raise CompileError(
                    f"query {name!r} output arity does not match stream {tgt!r}")

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        if not self._started:
            self._scheduler.start()
            self._started = True
            now = self.timestamp_millis()
            # @async(buffer.size, workers) streams get an ingress queue +
            # workers (reference: Disruptor ring per junction).  Playback
            # keeps synchronous dispatch: event-time must stay ordered.
            if not self.playback:
                for sid, j in self.junctions.items():
                    sdef = self.app.stream_definition_map.get(sid)
                    ann = sdef.get_annotation("async") \
                        if sdef is not None else None
                    if ann is not None:
                        j.enable_async(
                            int(ann.element("buffer.size", 256) or 256),
                            int(ann.element("workers", 1) or 1),
                            str(ann.element("queue.policy", "block")
                                or "block").lower())
            for sk in self.sinks:
                sk.start()
            for src in self.sources:
                src.start()
            for tr in self.triggers.values():
                tr.start(now)
            for lim in self._timed_limiters:
                self._scheduler.notify_at(now + lim.interval, lim)
            if self._stats_reporter is not None:
                self._stats_reporter.start()
            if self.playback and self._playback_idle_ms:
                self._playback_last_wall = current_millis()
                self._idle_stop = threading.Event()
                self._idle_thread = threading.Thread(
                    target=self._run_playback_idle, daemon=True,
                    name="siddhi-playback-idle")
                self._idle_thread._siddhi_internal = True
                self._idle_thread.start()

    def _run_playback_idle(self) -> None:
        """Quiet-input clock advance for @app:playback(idle.time, increment)
        (reference: TimestampGeneratorImpl.java:118-140: a periodic task
        checks wall-clock idleness and bumps the event clock)."""
        idle_s = self._playback_idle_ms / 1000.0
        while not self._idle_stop.wait(idle_s):
            if current_millis() - self._playback_last_wall \
                    < self._playback_idle_ms:
                continue
            with self._lock:
                self._playback_time += self._playback_increment_ms
                self._scheduler.drain_playback(self._playback_time)

    def shutdown(self) -> None:
        if self._started:
            for src in self.sources:
                src.stop()
            if self._stats_reporter is not None:
                self._stats_reporter.stop()
            if self._idle_stop is not None:
                self._idle_stop.set()
                if self._idle_thread is not None:
                    self._idle_thread.join(timeout=2.0)
            for j in self.junctions.values():
                j.stop_async()       # drain accepted sends, stop workers
            for qr in self._step_runtimes():
                # buffered @fuse stacks (per-query AND merged-group) and
                # held @pipeline emissions deliver before teardown: an
                # accepted send's output must not vanish (at-least-once)
                _fusion.drain(qr)
                _drain_pending_emit(qr)
            # serving rings drain BEFORE sinks stop: fuse/pipeline drains
            # above may have appended, and an accepted send's output must
            # not die in device memory (at-least-once)
            self._serve_drainer.stop()
            for sk in self.sinks:
                sk.stop()
            self._drainer.stop()
            self._scheduler.stop()
            self._started = False
        # release this app's compile-gate owner labels whether or not it
        # ever started (deploy-then-undeploy without traffic is common)
        adm = getattr(self, "admission", None)
        if adm is not None:
            adm.unregister()

    def pause_sources(self) -> None:
        """reference: SiddhiAppRuntimeImpl pauses Sources around persist."""
        for src in self.sources:
            src.pause()

    def resume_sources(self) -> None:
        for src in self.sources:
            src.resume()

    def flush(self) -> None:
        """Wait until all asynchronously ingested batches are processed and
        all asynchronously emitted output has been delivered.  Iterates to
        a fixpoint: drained output may re-enter another @async stream."""
        for _ in range(64):
            for j in self.junctions.values():
                j.flush_async()
            for qr in self._step_runtimes():
                _fusion.drain(qr)   # partial @fuse stacks process NOW
                _drain_pending_emit(qr)
            self._drainer.flush()
            self._serve_drainer.drain_all()   # serving rings -> empty
            if all(j.pending_async() == 0 for j in self.junctions.values()) \
                    and not any(getattr(qr, "_pending_emit", None) or
                                _fusion.pending(qr)
                                for qr in self._step_runtimes()) \
                    and self._serve_drainer.pending() == 0:
                return
        import logging
        logging.getLogger("siddhi_tpu").warning(
            "flush() gave up after 64 rounds with async batches still "
            "pending (sustained re-ingestion?)")

    def _step_runtimes(self):
        """Every runtime that can hold a @fuse stack or deferred
        emissions: the per-query runtimes plus merged-group dispatchers
        (optimizer/mqo.py) — flush/quiesce/shutdown drain them all."""
        return list(self.query_runtimes.values()) + \
            list(getattr(self, "merged_groups", {}).values())

    def in_probe_tables(self, deps):
        """Snapshots for `x in Table` probes: (first column, validity) per
        dep — the ONE place defining what an In-probe sees (plain, keyed,
        and pattern steps all ship these into their jitted programs)."""
        return tuple((self.tables[d].cols[0], self.tables[d].valid)
                     for d in deps)

    def _validate_in_deps(self, deps, qname: str) -> None:
        """`x in <id>` only probes DEFINED TABLES (reference:
        InConditionExpressionExecutor resolves a table); reject named
        windows / aggregations / typos at plan time, not as a KeyError on
        the first send."""
        for d in deps:
            if d not in self.tables:
                raise CompileError(
                    f"query {qname!r}: `in {d}` requires a defined table "
                    f"(named windows and aggregations are not probe-able "
                    f"with `in`; defined tables: {sorted(self.tables)})")

    def _gate_wait(self) -> None:
        """Entry valve (reference: InputEntryValve + ThreadBarrier): external
        producer threads block while a snapshot quiesces the app.  The
        app's OWN threads (async ingest workers, emission drainer,
        scheduler) are exempt — a worker whose callback re-ingests must
        keep draining or _quiesce's queue join would deadlock against the
        closed gate."""
        if getattr(threading.current_thread(), "_siddhi_internal", False):
            return
        self._ingress_gate.wait()

    @contextlib.contextmanager
    def _quiesce(self):
        """Close the ingress gate (producers block at the entry valve),
        drain async queues, then acquire the app lock plus EVERY query lock
        (the reference's ThreadBarrier quiescing event threads for
        snapshots).  The gate must close BEFORE the drain: joining a queue
        that a persistent producer keeps refilling livelocks — observed as
        an indefinitely-spinning snapshot under load.  Accepted-but-queued
        events still land in the snapshotted state (at-least-once across a
        persist/restore)."""
        self._ingress_gate.clear()
        cur = threading.current_thread()
        prev_internal = getattr(cur, "_siddhi_internal", False)
        # the quiescing thread delivers held @pipeline emissions below; a
        # delivery callback that re-ingests must not block on the gate THIS
        # thread closed (it would deadlock the snapshot) — mark it internal
        # for the duration, and iterate drain+deliver to a fixpoint so
        # re-ingested events land in the snapshotted state too
        cur._siddhi_internal = True
        try:
            for _ in range(64):
                for j in self.junctions.values():
                    j.flush_async()
                for qr in self._step_runtimes():
                    # @fuse stacks hold UNPROCESSED events — they must
                    # land in the snapshotted state, not vanish
                    _fusion.drain(qr)
                    _drain_pending_emit(qr)
                # serving rings drain to EMPTY under quiesce: ring
                # contents are in-flight output, never snapshotted state
                self._serve_drainer.drain_all()
                if all(j.pending_async() == 0
                       for j in self.junctions.values()) and \
                        not any(getattr(qr, "_pending_emit", None) or
                                _fusion.pending(qr)
                                for qr in self._step_runtimes()) and \
                        self._serve_drainer.pending() == 0:
                    break
            locks = [self._lock]
            for qname in sorted(self.query_runtimes):
                lk = getattr(self.query_runtimes[qname], "_qlock", None)
                if lk is not None:
                    locks.append(lk)
            for wid in sorted(self.named_windows):
                locks.append(self.named_windows[wid]._qlock)
            with _acquire_all(locks):
                yield
        finally:
            cur._siddhi_internal = prev_internal
            self._ingress_gate.set()

    def timestamp_millis(self) -> int:
        if self.playback:
            return self._playback_time
        return current_millis()

    # -- I/O ------------------------------------------------------------------
    def get_input_handler(self, stream_id: str) -> InputHandler:
        if stream_id not in self.junctions:
            raise DefinitionNotExistError(f"undefined stream {stream_id!r}")
        return InputHandler(stream_id, self)

    def replay_errors(self, ids=None, stream_id: Optional[str] = None
                      ) -> Dict[str, int]:
        """Re-inject error-store entries through the normal InputHandler
        path, original timestamps preserved (reference: the error
        store's replay admin API).  Entries leave the store BEFORE
        injection — exactly-once handoff; if re-processing fails again
        the failure path captures them as fresh entries.  Returns
        {"entries": n, "events": m, "skipped": k}."""
        taken = self.error_store.take(ids=ids, stream_id=stream_id)
        n_entries = n_events = skipped = 0
        for entry in taken:
            if entry.stream_id not in self.junctions:
                # stream vanished (app edit between capture and replay):
                # keep the events instead of silently losing them
                self.error_store.store(
                    entry.stream_id, entry.events,
                    RuntimeError(f"replay skipped: stream "
                                 f"{entry.stream_id!r} no longer exists"),
                    origin=entry.origin)
                skipped += 1
                continue
            h = self.get_input_handler(entry.stream_id)
            # replay is exactly-once recovery of events the engine
            # already accepted — the admission rate limit must not
            # shed them a second time
            h._admit = False
            for e in entry.events:
                h.send(e)
            n_entries += 1
            n_events += len(entry.events)
        return {"entries": n_entries, "events": n_events,
                "skipped": skipped}

    def add_batch_callback(self, query_name: str, cb) -> None:
        """High-throughput query callback receiving columnar numpy batches
        (ts, kind, valid, cols dict) without per-event decoding."""
        if query_name not in self.query_runtimes:
            raise QueryNotExistError(f"no query named {query_name!r}")
        self.query_runtimes[query_name].batch_callbacks.append(cb)

    def add_callback(self, name: str, cb) -> None:
        """Stream name -> StreamCallback; query name -> QueryCallback."""
        if name in self.named_windows:
            self.named_windows[name].stream_callbacks.append(
                _wrap_stream_callback(cb))
        elif name in self.junctions and name not in self.query_runtimes:
            self.junctions[name].subscribe_callback(_wrap_stream_callback(cb))
        elif name in self.query_runtimes:
            self.query_runtimes[name].callbacks.append(_wrap_query_callback(cb))
        else:
            raise QueryNotExistError(f"no stream or query named {name!r}")

    def _route_columns(self, stream_id: str, cols, timestamps) -> None:
        junction = self.junctions.get(stream_id)
        if junction is None:
            raise DefinitionNotExistError(f"undefined stream {stream_id!r}")
        pack_t0 = time.perf_counter_ns()
        n = len(cols[0])
        cap = ev.bucket_size(max(n, 1))
        schema = junction.schema
        if timestamps is None:
            ts0 = self.timestamp_millis()
            ts = np.full((cap,), ts0, np.int64)
        elif n == cap and isinstance(timestamps, np.ndarray) and \
                timestamps.dtype == np.int64 and timestamps.flags.c_contiguous:
            # zero-copy staging: a full-bucket send adopts the caller's
            # buffers (send_columns transfers ownership — callers must not
            # mutate after send).  Beyond skipping the memcpy, re-sent
            # buffers stay IDENTICAL objects, which the tunneled device
            # client dedupes — steady-state H2D ships only genuinely new
            # bytes (PERF.md: fresh-H2D is the flagship bottleneck)
            ts = timestamps
        else:
            ts = np.zeros((cap,), np.int64)
            ts[:n] = timestamps
        if n == cap:
            # full buckets share immutable all-true/all-zero planes: the
            # tunnel client dedupes repeated identical buffers
            valid, kind = _full_bucket_planes(cap)
        else:
            valid = np.zeros((cap,), np.bool_)
            valid[:n] = True
            kind = np.zeros((cap,), np.int32)
        padded = []
        for c, t in zip(cols, schema.types):
            d = ev.np_dtype(t)
            if n == cap and isinstance(c, np.ndarray) and c.dtype == d \
                    and c.flags.c_contiguous:
                padded.append(c)
                continue
            a = np.zeros((cap,), d)
            a[:n] = c
            padded.append(a)
        staged = ev.StagedBatch(ts, kind, valid, padded, n)
        if self.stats.enabled and junction.queries:
            # columnar pad/adopt staging: stage_host for every subscriber
            # (pack_np-path sends get the same charge inside publish)
            pack_ns = time.perf_counter_ns() - pack_t0
            ph = self.stats.phases
            for sub in junction.queries:
                ph.add(_sub_name(sub, stream_id), "stage_host", pack_ns)
        if self.playback and n:
            with self._lock:   # vs the idle-advance thread's bump
                self._playback_time = max(self._playback_time,
                                          int(ts[:n].max()))
                self._playback_last_wall = current_millis()
        now = self.timestamp_millis()
        if self.playback:
            with self._lock:
                self._scheduler.drain_playback(now)
        elif junction._async_q is not None:
            junction.enqueue("staged", staged, now)
            return
        junction.dispatch_staged(staged, now)

    def _route(self, stream_id: str, events: List[ev.Event]) -> None:
        if stream_id in self.named_windows:
            nw = self.named_windows[stream_id]
            if self.playback and events:
                with self._lock:
                    self._playback_time = max(
                        self._playback_time,
                        max(e.timestamp for e in events))
                    self._playback_last_wall = current_millis()
            now = self.timestamp_millis()
            if self.playback:
                with self._lock:
                    self._scheduler.drain_playback(now)
            with nw._qlock:
                nw.process_staged(ev.pack_np(nw.schema, events), now)
            return
        junction = self.junctions.get(stream_id)
        if junction is None:
            raise DefinitionNotExistError(f"undefined stream {stream_id!r}")
        if self.playback and events:
            with self._lock:
                self._playback_time = max(self._playback_time,
                                          max(e.timestamp for e in events))
                self._playback_last_wall = current_millis()
        now = self.timestamp_millis()
        if self.playback:
            # in playback, fire timers the event clock has passed first
            # (they are earlier in event time than the new events)
            with self._lock:
                self._scheduler.drain_playback(now)
        elif junction._async_q is not None:
            junction.enqueue("pub", events, now)
            return
        junction.publish(events, now)

    # -- statistics / debugging -----------------------------------------------
    def statistics(self) -> Dict:
        """Metric report (reference: SiddhiStatisticsManager)."""
        return self.stats.report(self)

    def buffered_emissions(self) -> int:
        """Device outputs queued in the async emission drainer (public
        accessor — reference: SiddhiBufferedEventsMetric).  Returns 0 on a
        stopped or mid-teardown app instead of raising."""
        d = getattr(self, "_drainer", None)
        if d is None:
            return 0
        try:
            return d.pending()
        except Exception:  # noqa: BLE001 — metrics must not throw
            return 0

    def buffered_ingress(self) -> Dict[str, int]:
        """Batches pending in @async ingress queues, per stream (only
        streams with a non-zero backlog).  Safe mid-shutdown: a junction
        whose queue was already torn down reports nothing."""
        out: Dict[str, int] = {}
        for sid, j in list(self.junctions.items()):
            try:
                n = j.pending_async()
            except Exception:  # noqa: BLE001 — metrics must not throw
                n = 0
            if n > 0:
                out[sid] = n
        return out

    def queue_depths(self) -> Dict[str, int]:
        """Current @async ingress queue depth per stream (only streams
        running an async queue; zero-depth queues ARE reported so the
        gauge exists before the first backlog).  Host-side qsize reads —
        safe mid-shutdown."""
        out: Dict[str, int] = {}
        for sid, j in list(self.junctions.items()):
            try:
                if j._async_q is not None:
                    out[sid] = j.queue_depth()
            except Exception:  # noqa: BLE001 — metrics must not throw
                pass
        return out

    def drainer_depth(self) -> int:
        """Device outputs sitting in the async emission drainer queue
        (siddhi_drainer_queue_depth; 0 on a stopped app)."""
        d = getattr(self, "_drainer", None)
        if d is None:
            return 0
        try:
            return d.depth()
        except Exception:  # noqa: BLE001 — metrics must not throw
            return 0

    def serve_rings(self) -> Dict[str, "object"]:
        """{query: EmissionRing} for every runtime that has opened a
        serving ring (host-side attribute reads only)."""
        out: Dict[str, object] = {}
        for qname, qr in list(self.query_runtimes.items()):
            ring = qr.__dict__.get("_serve_ring")
            if ring is not None:
                out[qname] = ring
        return out

    def ring_occupancies(self) -> Dict[str, int]:
        """Pending (appended, undrained) serving-ring entries per query
        — the siddhi_ring_occupancy gauge (safe mid-shutdown)."""
        out: Dict[str, int] = {}
        for qname, ring in self.serve_rings().items():
            try:
                out[qname] = ring.occupancy()
            except Exception:  # noqa: BLE001 — metrics must not throw
                out[qname] = 0
        return out

    def serve_drainer_depth(self) -> int:
        """Ring entries awaiting the serving drainer across all rings
        (the serving analog of drainer_depth; 0 on a stopped app)."""
        d = getattr(self, "_serve_drainer", None)
        if d is None:
            return 0
        try:
            return d.depth()
        except Exception:  # noqa: BLE001 — metrics must not throw
            return 0

    def timeseries(self) -> Dict:
        """Windowed time-series report for this app: every sampled series
        (ring-buffer {t, v} arrays), the per-tenant account, and the SLO
        state — filled by the manager's TimeSeriesSampler
        (observability/timeseries.py; `enabled` is False until it has
        ticked).  Served as `GET /siddhi-apps/<name>/timeseries`."""
        store = self.__dict__.get("_timeseries")
        out: Dict = {
            "app": self.name,
            "enabled": store is not None,
            "series": store.to_dict() if store is not None else {},
        }
        acct = self.__dict__.get("_tenant_account")
        if acct is not None:
            out["tenant"] = acct
        slo = self.__dict__.get("_slo_state")
        if slo is not None:
            out["slo"] = slo
        return out

    def trace_dump(self, query: Optional[str] = None,
                   limit: int = 64) -> List[Dict]:
        """Recent DETAIL-level batch traces, newest first, optionally only
        those that touched `query` (see observability/tracing.py)."""
        return self.stats.tracer.dump(query, limit)

    def phase_report(self) -> Dict:
        """Per-query phase budget (seconds + share per pipeline phase)
        against the `<query>:e2e` histogram, unattributed remainder as
        `other` — see observability/phases.py.  Host-side reads only:
        safe to call on a live app."""
        from ..observability.phases import phase_report as _pr
        return _pr(self)

    def state_report(self) -> Dict:
        """State observatory report: per-(query, structure) occupancy /
        capacity / high-water utilization, key hotness (count-min +
        top-K), near-capacity verdicts, and the sizing-hints ledger a
        snapshot would persist — see observability/stateobs.py.  Host-
        side reads only: safe to call on a live app."""
        from ..observability.stateobs import state_report as _sr
        return _sr(self)

    def explain(self, query_name: Optional[str] = None,
                deep: bool = True) -> Dict:
        """EXPLAIN report: planned operator tree + per-step XLA cost
        analysis (flops, bytes accessed, estimated peak memory), state
        shapes and nbytes, emission caps, fusion eligibility with the
        concrete exclusion reason, and recompile history.  One query, or
        every query when `query_name` is None (then shallow by default —
        see observability/explain.py).  May compile; this is an on-demand
        diagnostic, never called from the scrape path."""
        from ..observability.explain import explain_app, explain_query
        if query_name is None:
            return explain_app(self, deep=False)
        return explain_query(self, query_name, deep=deep)

    def state_memory(self) -> Dict:
        """{owner: {component: nbytes}} across the app's device state —
        window buffers, pattern slot blocks, selector slabs, tables,
        named windows, aggregations, fuse stacks.  Metadata-only walk
        (no device fetch); also exported as `siddhi_state_bytes` in
        /metrics (observability/memory.py)."""
        from ..observability.memory import component_bytes
        return component_bytes(self)

    def health(self) -> Dict:
        """Host-side health report for this app: readiness/liveness
        verdicts, per-stream last-event age + ingress backlog, and
        sliding-window drop/recompile rates (observability/health.py)."""
        from ..observability.health import app_health
        return app_health(self)

    def analyze(self, config=None) -> Dict:
        """Static lint findings for this app from its ACTUAL compiled
        plans (real emission caps, measured state bytes, mesh-aware
        fusion exclusions) — attribute and metadata reads only, never
        executes or traces (siddhi_tpu/analysis).  Also served as
        `GET /siddhi-apps/<app>/lint` and echoed into explain()."""
        from ..analysis import analyze as _analyze, report as _report
        findings = _analyze(self, config=config,
                            source_name=f"<{self.name}>")
        rep = _report(findings)
        rep["app"] = self.name
        return rep

    def set_statistics_level(self, level: str) -> None:
        self.stats.level = level.upper()

    def set_exception_listener(self, fn) -> None:
        """reference: SiddhiAppRuntimeImpl.handleRuntimeExceptionWith"""
        self.exception_listener = fn

    def debug(self):
        """Attach a debugger; returns it (reference:
        SiddhiAppRuntimeImpl.debug :657-675)."""
        from .debugger import SiddhiDebugger
        self._debugger = SiddhiDebugger(self)
        return self._debugger

    # -- on-demand (store) queries --------------------------------------------
    _ONDEMAND_CACHE_MAX = 50   # reference: SiddhiAppRuntimeImpl.java:304-367

    def query(self, q) -> List[ev.Event]:
        """Execute a one-shot store query against tables/windows/aggregations
        (reference: SiddhiAppRuntimeImpl.query :304-367).  String queries
        hit an LRU (≤50) of parsed+compiled plans, so a repeated store query
        re-plans nothing — only the data pass runs."""
        from ..query_api.query import OnDemandQuery
        from .ondemand import OnDemandPlanMemo, execute_on_demand
        memo = None
        if isinstance(q, str):
            with self._ondemand_cache_lock:
                ent = self._ondemand_cache.get(q)
                if ent is not None:
                    self._ondemand_cache.move_to_end(q)
            if ent is None:
                from ..compiler import SiddhiCompiler
                parsed = SiddhiCompiler.parse_on_demand_query(q)
                ent = (parsed, OnDemandPlanMemo())
                with self._ondemand_cache_lock:
                    self._ondemand_cache[q] = ent
                    while len(self._ondemand_cache) > \
                            self._ONDEMAND_CACHE_MAX:
                        self._ondemand_cache.popitem(last=False)
            q, memo = ent
        assert isinstance(q, OnDemandQuery)
        with self._quiesce():
            return execute_on_demand(self, q, memo)

    # -- snapshot/restore ------------------------------------------------------
    def snapshot(self) -> bytes:
        """Full state snapshot (reference: SnapshotService.fullSnapshot
        CORE/util/snapshot/SnapshotService.java:90) — here simply the state
        pytrees + slot maps, no stop-the-world object walk needed."""
        with self._quiesce():
            states = {}
            for name, qr in self.query_runtimes.items():
                host_state = jax.tree.map(lambda x: np.asarray(x), qr.state)
                alloc = _allocator_of(qr)
                alloc2 = getattr(qr.planned, "slot_allocator2", None)
                jk = getattr(qr.planned, "join_key_allocator", None)
                states[name] = {
                    "state": host_state,
                    "slots": alloc.snapshot() if alloc is not None else None,
                    "slots2": alloc2.snapshot()
                    if alloc2 is not None else None,
                    "slots_jk": jk.snapshot() if jk is not None else None,
                    "slots_pairs": [
                        a.snapshot() for a, _ in
                        getattr(qr.planned, "pair_allocs", [])] or None,
                    "wake": getattr(qr, "next_wakeup", None),
                    # key-state row order (mesh layout) this snapshot is
                    # written in: restore re-buckets through the router
                    # when the target runtime's mesh size differs
                    "layout": _sharding.query_layout(qr),
                }
            windows = {
                wid: jax.tree.map(lambda x: np.asarray(x), nw.state)
                for wid, nw in self.named_windows.items()}
            aggs = {aid: {d: dict(s) for d, s in a.stores.items()}
                    for aid, a in self.aggregations.items()}
            from .table import _table_state
            tables = {tid: _table_state(t) for tid, t in self.tables.items()}
            _stateobs.collect(self)
            payload = {
                "states": states,
                "windows": windows,
                "aggregations": aggs,
                "tables": tables,
                "interner": list(self.interner._to_str),
                # sizing-hints ledger: learned high-water marks ride the
                # snapshot so a restarted app reports its observed
                # capacities from tick zero (observability/stateobs.py)
                "sizing": self.stats.stateobs.ledger(),
            }
            # a full snapshot resets the incremental baseline
            for qr in self.query_runtimes.values():
                if getattr(qr, "_dirty", None) is not None:
                    qr._dirty[:] = False
                alloc = _allocator_of(qr)
                if alloc is not None:
                    alloc.journal.clear()
            for a in self.aggregations.values():
                a.clear_snapshot_baseline()
            return pickle.dumps(payload)

    def snapshot_incremental(self) -> bytes:
        """Delta since the last snapshot: for partitioned pattern queries
        only the state columns of keys touched since then (plus their slot
        journal); small states ship whole (reference: incremental snapshots
        via per-element op-logs, SnapshotService.incrementalSnapshot :189 —
        here the op-log is the host-tracked dirty key mask)."""
        with self._quiesce():
            deltas = {}
            for name, qr in self.query_runtimes.items():
                alloc = _allocator_of(qr)
                dirty = getattr(qr, "_dirty", None)
                if dirty is not None and isinstance(qr.state, tuple) and \
                        len(qr.state) == 2 and isinstance(qr.state[0], tuple):
                    idx = np.nonzero(dirty)[0]
                    b32, b64, scalars = qr.state[0]
                    deltas[name] = {
                        "kind": "keyed",
                        "slots": idx,
                        "b32": np.asarray(b32)[:, idx],
                        "b64": np.asarray(b64)[:, idx],
                        "scalars": [np.asarray(s) for s in scalars],
                        "sel_state": jax.tree.map(
                            lambda x: np.asarray(x), qr.state[1]),
                        "journal": alloc.drain_journal()
                        if alloc is not None else [],
                        "wake": getattr(qr, "next_wakeup", None),
                        "layout": _sharding.query_layout(qr),
                    }
                    dirty[:] = False
                else:
                    alloc2 = getattr(qr.planned, "slot_allocator2", None)
                    jk = getattr(qr.planned, "join_key_allocator", None)
                    deltas[name] = {
                        "kind": "full",
                        "state": jax.tree.map(
                            lambda x: np.asarray(x), qr.state),
                        "slots": alloc.snapshot()
                        if alloc is not None else None,
                        "slots2": alloc2.snapshot()
                        if alloc2 is not None else None,
                        "slots_jk": jk.snapshot()
                        if jk is not None else None,
                        "slots_pairs": [
                            a.snapshot() for a, _ in
                            getattr(qr.planned, "pair_allocs", [])] or None,
                        "wake": getattr(qr, "next_wakeup", None),
                        "layout": _sharding.query_layout(qr),
                    }
            from .table import _table_state
            payload = {
                "deltas": deltas,
                "windows": {
                    wid: jax.tree.map(lambda x: np.asarray(x), nw.state)
                    for wid, nw in self.named_windows.items()},
                # delta: only buckets written since the last baseline
                "aggregations": {aid: a.snapshot_delta()
                                 for aid, a in self.aggregations.items()},
                "agg_delta": True,
                "tables": {tid: _table_state(t)
                           for tid, t in self.tables.items()},
                "interner": list(self.interner._to_str),
            }
            _stateobs.collect(self)
            payload["sizing"] = self.stats.stateobs.ledger()
            return pickle.dumps(payload)

    def restore_increment(self, blob: bytes) -> None:
        payload = pickle.loads(blob)
        with self._quiesce():
            for s in payload["interner"]:
                self.interner.intern(s)
            for name, d in payload["deltas"].items():
                qr = self.query_runtimes.get(name)
                if qr is None:
                    continue
                alloc = _allocator_of(qr)
                if d["kind"] == "keyed":
                    (b32, b64, scalars), _ = qr.state
                    # incremental deltas index by state ROW: remap rows
                    # (and the full selector tree riding along) when the
                    # snapshot was cut under a different mesh size
                    old_l = d.get("layout")
                    new_l = _sharding.query_layout(qr)
                    d_slots = np.asarray(d["slots"])
                    sel_host = d["sel_state"]
                    if _sharding.needs_rebucket(old_l, new_l):
                        d_slots = _sharding.rebucket_rows(
                            d_slots, old_l, new_l)
                        sel_host = _sharding.rebucket_selector(
                            sel_host, old_l, new_l, qr.planned)
                    sharded = len(getattr(
                        b32, "sharding", None).device_set) > 1 \
                        if getattr(b32, "sharding", None) is not None else \
                        False
                    if sharded:
                        # host-context scatters into sharded slabs drop
                        # remote-shard columns (core/shardsafe.py): go
                        # through a dense masked where instead
                        from .shardsafe import key_mask, masked_fill
                        slots = d_slots
                        K = b32.shape[1]
                        mask = key_mask(slots, K)
                        up32 = np.zeros(b32.shape, np.asarray(
                            d["b32"]).dtype)
                        up32[:, slots] = d["b32"]
                        up64 = np.zeros(b64.shape, np.asarray(
                            d["b64"]).dtype)
                        up64[:, slots] = d["b64"]
                        b32 = masked_fill(b32, mask,
                                          jax.numpy.asarray(up32),
                                          key_axis=1)
                        b64 = masked_fill(b64, mask,
                                          jax.numpy.asarray(up64),
                                          key_axis=1)
                    else:
                        idx = jax.numpy.asarray(d_slots)
                        b32 = b32.at[:, idx].set(
                            jax.numpy.asarray(d["b32"]))
                        b64 = b64.at[:, idx].set(
                            jax.numpy.asarray(d["b64"]))
                    scalars = tuple(jax.numpy.asarray(s)
                                    for s in d["scalars"])
                    sel_state = jax.tree.map(lambda x: jax.numpy.asarray(x),
                                             sel_host)
                    qr.state = ((b32, b64, scalars), sel_state)
                    if alloc is not None:
                        alloc.apply_journal(d["journal"])
                else:
                    host_state = _rebucket_for(qr, d.get("layout"),
                                               d["state"])
                    restored = jax.tree.map(
                        lambda x: jax.numpy.asarray(x), host_state)
                    qr.state = qr.place_state(restored) \
                        if hasattr(qr, "place_state") else restored
                    if d["slots"] is not None and alloc is not None:
                        alloc.restore(d["slots"])
                    alloc2 = getattr(qr.planned, "slot_allocator2", None)
                    if d.get("slots2") is not None and alloc2 is not None:
                        alloc2.restore(d["slots2"])
                    jk = getattr(qr.planned, "join_key_allocator", None)
                    if d.get("slots_jk") is not None and jk is not None:
                        jk.restore(d["slots_jk"])
                    pairs = d.get("slots_pairs")
                    if pairs:
                        for (a, _), snap in zip(
                                getattr(qr.planned, "pair_allocs", []),
                                pairs):
                            a.restore(snap)
                    if hasattr(qr, "_after_restore"):
                        qr._after_restore(host_state)
                w = d.get("wake")
                if w is not None and hasattr(qr, "_apply_wake"):
                    qr._apply_wake(int(w))
            self._restore_shared(payload)

    def restore(self, blob: bytes) -> None:
        payload = pickle.loads(blob)
        with self._quiesce():
            for s in payload["interner"]:
                self.interner.intern(s)
            for name, data in payload["states"].items():
                qr = self.query_runtimes.get(name)
                if qr is None:
                    continue
                host_state = _rebucket_for(qr, data.get("layout"),
                                           data["state"])
                restored = jax.tree.map(
                    lambda x: jax.numpy.asarray(x), host_state)
                qr.state = qr.place_state(restored) \
                    if hasattr(qr, "place_state") else restored
                alloc = _allocator_of(qr)
                if data["slots"] is not None and alloc is not None:
                    alloc.restore(data["slots"])
                alloc2 = getattr(qr.planned, "slot_allocator2", None)
                if data.get("slots2") is not None and alloc2 is not None:
                    alloc2.restore(data["slots2"])
                jk = getattr(qr.planned, "join_key_allocator", None)
                if data.get("slots_jk") is not None and jk is not None:
                    jk.restore(data["slots_jk"])
                pairs = data.get("slots_pairs")
                if pairs:
                    for (a, _), snap in zip(
                            getattr(qr.planned, "pair_allocs", []), pairs):
                        a.restore(snap)
                if hasattr(qr, "_after_restore"):
                    qr._after_restore(host_state)
                # re-arm pending timers (absent deadlines, window expiry):
                # the scheduler of this fresh runtime knows nothing of the
                # wakeups the snapshotted state still expects
                w = data.get("wake")
                if w is not None and hasattr(qr, "_apply_wake"):
                    qr._apply_wake(int(w))
            self._restore_shared(payload)

    def _restore_shared(self, payload) -> None:
        from .table import _restore_table_state
        for wid, wstate in payload.get("windows", {}).items():
            nw = self.named_windows.get(wid)
            if nw is not None:
                nw.state = jax.tree.map(
                    lambda x: jax.numpy.asarray(x), wstate)
        agg_delta = payload.get("agg_delta", False)
        for aid, stores in payload.get("aggregations", {}).items():
            agg = self.aggregations.get(aid)
            if agg is None:
                continue
            if agg_delta:
                agg.apply_delta(stores)
            else:
                agg.stores = {d: dict(s) for d, s in stores.items()}
        for tid, tdata in payload.get("tables", {}).items():
            t = self.tables.get(tid)
            if t is not None:
                _restore_table_state(t, tdata)
        # sizing-hints ledger: max-merge the snapshotted high-water
        # marks so the restored app reports them from tick zero
        sizing = payload.get("sizing")
        if sizing:
            self.stats.stateobs.adopt_ledger(sizing)


class SiddhiManager:
    """reference: CORE/SiddhiManager.java:49"""

    def __init__(self):
        from ..utils.config import ConfigManager
        from ..utils.persistence import InMemoryPersistenceStore
        self.interner = ev.StringInterner()
        from ..utils.persistence import AsyncSnapshotPersistor
        self.runtimes: Dict[str, SiddhiAppRuntime] = {}
        self.persistence_store = InMemoryPersistenceStore()
        self.config_manager = ConfigManager()
        self._persistor = AsyncSnapshotPersistor()
        self._has_base: set = set()
        # time-series sampler (observability/timeseries.py): started on
        # demand (REST service auto-starts one; bench --mode soak too)
        self._sampler = None

    def set_persistence_store(self, store) -> None:
        """reference: SiddhiManager.setPersistenceStore (full or
        incremental store)."""
        self.persistence_store = store
        self._has_base.clear()

    def set_config_manager(self, config_manager) -> None:
        """reference: SiddhiManager.setConfigManager — supplies system-wide
        properties and per-extension ConfigReaders (utils/config.py)."""
        self.config_manager = config_manager

    def set_extension(self, name: str, impl) -> None:
        """reference: SiddhiManager.setExtension :213 — register a custom
        extension by `namespace:name`.  The implementation kind is
        inferred: WindowProcessor subclasses register as windows, Source/
        Sink subclasses as transports, callables as scalar functions
        (returning a CompiledExpr from a list of compiled args)."""
        from ..io.mappers import SinkMapper, SourceMapper
        from ..io.sink import DistributionStrategy, Sink, register_sink_type
        from ..io.source import Source, register_source_type
        from .extension import (AttributeAggregator,
                                IncrementalAttributeAggregator,
                                attribute_aggregator, distribution_strategy,
                                incremental_attribute_aggregator,
                                scalar_function, sink_mapper, source_mapper,
                                window_extension)
        from .window import WindowProcessor
        if isinstance(impl, type) and issubclass(impl, WindowProcessor):
            window_extension(name, replace=True)(impl)
        elif isinstance(impl, type) and issubclass(impl, AttributeAggregator):
            attribute_aggregator(name, replace=True)(impl)
        elif isinstance(impl, type) and issubclass(
                impl, IncrementalAttributeAggregator):
            incremental_attribute_aggregator(name, replace=True)(impl)
        elif isinstance(impl, type) and issubclass(impl, DistributionStrategy):
            distribution_strategy(name, replace=True)(impl)
        elif isinstance(impl, type) and issubclass(impl, SourceMapper):
            source_mapper(name, replace=True)(impl)
        elif isinstance(impl, type) and issubclass(impl, SinkMapper):
            sink_mapper(name, replace=True)(impl)
        elif isinstance(impl, type) and issubclass(impl, Source):
            register_source_type(name, impl)
        elif isinstance(impl, type) and issubclass(impl, Sink):
            register_sink_type(name, impl)
        elif callable(impl):
            scalar_function(name, replace=True)(impl)
        else:
            raise TypeError(
                f"cannot infer extension kind for {type(impl).__name__}; "
                f"use the @scalar_function/@window_extension/"
                f"@attribute_aggregator/@source_mapper/@sink_mapper "
                f"decorators or register_source_type/register_sink_type "
                f"directly")

    def create_sandbox_siddhi_app_runtime(
            self, app: Union[str, SiddhiApp],
            mesh=None) -> "SiddhiAppRuntime":
        """reference: SiddhiManager.createSandboxSiddhiAppRuntime — deploy
        an app with its EXTERNAL dependencies stripped for testing: only
        inMemory sources/sinks survive, @store tables become plain
        in-memory tables (SandboxTestCase expectations)."""
        from ..compiler import SiddhiCompiler
        if isinstance(app, str):
            app = SiddhiCompiler.parse(app)
        else:
            # never mutate the caller's app object: the same SiddhiApp may
            # be deployed for real afterwards with its transports intact
            import copy
            app = copy.deepcopy(app)

        def keep(ann) -> bool:
            if ann.name.lower() not in ("source", "sink"):
                return True
            t = ann.element("type") or ann.element(None)
            return str(t).lower() == "inmemory"

        for sdef in app.stream_definition_map.values():
            sdef.annotations = [a for a in sdef.annotations if keep(a)]
        for tdef in app.table_definition_map.values():
            tdef.annotations = [a for a in tdef.annotations
                                if a.name.lower() != "store"]
        # aggregations may also carry @store (distributed shardId mode) —
        # a sandboxed app must not reach that external DB either
        for adef in app.aggregation_definition_map.values():
            adef.annotations = [a for a in adef.annotations
                                if a.name.lower() != "store"]
        return self.create_siddhi_app_runtime(app, mesh=mesh)

    setPersistenceStore = set_persistence_store
    setConfigManager = set_config_manager
    setExtension = set_extension
    createSandboxSiddhiAppRuntime = create_sandbox_siddhi_app_runtime

    def create_siddhi_app_runtime(
            self, app: Union[str, SiddhiApp],
            mesh=None) -> SiddhiAppRuntime:
        if isinstance(app, str):
            from ..compiler import SiddhiCompiler
            app = SiddhiCompiler.parse(app)
        # deploy-time admission gate: the static state estimate is
        # checked against the configured memory ceilings BEFORE the
        # runtime is constructed — a denial provably precedes any
        # planning, tracing, or device allocation (core/admission.py)
        from .admission import check_deploy
        check_deploy(app, self, mesh=mesh)
        runtime = SiddhiAppRuntime(app, self, mesh=mesh)
        self.runtimes[runtime.name] = runtime
        return runtime

    # camelCase alias mirroring the reference API surface
    createSiddhiAppRuntime = create_siddhi_app_runtime

    def persist(self) -> List[str]:
        """Snapshot every app into the persistence store (reference:
        SiddhiManager.persist :281; sources pause around the snapshot as in
        SiddhiAppRuntimeImpl.persist :677-691).

        With an IncrementalPersistenceStore, the first persist writes a full
        BASE snapshot and subsequent calls write dirty-key INCREMENTS.  The
        store write happens on the async persistor thread (reference:
        AsyncSnapshotPersistor); call wait_for_persistence() to block on it.
        Returns the revision ids."""
        from ..utils.persistence import (
            IncrementalPersistenceStore,
            new_revision,
        )
        store = self.persistence_store
        incremental = isinstance(store, IncrementalPersistenceStore)
        # a failed async write leaves a hole in the increment chain; demote
        # the affected app to a fresh BASE snapshot instead of stacking
        # increments on the hole
        for tag in self._persistor.take_failed_tags():
            import logging
            logging.getLogger("siddhi_tpu").warning(
                "previous persist of %s failed; writing a full base "
                "snapshot", tag)
            self._has_base.discard(tag)
        revs = []
        for name, rt in self.runtimes.items():
            rt.pause_sources()
            try:
                rev = new_revision(name)
                if incremental:
                    if name not in self._has_base:
                        blob = rt.snapshot()
                        self._persistor.submit(store.save_base, name, rev,
                                               blob, tag=name)
                        self._has_base.add(name)
                    else:
                        blob = rt.snapshot_incremental()
                        self._persistor.submit(store.save_increment, name,
                                               rev, blob, tag=name)
                else:
                    self._persistor.submit(store.save, name, rev,
                                           rt.snapshot(), tag=name)
                revs.append(rev)
            finally:
                rt.resume_sources()
        return revs

    def wait_for_persistence(self) -> None:
        self._persistor.flush()

    def restore_revision(self, revision: str) -> None:
        """Restore every app from a specific full-snapshot revision
        (reference: SiddhiAppRuntimeImpl.restoreRevision)."""
        self.wait_for_persistence()
        store = self.persistence_store
        if not hasattr(store, "load"):
            raise CannotRestoreStateError(
                "revision restore requires a full-snapshot PersistenceStore")
        for name, rt in self.runtimes.items():
            blob = store.load(name, revision)
            if blob is None:
                raise CannotRestoreStateError(
                    f"revision {revision!r} not found for app {name!r}")
            rt.restore(blob)

    def restore_last_revision(self) -> None:
        """Restore every app from its newest INTACT revision.  A corrupt
        or unreadable revision (torn write, CRC mismatch, truncation —
        see utils/persistence.seal/unseal) is skipped with a warning and
        the previous revision is tried, bumping the app's
        `restore_fallbacks` counter (siddhi_restore_fallbacks_total);
        CannotRestoreStateError is raised only when revisions exist but
        NONE of them restores."""
        import logging
        from ..utils.persistence import IncrementalPersistenceStore
        _log = logging.getLogger("siddhi_tpu")
        self.wait_for_persistence()
        store = self.persistence_store
        for name, rt in self.runtimes.items():
            if isinstance(store, IncrementalPersistenceStore):
                try:
                    chain = store.load_chain(name)
                except Exception as exc:  # noqa: BLE001 — corrupt base
                    rt.restore_fallbacks += 1
                    _log.error(
                        "incremental chain for %s unrestorable (%r); "
                        "state NOT restored", name, exc)
                    continue
                if chain is None:
                    continue
                base, incs = chain
                rt.restore(base)
                for inc in incs:
                    rt.restore_increment(inc)
                continue
            revs = store.get_revisions(name)
            if not revs:
                continue
            restored = False
            for rev in reversed(revs):
                try:
                    blob = store.load(name, rev)
                    if blob is None:
                        continue
                    rt.restore(blob)
                    restored = True
                    break
                except Exception as exc:  # noqa: BLE001 — fall back
                    rt.restore_fallbacks += 1
                    _log.warning(
                        "revision %r of %s unrestorable (%r); falling "
                        "back to the previous revision", rev, name, exc)
            if not restored:
                raise CannotRestoreStateError(
                    f"no intact revision among {len(revs)} stored for "
                    f"app {name!r}")

    def start_sampler(self, interval_s=None, window=None, rules=None,
                      clock=None):
        """Start (or return) the manager's in-process time-series sampler:
        a daemon thread snapshotting every app's host-side metrics into
        ring-buffer series each tick and evaluating the SLO rules over
        them (observability/timeseries.py, observability/slo.py).
        Interval/window default from config properties
        `metrics.sampler.interval.seconds` / `metrics.sampler.window`.
        Idempotent; pass `clock`+drive `tick()` yourself in tests."""
        if self._sampler is None:
            from ..observability.timeseries import TimeSeriesSampler
            self._sampler = TimeSeriesSampler(
                self, interval_s=interval_s, window=window, rules=rules,
                clock=clock)
            if clock is None:      # test-driven samplers tick manually
                self._sampler.start()
        return self._sampler

    def stop_sampler(self) -> None:
        s, self._sampler = self._sampler, None
        if s is not None:
            s.stop()

    def shutdown(self) -> None:
        self.stop_sampler()
        for rt in self.runtimes.values():
            rt.shutdown()
