"""Admission control & graceful degradation: decide overload, don't
discover it.

Reference (what): the reference engine degrades *deliberately* under
overload — the `@async` ingress is a bounded Disruptor ring that
backpressures producers (StreamJunction.java:276-313), and
`OnErrorAction` policies choose what happens to events the engine
cannot process (PAPER.md L4/L6).  It never OOMs from one bad tenant:
capacity is decided at the edges.

TPU design (how): a multi-tenant TPU server has three scarce resources
a single tenant can exhaust for everyone — **HBM** (state slabs are
dense device arrays sized at plan time), the **XLA compile path** (one
recompile stalls its thread for seconds on CPU and minutes through the
remote tunnel), and **host dispatch** (the drainer and query locks).
This module gates all three:

1. **Deploy-time memory gate** (`check_deploy`): before anything is
   planned or traced, the app's static state estimate — the SAME
   shape×dtype estimator lint MEM001 uses
   (`core/plan_facts.static_state_components`) — is checked against
   `admission.max.state.bytes` (per app) and
   `admission.global.max.state.bytes` (the box).  Denial is a typed
   `AdmissionDeniedError` listing the offending components; nothing
   was compiled, nothing leaks.

2. **Runtime quotas** (`AdmissionController`, one per app):
   - a token-bucket ingest rate (`admission.max.events.per.sec`)
     enforced at the external edges (InputHandler sends + @source
     delivery — internal routing is never throttled);
   - the state ceiling re-checked on every adaptive emission-cap
     growth (`_grow_emission_cap`): growth past the ceiling is DENIED
     and the app flips to a `shedding` quota state — overflow rows
     drop loudly (counted) instead of OOMing the chip;
   - a recompile-rate budget (`admission.max.recompiles.per.min`)
     enforced by the shared `CompileGate`: every non-diagnostic XLA
     trace passes through one process-wide admission lock, and an
     owner over its budget is penalized (`admission.compile.penalty.ms`
     sleep) BEFORE it may take the lock — a storming tenant's compiles
     queue behind everyone else's dispatch instead of in front of it.

3. **Mitigation ladder** (`admission.overload`):
   - `'block'`   — caller backpressure: the send waits for bucket
     refill up to `admission.block.timeout.ms`, then raises
     `AdmissionDeniedError` (the resilience `wait` contract:
     deadline-bounded blocking with a typed timeout);
   - `'shed'`    — the send is dropped at the edge, counted per
     stream (`siddhi_admission_shed_total{app,stream}`), never routed;
   - `'degrade'` — sheds like `'shed'`, but the effective rate HALVES
     each sampler tick the app's SLO verdict is FIRING and recovers
     one halving per `admission.degrade.recovery.ticks` consecutive
     ok ticks (hysteresis) — the ladder the SLO engine climbs down.

Every decision is observable: controller counters feed
`siddhi_admission_{shed_total,blocked_ms,denied_deploys,
compile_queue_depth,quota_state}` in /metrics, an `admission` section
in /healthz and EXPLAIN, sampler series, and
`GET/PUT /siddhi-apps/<app>/admission`.

Invariant shared with the whole scrape path: admission decisions read
host counters, config, and shape/dtype metadata ONLY — never a device
fetch, never a trace (tests/test_admission.py guards this by
monkeypatching `jax.jit` and `jax.device_get` over every decision
path).  Clock and sleep are injectable so the quota ladder is tested
on a virtual timeline with zero real sleeps.
"""
from __future__ import annotations

import contextlib
import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..exceptions import AdmissionDeniedError
from .plan_facts import format_component_bytes, static_state_components

log = logging.getLogger("siddhi_tpu")

OVERLOAD_POLICIES = ("block", "shed", "degrade")

# quota_state gauge encoding (siddhi_admission_quota_state)
QUOTA_OK, QUOTA_DEGRADED, QUOTA_SHEDDING = "ok", "degraded", "shedding"
QUOTA_GAUGE = {QUOTA_OK: 0, QUOTA_DEGRADED: 1, QUOTA_SHEDDING: 2}

_DEFAULT_BLOCK_TIMEOUT_MS = 1000.0
_DEFAULT_COMPILE_PENALTY_MS = 100.0
_DEFAULT_RECOVERY_TICKS = 5
_MAX_DEGRADE_LEVEL = 6          # rate floor: configured / 64
_COMPILE_WINDOW_S = 60.0        # the "per.min" of the recompile budget


def _mib(n: float) -> str:
    return f"{n / (1024 * 1024):.1f} MiB"


class TokenBucket:
    """Classic token bucket: `rate` tokens/s refill up to `burst`.
    All-or-nothing takes (a batch is admitted whole or not at all) so
    accounting reconciles exactly: offered == accepted + shed."""

    def __init__(self, rate: float, burst: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self.rate = max(1e-9, float(rate))
        self.burst = float(burst) if burst else max(self.rate, 1.0)
        self.tokens = self.burst
        self._last = clock()

    def _refill(self, now: float) -> None:
        dt = now - self._last
        if dt > 0:
            self.tokens = min(self.burst, self.tokens + dt * self.rate)
            self._last = now

    def try_take(self, n: int) -> bool:
        with self._lock:
            self._refill(self._clock())
            if self.tokens >= n:
                self.tokens -= n
                return True
            return False

    def need_s(self, n: int) -> float:
        """Seconds until `n` tokens could be available (0 when they
        already are; capped at the time to fill from empty)."""
        with self._lock:
            self._refill(self._clock())
            missing = min(float(n), self.burst) - self.tokens
            return max(0.0, missing / self.rate)

    def set_rate(self, rate: float) -> None:
        with self._lock:
            self._refill(self._clock())
            self.rate = max(1e-9, float(rate))


class CompileGate:
    """Process-wide XLA compile admission: every non-diagnostic trace
    (steputil.jit_step) enters through `admit(owner)`.

    Two mechanisms compose:
    - **serialization**: one RLock means at most one tenant traces at a
      time — tenant N+1's compile storm queues instead of interleaving
      with (and GIL-starving) tenant 1's dispatch.  Re-entrant, so a
      fused step tracing its inner bodies on the same thread cannot
      deadlock.
    - **deprioritization**: an owner whose app is over its
      `admission.max.recompiles.per.min` budget sleeps its app's
      compile penalty BEFORE contending for the lock, so a within-
      budget tenant already waiting wins the next slot.

    Owners register via their app's AdmissionController (labels are the
    recompile-accounting owners: query names, `fused:<q>`, `table:<t>`,
    …).  Colliding labels across apps resolve to the most recently
    registered app — acceptable blame blur, never a correctness issue.
    Clock/sleep injectable; `waiting` is the
    siddhi_admission_compile_queue_depth gauge."""

    # escalation cap: a persistently-storming owner's penalty grows one
    # quantum per over-budget compile but never past this bound (the
    # app's `admission.compile.penalty.max.ms` raises/lowers it — a cap
    # shorter than the owner's per-compile busy time can never converge
    # a storm's compile rate down to its budget, it only lags it)
    MAX_PENALTY_S = 5.0

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self._lock = threading.RLock()
        self._meta = threading.Lock()
        self._owners: Dict[str, "AdmissionController"] = {}
        # per-owner-LABEL compile history: the budget must survive
        # deploy/undeploy churn (a tenant hot-redeploying its app gets
        # a fresh AdmissionController each cycle — if the history lived
        # there, churn would reset the budget and the storm would never
        # be penalized)
        self._label_times: Dict[str, deque] = {}
        self._clock = clock
        self._sleep = sleep
        self.waiting = 0
        self.penalized_total = 0

    def register(self, owner: str, ctrl: "AdmissionController") -> None:
        with self._meta:
            self._owners[owner] = ctrl

    def unregister_app(self, ctrl: "AdmissionController") -> None:
        with self._meta:
            for k in [k for k, v in self._owners.items() if v is ctrl]:
                del self._owners[k]

    def controller_of(self, owner: str) -> Optional["AdmissionController"]:
        with self._meta:
            return self._owners.get(owner)

    def _penalty_for(self, owner: str,
                     ctrl: Optional["AdmissionController"]) -> float:
        """Escalating pre-lock penalty for an over-budget owner: one
        `compile.penalty.ms` quantum per compile past the budget in the
        trailing minute, capped at MAX_PENALTY_S — the owner's compile
        rate converges toward its budget instead of merely lagging it."""
        if ctrl is None:
            return 0.0
        budget = ctrl.max_recompiles_per_min
        if budget is None:
            return 0.0
        now = self._clock()
        with self._meta:
            dq = self._label_times.get(owner)
            if dq is None:
                return 0.0
            while dq and now - dq[0] > _COMPILE_WINDOW_S:
                dq.popleft()
            recent = len(dq)
        if recent < budget:
            return 0.0
        over = recent - budget + 1
        cap = getattr(ctrl, "compile_penalty_max_ms",
                      self.MAX_PENALTY_S * 1e3) / 1e3
        return min(cap, ctrl.compile_penalty_ms / 1e3 * over)

    def _note_label_compile(self, owner: str) -> None:
        with self._meta:
            dq = self._label_times.get(owner)
            if dq is None:
                dq = self._label_times[owner] = deque(maxlen=4096)
            dq.append(self._clock())

    @contextlib.contextmanager
    def admit(self, owner: str):
        ctrl = self.controller_of(owner)
        penalty = self._penalty_for(owner, ctrl)
        with self._meta:
            self.waiting += 1
            if penalty > 0:
                self.penalized_total += 1
        acquired = False
        try:
            if penalty > 0:
                # over-budget owners pay the penalty OUTSIDE the lock:
                # within-budget tenants overtake them at the gate
                self._sleep(penalty)
                if ctrl is not None:
                    ctrl.note_compile_penalty(penalty)
            self._lock.acquire()
            acquired = True
            with self._meta:
                self.waiting -= 1
            yield
        finally:
            if acquired:
                self._note_label_compile(owner)
                if ctrl is not None:
                    ctrl.note_compile(owner)
                self._lock.release()
            else:
                # the penalty sleep (or the caller) raised before the
                # lock body balanced `waiting`
                with self._meta:
                    self.waiting -= 1


# the one gate steputil.jit_step routes every trace through
COMPILE_GATE = CompileGate()

# process-wide deploy denials (deploys denied before a runtime exists
# have no app to hang a counter on)
_denied_lock = threading.Lock()
_denied_deploys = 0


def denied_deploys() -> int:
    return _denied_deploys


def _count_denied() -> None:
    global _denied_deploys
    with _denied_lock:
        _denied_deploys += 1


def _flat_components(app, mesh_devices: int = 0,
                     merged: bool = True) -> Dict[str, int]:
    """{'query/component': bytes} — the deploy gate's breakdown keys.
    Merge-aware (core/plan_facts): a window buffer the multi-query
    optimizer will share across a group is charged ONCE, under its
    `merged:<group>` owner, exactly as the live accounting reports it."""
    out: Dict[str, int] = {}
    for qname, comps in static_state_components(
            app, mesh_devices=mesh_devices, merged=merged).items():
        for comp, nb in comps.items():
            out[f"{qname}/{comp}"] = nb
    return out


def _ann_element(app, key: str) -> Optional[str]:
    ann = app.get_annotation("app:admission")
    if ann is None:
        return None
    v = ann.element(key)
    return None if v is None else str(v)


def _prop(manager, key: str) -> Optional[str]:
    try:
        cm = getattr(manager, "config_manager", None)
        v = cm.extract_property(key) if cm is not None else None
        return None if v is None else str(v)
    except Exception:  # noqa: BLE001 — config must not break admission
        return None


def _resolve(app, manager, ann_key: str, prop_key: str) -> Optional[str]:
    """@app:admission(<ann_key>=…) wins over the manager property."""
    v = _ann_element(app, ann_key)
    return v if v is not None else _prop(manager, prop_key)


def _opt_float(v: Optional[str]) -> Optional[float]:
    if v is None or str(v).strip() == "":
        return None
    f = float(v)
    return f if f > 0 else None


def resident_state_bytes(manager, exclude=None) -> int:
    """Measured device-state bytes across every deployed app (metadata
    walk only — observability/memory)."""
    from ..observability.memory import total_bytes
    total = 0
    for rt in list(getattr(manager, "runtimes", {}).values()):
        if rt is exclude:
            continue
        try:
            total += int(total_bytes(rt))
        except Exception:  # noqa: BLE001 — one sick app must not block
            pass
    return total


def check_deploy(app, manager, mesh=None) -> None:
    """Deploy-time memory gate: runs BEFORE SiddhiAppRuntime is
    constructed, so a denial provably precedes any planning, tracing,
    or device allocation.  Raises AdmissionDeniedError listing the
    offending components (the MEM001 breakdown) when the app's static
    state estimate exceeds `admission.max.state.bytes`, or would push
    the box past `admission.global.max.state.bytes` on top of the
    measured resident state of the already-deployed apps.  `mesh` is
    the deploy target (merge-aware sharing is off on a multi-device
    mesh, matching the optimizer pass)."""
    per_app = _opt_float(_resolve(app, manager, "max.state.bytes",
                                  "admission.max.state.bytes"))
    global_ceiling = _opt_float(
        _prop(manager, "admission.global.max.state.bytes"))
    if per_app is None and global_ceiling is None:
        return
    mesh_n = int(mesh.devices.size) if mesh is not None else 0
    merge_prop = _prop(manager, "optimizer.merge.enabled")
    merged = merge_prop is None or \
        str(merge_prop).strip().lower() not in ("false", "0", "off", "no")
    comps = _flat_components(app, mesh_devices=mesh_n, merged=merged)
    estimate = sum(comps.values())
    name = app.name or "SiddhiApp"
    if per_app is not None and estimate > per_app:
        _count_denied()
        raise AdmissionDeniedError(
            f"deploy of {name!r} denied: static state estimate "
            f"{_mib(estimate)} exceeds admission.max.state.bytes "
            f"{_mib(per_app)} ({format_component_bytes(comps)})",
            components=comps)
    if global_ceiling is not None:
        resident = resident_state_bytes(manager)
        if resident + estimate > global_ceiling:
            _count_denied()
            raise AdmissionDeniedError(
                f"deploy of {name!r} denied: static state estimate "
                f"{_mib(estimate)} on top of {_mib(resident)} already "
                f"resident exceeds admission.global.max.state.bytes "
                f"{_mib(global_ceiling)} "
                f"({format_component_bytes(comps)})",
                components=comps)


class AdmissionController:
    """Per-app runtime quota enforcement + the overload ladder.  Created
    unconditionally on every SiddhiAppRuntime (cheap, host-only); does
    nothing on the ingest path until a rate is configured."""

    def __init__(self, rt, clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.rt = rt
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()

        app, manager = rt.app, rt.manager

        def res(ann_key, prop_key):
            return _resolve(app, manager, ann_key, prop_key)

        policy = (res("overload", "admission.overload") or "block").lower()
        if policy not in OVERLOAD_POLICIES:
            raise AdmissionDeniedError(
                f"unknown admission.overload policy {policy!r}; one of "
                f"{OVERLOAD_POLICIES}")
        self.policy = policy
        # whether the operator SAID anything (lint ADM001 wants to know
        # explicit-vs-defaulted, not the resolved value)
        self.policy_explicit = res("overload",
                                   "admission.overload") is not None
        self.base_rate = _opt_float(res("max.events.per.sec",
                                        "admission.max.events.per.sec"))
        self.burst = _opt_float(res("burst", "admission.burst"))
        self.max_state_bytes = _opt_float(
            res("max.state.bytes", "admission.max.state.bytes"))
        self.global_max_state_bytes = _opt_float(
            _prop(manager, "admission.global.max.state.bytes"))
        self.block_timeout_ms = float(
            res("block.timeout.ms", "admission.block.timeout.ms")
            or _DEFAULT_BLOCK_TIMEOUT_MS)
        self.max_recompiles_per_min = _opt_float(
            res("max.recompiles.per.min",
                "admission.max.recompiles.per.min"))
        self.compile_penalty_ms = float(
            res("compile.penalty.ms", "admission.compile.penalty.ms")
            or _DEFAULT_COMPILE_PENALTY_MS)
        self.compile_penalty_max_ms = float(
            res("compile.penalty.max.ms",
                "admission.compile.penalty.max.ms")
            or CompileGate.MAX_PENALTY_S * 1e3)
        self.recovery_ticks = int(
            res("degrade.recovery.ticks",
                "admission.degrade.recovery.ticks")
            or _DEFAULT_RECOVERY_TICKS)

        self.bucket: Optional[TokenBucket] = None
        if self.base_rate is not None:
            self.bucket = TokenBucket(self.base_rate, self.burst,
                                      clock=clock)

        # counters (plain ints read lock-free by the scrape path)
        self.shed_total = 0
        self.shed_by_stream: Dict[str, int] = {}
        self.blocked_ms_total = 0
        self.blocked_sends = 0
        self.block_timeouts = 0
        self.growth_denials = 0
        self.compiles_total = 0
        self.compile_penalties = 0
        self.compile_penalty_ms_total = 0
        self._compile_times: deque = deque(maxlen=4096)

        # ladder state
        self.degrade_level = 0
        self._ok_ticks = 0
        self.ceiling_hit = False
        self._warned_shed = 0.0

    # -- ingest edge -----------------------------------------------------------
    @property
    def ingest_enabled(self) -> bool:
        return self.bucket is not None

    def effective_rate(self) -> Optional[float]:
        if self.base_rate is None:
            return None
        return self.base_rate / (1 << self.degrade_level)

    @property
    def quota_state(self) -> str:
        if self.ceiling_hit:
            return QUOTA_SHEDDING
        if self.degrade_level > 0:
            return QUOTA_DEGRADED
        return QUOTA_OK

    def admit_ingest(self, stream_id: str, n: int) -> bool:
        """Decide one external send of `n` events.  True = route it.
        False = SHED (already counted; the caller just drops).  `block`
        policy never returns False — it waits for bucket refill up to
        the deadline, then raises AdmissionDeniedError."""
        bucket = self.bucket
        if bucket is None or n <= 0:
            return True
        if bucket.try_take(n):
            return True
        if self.policy == "block":
            return self._block(stream_id, n, bucket)
        self._note_shed(stream_id, n)
        return False

    def _block(self, stream_id: str, n: int, bucket: TokenBucket) -> bool:
        deadline = self._clock() + self.block_timeout_ms / 1e3
        t0 = self._clock()
        while True:
            need = bucket.need_s(n)
            now = self._clock()
            if now + need > deadline:
                waited_ms = int((now - t0) * 1e3)
                with self._lock:
                    self.blocked_ms_total += waited_ms
                    self.block_timeouts += 1
                raise AdmissionDeniedError(
                    f"send of {n} events to {stream_id!r} blocked "
                    f"{self.block_timeout_ms:.0f}ms at the admission "
                    f"rate limit ({bucket.rate:.0f} ev/s) without "
                    "tokens (admission.overload='block' deadline)")
            self._sleep(max(need, 1e-4))
            if bucket.try_take(n):
                waited_ms = int((self._clock() - t0) * 1e3)
                with self._lock:
                    self.blocked_ms_total += waited_ms
                    self.blocked_sends += 1
                return True

    def _note_shed(self, stream_id: str, n: int) -> None:
        with self._lock:
            self.shed_total += n
            self.shed_by_stream[stream_id] = \
                self.shed_by_stream.get(stream_id, 0) + n
        now = self._clock()
        if now - self._warned_shed >= 10.0:   # loud but rate-limited
            self._warned_shed = now
            log.warning(
                "%s: admission shed %d events on %r (policy=%s, "
                "effective rate %.0f ev/s, %d shed total)",
                self.rt.name, n, stream_id, self.policy,
                self.effective_rate() or 0.0, self.shed_total)

    # -- state ceiling (growth admission) --------------------------------------
    def admit_growth(self, owner: str, delta_bytes: int) -> bool:
        """Re-check the state ceilings before an adaptive emission-cap
        (or other state) growth of `delta_bytes`.  Denial flips the app
        into the `shedding` quota state: the overflow that wanted the
        growth keeps dropping loudly (counted by the existing overflow
        path) instead of allocating past the ceiling."""
        lim_app = self.max_state_bytes
        lim_glob = self.global_max_state_bytes
        if lim_app is None and lim_glob is None:
            return True
        from ..observability.memory import total_bytes
        try:
            cur = int(total_bytes(self.rt))
        except Exception:  # noqa: BLE001 — accounting must not block
            cur = 0
        deny_reason = None
        if lim_app is not None and cur + delta_bytes > lim_app:
            deny_reason = (f"app state {_mib(cur)} + growth "
                           f"{_mib(delta_bytes)} exceeds "
                           f"admission.max.state.bytes {_mib(lim_app)}")
        elif lim_glob is not None:
            resident = resident_state_bytes(self.rt.manager,
                                            exclude=self.rt) + cur
            if resident + delta_bytes > lim_glob:
                deny_reason = (
                    f"box state {_mib(resident)} + growth "
                    f"{_mib(delta_bytes)} exceeds "
                    f"admission.global.max.state.bytes {_mib(lim_glob)}")
        if deny_reason is None:
            return True
        with self._lock:
            self.growth_denials += 1
            self.ceiling_hit = True
        log.error(
            "%s: state growth for %r DENIED (%s); app enters degraded "
            "shedding mode — overflow rows drop at the current cap",
            self.rt.name, owner, deny_reason)
        stats = getattr(self.rt, "stats", None)
        if stats is not None and stats.enabled:
            stats.counter_inc(f"{owner}.growth_denied")
        return False

    # -- recompile budget ------------------------------------------------------
    def compile_penalty_s(self) -> float:
        """Penalty the CompileGate applies before this app's next trace
        may contend for the lock: 0 while within budget."""
        budget = self.max_recompiles_per_min
        if budget is None:
            return 0.0
        now = self._clock()
        with self._lock:
            while self._compile_times and \
                    now - self._compile_times[0] > _COMPILE_WINDOW_S:
                self._compile_times.popleft()
            if len(self._compile_times) < budget:
                return 0.0
        return self.compile_penalty_ms / 1e3

    def note_compile(self, owner: str) -> None:
        with self._lock:
            self.compiles_total += 1
            self._compile_times.append(self._clock())

    def note_compile_penalty(self, penalty_s: float) -> None:
        with self._lock:
            self.compile_penalties += 1
            self.compile_penalty_ms_total += int(penalty_s * 1e3)

    def compiles_last_min(self) -> int:
        now = self._clock()
        with self._lock:
            return sum(1 for t in self._compile_times
                       if now - t <= _COMPILE_WINDOW_S)

    # -- SLO ladder ------------------------------------------------------------
    def on_slo(self, slo_state: Optional[Dict], now: float) -> None:
        """One sampler tick of the mitigation ladder: under the
        `degrade` policy the effective rate halves each tick the SLO
        verdict is FIRING and recovers one halving per
        `recovery_ticks` consecutive non-firing ticks."""
        if self.policy != "degrade" or self.bucket is None:
            return
        firing = bool(slo_state) and slo_state.get("verdict") == "firing"
        changed = False
        with self._lock:
            if firing:
                self._ok_ticks = 0
                if self.degrade_level < _MAX_DEGRADE_LEVEL:
                    self.degrade_level += 1
                    changed = True
            elif self.degrade_level > 0:
                self._ok_ticks += 1
                if self._ok_ticks >= self.recovery_ticks:
                    self._ok_ticks = 0
                    self.degrade_level -= 1
                    changed = True
        if changed:
            rate = self.effective_rate()
            self.bucket.set_rate(rate)
            log.warning(
                "%s: admission ladder %s -> effective rate %.0f ev/s "
                "(level %d/%d)", self.rt.name,
                "halved under FIRING SLO" if firing else "recovered",
                rate, self.degrade_level, _MAX_DEGRADE_LEVEL)

    # -- registration ----------------------------------------------------------
    def register_owners(self, owners: List[str]) -> None:
        for o in owners:
            COMPILE_GATE.register(o, self)

    def unregister(self) -> None:
        COMPILE_GATE.unregister_app(self)

    # -- surfaces --------------------------------------------------------------
    def report(self) -> Dict[str, Any]:
        """The `admission` section of /healthz, EXPLAIN, and
        GET /siddhi-apps/<app>/admission — host-side reads only."""
        return {
            "policy": self.policy,
            "quota_state": self.quota_state,
            "max_events_per_sec": self.base_rate,
            "effective_events_per_sec": self.effective_rate(),
            "degrade_level": self.degrade_level,
            "burst": self.bucket.burst if self.bucket else None,
            "tokens": round(self.bucket.tokens, 3)
            if self.bucket else None,
            "max_state_bytes": self.max_state_bytes,
            "global_max_state_bytes": self.global_max_state_bytes,
            "block_timeout_ms": self.block_timeout_ms,
            "max_recompiles_per_min": self.max_recompiles_per_min,
            "compile_penalty_ms": self.compile_penalty_ms,
            "compile_penalty_max_ms": self.compile_penalty_max_ms,
            "shed_total": self.shed_total,
            "shed_by_stream": dict(self.shed_by_stream),
            "blocked_ms_total": self.blocked_ms_total,
            "blocked_sends": self.blocked_sends,
            "block_timeouts": self.block_timeouts,
            "growth_denials": self.growth_denials,
            "compiles_total": self.compiles_total,
            "compiles_last_min": self.compiles_last_min(),
            "compile_penalties": self.compile_penalties,
            "compile_penalty_ms_total": self.compile_penalty_ms_total,
        }

    def configure(self, updates: Dict[str, Any]) -> Dict[str, Any]:
        """Apply a REST PUT: accepts the config-key spellings
        ('overload', 'max.events.per.sec', 'max.state.bytes', 'burst',
        'block.timeout.ms', 'max.recompiles.per.min',
        'compile.penalty.ms').  Returns the post-change report."""
        known = {"overload", "max.events.per.sec", "max.state.bytes",
                 "burst", "block.timeout.ms", "max.recompiles.per.min",
                 "compile.penalty.ms", "compile.penalty.max.ms",
                 "degrade.recovery.ticks"}
        unknown = set(updates) - known
        if unknown:
            raise AdmissionDeniedError(
                f"unknown admission keys {sorted(unknown)}; "
                f"known: {sorted(known)}")
        if "overload" in updates:
            policy = str(updates["overload"]).lower()
            if policy not in OVERLOAD_POLICIES:
                raise AdmissionDeniedError(
                    f"unknown admission.overload policy {policy!r}; "
                    f"one of {OVERLOAD_POLICIES}")
            self.policy = policy
            self.policy_explicit = True
        if "max.events.per.sec" in updates:
            self.base_rate = _opt_float(updates["max.events.per.sec"])
            if self.base_rate is None:
                self.bucket = None
                self.degrade_level = 0
            else:
                self.bucket = TokenBucket(
                    self.effective_rate(), self.burst, clock=self._clock)
        if "burst" in updates:
            self.burst = _opt_float(updates["burst"])
            if self.bucket is not None:
                self.bucket = TokenBucket(
                    self.effective_rate(), self.burst, clock=self._clock)
        if "max.state.bytes" in updates:
            self.max_state_bytes = _opt_float(updates["max.state.bytes"])
            self.ceiling_hit = False       # operator raised it: re-check
        if "block.timeout.ms" in updates:
            self.block_timeout_ms = float(updates["block.timeout.ms"])
        if "max.recompiles.per.min" in updates:
            self.max_recompiles_per_min = _opt_float(
                updates["max.recompiles.per.min"])
        if "compile.penalty.ms" in updates:
            self.compile_penalty_ms = float(updates["compile.penalty.ms"])
        if "compile.penalty.max.ms" in updates:
            self.compile_penalty_max_ms = float(
                updates["compile.penalty.max.ms"])
        if "degrade.recovery.ticks" in updates:
            self.recovery_ticks = int(updates["degrade.recovery.ticks"])
        return self.report()
