"""Block-parallel NFA advance for single-key (non-partitioned) patterns.

Reference behavior (what): StreamPreStateProcessor.java:363-403 — one event
at a time walks every pending state; a non-partitioned `from every e1=A ->
e2=B[...]` query is a single NFA consuming the stream sequentially.

TPU-native design (how): the scan path (pattern.py tick) is semantically
complete but sequential: K=1 batches degrade to E tiny [P,1] ticks per send
(round-4 bench: 776 ev/s on `sequence_within`).  For the COMMON simple-chain
shape — every atom min=max=1, no logical pairs, no absent — the per-key
advance over a block of E events is computable in S-1 *parallel stages*
instead of E sequential ticks:

  threads = P slab states + one candidate per in-block seed event.
  stage s evaluates filter_s over the [T, W] (thread x event) grid in one
  vectorized shot; a PATTERN thread advances at its first matching event
  (cumsum first-true), a SEQUENCE thread must match the next valid event
  after its previous capture (strict continuity, next-valid gather) or die.
  Both resolve with one-hot contractions (oh_take) — no serialized gathers.

Events are processed in W-sized chunks under lax.scan so the [T, W] grid
stays bounded (quadratic in W, linear in E); pending threads at a chunk
boundary re-enter the P-slot slab exactly like tick forks (overflow counts
into `dropped`).  Known benign divergences from the scan path, documented
here because the scan path is the semantic reference:

- WITHIN-chunk pendings are unbounded (a burst of seeds that completes
  inside one chunk never touches the P-slot cap), so the block path drops
  strictly fewer states than per-event slot allocation.  Chunk-boundary
  pressure is identical (P slots).
- After a non-every pattern completes (`done`), tick keeps advancing slab
  bookkeeping for the rest of the batch; the block path freezes at the
  completion index.  Unobservable through emissions (done gates all future
  matching for the key); resolves on @purge.
- A seed filter that reads ANOTHER atom's captures (pathological) sees
  fresh-slot zeros here; tick aliases it to slot row 0's captures.
- Capture TIMESTAMP slabs (caps[ck][0]) go stale in the carried state:
  nothing reads them (emission env and filters bind capture COLUMNS only),
  they exist for layout parity with the scan path's packer.
"""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
from jax import lax

from . import event as ev
from .pattern import BIG, PatternExec, PatternSpec, oh_take
from .selector import SelectorExec
from .window import NO_WAKEUP, Rows

CHUNK = 128


def block_eligible(spec: PatternSpec) -> bool:
    """Simple chains only: single-count atoms, no logical pairs, no absent
    (timer machinery), PATTERN or SEQUENCE.  Everything else keeps the
    fully-general scan path."""
    for a in spec.atoms:
        if a.absent or a.partner is not None or a.is_count:
            return False
        if a.capture_depth != 1:
            return False
    return spec.state_type in ("PATTERN", "SEQUENCE")


def make_block_step(spec: PatternSpec, pexec: PatternExec, sel: SelectorExec,
                    schemas, packer, stream_id: str, compact_rows: int):
    """Build the (packed, sel_state, raw_cols, raw_ts, sel_idx, key_ref,
    now, in_tabs) -> (packed', sel_state', out, wake) step — same signature
    as the scan step so the runtime drives either interchangeably."""
    S = spec.n_states
    atoms = spec.atoms
    P = pexec.P
    schema = schemas[stream_id]
    a0 = atoms[0]
    emit_refs = pexec.emit_refs
    is_seq = spec.state_type == "SEQUENCE"

    def step(packed, sel_state, raw_cols, raw_ts, sel_idx, key_ref, now,
             in_tabs=()):
        def probe_env(env):
            for dep, (tcol0, tvalid) in zip(pexec.in_deps, in_tabs):
                def probe(vals, _tc=tcol0, _tv=tvalid):
                    return jnp.any(jnp.logical_and(
                        vals[..., None] == _tc, _tv), axis=-1)
                env["__in__:" + dep] = probe
            return env

        def bind(env, ref, cols):
            env[ref] = cols
            env[f"{ref}@0"] = cols
            env[f"{ref}@-1"] = cols

        def chunk_advance(carry, xs):
            """One W-event chunk: seeds + S-1 vectorized stages + refill."""
            (active, pos, start_ts, entry_ts, slab_caps, seed_on, done,
             dropped) = carry
            ev_cols, ts, valid, base = xs
            W = ts.shape[0]
            T = P + W
            iota_w = jnp.arange(W, dtype=jnp.int32)

            # ---- seeds -----------------------------------------------------
            if a0.stream_id == stream_id:
                filt0 = pexec._filters[a0.ckey]
                if filt0 is None:
                    c0 = jnp.ones((W,), jnp.bool_)
                else:
                    env0 = probe_env({"__ts__": ts})
                    for a in atoms:
                        bind(env0, a.ref,
                             ev_cols if a.ref == a0.ref else tuple(
                                 jnp.zeros((W,), d)
                                 for d in schemas[a.stream_id].dtypes))
                    c0 = jnp.broadcast_to(filt0.fn(env0), (W,))
                c0 = jnp.logical_and(jnp.logical_and(c0, valid),
                                     jnp.logical_not(done))
                if a0.every:
                    seed_fire = c0
                else:
                    cs0 = jnp.cumsum(c0.astype(jnp.int32))
                    seed_fire = jnp.logical_and(
                        jnp.logical_and(c0, cs0 == 1), seed_on)
                    seed_on = jnp.logical_and(
                        seed_on, jnp.logical_not(jnp.any(c0)))
            else:
                seed_fire = jnp.zeros((W,), jnp.bool_)

            if S == 1:
                # single-atom pattern: every seed completes instantly
                comp_valid = jnp.concatenate(
                    [jnp.zeros((P,), jnp.bool_), seed_fire])
                comp_idx = jnp.concatenate(
                    [jnp.zeros((P,), jnp.int64),
                     base + iota_w.astype(jnp.int64)])
                comp_ts = jnp.concatenate([jnp.zeros((P,), jnp.int64), ts])
                caps_t = {
                    a.ref: tuple(
                        jnp.concatenate([jnp.zeros((P,), c.dtype), c])
                        for c in (ev_cols if a.ref == a0.ref else tuple(
                            jnp.zeros((W,), d)
                            for d in schemas[a.stream_id].dtypes)))
                    for a in atoms}
                if not a0.every:
                    done = jnp.logical_or(done, jnp.any(comp_valid))
                ncarry = (active, pos, start_ts, entry_ts, slab_caps,
                          seed_on, done, dropped)
                return ncarry, (comp_valid, comp_idx, comp_ts, caps_t)

            # ---- thread arrays [T] -----------------------------------------
            T_ = T
            alive = jnp.concatenate([active, seed_fire])
            cur_pos = jnp.concatenate([pos, jnp.ones((W,), jnp.int32)])
            avail = jnp.concatenate(
                [jnp.zeros((P,), jnp.int32), iota_w + 1])
            start = jnp.concatenate([start_ts, ts])
            entry = jnp.concatenate([entry_ts, ts])
            caps_t = {}
            for a in atoms:
                seed_cols = ev_cols if (a.ref == a0.ref and
                                        a0.stream_id == stream_id) else \
                    tuple(jnp.zeros((W,), d)
                          for d in schemas[a.stream_id].dtypes)
                caps_t[a.ref] = tuple(
                    jnp.concatenate([sc, tc.astype(sc.dtype)])
                    for sc, tc in zip(slab_caps[a.ref], seed_cols))

            comp_valid = jnp.zeros((T_,), jnp.bool_)
            comp_idx = jnp.zeros((T_,), jnp.int64)
            comp_ts = jnp.zeros((T_,), jnp.int64)

            if is_seq:
                # next_valid[k] = first valid event index >= k (W if none)
                idxs = jnp.where(valid, iota_w, W)
                next_valid = lax.cummin(idxs, axis=0, reverse=True)

                def req_of(av):
                    oh_av = iota_w[None, :] == jnp.clip(av, 0, W - 1)[:, None]
                    nv = oh_take(jnp.broadcast_to(next_valid[None, :],
                                                  (T_, W)), oh_av, 1)
                    exists = jnp.logical_and(av < W, nv < W)
                    return nv, exists

            gate = jnp.logical_not(done)
            # ---- stages (unrolled: S is small) -----------------------------
            for s in range(1, S):
                a = atoms[s]
                eligible = jnp.logical_and(alive, cur_pos == s)
                if a.stream_id != stream_id:
                    if is_seq:
                        # strict continuity: any remaining valid event kills
                        # a thread waiting on another stream's atom
                        _nv, exists = req_of(avail)
                        alive = jnp.logical_and(
                            alive, jnp.logical_not(
                                jnp.logical_and(eligible, exists)))
                    continue
                filt = pexec._filters[a.ckey]
                env = probe_env({"__ts__": ts[None, :]})
                for other in atoms:
                    bind(env, other.ref,
                         tuple(c[None, :] for c in ev_cols)
                         if other.ref == a.ref else
                         tuple(c[:, None] for c in caps_t[other.ref]))
                if filt is None:
                    cond = jnp.ones((T_, W), jnp.bool_)
                else:
                    cond = jnp.broadcast_to(filt.fn(env), (T_, W))
                m = jnp.logical_and(cond, valid[None, :])
                m = jnp.logical_and(m, iota_w[None, :] >= avail[:, None])
                m = jnp.logical_and(m, eligible[:, None])
                m = jnp.logical_and(m, gate)
                if spec.within is not None:
                    m = jnp.logical_and(
                        m, ts[None, :] - start[:, None] <= spec.within)
                if is_seq:
                    nv, exists = req_of(avail)
                    first = jnp.logical_and(
                        m, jnp.logical_and(
                            iota_w[None, :] ==
                            jnp.clip(nv, 0, W - 1)[:, None],
                            exists[:, None]))
                    hit = jnp.any(first, axis=1)
                    # a next event exists but doesn't match: thread dies
                    alive = jnp.logical_and(alive, jnp.logical_not(
                        jnp.logical_and(
                            jnp.logical_and(eligible, exists),
                            jnp.logical_not(hit))))
                else:
                    cs = jnp.cumsum(m.astype(jnp.int32), axis=1)
                    first = jnp.logical_and(m, cs == 1)
                    hit = jnp.any(first, axis=1)
                j_hit = oh_take(jnp.broadcast_to(
                    iota_w[None, :].astype(jnp.int64), (T_, W)), first, 1)
                ts_hit = oh_take(jnp.broadcast_to(ts[None, :], (T_, W)),
                                 first, 1)
                caps_t[a.ref] = tuple(
                    jnp.where(hit,
                              oh_take(jnp.broadcast_to(c[None, :], (T_, W)),
                                      first, 1), old)
                    for c, old in zip(ev_cols, caps_t[a.ref]))
                avail = jnp.where(hit, (j_hit + 1).astype(jnp.int32), avail)
                entry = jnp.where(hit, ts_hit, entry)
                if s == S - 1:
                    comp_valid = jnp.logical_or(comp_valid, hit)
                    comp_idx = jnp.where(hit, base + j_hit, comp_idx)
                    comp_ts = jnp.where(hit, ts_hit, comp_ts)
                    alive = jnp.logical_and(alive, jnp.logical_not(hit))
                else:
                    cur_pos = jnp.where(hit, s + 1, cur_pos).astype(jnp.int32)

            if not a0.every:
                # only the FIRST completion emits; it latches `done`
                cstar = jnp.min(jnp.where(comp_valid, comp_idx, BIG))
                comp_valid = jnp.logical_and(comp_valid, comp_idx == cstar)
                done = jnp.logical_or(done, jnp.any(comp_valid))

            # ---- slab refill: surviving seed threads -> free slots ---------
            slab_alive = alive[:P]
            seed_pending = alive[P:]
            free = jnp.logical_not(slab_alive)
            rank = jnp.cumsum(seed_pending.astype(jnp.int32)) - 1     # [W]
            free_rank = jnp.cumsum(free.astype(jnp.int32)) - 1        # [P]
            hot = jnp.logical_and(
                jnp.logical_and(free[:, None], seed_pending[None, :]),
                free_rank[:, None] == rank[None, :])                  # [P,W]
            has = jnp.any(hot, axis=1)
            dropped = dropped + jnp.maximum(
                jnp.sum(seed_pending.astype(jnp.int64)) -
                jnp.sum(free.astype(jnp.int64)), 0)

            def pull(seed_field, old_field):
                got = oh_take(seed_field[None, :], hot, 1)
                return jnp.where(has, got, old_field)

            ncarry = (
                jnp.logical_or(slab_alive, has),
                pull(cur_pos[P:], cur_pos[:P]).astype(jnp.int32),
                pull(start[P:], start[:P]),
                pull(entry[P:], entry[:P]),
                {a.ref: tuple(pull(tc[P:], tc[:P]) for tc in caps_t[a.ref])
                 for a in atoms},
                seed_on, done, dropped)
            return ncarry, (comp_valid, comp_idx, comp_ts, caps_t)

        # ---- unpack state, chunk the block, scan ---------------------------
        b32, b64, scalars = packed
        B = raw_ts.shape[0]
        csel = jnp.clip(sel_idx[0], 0, B - 1)                     # [E]
        cols = tuple(c[csel].astype(d)
                     for c, d in zip(raw_cols, schema.dtypes))
        ts = raw_ts[csel]
        valid = sel_idx[0] >= 0
        st = packer.unpack(b32, b64, scalars)
        E = ts.shape[0]
        W = min(CHUNK, E)
        C = (E + W - 1) // W
        pad = C * W - E
        if pad:
            cols = tuple(jnp.pad(c, (0, pad)) for c in cols)
            ts = jnp.pad(ts, (0, pad))
            valid = jnp.pad(valid, (0, pad))
        T = P + W

        sq = lambda x: x[..., 0]                 # drop the K=1 axis
        carry = (
            sq(st.active), sq(st.pos), sq(st.start_ts), sq(st.entry_ts),
            {a.ref: tuple(sq(c[:, 0]) for c in st.caps[a.ckey][1])
             for a in atoms},
            sq(st.seed_on), sq(st.done), st.dropped)
        xs = (tuple(c.reshape(C, W) for c in cols), ts.reshape(C, W),
              valid.reshape(C, W),
              jnp.arange(C, dtype=jnp.int64) * W)
        carry, comps = lax.scan(chunk_advance, carry, xs)
        (factive, fpos, fstart, fentry, fcaps, fseed_on, fdone,
         fdropped) = carry
        if spec.within is not None:
            factive = jnp.logical_and(factive, now - fstart <= spec.within)

        # ---- write the slab back in packed form ----------------------------
        uq = lambda x: x[..., None]
        ncapd = {}
        for a in atoms:
            old_ts, _old_cols = st.caps[a.ckey]
            ncapd[a.ckey] = (old_ts, tuple(
                uq(uq(c)) for c in fcaps[a.ref]))
        nst = st._replace(
            active=uq(factive), pos=uq(fpos),
            count=jnp.zeros_like(st.count), lmask=jnp.zeros_like(st.lmask),
            start_ts=uq(fstart), entry_ts=uq(fentry),
            seed_on=uq(fseed_on), done=uq(fdone), dropped=fdropped,
            caps=ncapd)
        nb32, nb64, nscal = packer.pack(nst)

        # ---- emission: order completions by arrival, run the selector ------
        comp_valid, comp_idx, comp_ts, caps_stack = comps    # [C,T] / nested
        CT = C * T
        thread_rank = jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int64)[None, :], (C, T))
        key = jnp.where(comp_valid,
                        comp_idx * (T + 1) + thread_rank,
                        jnp.asarray(BIG, jnp.int64)).reshape(CT)
        order = jnp.argsort(key)
        o_valid = comp_valid.reshape(CT)[order]
        o_ts = comp_ts.reshape(CT)[order]

        env: Dict[str, Any] = {"__ts__": o_ts, "__now__": now}
        for a in atoms:
            if emit_refs is not None and a.ref not in emit_refs:
                continue
            ocols = tuple(c.reshape(CT)[order]
                          for c in caps_stack[a.ref])
            bind(env, a.ref, ocols)
        rows = Rows(
            ts=o_ts,
            kind=jnp.full((CT,), ev.CURRENT, jnp.int32),
            valid=o_valid,
            seq=jnp.arange(CT, dtype=jnp.int64),
            gslot=jnp.zeros((CT,), jnp.int32),
            cols=(),
        )
        sel_state, out = sel.process(sel_state, rows, env)
        ots, okind, ovalid, ocols2 = out
        R = min(compact_rows, CT)
        if R < CT:
            # rows are arrival-ordered; valid rows beyond the @emit cap drop
            rankv = jnp.cumsum(ovalid.astype(jnp.int32)) - 1
            keep = jnp.logical_and(ovalid, rankv < R)
            n_valid = jnp.sum(keep.astype(jnp.int64))
            n_dropped = jnp.sum(ovalid.astype(jnp.int64)) - n_valid
            out = (ots, okind, keep, ocols2)
        else:
            n_valid = jnp.sum(ovalid.astype(jnp.int64))
            n_dropped = jnp.zeros((), jnp.int64)
        out = (n_valid, n_dropped) + out
        wake = jnp.asarray(NO_WAKEUP, jnp.int64)
        return (nb32, nb64, nscal), sel_state, out, wake

    return step
