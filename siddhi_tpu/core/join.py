"""Join queries: stream-stream (windowed), stream-table, stream-named-window.

Reference behavior (what): CORE/query/input/stream/join/JoinProcessor.java:45
— each CURRENT/EXPIRED event on one side probes the other side's window via
find(); left/right/full outer emit unmatched rows with nulls; unidirectional
restricts the triggering side.

TPU-native design (how): each side's window is the columnar Buffer; a batch
of trigger-side rows joins against the other side's buffer as one masked
[R, C] cross evaluation of the compiled on-condition — the reference's
per-event find() loop becomes a single fused comparison + gather.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..query_api.definition import StreamDefinition
from ..query_api.query import JoinInputStream, Query, SingleInputStream
from . import event as ev
from .executor import CompileError, CompiledExpr, Scope, compile_expression
from .selector import SelectorExec
from .steputil import jit_step
from .window import Buffer, NoWindow, Rows, WindowProcessor, create_window


@dataclasses.dataclass
class JoinSide:
    stream_id: str
    key: str                      # scope key (alias or stream id)
    schema: ev.Schema
    window: Optional[WindowProcessor]   # None => table / named window side
    is_table: bool = False
    is_aggregation: bool = False
    # `define window` shared instance probed like a table: the join reads
    # its live buffer per step (reference: WindowWindowProcessor adapter)
    is_named_window: bool = False
    pre_filters: List[CompiledExpr] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class PlannedJoinQuery:
    name: str
    left: JoinSide
    right: JoinSide
    join_type: str
    trigger: str
    out_schema: ev.Schema
    output_target: str
    output_event_type: str
    selector_exec: SelectorExec
    step_left: Optional[Callable]
    step_right: Optional[Callable]
    init_state: Callable
    batch_capacity: int
    needs_timer: bool
    within_range: Optional[Tuple[int, int]] = None
    per_duration: Optional[str] = None
    # group-by in joins: per-side group keys resolve to per-side slots on
    # the host; the joined row's group slot composes on device as
    # gl * (Kr + 1) + gr (the +1 factor is the outer-join null group)
    slot_allocator: Optional[Any] = None      # left-side group allocator
    slot_allocator2: Optional[Any] = None     # right-side group allocator
    gl_pos: List[int] = dataclasses.field(default_factory=list)
    gr_pos: List[int] = dataclasses.field(default_factory=list)
    # UUID() appears in this query: emission materializes sentinels once
    emits_uuid: bool = False
    # device-side emission compaction: the [R*C] join grid is squeezed to
    # `compact_rows` valid-first rows before the host fetch (None = the
    # per-trace default max(2R, 1024)).  emit_explicit marks a user
    # @emit(rows='N') — overflow then warns instead of growing.
    compact_rows: Optional[int] = None
    emit_explicit: bool = False
    # join emissions carry CURRENT and EXPIRED rows; the runtime must not
    # assume all-current when deriving batch counts from the header
    mixed_kinds: bool = True
    # un-jitted side bodies for @fuse(batches=K) scan fusion (core/fusion.py)
    raw_left: Optional[Callable] = None
    raw_right: Optional[Callable] = None

    @staticmethod
    def _describe_side(s: "JoinSide") -> Dict:
        kind = "aggregation" if s.is_aggregation else \
            "named_window" if s.is_named_window else \
            "table" if s.is_table else "stream"
        d: Dict[str, Any] = {"id": s.stream_id, "kind": kind,
                             "columns": list(s.schema.names)}
        if s.window is not None:
            d["window_processor"] = type(s.window).__name__
        if s.pre_filters:
            d["pre_filters"] = len(s.pre_filters)
        return d

    def describe(self) -> Dict:
        """Compiled-plan facts for EXPLAIN (observability/explain.py):
        side kinds (stream/table/window/aggregation), the window
        processors chosen, emission compaction — beyond the query AST."""
        d: Dict[str, Any] = {
            "join_type": self.join_type,
            "trigger": self.trigger,
            "left": self._describe_side(self.left),
            "right": self._describe_side(self.right),
            "needs_timer": self.needs_timer,
            "out_columns": list(self.out_schema.names),
            "emission_cap_rows": self.compact_rows,
            "emission_cap_explicit": bool(self.emit_explicit),
        }
        if self.slot_allocator is not None:
            d["group_slot_capacity"] = (
                self.slot_allocator.capacity,
                self.slot_allocator2.capacity
                if self.slot_allocator2 is not None else None)
        if self.per_duration is not None:
            d["aggregation_per"] = self.per_duration
        return d


def _mk_side(sis: SingleInputStream, schemas, tables, batch_capacity,
             scope: Scope, window_capacity_hint: int,
             aggregations=None, named_windows=None) -> JoinSide:
    sid = sis.stream_id
    key = sis.stream_reference_id or sid
    if aggregations and sid in aggregations:
        # aggregation side: columnar snapshot per step (reference:
        # AggregationRuntime.find via AggregateWindowProcessor adapter)
        schema = aggregations[sid].make_schema()
        scope.add_source(key, schema, alias=None)
        return JoinSide(sid, key, schema, None, is_table=True,
                        is_aggregation=True)
    if named_windows and sid in named_windows:
        nw = named_windows[sid]
        if nw.wproc.current_buffer(nw.state) is None:
            raise CompileError(
                f"named window {sid!r} ({nw.wproc.name}) does not expose a "
                f"probe-able buffer for joins")
        schema = nw.schema
        scope.add_source(key, schema, alias=None)
        # bidirectional (reference: Window.java:145-184 — the join both
        # probes the shared window's buffer AND triggers on events flowing
        # through it).  The trigger path gets a pass-through window: rows
        # the named window emits probe the other side; retention lives in
        # the NamedWindowRuntime, never here.
        from .window import PassAllWindow
        return JoinSide(sid, key, schema,
                        PassAllWindow(schema, [], batch_capacity),
                        is_table=True, is_named_window=True)
    is_table = sid in tables
    schema = tables[sid].schema if is_table else schemas[sid]
    scope.add_source(key, schema, alias=None)
    win = None
    if not is_table:
        wh = sis.window_handler
        if wh is None:
            # windowless stream side: valid when probing a table-like side
            # (reference: JoinInputStreamParser wraps it in an empty window)
            win = NoWindow(schema, [], batch_capacity)
        else:
            win = create_window(
                (wh.namespace + ":" if wh.namespace else "") + wh.name,
                schema, wh.parameters, batch_capacity,
                capacity_hint=window_capacity_hint)
            if win.name not in ("length", "time"):
                raise CompileError(
                    f"join windows must be sliding (length/time), got "
                    f"{win.name!r}")
    side = JoinSide(sid, key, schema, win, is_table)
    return side


def _constrain_state(state, mesh):
    """Pin the persistent state's sharding INSIDE the jitted step.  The
    host-side device_put in JoinQueryRuntime.place_state only seeds the
    layout; without an in-graph constraint GSPMD is free to (and does)
    choose replicated output shardings, silently un-distributing the
    window buffers after the first step.  One constraint per eligible leaf
    keeps each buffer at 1/n rows per device across steps."""
    if mesh is None or mesh.devices.size < 2:
        return state
    from .shardsafe import axis0_sharding

    def _c(x):
        s = axis0_sharding(mesh, x)
        return jax.lax.with_sharding_constraint(x, s) if s is not None else x
    return jax.tree.map(_c, state)


def plan_join_query(
    query: Query,
    name: str,
    schemas: Dict[str, ev.Schema],
    tables: Dict[str, Any],
    interner: ev.StringInterner,
    batch_capacity: int = 512,
    window_capacity_hint: int = 512,
    aggregations=None,
    named_windows=None,
    mesh=None,
    emit_rows_override: Optional[int] = None,
) -> PlannedJoinQuery:
    jis = query.input_stream
    assert isinstance(jis, JoinInputStream)
    scope = Scope()
    scope.interner = interner
    left = _mk_side(jis.left_input_stream, schemas, tables, batch_capacity,
                    scope, window_capacity_hint, aggregations, named_windows)
    right = _mk_side(jis.right_input_stream, schemas, tables, batch_capacity,
                     scope, window_capacity_hint, aggregations,
                     named_windows)
    if left.is_table and right.is_table and \
            not (left.is_named_window or right.is_named_window):
        raise CompileError("cannot join two tables in a streaming query")
    if not left.is_table and not right.is_table and (
            isinstance(left.window, NoWindow) or
            isinstance(right.window, NoWindow)):
        raise CompileError(
            "stream-stream joins need a window on each side")

    within_range = per_duration = None
    if left.is_aggregation or right.is_aggregation:
        from .aggregation import parse_per, parse_within
        within_range = parse_within(jis.within)
        per_duration = parse_per(jis.per)

    # side filters ([filter] before window)
    for side, sis in ((left, jis.left_input_stream),
                      (right, jis.right_input_stream)):
        from ..query_api.query import Filter
        fscope = Scope()
        fscope.interner = interner
        fscope.add_source(side.key, side.schema)
        for h in sis.stream_handlers:
            if isinstance(h, Filter):
                side.pre_filters.append(
                    compile_expression(h.expression, fscope))

    on = None
    if jis.on_compare is not None:
        on = compile_expression(jis.on_compare, scope)

    # group-by in joins (reference: JoinProcessor + QuerySelector
    # processGroupBy, JoinProcessor.java:107-190): group attrs resolve to
    # per-side slot ids at ingestion; the joined row's slot composes the two
    gl_pos: List[int] = []
    gr_pos: List[int] = []
    for v in query.selector.group_by_list:
        key, pos, _ = scope.resolve(v)
        if key == left.key:
            if left.is_table:
                raise CompileError(
                    "join group-by attributes must come from stream sides")
            gl_pos.append(pos)
        elif key == right.key:
            if right.is_table:
                raise CompileError(
                    "join group-by attributes must come from stream sides")
            gr_pos.append(pos)
        else:
            raise CompileError(
                f"cannot resolve group-by attribute {v.attribute_name!r} "
                f"to a join side")
    if gl_pos and gr_pos:
        Kl = Kr = 63
    elif gl_pos:
        Kl, Kr = 2047, 0
    elif gr_pos:
        Kl, Kr = 0, 2047
    else:
        Kl = Kr = 0
    from .keyslots import SlotAllocator
    gl_alloc = SlotAllocator(Kl, name=f"{name}:gl") if gl_pos else None
    gr_alloc = SlotAllocator(Kr, name=f"{name}:gr") if gr_pos else None
    sel = SelectorExec(query.selector, scope, left.schema,
                       max((Kl + 1) * (Kr + 1), 64),
                       (query.output_stream.target_id
                        if query.output_stream else name), interner)
    if sel.bank.pair_sources:
        raise CompileError(
            "distinctCount/unionSet in join queries lands in a later phase")

    out_target = query.output_stream.target_id if query.output_stream else ""
    out_def = StreamDefinition(out_target or f"#{name}.out")
    for n, t in zip(sel.out_names, sel.out_types):
        out_def.attribute(n, t)
    out_schema = ev.Schema(out_def, interner)

    jt = jis.type
    trigger = jis.trigger

    # emission compaction cap: @emit(rows='N') = total delivered rows per
    # batch (pattern queries use per-key rows; joins have no key axis).
    # Without it the per-trace default max(2R, 1024) covers ~1 match per
    # window row and adaptive growth (JoinQueryRuntime._grow_emission_cap)
    # handles denser fan-outs.
    emit_ann = query.get_annotation("emit")
    emit_explicit = emit_ann is not None and emit_rows_override is None
    emit_rows = emit_rows_override
    if emit_explicit:
        emit_rows = int(emit_ann.element("rows", 0)) or None

    def make_step(this: JoinSide, other: JoinSide, this_is_left: bool):
        """Step for a batch arriving on `this` side."""
        emit_unmatched_this = (
            (jt == "LEFT_OUTER_JOIN" and this_is_left) or
            (jt == "RIGHT_OUTER_JOIN" and not this_is_left) or
            jt == "FULL_OUTER_JOIN")
        K_other = Kr if this_is_left else Kl

        def step(state, ts, kind, valid, cols, gslot, other_table_cols,
                 now):
            wl_state, wr_state, sel_state = state
            this_state = wl_state if this_is_left else wr_state
            other_state = wr_state if this_is_left else wl_state

            env0 = {this.key: cols, "__ts__": ts, "__now__": now}
            keep = valid
            is_cur = kind == ev.CURRENT
            for f in this.pre_filters:
                keep = jnp.logical_and(keep, jnp.logical_or(
                    jnp.logical_not(is_cur), f.fn(env0)))
            rows = Rows(ts=ts, kind=kind, valid=keep,
                        seq=jnp.zeros_like(ts), gslot=gslot, cols=cols)
            this_state, wout = this.window.process(this_state, rows, now)
            orows = wout.rows                       # [R]

            # other side's buffer (gslot rides the window buffer rows)
            if other.is_table:
                o_cols, o_ts, o_alive = other_table_cols
                o_gslot = jnp.zeros(o_ts.shape, jnp.int32)
            else:
                obuf: Buffer = other_state[0]
                o_cols, o_ts, o_alive = obuf.cols, obuf.ts, obuf.alive
                o_gslot = obuf.gslot

            R = orows.ts.shape[0]
            C = o_ts.shape[0]
            env = {
                this.key: tuple(c[:, None] for c in orows.cols),
                other.key: tuple(c[None, :] for c in o_cols),
                "__ts__": orows.ts[:, None],
                "__now__": now,
            }
            if on is None:
                m = jnp.ones((R, C), jnp.bool_)
            else:
                m = jnp.broadcast_to(on.fn(env), (R, C))
            data_row = jnp.logical_and(
                orows.valid,
                jnp.logical_or(orows.kind == ev.CURRENT,
                               orows.kind == ev.EXPIRED))
            m = jnp.logical_and(m, data_row[:, None])
            m = jnp.logical_and(m, o_alive[None, :])

            # matched pair rows [R*C] + unmatched rows [R] for outer joins
            pair_valid = m.reshape(-1)
            left_idx = jnp.repeat(jnp.arange(R), C)
            right_idx = jnp.tile(jnp.arange(C), R)
            unmatched = jnp.logical_and(data_row, jnp.logical_not(
                jnp.any(m, axis=1)))
            if emit_unmatched_this:
                all_valid = jnp.concatenate([pair_valid, unmatched])
                li = jnp.concatenate([left_idx, jnp.arange(R)])
                ri = jnp.concatenate([right_idx, jnp.zeros((R,), jnp.int32)])
                null_tail = jnp.concatenate(
                    [jnp.zeros((R * C,), jnp.bool_), unmatched])
            else:
                all_valid = pair_valid
                li, ri = left_idx, right_idx
                null_tail = jnp.zeros((R * C,), jnp.bool_)

            N = all_valid.shape[0]
            this_cols = tuple(c[li] for c in orows.cols)
            # unmatched outer-join rows carry REAL nulls on the other side
            # (reference: JoinProcessor.java:107-190 emits null attributes;
            # numerics use the reserved in-band null, core/event.py)
            other_cols_g = tuple(
                jnp.where(null_tail,
                          jnp.asarray(ev.null_value(t), dtype=c.dtype),
                          c[ri])
                for c, t in zip(o_cols, other.schema.types))
            sel_env = {
                this.key: this_cols,
                other.key: other_cols_g,
                "__ts__": orows.ts[li],
                "__now__": now,
            }
            # composed group slot: gl * (Kr + 1) + gr; unmatched outer rows
            # take the other side's null-group id (K_other)
            tg = orows.gslot[li]
            og = jnp.where(null_tail, K_other,
                           o_gslot[jnp.clip(ri, 0, C - 1)])
            if this_is_left:
                comp = tg * (Kr + 1) + og
            else:
                comp = og * (Kr + 1) + tg
            jrows = Rows(
                ts=orows.ts[li],
                kind=orows.kind[li],
                valid=all_valid,
                seq=orows.seq[li] * (C + 1) + ri,
                gslot=comp.astype(jnp.int32),
                cols=(),
            )
            sel_state, out = sel.process(sel_state, jrows, sel_env)
            # device-side compaction: the [N] grid (N = R*C(+R)) would cost
            # N-row host fetches per send — megabytes over a tunneled
            # device for kilobytes of matches.  Stable valid-first argsort
            # keeps delivery order; rows beyond the cap are counted as
            # dropped and the runtime grows the cap (a planned recompile)
            # when the cap was implicit.
            o_ts, o_kind, o_valid, o_cols = out
            N = o_ts.shape[0]
            cap = min(N, emit_rows if emit_rows is not None
                      else max(2 * R, 1024))
            n_tot = jnp.sum(o_valid).astype(jnp.int32)
            if cap < N:
                order = jnp.argsort(jnp.logical_not(o_valid),
                                    stable=True)[:cap]
                o_ts, o_kind, o_valid = \
                    o_ts[order], o_kind[order], o_valid[order]
                o_cols = tuple(c[order] for c in o_cols)
            n_del = jnp.minimum(n_tot, jnp.int32(cap))
            # header ships [n_valid, n_current] so count-only consumers
            # (the common bench/monitoring shape) cost ZERO bulk fetches;
            # n_expired derives as n_valid - n_current host-side
            n_cur = jnp.sum(jnp.logical_and(
                o_valid, o_kind == ev.CURRENT)).astype(jnp.int32)
            out = (jnp.stack([n_del, n_cur]), n_tot - n_del,
                   o_ts, o_kind, o_valid, o_cols)
            nstate = ((this_state, other_state) if this_is_left
                      else (other_state, this_state))
            new_state = _constrain_state(
                (nstate[0], nstate[1], sel_state), mesh)
            return new_state, out, wout.next_wakeup

        return step

    # raw (un-jitted) bodies are kept on the plan: @fuse(batches=K) wraps
    # them in its lax.scan so fused execution runs the identical per-batch
    # program (core/fusion.py)
    step_left = raw_left = None
    step_right = raw_right = None
    # named-window sides trigger too (bidirectional, Window.java:145-184);
    # plain table/aggregation sides stay probe-only
    if (not left.is_table or left.is_named_window) and \
            trigger in ("ALL_EVENTS", "LEFT"):
        raw_left = make_step(left, right, True)
    if (not right.is_table or right.is_named_window) and \
            trigger in ("ALL_EVENTS", "RIGHT"):
        raw_right = make_step(right, left, False)
    # non-triggering stream sides still need their window maintained
    if not left.is_table and raw_left is None:
        raw_left = _make_feed_only(left, True, mesh)
    if not right.is_table and raw_right is None:
        raw_right = _make_feed_only(right, False, mesh)
    if raw_left is not None:
        step_left = jit_step(raw_left, owner=name, donate_argnums=(0,))
    if raw_right is not None:
        step_right = jit_step(raw_right, owner=name, donate_argnums=(0,))

    def init_state():
        wl = left.window.init_state() if left.window else ()
        wr = right.window.init_state() if right.window else ()
        return (wl, wr, sel.init_state())

    return PlannedJoinQuery(
        name=name, left=left, right=right, join_type=jt, trigger=trigger,
        within_range=within_range, per_duration=per_duration,
        out_schema=out_schema,
        output_target=out_target,
        output_event_type=(query.output_stream.output_event_type
                           if query.output_stream and
                           query.output_stream.output_event_type
                           else "CURRENT_EVENTS"),
        selector_exec=sel,
        step_left=step_left, step_right=step_right,
        init_state=init_state, batch_capacity=batch_capacity,
        slot_allocator=gl_alloc, slot_allocator2=gr_alloc,
        gl_pos=gl_pos, gr_pos=gr_pos,
        needs_timer=(left.window is not None and left.window.needs_timer) or
                    (right.window is not None and right.window.needs_timer),
        emits_uuid=scope.uses_uuid,
        compact_rows=emit_rows, emit_explicit=emit_explicit,
        raw_left=raw_left, raw_right=raw_right)


def _make_feed_only(side: JoinSide, is_left: bool, mesh=None):
    def step(state, ts, kind, valid, cols, gslot, other_table_cols, now):
        wl_state, wr_state, sel_state = state
        this_state = wl_state if is_left else wr_state
        env0 = {side.key: cols, "__ts__": ts, "__now__": now}
        keep = valid
        is_cur = kind == ev.CURRENT
        for f in side.pre_filters:
            keep = jnp.logical_and(keep, jnp.logical_or(
                jnp.logical_not(is_cur), f.fn(env0)))
        rows = Rows(ts=ts, kind=kind, valid=keep, seq=jnp.zeros_like(ts),
                    gslot=gslot, cols=cols)
        this_state, wout = side.window.process(this_state, rows, now)
        out_empty = (
            jnp.zeros((1,), jnp.int64), jnp.zeros((1,), jnp.int32),
            jnp.zeros((1,), jnp.bool_), tuple())
        new_state = (this_state, wr_state, sel_state) if is_left else \
            (wl_state, this_state, sel_state)
        return _constrain_state(new_state, mesh), out_empty, \
            wout.next_wakeup

    return step
