"""Join queries: stream-stream (windowed), stream-table, stream-named-window.

Reference behavior (what): CORE/query/input/stream/join/JoinProcessor.java:45
— each CURRENT/EXPIRED event on one side probes the other side's window via
find(); left/right/full outer emit unmatched rows with nulls; unidirectional
restricts the triggering side.

TPU-native design (how): each side's window is the columnar Buffer; a batch
of trigger-side rows joins against the other side's buffer as one masked
[R, C] cross evaluation of the compiled on-condition — the reference's
per-event find() loop becomes a single fused comparison + gather.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..query_api.definition import StreamDefinition
from ..query_api.query import JoinInputStream, Query, SingleInputStream
from . import event as ev
from .executor import CompileError, CompiledExpr, Scope, compile_expression
from .keyslots import SlotAllocator
from .plan_facts import JOIN_LANE_K_MIN, join_fastpath, table_probe_attrs_of
from .selector import SelectorExec
from .steputil import jit_step
from .window import Buffer, NoWindow, Rows, WindowProcessor, create_window


@dataclasses.dataclass
class JoinSide:
    stream_id: str
    key: str                      # scope key (alias or stream id)
    schema: ev.Schema
    window: Optional[WindowProcessor]   # None => table / named window side
    is_table: bool = False
    is_aggregation: bool = False
    # `define window` shared instance probed like a table: the join reads
    # its live buffer per step (reference: WindowWindowProcessor adapter)
    is_named_window: bool = False
    pre_filters: List[CompiledExpr] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class PlannedJoinQuery:
    name: str
    left: JoinSide
    right: JoinSide
    join_type: str
    trigger: str
    out_schema: ev.Schema
    output_target: str
    output_event_type: str
    selector_exec: SelectorExec
    step_left: Optional[Callable]
    step_right: Optional[Callable]
    init_state: Callable
    batch_capacity: int
    needs_timer: bool
    within_range: Optional[Tuple[int, int]] = None
    per_duration: Optional[str] = None
    # group-by in joins: per-side group keys resolve to per-side slots on
    # the host; the joined row's group slot composes on device as
    # gl * (Kr + 1) + gr (the +1 factor is the outer-join null group)
    slot_allocator: Optional[Any] = None      # left-side group allocator
    slot_allocator2: Optional[Any] = None     # right-side group allocator
    gl_pos: List[int] = dataclasses.field(default_factory=list)
    gr_pos: List[int] = dataclasses.field(default_factory=list)
    # UUID() appears in this query: emission materializes sentinels once
    emits_uuid: bool = False
    # device-side emission compaction: the [R*C] join grid is squeezed to
    # `compact_rows` valid-first rows before the host fetch (None = the
    # per-trace default max(2R, 1024)).  emit_explicit marks a user
    # @emit(rows='N') — overflow then warns instead of growing.
    compact_rows: Optional[int] = None
    emit_explicit: bool = False
    # join emissions carry CURRENT and EXPIRED rows; the runtime must not
    # assume all-current when deriving batch counts from the header
    mixed_kinds: bool = True
    # un-jitted side bodies for @fuse(batches=K) scan fusion (core/fusion.py)
    raw_left: Optional[Callable] = None
    raw_right: Optional[Callable] = None
    # ---- equi-join fast path (ROADMAP item 2) ----
    # 'bucket': both stream windows carry a key-slot column; the step
    # probes only same-bucket pairs through a lane table derived from
    # the buffer each dispatch.  'table': the table side's hash index
    # answers [B, K] candidates host-side.  None: full [R, C] grid.
    fastpath: Optional[str] = None
    # why an equality conjunct exists but the fast path stays off
    # (plan_facts.join_fastpath wording — lint JOIN002 prints the same)
    fastpath_reason: Optional[str] = None
    key_attrs: List[Tuple[str, str]] = dataclasses.field(
        default_factory=list)            # [(left attr, right attr)]
    key_left: List[int] = dataclasses.field(default_factory=list)
    key_right: List[int] = dataclasses.field(default_factory=list)
    key_dtypes: List[Any] = dataclasses.field(default_factory=list)
    residual: bool = False       # ON carries conjuncts beyond the keys
    lane_k: int = 0              # candidate lane width (bucket mode)
    lane_buckets: Tuple[int, int] = (0, 0)   # per-side lane-table rows
    ring_caps: Tuple[int, int] = (0, 0)      # per-side retention bound
    # shared key->slot allocator (both sides; carried across replans)
    join_key_allocator: Optional[Any] = None
    # table mode: which side is the table and the probe columns
    table_is_left: bool = False
    table_pos: int = -1          # indexed table column
    stream_key_pos: int = -1     # stream-side key column

    @staticmethod
    def _describe_side(s: "JoinSide") -> Dict:
        kind = "aggregation" if s.is_aggregation else \
            "named_window" if s.is_named_window else \
            "table" if s.is_table else "stream"
        d: Dict[str, Any] = {"id": s.stream_id, "kind": kind,
                             "columns": list(s.schema.names)}
        if s.window is not None:
            d["window_processor"] = type(s.window).__name__
        if s.pre_filters:
            d["pre_filters"] = len(s.pre_filters)
        return d

    def describe(self) -> Dict:
        """Compiled-plan facts for EXPLAIN (observability/explain.py):
        side kinds (stream/table/window/aggregation), the window
        processors chosen, emission compaction — beyond the query AST."""
        d: Dict[str, Any] = {
            "join_type": self.join_type,
            "trigger": self.trigger,
            "left": self._describe_side(self.left),
            "right": self._describe_side(self.right),
            "needs_timer": self.needs_timer,
            "out_columns": list(self.out_schema.names),
            "emission_cap_rows": self.compact_rows,
            "emission_cap_explicit": bool(self.emit_explicit),
        }
        if self.slot_allocator is not None:
            d["group_slot_capacity"] = (
                self.slot_allocator.capacity,
                self.slot_allocator2.capacity
                if self.slot_allocator2 is not None else None)
        if self.per_duration is not None:
            d["aggregation_per"] = self.per_duration
        d["equi_fastpath"] = self.fastpath_facts()
        return d

    def fastpath_facts(self) -> Dict:
        """Bucket stats for EXPLAIN / lint: the fast-path mode, the key
        attributes it buckets on, the candidate lane capacity, and
        whether a residual predicate rides the probe."""
        node: Dict[str, Any] = {"active": self.fastpath is not None}
        if self.fastpath is not None:
            node["mode"] = self.fastpath
            node["key_attrs"] = [list(p) for p in self.key_attrs]
            node["residual_predicate"] = bool(self.residual)
            if self.fastpath == "bucket":
                node["lane_k"] = int(self.lane_k)
                node["lane_buckets"] = list(self.lane_buckets)
                node["key_capacity"] = (
                    self.join_key_allocator.capacity
                    if self.join_key_allocator is not None else None)
        elif self.fastpath_reason is not None:
            node["reason"] = self.fastpath_reason
        return node


# A-B kill switch: bench `--mode join_compare` and the parity tests plan
# one runtime with the fast path off to prove byte-identical outputs.
# Consulted once at plan time; never flipped on a live runtime.
FASTPATH_ENABLED = True

JSLOT_COL = "#jslot"


def _probe_schema(schema: ev.Schema) -> ev.Schema:
    """The window-buffer schema of a bucketed join side: the stream's
    columns plus one synthetic INT column carrying the key's bucket
    slot.  The column rides the buffer through every window gather, so
    EXPIRED trigger rows keep the slot they were bucketed under at
    arrival — no re-hashing of buffered rows, ever."""
    d = StreamDefinition(f"{schema.id}{JSLOT_COL}")
    for n, t in zip(schema.names, schema.types):
        d.attribute(n, t)
    d.attribute(JSLOT_COL, "INT")
    return ev.Schema(d, schema.interner)


def _mk_side(sis: SingleInputStream, schemas, tables, batch_capacity,
             scope: Scope, window_capacity_hint: int,
             aggregations=None, named_windows=None,
             probe_col: bool = False) -> JoinSide:
    sid = sis.stream_id
    key = sis.stream_reference_id or sid
    if aggregations and sid in aggregations:
        # aggregation side: columnar snapshot per step (reference:
        # AggregationRuntime.find via AggregateWindowProcessor adapter)
        schema = aggregations[sid].make_schema()
        scope.add_source(key, schema, alias=None)
        return JoinSide(sid, key, schema, None, is_table=True,
                        is_aggregation=True)
    if named_windows and sid in named_windows:
        nw = named_windows[sid]
        if nw.wproc.current_buffer(nw.state) is None:
            raise CompileError(
                f"named window {sid!r} ({nw.wproc.name}) does not expose a "
                f"probe-able buffer for joins")
        schema = nw.schema
        scope.add_source(key, schema, alias=None)
        # bidirectional (reference: Window.java:145-184 — the join both
        # probes the shared window's buffer AND triggers on events flowing
        # through it).  The trigger path gets a pass-through window: rows
        # the named window emits probe the other side; retention lives in
        # the NamedWindowRuntime, never here.
        from .window import PassAllWindow
        return JoinSide(sid, key, schema,
                        PassAllWindow(schema, [], batch_capacity),
                        is_table=True, is_named_window=True)
    is_table = sid in tables
    schema = tables[sid].schema if is_table else schemas[sid]
    scope.add_source(key, schema, alias=None)
    win = None
    if not is_table:
        wh = sis.window_handler
        # bucketed sides build their buffers with the key-slot column
        # appended (the side's visible schema stays the original)
        win_schema = _probe_schema(schema) if probe_col else schema
        if wh is None:
            # windowless stream side: valid when probing a table-like side
            # (reference: JoinInputStreamParser wraps it in an empty window)
            win = NoWindow(win_schema, [], batch_capacity)
        else:
            win = create_window(
                (wh.namespace + ":" if wh.namespace else "") + wh.name,
                win_schema, wh.parameters, batch_capacity,
                capacity_hint=window_capacity_hint)
            if win.name not in ("length", "time"):
                raise CompileError(
                    f"join windows must be sliding (length/time), got "
                    f"{win.name!r}")
    side = JoinSide(sid, key, schema, win, is_table)
    return side


def _constrain_state(state, mesh):
    """Pin the persistent state's sharding INSIDE the jitted step.  The
    host-side device_put in JoinQueryRuntime.place_state only seeds the
    layout; without an in-graph constraint GSPMD is free to (and does)
    choose replicated output shardings, silently un-distributing the
    window buffers after the first step.  One constraint per eligible leaf
    keeps each buffer at 1/n rows per device across steps."""
    if mesh is None or mesh.devices.size < 2:
        return state
    from .shardsafe import axis0_sharding

    def _c(x):
        s = axis0_sharding(mesh, x)
        return jax.lax.with_sharding_constraint(x, s) if s is not None else x
    return jax.tree.map(_c, state)


def plan_join_query(
    query: Query,
    name: str,
    schemas: Dict[str, ev.Schema],
    tables: Dict[str, Any],
    interner: ev.StringInterner,
    batch_capacity: int = 512,
    window_capacity_hint: int = 512,
    aggregations=None,
    named_windows=None,
    mesh=None,
    emit_rows_override: Optional[int] = None,
    lane_k_override: Optional[int] = None,
) -> PlannedJoinQuery:
    jis = query.input_stream
    assert isinstance(jis, JoinInputStream)

    # equi-join fast path: decided from the AST BEFORE the sides build,
    # so bucketed windows can carry the key-slot column from birth
    def _side_kind(sid: str) -> str:
        if aggregations and sid in aggregations:
            return "aggregation"
        if named_windows and sid in named_windows:
            return "named_window"
        if sid in tables:
            return "table"
        return "stream"

    fp_mode, fp_pairs, fp_reason = join_fastpath(
        jis, _side_kind,
        lambda sid: table_probe_attrs_of(tables[sid].definition))
    if not FASTPATH_ENABLED and fp_mode is not None:
        fp_mode, fp_reason = None, "fast path disabled (A-B comparison)"

    scope = Scope()
    scope.interner = interner
    left = _mk_side(jis.left_input_stream, schemas, tables, batch_capacity,
                    scope, window_capacity_hint, aggregations, named_windows,
                    probe_col=fp_mode == "bucket")
    right = _mk_side(jis.right_input_stream, schemas, tables, batch_capacity,
                     scope, window_capacity_hint, aggregations,
                     named_windows, probe_col=fp_mode == "bucket")
    if left.is_table and right.is_table and \
            not (left.is_named_window or right.is_named_window):
        raise CompileError("cannot join two tables in a streaming query")
    if not left.is_table and not right.is_table and (
            isinstance(left.window, NoWindow) or
            isinstance(right.window, NoWindow)):
        raise CompileError(
            "stream-stream joins need a window on each side")

    within_range = per_duration = None
    if left.is_aggregation or right.is_aggregation:
        from .aggregation import parse_per, parse_within
        within_range = parse_within(jis.within)
        per_duration = parse_per(jis.per)

    # side filters ([filter] before window)
    for side, sis in ((left, jis.left_input_stream),
                      (right, jis.right_input_stream)):
        from ..query_api.query import Filter
        fscope = Scope()
        fscope.interner = interner
        fscope.add_source(side.key, side.schema)
        for h in sis.stream_handlers:
            if isinstance(h, Filter):
                side.pre_filters.append(
                    compile_expression(h.expression, fscope))

    on = None
    if jis.on_compare is not None:
        on = compile_expression(jis.on_compare, scope)

    # ---- equi-join fast-path plan details ---------------------------------
    key_attrs: List[Tuple[str, str]] = []
    key_left: List[int] = []
    key_right: List[int] = []
    key_dtypes: List[Any] = []
    lane_k = 0
    lane_buckets = (0, 0)
    ring_caps = (0, 0)
    jk_alloc = None
    table_is_left = False
    table_pos = -1
    stream_key_pos = -1
    if fp_mode == "bucket":
        for _c, lv, rv in fp_pairs:
            lp = left.schema.position(lv.attribute_name)
            rp = right.schema.position(rv.attribute_name)
            key_left.append(lp)
            key_right.append(rp)
            key_attrs.append((lv.attribute_name, rv.attribute_name))
            # both sides hash the PROMOTED encoding, so any two values
            # the compiled `==` would call equal land in one bucket
            key_dtypes.append(np.promote_types(
                ev.np_dtype(left.schema.types[lp]),
                ev.np_dtype(right.schema.types[rp])))
        ring_caps = (_retention_rows(left.window),
                     _retention_rows(right.window))
        lane_buckets = (_lane_bucket_count(ring_caps[0]),
                        _lane_bucket_count(ring_caps[1]))
        # initial lane width: cover small windows outright (occupancy
        # can never exceed the retention bound, so tiny-window joins
        # never pay a growth recompile) and start larger shapes at the
        # K a roughly-uniform key spread settles into
        auto_k = 1 << (max(1, min(max(ring_caps), 16)) - 1).bit_length()
        lane_k = max(JOIN_LANE_K_MIN, auto_k, int(lane_k_override or 0))
        # key slots live while EITHER ring retains them plus one batch
        # of new arrivals in flight (JoinKeyTracker evicts before it
        # allocates, so this bound holds transiently too)
        jk_alloc = SlotAllocator(
            ring_caps[0] + ring_caps[1] + 2 * max(batch_capacity, 8192),
            name=f"{name}:joinkey")
    elif fp_mode == "table":
        tside, sside = (left, right) if left.is_table else (right, left)
        table_is_left = left.is_table
        _c, lv, rv = fp_pairs[0]
        t_var, s_var = (lv, rv) if table_is_left else (rv, lv)
        table_pos = tside.schema.position(t_var.attribute_name)
        stream_key_pos = sside.schema.position(s_var.attribute_name)
        key_attrs = [(lv.attribute_name, rv.attribute_name)]
    n_conj = _conjunct_count(jis.on_compare)
    fp_residual = fp_mode is not None and n_conj > len(key_attrs)

    # group-by in joins (reference: JoinProcessor + QuerySelector
    # processGroupBy, JoinProcessor.java:107-190): group attrs resolve to
    # per-side slot ids at ingestion; the joined row's slot composes the two
    gl_pos: List[int] = []
    gr_pos: List[int] = []
    for v in query.selector.group_by_list:
        key, pos, _ = scope.resolve(v)
        if key == left.key:
            if left.is_table:
                raise CompileError(
                    "join group-by attributes must come from stream sides")
            gl_pos.append(pos)
        elif key == right.key:
            if right.is_table:
                raise CompileError(
                    "join group-by attributes must come from stream sides")
            gr_pos.append(pos)
        else:
            raise CompileError(
                f"cannot resolve group-by attribute {v.attribute_name!r} "
                f"to a join side")
    if gl_pos and gr_pos:
        Kl = Kr = 63
    elif gl_pos:
        Kl, Kr = 2047, 0
    elif gr_pos:
        Kl, Kr = 0, 2047
    else:
        Kl = Kr = 0
    gl_alloc = SlotAllocator(Kl, name=f"{name}:gl") if gl_pos else None
    gr_alloc = SlotAllocator(Kr, name=f"{name}:gr") if gr_pos else None
    sel = SelectorExec(query.selector, scope, left.schema,
                       max((Kl + 1) * (Kr + 1), 64),
                       (query.output_stream.target_id
                        if query.output_stream else name), interner)
    if sel.bank.pair_sources:
        raise CompileError(
            "distinctCount/unionSet in join queries lands in a later phase")

    out_target = query.output_stream.target_id if query.output_stream else ""
    out_def = StreamDefinition(out_target or f"#{name}.out")
    for n, t in zip(sel.out_names, sel.out_types):
        out_def.attribute(n, t)
    out_schema = ev.Schema(out_def, interner)

    jt = jis.type
    trigger = jis.trigger

    # emission compaction cap: @emit(rows='N') = total delivered rows per
    # batch (pattern queries use per-key rows; joins have no key axis).
    # Without it the per-trace default max(2R, 1024) covers ~1 match per
    # window row and adaptive growth (JoinQueryRuntime._grow_emission_cap)
    # handles denser fan-outs.
    emit_ann = query.get_annotation("emit")
    emit_explicit = emit_ann is not None and emit_rows_override is None
    emit_rows = emit_rows_override
    if emit_explicit:
        emit_rows = int(emit_ann.element("rows", 0)) or None

    def make_step(this: JoinSide, other: JoinSide, this_is_left: bool):
        """Step for a batch arriving on `this` side."""
        emit_unmatched_this = (
            (jt == "LEFT_OUTER_JOIN" and this_is_left) or
            (jt == "RIGHT_OUTER_JOIN" and not this_is_left) or
            jt == "FULL_OUTER_JOIN")
        K_other = Kr if this_is_left else Kl
        # fast-path shape facts baked into the trace
        bucket = fp_mode == "bucket"
        table_probe = fp_mode == "table" and not this.is_table
        nbl_other = (lane_buckets[1] if this_is_left else
                     lane_buckets[0]) if bucket else 0

        def step(state, ts, kind, valid, cols, gslot, *rest):
            if bucket or table_probe:
                probe, other_table_cols, now = rest
            else:
                other_table_cols, now = rest
            wl_state, wr_state, sel_state = state
            this_state = wl_state if this_is_left else wr_state
            other_state = wr_state if this_is_left else wl_state

            env0 = {this.key: cols, "__ts__": ts, "__now__": now}
            keep = valid
            is_cur = kind == ev.CURRENT
            for f in this.pre_filters:
                keep = jnp.logical_and(keep, jnp.logical_or(
                    jnp.logical_not(is_cur), f.fn(env0)))
            in_cols = cols
            if bucket:
                # key bucket slot rides the window buffer as a column
                in_cols = cols + (probe,)
            elif table_probe:
                # original batch row index rides the (windowless) window
                # so compacted trigger rows can find their host-computed
                # table candidates
                in_cols = cols + (jnp.arange(ts.shape[0],
                                             dtype=jnp.int32),)
            rows = Rows(ts=ts, kind=kind, valid=keep,
                        seq=jnp.zeros_like(ts), gslot=gslot, cols=in_cols)
            this_state, wout = this.window.process(this_state, rows, now)
            orows = wout.rows                       # [R]
            if bucket or table_probe:
                trig_extra = orows.cols[-1]
                t_cols = orows.cols[:-1]
            else:
                trig_extra = None
                t_cols = orows.cols

            # other side's buffer (gslot rides the window buffer rows)
            if other.is_table:
                o_cols, o_ts, o_alive = other_table_cols
                o_gslot = jnp.zeros(o_ts.shape, jnp.int32)
            else:
                obuf: Buffer = other_state[0]
                o_cols, o_ts, o_alive = obuf.cols, obuf.ts, obuf.alive
                o_gslot = obuf.gslot
                if bucket:
                    o_jslot = o_cols[-1]
                    o_cols = o_cols[:-1]

            R = orows.ts.shape[0]
            C = o_ts.shape[0]
            data_row = jnp.logical_and(
                orows.valid,
                jnp.logical_or(orows.kind == ev.CURRENT,
                               orows.kind == ev.EXPIRED))
            if bucket:
                # [R, K] same-bucket candidates instead of the [R, C]
                # grid: the lane table is re-derived from the buffer's
                # slot column each dispatch (O(C log C), never O(R*C)),
                # the full ON-condition re-verifies every candidate, so
                # hash/lane collisions only cost work, never matches
                lanes = _bucket_lanes(o_jslot, o_alive, nbl_other,
                                      lane_k)
                tb = trig_extra.astype(jnp.int32) % nbl_other
                cand = lanes[tb]                       # [R, K]
                cand_ok = cand < C
                ri2 = jnp.minimum(cand, C - 1)
                env = {
                    this.key: tuple(c[:, None] for c in t_cols),
                    other.key: tuple(c[ri2] for c in o_cols),
                    "__ts__": orows.ts[:, None],
                    "__now__": now,
                }
                m = jnp.broadcast_to(on.fn(env), ri2.shape)
                m = jnp.logical_and(m, cand_ok)
                m = jnp.logical_and(m, o_alive[ri2])
            elif table_probe:
                cand_b, ok_b = probe                   # [B, K] host probe
                B = cand_b.shape[0]
                bix = jnp.clip(trig_extra, 0, B - 1)
                cand = cand_b[bix]                     # [R, K]
                cand_ok = jnp.logical_and(ok_b[bix], cand >= 0)
                ri2 = jnp.clip(cand, 0, C - 1)
                env = {
                    this.key: tuple(c[:, None] for c in t_cols),
                    other.key: tuple(c[ri2] for c in o_cols),
                    "__ts__": orows.ts[:, None],
                    "__now__": now,
                }
                m = jnp.broadcast_to(on.fn(env), ri2.shape)
                m = jnp.logical_and(m, cand_ok)
                m = jnp.logical_and(m, o_alive[ri2])
            else:
                env = {
                    this.key: tuple(c[:, None] for c in t_cols),
                    other.key: tuple(c[None, :] for c in o_cols),
                    "__ts__": orows.ts[:, None],
                    "__now__": now,
                }
                if on is None:
                    m = jnp.ones((R, C), jnp.bool_)
                else:
                    m = jnp.broadcast_to(on.fn(env), (R, C))
                m = jnp.logical_and(m, o_alive[None, :])
                ri2 = jnp.broadcast_to(
                    jnp.arange(C, dtype=jnp.int32)[None, :], (R, C))
            m = jnp.logical_and(m, data_row[:, None])

            # matched pair rows [R*Q] + unmatched rows [R] for outer
            # joins; ri carries REAL buffer positions so seq/order match
            # the grid path bit for bit
            Q = m.shape[1]
            pair_valid = m.reshape(-1)
            left_idx = jnp.repeat(jnp.arange(R), Q)
            right_idx = ri2.astype(jnp.int32).reshape(-1)
            unmatched = jnp.logical_and(data_row, jnp.logical_not(
                jnp.any(m, axis=1)))
            if emit_unmatched_this:
                all_valid = jnp.concatenate([pair_valid, unmatched])
                li = jnp.concatenate([left_idx, jnp.arange(R)])
                ri = jnp.concatenate([right_idx, jnp.zeros((R,), jnp.int32)])
                null_tail = jnp.concatenate(
                    [jnp.zeros((R * Q,), jnp.bool_), unmatched])
            else:
                all_valid = pair_valid
                li, ri = left_idx, right_idx
                null_tail = jnp.zeros((R * Q,), jnp.bool_)

            N = all_valid.shape[0]
            this_cols = tuple(c[li] for c in t_cols)
            # unmatched outer-join rows carry REAL nulls on the other side
            # (reference: JoinProcessor.java:107-190 emits null attributes;
            # numerics use the reserved in-band null, core/event.py)
            other_cols_g = tuple(
                jnp.where(null_tail,
                          jnp.asarray(ev.null_value(t), dtype=c.dtype),
                          c[ri])
                for c, t in zip(o_cols, other.schema.types))
            sel_env = {
                this.key: this_cols,
                other.key: other_cols_g,
                "__ts__": orows.ts[li],
                "__now__": now,
            }
            # composed group slot: gl * (Kr + 1) + gr; unmatched outer rows
            # take the other side's null-group id (K_other)
            tg = orows.gslot[li]
            og = jnp.where(null_tail, K_other,
                           o_gslot[jnp.clip(ri, 0, C - 1)])
            if this_is_left:
                comp = tg * (Kr + 1) + og
            else:
                comp = og * (Kr + 1) + tg
            jrows = Rows(
                ts=orows.ts[li],
                kind=orows.kind[li],
                valid=all_valid,
                seq=orows.seq[li] * (C + 1) + ri,
                gslot=comp.astype(jnp.int32),
                cols=(),
            )
            sel_state, out = sel.process(sel_state, jrows, sel_env)
            # device-side compaction: the [N] grid (N = R*C(+R)) would cost
            # N-row host fetches per send — megabytes over a tunneled
            # device for kilobytes of matches.  Stable valid-first argsort
            # keeps delivery order; rows beyond the cap are counted as
            # dropped and the runtime grows the cap (a planned recompile)
            # when the cap was implicit.
            o_ts, o_kind, o_valid, o_cols = out
            N = o_ts.shape[0]
            cap = min(N, emit_rows if emit_rows is not None
                      else max(2 * R, 1024))
            n_tot = jnp.sum(o_valid).astype(jnp.int32)
            if cap < N:
                order = jnp.argsort(jnp.logical_not(o_valid),
                                    stable=True)[:cap]
                o_ts, o_kind, o_valid = \
                    o_ts[order], o_kind[order], o_valid[order]
                o_cols = tuple(c[order] for c in o_cols)
            n_del = jnp.minimum(n_tot, jnp.int32(cap))
            # header ships [n_valid, n_current] so count-only consumers
            # (the common bench/monitoring shape) cost ZERO bulk fetches;
            # n_expired derives as n_valid - n_current host-side
            n_cur = jnp.sum(jnp.logical_and(
                o_valid, o_kind == ev.CURRENT)).astype(jnp.int32)
            out = (jnp.stack([n_del, n_cur]), n_tot - n_del,
                   o_ts, o_kind, o_valid, o_cols)
            nstate = ((this_state, other_state) if this_is_left
                      else (other_state, this_state))
            new_state = _constrain_state(
                (nstate[0], nstate[1], sel_state), mesh)
            return new_state, out, wout.next_wakeup

        return step

    # raw (un-jitted) bodies are kept on the plan: @fuse(batches=K) wraps
    # them in its lax.scan so fused execution runs the identical per-batch
    # program (core/fusion.py)
    step_left = raw_left = None
    step_right = raw_right = None
    # named-window sides trigger too (bidirectional, Window.java:145-184);
    # plain table/aggregation sides stay probe-only
    if (not left.is_table or left.is_named_window) and \
            trigger in ("ALL_EVENTS", "LEFT"):
        raw_left = make_step(left, right, True)
    if (not right.is_table or right.is_named_window) and \
            trigger in ("ALL_EVENTS", "RIGHT"):
        raw_right = make_step(right, left, False)
    # non-triggering stream sides still need their window maintained
    if not left.is_table and raw_left is None:
        raw_left = _make_feed_only(left, True, mesh, fp_mode)
    if not right.is_table and raw_right is None:
        raw_right = _make_feed_only(right, False, mesh, fp_mode)
    if raw_left is not None:
        step_left = jit_step(raw_left, owner=name, donate_argnums=(0,))
    if raw_right is not None:
        step_right = jit_step(raw_right, owner=name, donate_argnums=(0,))

    def init_state():
        wl = left.window.init_state() if left.window else ()
        wr = right.window.init_state() if right.window else ()
        return (wl, wr, sel.init_state())

    return PlannedJoinQuery(
        name=name, left=left, right=right, join_type=jt, trigger=trigger,
        within_range=within_range, per_duration=per_duration,
        out_schema=out_schema,
        output_target=out_target,
        output_event_type=(query.output_stream.output_event_type
                           if query.output_stream and
                           query.output_stream.output_event_type
                           else "CURRENT_EVENTS"),
        selector_exec=sel,
        step_left=step_left, step_right=step_right,
        init_state=init_state, batch_capacity=batch_capacity,
        slot_allocator=gl_alloc, slot_allocator2=gr_alloc,
        gl_pos=gl_pos, gr_pos=gr_pos,
        needs_timer=(left.window is not None and left.window.needs_timer) or
                    (right.window is not None and right.window.needs_timer),
        emits_uuid=scope.uses_uuid,
        compact_rows=emit_rows, emit_explicit=emit_explicit,
        raw_left=raw_left, raw_right=raw_right,
        fastpath=fp_mode, fastpath_reason=fp_reason,
        key_attrs=key_attrs, key_left=key_left, key_right=key_right,
        key_dtypes=key_dtypes, residual=fp_residual,
        lane_k=lane_k, lane_buckets=lane_buckets, ring_caps=ring_caps,
        join_key_allocator=jk_alloc,
        table_is_left=table_is_left, table_pos=table_pos,
        stream_key_pos=stream_key_pos)


def _make_feed_only(side: JoinSide, is_left: bool, mesh=None,
                    fp_mode: Optional[str] = None):
    takes_probe = fp_mode in ("bucket", "table")

    def step(state, ts, kind, valid, cols, gslot, *rest):
        if takes_probe:
            probe, other_table_cols, now = rest
        else:
            other_table_cols, now = rest
        wl_state, wr_state, sel_state = state
        this_state = wl_state if is_left else wr_state
        env0 = {side.key: cols, "__ts__": ts, "__now__": now}
        keep = valid
        is_cur = kind == ev.CURRENT
        for f in side.pre_filters:
            keep = jnp.logical_and(keep, jnp.logical_or(
                jnp.logical_not(is_cur), f.fn(env0)))
        in_cols = cols
        if fp_mode == "bucket":
            in_cols = cols + (probe,)
        elif fp_mode == "table":
            in_cols = cols + (jnp.arange(ts.shape[0], dtype=jnp.int32),)
        rows = Rows(ts=ts, kind=kind, valid=keep, seq=jnp.zeros_like(ts),
                    gslot=gslot, cols=in_cols)
        this_state, wout = side.window.process(this_state, rows, now)
        out_empty = (
            jnp.zeros((1,), jnp.int64), jnp.zeros((1,), jnp.int32),
            jnp.zeros((1,), jnp.bool_), tuple())
        new_state = (this_state, wr_state, sel_state) if is_left else \
            (wl_state, this_state, sel_state)
        return _constrain_state(new_state, mesh), out_empty, \
            wout.next_wakeup

    return step


# ---------------------------------------------------------------------------
# equi-join fast path machinery (ROADMAP item 2)
# ---------------------------------------------------------------------------

def _retention_rows(win: Optional[WindowProcessor]) -> int:
    """Upper bound on rows a join window retains: length windows keep
    exactly `length`; time windows drop-oldest above `capacity`."""
    if win is None:
        return 0
    n = getattr(win, "length", None)
    if n is None:
        n = getattr(win, "capacity", None)
    return int(n if n is not None else win.batch_capacity)


def _lane_bucket_count(ring: int) -> int:
    """Power-of-two lane-table rows for a buffer bound: ~2 buckets per
    resident row keeps slot-modulo collisions (which only widen lanes,
    never lose matches) rare while the device table stays small."""
    return max(64, min(1 << 17, 1 << (2 * max(ring, 1) - 1).bit_length()))


def _conjunct_count(on) -> int:
    from ..query_api.expression import And
    if on is None:
        return 0
    if isinstance(on, And):
        return _conjunct_count(on.left) + _conjunct_count(on.right)
    return 1


def _bucket_lanes(jslot, alive, nbl: int, k: int):
    """Derive the per-bucket candidate lane table [nbl, k] from a window
    buffer's key-slot column: entries are buffer positions ascending
    within each bucket (grid-path emission order), `C` where a lane is
    empty.  O(C log C) work on the buffer only — never on the grid.
    Lane overflow cannot happen by construction: the host
    JoinKeyTracker grows the planned `k` past the worst same-bucket
    occupancy BEFORE the batch that would need it dispatches."""
    C = jslot.shape[0]
    bkt = jnp.where(alive, jslot.astype(jnp.int32) % nbl, nbl)
    order = jnp.argsort(bkt, stable=True).astype(jnp.int32)
    sb = bkt[order]
    first = jnp.searchsorted(sb, sb, side="left")
    rank = jnp.arange(C, dtype=jnp.int32) - first.astype(jnp.int32)
    lanes = jnp.full((nbl + 1, k + 1), C, jnp.int32)
    lanes = lanes.at[jnp.minimum(sb, nbl),
                     jnp.minimum(rank, k)].set(order)
    return lanes[:nbl, :k]


def _norm_key_cols(staged_cols, positions, dtypes) -> List[np.ndarray]:
    """Key columns normalized to the promoted compare dtype so both
    sides of `L.a == R.b` hash identically (float -0.0 folds into +0.0,
    same as table_index.AttributeIndex._key_cols)."""
    out = []
    for pos, dt in zip(positions, dtypes):
        c = np.asarray(staged_cols[pos]).astype(dt, copy=False)
        if np.issubdtype(dt, np.floating):
            c = c + np.dtype(dt).type(0.0)
        out.append(np.ascontiguousarray(c))
    return out


class _TrackSide:
    """One side's retention ring: slot ids of the last `cap` admitted
    arrivals, plus per-lane (slot % nbl) occupancy counts."""

    __slots__ = ("cap", "nbl", "ring", "head", "n", "lane")

    def __init__(self, cap: int, nbl: int):
        self.cap = max(1, int(cap))
        self.nbl = max(1, int(nbl))
        self.ring = np.full(self.cap, -1, np.int64)
        self.head = 0
        self.n = 0
        self.lane = np.zeros(self.nbl, np.int64)

    def oldest(self, k: int) -> np.ndarray:
        idx = (self.head + np.arange(k)) % self.cap
        return self.ring[idx]

    def pop(self, k: int) -> None:
        self.head = (self.head + k) % self.cap
        self.n -= k

    def push(self, arr: np.ndarray) -> None:
        idx = (self.head + self.n + np.arange(arr.size)) % self.cap
        self.ring[idx] = arr
        self.n += arr.size


class JoinKeyTracker:
    """Host mirror of per-key window retention for the bucketed
    equi-join fast path.

    Conservative invariant: each side's ring holds the key slots of the
    last `cap` admitted arrivals — a SUPERSET of the rows alive in that
    side's device buffer (length windows retain exactly the last
    `length` arrivals; time windows drop-oldest above `cap` and time
    expiry only shrinks the alive set further).  Two guarantees ride on
    it: (1) the max same-lane occupancy across both rings never
    under-counts the device buffers, so the planned lane width K always
    covers every candidate — an under-sized K would silently diverge
    from the grid path; (2) a key slot recycles only when NEITHER ring
    retains it, so no alive buffer row can be left holding a slot that
    a new key re-binds (which would hide its future matches)."""

    def __init__(self, alloc: SlotAllocator, ring_caps, lane_buckets):
        self.alloc = alloc
        self.sides = (
            _TrackSide(ring_caps[0], lane_buckets[0]),
            _TrackSide(ring_caps[1], lane_buckets[1]),
        )
        self.refs = np.zeros(alloc.capacity, np.int64)

    def needed_k(self) -> int:
        return max(int(s.lane.max(initial=0)) for s in self.sides)

    def _evict(self, s: _TrackSide, incoming: int, dead: set) -> None:
        k = min(max(s.n + incoming - s.cap, 0), s.n)
        if k <= 0:
            return
        old = s.oldest(k)
        s.pop(k)
        np.subtract.at(self.refs, old, 1)
        np.subtract.at(s.lane, old % s.nbl, 1)
        for sl in np.unique(old):
            if self.refs[sl] <= 0:
                dead.add(int(sl))

    def track(self, is_left: bool, key_cols, valid) -> np.ndarray:
        """Allocate bucket slots for one batch and fold it into the
        side's ring.  Evicts BEFORE allocating so the allocator's
        capacity bound (ring_l + ring_r + one batch) holds transiently,
        and purges any slot neither ring retains afterwards."""
        s = self.sides[0 if is_left else 1]
        nv = int(valid.sum())
        dead: set = set()
        if nv:
            self._evict(s, min(nv, s.cap), dead)
        slots = self.alloc.slots_for(key_cols, valid)
        ins = slots[valid].astype(np.int64)
        skipped = None
        if ins.size > s.cap:
            # a batch larger than the window: only its last `cap` rows
            # survive the step's own eviction — earlier rows join
            # transiently within the step but retain nothing
            skipped, ins = ins[:-s.cap], ins[-s.cap:]
        if ins.size:
            np.add.at(self.refs, ins, 1)
            np.add.at(s.lane, ins % s.nbl, 1)
            s.push(ins)
        if skipped is not None:
            dead.update(int(x) for x in np.unique(skipped))
        gone = [d for d in dead if self.refs[d] <= 0]
        if gone:
            self.alloc.purge(gone)
        return slots

    def rebuild(self, per_side_slots) -> None:
        """Restore path: re-seed both rings from the snapshot's buffer
        contents (alive rows in arrival order) and drop every allocator
        binding neither window retains."""
        self.refs[:] = 0
        self.sides = tuple(
            _TrackSide(s.cap, s.nbl) for s in self.sides)
        for s, slots in zip(self.sides, per_side_slots):
            arr = np.asarray(slots, np.int64)[-s.cap:]
            if arr.size:
                np.add.at(self.refs, arr, 1)
                np.add.at(s.lane, arr % s.nbl, 1)
                s.push(arr)
        live = np.zeros(self.alloc.capacity, bool)
        for key, slot in self.alloc.snapshot().items():
            live[slot] = True
        gone = np.nonzero(live & (self.refs <= 0))[0]
        if gone.size:
            self.alloc.purge([int(x) for x in gone])
