"""Extension registry: decorator-based equivalent of the reference's
@Extension + classpath scanning (modules/siddhi-annotations/.../Extension.java:56,
CORE/util/SiddhiExtensionLoader.java:58) with the annotation processor's
convention validation (SiddhiAnnotationProcessor.java:56).

Extensions are registered explicitly (Python has no classpath scan):

    @scalar_function("str:length", description="string length",
                     parameters=["value (STRING)"], return_type="INT")
    def str_length(args):  # args: list[CompiledExpr]
        ...returns CompiledExpr
"""
from __future__ import annotations

import dataclasses
import re
from typing import Callable, Dict, List, Optional

from ..exceptions import CompileError

_NAME_RE = re.compile(r"^([A-Za-z][A-Za-z0-9_]*:)?[A-Za-z][A-Za-z0-9_]*$")


@dataclasses.dataclass
class ExtensionMeta:
    """Reference: @Extension(name, namespace, description, parameters,
    returnAttributes) metadata consumed by doc-gen and validation."""

    name: str
    kind: str                      # 'scalar_function' | 'window' | ...
    description: str = ""
    parameters: List[str] = dataclasses.field(default_factory=list)
    return_type: str = ""


_SCALAR_FUNCTIONS: Dict[str, Callable] = {}
_WINDOW_TYPES: Dict[str, type] = {}
_ATTRIBUTE_AGGREGATORS: Dict[str, type] = {}
_SCRIPT_ENGINES: Dict[str, Callable] = {}
_METADATA: Dict[str, ExtensionMeta] = {}


class AttributeAggregator:
    """Custom attribute aggregator SPI (reference: custom @Extension
    AttributeAggregatorExecutors resolved through
    AttributeAggregatorExtensionHolder, CORE/util/extension/holder/
    AttributeAggregatorExtensionHolder.java).

    TPU design: instead of the reference's per-event processAdd/processRemove
    object, a custom aggregator CONTRIBUTES accumulator columns to the
    query's segmented-scan bank (core/selector.py AggregatorBank) — the same
    machinery the 14 built-ins compile into, so customs jit and shard
    identically.  Subclass and implement `build`:

        @attribute_aggregator('custom:geomMean', return_type='DOUBLE')
        class GeomMean(AttributeAggregator):
            def build(self, args, add_spec, expr_key):
                # args: list[CompiledExpr] (compiled call arguments)
                # add_spec(suffix, op, init, dtype, vals_fn) -> spec index;
                #   vals_fn(env, sign) -> [B] per-row contribution, sign is
                #   +1 for CURRENT rows, -1 for EXPIRED (window retraction)
                a = args[0]
                i_log = add_spec('logsum', jnp.add, 0.0, jnp.float32,
                                 lambda env, s: jnp.log(a.fn(env)) * s)
                i_cnt = add_spec('cnt', jnp.add, 0, jnp.int64,
                                 lambda env, s: jnp.asarray(s, jnp.int64))
                def result(res):
                    c = jnp.maximum(res[i_cnt], 1)
                    return jnp.exp(res[i_log] / c.astype(jnp.float32))
                return result

    `result(scan_results)` maps the per-row running accumulator values to
    the output column.  Set `return_type` (SiddhiQL type string) on the
    class or return `(type, result)` from build to override per-call."""

    return_type: str = "DOUBLE"

    def build(self, args, add_spec, expr_key):
        raise NotImplementedError


def _validate(name: str, kind: str, replace: bool) -> None:
    """Reference: SiddhiAnnotationProcessor validates naming conventions
    at compile time; here at registration time."""
    if not _NAME_RE.match(name):
        raise CompileError(
            f"invalid extension name {name!r}: expected "
            f"[namespace:]name with [A-Za-z][A-Za-z0-9_]* segments")
    if replace:
        return
    taken = f"{kind}:{name}" in _METADATA
    if kind == "scalar_function":
        taken = taken or name in _SCALAR_FUNCTIONS
    elif kind == "window":
        # built-ins live in WINDOW_TYPES without metadata entries
        from .window import WINDOW_TYPES
        taken = taken or name in WINDOW_TYPES
    if taken:
        raise CompileError(
            f"extension {name!r} ({kind}) is already registered; pass "
            f"replace=True to override")


def scalar_function(name: str, description: str = "",
                    parameters: Optional[List[str]] = None,
                    return_type: str = "", replace: bool = False):
    def deco(fn):
        _validate(name, "scalar_function", replace)
        _SCALAR_FUNCTIONS[name] = fn
        _METADATA[f"scalar_function:{name}"] = ExtensionMeta(
            name, "scalar_function",
            description or (fn.__doc__ or "").strip().split("\n")[0],
            list(parameters or []), return_type)
        return fn
    return deco


def scalar_function_registry() -> Dict[str, Callable]:
    return _SCALAR_FUNCTIONS


def window_extension(name: str, description: str = "",
                     parameters: Optional[List[str]] = None,
                     replace: bool = False):
    def deco(cls):
        _validate(name, "window", replace)
        from .window import WINDOW_TYPES
        WINDOW_TYPES[name] = cls
        _WINDOW_TYPES[name] = cls
        _METADATA[f"window:{name}"] = ExtensionMeta(
            name, "window",
            description or (cls.__doc__ or "").strip().split("\n")[0],
            list(parameters or []))
        return cls
    return deco


class IncrementalAttributeAggregator:
    """Custom incremental aggregator for `define aggregation` (reference:
    IncrementalAttributeAggregator SPI + its ExtensionHolder; the built-in
    avg is the canonical instance — AvgIncrementalAttributeAggregator
    decomposes into sum+count base attributes, :57-95).

    Subclass and implement `decompose(args, add_base)`:
      - args: list[CompiledExpr] (the compiled call arguments)
      - add_base(kind, value_fn, value_type) -> base index, with kind one
        of 'sum'|'count'|'min'|'max' and value_fn(env) -> [B] values
        (None for count)
      - return (base_indices, finalize) where finalize(cols) maps the
        running base columns (numpy, bucket-major) to the output column.
    Base accumulators merge across duration rollups and shards exactly
    like the built-ins (device slabs, out-of-order, @store rebuild)."""

    return_type: str = "DOUBLE"

    def decompose(self, args, add_base):
        raise NotImplementedError


_INCREMENTAL_AGGREGATORS: Dict[str, type] = {}


def incremental_attribute_aggregator(name: str, return_type: str = "",
                                     description: str = "",
                                     replace: bool = False):
    """Register a custom incremental aggregator usable from
    `define aggregation ... select namespace:name(x) as y ...`."""
    def deco(cls):
        if not (isinstance(cls, type) and
                issubclass(cls, IncrementalAttributeAggregator)):
            raise CompileError(
                f"{name!r}: incremental aggregators subclass "
                f"IncrementalAttributeAggregator")
        if ":" not in name:
            # the aggregation compiler resolves ONLY namespaced calls
            # (bare names are the built-in sum/count/avg/min/max); a bare
            # registration would be permanently unreachable
            raise CompileError(
                f"incremental aggregator {name!r} needs a 'namespace:name' "
                f"form")
        _validate(name, "incremental_aggregator", replace)
        if return_type:
            cls.return_type = return_type.upper()
        _INCREMENTAL_AGGREGATORS[name] = cls
        _METADATA[f"incremental_aggregator:{name}"] = ExtensionMeta(
            name, "incremental_aggregator",
            description or (cls.__doc__ or "").strip().split("\n")[0],
            [], cls.return_type)
        return cls
    return deco


def incremental_aggregator_registry() -> Dict[str, type]:
    return _INCREMENTAL_AGGREGATORS


def distribution_strategy(name: str, description: str = "",
                          replace: bool = False):
    """Register a custom @distribution(strategy='<name>') router
    (reference: DistributionStrategy SPI via its ExtensionHolder)."""
    def deco(cls):
        from ..io.sink import DIST_STRATEGIES, DistributionStrategy as _Base
        if not (isinstance(cls, type) and issubclass(cls, _Base)):
            raise CompileError(
                f"{name!r}: distribution strategies subclass "
                f"io.sink.DistributionStrategy")
        _validate(name, "distribution_strategy", replace)
        if not replace and name.lower() in DIST_STRATEGIES:
            raise CompileError(
                f"distribution strategy {name!r} is already registered; "
                f"pass replace=True to override")
        DIST_STRATEGIES[name.lower()] = cls
        _METADATA[f"distribution_strategy:{name}"] = ExtensionMeta(
            name, "distribution_strategy",
            description or (cls.__doc__ or "").strip().split("\n")[0])
        return cls
    return deco


def attribute_aggregator(name: str, return_type: str = "",
                         description: str = "",
                         parameters: Optional[List[str]] = None,
                         replace: bool = False):
    """Register a custom attribute aggregator usable from SiddhiQL as
    `namespace:name(args)` in select/having clauses."""
    def deco(cls):
        if not (isinstance(cls, type) and
                issubclass(cls, AttributeAggregator)):
            raise CompileError(
                f"{name!r}: attribute aggregators subclass "
                f"AttributeAggregator")
        _validate(name, "attribute_aggregator", replace)
        if not replace:
            from .executor import AGGREGATOR_NAMES
            if name in _ATTRIBUTE_AGGREGATORS or name in AGGREGATOR_NAMES:
                raise CompileError(
                    f"aggregator {name!r} is already registered; pass "
                    f"replace=True to override")
        if return_type:
            cls.return_type = return_type.upper()
        _ATTRIBUTE_AGGREGATORS[name] = cls
        _METADATA[f"attribute_aggregator:{name}"] = ExtensionMeta(
            name, "attribute_aggregator",
            description or (cls.__doc__ or "").strip().split("\n")[0],
            list(parameters or []), cls.return_type)
        return cls
    return deco


def attribute_aggregator_registry() -> Dict[str, type]:
    return _ATTRIBUTE_AGGREGATORS


def source_mapper(name: str, description: str = "", replace: bool = False):
    """Register a custom @map(type='<name>') payload->events mapper
    (reference: custom SourceMapper @Extensions via
    SourceMapperExtensionHolder)."""
    def deco(cls):
        from ..io.mappers import SOURCE_MAPPERS, SourceMapper as _Base
        if not (isinstance(cls, type) and issubclass(cls, _Base)):
            raise CompileError(
                f"{name!r}: source mappers subclass io.mappers.SourceMapper")
        _validate(name, "source_mapper", replace)
        if not replace and name in SOURCE_MAPPERS:
            raise CompileError(
                f"source mapper {name!r} is already registered; pass "
                f"replace=True to override")
        SOURCE_MAPPERS[name] = cls
        _METADATA[f"source_mapper:{name}"] = ExtensionMeta(
            name, "source_mapper",
            description or (cls.__doc__ or "").strip().split("\n")[0])
        return cls
    return deco


def sink_mapper(name: str, description: str = "", replace: bool = False):
    """Register a custom @map(type='<name>') events->payload mapper
    (reference: custom SinkMapper @Extensions via
    SinkMapperExtensionHolder)."""
    def deco(cls):
        from ..io.mappers import SINK_MAPPERS, SinkMapper as _Base
        if not (isinstance(cls, type) and issubclass(cls, _Base)):
            raise CompileError(
                f"{name!r}: sink mappers subclass io.mappers.SinkMapper")
        _validate(name, "sink_mapper", replace)
        if not replace and name in SINK_MAPPERS:
            raise CompileError(
                f"sink mapper {name!r} is already registered; pass "
                f"replace=True to override")
        SINK_MAPPERS[name] = cls
        _METADATA[f"sink_mapper:{name}"] = ExtensionMeta(
            name, "sink_mapper",
            description or (cls.__doc__ or "").strip().split("\n")[0])
        return cls
    return deco


def script_engine(language: str, replace: bool = False):
    """Register a `define function f[<language>] ...` script engine
    (reference: Script extension type via ScriptExtensionHolder; core ships
    javascript — here python is built in and other engines plug in).

    The decorated callable receives the FunctionDefinition and returns a
    host callable fn(data: list) -> value, invoked per row batch via
    jax.pure_callback."""
    def deco(fn):
        key = language.lower()
        if not replace and key in _SCRIPT_ENGINES:
            raise CompileError(
                f"script engine {language!r} is already registered; pass "
                f"replace=True to override")
        _SCRIPT_ENGINES[key] = fn
        _METADATA[f"script_engine:{key}"] = ExtensionMeta(
            key, "script_engine",
            (fn.__doc__ or "").strip().split("\n")[0])
        return fn
    return deco


def script_engine_registry() -> Dict[str, Callable]:
    return _SCRIPT_ENGINES


def extension_metadata() -> Dict[str, ExtensionMeta]:
    """All registered extension metadata (doc-gen input)."""
    return dict(_METADATA)
