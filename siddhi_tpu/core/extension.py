"""Extension registry: decorator-based equivalent of the reference's
@Extension + classpath scanning (modules/siddhi-annotations/.../Extension.java:56,
CORE/util/SiddhiExtensionLoader.java:58).

Extensions are registered explicitly (Python has no classpath scan):

    @scalar_function("str:length", return_type="INT")
    def str_length(args):  # args: list[CompiledExpr]
        ...returns CompiledExpr
"""
from __future__ import annotations

from typing import Callable, Dict

_SCALAR_FUNCTIONS: Dict[str, Callable] = {}
_WINDOW_TYPES: Dict[str, type] = {}


def scalar_function(name: str):
    def deco(fn):
        _SCALAR_FUNCTIONS[name] = fn
        return fn
    return deco


def scalar_function_registry() -> Dict[str, Callable]:
    return _SCALAR_FUNCTIONS


def window_extension(name: str):
    def deco(cls):
        from .window import WINDOW_TYPES
        WINDOW_TYPES[name] = cls
        _WINDOW_TYPES[name] = cls
        return cls
    return deco
