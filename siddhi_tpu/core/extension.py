"""Extension registry: decorator-based equivalent of the reference's
@Extension + classpath scanning (modules/siddhi-annotations/.../Extension.java:56,
CORE/util/SiddhiExtensionLoader.java:58) with the annotation processor's
convention validation (SiddhiAnnotationProcessor.java:56).

Extensions are registered explicitly (Python has no classpath scan):

    @scalar_function("str:length", description="string length",
                     parameters=["value (STRING)"], return_type="INT")
    def str_length(args):  # args: list[CompiledExpr]
        ...returns CompiledExpr
"""
from __future__ import annotations

import dataclasses
import re
from typing import Callable, Dict, List, Optional

from ..exceptions import CompileError

_NAME_RE = re.compile(r"^([A-Za-z][A-Za-z0-9_]*:)?[A-Za-z][A-Za-z0-9_]*$")


@dataclasses.dataclass
class ExtensionMeta:
    """Reference: @Extension(name, namespace, description, parameters,
    returnAttributes) metadata consumed by doc-gen and validation."""

    name: str
    kind: str                      # 'scalar_function' | 'window' | ...
    description: str = ""
    parameters: List[str] = dataclasses.field(default_factory=list)
    return_type: str = ""


_SCALAR_FUNCTIONS: Dict[str, Callable] = {}
_WINDOW_TYPES: Dict[str, type] = {}
_METADATA: Dict[str, ExtensionMeta] = {}


def _validate(name: str, kind: str, replace: bool) -> None:
    """Reference: SiddhiAnnotationProcessor validates naming conventions
    at compile time; here at registration time."""
    if not _NAME_RE.match(name):
        raise CompileError(
            f"invalid extension name {name!r}: expected "
            f"[namespace:]name with [A-Za-z][A-Za-z0-9_]* segments")
    if replace:
        return
    taken = f"{kind}:{name}" in _METADATA
    if kind == "scalar_function":
        taken = taken or name in _SCALAR_FUNCTIONS
    elif kind == "window":
        # built-ins live in WINDOW_TYPES without metadata entries
        from .window import WINDOW_TYPES
        taken = taken or name in WINDOW_TYPES
    if taken:
        raise CompileError(
            f"extension {name!r} ({kind}) is already registered; pass "
            f"replace=True to override")


def scalar_function(name: str, description: str = "",
                    parameters: Optional[List[str]] = None,
                    return_type: str = "", replace: bool = False):
    def deco(fn):
        _validate(name, "scalar_function", replace)
        _SCALAR_FUNCTIONS[name] = fn
        _METADATA[f"scalar_function:{name}"] = ExtensionMeta(
            name, "scalar_function",
            description or (fn.__doc__ or "").strip().split("\n")[0],
            list(parameters or []), return_type)
        return fn
    return deco


def scalar_function_registry() -> Dict[str, Callable]:
    return _SCALAR_FUNCTIONS


def window_extension(name: str, description: str = "",
                     parameters: Optional[List[str]] = None,
                     replace: bool = False):
    def deco(cls):
        _validate(name, "window", replace)
        from .window import WINDOW_TYPES
        WINDOW_TYPES[name] = cls
        _WINDOW_TYPES[name] = cls
        _METADATA[f"window:{name}"] = ExtensionMeta(
            name, "window",
            description or (cls.__doc__ or "").strip().split("\n")[0],
            list(parameters or []))
        return cls
    return deco


def extension_metadata() -> Dict[str, ExtensionMeta]:
    """All registered extension metadata (doc-gen input)."""
    return dict(_METADATA)
