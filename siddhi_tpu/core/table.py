"""In-memory tables: device-resident columnar event stores.

Reference behavior (what): CORE/table/InMemoryTable.java:58 +
IndexEventHolder (CORE/table/holder/IndexEventHolder.java:60 — primary key +
index maps), operators under CORE/util/collection/* (find/contains/update/
delete/update-or-insert with compiled conditions), and EventHolderPasser
(@PrimaryKey/@Index).

TPU-native design (how): a table is a fixed-capacity struct-of-arrays block
on device.  @PrimaryKey rows map to dense slots through the host
SlotAllocator (O(new keys) python, vectorized lookups), so keyed
insert/update/upsert are row scatters; conditions compile to masked [B, C]
broadcasts (stream rows x table rows) evaluated on device — the reference's
per-event TreeMap probes become one fused comparison kernel.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..query_api.definition import TableDefinition
from ..query_api.expression import Expression
from . import event as ev
from .executor import CompiledExpr, Scope, compile_expression
from .keyslots import SlotAllocator
from .table_index import AttributeIndex, IndexPlan, split_index_condition
from .steputil import jit_step


class TableCondition:
    """A compiled table condition + optional index plan (reference:
    CollectionExpressionParser.java splits a condition into an indexed probe
    and an exhaustive residual). `compiled` always holds the full dense
    condition (fallback + join path)."""

    def __init__(self, compiled: CompiledExpr,
                 plan: Optional[IndexPlan] = None,
                 rhs_fn=None, residual_fn=None):
        self.compiled = compiled
        self.plan = plan
        self.rhs_fn = rhs_fn
        self.residual_fn = residual_fn

    # CompiledExpr duck-typing for callers that pass this to match_matrix
    @property
    def fn(self):
        return self.compiled.fn

    @property
    def type(self):
        return self.compiled.type


class TableRuntime:
    def __init__(self, definition: TableDefinition, schema: ev.Schema,
                 capacity: int = 4096):
        self.definition = definition
        self.schema = schema
        cap_ann = definition.get_annotation("capacity")
        if cap_ann:
            capacity = int(cap_ann.element("rows", capacity))
        self.capacity = capacity
        self._lock = threading.RLock()

        pk = definition.get_annotation("PrimaryKey")
        self.pkey_positions: Optional[List[int]] = None
        self.allocator: Optional[SlotAllocator] = None
        if pk is not None:
            names = pk.positional_elements()
            self.pkey_positions = [schema.position(n) for n in names]
            self.allocator = SlotAllocator(capacity,
                                           name=f"table:{definition.id}")
        # @Index('a', 'b') declares one secondary index per attribute
        # (reference: IndexEventHolder.java:65-66, EventHolderPasser.java:48)
        self.indexes: Dict[int, AttributeIndex] = {}
        idx_ann = definition.get_annotation("Index")
        if idx_ann is not None:
            for n in idx_ann.positional_elements():
                p = schema.position(n)
                if self.pkey_positions == [p]:
                    continue  # the primary key is already an index
                self.indexes[p] = AttributeIndex(
                    capacity, ev.np_dtype(schema.types[p]),
                    name=f"{definition.id}.{n}")
        self.index_stats = {"indexed": 0, "dense": 0}
        # device state
        self.cols = tuple(
            jnp.full((capacity,), ev.default_value(t), dtype=d)
            for t, d in zip(schema.types, schema.dtypes))
        self.ts = jnp.zeros((capacity,), jnp.int64)
        self.valid = jnp.zeros((capacity,), jnp.bool_)
        self._append_ptr = 0  # non-keyed append position (host-tracked)
        self._free_rows: List[int] = []

        self._jit_write = jit_step(self._write_impl,
                                   owner=f"table:{definition.id}",
                                   donate_argnums=(0, 1, 2))
        self._jit_masked_delete = jit_step(self._masked_delete_impl,
                                          owner=f"table:{definition.id}",
                                          donate_argnums=(0,))

    # -- row-slot resolution ---------------------------------------------------
    def _slots_for_batch(self, staged_cols: Sequence[np.ndarray],
                         valid: np.ndarray, insert: bool) -> np.ndarray:
        """Target row per batch event (primary-key tables)."""
        key_cols = [staged_cols[i] for i in self.pkey_positions]
        if insert:
            return self.allocator.slots_for(key_cols, valid)
        # lookup-only: unknown keys -> -1, nothing is allocated (reference:
        # find/contains never mutate, CORE/table/holder/IndexEventHolder.java)
        return self.allocator.slots_for(key_cols, valid, lookup_only=True)

    def _append_slots(self, n: int) -> np.ndarray:
        out = np.empty((n,), np.int32)
        for i in range(n):
            if self._free_rows:
                out[i] = self._free_rows.pop()
            else:
                if self._append_ptr >= self.capacity:
                    raise RuntimeError(
                        f"table {self.definition.id!r} capacity "
                        f"{self.capacity} exhausted; use "
                        f"@capacity(rows='...')")
                out[i] = self._append_ptr
                self._append_ptr += 1
        return out

    # -- device ops ------------------------------------------------------------
    @staticmethod
    def _write_impl(cols, ts, valid, new_cols, new_ts, slots, row_valid):
        tgt = jnp.where(row_valid, slots, jnp.iinfo(jnp.int32).max)
        # incoming batches may carry wider dtypes than the table column
        # (on-demand #sel stages ints as LONG): cast at the boundary
        cols = tuple(c.at[tgt].set(jnp.asarray(nc, c.dtype), mode="drop")
                     for c, nc in zip(cols, new_cols))
        ts = ts.at[tgt].set(new_ts, mode="drop")
        valid = valid.at[tgt].set(True, mode="drop")
        return cols, ts, valid

    @staticmethod
    def _masked_delete_impl(valid, kill):
        return jnp.logical_and(valid, jnp.logical_not(kill))

    # -- public API ------------------------------------------------------------
    def _materialize_uuids(self, batch: ev.EventBatch,
                           staged: ev.StagedBatch):
        """UUID() sentinels must become real interned strings at the storage
        boundary — a stored sentinel would decode to a different id on every
        read (reference: one UUID per event, UUIDFunctionExecutor)."""
        changed = ev.materialize_uuid_sentinels(
            self.schema, np.asarray(staged.valid), staged.cols)
        if not changed:
            return batch
        new_batch_cols = list(batch.cols)
        for pos, col in changed:
            scols = list(staged.cols)
            scols[pos] = col
            staged.cols = scols
            new_batch_cols[pos] = jnp.asarray(col).astype(
                batch.cols[pos].dtype)
        return batch.with_cols(new_batch_cols)

    def _materialize_uuid_col(self, val, hit):
        """`set T.s = UUID()` writes the sentinel; stored cells must hold
        REAL interned ids or every read mints a different uuid (same
        contract as _materialize_uuids on the insert path)."""
        vnp = np.asarray(val)
        mask = np.asarray(hit) & (vnp == ev.UUID_SENTINEL)
        if not mask.any():
            return val
        return jnp.asarray(
            ev.fill_uuid_cells(self.schema.interner, vnp, mask))

    def insert(self, batch: ev.EventBatch, staged: ev.StagedBatch) -> None:
        """Insert CURRENT rows (keyed: upsert on primary key; else append)."""
        with self._lock:
            n = int(np.sum(staged.valid))
            if n == 0:
                return
            batch = self._materialize_uuids(batch, staged)
            if self.pkey_positions is not None:
                slots = self._slots_for_batch(staged.cols, staged.valid, True)
            else:
                slots = np.full((staged.valid.shape[0],), -1, np.int32)
                slots[staged.valid] = self._append_slots(n)
            if self.indexes:
                mask = staged.valid & (slots >= 0)
                rows = slots[mask].astype(np.int64)
                for pos, idx in self.indexes.items():
                    idx.on_write(rows, np.asarray(staged.cols[pos])[mask])
            self.cols, self.ts, self.valid = self._jit_write(
                self.cols, self.ts, self.valid, batch.cols, batch.ts,
                jnp.asarray(slots), jnp.asarray(staged.valid))

    def compile_condition(self, cond: Expression, other_schema: ev.Schema,
                          other_key: str, interner) -> CompiledExpr:
        """Compile `on` condition over (stream rows [B,1], table rows [1,C])."""
        scope = Scope()
        scope.interner = interner
        scope.add_source(self.definition.id, self.schema)
        scope.add_source(other_key, other_schema)
        return compile_expression(cond, scope)

    def plan_condition(self, cond_expr: Expression, scope: Scope,
                       table_id: Optional[str] = None,
                       unqualified_is_table: bool = False,
                       ) -> TableCondition:
        """Compile a table condition with index-aware planning: if one AND-
        conjunct is `table.attr == <stream expr>` on an indexed attribute (or
        a single-column primary key), later matches probe that index instead
        of the dense [B, C] broadcast (reference:
        CollectionExpressionParser.java; IndexOperator.java).

        `table_id`/`unqualified_is_table` override the reference scoping for
        on-demand store queries (alias id, bare names bind to the store)."""
        compiled = compile_expression(cond_expr, scope)
        probe_positions = list(self.indexes)
        if self.pkey_positions is not None and len(self.pkey_positions) == 1:
            probe_positions.append(self.pkey_positions[0])
        plan = None
        if probe_positions:
            plan = split_index_condition(
                cond_expr, table_id or self.definition.id, self.schema,
                probe_positions, unqualified_is_table=unqualified_is_table)
        if plan is None:
            return TableCondition(compiled)
        if plan.kind == "range" and plan.pos not in self.indexes:
            return TableCondition(compiled)  # pkey has no sorted view
        rhs_fn = compile_expression(plan.rhs, scope).fn
        residual_fn = (compile_expression(plan.residual, scope).fn
                       if plan.residual is not None else None)
        return TableCondition(compiled, plan, rhs_fn, residual_fn)

    def _probe_candidates(self, pos: int, values: np.ndarray):
        """values [B] -> (cand [B, K] int32, ok [B, K] bool)."""
        values = np.asarray(values).astype(
            ev.np_dtype(self.schema.types[pos]))
        if pos in self.indexes:
            return self.indexes[pos].probe_eq(values)
        # single-column primary key: the slot allocator IS the index
        slots = self.allocator.slots_for(
            [np.ascontiguousarray(values)],
            np.ones(values.shape[0], bool), lookup_only=True)
        cand = slots.astype(np.int32)[:, None]
        return cand, cand >= 0

    def probe_rows(self, pos: int, values: np.ndarray):
        """Public index probe for the equi-join fast path (and tests):
        candidate row ids per value via the @Index lane table or the
        primary-key allocator — one vectorized lookup, no device work.
        Candidates narrow; the caller's full-condition re-check decides
        (exactly the `_match` contract)."""
        self.index_stats["indexed"] += 1
        return self._probe_candidates(pos, values)

    def _match(self, cond, other_key: str, batch: ev.EventBatch,
               staged: Optional[ev.StagedBatch] = None):
        """Unified match for delete/update paths.

        Returns (hit [C] bool, src [C] int last-matching-stream-row — device
        arrays on the dense path, host on the indexed path — and
        matched_any(), a thunk for the [B] per-stream-row hit mask so the
        dense path pays no device sync unless upsert needs it)."""
        C = self.capacity
        plan = cond.plan if isinstance(cond, TableCondition) else None
        if plan is None or plan.kind != "eq":
            self.index_stats["dense"] += 1
            m = self.match_matrix(cond, other_key, batch)      # [B, C]
            hit = jnp.any(m, axis=0)
            B = m.shape[0]
            rowid = jnp.arange(B)[:, None]
            src = jnp.max(jnp.where(m, rowid, -1), axis=0)
            return hit, src, lambda: np.asarray(jnp.any(m, axis=1))
        self.index_stats["indexed"] += 1
        # stream-side key values: [B] on host (staged cols when available,
        # else one small device read)
        if staged is not None:
            env_np = {other_key: tuple(staged.cols), "__ts__": staged.ts}
            vals = np.asarray(cond.rhs_fn(env_np))
        else:
            env_d = {other_key: batch.cols, "__ts__": batch.ts}
            vals = np.asarray(cond.rhs_fn(env_d))
        if vals.ndim == 0:
            vals = np.broadcast_to(vals, (batch.ts.shape[0],))
        cand, ok = self._probe_candidates(plan.pos, vals)       # [B, K]
        bvalid = np.asarray(batch.valid)
        ok = ok & bvalid[:, None]
        if ok.any():
            tvalid = np.asarray(self.valid)
            safe = np.clip(cand, 0, C - 1)
            ok = ok & tvalid[safe]
        if ok.any():
            # re-evaluate the FULL condition on the gathered candidates:
            # the hash probe only narrows, it never decides — this keeps
            # exact dense `==` semantics under dtype casts (LONG rhs vs INT
            # column) and hash-collision corner cases
            safe = jnp.asarray(np.clip(cand, 0, C - 1))
            env = {
                self.definition.id: tuple(c[safe] for c in self.cols),
                other_key: tuple(c[:, None] for c in batch.cols),
                "__ts__": batch.ts[:, None],
            }
            ok = ok & np.asarray(cond.compiled.fn(env)).astype(bool)
        hit = np.zeros(C, bool)
        src = np.full(C, -1, np.int64)
        rows = cand[ok]
        if rows.size:
            hit[rows] = True
            bs = np.broadcast_to(
                np.arange(ok.shape[0], dtype=np.int64)[:, None],
                ok.shape)[ok]
            np.maximum.at(src, rows, bs)
        return hit, src, lambda: ok.any(axis=1)

    def match_matrix(self, compiled: CompiledExpr, other_key: str,
                     batch: ev.EventBatch):
        """[B, C] boolean matches (pure; caller jits)."""
        env = {
            self.definition.id: tuple(c[None, :] for c in self.cols),
            other_key: tuple(c[:, None] for c in batch.cols),
            "__ts__": batch.ts[:, None],
        }
        m = compiled.fn(env)
        m = jnp.logical_and(m, self.valid[None, :])
        m = jnp.logical_and(m, batch.valid[:, None])
        return m

    def delete_where(self, compiled: CompiledExpr, other_key: str,
                     batch: ev.EventBatch, staged=None) -> None:
        with self._lock:
            kill, _, _ = self._match(compiled, other_key, batch, staged)
            self.valid = self._jit_masked_delete(self.valid,
                                                 jnp.asarray(kill))
            self._reclaim(np.asarray(kill))

    def _reclaim(self, kill) -> None:
        killed = np.nonzero(np.asarray(kill))[0]
        if self.pkey_positions is not None:
            if killed.size:
                self.allocator.purge(killed.tolist())
        else:
            self._free_rows.extend(int(x) for x in killed)
        if killed.size:
            for idx in self.indexes.values():
                idx.on_delete(killed)

    def update_where(self, compiled: CompiledExpr, other_key: str,
                     batch: ev.EventBatch,
                     set_fns: List[Tuple[int, Callable]],
                     upsert: bool = False,
                     staged: Optional[ev.StagedBatch] = None,
                     insert_map: Optional[List[int]] = None) -> None:
        """set_fns: [(table_col_pos, fn(env)->[B] value)], applied from the
        LAST matching stream row per table row (batch order semantics)."""
        with self._lock:
            hit, src, matched_any = self._match(
                compiled, other_key, batch, staged)
            hit = jnp.asarray(hit)                              # [C]
            src_c = jnp.clip(jnp.asarray(src), 0, batch.ts.shape[0] - 1)
            env = {
                other_key: tuple(c[src_c] for c in batch.cols),
                self.definition.id: self.cols,
                "__ts__": batch.ts[src_c],
            }
            new_cols = list(self.cols)
            # index maintenance needs host rows only when a set expression
            # actually writes an indexed column (the sync is not free)
            touches_index = any(pos in self.indexes for pos, _ in set_fns)
            hit_rows = (np.nonzero(np.asarray(hit))[0]
                        if touches_index else None)
            for pos, fn in set_fns:
                val = jnp.asarray(fn(env))
                if val.ndim == 0:        # constant set expressions are 0-d
                    val = jnp.broadcast_to(val, (self.capacity,))
                if self.schema.types[pos] == "STRING":
                    val = self._materialize_uuid_col(val, hit)
                new_cols[pos] = jnp.where(hit, val.astype(self.cols[pos].dtype),
                                          self.cols[pos])
                if pos in self.indexes and hit_rows is not None \
                        and hit_rows.size:
                    self.indexes[pos].on_write(
                        hit_rows, np.asarray(val)[hit_rows])
            self.cols = tuple(new_cols)
            if upsert and staged is not None:
                miss = staged.valid & ~matched_any()
                if miss.any():
                    sub_staged = ev.StagedBatch(
                        staged.ts, staged.kind, miss,
                        [staged.cols[i] for i in insert_map]
                        if insert_map else staged.cols, int(miss.sum()))
                    sub_batch = ev.EventBatch(
                        batch.ts, batch.kind, jnp.asarray(miss),
                        tuple(batch.cols[i] for i in insert_map)
                        if insert_map else batch.cols)
                    self.insert(sub_batch, sub_staged)

    def snapshot_rows(self) -> List[ev.Event]:
        with self._lock:
            batch = ev.EventBatch(self.ts, jnp.zeros_like(self.ts,
                                                          dtype=jnp.int32),
                                  self.valid, self.cols)
            return [e for _, e in ev.unpack(self.schema, batch)]

    # find for on-demand queries / joins
    def all_rows_batch(self) -> ev.EventBatch:
        return ev.EventBatch(self.ts,
                             jnp.zeros(self.ts.shape, jnp.int32),
                             self.valid, self.cols)


class RecordTableRuntime(TableRuntime):
    """`@store(type='...')` table: an external RecordTable store stays
    authoritative while its rows are mirrored into the device-resident
    columnar table, so joins/filters run on the TPU and writes flow through
    the store SPI (reference: AbstractRecordTable.java:449; cache layer
    CacheTable.java:62).

    The mirror is preloaded at startup (reference:
    AbstractQueryableRecordTable pre-load) and kept in sync write-through.
    """

    def __init__(self, definition, schema, store, interner,
                 cache=None, capacity: int = 4096):
        from ..io.store import connect_with_retry
        super().__init__(definition, schema, capacity)
        self.store = store
        self.cache = cache
        self._interner = interner
        connect_with_retry(store, definition.id)
        rows = store.read_all()
        if rows:
            self._mirror_insert(rows)

    # -- encode/decode ---------------------------------------------------------
    def _decode_row(self, vals) -> tuple:
        out = []
        for v, t in zip(vals, self.schema.types):
            if t == "STRING":
                out.append(self._interner.lookup(int(v)))
            elif t in ("INT", "LONG"):
                out.append(int(v))
            elif t in ("FLOAT", "DOUBLE"):
                out.append(float(v))
            elif t == "BOOL":
                out.append(bool(v))
            else:
                out.append(v)
        return tuple(out)

    def _decode_staged(self, staged) -> List[tuple]:
        idx = np.nonzero(staged.valid)[0]
        return [self._decode_row([c[i] for c in staged.cols])
                for i in idx]

    def _decode_mirror(self, mask: np.ndarray) -> List[tuple]:
        cols = [np.asarray(c) for c in self.cols]
        return [self._decode_row([c[i] for c in cols])
                for i in np.nonzero(mask)[0]]

    def _mirror_insert(self, rows: List[tuple]) -> None:
        """Load store rows into the device mirror without re-adding them."""
        enc_cols = []
        for j, t in enumerate(self.schema.types):
            vals = [r[j] for r in rows]
            if t == "STRING":
                vals = [self._interner.intern(v) for v in vals]
            enc_cols.append(np.asarray(vals, ev.np_dtype(t)))
        n = len(rows)
        staged = ev.StagedBatch(
            np.zeros(n, np.int64), np.zeros(n, np.int8),
            np.ones(n, bool), enc_cols, n)
        batch = ev.EventBatch(
            jnp.zeros(n, jnp.int64), jnp.zeros(n, jnp.int32),
            jnp.ones(n, jnp.bool_),
            tuple(jnp.asarray(c).astype(d)
                  for c, d in zip(enc_cols, self.schema.dtypes)))
        super().insert(batch, staged)

    # -- write-through ops -----------------------------------------------------
    def insert(self, batch, staged) -> None:
        rows = self._decode_staged(staged)
        if rows:
            self.store.add(rows)
            if self.cache is not None:
                self.cache.on_add(rows)
        super().insert(batch, staged)

    def delete_where(self, compiled, other_key, batch, staged=None) -> None:
        with self._lock:
            kill, _, _ = self._match(compiled, other_key, batch, staged)
            kill = np.asarray(kill)
            rows = self._decode_mirror(kill & np.asarray(self.valid))
            if rows:
                self.store.delete_rows(rows)
                if self.cache is not None:
                    self.cache.on_delete(rows)
            self.valid = self._jit_masked_delete(self.valid, jnp.asarray(kill))
            self._reclaim(kill)

    def update_where(self, compiled, other_key, batch, set_fns,
                     upsert=False, staged=None, insert_map=None) -> None:
        with self._lock:
            hit, _, _ = self._match(compiled, other_key, batch, staged)
            hit = np.asarray(hit) & np.asarray(self.valid)
            old_rows = self._decode_mirror(hit)
        super().update_where(compiled, other_key, batch, set_fns,
                             upsert=upsert, staged=staged,
                             insert_map=insert_map)
        with self._lock:
            new_rows = self._decode_mirror(hit)
        if old_rows:
            self.store.update_rows(old_rows, new_rows)
            if self.cache is not None:
                self.cache.on_update(old_rows, new_rows)


def _table_state(t: TableRuntime) -> Dict:
    """Host snapshot of a table's device state (reference: InMemoryTable
    state; record tables rebuild their mirror from the store on restore)."""
    if isinstance(t, RecordTableRuntime):
        return {"record": True}
    return {
        "record": False,
        "cols": [np.asarray(c) for c in t.cols],
        "ts": np.asarray(t.ts),
        "valid": np.asarray(t.valid),
        "append_ptr": t._append_ptr,
        "free_rows": list(t._free_rows),
        "slots": t.allocator.snapshot() if t.allocator else None,
    }


def _restore_table_state(t: TableRuntime, data: Dict) -> None:
    if data.get("record"):
        return
    with t._lock:
        t.cols = tuple(jnp.asarray(c).astype(d)
                       for c, d in zip(data["cols"], t.schema.dtypes))
        t.ts = jnp.asarray(data["ts"])
        t.valid = jnp.asarray(data["valid"])
        t._append_ptr = data["append_ptr"]
        t._free_rows = list(data["free_rows"])
        if data["slots"] is not None and t.allocator:
            t.allocator.restore(data["slots"])
        valid = np.asarray(t.valid)
        for pos, idx in t.indexes.items():
            idx.rebuild(np.asarray(t.cols[pos]), valid)
