"""Query selector: projection, group-by aggregation, having, order-by/limit.

Reference behavior (what): CORE/query/selector/QuerySelector.java:44 — per
event: update aggregators (keyed by group-by key), evaluate select
expressions, apply having, order-by/limit per chunk; EXPIRED events subtract
from aggregators, RESET events clear them (batch windows).
Attribute aggregators: CORE/query/selector/attribute/aggregator/*.

TPU-native design (how): rows arrive seq-ordered with a precomputed group
slot id per row (host-side vectorized key->slot allocation, see
core/keyslots.py).  Running aggregate values — Siddhi's "value after this
event's update" semantics — are computed with *segmented associative scans*:
rows are stably sorted by (group slot, reset epoch), an inclusive
associative scan runs per segment, carry-in state is injected at segment
heads, and results are unsorted back.  O(B log B), no per-event control flow,
exact sequential semantics.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp
from jax import lax

from ..query_api.expression import (
    AttributeFunction,
    Compare,
    Constant,
    Expression,
    Variable,
    Add,
    Subtract,
    Multiply,
    Divide,
    Mod,
    And,
    Or,
    Not,
    IsNull,
    In,
)
from ..query_api.query import Selector
from . import event as ev
from .executor import (
    AGGREGATOR_NAMES,
    CompileError,
    CompiledExpr,
    Scope,
    compile_expression,
)
from .window import Rows

BIG = jnp.iinfo(jnp.int64).max // 4


# ---------------------------------------------------------------------------
# segmented inclusive scan over seg-sorted rows
# ---------------------------------------------------------------------------

def _segmented_scan(vals, segs, op):
    """Inclusive scan of `op` within runs of equal `segs` (must be sorted)."""
    def combine(a, b):
        va, sa = a
        vb, sb = b
        return jnp.where(sa == sb, op(va, vb), vb), sb
    out, _ = lax.associative_scan(combine, (vals, segs))
    return out


@dataclasses.dataclass
class _AggSpec:
    """One physical accumulator column (a scan over signed contributions)."""

    key: str                      # dedupe key
    op: Callable                  # associative op
    init: Any                     # identity scalar
    dtype: Any
    # vals_fn(env, sign) -> [B] contribution per row; may read
    # env['__scanres__'][i] (running values of earlier specs)
    vals_fn: Callable
    # segment by a pair-slot column (env['__pslot__<j>']) instead of the
    # group slot — used by distinctCount's per-(group, value) refcounts
    slot_src: Optional[int] = None
    K_override: Optional[int] = None


class AggregatorBank:
    """Compiles all aggregator calls of a query into a set of scan columns
    plus per-slot carry state [K]."""

    def __init__(self, group_slots: int):
        self.K = group_slots
        self.specs: List[_AggSpec] = []
        self._index: Dict[str, int] = {}
        # distinctCount: Variables whose (group, value) pairs get host
        # slot allocation; planner resolves them to column positions
        self.pair_sources: List[Variable] = []

    def _add(self, spec: _AggSpec) -> int:
        if spec.key in self._index:
            return self._index[spec.key]
        self._index[spec.key] = len(self.specs)
        self.specs.append(spec)
        return len(self.specs) - 1

    def init_state(self):
        return tuple(
            jnp.full((s.K_override or self.K,), s.init, dtype=s.dtype)
            for s in self.specs)

    # -- aggregator compilation ----------------------------------------------
    def compile_call(self, fn_expr: AttributeFunction, scope: Scope,
                     expr_key: str) -> Tuple[str, Callable, str]:
        """Returns (result_type, result_fn(scan_results)->array, name).
        `scan_results` is the tuple of per-row running values, one per spec."""
        name = fn_expr.name
        full = f"{fn_expr.namespace}:{name}" if fn_expr.namespace else name
        from .extension import attribute_aggregator_registry
        ext = attribute_aggregator_registry().get(full)
        if ext is not None:
            # custom aggregator: contributes scan columns through the same
            # bank as the built-ins (jits and shards identically)
            ext_args = [compile_expression(p, scope)
                        for p in fn_expr.parameters]

            def add_spec(suffix, op, init, dtype, vals_fn,
                         _full=full, _key=expr_key):
                return self._add(_AggSpec(
                    f"{_full}:{suffix}:{_key}", op, init, dtype, vals_fn))

            built = ext().build(ext_args, add_spec, expr_key)
            if isinstance(built, tuple):
                out_t, result = built
            else:
                out_t, result = ext.return_type, built
            return out_t.upper(), result, full
        if name == "distinctCount":
            orig = fn_expr.parameters[0]
            if not isinstance(orig, Variable):
                raise CompileError(
                    "distinctCount needs a plain attribute argument")
            i_dc = self._distinct_spec(orig, expr_key)
            return "LONG", (lambda res, _i=i_dc: res[_i]), name
        if name == "unionSet":
            # reference: UnionSetAttributeAggregatorExecutor over
            # createSet(attr) values.  The set itself cannot materialize in
            # a columnar output; sizeOfSet(unionSet(createSet(x))) — the
            # reference's canonical composition — maps onto the exact
            # distinct machinery, so the 'SET' pseudo-value carries the
            # running distinct count.  (Handled before arg compilation:
            # bare createSet deliberately fails to compile.)
            inner = fn_expr.parameters[0]
            if not (isinstance(inner, AttributeFunction) and
                    not inner.namespace and inner.name == "createSet" and
                    len(inner.parameters) == 1 and
                    isinstance(inner.parameters[0], Variable)):
                raise CompileError(
                    "unionSet expects createSet(<attribute>) in this build")
            i_dc = self._distinct_spec(inner.parameters[0], expr_key)
            return "SET", (lambda res, _i=i_dc: res[_i]), name
        args = [compile_expression(p, scope) for p in fn_expr.parameters]

        def fvals(c: CompiledExpr, dtype):
            # null arguments contribute nothing (reference: every aggregator
            # executor skips null inputs — Sum/Avg/StdDev processAdd)
            def vals(env, sign):
                v = c.fn(env)
                contrib = jnp.asarray(v, dtype) * jnp.asarray(sign, dtype)
                return jnp.where(ev.null_mask(v, c.type),
                                 jnp.asarray(0, dtype), contrib)
            return vals

        def fcount_nonnull(c: CompiledExpr):
            def vals(env, sign):
                v = c.fn(env)
                return jnp.where(ev.null_mask(v, c.type),
                                 jnp.asarray(0, jnp.int64),
                                 jnp.asarray(sign, jnp.int64))
            return vals

        if name == "sum" or name == "avg" or name == "stdDev":
            (a,) = args
            out_t = "LONG" if (name == "sum" and a.type in ("INT", "LONG")) \
                else "DOUBLE"
            acc_dtype = ev.dtype_of("LONG") if out_t == "LONG" \
                else ev.dtype_of("DOUBLE")
            i_sum = self._add(_AggSpec(
                f"sum:{expr_key}", jnp.add, 0, acc_dtype, fvals(a, acc_dtype)))
            i_cnt = self._add(_AggSpec(
                f"cnt:{expr_key}", jnp.add, 0, jnp.int64, fcount_nonnull(a)))
            if name == "sum":
                # null until the first non-null value arrives (and again if
                # the window retracts every contribution — reference: Sum
                # returns null at count 0)
                def fsum(res, _s=i_sum, _c=i_cnt, _t=out_t):
                    return jnp.where(
                        res[_c] != 0, res[_s],
                        jnp.asarray(ev.null_value(_t), res[_s].dtype))
                return out_t, fsum, name
            if name == "avg":
                def favg(res, _s=i_sum, _c=i_cnt):
                    c = res[_c]
                    # zero non-null contributions -> null (reference: Avg
                    # returns null before the first value arrives)
                    return jnp.where(
                        c != 0,
                        res[_s].astype(jnp.float32) / c.astype(jnp.float32),
                        jnp.asarray(jnp.nan, jnp.float32))
                return "DOUBLE", favg, name
            # stdDev = sqrt(E[x^2] - E[x]^2)
            def sqvals(env, sign, _a=a):
                v0 = _a.fn(env)
                v = jnp.asarray(v0, jnp.float32)
                return jnp.where(ev.null_mask(v0, _a.type),
                                 jnp.asarray(0.0, jnp.float32),
                                 v * v * jnp.asarray(sign, jnp.float32))
            i_sq = self._add(_AggSpec(
                f"sumsq:{expr_key}", jnp.add, 0, jnp.float32, sqvals))
            def fstd(res, _s=i_sum, _c=i_cnt, _q=i_sq):
                c = jnp.maximum(res[_c], 1).astype(jnp.float32)
                m = res[_s].astype(jnp.float32) / c
                var = jnp.maximum(res[_q] / c - m * m, 0.0)
                return jnp.where(res[_c] != 0, jnp.sqrt(var),
                                 jnp.asarray(jnp.nan, jnp.float32))
            return "DOUBLE", fstd, name

        if name == "count":
            i_cnt = self._add(_AggSpec(
                f"count:{expr_key}", jnp.add, 0, jnp.int64,
                lambda env, sign: jnp.asarray(sign, jnp.int64)))
            return "LONG", (lambda res, _i=i_cnt: res[_i]), name

        if name in ("min", "max", "minForever", "maxForever"):
            (a,) = args
            if a.type not in ("INT", "LONG", "FLOAT", "DOUBLE"):
                raise CompileError(f"{name}() needs a numeric argument")
            dtype = ev.dtype_of(a.type)
            big = jnp.asarray(
                jnp.inf if dtype in (jnp.float32, jnp.float64)
                else jnp.iinfo(dtype).max, dtype)
            is_min = name.startswith("min")
            ident = big if is_min else (-big if dtype in (jnp.float32,) else
                                        jnp.asarray(jnp.iinfo(dtype).min, dtype)
                                        if dtype not in (jnp.float32, jnp.float64)
                                        else -big)
            opf = jnp.minimum if is_min else jnp.maximum
            def vals(env, sign, _a=a, _id=ident, _d=dtype):
                v0 = _a.fn(env)
                v = jnp.asarray(v0, _d)
                # only CURRENT rows contribute; EXPIRED need window exposure;
                # null inputs contribute the identity (reference: MinMax
                # aggregators skip nulls)
                contribute = jnp.logical_and(
                    jnp.asarray(sign) > 0,
                    jnp.logical_not(ev.null_mask(v0, _a.type)))
                return jnp.where(contribute, v, _id)
            i = self._add(_AggSpec(
                f"{name}:{expr_key}", opf, ident, dtype, vals))
            # null until the first non-null CURRENT value is seen — the
            # accumulator identity must never leak to callbacks (reference:
            # MinMax aggregators return null before the first value).  The
            # seen-count is monotone because this min/max does not retract.
            def seen_vals(env, sign, _a=a):
                v = _a.fn(env)
                hit = jnp.logical_and(
                    jnp.asarray(sign) > 0,
                    jnp.logical_not(ev.null_mask(v, _a.type)))
                return jnp.where(hit, jnp.asarray(1, jnp.int64),
                                 jnp.asarray(0, jnp.int64))
            i_seen = self._add(_AggSpec(
                f"seen:{expr_key}", jnp.add, 0, jnp.int64, seen_vals))

            def fminmax(res, _i=i, _s=i_seen, _t=a.type, _d=dtype):
                return jnp.where(res[_s] > 0, res[_i],
                                 jnp.asarray(ev.null_value(_t), _d))
            return a.type, fminmax, name

        if name in ("and", "or"):
            (a,) = args
            want = name == "or"   # or: count trues; and: count falses
            def vals(env, sign, _a=a, _w=want):
                v = jnp.asarray(_a.fn(env), jnp.bool_)
                hit = v if _w else jnp.logical_not(v)
                return jnp.where(hit, jnp.asarray(sign, jnp.int64), 0)
            i = self._add(_AggSpec(
                f"{name}:{expr_key}", jnp.add, 0, jnp.int64, vals))
            if want:
                return "BOOL", (lambda res, _i=i: res[_i] > 0), name
            return "BOOL", (lambda res, _i=i: res[_i] == 0), name

        raise CompileError(f"unknown aggregator {name!r}")

    def _distinct_spec(self, var: Variable, expr_key: str) -> int:
        """Exact distinct count (reference: DistinctCountAttribute-
        AggregatorExecutor's per-value refcount map).  TPU design:
        (group, value) pairs resolve to pair slots on the host; a
        pair-segmented scan maintains refcounts, and 0<->1 refcount
        transitions feed a group-segmented scan as +-1 contributions."""
        j = len(self.pair_sources)
        self.pair_sources.append(var)
        i_ref = self._add(_AggSpec(
            f"ref:{expr_key}", jnp.add, 0, jnp.int64,
            lambda env, sign: jnp.asarray(sign, jnp.int64),
            slot_src=j, K_override=self.K * 8))

        def dvals(env, sign, _r=i_ref):
            r = env["__scanres__"][_r]
            return jnp.where(
                jnp.logical_and(jnp.asarray(sign) > 0, r == 1),
                jnp.asarray(1, jnp.int64),
                jnp.where(
                    jnp.logical_and(jnp.asarray(sign) < 0, r == 0),
                    jnp.asarray(-1, jnp.int64),
                    jnp.asarray(0, jnp.int64)))
        return self._add(_AggSpec(
            f"dc:{expr_key}", jnp.add, 0, jnp.int64, dvals))

    # -- runtime -------------------------------------------------------------
    def process(self, state, rows: Rows, env) -> Tuple[Any, Tuple]:
        """Returns (new_state, per-row running values per spec)."""
        if not self.specs:
            return state, ()
        B = rows.capacity
        sign = jnp.where(
            jnp.logical_and(rows.valid, rows.kind == ev.CURRENT), 1,
            jnp.where(jnp.logical_and(rows.valid, rows.kind == ev.EXPIRED),
                      -1, 0))
        gslot = jnp.where(rows.gslot >= 0, rows.gslot, 0).astype(jnp.int32)

        is_reset = jnp.logical_and(rows.valid, rows.kind == ev.RESET)
        reset_epoch = jnp.cumsum(is_reset.astype(jnp.int64))  # after row i
        epoch_before = reset_epoch - is_reset.astype(jnp.int64)
        total_resets = reset_epoch[-1]

        def layout(slot_vec):
            # segment id: (slot, epoch); rows already seq-ordered
            seg = slot_vec.astype(jnp.int64) * (B + 2) + epoch_before
            order = jnp.argsort(seg, stable=True)
            unorder = jnp.zeros((B,), jnp.int32).at[order].set(
                jnp.arange(B, dtype=jnp.int32))
            seg_s = seg[order]
            first = jnp.concatenate([
                jnp.ones((1,), jnp.bool_), seg_s[1:] != seg_s[:-1]])
            return (order, unorder, seg_s, first, sign[order],
                    slot_vec[order], epoch_before[order])

        layouts = {None: layout(gslot)}
        for j in range(len(self.pair_sources)):
            ps = env.get(f"__pslot__{j}")
            if ps is not None:
                layouts[j] = layout(
                    jnp.where(ps >= 0, ps, 0).astype(jnp.int32))

        env = dict(env)
        env["__scanres__"] = results = []
        new_state = []
        for spec, st in zip(self.specs, state):
            (order, unorder, seg_s, first, sign_s, slot_s,
             epoch_s) = layouts[spec.slot_src]
            # slot count from the STATE shape, not the plan: under
            # shard_map each device owns a K/n slice of the slot axis
            K = st.shape[0]
            vals = spec.vals_fn(env, sign)
            # rows that don't contribute carry the identity
            vals = jnp.where(sign != 0, vals,
                             jnp.asarray(spec.init, spec.dtype))
            v_s = vals[order]
            # inject carry state at heads of epoch-0 segments
            carry = st[slot_s]
            v_s = jnp.where(
                jnp.logical_and(first, epoch_s == 0),
                spec.op(carry, v_s), v_s)
            scanned = _segmented_scan(v_s, seg_s, spec.op)
            results.append(scanned[unorder])

            # new state: per slot, value after the last row in the final epoch
            contrib = jnp.logical_and(sign_s != 0, epoch_s == total_resets)
            idx = jnp.arange(B)
            # scatter-max of sorted index per slot for contributing rows
            last_idx = jnp.full((K,), -1, jnp.int32).at[
                jnp.where(contrib, slot_s, K).astype(jnp.int32)
            ].max(jnp.where(contrib, idx, -1).astype(jnp.int32), mode="drop")
            has = last_idx >= 0
            gathered = scanned[jnp.clip(last_idx, 0, B - 1)]
            base = jnp.where(total_resets > 0,
                             jnp.full((K,), spec.init, spec.dtype), st)
            # carry survives only if no reset happened
            ns = jnp.where(has, gathered, base)
            new_state.append(ns)

        return tuple(new_state), tuple(results)


# ---------------------------------------------------------------------------
# Selector executor
# ---------------------------------------------------------------------------

def _rewrite_aggregators(expr: Expression, found: List[AttributeFunction],
                         prefix: str) -> Expression:
    """Replace aggregator calls with bound pseudo-variables __agg<i>."""
    if isinstance(expr, AttributeFunction):
        is_agg = not expr.namespace and expr.name in AGGREGATOR_NAMES
        if not is_agg:
            from .extension import attribute_aggregator_registry
            full = f"{expr.namespace}:{expr.name}" if expr.namespace \
                else expr.name
            is_agg = full in attribute_aggregator_registry()
        if is_agg:
            found.append(expr)
            return Variable(f"{prefix}{len(found) - 1}")
        return AttributeFunction(expr.namespace, expr.name, [
            _rewrite_aggregators(p, found, prefix) for p in expr.parameters])
    if isinstance(expr, (Add, Subtract, Multiply, Divide, Mod)):
        return type(expr)(_rewrite_aggregators(expr.left, found, prefix),
                          _rewrite_aggregators(expr.right, found, prefix))
    if isinstance(expr, Compare):
        return Compare(_rewrite_aggregators(expr.left, found, prefix),
                       expr.operator,
                       _rewrite_aggregators(expr.right, found, prefix))
    if isinstance(expr, (And, Or)):
        return type(expr)(_rewrite_aggregators(expr.left, found, prefix),
                          _rewrite_aggregators(expr.right, found, prefix))
    if isinstance(expr, Not):
        return Not(_rewrite_aggregators(expr.expression, found, prefix))
    if isinstance(expr, IsNull) and expr.expression is not None:
        return IsNull(_rewrite_aggregators(expr.expression, found, prefix))
    if isinstance(expr, In):
        return In(_rewrite_aggregators(expr.expression, found, prefix),
                  expr.source_id)
    return expr


class SelectorExec:
    """Compiled select clause over ordered Rows."""

    def __init__(self, selector: Selector, scope: Scope,
                 in_schema: ev.Schema, group_slots: int,
                 out_stream_id: str, interner: ev.StringInterner):
        self.selector = selector
        self.scope = scope
        self.group_by_positions: List[int] = []
        for v in selector.group_by_list:
            _, pos, _ = scope.resolve(v)
            self.group_by_positions.append(pos)

        self.bank = AggregatorBank(group_slots)
        self._agg_calls: List[AttributeFunction] = []

        # select list (select-all expands to the input schema)
        sel_list = selector.selection_list
        if not sel_list:
            from ..query_api.query import OutputAttribute
            sel_list = [
                OutputAttribute(None, Variable(n)) for n in in_schema.names]

        self.out_names: List[str] = []
        self._proj: List[Tuple[Expression, str]] = []  # rewritten expr
        for oa in sel_list:
            rewritten = _rewrite_aggregators(oa.expression, self._agg_calls,
                                             "__agg")
            self.out_names.append(oa.name if oa.rename or isinstance(
                oa.expression, Variable) else oa.name)
            self._proj.append((rewritten, oa.name))

        # compile aggregator calls -> result fns; bind pseudo-columns
        self._agg_results: List[Tuple[str, Callable]] = []
        for i, call in enumerate(self._agg_calls):
            ekey = f"{out_stream_id}:{i}:{_expr_fingerprint(call)}"
            t, fn, _ = self.bank.compile_call(call, scope, ekey)
            self._agg_results.append((t, fn))
            scope.bind(f"__agg{i}",
                       CompiledExpr(fn=None, type=t))  # type only; fn later

        # compile projections / having with pseudo-columns resolved lazily:
        # we compile in process() env style: pseudo columns injected into env
        self._compiled_proj: List[CompiledExpr] = []
        for rewritten, name in self._proj:
            self._compiled_proj.append(
                _compile_with_pseudo(rewritten, scope, self._agg_results))
        self.out_types = [c.type for c in self._compiled_proj]
        if "SET" in self.out_types:
            raise CompileError(
                "set values cannot materialize in columnar outputs; wrap "
                "with sizeOfSet(...)")

        self.having = None
        if selector.having_expression is not None:
            # having may reference select ALIASES (reference: having runs
            # over the output event); substitute them with the projected
            # expression before aggregator rewriting
            alias_map = {}
            for oa, (expr, _) in zip(sel_list, self._proj):
                if oa.rename:
                    alias_map[oa.rename] = oa.expression
            hre = _substitute_aliases(
                selector.having_expression, alias_map, scope)
            hre = _rewrite_aggregators(hre, self._agg_calls, "__agg")
            # new aggs may have been appended by having
            while len(self._agg_results) < len(self._agg_calls):
                i = len(self._agg_results)
                call = self._agg_calls[i]
                ekey = f"{out_stream_id}:h{i}:{_expr_fingerprint(call)}"
                t, fn, _ = self.bank.compile_call(call, scope, ekey)
                self._agg_results.append((t, fn))
                scope.bind(f"__agg{i}", CompiledExpr(fn=None, type=t))
            self.having = _compile_with_pseudo(hre, scope, self._agg_results)

        # order-by / limit
        self._order_by = []
        for ob in selector.order_by_list:
            c = compile_expression(ob.variable, _projection_scope(
                self.out_names, self.out_types, interner))
            self._order_by.append((c, ob.order))
        self.interner = interner

    @property
    def has_aggregation(self) -> bool:
        return bool(self.bank.specs)

    def init_state(self):
        return self.bank.init_state()

    def process(self, state, rows: Rows, env: Dict[str, Any]):
        """env must contain the scope's source cols; returns
        (state', out_ts, out_kind, out_valid, out_cols tuple)."""
        new_state, scans = self.bank.process(state, rows, env)
        env = dict(env)
        env["__aggscan__"] = scans

        out_cols = tuple(c.fn(env) for c in self._compiled_proj)
        valid = jnp.logical_and(
            rows.valid,
            jnp.logical_or(rows.kind == ev.CURRENT, rows.kind == ev.EXPIRED))
        if self.having is not None:
            valid = jnp.logical_and(valid, self.having.fn(env))

        ts, kind = rows.ts, rows.kind
        if self._order_by or self.selector.limit is not None \
                or self.selector.offset is not None:
            ts, kind, valid, out_cols = self._order_limit(
                ts, kind, valid, out_cols)
        return new_state, (ts, kind, valid, out_cols)

    def _order_limit(self, ts, kind, valid, out_cols):
        B = ts.shape[0]
        if self._order_by:
            env = {"__out__": out_cols}
            keys = []
            for c, order in reversed(self._order_by):
                k = c.fn(env)
                if order == "DESC":
                    k = -k if k.dtype != jnp.bool_ else jnp.logical_not(k)
                keys.append(k)
            idx = jnp.arange(B)
            for k in keys:  # last applied = primary (stable sorts)
                big = jnp.asarray(
                    jnp.inf if k.dtype in (jnp.float32, jnp.float64)
                    else jnp.iinfo(k.dtype).max
                    if k.dtype not in (jnp.bool_,) else True)
                kk = jnp.where(valid[idx], k[idx], big)
                s = jnp.argsort(kk, stable=True)
                idx = idx[s]
            ts, kind, valid = ts[idx], kind[idx], valid[idx]
            out_cols = tuple(c[idx] for c in out_cols)
        if self.selector.offset is not None or self.selector.limit is not None:
            rank = jnp.cumsum(valid.astype(jnp.int32)) - 1
            lo = self.selector.offset or 0
            keep = rank >= lo
            if self.selector.limit is not None:
                keep = jnp.logical_and(keep, rank < lo + self.selector.limit)
            valid = jnp.logical_and(valid, keep)
        return ts, kind, valid, out_cols


def _substitute_aliases(e: Expression, alias_map, scope) -> Expression:
    """Replace unqualified Variables naming a select alias with the aliased
    expression, unless the name also resolves to a real input attribute
    (input attributes win, matching single-source behavior)."""
    if isinstance(e, Variable) and e.stream_id is None and \
            e.attribute_name in alias_map:
        try:
            scope.resolve(e)
            return e              # a real input attribute shadows the alias
        except CompileError:
            return alias_map[e.attribute_name]
    for f in getattr(e, "__dataclass_fields__", {}):
        v = getattr(e, f)
        if isinstance(v, Expression):
            setattr(e, f, _substitute_aliases(v, alias_map, scope))
        elif isinstance(v, list):
            setattr(e, f, [
                _substitute_aliases(x, alias_map, scope)
                if isinstance(x, Expression) else x for x in v])
    return e


def _expr_fingerprint(e: Expression) -> str:
    if isinstance(e, Variable):
        return f"v:{e.stream_id}.{e.attribute_name}[{e.stream_index}]"
    if isinstance(e, Constant):
        return f"c:{e.value}"
    if isinstance(e, AttributeFunction):
        inner = ",".join(_expr_fingerprint(p) for p in e.parameters)
        return f"f:{e.namespace}:{e.name}({inner})"
    if isinstance(e, Compare):
        return f"({_expr_fingerprint(e.left)}{e.operator}{_expr_fingerprint(e.right)})"
    if isinstance(e, (Add, Subtract, Multiply, Divide, Mod, And, Or)):
        return (f"({_expr_fingerprint(e.left)}{type(e).__name__}"
                f"{_expr_fingerprint(e.right)})")
    if isinstance(e, Not):
        return f"!({_expr_fingerprint(e.expression)})"
    return repr(e)


def _compile_with_pseudo(expr: Expression, scope: Scope,
                         agg_results: List[Tuple[str, Callable]]) -> CompiledExpr:
    """Compile an expression where __aggN variables read from env['__aggscan__']."""

    class _PseudoScope:
        def __init__(self, base: Scope):
            self.base = base

        def __getattr__(self, item):
            return getattr(self.base, item)

        def resolve(self, var):
            return self.base.resolve(var)

    # bind real fns for pseudo vars
    for i, (t, fn) in enumerate(agg_results):
        def make(fn):
            return lambda env: fn(env["__aggscan__"])
        scope.bind(f"__agg{i}", CompiledExpr(fn=make(fn), type=t))
    return compile_expression(expr, scope)


def _projection_scope(names, types, interner) -> Scope:
    """Scope over the projected output columns (for order-by)."""
    from ..query_api.definition import StreamDefinition

    d = StreamDefinition("__out__")
    for n, t in zip(names, types):
        d.attribute(n, t)
    s = Scope()
    s.add_source("__out__", ev.Schema(d, interner))
    return s
