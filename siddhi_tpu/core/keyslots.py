"""Host-side vectorized key -> dense slot allocation.

Replaces the reference's thread-local keyed state maps
(CORE/util/snapshot/state/PartitionStateHolder.java:43 — nested
Map<partitionKey, Map<groupByKey, State>> — and
CORE/query/selector/GroupByKeyGenerator.java:37's per-event string-concat
keys) with a batched design: group-by / partition keys are extracted from the
already-encoded integer columns with numpy, hashed to 128 bits, and resolved
to dense slot ids through a vectorized open-addressing table (linear
probing).  Python cost is O(first-seen keys) only — steady-state batches
resolve entirely in numpy (the previous per-unique-key dict loop cost ~70ms
per 131k-key batch).  Device state is then plain [..., K] arrays indexed by
slot, so aggregation is a segment op and partitioning is an axis — no hash
probing on the critical path on device.

Slots are recycled through a free list on purge (reference: @purge idle-key
GC, PartitionRuntimeImpl.java:120-147).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_EMPTY = np.uint64(0)
_TOMB = np.uint64(1)
_FNV_OFF = np.uint64(0xCBF29CE484222325)
_FNV_PRIME = np.uint64(0x100000001B3)
_MIX = np.uint64(0x9E3779B97F4A7C15)


def _hash_words(words: np.ndarray, seed) -> np.ndarray:
    """Fold [n, L8] u64 key words into one u64 per row (vectorized FNV-ish)."""
    h = np.full(words.shape[0], _FNV_OFF ^ np.uint64(seed), np.uint64)
    with np.errstate(over="ignore"):
        for j in range(words.shape[1]):
            h = (h ^ words[:, j]) * _FNV_PRIME
            h = (h ^ (h >> np.uint64(29))) * _MIX
        h ^= h >> np.uint64(32)
    return h


class SlotAllocator:
    def __init__(self, capacity: int, name: str = "?"):
        self.capacity = capacity
        self.name = name
        self._map: Dict[bytes, int] = {}       # exact keys (snapshot/purge)
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        self._lock = threading.Lock()
        self._keys_by_slot: Dict[int, bytes] = {}
        # vectorized probe table: 128-bit key hash -> slot
        self._cap2 = 1 << max(10, int(2 * capacity - 1).bit_length())
        self._mask = np.uint64(self._cap2 - 1)
        self._th = np.zeros(self._cap2, np.uint64)    # 0 empty, 1 tombstone
        self._th2 = np.zeros(self._cap2, np.uint64)
        self._tslot = np.full(self._cap2, -1, np.int32)
        self._cell_by_slot = np.full(capacity, -1, np.int64)
        self._tombstones = 0
        # insertion journal for incremental snapshots (drained per snapshot)
        self.journal: List[Tuple[bytes, int]] = []

    def __len__(self):
        return len(self._map)

    # -- hashing -------------------------------------------------------------
    @staticmethod
    def _key_words(key_cols: Sequence[np.ndarray]) -> np.ndarray:
        """Pack key columns into [n, L8] u64 words (zero-padded bytes)."""
        n = len(key_cols[0])
        bs = []
        for c in key_cols:
            if c.dtype == np.bool_:
                b = c.astype(np.uint8).reshape(n, 1)
            else:
                b = np.ascontiguousarray(c).view(np.uint8).reshape(n, -1)
            bs.append(b)
        raw = np.concatenate(bs, axis=1) if len(bs) > 1 else bs[0]
        L = raw.shape[1]
        pad = (-L) % 8
        if pad:
            raw = np.concatenate(
                [raw, np.zeros((n, pad), np.uint8)], axis=1)
        return np.ascontiguousarray(raw).view(np.uint64)

    def _table_insert(self, h1: int, h2: int, slot: int) -> None:
        mask = self._cap2 - 1
        i = int(h1) & mask
        while self._th[i] > _TOMB:
            i = (i + 1) & mask
        self._th[i] = np.uint64(h1)
        self._th2[i] = np.uint64(h2)
        self._tslot[i] = slot
        self._cell_by_slot[slot] = i

    def _rebuild_table(self) -> None:
        self._th[:] = _EMPTY
        self._th2[:] = _EMPTY
        self._tslot[:] = -1
        self._cell_by_slot[:] = -1
        self._tombstones = 0
        for key, slot in self._map.items():
            w = np.frombuffer(key, np.uint64)[None, :]
            h1 = max(int(_hash_words(w, 0)[0]), 2)
            h2 = int(_hash_words(w, 0xABCD)[0])
            self._table_insert(h1, h2, slot)

    # -- lookup/insert -------------------------------------------------------
    def slots_for(self, key_cols: Sequence[np.ndarray],
                  valid: Optional[np.ndarray] = None) -> np.ndarray:
        """Vectorized lookup/insert: key_cols are 1-D arrays of equal length.
        Returns int32 slot ids (-1 for invalid rows)."""
        n = len(key_cols[0])
        if n == 0:
            return np.empty((0,), np.int32)
        words = self._key_words(key_cols)
        h1 = np.maximum(_hash_words(words, 0), np.uint64(2))  # 0/1 reserved
        h2 = _hash_words(words, 0xABCD)
        live = np.ones(n, bool) if valid is None else valid.astype(bool)

        with self._lock:
            # purge churn turns EMPTY cells into tombstones; once EMPTY runs
            # out, probes for new keys could never terminate at an insertable
            # cell.  Rebuild (clearing tombstones) past a load threshold.
            if (len(self._map) + self._tombstones) * 4 > self._cap2 * 3:
                self._rebuild_table()
            out, new_mask = self._probe(h1, h2, live)
            if new_mask.any():
                self._insert_new(words, h1, h2, new_mask)
                out, still_new = self._probe(h1, h2, live)
                if still_new.any():
                    raise RuntimeError(
                        f"slot table inconsistency in {self.name!r}")
        out[~live] = -1
        return out

    def _probe(self, h1, h2, live) -> Tuple[np.ndarray, np.ndarray]:
        """Vectorized linear probing.  Returns (slots, first-seen mask)."""
        n = h1.shape[0]
        out = np.full(n, -1, np.int32)
        new = np.zeros(n, bool)
        idx = (h1 & self._mask).astype(np.int64)
        unresolved = live.copy()
        for _ in range(self._cap2):
            uidx = np.nonzero(unresolved)[0]
            if uidx.size == 0:
                break
            ui = idx[uidx]
            ch, ch2, cs = self._th[ui], self._th2[ui], self._tslot[ui]
            hit = (ch == h1[uidx]) & (ch2 == h2[uidx]) & (ch > _TOMB)
            empty = ch == _EMPTY
            out[uidx[hit]] = cs[hit]
            new[uidx[empty]] = True
            cont = ~(hit | empty)
            unresolved[uidx[~cont]] = False
            idx[uidx[cont]] = (ui[cont] + 1) & np.int64(self._cap2 - 1)
        return out, new

    def _insert_new(self, words, h1, h2, new_mask) -> None:
        """Python path for first-seen keys only (one-time per key)."""
        for r in np.nonzero(new_mask)[0].tolist():
            key = words[r].tobytes()
            if key in self._map:
                continue
            if not self._free:
                raise RuntimeError(
                    f"slot capacity {self.capacity} exhausted for "
                    f"{self.name!r}; raise via @slots annotation")
            slot = self._free.pop()
            self._map[key] = slot
            self._keys_by_slot[slot] = key
            self._table_insert(int(h1[r]), int(h2[r]), slot)
            self.journal.append((key, slot))

    def purge(self, slots: Sequence[int]) -> None:
        with self._lock:
            for s in slots:
                key = self._keys_by_slot.pop(int(s), None)
                if key is not None:
                    del self._map[key]
                    self._free.append(int(s))
                    cell = int(self._cell_by_slot[int(s)])
                    if cell >= 0:
                        self._th[cell] = _TOMB
                        self._th2[cell] = _EMPTY
                        self._tslot[cell] = -1
                        self._cell_by_slot[int(s)] = -1
                        self._tombstones += 1

    def snapshot(self) -> Dict[bytes, int]:
        with self._lock:
            return dict(self._map)

    def drain_journal(self) -> List[Tuple[bytes, int]]:
        """Insertions since the last drain (incremental snapshot delta)."""
        with self._lock:
            j, self.journal = self.journal, []
            return j

    def apply_journal(self, entries: List[Tuple[bytes, int]]) -> None:
        """Replay journal entries from an incremental snapshot."""
        with self._lock:
            taken = set()
            for key, slot in entries:
                if key in self._map:
                    continue
                self._map[key] = slot
                self._keys_by_slot[slot] = key
                taken.add(slot)
                w = np.frombuffer(key, np.uint64)[None, :]
                h1 = max(int(_hash_words(w, 0)[0]), 2)
                h2 = int(_hash_words(w, 0xABCD)[0])
                self._table_insert(h1, h2, slot)
            if taken:
                self._free = [s for s in self._free if s not in taken]

    def restore(self, mapping: Dict[bytes, int]) -> None:
        with self._lock:
            self._map = dict(mapping)
            self._keys_by_slot = {v: k for k, v in mapping.items()}
            used = set(mapping.values())
            self._free = [i for i in range(self.capacity - 1, -1, -1)
                          if i not in used]
            self._rebuild_table()


def group_events_by_key(slots: np.ndarray, valid: np.ndarray,
                        pad: int = 2**30):
    """Arrange a batch into the per-key [Kb, E] device layout.

    Returns (key_idx [Kb] int32, sel [Kb, E] int32 original-batch indices
    (-1 = padding), kvalid [Kb, E] bool).  Kb/E are padded to buckets to
    bound recompilation.  Events of one key keep their batch order along E
    (sequential NFA semantics per key).

    Padding key rows get index `pad` (= state capacity): the device gather
    clamps them to a real row (their events are invalid, so the scan is a
    no-op there) and the scatter-back DROPS them as out-of-bounds — a pad row
    must never alias a live key's slot, or its stale state would clobber it."""
    vmask = valid & (slots >= 0)
    idx = np.nonzero(vmask)[0]
    if idx.size == 0:
        key_idx = np.full((1,), pad, np.int32)
        sel = np.full((1, 1), -1, np.int32)
        return key_idx, sel, np.zeros((1, 1), np.bool_)
    s = slots[idx]
    order = np.argsort(s, kind="stable")
    s_sorted = s[order]
    idx_sorted = idx[order]
    uniq, starts, counts = np.unique(s_sorted, return_index=True,
                                     return_counts=True)
    E = _bucket(int(counts.max()), _E_BUCKETS)
    Kb = _bucket(len(uniq), _KB_BUCKETS)
    key_idx = np.full((Kb,), pad, np.int32)
    key_idx[:len(uniq)] = uniq.astype(np.int32)
    within = np.arange(len(s_sorted)) - np.repeat(starts, counts)
    sel = np.full((Kb, E), -1, np.int32)
    group_rank = np.repeat(np.arange(len(uniq)), counts)
    sel[group_rank, within] = idx_sorted.astype(np.int32)
    return key_idx, sel, sel >= 0


def _bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


_KB_BUCKETS = (1, 8, 64, 512, 4096, 16384, 65536, 131072,
               262144, 524288, 1048576)
_E_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096, 16384)
