"""Host-side vectorized key -> dense slot allocation.

Replaces the reference's thread-local keyed state maps
(CORE/util/snapshot/state/PartitionStateHolder.java:43 — nested
Map<partitionKey, Map<groupByKey, State>> — and
CORE/query/selector/GroupByKeyGenerator.java:37's per-event string-concat
keys) with a batched design: group-by / partition keys are extracted from the
already-encoded integer columns, hashed to 128 bits, and resolved to dense
slot ids through an open-addressing table (linear probing).  Device state is
then plain [..., K] arrays indexed by slot, so aggregation is a segment op
and partitioning is an axis — no hash probing on the critical path on device.

Two backends share identical semantics and snapshot format:
- native (default): `native/staging.c` does the fused hash+probe+insert and
  the counting-sort grouping in C passes over numpy-owned buffers with an
  interleaved cell table (~75ms -> ~25ms per 524k-event batch on the 1-core
  driver host; `slots_and_group` fuses the count pass into the probe);
- numpy fallback when no C toolchain exists.

Slots are recycled through a free list on purge (reference: @purge idle-key
GC, PartitionRuntimeImpl.java:120-147).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..exceptions import CapacityExceededError
from ..native import LIB, ptr

_EMPTY = np.uint64(0)
_TOMB = np.uint64(1)
_FNV_OFF = np.uint64(0xCBF29CE484222325)
_FNV_PRIME = np.uint64(0x100000001B3)
_MIX = np.uint64(0x9E3779B97F4A7C15)

if LIB is not None:
    import ctypes


def _hash_words(words: np.ndarray, seed) -> np.ndarray:
    """Fold [n, L8] u64 key words into one u64 per row (vectorized FNV-ish).
    Must match sg_slots_for's hash in native/staging.c."""
    h = np.full(words.shape[0], _FNV_OFF ^ np.uint64(seed), np.uint64)
    with np.errstate(over="ignore"):
        for j in range(words.shape[1]):
            h = (h ^ words[:, j]) * _FNV_PRIME
            h = (h ^ (h >> np.uint64(29))) * _MIX
        h ^= h >> np.uint64(32)
    return h


def _key_words(key_cols: Sequence[np.ndarray]) -> np.ndarray:
    """Pack key columns into [n, L8] u64 words (zero-padded bytes)."""
    n = len(key_cols[0])
    bs = []
    for c in key_cols:
        if c.dtype == np.bool_:
            b = c.astype(np.uint8).reshape(n, 1)
        else:
            b = np.ascontiguousarray(c).view(np.uint8).reshape(n, -1)
        bs.append(b)
    raw = np.concatenate(bs, axis=1) if len(bs) > 1 else bs[0]
    L = raw.shape[1]
    pad = (-L) % 8
    if pad:
        raw = np.concatenate(
            [raw, np.zeros((n, pad), np.uint8)], axis=1)
    return np.ascontiguousarray(raw).view(np.uint64)


class _JournalView:
    """List-shaped facade over the native journal buffer (runtime calls
    `.clear()` after full snapshots)."""

    def __init__(self, alloc: "SlotAllocator"):
        self._a = alloc

    def clear(self):
        self._a._meta[3] = 0
        self._a._meta[4] = 0

    def __len__(self):
        return int(self._a._meta[3]) + \
            (1 << 30 if self._a._meta[4] else 0)


class SlotAllocator:
    """Key->slot allocator over numpy buffers shared with the C kernels;
    snapshots read the buffers directly."""

    def __init__(self, capacity: int, name: str = "?"):
        self.capacity = capacity
        self.name = name
        self._lock = threading.Lock()
        self._cap2 = 1 << max(10, int(2 * capacity - 1).bit_length())
        self._mask = np.uint64(self._cap2 - 1)
        # interleaved probe cells [cap2, 3] = (h1, h2, slot): one cache line
        # per probe instead of three; h1 0=empty, 1=tombstone
        self._cells = np.zeros((self._cap2, 3), np.uint64)
        self._cell_by_slot = np.full(capacity, -1, np.int64)
        self._used = np.zeros(capacity, np.uint8)
        self._free = np.arange(capacity - 1, -1, -1, dtype=np.int32)
        # meta: [count, free_top, tombstones, journal_len, journal_overflow,
        #        journal_cap]
        jcap = min(2 * capacity, capacity + (1 << 20))
        self._journal = np.zeros(jcap, np.int32)
        self._meta = np.array([0, capacity, 0, 0, 0, jcap], np.int64)
        self._w8 = 0                    # key width in u64 words (fixed)
        self._arena = None              # [capacity, w8*8] u8
        # bumped whenever key->slot bindings change (insert/purge/restore):
        # callers memoizing resolved slot blocks key their cache on this
        self.version = 0
        # L2-resident direct-mapped probe cache (h1, h2, slot); cleared on
        # any unbinding mutation (purge/rebuild/restore)
        self._pcache = np.zeros((1 << 14, 3), np.uint64)
        self.journal = _JournalView(self)

    def __len__(self):
        return int(self._meta[0])

    def _ensure_arena(self, w8: int):
        if self._arena is None:
            self._w8 = w8
            self._arena = np.zeros((self.capacity, w8 * 8), np.uint8)
        elif w8 > self._w8:
            # an allocator shared across streams may see wider keys later:
            # zero-pad existing keys to the new width and re-hash the table
            # (hashes cover all w8 words, so every binding changes)
            wider = np.zeros((self.capacity, w8 * 8), np.uint8)
            wider[:, : self._w8 * 8] = self._arena
            self._arena = wider
            self._w8 = w8
            self._rebuild_table()

    # -- lookup/insert -------------------------------------------------------
    def slots_for(self, key_cols: Sequence[np.ndarray],
                  valid: Optional[np.ndarray] = None,
                  lookup_only: bool = False) -> np.ndarray:
        """Vectorized lookup/insert: key_cols are 1-D arrays of equal length.
        Returns int32 slot ids (-1 for invalid rows; with lookup_only also
        -1 for unknown keys, and nothing is allocated)."""
        out, _ = self._slots(key_cols, valid, lookup_only, group=False,
                             pad=0)
        return out

    def slots_and_group(self, key_cols: Sequence[np.ndarray],
                        valid: Optional[np.ndarray], pad: int):
        """Fused resolve + group: one C pass probes/inserts AND accumulates
        per-slot counts, then the fill pass emits the [Kb, E] device layout.
        Returns (slots, key_idx, sel)."""
        if LIB is None:
            slots = self.slots_for(key_cols, valid)
            v = np.ones(slots.shape[0], bool) if valid is None else valid
            key_idx, sel, _ = group_events_by_key(slots, v, pad=pad)
            return slots, key_idx, sel
        out, grouped = self._slots(key_cols, valid, False, group=True,
                                   pad=pad)
        return out, grouped[0], grouped[1]

    def _slots(self, key_cols, valid, lookup_only, group: bool, pad: int):
        n = len(key_cols[0])
        if n == 0:
            return np.empty((0,), np.int32), None
        words = _key_words(key_cols)
        live = None if valid is None else \
            np.ascontiguousarray(valid, np.uint8)
        out = np.empty(n, np.int32)
        grouped = None
        with self._lock:
            count_before = int(self._meta[0])
            if self._arena is not None and words.shape[1] < self._w8:
                # narrower key than the arena width: zero-pad to match
                words = np.ascontiguousarray(np.concatenate(
                    [words, np.zeros((n, self._w8 - words.shape[1]),
                                     np.uint64)], axis=1))
            self._ensure_arena(words.shape[1])
            # purge churn turns EMPTY cells into tombstones; once EMPTY runs
            # out, probes for new keys could never terminate.  Rebuild
            # (clearing tombstones) past a load threshold.
            if (self._meta[0] + self._meta[2]) * 4 > self._cap2 * 3:
                self._rebuild_table()
            if LIB is not None:
                if group:
                    _group_scratch_lock.acquire()
                    cnt, rank, touched = _scratch(self.capacity)
                    gmeta = np.zeros(2, np.int64)
                    gargs = (ptr(cnt, ctypes.c_int32),
                             ptr(touched, ctypes.c_int32),
                             ptr(gmeta, ctypes.c_int64))
                else:
                    gargs = (None, None, None)
                try:
                    rc = LIB.sg_slots_for(
                        ptr(words, ctypes.c_uint64), n, self._w8,
                        None if live is None else ptr(live, ctypes.c_uint8),
                        ptr(self._cells, ctypes.c_uint64), self._cap2,
                        ptr(self._cell_by_slot, ctypes.c_int64),
                        ptr(self._arena, ctypes.c_uint8),
                        ptr(self._free, ctypes.c_int32),
                        ptr(self._journal, ctypes.c_int32),
                        ptr(self._used, ctypes.c_uint8),
                        ptr(self._meta, ctypes.c_int64),
                        1 if lookup_only else 0,
                        ptr(out, ctypes.c_int32), *gargs,
                        ptr(self._pcache, ctypes.c_uint64),
                        self._pcache.shape[0] - 1)
                    if rc < 0:
                        if group:
                            # re-zero count scratch the aborted pass touched
                            cnt[:] = 0
                        raise CapacityExceededError(
                            f"slot capacity {self.capacity} exhausted for "
                            f"{self.name!r}; raise via @capacity annotation")
                    if group:
                        grouped = _fill_groups(out, live, n, cnt, rank,
                                               touched, int(gmeta[0]),
                                               int(gmeta[1]), pad)
                finally:
                    if group:
                        _group_scratch_lock.release()
            else:
                self._py_slots_for(words, live, lookup_only, out)
            if int(self._meta[0]) != count_before:
                self.version += 1
        if live is not None:
            out[live == 0] = -1
        return out, grouped

    # -- numpy fallback ------------------------------------------------------
    def _py_slots_for(self, words, live, lookup_only, out) -> None:
        n = words.shape[0]
        h1 = np.maximum(_hash_words(words, 0), np.uint64(2))
        h2 = _hash_words(words, 0xABCD)
        livemask = np.ones(n, bool) if live is None else live.astype(bool)
        slots, new = self._py_probe(h1, h2, livemask)
        if new.any() and not lookup_only:
            for r in np.nonzero(new)[0].tolist():
                # duplicate keys within the batch: re-probe before insert
                s = self._py_probe_one(int(h1[r]), int(h2[r]))
                if s >= 0:
                    slots[r] = s
                    continue
                if self._meta[1] <= 0:
                    raise CapacityExceededError(
                        f"slot capacity {self.capacity} exhausted for "
                        f"{self.name!r}; raise via @capacity annotation")
                self._meta[1] -= 1
                slot = int(self._free[self._meta[1]])
                self._cell_insert(int(h1[r]), int(h2[r]), slot)
                self._arena[slot] = words[r].view(np.uint8)
                self._used[slot] = 1
                self._meta[0] += 1
                if self._meta[3] < self._meta[5]:
                    self._journal[self._meta[3]] = slot
                    self._meta[3] += 1
                else:
                    self._meta[4] = 1
                slots[r] = slot
        elif new.any():
            slots[new] = -1
        out[:] = slots

    def _cell_insert(self, h1: int, h2: int, slot: int) -> None:
        j = h1 & (self._cap2 - 1)
        while self._cells[j, 0] > _TOMB:
            j = (j + 1) & (self._cap2 - 1)
        self._cells[j, 0] = np.uint64(h1)
        self._cells[j, 1] = np.uint64(h2)
        self._cells[j, 2] = np.uint64(np.uint32(slot))
        self._cell_by_slot[slot] = j

    def _py_probe_one(self, h1: int, h2: int) -> int:
        # bounded: cap2 probes visit every cell; when tombstones have eaten
        # the last EMPTY cell, exceeding the bound proves absence
        j = h1 & (self._cap2 - 1)
        for _ in range(self._cap2):
            c = int(self._cells[j, 0])
            if c == int(h1) and int(self._cells[j, 1]) == int(h2):
                return int(np.int32(np.uint32(self._cells[j, 2])))
            if c == 0:
                return -1
            j = (j + 1) & (self._cap2 - 1)
        return -1

    def _py_probe(self, h1, h2, live) -> Tuple[np.ndarray, np.ndarray]:
        n = h1.shape[0]
        out = np.full(n, -1, np.int32)
        new = np.zeros(n, bool)
        idx = (h1 & self._mask).astype(np.int64)
        unresolved = live.copy()
        for _ in range(self._cap2):
            uidx = np.nonzero(unresolved)[0]
            if uidx.size == 0:
                break
            ui = idx[uidx]
            ch, ch2 = self._cells[ui, 0], self._cells[ui, 1]
            cs = self._cells[ui, 2].astype(np.uint32).astype(np.int32)
            hit = (ch == h1[uidx]) & (ch2 == h2[uidx]) & (ch > _TOMB)
            empty = ch == _EMPTY
            out[uidx[hit]] = cs[hit]
            new[uidx[empty]] = True
            cont = ~(hit | empty)
            unresolved[uidx[~cont]] = False
            idx[uidx[cont]] = (ui[cont] + 1) & np.int64(self._cap2 - 1)
        return out, new

    def _rebuild_table(self) -> None:
        self._pcache[:] = 0
        self._meta[2] = 0
        if self._arena is None:
            self._cells[:] = 0
            self._cell_by_slot[:] = -1
            return
        if LIB is not None:
            LIB.sg_rebuild(
                ptr(self._cells, ctypes.c_uint64), self._cap2,
                ptr(self._cell_by_slot, ctypes.c_int64),
                ptr(self._arena, ctypes.c_uint8), self._w8,
                ptr(self._used, ctypes.c_uint8), self.capacity)
            return
        self._cells[:] = 0
        self._cell_by_slot[:] = -1
        for s in np.nonzero(self._used)[0].tolist():
            w = self._arena[s].view(np.uint64)[None, :]
            h1 = max(int(_hash_words(w, 0)[0]), 2)
            h2 = int(_hash_words(w, 0xABCD)[0])
            self._cell_insert(h1, h2, int(s))

    # -- lifecycle ------------------------------------------------------------
    def purge(self, slots: Sequence[int]) -> None:
        with self._lock:
            self.version += 1
            self._pcache[:] = 0
            for s in slots:
                s = int(s)
                if s < 0 or s >= self.capacity or not self._used[s]:
                    continue
                self._used[s] = 0
                self._free[self._meta[1]] = s
                self._meta[1] += 1
                self._meta[0] -= 1
                cell = int(self._cell_by_slot[s])
                if cell >= 0:
                    self._cells[cell, 0] = _TOMB
                    self._cells[cell, 1] = _EMPTY
                    self._cells[cell, 2] = np.uint64(0xFFFFFFFF)
                    self._cell_by_slot[s] = -1
                    self._meta[2] += 1

    def snapshot(self) -> Dict[bytes, int]:
        with self._lock:
            if self._arena is None:
                return {}
            return {self._arena[s].tobytes(): int(s)
                    for s in np.nonzero(self._used)[0]}

    def drain_journal(self) -> List[Tuple[bytes, int]]:
        """Insertions since the last drain (incremental snapshot delta).
        Slots purged since insertion are skipped (their arena bytes are
        stale).  On journal overflow, falls back to the full mapping — a
        superset of the delta, so restore stays correct."""
        with self._lock:
            if self._meta[4]:
                self._meta[3] = 0
                self._meta[4] = 0
                if self._arena is None:
                    return []
                return [(self._arena[s].tobytes(), int(s))
                        for s in np.nonzero(self._used)[0]]
            n = int(self._meta[3])
            self._meta[3] = 0
            return [(self._arena[s].tobytes(), int(s))
                    for s in self._journal[:n] if self._used[s]]

    def apply_journal(self, entries: List[Tuple[bytes, int]]) -> None:
        """Replay journal entries from an incremental snapshot.  A later
        entry re-binding an occupied slot wins (the source recycled it)."""
        with self._lock:
            for key, slot in entries:
                self._insert_exact(key, int(slot))
            # rebuild the free stack once for the whole batch
            free = np.nonzero(self._used == 0)[0][::-1].astype(np.int32)
            self._free[:free.shape[0]] = free
            self._meta[1] = free.shape[0]

    def _unbind(self, slot: int) -> None:
        self._pcache[:] = 0
        cell = int(self._cell_by_slot[slot])
        if cell >= 0:
            self._cells[cell, 0] = _TOMB
            self._cells[cell, 1] = _EMPTY
            self._cells[cell, 2] = np.uint64(0xFFFFFFFF)
            self._cell_by_slot[slot] = -1
            self._meta[2] += 1
        self._used[slot] = 0
        self._meta[0] -= 1

    def _insert_exact(self, key: bytes, slot: int) -> None:
        """Insert a key at a KNOWN slot (restore path).  Caller rebuilds the
        free stack afterwards."""
        if self._arena is None:
            self._w8 = len(key) // 8
            self._arena = np.zeros((self.capacity, len(key)), np.uint8)
        elif len(key) > self._w8 * 8:
            # source allocator widened after the base snapshot; mirror it
            self._ensure_arena(len(key) // 8)
        elif len(key) < self._w8 * 8:
            key = key + b"\x00" * (self._w8 * 8 - len(key))
        if self._used[slot]:
            if self._arena[slot].tobytes() == key:
                return
            self._unbind(slot)        # source recycled the slot to a new key
        w = np.frombuffer(key, np.uint64)[None, :]
        h1 = max(int(_hash_words(w, 0)[0]), 2)
        h2 = int(_hash_words(w, 0xABCD)[0])
        prev = self._py_probe_one(h1, h2)
        if prev >= 0:
            if prev == slot:
                self._arena[slot] = np.frombuffer(key, np.uint8)
                self._used[slot] = 1
                return
            self._unbind(prev)        # key moved to a different slot
        self._cell_insert(h1, h2, slot)
        self._arena[slot] = np.frombuffer(key, np.uint8)
        self._used[slot] = 1
        self._meta[0] += 1

    def restore(self, mapping: Dict[bytes, int]) -> None:
        with self._lock:
            self.version += 1
            self._used[:] = 0
            self._cell_by_slot[:] = -1
            self._cells[:] = 0
            self._meta[0] = 0
            self._meta[2] = 0
            self._meta[3] = 0
            self._meta[4] = 0
            if mapping:
                w = len(next(iter(mapping)))
                if self._arena is None or self._arena.shape[1] != w:
                    self._w8 = w // 8
                    self._arena = np.zeros((self.capacity, w), np.uint8)
                for key, slot in mapping.items():
                    self._arena[slot] = np.frombuffer(key, np.uint8)
                    self._used[slot] = 1
                self._meta[0] = len(mapping)
            free = np.nonzero(self._used == 0)[0][::-1].astype(np.int32)
            self._free[:free.shape[0]] = free
            self._meta[1] = free.shape[0]
            self._rebuild_table()


# scratch buffers for grouping, keyed by minimum capacity; RLock because
# group_events_by_key holds it across _scratch()+fill
_group_scratch: Dict[int, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
_group_scratch_lock = threading.RLock()


def _scratch(capacity: int):
    with _group_scratch_lock:
        for cap, bufs in _group_scratch.items():
            if cap >= capacity:
                return bufs
        cap = max(capacity, 1 << 16)
        bufs = (np.zeros(cap, np.int32), np.zeros(cap, np.int32),
                np.zeros(cap, np.int32))
        _group_scratch[cap] = bufs
        return bufs


def _fill_groups(slots, live, n, cnt, rank, touched, nu, maxc, pad):
    """Shared fill phase: bucket Kb/E, run sg_group_fill.  cnt holds counts
    from the count pass and is re-zeroed by the C fill."""
    if nu == 0:
        key_idx = np.full((1,), pad, np.int32)
        sel = np.full((1, 1), -1, np.int32)
        return key_idx, sel
    E = _bucket(maxc, _E_BUCKETS)
    Kb = _bucket(nu, _KB_BUCKETS)
    key_idx = np.empty(Kb, np.int32)
    sel = np.empty((Kb, E), np.int32)
    LIB.sg_group_fill(
        ptr(slots, ctypes.c_int32),
        None if live is None else ptr(live, ctypes.c_uint8), n,
        ptr(cnt, ctypes.c_int32), ptr(rank, ctypes.c_int32),
        ptr(touched, ctypes.c_int32), nu, Kb, E, pad,
        ptr(key_idx, ctypes.c_int32), ptr(sel, ctypes.c_int32))
    return key_idx, sel


def group_events_by_key(slots: np.ndarray, valid: np.ndarray,
                        pad: int = 2**30):
    """Arrange a batch into the per-key [Kb, E] device layout.

    Returns (key_idx [Kb] int32, sel [Kb, E] int32 original-batch indices
    (-1 = padding), kvalid [Kb, E] bool).  Kb/E are padded to buckets to
    bound recompilation.  Events of one key keep their batch order along E
    (sequential NFA semantics per key).

    Padding key rows get index `pad` (= state capacity): the device gather
    clamps them to a real row (their events are invalid, so the scan is a
    no-op there) and the scatter-back DROPS them as out-of-bounds — a pad row
    must never alias a live key's slot, or its stale state would clobber it."""
    if LIB is not None and pad < 2**30:
        n = slots.shape[0]
        slots = np.ascontiguousarray(slots, np.int32)
        live = np.ascontiguousarray(valid, np.uint8)
        with _group_scratch_lock:
            cnt, rank, touched = _scratch(
                max(pad, int(slots.max(initial=0)) + 1))
            maxc = np.zeros(1, np.int64)
            nu = LIB.sg_group_count(
                ptr(slots, ctypes.c_int32), ptr(live, ctypes.c_uint8), n,
                ptr(cnt, ctypes.c_int32), ptr(touched, ctypes.c_int32),
                ptr(maxc, ctypes.c_int64))
            key_idx, sel = _fill_groups(slots, live, n, cnt, rank, touched,
                                        int(nu), int(maxc[0]), pad)
        if int(nu) == 0:
            return key_idx, sel, np.zeros((1, 1), np.bool_)
        return key_idx, sel, sel >= 0
    vmask = valid & (slots >= 0)
    idx = np.nonzero(vmask)[0]
    if idx.size == 0:
        key_idx = np.full((1,), pad, np.int32)
        sel = np.full((1, 1), -1, np.int32)
        return key_idx, sel, np.zeros((1, 1), np.bool_)
    s = slots[idx]
    order = np.argsort(s, kind="stable")
    s_sorted = s[order]
    idx_sorted = idx[order]
    uniq, starts, counts = np.unique(s_sorted, return_index=True,
                                     return_counts=True)
    E = _bucket(int(counts.max()), _E_BUCKETS)
    Kb = _bucket(len(uniq), _KB_BUCKETS)
    key_idx = np.full((Kb,), pad, np.int32)
    key_idx[:len(uniq)] = uniq.astype(np.int32)
    within = np.arange(len(s_sorted)) - np.repeat(starts, counts)
    sel = np.full((Kb, E), -1, np.int32)
    group_rank = np.repeat(np.arange(len(uniq)), counts)
    sel[group_rank, within] = idx_sorted.astype(np.int32)
    return key_idx, sel, sel >= 0


def _bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    # beyond the table: next power of two (never clamp — a clamped bucket
    # would overflow the sel buffer in the C fill pass)
    return 1 << (n - 1).bit_length()


_KB_BUCKETS = (1, 8, 64, 512, 4096, 16384, 65536, 131072,
               262144, 524288, 1048576)
_E_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096, 16384)
