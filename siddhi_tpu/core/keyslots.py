"""Host-side vectorized key -> dense slot allocation.

Replaces the reference's thread-local keyed state maps
(CORE/util/snapshot/state/PartitionStateHolder.java:43 — nested
Map<partitionKey, Map<groupByKey, State>> — and
CORE/query/selector/GroupByKeyGenerator.java:37's per-event string-concat
keys) with a batched design: group-by / partition keys are extracted from the
already-encoded integer columns with numpy, deduped per batch, and mapped to
dense slot ids through a persistent dict (Python cost is O(new keys), not
O(events)).  Device state is then plain [K, ...] arrays indexed by slot, so
aggregation is a segment op and partitioning is an axis — no hash probing on
the critical path on device.

Slots are recycled through a free list on purge (reference: @purge idle-key
GC, PartitionRuntimeImpl.java:120-147).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class SlotAllocator:
    def __init__(self, capacity: int, name: str = "?"):
        self.capacity = capacity
        self.name = name
        self._map: Dict[bytes, int] = {}
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        self._lock = threading.Lock()
        self._keys_by_slot: Dict[int, bytes] = {}

    def __len__(self):
        return len(self._map)

    def slots_for(self, key_cols: Sequence[np.ndarray],
                  valid: Optional[np.ndarray] = None) -> np.ndarray:
        """Vectorized lookup/insert: key_cols are 1-D arrays of equal length.
        Returns int32 slot ids (-1 for invalid rows)."""
        n = len(key_cols[0])
        if n == 0:
            return np.empty((0,), np.int32)
        # pack the key columns into fixed-width bytes rows
        stacked = np.stack(
            [np.ascontiguousarray(c).view(np.uint8).reshape(n, -1)
             if c.dtype != np.bool_ else
             c.astype(np.uint8).reshape(n, 1)
             for c in key_cols], axis=1) if len(key_cols) > 1 else \
            _as_bytes_2d(key_cols[0])
        if stacked.ndim == 3:
            stacked = stacked.reshape(n, -1)
        rows = stacked.view(
            np.dtype((np.void, stacked.shape[1]))).reshape(n)
        uniq, inverse = np.unique(rows, return_inverse=True)
        uslots = np.empty(len(uniq), np.int32)
        with self._lock:
            for i, u in enumerate(uniq.tolist()):
                key = bytes(u) if not isinstance(u, bytes) else u
                got = self._map.get(key)
                if got is None:
                    if not self._free:
                        raise RuntimeError(
                            f"slot capacity {self.capacity} exhausted for "
                            f"{self.name!r}; raise via @slots annotation")
                    got = self._free.pop()
                    self._map[key] = got
                    self._keys_by_slot[got] = key
                uslots[i] = got
        slots = uslots[inverse].astype(np.int32)
        if valid is not None:
            slots = np.where(valid, slots, -1).astype(np.int32)
        return slots

    def purge(self, slots: Sequence[int]) -> None:
        with self._lock:
            for s in slots:
                key = self._keys_by_slot.pop(int(s), None)
                if key is not None:
                    del self._map[key]
                    self._free.append(int(s))

    def snapshot(self) -> Dict[bytes, int]:
        with self._lock:
            return dict(self._map)

    def restore(self, mapping: Dict[bytes, int]) -> None:
        with self._lock:
            self._map = dict(mapping)
            self._keys_by_slot = {v: k for k, v in mapping.items()}
            used = set(mapping.values())
            self._free = [i for i in range(self.capacity - 1, -1, -1)
                          if i not in used]


def _as_bytes_2d(c: np.ndarray) -> np.ndarray:
    n = len(c)
    if c.dtype == np.bool_:
        return c.astype(np.uint8).reshape(n, 1)
    return np.ascontiguousarray(c).view(np.uint8).reshape(n, -1)


def _bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


_KB_BUCKETS = (1, 8, 64, 512, 4096, 16384, 65536, 131072,
               262144, 524288, 1048576)
_E_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096, 16384)


def group_events_by_key(slots: np.ndarray, valid: np.ndarray,
                        pad: int = 2**30):
    """Arrange a batch into the per-key [Kb, E] device layout.

    Returns (key_idx [Kb] int32, sel [Kb, E] int32 original-batch indices
    (-1 = padding), kvalid [Kb, E] bool).  Kb/E are padded to buckets to
    bound recompilation.  Events of one key keep their batch order along E
    (sequential NFA semantics per key).

    Padding key rows get index `pad` (= state capacity): the device gather
    clamps them to a real row (their events are invalid, so the scan is a
    no-op there) and the scatter-back DROPS them as out-of-bounds — a pad row
    must never alias a live key's slot, or its stale state would clobber it."""
    vmask = valid & (slots >= 0)
    idx = np.nonzero(vmask)[0]
    if idx.size == 0:
        key_idx = np.full((1,), pad, np.int32)
        sel = np.full((1, 1), -1, np.int32)
        return key_idx, sel, np.zeros((1, 1), np.bool_)
    s = slots[idx]
    order = np.argsort(s, kind="stable")
    s_sorted = s[order]
    idx_sorted = idx[order]
    uniq, starts, counts = np.unique(s_sorted, return_index=True,
                                     return_counts=True)
    E = _bucket(int(counts.max()), _E_BUCKETS)
    Kb = _bucket(len(uniq), _KB_BUCKETS)
    key_idx = np.full((Kb,), pad, np.int32)
    key_idx[:len(uniq)] = uniq.astype(np.int32)
    within = np.arange(len(s_sorted)) - np.repeat(starts, counts)
    sel = np.full((Kb, E), -1, np.int32)
    group_rank = np.repeat(np.arange(len(uniq)), counts)
    sel[group_rank, within] = idx_sorted.astype(np.int32)
    return key_idx, sel, sel >= 0
